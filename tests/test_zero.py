"""ZeRO-1/2 sharding tests (parallel/zero.py) on the 8-device virtual
CPU mesh.

Oracle: ZeRO is a memory layout, not a numerics change — N steps with the
sharded flat momentum (and, for ZeRO-2, the sharded faithful reduction)
must match N steps of the replicated implementation exactly."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from cpd_tpu.compat import shard_map
from cpd_tpu.models import tiny_cnn
from cpd_tpu.parallel.mesh import data_parallel_mesh
from cpd_tpu.parallel.zero import zero1_sgd, zero2_sgd
from cpd_tpu.train import create_train_state, make_optimizer, make_train_step
from cpd_tpu.train.state import TrainState


def _data(batch, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(batch, 32, 32, 3).astype(np.float32)
    y = rng.randint(0, 10, size=batch).astype(np.int32)
    return jnp.asarray(x), jnp.asarray(y)


def _assert_params_close(got_tree, want_tree, rtol=1e-6, atol=1e-7):
    """Leaf-by-leaf comparison with path-labelled failures — the shared
    replicated-vs-ZeRO oracle check (update arithmetic differs by
    last-ulp flat-vs-leaf op order, hence the tolerance)."""
    got = jax.tree_util.tree_flatten_with_path(
        jax.tree.map(np.asarray, got_tree))[0]
    want = jax.tree_util.tree_flatten_with_path(
        jax.tree.map(np.asarray, want_tree))[0]
    assert len(got) == len(want)
    for (path, g), (_, w) in zip(got, want):
        np.testing.assert_allclose(g, w, rtol=rtol, atol=atol,
                                   err_msg=str(path))


def _assert_sharded_1w(arr, n_params: int, w: int):
    """Every device holds exactly the ceil(n_params/W)-sized shard of a
    flat (W*S,) array — S derived from the true param count, so a
    self-consistently inflated _shard_size would fail here."""
    s_per_rank = -(-n_params // w)
    assert arr.shape == (w * s_per_rank,)
    shard_shapes = {tuple(sh.data.shape) for sh in arr.addressable_shards}
    assert shard_shapes == {(s_per_rank,)}


def test_zero1_matches_replicated_sgd():
    mesh = data_parallel_mesh()
    w = mesh.devices.size
    model = tiny_cnn()
    schedule = lambda s: jnp.float32(0.05)                     # noqa: E731
    x, y = _data(16)

    # --- replicated baseline ---
    tx = make_optimizer("sgd", schedule, momentum=0.9, weight_decay=1e-2)
    state = create_train_state(model, tx, x[:2], jax.random.PRNGKey(0))
    step = make_train_step(model, tx, mesh, donate=False)
    s_ref = state
    for _ in range(3):
        s_ref, m_ref = step(s_ref, x, y)

    # --- ZeRO-1 ---
    z = zero1_sgd(schedule, world=w, momentum=0.9, weight_decay=1e-2)
    z_state = TrainState(step=jnp.zeros([], jnp.int32),
                         params=state.params,
                         batch_stats=state.batch_stats,
                         opt_state=z.init(state.params))
    spec_tree = TrainState(step=P(), params=P(), batch_stats=P(),
                           opt_state=z.state_spec())
    z_state = jax.device_put(
        z_state, jax.tree.map(lambda s: NamedSharding(mesh, s), spec_tree,
                              is_leaf=lambda s: isinstance(s, P)))
    z_step = make_train_step(model, None, mesh, donate=False,
                             update_fn=z.update_fn,
                             opt_state_spec=z.state_spec())
    s_z = z_state
    for _ in range(3):
        s_z, m_z = z_step(s_z, x, y)

    np.testing.assert_allclose(float(m_z["loss"]), float(m_ref["loss"]),
                               rtol=1e-6)
    _assert_params_close(s_z.params, s_ref.params)

    # the momentum buffer is genuinely sharded: one (S,) shard per device
    n_params = sum(l.size for l in jax.tree.leaves(state.params))
    _assert_sharded_1w(s_z.opt_state.momentum, n_params, w)


def test_zero1_quantized_path():
    """ZeRO-1 composes with the APS/Kahan quantized all-reduce."""
    mesh = data_parallel_mesh()
    model = tiny_cnn()
    schedule = lambda s: jnp.float32(0.05)                     # noqa: E731
    x, y = _data(16)
    tx = make_optimizer("sgd", schedule)
    state = create_train_state(model, tx, x[:2], jax.random.PRNGKey(0))
    z = zero1_sgd(schedule, world=mesh.devices.size)
    z_state = TrainState(step=jnp.zeros([], jnp.int32), params=state.params,
                         batch_stats=state.batch_stats,
                         opt_state=z.init(state.params))
    step = make_train_step(model, None, mesh, use_aps=True, grad_exp=5,
                           grad_man=2, use_kahan=True, donate=False,
                           update_fn=z.update_fn,
                           opt_state_spec=z.state_spec())
    z_state, metrics = step(z_state, x, y)
    assert np.isfinite(float(metrics["loss"]))
    for leaf in jax.tree.leaves(z_state.params):
        assert np.all(np.isfinite(np.asarray(leaf)))


@pytest.mark.parametrize("exp,man,kahan", [(5, 2, False), (4, 3, True)])
def test_zero2_matches_replicated_faithful(exp, man, kahan):
    """ZeRO-2's sharded reduce-scatter (all_to_all + shard-local ordered
    scan, incl. the e5m2 wire-compression case) matches the replicated
    faithful sum_gradients path, composed with APS (+Kahan).

    The reduction itself is asserted BITWISE below; the end-to-end params
    get the same tolerance as the ZeRO-1 oracle because the flat-vector
    SGD arithmetic differs from optax's per-leaf op order by last-ulp."""
    mesh = data_parallel_mesh()
    w = mesh.devices.size
    model = tiny_cnn()
    schedule = lambda s: jnp.float32(0.05)                     # noqa: E731
    x, y = _data(16, seed=3)
    quant = dict(use_aps=True, grad_exp=exp, grad_man=man, use_kahan=kahan)

    # --- replicated faithful baseline ---
    tx = make_optimizer("sgd", schedule, momentum=0.9, weight_decay=1e-2)
    state = create_train_state(model, tx, x[:2], jax.random.PRNGKey(0))
    step = make_train_step(model, tx, mesh, donate=False, mode="faithful",
                           **quant)
    s_ref = state
    for _ in range(3):
        s_ref, m_ref = step(s_ref, x, y)

    # --- ZeRO-2: reduction + update sharded (precision comes from the
    # step via reduce_in_update — single source of truth) ---
    z = zero2_sgd(schedule, world=w, momentum=0.9, weight_decay=1e-2)
    z_state = TrainState(step=jnp.zeros([], jnp.int32), params=state.params,
                         batch_stats=state.batch_stats,
                         opt_state=z.init(state.params))
    z_step = make_train_step(model, None, mesh, donate=False,
                             update_fn=z.update_fn,
                             opt_state_spec=z.state_spec(),
                             reduce_in_update=True, **quant)
    s_z = z_state
    for _ in range(3):
        s_z, m_z = z_step(s_z, x, y)

    np.testing.assert_allclose(float(m_z["loss"]), float(m_ref["loss"]),
                               rtol=1e-6)
    _assert_params_close(s_z.params, s_ref.params)

    # momentum genuinely sharded
    n_params = sum(l.size for l in jax.tree.leaves(state.params))
    _assert_sharded_1w(s_z.opt_state.momentum, n_params, w)


@pytest.mark.parametrize("exp,man,kahan", [(5, 2, False), (4, 3, True)])
def test_zero2_reduce_scatter_bitwise(exp, man, kahan):
    """The shard-local ordered quantized sum IS the corresponding slice of
    the replicated faithful reduction — bit for bit (APS on; (5,2) also
    exercises the e5m2 wire compression)."""
    from jax import lax
    from cpd_tpu.parallel.dist import sum_gradients

    mesh = data_parallel_mesh()
    w = mesh.devices.size
    rng = np.random.RandomState(9)
    tree = {"a": jnp.asarray(rng.randn(w, 33).astype(np.float32)),
            "b": jnp.asarray(rng.randn(w, 7, 5).astype(np.float32))}
    z = zero2_sgd(lambda s: 0.1, world=w)

    def body(t):
        local = jax.tree.map(lambda g: g[0], t)
        ref = sum_gradients(local, "dp", use_aps=True, grad_exp=exp,
                            grad_man=man, use_kahan=kahan, mode="faithful")
        sh = z._grad_shard(local, None, "dp", use_aps=True, grad_exp=exp,
                           grad_man=man, use_kahan=kahan)
        return ref, lax.all_gather(sh, "dp", axis=0, tiled=True)

    in_spec = jax.tree.map(lambda _: P("dp"), tree)
    ref, full = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(in_spec,),
        out_specs=(jax.tree.map(lambda _: P(), tree), P()),
        check_vma=False))(tree)
    flat_ref = np.concatenate([np.asarray(l).ravel()
                               for l in jax.tree.leaves(ref)])
    np.testing.assert_array_equal(flat_ref,
                                  np.asarray(full)[:flat_ref.size])


@pytest.mark.parametrize("use_aps,kahan", [(True, False), (False, False),
                                           (True, True)])
def test_zero2_reduce_scatter_bitwise_sr(use_aps, kahan):
    """Stochastic rounding composes with the sharded reduce-scatter: the
    SR bitstream is indexed by GLOBAL flat offset, so each rank's shard
    reproduces the replicated faithful SR reduction's slice bit for bit
    (round-4 item: SR + ZeRO-2/3).  Covers APS-prequantized, raw-fp32
    gather, and Kahan (4 SR sites per rank step) variants."""
    from jax import lax
    from cpd_tpu.parallel.dist import sum_gradients

    mesh = data_parallel_mesh()
    w = mesh.devices.size
    rng = np.random.RandomState(13)
    tree = {"a": jnp.asarray(rng.randn(w, 33).astype(np.float32)),
            "b": jnp.asarray(rng.randn(w, 7, 5).astype(np.float32))}
    z = zero2_sgd(lambda s: 0.1, world=w)
    key = jax.random.PRNGKey(11)

    def body(t):
        local = jax.tree.map(lambda g: g[0], t)
        ref = sum_gradients(local, "dp", use_aps=use_aps, grad_exp=4,
                            grad_man=3, use_kahan=kahan, mode="faithful",
                            rounding="stochastic", key=key)
        sh = z._grad_shard(local, None, "dp", use_aps=use_aps, grad_exp=4,
                           grad_man=3, use_kahan=kahan,
                           rounding="stochastic", key=key)
        return ref, lax.all_gather(sh, "dp", axis=0, tiled=True)

    in_spec = jax.tree.map(lambda _: P("dp"), tree)
    ref, full = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(in_spec,),
        out_specs=(jax.tree.map(lambda _: P(), tree), P()),
        check_vma=False))(tree)
    flat_ref = np.concatenate([np.asarray(l).ravel()
                               for l in jax.tree.leaves(ref)])
    np.testing.assert_array_equal(flat_ref,
                                  np.asarray(full)[:flat_ref.size])
    # SR actually engaged: the draw differs from the RTNE reduction
    def body_rtne(t):
        local = jax.tree.map(lambda g: g[0], t)
        return sum_gradients(local, "dp", use_aps=use_aps, grad_exp=4,
                             grad_man=3, use_kahan=kahan, mode="faithful")
    rtne = jax.jit(shard_map(
        body_rtne, mesh=mesh, in_specs=(in_spec,),
        out_specs=jax.tree.map(lambda _: P(), tree),
        check_vma=False))(tree)
    flat_rtne = np.concatenate([np.asarray(l).ravel()
                                for l in jax.tree.leaves(rtne)])
    assert np.any(flat_ref != flat_rtne)


@pytest.mark.parametrize("emulate", [
    1, pytest.param(2, marks=pytest.mark.slow)])  # emulate=2 compiles a
# much larger fused scan (94 s measured) — slow tier
def test_zero2_sr_train_step_end_to_end(emulate):
    """make_train_step(grad_rounding='stochastic', reduce_in_update=True)
    — rejected until round 3 — now trains, matches the replicated SR step
    (grads bitwise; update arithmetic differs by last-ulp flat-vs-leaf
    order), and stays seed-deterministic.  emulate=2 additionally runs
    the rank-local SR emulate-node reduce ahead of the sharded
    reduce-scatter (identical in both paths by construction)."""
    mesh = data_parallel_mesh()
    w = mesh.devices.size
    model = tiny_cnn()
    schedule = lambda s: jnp.float32(0.05)                     # noqa: E731
    x, y = _data(16 * emulate, seed=21)
    quant = dict(use_aps=True, grad_exp=4, grad_man=3,
                 grad_rounding="stochastic", grad_seed=7,
                 emulate_node=emulate)

    tx = make_optimizer("sgd", schedule, momentum=0.9, weight_decay=1e-2)
    state = create_train_state(model, tx, x[:2], jax.random.PRNGKey(0))
    step = make_train_step(model, tx, mesh, donate=False, mode="faithful",
                           **quant)
    s_ref = state
    for _ in range(3):
        s_ref, m_ref = step(s_ref, x, y)

    z = zero2_sgd(schedule, world=w, momentum=0.9, weight_decay=1e-2)
    z_state = TrainState(step=jnp.zeros([], jnp.int32), params=state.params,
                         batch_stats=state.batch_stats,
                         opt_state=z.init(state.params))
    z_step = make_train_step(model, None, mesh, donate=False,
                             update_fn=z.update_fn,
                             opt_state_spec=z.state_spec(),
                             reduce_in_update=True, **quant)
    s_z = z_state
    for _ in range(3):
        s_z, m_z = z_step(s_z, x, y)

    np.testing.assert_allclose(float(m_z["loss"]), float(m_ref["loss"]),
                               rtol=1e-6)
    _assert_params_close(s_z.params, s_ref.params)
    # deterministic given seed
    s_z2 = z_state
    for _ in range(3):
        s_z2, _ = z_step(s_z2, x, y)
    for a, b in zip(jax.tree.leaves(s_z.params),
                    jax.tree.leaves(s_z2.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_zero3_matches_replicated_faithful():
    """ZeRO-3 (params sharded at rest, gathered transiently per step)
    trains identically to the replicated faithful path."""
    from cpd_tpu.parallel.zero import zero3_sgd

    mesh = data_parallel_mesh()
    w = mesh.devices.size
    model = tiny_cnn()
    schedule = lambda s: jnp.float32(0.05)                     # noqa: E731
    x, y = _data(16, seed=5)
    quant = dict(use_aps=True, grad_exp=5, grad_man=2, use_kahan=True)

    tx = make_optimizer("sgd", schedule, momentum=0.9, weight_decay=1e-2)
    state = create_train_state(model, tx, x[:2], jax.random.PRNGKey(0))
    step = make_train_step(model, tx, mesh, donate=False, mode="faithful",
                           **quant)
    s_ref = state
    for _ in range(3):
        s_ref, m_ref = step(s_ref, x, y)

    z = zero3_sgd(schedule, world=w, template=state.params, momentum=0.9,
                  weight_decay=1e-2)
    z_state = z.make_state(state, mesh)
    z_step = make_train_step(model, None, mesh, donate=False,
                             update_fn=z.update_fn,
                             opt_state_spec=z.state_spec(),
                             params_spec=z.param_spec(),
                             unpack_params=z.unpack,
                             reduce_in_update=True, **quant)
    s_z = z_state
    for _ in range(3):
        s_z, m_z = z_step(s_z, x, y)

    np.testing.assert_allclose(float(m_z["loss"]), float(m_ref["loss"]),
                               rtol=1e-6)
    _assert_params_close(z.to_pytree(jnp.asarray(np.asarray(s_z.params))),
                         s_ref.params)

    # params and momentum genuinely sharded 1/W per device
    n_params = sum(l.size for l in jax.tree.leaves(state.params))
    for arr in (s_z.params, s_z.opt_state.momentum):
        _assert_sharded_1w(arr, n_params, w)


def test_zero1_lars_matches_replicated():
    """ZeRO-1 x LARS (round 5, VERDICT r4 ask #5): the flagship LARS
    recipe with its momentum sharded 1/W — per-layer trust ratios
    recovered via segment-sum + psum — must train like the replicated
    `lars` to fp32 round-off (the segmented norm sums associate
    differently; see _LarsRule docstring)."""
    from cpd_tpu.parallel.zero import zero1_lars

    mesh = data_parallel_mesh()
    w = mesh.devices.size
    model = tiny_cnn()
    schedule = lambda s: jnp.float32(0.8)                      # noqa: E731
    x, y = _data(16, seed=7)

    tx = make_optimizer("lars", schedule, momentum=0.9,
                        weight_decay=5e-4)
    state = create_train_state(model, tx, x[:2], jax.random.PRNGKey(0))
    step = make_train_step(model, tx, mesh, donate=False)
    s_ref = state
    for _ in range(3):
        s_ref, m_ref = step(s_ref, x, y)

    z = zero1_lars(schedule, world=w, momentum=0.9, weight_decay=5e-4)
    z_state = TrainState(step=jnp.zeros([], jnp.int32),
                         params=state.params,
                         batch_stats=state.batch_stats,
                         opt_state=z.init(state.params))
    spec_tree = TrainState(step=P(), params=P(), batch_stats=P(),
                           opt_state=z.state_spec())
    z_state = jax.device_put(
        z_state, jax.tree.map(lambda s: NamedSharding(mesh, s), spec_tree,
                              is_leaf=lambda s: isinstance(s, P)))
    z_step = make_train_step(model, None, mesh, donate=False,
                             update_fn=z.update_fn,
                             opt_state_spec=z.state_spec())
    s_z = z_state
    for _ in range(3):
        s_z, m_z = z_step(s_z, x, y)

    np.testing.assert_allclose(float(m_z["loss"]), float(m_ref["loss"]),
                               rtol=1e-6)
    _assert_params_close(s_z.params, s_ref.params, rtol=2e-6, atol=2e-7)
    n_params = sum(l.size for l in jax.tree.leaves(state.params))
    _assert_sharded_1w(s_z.opt_state.momentum, n_params, w)


@pytest.mark.slow  # ~12 s; the fast tier keeps zero1_lars (7 s) as the
                   # LARS-rule gate, this adds the zero3+quantized arm
def test_zero3_lars_matches_replicated_quantized():
    """ZeRO-3 x LARS with the faithful APS-quantized sharded reduction:
    params, momentum, reduction AND the LARS trust-ratio norms all
    sharded — vs the replicated lars step on identically-quantized
    gradients."""
    from cpd_tpu.parallel.zero import zero3_lars

    mesh = data_parallel_mesh()
    w = mesh.devices.size
    model = tiny_cnn()
    schedule = lambda s: jnp.float32(0.8)                      # noqa: E731
    x, y = _data(16, seed=8)
    quant = dict(use_aps=True, grad_exp=5, grad_man=2)

    tx = make_optimizer("lars", schedule, momentum=0.9,
                        weight_decay=5e-4)
    state = create_train_state(model, tx, x[:2], jax.random.PRNGKey(0))
    step = make_train_step(model, tx, mesh, donate=False, mode="faithful",
                           **quant)
    s_ref = state
    for _ in range(2):
        s_ref, m_ref = step(s_ref, x, y)

    z = zero3_lars(schedule, world=w, template=state.params,
                   momentum=0.9, weight_decay=5e-4)
    z_state = z.make_state(state, mesh)
    z_step = make_train_step(model, None, mesh, donate=False,
                             update_fn=z.update_fn,
                             opt_state_spec=z.state_spec(),
                             params_spec=z.param_spec(),
                             unpack_params=z.unpack,
                             reduce_in_update=True, **quant)
    s_z = z_state
    for _ in range(2):
        s_z, m_z = z_step(s_z, x, y)

    np.testing.assert_allclose(float(m_z["loss"]), float(m_ref["loss"]),
                               rtol=1e-6)
    _assert_params_close(z.to_pytree(jnp.asarray(np.asarray(s_z.params))),
                         s_ref.params, rtol=2e-6, atol=2e-7)
    n_params = sum(l.size for l in jax.tree.leaves(state.params))
    for arr in (s_z.params, s_z.opt_state.momentum):
        _assert_sharded_1w(arr, n_params, w)


def test_zero2_lars_sr_composes():
    """ZeRO-2 x LARS x stochastic rounding in one step: the SR sharded
    reduce-scatter feeds the segment-sum trust ratios — finite,
    deterministic given the seed, seed-sensitive."""
    from cpd_tpu.parallel.zero import zero2_lars

    mesh = data_parallel_mesh()
    model = tiny_cnn()
    schedule = lambda s: jnp.float32(0.8)                      # noqa: E731
    x, y = _data(16, seed=12)
    tx = make_optimizer("lars", schedule, momentum=0.9,
                        weight_decay=5e-4)
    state = create_train_state(model, tx, x[:2], jax.random.PRNGKey(0))
    z = zero2_lars(schedule, world=mesh.devices.size, momentum=0.9,
                   weight_decay=5e-4)
    z_state, extra = z.mesh_layout(
        state.replace(opt_state=z.init(state.params)), mesh)

    def run(seed):
        step = make_train_step(model, None, mesh, donate=False,
                               mode="faithful", use_aps=True, grad_exp=4,
                               grad_man=3, grad_rounding="stochastic",
                               grad_seed=seed, **extra)
        s, m = step(z_state, x, y)
        return s, float(m["loss"])

    s1, l1 = run(0)
    s1b, l1b = run(0)
    assert np.isfinite(l1) and l1 == l1b
    for a, b in zip(jax.tree.leaves(s1.params),
                    jax.tree.leaves(s1b.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    s2, _ = run(1)
    assert any(
        not np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(s1.params),
                        jax.tree.leaves(s2.params)))


def test_zero1_checkpoint_portable_across_world(tmp_path):
    """Round 5: ZeRO-1/2 checkpoints use the same portable contract as
    ZeRO-3 — export_state trims the world-size pad, so a checkpoint
    written at world=8 restores at world=4 and keeps training (the
    momentum re-padded by import_state for the new shard size)."""
    from cpd_tpu.parallel.mesh import make_mesh
    from cpd_tpu.parallel.zero import zero1_sgd
    from cpd_tpu.train import CheckpointManager

    schedule = lambda s: jnp.float32(0.05)                     # noqa: E731
    model = tiny_cnn()
    x, y = _data(16, seed=11)
    tx = make_optimizer("sgd", schedule, momentum=0.9)
    state0 = create_train_state(model, tx, x[:2], jax.random.PRNGKey(0))

    def build(world, mesh):
        z = zero1_sgd(schedule, world=world, momentum=0.9)
        step = make_train_step(model, None, mesh, donate=False,
                               update_fn=z.update_fn,
                               opt_state_spec=z.state_spec())
        return z, step

    mesh8 = data_parallel_mesh()
    z8, step8 = build(8, mesh8)
    s8, _ = z8.mesh_layout(
        state0.replace(opt_state=z8.init(state0.params)), mesh8)
    s8, _m = step8(s8, x, y)

    mgr = CheckpointManager(str(tmp_path), track_best=False)
    mgr.save(1, z8.export_state(s8), force=True)
    mgr.wait()

    mesh4 = make_mesh(dp=4, devices=jax.devices()[:4])
    z4, step4 = build(4, mesh4)
    restored = mgr.restore(z4.portable_template(state0))
    mgr.close()
    assert restored is not None
    s4, _ = z4.mesh_layout(z4.import_state(restored), mesh4)
    # the un-padded momentum content survives the world change exactly
    total = sum(l.size for l in jax.tree.leaves(state0.params))
    np.testing.assert_array_equal(
        np.asarray(s4.opt_state.momentum)[:total],
        np.asarray(s8.opt_state.momentum)[:total])
    s4, m4 = step4(s4, x[:8], y[:8])
    assert np.isfinite(float(m4["loss"]))


def test_zero2_elastic_restore_across_world(tmp_path):
    """ISSUE 4 elastic restart: a ZeRO-2 state saved in its PADDED
    world=8 layout (a preemption snapshot, no export_state conversion)
    restores at world=4 — and back at world=8 — through
    `restore_latest_valid(world=...)`'s re-flatten, with
    bitwise-identical params and reassembled optimizer state."""
    from cpd_tpu.parallel.ring import pad_to_world
    from cpd_tpu.parallel.zero import Zero1State, zero2_sgd
    from cpd_tpu.train import CheckpointManager
    from cpd_tpu.train.state import TrainState

    schedule = lambda s: jnp.float32(0.05)                     # noqa: E731
    # leaf sizes chosen so total (42) divides neither 8 nor 4: both
    # world paddings are non-trivial and exercised
    params = {"w": jnp.asarray(np.random.RandomState(0)
                               .randn(37).astype(np.float32)),
              "b": jnp.asarray(np.linspace(2, 3, 5), jnp.float32)}
    total = 42
    vals = jnp.asarray(np.random.RandomState(1)
                       .randn(total).astype(np.float32))
    z8 = zero2_sgd(schedule, world=8)
    s8 = TrainState(step=jnp.asarray(9, jnp.int32), params=params,
                    batch_stats={},
                    opt_state=Zero1State(jnp.asarray(9, jnp.int32),
                                         pad_to_world(vals, 8)))
    mgr = CheckpointManager(str(tmp_path / "w8"), track_best=False)
    mgr.save(1, s8, force=True)
    mgr.wait()
    # the save recorded the shard layout for the elastic re-flatten
    assert mgr.metadata(1)["zero_layout"]["momentum_padded"] == 48

    z4 = zero2_sgd(schedule, world=4)
    tmpl4 = TrainState(step=jnp.zeros([], jnp.int32), params=params,
                       batch_stats={}, opt_state=z4.init(params))
    res = mgr.restore_latest_valid(tmpl4, world=4)
    mgr.close()
    assert res is not None and res.step == 1 and res.verified is True
    m4 = np.asarray(res.state.opt_state.momentum)
    assert m4.shape == np.asarray(z4.init(params).momentum).shape
    np.testing.assert_array_equal(m4[:total].view(np.uint32),
                                  np.asarray(vals).view(np.uint32))
    assert (m4[total:] == 0).all()
    assert int(res.state.opt_state.step) == 9
    for k in params:
        np.testing.assert_array_equal(
            np.asarray(res.state.params[k]).view(np.uint32),
            np.asarray(params[k]).view(np.uint32))

    # and back up: the W=4 snapshot reassembles bitwise at W=8
    mgr2 = CheckpointManager(str(tmp_path / "w4"), track_best=False)
    mgr2.save(1, res.state, force=True)
    mgr2.wait()
    tmpl8 = TrainState(step=jnp.zeros([], jnp.int32), params=params,
                       batch_stats={}, opt_state=z8.init(params))
    res8 = mgr2.restore_latest_valid(tmpl8, world=8)
    mgr2.close()
    assert res8 is not None
    np.testing.assert_array_equal(
        np.asarray(res8.state.opt_state.momentum).view(np.uint32),
        np.asarray(s8.opt_state.momentum).view(np.uint32))
    # same-world restore (world passed but layouts already match) stays
    # on the plain path and is equally exact
    mgr3 = CheckpointManager(str(tmp_path / "w8"), track_best=False)
    same = mgr3.restore_latest_valid(tmpl8, world=8)
    mgr3.close()
    np.testing.assert_array_equal(
        np.asarray(same.state.opt_state.momentum).view(np.uint32),
        np.asarray(s8.opt_state.momentum).view(np.uint32))


def test_zero_elastic_template_world_mismatch_raises(tmp_path):
    """restore(world=W') with a template built for a DIFFERENT world
    than W' must fail loudly, not reshape into silent corruption."""
    from cpd_tpu.parallel.ring import pad_to_world
    from cpd_tpu.parallel.zero import Zero1State, zero2_sgd
    from cpd_tpu.train import CheckpointManager
    from cpd_tpu.train.state import TrainState

    schedule = lambda s: jnp.float32(0.05)                     # noqa: E731
    params = {"w": jnp.zeros((42,), jnp.float32)}
    s8 = TrainState(step=jnp.zeros([], jnp.int32), params=params,
                    batch_stats={},
                    opt_state=Zero1State(jnp.zeros([], jnp.int32),
                                         pad_to_world(jnp.arange(42.0),
                                                      8)))
    mgr = CheckpointManager(str(tmp_path), track_best=False)
    mgr.save(1, s8, force=True)
    mgr.wait()
    z2 = zero2_sgd(schedule, world=2)
    tmpl2 = TrainState(step=jnp.zeros([], jnp.int32), params=params,
                       batch_stats={}, opt_state=z2.init(params))
    with pytest.raises(ValueError, match="template world"):
        mgr.restore(tmpl2, step=1, world=4)   # template says world=2
    mgr.close()


def test_zero_elastic_restore_non_divisible_world(tmp_path):
    """ISSUE 19 satellite: the shrink target need not divide the home
    world OR the parameter count — a padded world=8 snapshot restores at
    world=3 (a pow2=False fleet losing hosts 3..7), the momentum
    re-padded through `pad_to_world` at the new world, and reassembles
    bitwise back at world=8."""
    from cpd_tpu.parallel.ring import pad_to_world
    from cpd_tpu.parallel.zero import Zero1State, zero2_sgd
    from cpd_tpu.train import CheckpointManager
    from cpd_tpu.train.state import TrainState

    schedule = lambda s: jnp.float32(0.05)                     # noqa: E731
    # total=41: 41 % 8 == 1 and 41 % 3 == 2 — both pads non-trivial —
    # and 3 divides neither 8 nor 41 (the non-divisible shrink)
    params = {"w": jnp.asarray(np.random.RandomState(2)
                               .randn(37).astype(np.float32)),
              "b": jnp.asarray(np.linspace(-1, 1, 4), jnp.float32)}
    total = 41
    vals = jnp.asarray(np.random.RandomState(3)
                       .randn(total).astype(np.float32))
    s8 = TrainState(step=jnp.asarray(7, jnp.int32), params=params,
                    batch_stats={},
                    opt_state=Zero1State(jnp.asarray(7, jnp.int32),
                                         pad_to_world(vals, 8)))
    mgr = CheckpointManager(str(tmp_path / "w8"), track_best=False)
    mgr.save(1, s8, force=True)
    mgr.wait()
    assert mgr.metadata(1)["zero_layout"]["momentum_padded"] == 48

    z3 = zero2_sgd(schedule, world=3)
    tmpl3 = TrainState(step=jnp.zeros([], jnp.int32), params=params,
                       batch_stats={}, opt_state=z3.init(params))
    res = mgr.restore_latest_valid(tmpl3, world=3)
    mgr.close()
    assert res is not None and res.verified is True
    m3 = np.asarray(res.state.opt_state.momentum)
    assert m3.shape == np.asarray(z3.init(params).momentum).shape
    assert m3.shape[0] % 3 == 0 and m3.shape[0] >= total
    np.testing.assert_array_equal(m3[:total].view(np.uint32),
                                  np.asarray(vals).view(np.uint32))
    assert (m3[total:] == 0).all()
    for k in params:
        np.testing.assert_array_equal(
            np.asarray(res.state.params[k]).view(np.uint32),
            np.asarray(params[k]).view(np.uint32))

    # regrow: the W=3 snapshot reassembles bitwise at W=8
    z8 = zero2_sgd(schedule, world=8)
    mgr2 = CheckpointManager(str(tmp_path / "w3"), track_best=False)
    mgr2.save(1, res.state, force=True)
    mgr2.wait()
    tmpl8 = TrainState(step=jnp.zeros([], jnp.int32), params=params,
                       batch_stats={}, opt_state=z8.init(params))
    res8 = mgr2.restore_latest_valid(tmpl8, world=8)
    mgr2.close()
    assert res8 is not None
    np.testing.assert_array_equal(
        np.asarray(res8.state.opt_state.momentum).view(np.uint32),
        np.asarray(s8.opt_state.momentum).view(np.uint32))


def test_zero_elastic_shrink_regrow_keeps_escalated_precision(tmp_path):
    """ISSUE 19 satellite: a shrink that lands mid-escalation must
    resume INSIDE the precision ladder.  The supervisor's rung rides the
    checkpoint metadata sidecar through shrink AND regrow — the resumed
    run re-enters at the escalated format (and can still earn probation
    back to home), never re-diverges from rung 0."""
    from cpd_tpu.parallel.ring import pad_to_world
    from cpd_tpu.parallel.zero import Zero1State, zero2_sgd
    from cpd_tpu.resilience.precision import PrecisionSupervisor
    from cpd_tpu.train import CheckpointManager
    from cpd_tpu.train.state import TrainState

    schedule = lambda s: jnp.float32(0.05)                     # noqa: E731
    ladder = "e4m3,e5m7,e8m23"
    sup = PrecisionSupervisor(ladder, threshold=1e-3, patience=2,
                              probation=3)
    hot = {"prec_wire_sat": 50.0, "prec_wire_total": 100.0}
    sup.on_metrics(3, hot)
    assert sup.on_metrics(4, hot) == "escalate" and sup.name == "e5m7"

    params = {"w": jnp.asarray(np.random.RandomState(4)
                               .randn(42).astype(np.float32))}
    vals = jnp.asarray(np.random.RandomState(5)
                       .randn(42).astype(np.float32))
    s8 = TrainState(step=jnp.asarray(4, jnp.int32), params=params,
                    batch_stats={},
                    opt_state=Zero1State(jnp.asarray(4, jnp.int32),
                                         pad_to_world(vals, 8)))
    mgr = CheckpointManager(str(tmp_path / "w8"), track_best=False)
    mgr.save(4, s8, force=True, metadata={"precision": sup.state_dict()})
    mgr.wait()

    # shrink to W'=4: the sidecar hands the escalated rung to the run
    # that resumes at the smaller world
    z4 = zero2_sgd(schedule, world=4)
    tmpl4 = TrainState(step=jnp.zeros([], jnp.int32), params=params,
                       batch_stats={}, opt_state=z4.init(params))
    res = mgr.restore_latest_valid(tmpl4, world=4)
    mgr.close()
    assert res is not None and res.metadata["precision"]["level"] == 1
    sup4 = PrecisionSupervisor(ladder, threshold=1e-3, patience=2,
                               probation=3)
    sup4.load_state_dict(res.metadata["precision"])
    assert sup4.escalated and sup4.fmt == (5, 7) and sup4.home == (4, 3)

    # regrow to W=8: the rung survives the second re-flatten too
    mgr2 = CheckpointManager(str(tmp_path / "w4"), track_best=False)
    mgr2.save(5, res.state, force=True,
              metadata={"precision": sup4.state_dict()})
    mgr2.wait()
    z8 = zero2_sgd(schedule, world=8)
    tmpl8 = TrainState(step=jnp.zeros([], jnp.int32), params=params,
                       batch_stats={}, opt_state=z8.init(params))
    res8 = mgr2.restore_latest_valid(tmpl8, world=8)
    mgr2.close()
    assert res8 is not None
    sup8 = PrecisionSupervisor(ladder, threshold=1e-3, patience=2,
                               probation=3)
    sup8.load_state_dict(res8.metadata["precision"])
    assert sup8.escalated and sup8.fmt == (5, 7)
    # still INSIDE the ladder, not pinned: probation quiet steps earn
    # the home format back on the regrown fleet
    quiet = {"prec_wire_sat": 0.0, "prec_wire_total": 100.0}
    sup8.on_metrics(6, quiet)
    sup8.on_metrics(7, quiet)
    assert sup8.on_metrics(8, quiet) == "deescalate"
    assert sup8.fmt == sup8.home


def test_zero_elastic_tampered_sidecar_refused_before_restore(tmp_path):
    """ISSUE 19 satellite: a tampered checkpoint is refused BEFORE any
    param bytes are read back — `restore_latest_valid(world=W')` runs
    the digest check first, so the orbax restore is never even invoked
    for the bad step, and the scan falls back to the older valid one."""
    from cpd_tpu.parallel.ring import pad_to_world
    from cpd_tpu.parallel.zero import Zero1State, zero2_sgd
    from cpd_tpu.train import CheckpointManager
    from cpd_tpu.train.state import TrainState

    schedule = lambda s: jnp.float32(0.05)                     # noqa: E731
    params = {"w": jnp.arange(42, dtype=jnp.float32)}

    def snap(tag):
        return TrainState(
            step=jnp.asarray(tag, jnp.int32), params=params,
            batch_stats={},
            opt_state=Zero1State(jnp.asarray(tag, jnp.int32),
                                 pad_to_world(
                                     jnp.full((42,), float(tag)), 8)))

    mgr = CheckpointManager(str(tmp_path), track_best=False)
    try:
        mgr.save(2, snap(2), force=True)
        mgr.save(5, snap(5), force=True)
        mgr.wait()
        # flip one byte in the newest step's largest file
        victim, size = max(
            ((os.path.join(r, f), os.path.getsize(os.path.join(r, f)))
             for r, _, fs in os.walk(str(tmp_path / "5")) for f in fs),
            key=lambda t: t[1])
        with open(victim, "r+b") as f:
            f.seek(size // 2)
            byte = f.read(1)
            f.seek(size // 2)
            f.write(bytes([byte[0] ^ 0xFF]))

        restored_steps = []
        inner = mgr._mgr.restore

        def spy(step, *a, **kw):
            restored_steps.append(step)
            return inner(step, *a, **kw)

        mgr._mgr.restore = spy
        z4 = zero2_sgd(schedule, world=4)
        tmpl4 = TrainState(step=jnp.zeros([], jnp.int32), params=params,
                           batch_stats={}, opt_state=z4.init(params))
        res = mgr.restore_latest_valid(tmpl4, world=4)
        assert res is not None
        assert res.step == 2 and res.skipped == (5,)
        # the refusal happened at the digest, before any param read:
        # orbax only ever touched the surviving step
        assert restored_steps == [2]
        np.testing.assert_array_equal(
            np.asarray(res.state.opt_state.momentum)[:42], 2.0)
    finally:
        mgr.close()


@pytest.mark.slow
def test_zero2_lars_res_cifar_recipe():
    """The actual ResNet18/CIFAR LARS recipe (reference mix.py:297-310
    semantics: momentum 0.9, wd 5e-4, coefficient 0.001) with ZeRO-2:
    momentum + faithful reduction sharded, trust ratios from sharded
    norms — vs the replicated lars step on the real res_cifar model."""
    from cpd_tpu.models import get_model
    from cpd_tpu.parallel.zero import zero2_lars

    mesh = data_parallel_mesh()
    w = mesh.devices.size
    model = get_model("res_cifar")
    schedule = lambda s: jnp.float32(0.8)                      # noqa: E731
    x, y = _data(16, seed=9)

    tx = make_optimizer("lars", schedule, momentum=0.9,
                        weight_decay=5e-4)
    state = create_train_state(model, tx, x[:2], jax.random.PRNGKey(0))
    step = make_train_step(model, tx, mesh, donate=False, mode="faithful")
    s_ref, m_ref = step(state, x, y)

    z = zero2_lars(schedule, world=w, momentum=0.9, weight_decay=5e-4)
    z_state = TrainState(step=jnp.zeros([], jnp.int32),
                         params=state.params,
                         batch_stats=state.batch_stats,
                         opt_state=z.init(state.params))
    spec_tree = TrainState(step=P(), params=P(), batch_stats=P(),
                           opt_state=z.state_spec())
    z_state = jax.device_put(
        z_state, jax.tree.map(lambda s: NamedSharding(mesh, s), spec_tree,
                              is_leaf=lambda s: isinstance(s, P)))
    z_step = make_train_step(model, None, mesh, donate=False,
                             update_fn=z.update_fn,
                             opt_state_spec=z.state_spec(),
                             reduce_in_update=True, mode="faithful")
    s_z, m_z = z_step(z_state, x, y)

    np.testing.assert_allclose(float(m_z["loss"]), float(m_ref["loss"]),
                               rtol=1e-6)
    _assert_params_close(s_z.params, s_ref.params, rtol=2e-6, atol=2e-7)


@pytest.mark.slow
def test_zero3_sr_lm_fsdp():
    """FSDP-style LM training: a transformer LM through the generic
    make_train_step with ZeRO-3 params-at-rest sharding AND stochastic
    rounding on the pure-dp mesh — the large-LM data-parallel recipe —
    matches the replicated SR step end-to-end (loss + params to
    last-ulp; the reduction's shard==replicated-slice BITWISE property
    itself is pinned by test_zero2_reduce_scatter_bitwise_sr) and keeps
    params/momentum sharded 1/W."""
    from cpd_tpu.models import transformer_lm
    from cpd_tpu.parallel.zero import zero3_sgd

    mesh = data_parallel_mesh()
    w = mesh.devices.size
    model = transformer_lm(vocab_size=64, d_model=32, n_layers=2,
                           n_heads=4, d_ff=64)
    schedule = lambda s: jnp.float32(0.05)                     # noqa: E731
    rng = np.random.RandomState(31)
    toks = jnp.asarray(rng.randint(0, 64, (16, 16)).astype(np.int32))
    tgts = jnp.asarray(np.roll(np.asarray(toks), -1, axis=1))
    quant = dict(use_aps=True, grad_exp=4, grad_man=3,
                 grad_rounding="stochastic", grad_seed=3)

    tx = make_optimizer("sgd", schedule, momentum=0.9)
    state = create_train_state(model, tx, toks[:2], jax.random.PRNGKey(0))
    step = make_train_step(model, tx, mesh, donate=False, mode="faithful",
                           **quant)
    s_ref = state
    for _ in range(2):
        s_ref, m_ref = step(s_ref, toks, tgts)

    z = zero3_sgd(schedule, world=w, template=state.params, momentum=0.9)
    z_state = z.make_state(state, mesh)
    z_step = make_train_step(model, None, mesh, donate=False,
                             update_fn=z.update_fn,
                             opt_state_spec=z.state_spec(),
                             params_spec=z.param_spec(),
                             unpack_params=z.unpack,
                             reduce_in_update=True, **quant)
    s_z = z_state
    for _ in range(2):
        s_z, m_z = z_step(s_z, toks, tgts)

    np.testing.assert_allclose(float(m_z["loss"]), float(m_ref["loss"]),
                               rtol=1e-6)
    _assert_params_close(z.to_pytree(jnp.asarray(np.asarray(s_z.params))),
                         s_ref.params)
    n_params = sum(l.size for l in jax.tree.leaves(state.params))
    for arr in (s_z.params, s_z.opt_state.momentum):
        _assert_sharded_1w(arr, n_params, w)


def test_zero3_checkpoint_portable_across_world(tmp_path):
    """export_state's portable layout (pytree params, pad-trimmed
    momentum) restores at a DIFFERENT world size and keeps training."""
    from cpd_tpu.parallel.mesh import make_mesh
    from cpd_tpu.parallel.zero import zero3_sgd
    from cpd_tpu.train import CheckpointManager

    schedule = lambda s: jnp.float32(0.05)                     # noqa: E731
    model = tiny_cnn()
    x, y = _data(16, seed=7)
    tx = make_optimizer("sgd", schedule, momentum=0.9)
    state0 = create_train_state(model, tx, x[:2], jax.random.PRNGKey(0))

    def build(world, mesh):
        z = zero3_sgd(schedule, world=world, template=state0.params,
                      momentum=0.9)
        step = make_train_step(model, None, mesh, donate=False,
                               update_fn=z.update_fn,
                               opt_state_spec=z.state_spec(),
                               params_spec=z.param_spec(),
                               unpack_params=z.unpack,
                               reduce_in_update=True)
        return z, step

    mesh8 = data_parallel_mesh()
    z8, step8 = build(8, mesh8)
    s8 = z8.make_state(state0, mesh8)
    s8, _ = step8(s8, x, y)

    mgr = CheckpointManager(str(tmp_path), track_best=False)
    mgr.save(1, z8.export_state(s8), force=True)
    mgr.wait()

    mesh4 = make_mesh(dp=4, devices=jax.devices()[:4])
    z4, step4 = build(4, mesh4)
    restored = mgr.restore(z4.portable_template(state0))
    mgr.close()
    assert restored is not None
    s4 = z4.make_state(restored, mesh4)
    # params survive the world change exactly
    np.testing.assert_array_equal(
        np.concatenate([np.asarray(l).ravel()
                        for l in jax.tree.leaves(z4.to_pytree(
                            jnp.asarray(np.asarray(s4.params))))]),
        np.concatenate([np.asarray(l).ravel()
                        for l in jax.tree.leaves(
                            z8.to_pytree(jnp.asarray(np.asarray(
                                s8.params))))]))
    s4, m4 = step4(s4, x[:8], y[:8])
    assert np.isfinite(float(m4["loss"]))


def test_zero23_update_requires_reduce_in_update():
    """Building zero2/3 updates without reduce_in_update must fail at
    trace time, not silently double-count gradients by W."""
    from cpd_tpu.parallel.zero import zero3_sgd

    mesh = data_parallel_mesh()
    model = tiny_cnn()
    schedule = lambda s: jnp.float32(0.05)                     # noqa: E731
    tx = make_optimizer("sgd", schedule)
    x, y = _data(16)
    state = create_train_state(model, tx, x[:2], jax.random.PRNGKey(0))

    z2 = zero2_sgd(schedule, world=mesh.devices.size)
    z2_state = TrainState(step=jnp.zeros([], jnp.int32),
                          params=state.params,
                          batch_stats=state.batch_stats,
                          opt_state=z2.init(state.params))
    bad2 = make_train_step(model, None, mesh, donate=False,
                           update_fn=z2.update_fn,
                           opt_state_spec=z2.state_spec())  # no flag
    with pytest.raises(ValueError, match="reduce_in_update"):
        bad2(z2_state, x, y)

    z3 = zero3_sgd(schedule, world=mesh.devices.size,
                   template=state.params)
    z3_state = z3.make_state(state, mesh)
    bad3 = make_train_step(model, None, mesh, donate=False,
                           update_fn=z3.update_fn,
                           opt_state_spec=z3.state_spec(),
                           params_spec=z3.param_spec(),
                           unpack_params=z3.unpack)        # no flag
    with pytest.raises(ValueError, match="reduce_in_update"):
        bad3(z3_state, x, y)


def test_unpack_params_requires_update_fn():
    mesh = data_parallel_mesh()
    with pytest.raises(ValueError, match="unpack_params"):
        make_train_step(tiny_cnn(), None, mesh,
                        unpack_params=lambda p, a: p)


def test_reduce_in_update_requires_update_fn():
    mesh = data_parallel_mesh()
    with pytest.raises(ValueError, match="reduce_in_update"):
        make_train_step(tiny_cnn(), None, mesh, reduce_in_update=True)


def test_checkpoint_restore_directly_sharded(tmp_path):
    """CheckpointManager.restore(shardings=...) materializes each leaf in
    its target mesh layout — no single-device detour (round-2 addition)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from cpd_tpu.models import tiny_cnn
    from cpd_tpu.train import (CheckpointManager, create_train_state,
                               make_optimizer)

    mesh = data_parallel_mesh()
    model = tiny_cnn()
    tx = make_optimizer("sgd", lambda s: jnp.float32(0.1))
    x, _ = _data(8)
    state = create_train_state(model, tx, x[:2], jax.random.PRNGKey(0))
    z = zero1_sgd(lambda s: jnp.float32(0.1), world=mesh.devices.size)
    state = state.replace(opt_state=z.init(state.params))

    mgr = CheckpointManager(str(tmp_path), track_best=False)
    mgr.save(1, state, force=True)
    mgr.wait()

    spec_tree = TrainState(step=P(), params=P(), batch_stats=P(),
                           opt_state=z.state_spec())
    shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), spec_tree,
                             is_leaf=lambda s: isinstance(s, P))
    restored = mgr.restore(state, shardings=shardings)
    mgr.close()
    # momentum landed SHARDED 1/W per device, params replicated
    w = mesh.devices.size
    shard_shapes = {tuple(sh.data.shape)
                    for sh in restored.opt_state.momentum.addressable_shards}
    assert shard_shapes == {(restored.opt_state.momentum.shape[0] // w,)}
    for leaf in jax.tree.leaves(restored.params):
        assert len(leaf.sharding.device_set) == w   # replicated on all
    np.testing.assert_array_equal(
        np.asarray(restored.params["conv0"]["kernel"]),
        np.asarray(state.params["conv0"]["kernel"]))


# ---------------------------------------------------------------- ISSUE 12
# Block-scaled ZeRO-2 all_to_all wire, bucketed layout, and the overlap
# taps feeding reduce_in_update.

def _gather_shards(z, tree, mesh, **prec):
    """Run z._grad_shard inside shard_map and all_gather the per-rank
    shards into the oracle's (W*S,) rank-major layout."""
    from jax import lax

    def body(t):
        local = jax.tree.map(lambda g: g[0], t)
        sh = z._grad_shard(local, None, "dp", **prec)
        return lax.all_gather(sh, "dp", axis=0, tiled=True)

    in_spec = jax.tree.map(lambda _: P("dp"), tree)
    return jax.jit(shard_map(body, mesh=mesh, in_specs=(in_spec,),
                             out_specs=P(), check_vma=False))(tree)


def _odd_tree(w, seed=3):
    # odd leaf sizes -> shard chunks NOT divisible by the block size and
    # a non-empty world-size pad (33+85+19 = 137; ceil(137/8)=18 ->
    # pad 7, tail block of 18 % 8 = 2)
    rng = np.random.RandomState(seed)
    scale = np.exp2(rng.randint(-18, 12, size=(w, 1))).astype(np.float32)
    return {"a": jnp.asarray(rng.randn(w, 33).astype(np.float32) * scale),
            "b": jnp.asarray(rng.randn(w, 5, 17).astype(np.float32)),
            "c": jnp.asarray(rng.randn(w, 19).astype(np.float32) * scale)}


@pytest.mark.parametrize("exp,man,kahan,use_aps,sr", [
    (4, 3, False, True, False),
    pytest.param(5, 2, False, False, False, marks=pytest.mark.slow),
    pytest.param(4, 3, True, True, False, marks=pytest.mark.slow),
    pytest.param(4, 3, False, True, True, marks=pytest.mark.slow),
    pytest.param(5, 2, True, False, True, marks=pytest.mark.slow),
])  # one RTNE+APS combo in the default tier; the full matrix (and the
# reduce-smoke CI gate's 3 combos incl. SR/Kahan) ride the slow tier —
# suite-budget re-tiering, tests/test_zz_suite_budget.py
def test_zero2_blocked_matches_oracle(exp, man, kahan, use_aps, sr):
    """Blocked ZeRO-2 all_to_all (pack_exmy_blocked code words + shift
    sidecar on the wire, blocked scan casts) against the single-device
    `zero2_oracle_flat` — bitwise, across formats x kahan x rounding,
    with odd-tail shard chunks at a non-divisible block size."""
    from cpd_tpu.parallel.zero import zero2_oracle_flat
    mesh = data_parallel_mesh()
    w = mesh.devices.size
    tree = _odd_tree(w)
    z = zero2_sgd(lambda s: 0.1, world=w)
    key = jax.random.PRNGKey(5) if sr else None
    prec = dict(use_aps=use_aps, grad_exp=exp, grad_man=man,
                use_kahan=kahan, block_scale=True, block_size=8,
                key=key, rounding="stochastic" if sr else "nearest")
    got = _gather_shards(z, tree, mesh, **prec)
    want = zero2_oracle_flat(tree, w, use_aps=use_aps, grad_exp=exp,
                             grad_man=man, use_kahan=kahan, key=key,
                             block_scale=True, block_size=8)
    np.testing.assert_array_equal(np.asarray(got).view(np.uint32),
                                  np.asarray(want).view(np.uint32))


def test_zero2_blocked_wire_lossless_vs_unblocked():
    """The 'existing lossless path' gate: the blocked wire's
    pack -> all_to_all -> unpack trip reproduces the blocked-cast
    payload bit for bit (codec idempotence at the exact odd-tail
    (W, c) row shapes the ZeRO-2 wire ships), so riding the sidecar
    wire vs shipping the same blocked-cast values raw is a no-op."""
    from cpd_tpu.quant.numerics import (cast_body_blocked,
                                        pack_exmy_blocked,
                                        unpack_exmy_blocked)
    rng = np.random.RandomState(11)
    w, c = 8, 18                    # c % block != 0 -> odd tail block
    scale = np.exp2(rng.randint(-30, 20, size=(w, 1))).astype(np.float32)
    rows = jnp.asarray(rng.randn(w, c).astype(np.float32) * scale)
    for exp, man, block in [(4, 3, 8), (5, 2, 4), (5, 7, 16)]:
        cast = cast_body_blocked(rows, exp, man, block)
        wire = pack_exmy_blocked(cast, exp, man, block)
        back = unpack_exmy_blocked(wire, exp, man, c, block)
        np.testing.assert_array_equal(
            np.asarray(back).view(np.uint32),
            np.asarray(cast).view(np.uint32),
            err_msg=f"e{exp}m{man} block {block}")


def test_zero2_blocked_rejects_bad_formats():
    mesh = data_parallel_mesh()
    w = mesh.devices.size
    z = zero2_sgd(lambda s: 0.1, world=w)
    tree = {"a": jnp.zeros((w, 8), jnp.float32)}
    local = {"a": jnp.zeros((8,), jnp.float32)}
    with pytest.raises(ValueError, match=r"\(8, 23\)"):
        z._grad_shard(local, None, "dp", grad_exp=8, grad_man=23,
                      block_scale=True)
    with pytest.raises(ValueError, match="man_bits >= 2"):
        z._grad_shard(local, None, "dp", grad_exp=5, grad_man=1,
                      block_scale=True)
    del tree


@pytest.mark.slow
@pytest.mark.parametrize("block_scale", [False, True])
def test_zero2_bucketed_layout_matches_oracle(block_scale):
    """The bucketed flat layout (bucket_elems) — per-bucket all_to_all
    spans, interleaved pads — against the oracle at the same layout,
    per-tensor AND blocked wires."""
    from cpd_tpu.parallel.zero import zero2_oracle_flat
    mesh = data_parallel_mesh()
    w = mesh.devices.size
    tree = _odd_tree(w, seed=7)
    z = zero2_sgd(lambda s: 0.1, world=w, bucket_elems=64)
    lay = z._layout(jax.tree.map(lambda g: g[0], tree))
    assert len(lay.buckets) > 1   # the cap actually splits this tree
    prec = dict(use_aps=True, grad_exp=4, grad_man=3,
                block_scale=block_scale, block_size=8)
    got = _gather_shards(z, tree, mesh, **prec)
    want = zero2_oracle_flat(tree, w, use_aps=True, grad_exp=4,
                             grad_man=3, block_scale=block_scale,
                             block_size=8, bucket_elems=64)
    np.testing.assert_array_equal(np.asarray(got).view(np.uint32),
                                  np.asarray(want).view(np.uint32))


def test_zero2_bucketed_matches_unbucketed_values():
    """Bucketing is a WIRE layout, not a numerics change, on the
    per-tensor wire: the faithful scan is elementwise over ranks, so
    the bucketed shards reassemble to exactly the replicated faithful
    reduction (the pre-ISSUE-12 oracle, any bucket cap)."""
    from cpd_tpu.parallel.dist import sum_gradients
    from jax import lax
    mesh = data_parallel_mesh()
    w = mesh.devices.size
    tree = _odd_tree(w, seed=9)
    z = zero2_sgd(lambda s: 0.1, world=w, bucket_elems=64)
    template = jax.tree.map(lambda g: g[0], tree)
    lay = z._layout(template)

    got = _gather_shards(z, tree, mesh, use_aps=True, grad_exp=4,
                         grad_man=3)

    def body(t):
        local = jax.tree.map(lambda g: g[0], t)
        return sum_gradients(local, "dp", use_aps=True, grad_exp=4,
                             grad_man=3, mode="faithful")
    in_spec = jax.tree.map(lambda _: P("dp"), tree)
    ref = jax.jit(shard_map(body, mesh=mesh, in_specs=(in_spec,),
                            out_specs=jax.tree.map(lambda _: P(), tree),
                            check_vma=False))(tree)
    flat_ref = np.concatenate([np.asarray(l).ravel()
                               for l in jax.tree.leaves(ref)])
    # reassemble the bucketed rank-major gather into the flat layout
    stacked = np.asarray(got).reshape(w, lay.shard_size)
    off = 0
    for (a, m, c), idxs in zip(lay.meta, lay.buckets):
        span = stacked[:, off:off + c].reshape(-1)[:m]
        np.testing.assert_array_equal(span, flat_ref[a:a + m])
        off += c


def test_zero2_bucketed_export_import_roundtrip():
    from cpd_tpu.parallel.zero import Zero1State
    mesh = data_parallel_mesh()
    w = mesh.devices.size
    rng = np.random.RandomState(4)
    params = {"a": jnp.asarray(rng.randn(33).astype(np.float32)),
              "b": jnp.asarray(rng.randn(5, 17).astype(np.float32)),
              "c": jnp.asarray(rng.randn(19).astype(np.float32))}
    z = zero2_sgd(lambda s: 0.1, world=w, bucket_elems=64)
    lay = z._layout(params)
    assert len(lay.buckets) > 1
    mom = jnp.asarray(rng.randn(w * lay.shard_size).astype(np.float32))
    # zero the world-size pads (the Zero1State elastic invariant)
    mom_np = np.asarray(mom).reshape(w, lay.shard_size).copy()
    off = 0
    for a, m, c in lay.meta:
        span = mom_np[:, off:off + c].reshape(-1)
        span[m:] = 0.0
        mom_np[:, off:off + c] = span.reshape(w, c)
        off += c
    state = TrainState(step=jnp.zeros([], jnp.int32), params=params,
                       batch_stats={}, opt_state=Zero1State(
                           jnp.zeros([], jnp.int32),
                           jnp.asarray(mom_np.reshape(-1))))
    portable = z.export_state(state)
    assert portable.opt_state.momentum.shape == (lay.total,)
    back = z.import_state(portable)
    np.testing.assert_array_equal(np.asarray(back.opt_state.momentum),
                                  mom_np.reshape(-1))
    # and the portable layout re-pads at a DIFFERENT world size
    z4 = zero2_sgd(lambda s: 0.1, world=4, bucket_elems=64)
    lay4 = z4._layout(params)
    re4 = z4.import_state(portable)
    assert re4.opt_state.momentum.shape == (4 * lay4.shard_size,)
    p4 = z4.export_state(re4)
    np.testing.assert_array_equal(np.asarray(p4.opt_state.momentum),
                                  np.asarray(portable.opt_state.momentum))


@pytest.mark.slow
@pytest.mark.parametrize("bucket_elems,emulate", [(3000, 1), (None, 1)])
# both layouts in the slow tier (suite-budget re-tiering): the default
# tier keeps test_zero2_overlap_default_cap_regression — ZeRO overlap
# on/off bitwise at the default layout — plus the reduce-smoke CI gates
def test_zero2_overlap_bitwise_vs_monolith(bucket_elems, emulate):
    """ISSUE 12 acceptance: ZeRO-2 overlap on/off bitwise identical to
    the monolith at a fixed bucket layout — the taps run the updater's
    per-bucket reduce-scatter inside the backward (make_tap_reduce) and
    the update consumes the extracted shards.  bucket_elems=None is the
    legacy single-bucket layout (the monkeypatched-default regression
    lives in test_zero2_overlap_default_cap_regression)."""
    mesh = data_parallel_mesh()
    w = mesh.devices.size
    model = tiny_cnn()
    schedule = lambda s: jnp.float32(0.05)                     # noqa: E731
    x, y = _data(16 * emulate, seed=23)
    quant = dict(use_aps=True, grad_exp=4, grad_man=3,
                 grad_rounding="stochastic", grad_seed=11,
                 emulate_node=emulate, block_scale=True, block_size=128)

    tx = make_optimizer("sgd", schedule, momentum=0.9)
    state0 = create_train_state(model, tx, x[:2], jax.random.PRNGKey(0))
    z = zero2_sgd(schedule, world=w, momentum=0.9,
                  bucket_elems=bucket_elems)
    zs = TrainState(step=jnp.zeros([], jnp.int32), params=state0.params,
                    batch_stats=state0.batch_stats,
                    opt_state=z.init(state0.params))
    common = dict(update_fn=z.update_fn, opt_state_spec=z.state_spec(),
                  reduce_in_update=True, donate=False, **quant)
    mono = make_train_step(model, None, mesh, **common)
    tapped = make_train_step(model, None, mesh, overlap_reduce=True,
                             tap_reduce=z.make_tap_reduce,
                             bucket_elems=bucket_elems, **common)
    sa, ma = mono(zs, x, y)
    sb, mb = tapped(zs, x, y)
    for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_flatten_with_path(sa.params)[0],
            jax.tree_util.tree_flatten_with_path(sb.params)[0]):
        np.testing.assert_array_equal(np.asarray(a).view(np.uint32),
                                      np.asarray(b).view(np.uint32),
                                      err_msg=str(pa))
    np.testing.assert_array_equal(
        np.asarray(sa.opt_state.momentum),
        np.asarray(sb.opt_state.momentum))
    np.testing.assert_allclose(float(ma["loss"]), float(mb["loss"]),
                               rtol=0, atol=0)


def test_zero2_overlap_default_cap_regression(monkeypatch):
    """The monkeypatched-default regression (ISSUE 12 satellite): with
    overlap's DEFAULT_BUCKET_ELEMS shrunk so the generic tap plan WOULD
    split this tree, ZeRO overlap on/off must STAY bitwise at
    bucket_elems=None — the tap plan must come from the updater's own
    layout (make_tap_reduce), never the generic default cap."""
    import cpd_tpu.parallel.overlap as ov
    monkeypatch.setattr(ov, "DEFAULT_BUCKET_ELEMS", 64)
    mesh = data_parallel_mesh()
    w = mesh.devices.size
    model = tiny_cnn()
    schedule = lambda s: jnp.float32(0.05)                     # noqa: E731
    x, y = _data(16, seed=29)
    quant = dict(use_aps=True, grad_exp=4, grad_man=3)
    tx = make_optimizer("sgd", schedule, momentum=0.9)
    state0 = create_train_state(model, tx, x[:2], jax.random.PRNGKey(0))
    z = zero2_sgd(schedule, world=w, momentum=0.9)   # bucket_elems=None
    zs = TrainState(step=jnp.zeros([], jnp.int32), params=state0.params,
                    batch_stats=state0.batch_stats,
                    opt_state=z.init(state0.params))
    common = dict(update_fn=z.update_fn, opt_state_spec=z.state_spec(),
                  reduce_in_update=True, donate=False, **quant)
    sa, _ = make_train_step(model, None, mesh, **common)(zs, x, y)
    sb, _ = make_train_step(model, None, mesh, overlap_reduce=True,
                            tap_reduce=z.make_tap_reduce,
                            **common)(zs, x, y)
    for a, b in zip(jax.tree.leaves(sa.params),
                    jax.tree.leaves(sb.params)):
        np.testing.assert_array_equal(np.asarray(a).view(np.uint32),
                                      np.asarray(b).view(np.uint32))


@pytest.mark.slow
def test_zero1_composes_with_bucket_elems_and_overlap():
    """ZeRO-1 slices the step's fully-reduced gradients, so it composes
    with bucket_elems AND overlap_reduce with no updater hook — the
    lifted fail-fast's other half."""
    mesh = data_parallel_mesh()
    w = mesh.devices.size
    model = tiny_cnn()
    schedule = lambda s: jnp.float32(0.05)                     # noqa: E731
    x, y = _data(16, seed=31)
    tx = make_optimizer("sgd", schedule, momentum=0.9)
    state0 = create_train_state(model, tx, x[:2], jax.random.PRNGKey(0))
    z = zero1_sgd(schedule, world=w, momentum=0.9)
    zs = TrainState(step=jnp.zeros([], jnp.int32), params=state0.params,
                    batch_stats=state0.batch_stats,
                    opt_state=z.init(state0.params))
    common = dict(update_fn=z.update_fn, opt_state_spec=z.state_spec(),
                  donate=False, use_aps=True, grad_exp=5, grad_man=2)
    sa, _ = make_train_step(model, None, mesh, **common)(zs, x, y)
    sb, _ = make_train_step(model, None, mesh, overlap_reduce=True,
                            bucket_elems=3000, **common)(zs, x, y)
    for a, b in zip(jax.tree.leaves(sa.params),
                    jax.tree.leaves(sb.params)):
        np.testing.assert_array_equal(np.asarray(a).view(np.uint32),
                                      np.asarray(b).view(np.uint32))
