"""Smoke tests for the example CLI trainers — the end-to-end entry points
mirroring the reference's example/ scripts (SURVEY.md C18-C20, C22), run
with tiny synthetic workloads on the 8-device virtual CPU mesh.

These are the integration layer of the test pyramid the reference lacks
(SURVEY.md §4): each trainer must parse its reference-parity flags, build
the sharded quantized step, run real iterations, checkpoint, and report
metrics through the reference's log line protocol.
"""

import json
import math
import os

import numpy as np
import pytest

# every test here compiles a full trainer graph — the compile-heavy tier
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def tiny_cifar(tmp_path_factory, tiny_cifar_factory):
    return tiny_cifar_factory(tmp_path_factory.mktemp("cifar"))


@pytest.mark.parametrize("mode", ["fast", "faithful"])
def test_resnet18_trainer_aps_smoke(tiny_cifar, tmp_path, capsys, mode):
    from resnet18_cifar.train import main

    save = str(tmp_path / "ckpt")
    prof = str(tmp_path / "trace")
    extra = ["--profile-dir", prof] if mode == "fast" else []
    res = main(["--use_APS", "--grad_exp", "5", "--grad_man", "2",
                "--emulate_node", "2", "--use_lars", "--arch", "tiny",
                "--data-root", tiny_cifar, "--max-iter", "4",
                "--batch_size", "2", "--val_freq", "4",
                "--save_path", save, "--mode", mode] + extra)
    if mode == "fast":
        # jax.profiler must have written trace artifacts for steps 3..4
        found = [os.path.join(r, f) for r, _, fs in os.walk(prof) for f in fs]
        assert found, "no profiler trace artifacts written"
    assert res["step"] == 4
    assert math.isfinite(res["loss"])
    out = capsys.readouterr().out
    assert "* All Loss" in out            # draw_curve's grep contract
    # scalar stream exists and parses
    jsonl = os.path.join(save, "logs", "scalars.jsonl")
    assert os.path.isfile(jsonl)
    with open(jsonl) as f:
        recs = [json.loads(line) for line in f]
    assert any(r["tag"] == "train/loss" for r in recs)
    # checkpoint written at the val_freq boundary -> resumable
    from cpd_tpu.train import CheckpointManager
    mgr = CheckpointManager(save, track_best=False)
    assert mgr.latest_step() == 4
    mgr.close()


def test_resnet18_trainer_overlap_smoke(tiny_cifar, tmp_path):
    """--overlap-reduce end to end (ISSUE 8): the bucketed in-backward
    ring transport trains through the full CLI harness."""
    from resnet18_cifar.train import main

    res = main(["--use_APS", "--grad_exp", "5", "--grad_man", "2",
                "--emulate_node", "1", "--arch", "tiny",
                "--data-root", tiny_cifar, "--max-iter", "3",
                "--batch_size", "2", "--val_freq", "4",
                "--save_path", str(tmp_path / "ckpt"), "--mode", "ring",
                "--overlap-reduce", "--bucket-elems", "4096"])
    assert res["step"] == 3
    assert math.isfinite(res["loss"])


def test_resnet18_halts_on_nonfinite_loss(tiny_cifar, tmp_path, capsys):
    """A diverged run (NaN/inf loss) must stop with a clear verdict — a
    controlled stop (diverged=True in the result, teardown runs), not an
    exception that would kill in-process harnesses like aps_golden."""
    from resnet18_cifar.train import main

    res = main(["--arch", "tiny", "--data-root", tiny_cifar,
                "--max-iter", "8", "--batch_size", "2", "--val_freq", "8",
                "--peak-lr", "1e8",
                "--save_path", str(tmp_path / "ck"), "--mode", "fast"])
    assert res["diverged"] is True
    assert res["step"] < 8                     # stopped early
    err = capsys.readouterr().err
    assert "non-finite loss" in err and "diverged" in err


def test_resnet18_trainer_quant_optimizer_smoke(tiny_cifar, tmp_path):
    """--opt_exp/--opt_man: e5m2 Kahan momentum buffer through the CLI."""
    from resnet18_cifar.train import main

    res = main(["--arch", "tiny", "--data-root", tiny_cifar,
                "--max-iter", "3", "--batch_size", "2", "--val_freq", "3",
                "--opt_exp", "5", "--opt_man", "2", "--opt_kahan",
                "--save_path", str(tmp_path / "ck"), "--mode", "fast"])
    assert res["step"] == 3
    assert math.isfinite(res["loss"])


def test_resnet18_trainer_shampoo_lite_smoke(tiny_cifar, tmp_path):
    """--optimizer shampoo-lite at e5m7 ring statistics (ISSUE 15):
    the second-order updater owns the collective (reduce_in_update,
    like ZeRO) and the smoke must train inside the pinned loss
    envelope — CE for 10 classes starts at ln(10) ~= 2.303; a broken
    preconditioner (wrong grafting scale, bad inverse root) blows
    straight past it in the first steps."""
    from resnet18_cifar.train import main

    res = main(["--optimizer", "shampoo-lite",
                "--shampoo-stat-exp", "5", "--shampoo-stat-man", "7",
                "--arch", "tiny", "--data-root", tiny_cifar,
                "--max-iter", "3", "--batch_size", "2",
                "--val_freq", "3", "--use_kahan",
                "--save_path", str(tmp_path / "ck")])
    assert res["step"] == 3
    assert math.isfinite(res["loss"])
    assert res["loss"] <= 2.6, \
        f"shampoo-lite smoke loss {res['loss']:.3f} outside the " \
        f"pinned envelope (measured ~2.30 on this fixture)"
    assert not res["diverged"]


def test_resnet18_shampoo_lite_flag_conflicts(tiny_cifar, tmp_path):
    from resnet18_cifar.train import main

    base = ["--optimizer", "shampoo-lite", "--arch", "tiny",
            "--data-root", tiny_cifar, "--max-iter", "1",
            "--batch_size", "2", "--val_freq", "1",
            "--save_path", str(tmp_path / "ck")]
    for bad in (["--use_lars"], ["--opt_exp", "5", "--opt_man", "2"],
                ["--zero1"], ["--clip-grad", "1.0"],
                ["--overlap-reduce"], ["--bucket-elems", "4096"]):
        with pytest.raises(SystemExit):
            main(base + bad)
    # review regression: an explicit non-quant optimizer must not
    # silently drop the quantized-momentum flags (auto would have
    # selected quant_sgd for them)
    with pytest.raises(SystemExit, match="ignore"):
        main(["--optimizer", "sgd", "--opt_exp", "5", "--opt_man", "2",
              "--arch", "tiny", "--data-root", tiny_cifar,
              "--max-iter", "1", "--batch_size", "2", "--val_freq", "1",
              "--save_path", str(tmp_path / "ck2")])


def test_resnet18_trainer_evaluate_flag(tiny_cifar):
    from resnet18_cifar.train import main

    res = main(["-e", "--arch", "tiny", "--data-root", tiny_cifar])
    assert set(res) == {"loss", "top1", "top5"}


def test_davidnet_trainer_smoke(tiny_cifar, capsys):
    from davidnet.dawn import main

    # faithful mode: the gather+ordered-scan collective end-to-end
    res = main(["--epoch", "2", "--batch_size", "16", "--arch", "tiny",
                "--max-batches-per-epoch", "2", "--half", "1",
                "--use_APS", "--grad_exp", "5", "--grad_man", "2",
                "--loss_scale", "128", "--data-root", tiny_cifar,
                "--mode", "faithful"])
    assert res["epoch"] == 2
    assert math.isfinite(res["train loss"])
    out = capsys.readouterr().out
    assert "epoch\thours\ttop1Accuracy" in out   # DAWNBench TSV header


def test_resnet50_trainer_smoke_and_resume(tmp_path, capsys):
    from resnet50.main import main

    ckpt = str(tmp_path / "ck")
    logs = str(tmp_path / "logs")
    argv = ["--batch-size", "1", "--epochs", "1", "--arch", "tiny",
            "--num-classes", "10",
            "--max-batches-per-epoch", "2", "--image-size", "32",
            "--use-APS", "--grad_exp", "5", "--grad_man", "2",
            "--emulate-node", "2", "--checkpoint-dir", ckpt,
            "--log-dir", logs, "--mode", "faithful"]
    res = main(argv)
    assert res["epoch"] == 0
    assert math.isfinite(res["train_loss"])
    # second invocation must auto-resume past epoch 0 and do nothing
    res2 = main(argv)
    out = capsys.readouterr().out
    assert "auto-resumed" in out
    assert "epoch" not in res2             # all epochs already done


def _make_fake_guard(trigger_after_polls):
    """Deterministic PreemptionGuard stand-in: should_stop() turns True
    after N polls, so trainer save/resume logic is exercised without real
    signal timing (the signal mechanics have their own unit test)."""

    class FakeGuard:
        def __init__(self, *a, **k):
            self.polls = 0

        @property
        def triggered(self):
            return self.polls > trigger_after_polls

        def should_stop(self):
            self.polls += 1
            return self.triggered

        def uninstall(self):
            pass

    return FakeGuard


def test_preemption_guard_signal_mechanics():
    import signal

    from cpd_tpu.train import PreemptionGuard

    guard = PreemptionGuard()
    try:
        assert not guard.triggered
        os.kill(os.getpid(), signal.SIGTERM)   # delivered synchronously
        assert guard.triggered
    finally:
        guard.uninstall()
    # uninstall restored the previous disposition
    assert signal.getsignal(signal.SIGTERM) != guard._handle


def test_resnet50_preempt_saves_and_resumes_mid_epoch(tmp_path, capsys,
                                                      monkeypatch):
    """SIGTERM mid-epoch → checkpoint with (epoch, iter) → exact resume.

    The guard's signal mechanics are unit-tested above; here a fake guard
    triggers deterministically after one step so the trainer's
    save/resume logic is exercised without real signal timing."""
    from cpd_tpu.train import CheckpointManager, checkpoint
    from resnet50.main import main

    FakeGuard = _make_fake_guard(1)

    ckpt = str(tmp_path / "ck")
    argv = ["--batch-size", "1", "--epochs", "1", "--arch", "tiny",
            "--num-classes", "10", "--max-batches-per-epoch", "3",
            "--image-size", "32", "--use-APS", "--grad_exp", "5",
            "--grad_man", "2", "--checkpoint-dir", ckpt,
            "--log-dir", str(tmp_path / "logs"), "--mode", "fast"]

    monkeypatch.setattr(checkpoint, "PreemptionGuard", FakeGuard)
    res = main(argv)
    out = capsys.readouterr().out
    assert "preempted: saved step 1" in out
    assert "(epoch 0 iter 1)" in out
    assert "epoch" not in res              # epoch never completed

    mgr = CheckpointManager(ckpt, track_best=False)
    meta = mgr.metadata()
    mgr.close()
    assert meta == {"epoch": 0, "resume_it": 1, "iters_per_epoch": 3,
                    "global_batch": 8, "world": 1}   # batch 1 x 8 devices

    monkeypatch.undo()                     # real (never-fired) guard
    res2 = main(argv)
    out = capsys.readouterr().out
    assert "auto-resumed from epoch 0 iter 1" in out
    assert res2["epoch"] == 0
    assert math.isfinite(res2["train_loss"])


def test_resnet50_preempt_geometry_change_restarts_epoch(tmp_path, capsys,
                                                         monkeypatch):
    """resume_it is only exact for identical iteration geometry; when
    --max-batches-per-epoch changes after a preemption, the interrupted
    epoch restarts from iter 0 instead of mis-indexing the sampler."""
    from cpd_tpu.train import checkpoint
    from resnet50.main import main

    FakeGuard = _make_fake_guard(1)

    ckpt = str(tmp_path / "ck")
    base = ["--batch-size", "1", "--epochs", "1", "--arch", "tiny",
            "--num-classes", "10", "--image-size", "32", "--grad_exp", "5",
            "--grad_man", "2", "--checkpoint-dir", ckpt,
            "--log-dir", str(tmp_path / "logs"), "--mode", "fast"]
    monkeypatch.setattr(checkpoint, "PreemptionGuard", FakeGuard)
    main(base + ["--max-batches-per-epoch", "3"])
    capsys.readouterr()

    monkeypatch.undo()
    res = main(base + ["--max-batches-per-epoch", "2"])
    out = capsys.readouterr().out
    assert "iteration geometry changed" in out
    assert "auto-resumed from epoch 0" in out
    assert res["epoch"] == 0


def test_resnet18_preempt_saves_and_resumes(tmp_path, tiny_cifar, capsys,
                                            monkeypatch):
    """Iteration-based trainer: preempt at iter 2, resume at exactly 2."""
    from cpd_tpu.train import checkpoint
    from resnet18_cifar.train import main

    FakeGuard = _make_fake_guard(2)

    argv = ["--arch", "tiny", "--max-iter", "4", "--batch_size", "2",
            "--val_freq", "4", "--data-root", tiny_cifar,
            "--save_path", str(tmp_path / "ck"), "--mode", "fast"]
    monkeypatch.setattr(checkpoint, "PreemptionGuard", FakeGuard)
    res = main(argv)
    out = capsys.readouterr().out
    assert "preempted: saved iter 2" in out
    assert res["step"] == 2

    monkeypatch.undo()
    res2 = main(argv)
    out = capsys.readouterr().out
    assert "resumed from iter 2" in out
    assert res2["step"] == 4


def test_resnet50_trainer_on_committed_imagefolder(tmp_path):
    """The FLAGSHIP trainer's real-data path on COMMITTED bytes (round
    5): --train-dir points at the in-repo ImageFolder fixture, so the
    PIL decode + RandomResizedCrop + center-crop val pipeline runs on
    files the process did not fabricate — the ImageNet analog of the
    CIFAR canary."""
    from resnet50.main import main

    fixture = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "fixtures", "imagenet_folder")
    res = main(["--train-dir", fixture, "--batch-size", "1",
                "--epochs", "1", "--arch", "tiny", "--num-classes", "10",
                "--max-batches-per-epoch", "2", "--image-size", "32",
                "--use-APS", "--grad_exp", "5", "--grad_man", "2",
                "--checkpoint-dir", str(tmp_path / "ck"),
                "--log-dir", str(tmp_path / "logs"), "--mode", "fast"])
    assert res["epoch"] == 0
    assert math.isfinite(res["train_loss"])
    assert math.isfinite(res["val_loss"])


def test_resnet50_trainer_zero1_smoke(tmp_path):
    """--zero1 shards the momentum 1/N over dp through the flagship CLI."""
    from resnet50.main import main

    res = main(["--batch-size", "1", "--epochs", "1", "--arch", "tiny",
                "--num-classes", "10", "--max-batches-per-epoch", "2",
                "--image-size", "32", "--use-APS", "--grad_exp", "5",
                "--grad_man", "2", "--zero1",
                "--checkpoint-dir", str(tmp_path / "ck"),
                "--log-dir", str(tmp_path / "logs"), "--mode", "fast"])
    assert res["epoch"] == 0
    assert math.isfinite(res["train_loss"])


def test_resnet50_trainer_zero3_smoke(tmp_path):
    """--zero3 shards params+momentum+reduction 1/N over dp through the
    flagship CLI, including the unpacked-eval validation path."""
    from resnet50.main import main

    res = main(["--batch-size", "1", "--epochs", "1", "--arch", "tiny",
                "--num-classes", "10", "--max-batches-per-epoch", "2",
                "--image-size", "32", "--use-APS", "--grad_exp", "5",
                "--grad_man", "2", "--zero3",
                "--checkpoint-dir", str(tmp_path / "ck"),
                "--log-dir", str(tmp_path / "logs"), "--mode", "faithful"])
    assert res["epoch"] == 0
    assert math.isfinite(res["train_loss"])
    assert math.isfinite(res["val_loss"])


def test_resnet18_trainer_zero2_lars_smoke(tiny_cifar, tmp_path, capsys):
    """--zero2 + --use_lars through the LARS-recipe CLI (round 5): the
    sharded faithful reduction AND sharded per-layer trust ratios, end
    to end with APS."""
    from resnet18_cifar.train import main

    res = main(["--use_APS", "--grad_exp", "5", "--grad_man", "2",
                "--use_lars", "--zero2", "--arch", "tiny",
                "--data-root", tiny_cifar, "--max-iter", "4",
                "--batch_size", "2", "--val_freq", "4",
                "--save_path", str(tmp_path / "ck"), "--mode",
                "faithful"])
    assert math.isfinite(res["best_prec1"])
    out = capsys.readouterr().out
    assert "All Loss" in out


def test_resnet18_trainer_resume_continues_training(tiny_cifar, tmp_path):
    """Auto-resume must REPLICATE the orbax-restored state back onto the
    mesh and keep training — restore committed the arrays to one device,
    which crashed the sharded step (round-2 regression)."""
    from resnet18_cifar.train import main

    save = str(tmp_path / "ckpt")
    common = ["--arch", "tiny", "--data-root", tiny_cifar,
              "--batch_size", "2", "--val_freq", "100",
              "--save_path", save, "--mode", "fast"]
    res1 = main(common + ["--max-iter", "2"])
    assert res1["step"] == 2
    res2 = main(common + ["--max-iter", "4"])   # resumes at 2, trains 2 more
    assert res2["step"] == 4
    assert math.isfinite(res2["loss"])


def test_fcn_trainer_on_committed_cityscapes_tree(tmp_path):
    """The FCN trainer's real-data path on COMMITTED bytes (round 5):
    --data-root points at the in-repo leftImg8bit/gtFine fixture —
    completing the committed-real-format trio (CIFAR, ImageNet
    ImageFolder, Cityscapes)."""
    from fcn.train import main

    fixture = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "fixtures", "cityscapes_tree")
    res = main(["--crop-size", "32", "--batch-size", "1", "--data-root",
                fixture, "--tiny-backbone", "--use_APS", "--grad_exp",
                "5", "--grad_man", "2", "--max-iter", "2", "--val-freq",
                "2", "--save-path", str(tmp_path / "fcn"),
                "--mode", "fast"])
    assert res["step"] == 2
    assert math.isfinite(res["loss"])
    assert 0.0 <= res["val_pix_acc"] <= 1.0


def test_fcn_trainer_smoke(tmp_path):
    from fcn.train import main

    # faithful mode + aux head + REAL-format Cityscapes tree: stage-3
    # auxiliary loss through the full quantized pipeline, fed by the
    # leftImg8bit/gtFine walker (19 trainId classes)
    root = _write_tiny_cityscapes(str(tmp_path / "cs"))
    common = ["--crop-size", "32", "--batch-size", "1", "--data-root", root,
              "--tiny-backbone", "--aux-head", "--use_APS",
              "--grad_exp", "5", "--grad_man", "2", "--ckpt-freq", "2",
              "--save-path", str(tmp_path / "fcn"), "--mode", "faithful"]
    res = main(common + ["--max-iter", "2", "--val-freq", "2"])
    assert res["step"] == 2
    assert math.isfinite(res["loss"])
    assert 0.0 <= res["accuracy"] <= 1.0
    # periodic seg evaluation ran (mmseg EvalHook parity): pixAcc + mIoU
    assert 0.0 <= res["val_pix_acc"] <= 1.0
    assert 0.0 <= res["val_miou"] <= 1.0
    # interval checkpoint written; a second invocation must drive FCN's
    # OWN restore -> replicate wiring (train.py keeps its own copy of
    # that block, so the resnet18/resnet50 resume tests don't cover it).
    # No --val-freq: the resumed run has 0 iters left and must not pay
    # the eval-graph compile again.
    res2 = main(common + ["--max-iter", "2"])
    assert res2["step"] == 2 and "loss" not in res2


def test_draw_curve_parses_both_formats(tmp_path):
    import draw_curve

    log = tmp_path / "aps.log"
    log.write_text("noise\n * All Loss 1.2345 Prec@1 55.000 Prec@5 90.000\n"
                   " * All Loss 1.1000 Prec@1 60.000 Prec@5 92.000\n")
    assert draw_curve.parse_stdout_log(str(log)) == [55.0, 60.0]

    jsonl = tmp_path / "scalars.jsonl"
    jsonl.write_text(json.dumps({"tag": "val/top1", "step": 1,
                                 "value": 0.5}) + "\n" +
                     json.dumps({"tag": "train/loss", "step": 1,
                                 "value": 2.0}) + "\n")
    assert draw_curve.parse_jsonl(str(jsonl)) == [50.0]

    out = tmp_path / "c.png"
    draw_curve.main([str(log), str(jsonl), "-o", str(out)])
    assert out.is_file()


def test_synthetic_imagenet_determinism():
    from cpd_tpu.data.imagenet import SyntheticImageNet

    ds = ds2 = None
    ds = SyntheticImageNet(16, num_classes=10, size=8, seed=3)
    ds2 = SyntheticImageNet(16, num_classes=10, size=8, seed=3)
    x1, y1 = ds.batch([0, 5, 7])
    x2, y2 = ds2.batch([0, 5, 7])
    np.testing.assert_array_equal(y1, y2)
    np.testing.assert_array_equal(x1, x2)
    assert x1.shape == (3, 8, 8, 3)


def test_image_folder_dataset(tmp_path):
    from PIL import Image

    from cpd_tpu.data.imagenet import ImageFolderDataset

    rng = np.random.RandomState(0)
    for cls in ("cat", "dog"):
        d = tmp_path / cls
        d.mkdir()
        for i in range(2):
            arr = rng.randint(0, 255, size=(40, 48, 3), dtype=np.uint8)
            Image.fromarray(arr).save(d / f"{i}.png")
    ds = ImageFolderDataset(str(tmp_path), size=16, train=True)
    assert len(ds) == 4
    assert ds.class_to_idx == {"cat": 0, "dog": 1}
    x, y = ds.batch([0, 3], seed=1)
    assert x.shape == (2, 16, 16, 3)
    assert list(y) == [0, 1]
    # eval path: deterministic center crop
    ev = ImageFolderDataset(str(tmp_path), size=16, train=False)
    x1, _ = ev.batch([1])
    x2, _ = ev.batch([1])
    np.testing.assert_array_equal(x1, x2)


def _write_tiny_cityscapes(root, n_imgs=3, h=64, w=96):
    """Real-format leftImg8bit/gtFine fixture tree (two cities)."""
    from PIL import Image

    rng = np.random.RandomState(0)
    for city_i, city in enumerate(("aaa", "bbb")):
        for k in range(n_imgs):
            stem = f"{city}_{k:06d}_000019"
            img_dir = os.path.join(root, "leftImg8bit", "train", city)
            lab_dir = os.path.join(root, "gtFine", "train", city)
            os.makedirs(img_dir, exist_ok=True)
            os.makedirs(lab_dir, exist_ok=True)
            img = rng.randint(0, 256, (h, w, 3), dtype=np.uint8)
            # raw labelIds: road(7), car(26), sky(23) bands + void(0) strip
            lab = np.zeros((h, w), np.uint8)
            lab[: h // 3] = 23
            lab[h // 3: 2 * h // 3] = 7
            lab[2 * h // 3:] = 26
            lab[:, : w // 3] = 0                # void -> ignore
            Image.fromarray(img).save(
                os.path.join(img_dir, stem + "_leftImg8bit.png"))
            Image.fromarray(lab).save(
                os.path.join(lab_dir, stem + "_gtFine_labelIds.png"))
    return root


def test_cityscapes_loader_real_tree(tmp_path):
    from cpd_tpu.data.segmentation import (CITYSCAPES_IGNORE,
                                           CityscapesDataset,
                                           load_segmentation)

    root = _write_tiny_cityscapes(str(tmp_path))
    ds = load_segmentation(root, crop_size=48)
    assert isinstance(ds, CityscapesDataset)
    assert len(ds) == 6
    x, y = ds.batch([0, 3, 5], seed=1)
    assert x.shape == (3, 48, 48, 3) and x.dtype == np.float32
    assert y.shape == (3, 48, 48) and y.dtype == np.int32
    # labelId -> trainId: only {sky=10, road=0, car=13, ignore} can appear
    assert set(np.unique(y)) <= {0, 10, 13, CITYSCAPES_IGNORE}
    assert CITYSCAPES_IGNORE in np.unique(y)    # the void strip
    # normalized pixels are z-scores, not raw bytes
    assert np.abs(x).max() < 5.0
    # determinism under the (seed, index) contract
    x2, y2 = ds.batch([0, 3, 5], seed=1)
    np.testing.assert_array_equal(x, x2)
    np.testing.assert_array_equal(y, y2)
    # different seed -> different crops somewhere
    x3, _ = ds.batch([0, 3, 5], seed=2)
    assert not np.array_equal(x, x3)


def test_cityscapes_loader_pads_small_images(tmp_path):
    from cpd_tpu.data.segmentation import (CITYSCAPES_IGNORE,
                                           CityscapesDataset)

    root = _write_tiny_cityscapes(str(tmp_path), h=32, w=40)
    ds = CityscapesDataset(root, crop_size=64, flip=False)
    x, y = ds.batch([0], seed=0)
    # padded region: ignore labels, zero pixels
    assert np.all(y[0, 32:, :] == CITYSCAPES_IGNORE)
    assert np.all(x[0, 32:, :, :] == 0.0)
    assert np.any(y[0, :32, :40] != CITYSCAPES_IGNORE)


def test_load_segmentation_explicit_root_is_strict(tmp_path):
    """No root -> synthetic stand-in; an EXPLICIT root with no Cityscapes
    tree raises (a typo'd --data-root must not silently train on
    synthetic data — QUICKSTART.md contract)."""
    from cpd_tpu.data.segmentation import (SyntheticSegmentation,
                                           load_segmentation)

    ds = load_segmentation(None, crop_size=32, synthetic_size=8)
    assert isinstance(ds, SyntheticSegmentation)
    assert len(ds) == 8
    with pytest.raises(FileNotFoundError):
        load_segmentation(str(tmp_path / "nope"), crop_size=32)


def test_seg_loss_ignores_ignore_label():
    import jax.numpy as jnp

    from cpd_tpu.train import seg_cross_entropy_loss

    loss_fn = seg_cross_entropy_loss(ignore_label=255)
    logits = jnp.zeros((1, 2, 2, 3))
    labels = jnp.array([[[0, 255], [255, 255]]])
    # only one valid pixel, uniform logits -> CE = log(3)
    assert np.isclose(float(loss_fn(logits, labels)), np.log(3), atol=1e-6)


def test_lm_trainer_smoke(tmp_path):
    from lm.train import main

    argv = ["--dp", "2", "--sp", "2", "--tp", "2", "--seq-len", "32",
            "--d-model", "32", "--n-layers", "2", "--n-heads", "4",
            "--vocab-size", "64", "--batch-size", "2", "--max-iter", "3",
            "--use_APS", "--grad_exp", "5", "--grad_man", "2",
            "--ckpt-freq", "3", "--sample", "4",
            "--save-path", str(tmp_path / "lm"), "--mode", "faithful"]
    res = main(argv)
    assert res["step"] == 3
    assert math.isfinite(res["loss"])
    # --sample decoded 4 new tokens from an 8-token prompt
    assert len(res["sample"]) == 12
    assert all(0 <= t < 64 for t in res["sample"])
    # sharded-state checkpoint written; auto-resume restores and re-lays
    # it out over the dp x sp x tp mesh (0 iters left)
    res2 = main(argv)
    assert res2["step"] == 3 and "loss" not in res2


def test_lm_trainer_flash_gqa_pallas_bwd_reaches_kernel(tmp_path,
                                                       monkeypatch):
    """--attn-impl flash --n-kv-heads --flash-bwd pallas must actually
    route through the GQA flash kernel WITH the requested backward —
    regression for the round-5 indentation slip that left
    `model_kw.update(attn_impl=...)` stranded after a raise, silently
    training with xla attention while the flags validated clean."""
    import sys

    import cpd_tpu.ops.flash_gqa  # noqa: F401
    fg_mod = sys.modules["cpd_tpu.ops.flash_gqa"]
    from lm.train import main

    calls = []
    real = fg_mod.flash_gqa

    def spy(q, k, v, causal=True, bwd="chunked"):
        calls.append((q.shape[2], k.shape[2], bwd))
        return real(q, k, v, causal, bwd)

    monkeypatch.setattr(fg_mod, "flash_gqa", spy)
    res = main(["--dp", "8", "--seq-len", "16", "--d-model", "32",
                "--n-layers", "1", "--n-heads", "4", "--n-kv-heads", "2",
                "--attn-impl", "flash", "--flash-bwd", "pallas",
                "--vocab-size", "32", "--batch-size", "2",
                "--max-iter", "2", "--save-path", str(tmp_path / "lm")])
    assert math.isfinite(res["loss"])
    assert calls and all(c == (4, 2, "pallas") for c in calls), calls


def test_lm_trainer_pp_and_moe_paths(tmp_path):
    """--pp and --moe switch the trainer onto the pipeline / expert
    parallel step builders (GPipe streaming, all_to_all dispatch)."""
    from lm.train import main

    common = ["--seq-len", "32", "--d-model", "32", "--n-layers", "2",
              "--n-heads", "4", "--vocab-size", "64", "--batch-size", "4",
              "--max-iter", "2", "--val-freq", "2", "--ckpt-freq", "99",
              "--use_APS", "--grad_exp", "5", "--grad_man", "2"]
    r = main(common + ["--dp", "4", "--pp", "2",
                       "--save-path", str(tmp_path / "pp")])
    assert r["step"] == 2 and math.isfinite(r["loss"])
    r = main(common + ["--dp", "4", "--moe", "--ep", "2",
                       "--n-experts", "4",
                       "--save-path", str(tmp_path / "moe")])
    assert r["step"] == 2 and math.isfinite(r["loss"])


def test_load_cifar10_explicit_root_is_strict(tiny_cifar, tmp_path):
    """Explicit root: real tree loads, missing tree raises (never a silent
    synthetic fallback — QUICKSTART.md contract)."""
    from cpd_tpu.data.cifar import load_cifar10

    tx, ty, vx, vy = load_cifar10(tiny_cifar)
    assert tx.shape == (510, 32, 32, 3) and tx.dtype == np.uint8
    assert len(vy) == 64
    with pytest.raises(FileNotFoundError):
        load_cifar10(str(tmp_path / "nope"))


def test_load_imagenet_explicit_root_is_strict(tmp_path):
    from cpd_tpu.data.imagenet import load_imagenet

    with pytest.raises(FileNotFoundError):
        load_imagenet(str(tmp_path / "nope"))


def test_resnet50_trainer_vit_arch(tmp_path):
    """--arch vit: the registry's uniform model contract lets the ImageNet
    trainer drive the ViT family through the same quantized APS step."""
    from resnet50.main import main

    res = main(["--batch-size", "1", "--epochs", "1", "--arch", "vit",
                "--num-classes", "10", "--max-batches-per-epoch", "2",
                "--image-size", "32", "--use-APS", "--grad_exp", "5",
                "--grad_man", "2", "--checkpoint-dir",
                str(tmp_path / "ck"), "--log-dir", str(tmp_path / "logs"),
                "--mode", "faithful"])
    assert res["epoch"] == 0
    assert math.isfinite(res["train_loss"])
    assert not res["diverged"]
