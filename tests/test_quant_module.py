"""Tests for QuantLinear / QuantConv: forward vs torch-unfold oracle,
backward vs the reference gradient recipe (quant_module.py:36-52)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cpd_tpu.quant.quant_function import float_quantize, quant_gemm
from cpd_tpu.quant.quant_module import QuantConv, QuantLinear, quant_linear_fn


def test_quant_linear_forward():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((6, 5)).astype(np.float32)
    w = rng.standard_normal((3, 5)).astype(np.float32)
    b = rng.standard_normal((3,)).astype(np.float32)
    got = quant_linear_fn(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b), 5, 2)
    want = np.asarray(quant_gemm(jnp.asarray(x), jnp.asarray(w).T, man=2, exp=5)) + b
    np.testing.assert_array_equal(np.asarray(got), want)


def test_quant_linear_backward_recipe():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((6, 5)).astype(np.float32))
    w = jnp.asarray(rng.standard_normal((3, 5)).astype(np.float32))
    b = jnp.asarray(rng.standard_normal((3,)).astype(np.float32))
    g = jnp.asarray(rng.standard_normal((6, 3)).astype(np.float32))

    _, vjp = jax.vjp(lambda x_, w_, b_: quant_linear_fn(x_, w_, b_, 5, 2), x, w, b)
    gx, gw, gb = vjp(g)
    np.testing.assert_array_equal(
        np.asarray(gx), np.asarray(quant_gemm(g, w, man=2, exp=5)))
    np.testing.assert_array_equal(
        np.asarray(gw), np.asarray(quant_gemm(g.T, x, man=2, exp=5)))
    np.testing.assert_array_equal(
        np.asarray(gb), np.asarray(float_quantize(g.sum(0), 5, 2)))


def test_quant_linear_module():
    m = QuantLinear(in_features=5, out_features=3, exp=5, man=2)
    x = jnp.ones((2, 5))
    params = m.init(jax.random.PRNGKey(0), x)
    y = m.apply(params, x)
    assert y.shape == (2, 3)
    w = params["params"]["weight"]
    assert w.shape == (3, 5)
    bound = 1.0 / np.sqrt(5)
    assert np.all(np.abs(np.asarray(w)) <= bound)


@pytest.mark.parametrize("stride,padding", [(1, 0), (1, 1), (2, 1)])
def test_quant_conv_vs_torch_unfold_oracle(stride, padding):
    """The conv must equal: torch unfold -> (our) quantized GEMM -> fold.
    torch (CPU) provides the im2col layout oracle; the GEMM numerics are
    already oracle-tested."""
    import torch
    import torch.nn.functional as F

    rng = np.random.default_rng(2)
    B, C, H, W, O, k = 2, 3, 8, 8, 4, 3
    x = rng.standard_normal((B, C, H, W)).astype(np.float32)
    wgt = rng.standard_normal((O, C, k, k)).astype(np.float32)
    bias = rng.standard_normal((O,)).astype(np.float32)

    m = QuantConv(in_channels=C, out_channels=O, kernel_size=k, stride=stride,
                  padding=padding, exp=5, man=2)
    variables = {"params": {"weight": jnp.asarray(wgt), "bias": jnp.asarray(bias)}}
    got = np.asarray(m.apply(variables, jnp.asarray(x)))

    out_h = (H - k + 2 * padding) // stride + 1
    out_w = (W - k + 2 * padding) // stride + 1
    inp_unf = F.unfold(torch.from_numpy(x), (k, k), stride=stride,
                       padding=padding).transpose(1, 2)  # (B, L, C*k*k)
    a = inp_unf.reshape(B * out_h * out_w, C * k * k).numpy()
    w2 = wgt.reshape(O, C * k * k)
    y = np.asarray(quant_gemm(jnp.asarray(a), jnp.asarray(w2).T, man=2, exp=5)) + bias
    want = y.reshape(B, out_h * out_w, O).transpose(0, 2, 1).reshape(
        B, O, out_h, out_w)
    np.testing.assert_array_equal(got, want)


def test_quant_conv_grad_flows():
    m = QuantConv(in_channels=2, out_channels=3, kernel_size=3, padding=1,
                  exp=5, man=2)
    x = jnp.ones((1, 2, 6, 6))
    params = m.init(jax.random.PRNGKey(0), x)
    loss = lambda p, x_: jnp.sum(m.apply(p, x_) ** 2)
    grads = jax.grad(loss)(params, x)
    assert grads["params"]["weight"].shape == (3, 2, 3, 3)
    assert np.isfinite(np.asarray(grads["params"]["weight"])).all()


@pytest.mark.parametrize("dilation,groups", [(2, 1), (1, 2), (2, 2)])
def test_quant_conv_dilation_groups_vs_torch(dilation, groups):
    """Dilated/grouped QuantConv at fp32 precision must equal
    torch.nn.functional.conv2d (the quantized-GEMM numerics are separately
    oracle-tested; (8,23) makes the GEMM exact up to fp32 summation order,
    so compare with a small tolerance)."""
    import torch
    import torch.nn.functional as F

    rng = np.random.default_rng(7)
    B, C, H, W, O, k = 2, 4, 9, 9, 6, 3
    x = rng.standard_normal((B, C, H, W)).astype(np.float32)
    wgt = rng.standard_normal((O, C // groups, k, k)).astype(np.float32)
    bias = rng.standard_normal((O,)).astype(np.float32)

    m = QuantConv(in_channels=C, out_channels=O, kernel_size=k, stride=1,
                  padding=dilation, dilation=dilation, groups=groups,
                  exp=8, man=23)
    variables = {"params": {"weight": jnp.asarray(wgt),
                            "bias": jnp.asarray(bias)}}
    got = np.asarray(m.apply(variables, jnp.asarray(x)))

    want = F.conv2d(torch.from_numpy(x), torch.from_numpy(wgt),
                    torch.from_numpy(bias), stride=1, padding=dilation,
                    dilation=dilation, groups=groups).numpy()
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_quant_conv_groups_must_divide():
    m = QuantConv(in_channels=3, out_channels=4, kernel_size=3, groups=2)
    with pytest.raises(ValueError):
        m.init(jax.random.PRNGKey(0), jnp.ones((1, 3, 6, 6)))


# ------------------------------------------------------------- QuantDense

def test_quant_dense_matches_quant_linear_fn():
    """QuantDense is quant_linear_fn under flax Dense param layout."""
    from cpd_tpu.quant.quant_module import QuantDense, quant_linear_fn

    rng = np.random.RandomState(40)
    x = jnp.asarray(rng.randn(3, 5, 6).astype(np.float32))
    m = QuantDense(4, exp=4, man=3)
    variables = m.init(jax.random.PRNGKey(0), x)
    kernel = variables["params"]["kernel"]
    assert kernel.shape == (6, 4)           # flax (in, out) layout

    got = m.apply(variables, x)
    want = quant_linear_fn(np.asarray(x).reshape(-1, 6), kernel.T, None,
                           4, 3, "faithful").reshape(3, 5, 4)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_quant_dense_grads_follow_reference_recipe():
    """Gradients through QuantDense run the reference backward
    (quant_gemm on g and g^T — quant_module.py:36-52), so they differ
    from fp32 Dense grads at aggressive formats but stay finite."""
    from cpd_tpu.quant.quant_module import QuantDense

    rng = np.random.RandomState(41)
    x = jnp.asarray(rng.randn(8, 6).astype(np.float32))
    m = QuantDense(4, exp=4, man=3)
    variables = m.init(jax.random.PRNGKey(1), x)

    def loss(v):
        return (m.apply(v, x) ** 2).sum()

    g = jax.grad(loss)(variables)["params"]["kernel"]
    assert np.isfinite(np.asarray(g)).all()
    assert np.abs(np.asarray(g)).sum() > 0
