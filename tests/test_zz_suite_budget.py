"""Suite-tier wall-budget tripwire (VERDICT r3 weak #6).

Three rounds in a row, feature growth silently pushed the default tier
past the ~10-minute driver/CI budget and re-tiering happened reactively,
after a timeout.  This guard makes the budget a TEST: it runs last in the
default tier (the ``zz`` filename sorts it to the end of collection) and
fails the run when the measured wall time of everything before it exceeds
the budget — so the re-tiering conversation happens on the run where an
expensive test lands.

Budget: ``SUITE_BUDGET_SECS`` (default 900).  The default tier measures
~8-9 min solo on this 1-vCPU sandbox; shared-machine load inflates every
test's wall time (round 3 measured the same tier at 8m38 solo vs 10m58
under load), so the default carries ~40% headroom over solo — it trips on
genuine suite growth, not on a noisy neighbor.  Tighten via the env var
in CI environments with known-quiet machines.

Fails with the top offenders listed so the fix (mark `slow`, shrink the
model, share a compile) is immediate.
"""

import os

import pytest


def test_default_tier_within_budget(request, suite_durations):
    config = request.config
    if config.option.markexpr != "not slow":
        pytest.skip("budget guard applies to the default ('not slow') tier")
    if config.option.keyword:
        pytest.skip("budget guard needs the full collection (no -k)")
    if len(suite_durations) < 200:
        pytest.skip("budget guard needs the full default tier "
                    f"(only {len(suite_durations)} tests ran before it)")
    budget = float(os.environ.get("SUITE_BUDGET_SECS", "900"))
    total = sum(suite_durations.values())
    if total > budget:
        top = sorted(suite_durations.items(), key=lambda kv: -kv[1])[:10]
        lines = "\n".join(f"  {sec:7.1f}s  {nid}" for nid, sec in top)
        pytest.fail(
            f"default tier measured {total:.0f}s > budget {budget:.0f}s "
            f"(SUITE_BUDGET_SECS).  Re-tier before landing: mark the new "
            f"heavy tests `slow`, shrink their models, or fuse compiles.\n"
            f"Top offenders:\n{lines}", pytrace=False)
