"""Expert-parallelism tests (models/moe.py, train/moe.py) on the 8-device
virtual CPU mesh.

Oracle strategy: expert parallelism is a layout, not a numerics change —
with capacity high enough that no token is dropped, the ep-sharded model
must match the same model applied on one device (slot positions inside an
expert's capacity buffer are irrelevant to the combine)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from cpd_tpu.compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from cpd_tpu.models.moe import moe_lm, moe_param_specs
from cpd_tpu.parallel.mesh import make_mesh
from cpd_tpu.train import make_optimizer
from cpd_tpu.train.moe import make_moe_train_step, moe_state_specs
from cpd_tpu.train.state import TrainState


def _model(ep_size=1, n_experts=4, **kw):
    return moe_lm(vocab_size=64, d_model=32, n_layers=2, n_heads=4,
                  d_ff=64, n_experts=n_experts, capacity_factor=8.0,
                  ep_axis="ep" if ep_size > 1 else None, ep_size=ep_size,
                  **kw)


def _tokens(b=16, t=8, seed=0):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randint(0, 64, size=(b, t)).astype(np.int32))


def test_moe_forward_single_device_routes():
    model = _model()
    tokens = _tokens()
    variables = model.init(jax.random.PRNGKey(0), tokens[:2])
    out = model.apply(variables, tokens)
    assert out.shape == (16, 8, 64)
    assert np.all(np.isfinite(np.asarray(out)))
    # expert stacks exist with the global expert count on the leading axis
    wi = variables["params"]["block0"]["moe"]["wi"]
    assert wi.shape[0] == 4


def test_moe_forward_ep_sharded_matches_single_device():
    """dp2 x ep4 forward == one-device forward on the same params (no
    drops at capacity_factor=8)."""
    ep, dp = 4, 2
    mesh = make_mesh(dp=dp, ep=ep)
    tokens = _tokens(b=16, t=8)
    ref = _model(ep_size=1)
    variables = ref.init(jax.random.PRNGKey(0), tokens[:2])
    want = np.asarray(ref.apply(variables, tokens))

    sharded_model = _model(ep_size=ep)
    specs = moe_param_specs(variables["params"])

    def fwd(params, toks):
        return sharded_model.apply({"params": params}, toks)

    fn = jax.jit(shard_map(
        fwd, mesh=mesh, in_specs=(specs, P(("dp", "ep"))),
        out_specs=P(("dp", "ep")), check_vma=False))
    sharded = jax.device_put(variables["params"],
                             jax.tree.map(lambda s: NamedSharding(mesh, s),
                                          specs))
    got = np.asarray(fn(sharded, tokens))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_moe_train_step_matches_single_device():
    """One dp2 x ep4 MoE train step == sequential single-device step
    (aux_weight=0 so the local-vs-global load-balance statistics don't
    enter the gradients)."""
    import optax

    ep, dp = 4, 2
    mesh = make_mesh(dp=dp, ep=ep)
    tokens = _tokens(b=16, t=8, seed=3)
    targets = _tokens(b=16, t=8, seed=4)
    ref = _model(ep_size=1)
    variables = ref.init(jax.random.PRNGKey(1), tokens[:2])

    def loss_of(params):
        logits = ref.apply({"params": params}, tokens)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, targets).mean()

    want_loss, want_grads = jax.value_and_grad(loss_of)(variables["params"])

    moe_model = _model(ep_size=ep)
    tx = make_optimizer("sgd", lambda s: jnp.float32(0.1))
    state = TrainState(step=jnp.zeros([], jnp.int32),
                       params=variables["params"], batch_stats={},
                       opt_state=tx.init(variables["params"]))
    sharded_state = jax.device_put(
        state, jax.tree.map(lambda s: NamedSharding(mesh, s),
                            moe_state_specs(state)))
    step = make_moe_train_step(moe_model, tx, mesh, aux_weight=0.0,
                               donate=False)
    new_state, metrics = step(sharded_state, tokens, targets)

    np.testing.assert_allclose(float(metrics["loss"]), float(want_loss),
                               rtol=2e-4, atol=2e-4)
    want_params = jax.tree.map(lambda p, g: p - 0.1 * g,
                               variables["params"], want_grads)
    got_params = jax.tree.map(np.asarray, new_state.params)
    for (path, got), (_, want) in zip(
            jax.tree_util.tree_flatten_with_path(got_params)[0],
            jax.tree_util.tree_flatten_with_path(want_params)[0]):
        np.testing.assert_allclose(got, np.asarray(want), rtol=2e-3,
                                   atol=2e-4, err_msg=str(path))


@pytest.mark.slow
def test_moe_train_step_grad_rounding_sr():
    """SR through the MoE stepper (round 4): deterministic given seed,
    seed-sensitive, finite — and ep-replicated leaves (router/attention)
    stay bitwise consistent across ep copies after the SR dp-reduce."""
    ep, dp = 2, 4
    mesh = make_mesh(dp=dp, ep=ep)
    tokens = _tokens(b=16, t=8, seed=11)
    targets = _tokens(b=16, t=8, seed=12)
    ref = _model(ep_size=1)
    variables = ref.init(jax.random.PRNGKey(1), tokens[:2])
    moe_model = _model(ep_size=ep)
    tx = make_optimizer("sgd", lambda s: jnp.float32(0.1))
    state = TrainState(step=jnp.zeros([], jnp.int32),
                       params=variables["params"], batch_stats={},
                       opt_state=tx.init(variables["params"]))
    sharded_state = jax.device_put(
        state, jax.tree.map(lambda s: NamedSharding(mesh, s),
                            moe_state_specs(state)))

    def run(seed):
        step = make_moe_train_step(moe_model, tx, mesh, use_aps=True,
                                   grad_exp=4, grad_man=3,
                                   grad_rounding="stochastic",
                                   grad_seed=seed, donate=False)
        s, m = step(sharded_state, tokens, targets)
        s, m = step(s, tokens, targets)   # step 2 surfaces divergence
        return s, float(m["loss"])

    s1, l1 = run(0)
    s1b, l1b = run(0)
    assert np.isfinite(l1)
    assert l1 == l1b
    for a, b in zip(jax.tree.leaves(s1.params),
                    jax.tree.leaves(s1b.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    _, l2 = run(1)
    assert l1 != l2


def test_moe_capacity_drops_tokens():
    """With capacity_factor tiny, overflow tokens contribute nothing (the
    residual passes through) — outputs still finite, not equal to the
    high-capacity result."""
    tokens = _tokens(b=8, t=8, seed=7)
    big = moe_lm(vocab_size=64, d_model=32, n_layers=1, n_heads=4,
                 d_ff=64, n_experts=4, capacity_factor=8.0)
    small = moe_lm(vocab_size=64, d_model=32, n_layers=1, n_heads=4,
                   d_ff=64, n_experts=4, capacity_factor=0.25)
    variables = big.init(jax.random.PRNGKey(0), tokens[:2])
    out_big = np.asarray(big.apply(variables, tokens))
    out_small = np.asarray(small.apply(variables, tokens))
    assert np.all(np.isfinite(out_small))
    assert not np.allclose(out_big, out_small)


def test_moe_eval_step_matches_sequential():
    import optax
    from cpd_tpu.train.moe import make_moe_eval_step

    ep, dp = 4, 2
    mesh = make_mesh(dp=dp, ep=ep)
    tokens = _tokens(b=16, t=8, seed=9)
    targets = _tokens(b=16, t=8, seed=10)
    ref = _model(ep_size=1)
    variables = ref.init(jax.random.PRNGKey(2), tokens[:2])
    want = optax.softmax_cross_entropy_with_integer_labels(
        ref.apply(variables, tokens), targets).mean()

    moe_model = _model(ep_size=ep)
    tx = make_optimizer("sgd", lambda s: jnp.float32(0.1))
    state = TrainState(step=jnp.zeros([], jnp.int32),
                       params=variables["params"], batch_stats={},
                       opt_state=tx.init(variables["params"]))
    ev = make_moe_eval_step(moe_model, mesh)
    m = ev(state, tokens, targets)
    np.testing.assert_allclose(float(m["loss"]), float(want), rtol=2e-4,
                               atol=2e-4)
