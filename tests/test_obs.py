"""cpd_tpu.obs — tracing, registry, exporters, flight recorder (ISSUE
11), plus the satellites: the StepProfiler leak fix, the one-timer
dedupe, exporter determinism, and the provably-free contract.

The two acceptance pins:

* **obs is free**: a serve trace and a guarded train loop produce
  BITWISE-identical outputs (finished stores / counters / state) with
  and without a tracer attached — obs only observes;
* **timeline reconstruction is exact**: `loadgen.timeline_metrics` over
  a traced run's per-request timeline reproduces `run_trace`'s
  published TTFT/TPOT percentiles, goodput and counts float-for-float.
"""

import json
import os
import time

import numpy as np
import pytest

from cpd_tpu.obs import (FlightRecorder, MetricsRegistry, NULL_TRACER,
                         Stopwatch, Tracer, export_chrome_trace,
                         export_jsonl, export_prometheus,
                         parse_prometheus, write_all)
from cpd_tpu.obs.timing import Timer, now


# --------------------------------------------------------------- timing

def test_timer_is_the_one_implementation():
    """Satellite: train.metrics.Timer IS obs.timing.Timer (one home)."""
    from cpd_tpu.train.metrics import Timer as TrainTimer
    assert TrainTimer is Timer


def test_timer_accumulates():
    t = Timer()
    a = t()
    b = t(include_in_total=False)
    c = t()
    assert a >= 0 and b >= 0 and c >= 0
    assert t.total_time == pytest.approx(a + c, abs=1e-9)


def test_timer_state_is_o1():
    """Regression (host-unbounded, v4): Timer must keep only the last
    mark — the reference appended every timestamp to a list, which on a
    long-lived loop grows on the step clock forever."""
    t = Timer()
    deltas = [t() for _ in range(50)]
    assert all(d >= 0 for d in deltas)
    assert not any(isinstance(v, (list, dict, set))
                   for v in vars(t).values())


def test_stopwatch_laps_and_elapsed():
    w = Stopwatch()
    d1 = w.lap()
    d2 = w.lap()
    assert d1 >= 0 and d2 >= 0
    assert w.elapsed() >= d1 + d2 - 1e-9


# -------------------------------------------------- StepProfiler (leak fix)

class _FakeProfiler:
    def __init__(self):
        self.running = False
        self.starts = 0
        self.stops = 0

    def start_trace(self, d):
        if self.running:
            raise RuntimeError("trace already running")
        self.running = True
        self.starts += 1

    def stop_trace(self):
        if not self.running:
            raise RuntimeError("no trace running")
        self.running = False
        self.stops += 1


@pytest.fixture
def fake_profiler(monkeypatch, tmp_path):
    import jax
    fake = _FakeProfiler()
    monkeypatch.setattr(jax.profiler, "start_trace", fake.start_trace)
    monkeypatch.setattr(jax.profiler, "stop_trace", fake.stop_trace)
    return fake


def test_profiler_close_stops_inflight_trace(fake_profiler, tmp_path):
    """Satellite regression: a loop that exits INSIDE the window
    (watchdog interrupt, rollback past the end) must not leak a running
    jax.profiler trace."""
    from cpd_tpu.utils.profiling import StepProfiler
    p = StepProfiler(str(tmp_path / "prof"), start=2, num_steps=3)
    p.step(1)
    p.step(2)                      # window opens
    assert fake_profiler.running
    p.close()                      # loop died inside the window
    assert not fake_profiler.running
    p.close()                      # idempotent
    assert fake_profiler.stops == 1


def test_profiler_rollback_replay_does_not_double_start(fake_profiler,
                                                        tmp_path):
    """A rollback that rewinds the step counter back across the window
    start must not call start_trace on a running (or completed) trace —
    jax.profiler raises on the double start."""
    from cpd_tpu.utils.profiling import StepProfiler
    p = StepProfiler(str(tmp_path / "prof"), start=2, num_steps=3)
    p.step(2)
    p.step(3)
    p.step(2)                      # rollback replay through the window
    assert fake_profiler.starts == 1
    p.step(5)                      # window closes normally
    assert not fake_profiler.running
    p.step(2)                      # second replay after completion
    assert fake_profiler.starts == 1
    p.close()
    assert fake_profiler.stops == 1


# ------------------------------------------------------------------ tracer

def test_spans_nest_and_events_record_steps():
    tr = Tracer("t")
    with tr.span("outer", step=3):
        with tr.span("inner", step=3, cat="serve"):
            pass
        tr.event("mark", step=3, detail=7)
    spans = sorted(tr.spans)
    # inner exits first -> records first
    assert [s[1] for s in spans] == ["inner", "outer"]
    assert spans[0][6] == 1 and spans[1][6] == 0       # depths
    assert spans[0][3] == 3
    (_seq, name, cat, step, _wall, args), = list(tr.events)
    assert (name, cat, step, args) == ("mark", "mark", 3, {"detail": 7})


def test_tracer_ring_is_bounded_and_counts_drops():
    tr = Tracer("t", max_records=4)
    for i in range(10):
        tr.event("e", step=i)
    assert len(tr.events) == 4
    assert tr.events_dropped == 6
    assert [e[3] for e in tr.events] == [6, 7, 8, 9]   # newest kept


def test_null_tracer_is_inert():
    with NULL_TRACER.span("x", step=1):
        NULL_TRACER.event("y")
        NULL_TRACER.request_event(1, "z", 0)
    assert not NULL_TRACER
    assert NULL_TRACER.summary()["spans"] == 0


# ---------------------------------------------------------------- registry

def test_registry_counter_gauge_histogram():
    reg = MetricsRegistry()
    reg.inc("cpd_x_total", 2, kind="a")
    reg.inc("cpd_x_total", 3, kind="a")
    reg.inc("cpd_x_total", 1, kind="b")
    reg.set_gauge("cpd_y", 4.5)
    reg.declare("cpd_h", "histogram", buckets=(0.1, 1.0))
    reg.observe("cpd_h", 0.05)
    reg.observe("cpd_h", 0.5)
    reg.observe("cpd_h", 5.0)
    d = reg.as_dict()
    assert d["cpd_x_total"]["value"] == {"kind=a": 5.0, "kind=b": 1.0}
    assert d["cpd_y"]["value"] == 4.5
    h = [r for n, k, _h, _b, r in reg.collect() if n == "cpd_h"][0]
    assert h[0][1] == {"buckets": [1, 1], "sum": 5.55, "count": 3}


def test_registry_one_home_one_name():
    reg = MetricsRegistry()
    reg.inc("cpd_n")
    with pytest.raises(ValueError, match="one home, one name"):
        reg.set_gauge("cpd_n", 1.0)
    with pytest.raises(ValueError):
        reg.inc("cpd_n", -1)
    with pytest.raises(ValueError):
        reg.declare("0bad", "gauge")


def test_registry_absorbs_resilience_meter_and_step_metrics():
    from cpd_tpu.train.metrics import ResilienceMeter
    m = ResilienceMeter()
    m.bump("rollbacks", 2)
    m.observe_metrics({"guard_skipped": 3.0})
    reg = MetricsRegistry()
    reg.absorb_resilience_meter(m)
    d = reg.as_dict()
    assert d["cpd_train_rollbacks"]["value"] == 2.0
    assert d["cpd_train_steps_skipped"]["value"] == 3.0
    # step families adopted, training metrics (loss) left to
    # ScalarWriter
    reg.absorb_step_metrics({"prec_wire_sat": 7.0, "reduce_ok": 1.0,
                             "loss": 0.5, "accuracy": 0.9}, step=11)
    d = reg.as_dict()
    assert d["cpd_step_prec_wire_sat"]["value"] == 7.0
    assert d["cpd_step_reduce_ok"]["value"] == 1.0
    assert d["cpd_step_index"]["value"] == 11.0
    assert "cpd_step_loss" not in d


def test_registry_absorbs_supervisor_state():
    reg = MetricsRegistry()
    reg.absorb_supervisor("precision", {
        "level": 1, "hot": 2, "quiet": 0,
        "site": "wire", "ladder": [[5, 2], [5, 7]],
        "transitions": [[3, "e5m2", "e5m7"]]})
    d = reg.as_dict()
    assert d["cpd_sup_precision_level"]["value"] == 1.0
    assert d["cpd_sup_precision_ladder_len"]["value"] == 2.0
    assert d["cpd_sup_precision_info"]["value"] == {"site=wire": 1.0}


# --------------------------------------------------------------- exporters

def _toy_tracer_and_registry(wall_offset=0.0):
    tr = Tracer("toy", meta={"seed": 1})
    for i in range(3):
        with tr.span("step", step=i, cat="phase"):
            tr.request_event(i, "submit", i, verdict="ACCEPT",
                             arrival=i)
    reg = MetricsRegistry()
    reg.declare("cpd_demo_total", "counter", "demo counter")
    reg.inc("cpd_demo_total", 4, mode="ring")
    reg.set_gauge("cpd_demo_gauge", 1.25)
    reg.declare("cpd_demo_hist", "histogram", buckets=(0.5, 1.5))
    reg.observe("cpd_demo_hist", 1.0)
    return tr, reg


def test_exporters_deterministic_modulo_wall(tmp_path):
    """Satellite: the same logical run exported twice (different wall
    clocks) is byte-identical under strip_wall for BOTH the JSONL and
    the Chrome trace."""
    files = []
    for run in ("a", "b"):
        tr, reg = _toy_tracer_and_registry()
        time.sleep(0.01)   # guarantee the wall clocks differ
        j = export_jsonl(tr, str(tmp_path / f"{run}.jsonl"),
                         strip_wall=True)
        c = export_chrome_trace(tr, str(tmp_path / f"{run}.json"),
                                strip_wall=True)
        files.append((open(j, "rb").read(), open(c, "rb").read()))
    assert files[0][0] == files[1][0]
    assert files[0][1] == files[1][1]
    # and WITH wall the streams still parse per line
    tr, _ = _toy_tracer_and_registry()
    j = export_jsonl(tr, str(tmp_path / "wall.jsonl"))
    for line in open(j):
        rec = json.loads(line)
        assert rec["t"] in ("meta", "span", "event")


def test_chrome_trace_is_wellformed(tmp_path):
    tr, _ = _toy_tracer_and_registry()
    path = export_chrome_trace(tr, str(tmp_path / "t.json"))
    doc = json.load(open(path))
    assert isinstance(doc["traceEvents"], list) and doc["traceEvents"]
    for ev in doc["traceEvents"]:
        assert ev["ph"] in ("M", "X", "i")
        assert "name" in ev and "pid" in ev and "tid" in ev
        if ev["ph"] == "X":
            assert ev["dur"] >= 0 and "ts" in ev
    # request events ride their rid's lane
    req = [e for e in doc["traceEvents"] if e.get("cat") == "req"]
    assert {e["tid"] for e in req} == {1, 2, 3}


def test_prometheus_roundtrip_and_checker(tmp_path):
    _tr, reg = _toy_tracer_and_registry()
    text = export_prometheus(reg, str(tmp_path / "m.prom"))
    parsed = parse_prometheus(text)
    assert parsed["cpd_demo_total"]["type"] == "counter"
    assert parsed["cpd_demo_total"]["samples"] == [({"mode": "ring"},
                                                    4.0)]
    hist = parsed["cpd_demo_hist"]["samples"]
    les = [s[0].get("le") for s in hist if "le" in s[0]]
    assert les == ["0.5", "1.5", "+Inf"]
    # non-finite values export under the spec spellings instead of
    # crashing the end-of-run artifact write (a diverged run's NaN
    # telemetry), and round-trip through the checker
    reg2 = MetricsRegistry()
    reg2.set_gauge("cpd_bad", float("nan"))
    reg2.set_gauge("cpd_hi", float("inf"), side="up")
    reg2.set_gauge("cpd_lo", float("-inf"))
    text2 = export_prometheus(reg2)
    assert "cpd_bad NaN" in text2 and 'cpd_hi{side="up"} +Inf' in text2
    parsed2 = parse_prometheus(text2)
    assert parsed2["cpd_hi"]["samples"][0][1] == float("inf")
    assert parsed2["cpd_lo"]["samples"][0][1] == float("-inf")
    assert np.isnan(parsed2["cpd_bad"]["samples"][0][1])
    # the minimal checker is a real checker
    with pytest.raises(ValueError, match="malformed sample"):
        parse_prometheus("# TYPE cpd_ok gauge\ncpd_ok 1\n"
                         "not a sample !!\n")
    with pytest.raises(ValueError, match="no preceding # TYPE"):
        parse_prometheus("cpd_untyped 1\n")


def test_write_all_bundle(tmp_path):
    tr, reg = _toy_tracer_and_registry()
    out = write_all(str(tmp_path / "obs"), tr, reg)
    for key, p in out["artifacts"].items():
        assert os.path.isfile(p), key
    assert out["summary"]["spans"] == 3
    assert out["summary"]["metrics"] == 3
    parse_prometheus(open(out["artifacts"]["prometheus"]).read())
    json.load(open(out["artifacts"]["chrome_trace"]))


# ---------------------------------------------------------- flight recorder

def test_flight_ring_bounded_and_dump_appends(tmp_path):
    path = str(tmp_path / "flight.jsonl")
    fr = FlightRecorder(path, capacity=4)
    for i in range(10):
        fr.record("step", step=i, loss=float(i))
    assert len(fr) == 4
    fr.dump("rollback")
    fr.record("step", step=10)
    fr.dump("watchdog")
    lines = [json.loads(ln) for ln in open(path)]
    headers = [ln for ln in lines if "flight_dump" in ln]
    assert [h["reason"] for h in headers] == ["rollback", "watchdog"]
    assert headers[0]["events"] == 4
    # the ring is not cleared by a dump: the second block holds the
    # newest 4 events ending at step 10
    second = lines[len(headers[0:1]) + headers[0]["events"] + 1:]
    assert second[-1]["step"] == 10


def test_flight_without_path_is_loud_but_safe(capsys):
    fr = FlightRecorder(None, capacity=2)
    fr.record("step", step=1)
    assert fr.dump("watchdog") is None
    assert "no dump path" in capsys.readouterr().err


def test_watchdog_on_trip_dumps_flight(tmp_path):
    """The flight ring reaches disk at FIRE time, on the timer thread —
    before any interrupt/hard-exit handling."""
    from cpd_tpu.resilience import StepWatchdog
    path = str(tmp_path / "flight.jsonl")
    fr = FlightRecorder(path, capacity=8)
    fr.record("step", step=41, loss=2.5)
    wd = StepWatchdog(0.05, interrupt=False,
                      on_trip=lambda ctx: fr.dump("watchdog"))
    wd.arm(41, loss=2.5)
    time.sleep(0.4)
    wd.close()
    assert wd.tripped
    lines = [json.loads(ln) for ln in open(path)]
    assert lines[0]["reason"] == "watchdog"
    assert any(ln.get("step") == 41 for ln in lines[1:])


# ----------------------------------------------- run_guarded: obs is free

from types import SimpleNamespace


def _counting_step(state, x):
    import jax.numpy as jnp
    new = SimpleNamespace(step=state.step, w=state.w + jnp.asarray(x))
    return new, {"loss": float(np.asarray(state.w).sum())}


def test_run_guarded_bitwise_identical_with_obs(tmp_path):
    """Acceptance: obs-on leaves the guarded loop's outputs bitwise
    unchanged (and obs-off means no instrumentation exists at all)."""
    import jax.numpy as jnp
    from cpd_tpu.resilience import run_guarded

    def make():
        return SimpleNamespace(step=0, w=jnp.zeros((4,), jnp.float32))

    def nb(step, reseed):
        return (np.full((4,), 1.0 + step, np.float32),)

    s_off, rep_off = run_guarded(_counting_step, make(), nb, 5)
    tr = Tracer("guarded")
    fr = FlightRecorder(str(tmp_path / "f.jsonl"), capacity=16)
    s_on, rep_on = run_guarded(_counting_step, make(), nb, 5,
                               tracer=tr, flight=fr)
    assert np.array_equal(np.asarray(s_off.w), np.asarray(s_on.w))
    assert rep_off.counters == rep_on.counters
    assert rep_off.events == rep_on.events
    # the spans really were recorded: 5 data + 5 step
    names = [s[1] for s in tr.spans]
    assert names.count("data") == 5 and names.count("step") == 5
    assert len(fr) == 5


def test_run_guarded_abort_dumps_flight(tmp_path):
    from cpd_tpu.resilience import DivergenceSentinel, run_guarded

    calls = {"n": 0}

    def diverging_step(state, x):
        calls["n"] += 1
        return state, {"loss": 1.0 if calls["n"] < 3 else 1e9}

    fr = FlightRecorder(str(tmp_path / "f.jsonl"), capacity=16)
    _s, rep = run_guarded(diverging_step, SimpleNamespace(step=0),
                          lambda s, r: (0,), 10,
                          sentinel=DivergenceSentinel(2, factor=10),
                          flight=fr)
    assert rep.aborted == "diverged"
    lines = [json.loads(ln) for ln in open(str(tmp_path / "f.jsonl"))]
    assert lines[0]["reason"] == "diverged"
    assert any(ln.get("kind") == "abort" for ln in lines[1:])


# ------------------------------------------- serve: free + exact timelines

VOCAB = 64
ENGINE_KW = dict(n_slots=2, max_seq=32, page_size=8, prefill_chunk=4)


@pytest.fixture(scope="module")
def serve_model():
    import jax
    import jax.numpy as jnp
    from cpd_tpu.models import transformer_lm
    model = transformer_lm(vocab_size=VOCAB, d_model=32, n_layers=2,
                           n_heads=4, n_kv_heads=2, d_ff=64)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _serve_trace(n=6):
    from cpd_tpu.serve import mixed_trace, with_sla
    return with_sla(
        mixed_trace(n, VOCAB, prompt_lens=(4, 6), max_new=(4,), seed=5),
        [dict(sla_class=0), dict(sla_class=1, deadline_steps=64)])


def test_serve_obs_is_bitwise_free(serve_model):
    """Acceptance: tracer+flight attached vs not — identical counters,
    finished tokens and events (obs only observes)."""
    from cpd_tpu.serve import ServeEngine, run_trace
    model, params = serve_model
    trace = _serve_trace()

    def drive(**obs_kw):
        eng = ServeEngine(model, params, **ENGINE_KW, **obs_kw)
        m = run_trace(eng, list(trace))
        return eng, m

    e_off, m_off = drive()
    e_on, m_on = drive(tracer=Tracer("serve"),
                       flight=FlightRecorder(None, capacity=32))
    assert m_off["counters"] == m_on["counters"]
    assert e_off.finished == e_on.finished
    # same event sequence on the step clock (walls legitimately differ)
    assert [e[:3] for e in e_off.events] == [e[:3] for e in e_on.events]


def test_serve_timeline_reconstruction_is_exact(serve_model):
    """THE acceptance gate: reconstructed TTFT/TPOT/goodput/counts from
    the per-request timeline equal run_trace's published metrics
    exactly (same floats, same rounding)."""
    from cpd_tpu.serve import ServeEngine, run_trace, timeline_metrics
    model, params = serve_model
    trace = _serve_trace()
    tr = Tracer("serve")
    eng = ServeEngine(model, params, **ENGINE_KW, tracer=tr)
    pub = run_trace(eng, list(trace), sla_ttft_ms=500.0,
                    sla_tpot_ms=100.0)
    assert pub["counters"]["results_evicted"] == 0   # parity precondition
    rec = timeline_metrics(tr, sla_ttft_ms=500.0, sla_tpot_ms=100.0)
    for key in ("submitted", "completed", "shed", "deadline_misses",
                "dropped", "shed_rate", "deadline_miss_rate",
                "ttft_ms_p50", "ttft_ms_p99", "tpot_ms_p50",
                "tpot_ms_p99", "goodput_tok_per_s", "goodput_by_class",
                "tok_per_s", "duration_s"):
        assert rec[key] == pub[key], key
    assert rec["tokens_generated"] == \
        pub["counters"]["tokens_generated"]
    # the timeline carries the admission verdicts, and a full-window
    # run says so
    assert sum(rec["verdicts"].values()) == pub["submitted"]
    assert rec["timeline_truncated"] is False


def test_timeline_parity_holds_with_result_store_at_cap(serve_model):
    """ISSUE 13 satellite — the PR 11 parity caveat, closed: with the
    bounded `ResultStore` held AT CAP (evictions mid-run), the
    published metrics still equal the timeline reconstruction
    float-for-float, because `run_trace` now derives its per-request
    numbers from the timeline whenever a tracer is attached — a
    completed rid the store evicted keeps its true n_generated."""
    from cpd_tpu.serve import ServeEngine, run_trace, timeline_metrics
    model, params = serve_model
    trace = _serve_trace(12)
    tr = Tracer("serve", max_records=4096)
    eng = ServeEngine(model, params, **ENGINE_KW, finished_cap=2,
                      tracer=tr)
    pub = run_trace(eng, list(trace), sla_ttft_ms=500.0,
                    sla_tpot_ms=100.0)
    # the precondition the OLD caveat excluded: the store really
    # evicted finished entries mid-run
    assert pub["counters"]["results_evicted"] > 0
    assert len(eng.finished) <= 2
    # ... and the per-request metrics are NOT truncated by it anymore
    assert pub["metrics_truncated"] is False
    rec = timeline_metrics(tr, sla_ttft_ms=500.0, sla_tpot_ms=100.0)
    for key in ("submitted", "completed", "shed", "deadline_misses",
                "dropped", "shed_rate", "deadline_miss_rate",
                "ttft_ms_p50", "ttft_ms_p99", "tpot_ms_p50",
                "tpot_ms_p99", "goodput_tok_per_s", "goodput_by_class",
                "tok_per_s", "duration_s"):
        assert rec[key] == pub[key], key
    assert rec["tokens_generated"] == \
        pub["counters"]["tokens_generated"]
    assert rec["timeline_truncated"] is False


def test_run_trace_null_tracer_matches_tracerless_metrics(serve_model):
    """NULL_TRACER is the documented disabled path: `run_trace` must
    treat it exactly like ``tracer=None`` — store/event-derived
    published metrics, not an (empty) timeline derivation."""
    from cpd_tpu.obs.trace import NULL_TRACER
    from cpd_tpu.serve import ServeEngine, run_trace
    model, params = serve_model
    trace = _serve_trace()
    off = run_trace(ServeEngine(model, params, **ENGINE_KW),
                    list(trace))
    null = run_trace(ServeEngine(model, params, **ENGINE_KW,
                                 tracer=NULL_TRACER), list(trace))
    assert null["completed"] == off["completed"] == len(trace)
    # the real latency numbers are published (not None/0.0 from an
    # empty timeline); counters identical
    assert null["ttft_ms_p50"] is not None
    assert null["goodput_tok_per_s"] and null["goodput_tok_per_s"] > 0
    assert null["counters"] == off["counters"]


def test_timeline_metrics_without_run_trace_is_loud(serve_model):
    """An engine stepped manually records no step_begin walls —
    reconstruction must refuse (a silent wrong TTFT would betray the
    exactness contract) instead of KeyError-ing."""
    from cpd_tpu.serve import ServeEngine, timeline_metrics
    model, params = serve_model
    tr = Tracer("serve")
    eng = ServeEngine(model, params, **ENGINE_KW, tracer=tr)
    for r in _serve_trace(2):
        eng.submit(r)
    eng.run_until_drained()
    with pytest.raises(ValueError, match="no step_begin"):
        timeline_metrics(tr)


def test_serve_obs_run_exports_deterministically(serve_model, tmp_path):
    """Satellite: two runs of the same (trace, seed) produce
    byte-identical stripped JSONL + Chrome trace, and the Prometheus
    text parses."""
    from cpd_tpu.serve import ServeEngine, run_trace
    model, params = serve_model
    trace = _serve_trace()
    blobs = []
    for run in ("a", "b"):
        tr = Tracer("serve")
        reg = MetricsRegistry()
        eng = ServeEngine(model, params, **ENGINE_KW, tracer=tr)
        run_trace(eng, list(trace))
        reg.absorb_serve_counters(eng.counters)
        j = export_jsonl(tr, str(tmp_path / f"{run}.jsonl"),
                         strip_wall=True)
        c = export_chrome_trace(tr, str(tmp_path / f"{run}.json"),
                                strip_wall=True)
        p = export_prometheus(reg, str(tmp_path / f"{run}.prom"))
        blobs.append((open(j, "rb").read(), open(c, "rb").read(), p))
    assert blobs[0] == blobs[1]
    parsed = parse_prometheus(blobs[0][2])
    assert parsed["cpd_serve_completed"]["samples"][0][1] == \
        len(_serve_trace())


def test_serve_snapshot_dumps_flight(serve_model, tmp_path):
    from cpd_tpu.serve import ServeEngine
    model, params = serve_model
    fr = FlightRecorder(str(tmp_path / "flight.jsonl"), capacity=16)
    eng = ServeEngine(model, params, **ENGINE_KW, flight=fr)
    for r in _serve_trace(2):
        eng.submit(r)
    for _ in range(4):
        eng.step()
    eng.snapshot(str(tmp_path / "snap"))
    lines = [json.loads(ln)
             for ln in open(str(tmp_path / "flight.jsonl"))]
    assert lines[0]["reason"] == "snapshot"
    assert any(ln.get("kind") == "serve_step" for ln in lines[1:])
