"""Training-harness tests: optimizers vs torch semantics, schedules,
samplers, and the end-to-end jitted train step on an 8-device CPU mesh."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from cpd_tpu.data import (CIFAR10Pipeline, DistributedGivenIterationSampler,
                          GivenIterationSampler, synthetic_cifar10)
from cpd_tpu.models import tiny_cnn
from cpd_tpu.parallel.mesh import data_parallel_mesh
from cpd_tpu.train import (create_train_state, make_eval_step,
                           make_optimizer, make_train_step, piecewise_linear,
                           warmup_step_decay)
from cpd_tpu.train.optim import lars, quant_sgd, sgd
from cpd_tpu.train.schedules import iter_table


# ---------------------------------------------------------------- schedules

def test_warmup_step_decay_matches_reference_shape():
    # mix.py:181-198 with iter_per_epoch=10: warmup 50 iters 0.1->1.6,
    # x0.1 after 400, x0.01 after 800.
    s = warmup_step_decay(1.6, 50, [400, 800])
    assert np.isclose(float(s(0)), 0.1)
    assert np.isclose(float(s(50)), 1.6)
    assert np.isclose(float(s(400)), 1.6)
    assert np.isclose(float(s(401)), 0.16)
    assert np.isclose(float(s(801)), 0.016, atol=1e-6)


def test_piecewise_linear_davidnet():
    s = piecewise_linear([0, 5, 24], [0, 0.4, 0])  # dawn.py:65
    assert float(s(0)) == 0.0
    assert np.isclose(float(s(5)), 0.4)
    assert np.isclose(float(s(2.5)), 0.2)
    assert np.isclose(float(s(24)), 0.0)
    assert np.isclose(float(s(100)), 0.0)  # clamped


def test_iter_table():
    s = iter_table([100, 200], [0.1, 0.1], base_lr=1.0, warmup_steps=10,
                   warmup_lr=0.0)
    assert np.isclose(float(s(5)), 0.5)
    assert np.isclose(float(s(50)), 1.0)
    assert np.isclose(float(s(150)), 0.1)
    assert np.isclose(float(s(250)), 0.01)


# --------------------------------------------------------------- optimizers

def test_sgd_matches_torch():
    torch = pytest.importorskip("torch")
    w0 = np.random.RandomState(0).randn(4, 3).astype(np.float32)
    grads = [np.random.RandomState(i + 1).randn(4, 3).astype(np.float32)
             for i in range(5)]

    tw = torch.nn.Parameter(torch.tensor(w0))
    topt = torch.optim.SGD([tw], lr=0.1, momentum=0.9, weight_decay=1e-2,
                           nesterov=True)
    for g in grads:
        tw.grad = torch.tensor(g)
        topt.step()

    tx = sgd(lambda step: jnp.float32(0.1), momentum=0.9, weight_decay=1e-2,
             nesterov=True)
    params = {"w": jnp.asarray(w0)}
    state = tx.init(params)
    for g in grads:
        updates, state = tx.update({"w": jnp.asarray(g)}, state, params)
        params = jax.tree.map(lambda p, u: p + u, params, updates)

    np.testing.assert_allclose(np.asarray(params["w"]),
                               tw.detach().numpy(), rtol=1e-5, atol=1e-6)


def test_lars_matches_reference_formula():
    # mix.py:297-310 reimplemented in numpy as the oracle.
    rng = np.random.RandomState(0)
    w = rng.randn(10).astype(np.float32)
    lr, momentum, wd = 0.5, 0.9, 1e-4
    buf = np.zeros_like(w)
    w_ref = w.copy()
    gs = [rng.randn(10).astype(np.float32) for _ in range(4)]
    for g in gs:
        local_lr = (np.linalg.norm(w_ref)
                    / (np.linalg.norm(g) + wd * np.linalg.norm(w_ref))) * 0.001
        buf = momentum * buf + lr * local_lr * (g + wd * w_ref)
        w_ref = w_ref - buf

    tx = lars(lambda step: jnp.float32(lr), momentum=momentum,
              weight_decay=wd)
    params = {"w": jnp.asarray(w)}
    state = tx.init(params)
    for g in gs:
        updates, state = tx.update({"w": jnp.asarray(g)}, state, params)
        params = jax.tree.map(lambda p, u: p + u, params, updates)
    np.testing.assert_allclose(np.asarray(params["w"]), w_ref, rtol=1e-5)


def _run_opt(tx, w0, grads):
    params = {"w": jnp.asarray(w0)}
    state = tx.init(params)
    for g in grads:
        updates, state = tx.update({"w": jnp.asarray(g)}, state, params)
        params = jax.tree.map(lambda p, u: p + u, params, updates)
    return np.asarray(params["w"]), state


def test_quant_sgd_fp32_is_exact_sgd():
    """(8,23) momentum buffer without Kahan: quant_sgd must walk sgd's
    trajectory bitwise (the identity-cast shortcut, like
    float_quantize's).  WITH Kahan the compensation arithmetic itself
    changes fp32 rounding, so only ulp-closeness holds — the same
    shortcut asymmetry as the reference's fp32 Kahan all-reduce
    (dist_util.py:55-59 vs :72-89); mixed-magnitude grads make that
    divergence real, not hypothetical."""
    rng = np.random.RandomState(0)
    w0 = rng.randn(6, 5).astype(np.float32)
    grads = [(rng.randn(6, 5) * 10.0 ** rng.uniform(-3, 2, (6, 5))
              ).astype(np.float32) for _ in range(12)]
    sched = lambda s: jnp.where(s < 3, 0.2, 0.02)  # noqa: E731
    ref, _ = _run_opt(sgd(sched, momentum=0.9, weight_decay=1e-2,
                          nesterov=True), w0, grads)
    got, _ = _run_opt(quant_sgd(sched, momentum=0.9, weight_decay=1e-2,
                                exp=8, man=23, nesterov=True), w0, grads)
    assert np.array_equal(ref, got)
    got_k, _ = _run_opt(quant_sgd(sched, momentum=0.9, weight_decay=1e-2,
                                  exp=8, man=23, use_kahan=True,
                                  nesterov=True), w0, grads)
    np.testing.assert_allclose(got_k, ref, rtol=1e-4, atol=1e-5)


def test_quant_sgd_buffer_in_value_set():
    """The momentum buffer must hold only e4m3-representable values."""
    from cpd_tpu.quant.numerics import cast_to_format

    rng = np.random.RandomState(1)
    w0 = rng.randn(8).astype(np.float32)
    grads = [rng.randn(8).astype(np.float32) for _ in range(5)]
    _, state = _run_opt(quant_sgd(lambda s: jnp.float32(0.1), momentum=0.9,
                                  exp=4, man=3), w0, grads)
    buf = state.momentum_buf["w"]
    assert np.array_equal(np.asarray(buf),
                          np.asarray(cast_to_format(buf, 4, 3)))


def test_quant_sgd_kahan_recovers_flushed_gradients():
    """Sub-ulp gradients against a large low-precision buffer: naive
    accumulation flushes every one of them (0.04 < half-ulp(1.0) = 0.0625
    at m3), the quantized Kahan residual carries them across the rounding
    boundary — the same mechanism the reference's Kahan all-reduce exists
    for (dist_util.py:72-89), applied to the optimizer state.

    The increment must exceed half-ulp of the *residual's* binade or the
    quantized c itself pins at a round-to-nearest-even tie and stalls
    (e.g. 2e-3 increments pin c at -0.0625 exactly) — compensated
    accumulation in quantized arithmetic is better, not magic."""
    w0 = np.zeros(4, np.float32)
    # one big gradient builds the buffer to 1.0, then 200 sub-ulp ones
    grads = [np.full(4, 1.0, np.float32)] + \
            [np.full(4, 0.04, np.float32)] * 200
    sched = lambda s: jnp.float32(0.0)  # noqa: E731 — isolate the buffer
    kw = dict(momentum=1.0, weight_decay=0.0)
    _, st_naive = _run_opt(quant_sgd(sched, exp=4, man=3, **kw), w0, grads)
    _, st_kahan = _run_opt(quant_sgd(sched, exp=4, man=3, use_kahan=True,
                                     **kw), w0, grads)
    _, st_exact = _run_opt(sgd(sched, **kw), w0, grads)
    exact = np.asarray(st_exact.momentum_buf["w"])   # 1 + 200*0.04 = 9.0
    naive_err = np.abs(np.asarray(st_naive.momentum_buf["w"]) - exact).max()
    kahan_err = np.abs(np.asarray(st_kahan.momentum_buf["w"]) - exact).max()
    assert naive_err > 7.5, (naive_err, kahan_err)   # buffer stuck at 1.0
    assert kahan_err < 0.5, (naive_err, kahan_err)   # tracks 9.0


def test_make_optimizer_quant_sgd():
    tx = make_optimizer("quant_sgd", lambda s: jnp.float32(0.1),
                        opt_exp=5, opt_man=2, opt_kahan=True)
    params = {"w": jnp.ones(3)}
    state = tx.init(params)
    updates, state = tx.update({"w": jnp.ones(3)}, state, params)
    assert np.isfinite(np.asarray(updates["w"])).all()


def test_warmup_cosine_schedule():
    from cpd_tpu.train import warmup_cosine

    s = warmup_cosine(1.0, warmup_iters=10, total_iters=110, final_lr=0.1)
    np.testing.assert_allclose(float(s(0)), 0.0, atol=1e-7)
    np.testing.assert_allclose(float(s(10)), 1.0, rtol=1e-6)
    np.testing.assert_allclose(float(s(60)), 0.55, rtol=1e-5)  # midpoint
    np.testing.assert_allclose(float(s(110)), 0.1, rtol=1e-5)
    np.testing.assert_allclose(float(s(500)), 0.1, rtol=1e-5)  # clamped
    # warmup 0: first step trains at base_lr, not warmup_from=0
    s0 = warmup_cosine(1.0, warmup_iters=0, total_iters=100)
    np.testing.assert_allclose(float(s0(0)), 1.0, rtol=1e-6)
    import pytest
    with pytest.raises(ValueError, match="total_iters"):
        warmup_cosine(1.0, warmup_iters=10, total_iters=5)


def test_make_optimizer_clip_norm():
    """clip_norm prepends global-norm clipping and marks the transform
    norm-based so the shard-local LM stepper refuses it under tp."""
    import pytest

    tx = make_optimizer("sgd", lambda s: jnp.float32(1.0), momentum=0.0,
                        clip_norm=1.0)
    assert getattr(tx, "norm_based", False)
    params = {"w": jnp.zeros(4)}
    state = tx.init(params)
    g = {"w": jnp.full(4, 10.0)}        # norm 20 -> scaled to norm 1
    updates, _ = tx.update(g, state, params)
    np.testing.assert_allclose(
        float(jnp.linalg.norm(updates["w"])), 1.0, rtol=1e-5)

    with pytest.raises(ValueError, match="clip_norm"):
        make_optimizer("sgd", lambda s: 0.1, clip_norm=-1.0)

    # the LM guard rejects it under tp
    from cpd_tpu.models import transformer_lm
    from cpd_tpu.parallel.mesh import make_mesh
    from cpd_tpu.train import make_lm_train_step
    mesh = make_mesh(dp=4, tp=2)
    model = transformer_lm(vocab_size=32, d_model=16, n_layers=1,
                           n_heads=2, d_ff=32, tp_axis="tp", tp_size=2)
    with pytest.raises(ValueError, match="norm"):
        make_lm_train_step(model, tx, mesh)


def test_make_optimizer_adamw():
    """adamw registry entry: optax.adamw with momentum as b1 and the
    wd_mask routed to the decoupled decay."""
    mask = lambda p: {"w": True, "b": False}                   # noqa: E731
    tx = make_optimizer("adamw", lambda s: jnp.float32(0.1),
                        momentum=0.9, weight_decay=0.5, wd_mask=mask)
    params = {"w": jnp.ones(3), "b": jnp.ones(3)}
    state = tx.init(params)
    # zero grads: any update comes solely from weight decay — masked off
    # for "b", nonzero for "w"
    updates, state = tx.update({"w": jnp.zeros(3), "b": jnp.zeros(3)},
                               state, params)
    assert np.all(np.asarray(updates["w"]) != 0.0)
    assert np.all(np.asarray(updates["b"]) == 0.0)


def test_seg_eval_step_matches_numpy_oracle():
    """make_seg_eval_step's streamed sums (loss, pixel acc, per-class
    inter/union for mIoU) vs a direct numpy computation, with ignored
    pixels excluded — the Cityscapes metric definition."""
    import flax.linen as nn

    from cpd_tpu.train import create_train_state, make_seg_eval_step

    C = 4

    class TinySeg(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            return nn.Conv(C, (1, 1))(x)

    model = TinySeg()
    mesh = data_parallel_mesh()
    tx = sgd(lambda s: jnp.float32(0.1))
    state = create_train_state(model, tx, jnp.zeros((1, 8, 8, 3)),
                               jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    x = rng.randn(8, 8, 8, 3).astype(np.float32)
    y = rng.randint(0, C, (8, 8, 8)).astype(np.int32)
    y[0, :2, :] = 255                        # ignored region

    ev = make_seg_eval_step(model, mesh, num_classes=C)
    m = {k: np.asarray(v) for k, v in ev(state, jnp.asarray(x),
                                         jnp.asarray(y)).items()}

    logits = np.asarray(model.apply({"params": state.params},
                                    jnp.asarray(x), train=False))
    pred = logits.argmax(-1)
    valid = y != 255
    assert m["n_pix"] == valid.sum()
    assert m["correct"] == ((pred == y) & valid).sum()
    logp = logits - np.log(np.exp(logits - logits.max(-1, keepdims=True))
                           .sum(-1, keepdims=True)) - logits.max(
                               -1, keepdims=True)
    want_loss = -logp[valid, y[valid]].sum()
    np.testing.assert_allclose(m["loss_sum"], want_loss, rtol=1e-4)
    for c in range(C):
        pi, li = (pred == c) & valid, (y == c) & valid
        assert m["inter"][c] == (pi & li).sum()
        assert m["union"][c] == (pi | li).sum()


def test_wd_mask_excludes_leaves():
    tx = sgd(lambda s: jnp.float32(1.0), momentum=0.0, weight_decay=0.1,
             wd_mask=lambda p: {"w": True, "bn": False})
    params = {"w": jnp.ones(3), "bn": jnp.ones(3)}
    state = tx.init(params)
    zero = {"w": jnp.zeros(3), "bn": jnp.zeros(3)}
    updates, _ = tx.update(zero, state, params)
    assert np.all(np.asarray(updates["w"]) != 0)   # decayed
    assert np.all(np.asarray(updates["bn"]) == 0)  # masked out


# ----------------------------------------------------------------- samplers

def test_given_iteration_sampler_deterministic_and_resumable():
    s1 = GivenIterationSampler(100, total_iter=10, batch_size=8, seed=0)
    s2 = GivenIterationSampler(100, total_iter=10, batch_size=8, seed=0)
    np.testing.assert_array_equal(s1.indices, s2.indices)
    resumed = GivenIterationSampler(100, 10, 8, seed=0, last_iter=4)
    np.testing.assert_array_equal(list(resumed)[:8], s1.indices[40:48])


def test_distributed_sampler_blocks_disjoint_schedules():
    world = 4
    samplers = [DistributedGivenIterationSampler(
        1000, total_iter=5, batch_size=8, world_size=world, rank=r, seed=0)
        for r in range(world)]
    # per-rank schedules are contiguous blocks of one global shuffle
    # (train_util.py:212-215) => concatenation has no overlap in position.
    all_idx = np.concatenate([s.indices for s in samplers])
    assert len(all_idx) == 5 * 8 * world


def test_sampler_bit_exact_vs_reference_transcript():
    """Vendored transcript of the reference's gen_new_list output
    (train_util.py:196-215 run verbatim with np.random.seed(0)): tiles the
    capped dataset, one whole-schedule shuffle, contiguous rank slice.
    Covers the `indices[:all_size]` cap-before-tile quirk (dataset larger
    than the schedule, case B)."""
    # case A: dataset 10, 4 iters x batch 3, world 2
    expect_a = {
        0: [1, 0, 2, 4, 0, 1, 3, 3, 6, 8, 6, 7],
        1: [4, 2, 5, 8, 9, 7, 9, 3, 0, 1, 5, 2],
    }
    for rank, expected in expect_a.items():
        s = DistributedGivenIterationSampler(
            10, total_iter=4, batch_size=3, world_size=2, rank=rank, seed=0)
        np.testing.assert_array_equal(s.indices, expected)
    # case B: dataset (50) larger than the schedule (8) — the reference caps
    # indices at all_size BEFORE tiling, so only the first 8 images appear
    s = GivenIterationSampler(50, total_iter=2, batch_size=4, seed=0)
    np.testing.assert_array_equal(s.indices, [6, 2, 1, 7, 3, 0, 5, 4])
    # case C: single-rank, 7 elements, 3 iters x batch 2
    s = GivenIterationSampler(7, total_iter=3, batch_size=2, seed=0)
    np.testing.assert_array_equal(s.indices, [5, 2, 1, 3, 0, 4])


# ----------------------------------------------------- end-to-end train step

@pytest.fixture(scope="module")
def mesh():
    return data_parallel_mesh()


def _data(batch, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(batch, 32, 32, 3).astype(np.float32)
    y = rng.randint(0, 10, size=batch).astype(np.int32)
    return jnp.asarray(x), jnp.asarray(y)


def test_train_step_runs_and_learns(mesh):
    # tiny_cnn, not a zoo model: this test checks the harness mechanism
    # (scan, collectives, optimizer wiring), which is model-independent;
    # the full-model train step is covered by test_train_step_quantized_path
    # and the trainer CLI smokes (VERDICT.md round-1 weak-item 3).
    model = tiny_cnn()
    tx = make_optimizer("sgd", lambda s: jnp.float32(0.05), momentum=0.9)
    x, y = _data(16)
    state = create_train_state(model, tx, x[:2], jax.random.PRNGKey(0))
    step = make_train_step(model, tx, mesh, donate=False)
    losses = []
    for _ in range(5):
        state, metrics = step(state, x, y)
        losses.append(float(metrics["loss"]))
    assert int(state.step) == 5
    assert losses[-1] < losses[0], losses  # same batch -> loss must drop


def test_train_step_emulate_node_equivalence(mesh):
    """emulate_node=2 with fp32 formats must equal one big batch in grad
    direction: with (8,23) the quantized accumulation is near-identity, so
    losses should track closely.  tiny_cnn keeps the BN-running-stats
    semantics the assertion tolerates while fitting the CPU-mesh budget."""
    model = tiny_cnn()
    tx = make_optimizer("sgd", lambda s: jnp.float32(0.01))
    x, y = _data(32)
    state0 = create_train_state(model, tx, x[:2], jax.random.PRNGKey(0))

    step_plain = make_train_step(model, tx, mesh, emulate_node=1,
                                 donate=False)
    step_emu = make_train_step(model, tx, mesh, emulate_node=2,
                               donate=False)
    s1, m1 = step_plain(state0, x, y)
    s2, m2 = step_emu(state0, x, y)
    # identical data, fp32 path: parameters should be very close (BN micro-
    # batch statistics differ, so exact equality is not expected).
    p1 = np.concatenate([np.asarray(l).ravel()
                         for l in jax.tree.leaves(s1.params)])
    p2 = np.concatenate([np.asarray(l).ravel()
                         for l in jax.tree.leaves(s2.params)])
    assert np.allclose(p1, p2, atol=5e-3)


@pytest.mark.slow
def test_train_step_quantized_path(mesh):
    # tiny_cnn, not full DavidNet: the quantized Kahan step mechanism is
    # model-agnostic (full-DavidNet forward shapes/numerics have their own
    # tests in test_models.py) — compiling the full graph here cost ~46s
    # of suite budget for no extra mechanism coverage
    model = tiny_cnn()
    tx = make_optimizer("sgd", lambda s: jnp.float32(0.01))
    x, y = _data(16)
    state = create_train_state(model, tx, x[:2], jax.random.PRNGKey(0))
    step = make_train_step(model, tx, mesh, use_aps=True, grad_exp=5,
                           grad_man=2, use_kahan=True, donate=False)
    state, metrics = step(state, x, y)
    assert np.isfinite(float(metrics["loss"]))
    for leaf in jax.tree.leaves(state.params):
        assert np.all(np.isfinite(np.asarray(leaf)))


def test_eval_step(mesh):
    model = tiny_cnn()
    tx = make_optimizer("sgd", lambda s: jnp.float32(0.1))
    x, y = _data(16)
    state = create_train_state(model, tx, x[:2], jax.random.PRNGKey(0))
    ev = make_eval_step(model, mesh)
    metrics = ev(state, x, y)
    assert 0.0 <= float(metrics["top1"]) <= 1.0
    assert float(metrics["top5"]) >= float(metrics["top1"])


# ------------------------------------------------------------- data pipeline

def test_cifar_pipeline_shapes_and_determinism():
    tx_img, tx_lab, _, _ = synthetic_cifar10(512, 64)
    pipe = CIFAR10Pipeline(tx_img, tx_lab, batch_size=64)
    sampler = GivenIterationSampler(512, total_iter=4, batch_size=64, seed=0)
    batches = list(pipe.epoch(sampler.indices, seed=7))
    assert len(batches) == 4
    x, y = batches[0]
    assert x.shape == (64, 32, 32, 3) and y.shape == (64,)
    # determinism: same seed -> same bytes
    batches2 = list(pipe.epoch(sampler.indices, seed=7))
    np.testing.assert_array_equal(batches[0][0], batches2[0][0])


def test_cifar_eval_pipeline_no_augment():
    tx_img, tx_lab, _, _ = synthetic_cifar10(128, 64)
    pipe = CIFAR10Pipeline(tx_img, tx_lab, batch_size=32, augment=False)
    x, _ = next(pipe.epoch(np.arange(128)))
    assert x.shape == (32, 32, 32, 3)
    # normalised: roughly zero-mean-ish, well within (-3, 3)
    assert -3 < x.mean() < 3


def test_multi_step_fusion_bitwise(mesh):
    """k scan-fused steps == k single-step calls, bitwise (bench.py's
    measurement unit must be semantically identical training)."""
    from cpd_tpu.train.step import make_multi_train_step

    model = tiny_cnn()
    tx = make_optimizer("sgd", lambda s: jnp.float32(0.05), momentum=0.9)
    rng = np.random.RandomState(0)
    k, B = 3, 16
    xs = jnp.asarray(rng.randn(k, B, 32, 32, 3).astype(np.float32))
    ys = jnp.asarray(rng.randint(0, 10, (k, B)).astype(np.int32))
    state = create_train_state(model, tx, xs[0, :2], jax.random.PRNGKey(0))

    single = make_train_step(model, tx, mesh, use_aps=True, grad_exp=5,
                             grad_man=2, donate=False)
    s1 = state
    for i in range(k):
        s1, m1 = single(s1, xs[i], ys[i])

    multi = make_multi_train_step(model, tx, mesh, k, use_aps=True,
                                  grad_exp=5, grad_man=2, donate=False)
    s2, m2 = multi(state, xs, ys)
    assert int(s2.step) == k
    assert float(m1["loss"]) == float(m2["loss"])
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sync_bn_statistics_are_cross_replica(mesh):
    """bn_axis='dp': one train step's NEW running stats must reflect the
    GLOBAL batch variance, not the per-shard ones (which differ when
    shards see different data).

    The discriminating statistic is `var`, not `mean`: the step pmean's
    the local path's stats across ranks (train/step.py), and the pmean of
    per-shard means IS the global mean — but the pmean of per-shard
    variances is not the global variance (it misses the between-shard
    spread), so only `var` distinguishes sync from local BN."""
    model_sync = tiny_cnn(bn_axis="dp")
    model_local = tiny_cnn()
    tx = make_optimizer("sgd", lambda s: jnp.float32(0.0))
    rng = np.random.RandomState(0)
    # make shard 0's data wildly offset so local vs global stats differ
    x = rng.randn(16, 32, 32, 3).astype(np.float32)
    x[:2] += 50.0
    x = jnp.asarray(x)
    y = jnp.asarray(rng.randint(0, 10, 16).astype(np.int32))

    stats = {}
    for name, model in (("sync", model_sync), ("local", model_local)):
        state = create_train_state(model, tx, x[:2], jax.random.PRNGKey(0))
        step = make_train_step(model, tx, mesh, donate=False)
        new_state, _ = step(state, x, y)
        stats[name] = float(np.asarray(
            new_state.batch_stats["bn0"]["var"]).mean())
    # sync stats see the global batch (between-shard spread included);
    # the local path averages per-shard variances -> strictly smaller
    assert stats["sync"] > stats["local"]
    # sync running var after one step = 0.9*1 + 0.1*global_batch_var of
    # the stem input; sanity-check it moved off the init value
    assert stats["sync"] != 1.0


def test_prefetcher_order_exceptions_and_close():
    from cpd_tpu.utils.prefetch import Prefetcher

    assert list(Prefetcher(iter(range(20)), depth=2)) == list(range(20))

    def boom():
        yield 1
        raise RuntimeError("producer died")

    it = Prefetcher(boom(), depth=2)
    assert next(it) == 1
    with pytest.raises(RuntimeError, match="producer died"):
        next(it)

    # early close unblocks a full queue
    p = Prefetcher(iter(range(1000)), depth=1)
    assert next(p) == 0
    p.close()
