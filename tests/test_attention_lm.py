"""Ring attention + transformer LM + dp/sp/tp train step tests on the
8-virtual-device CPU mesh.

Long-context / multi-axis parallelism is new capability beyond the
reference (SURVEY.md §5: absent there); correctness oracle is agreement
between the sharded and single-device executions of the same math.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from cpd_tpu.compat import shard_map
from cpd_tpu.models.transformer import (TransformerLM, lm_param_specs,
                                        transformer_lm)
from cpd_tpu.ops.attention import local_attention, ring_attention
from cpd_tpu.parallel.mesh import make_mesh


def _rand_qkv(rng, b=2, t=32, h=4, d=8):
    q = rng.randn(b, t, h, d).astype(np.float32)
    k = rng.randn(b, t, h, d).astype(np.float32)
    v = rng.randn(b, t, h, d).astype(np.float32)
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


def test_local_attention_matches_naive():
    rng = np.random.RandomState(0)
    q, k, v = _rand_qkv(rng)
    out = local_attention(q, k, v, causal=True)
    # naive reference
    scale = 1.0 / np.sqrt(q.shape[-1])
    logits = np.einsum("bqhd,bkhd->bhqk", q, k) * scale
    tq = q.shape[1]
    mask = np.tril(np.ones((tq, tq), bool))
    logits = np.where(mask[None, None], logits, -1e30)
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    want = np.einsum("bhqk,bkhd->bqhd", probs, v)
    np.testing.assert_allclose(np.asarray(out), want, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_local(causal):
    """Ring attention over sp=8 equals single-device attention on the full
    sequence."""
    rng = np.random.RandomState(1)
    q, k, v = _rand_qkv(rng, b=2, t=64, h=2, d=16)
    full = local_attention(q, k, v, causal=causal)

    mesh = make_mesh(sp=8, dp=1)

    def body(ql, kl, vl):
        return ring_attention(ql, kl, vl, "sp", causal=causal)

    sharded = jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"), check_vma=False))(q, k, v)
    np.testing.assert_allclose(np.asarray(sharded), np.asarray(full),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_grads_match():
    rng = np.random.RandomState(2)
    q, k, v = _rand_qkv(rng, b=1, t=32, h=2, d=8)
    mesh = make_mesh(sp=8, dp=1)

    def loss_full(q, k, v):
        return jnp.sum(local_attention(q, k, v, causal=True) ** 2)

    def loss_ring(q, k, v):
        def body(ql, kl, vl):
            o = ring_attention(ql, kl, vl, "sp", causal=True)
            return lax.psum(jnp.sum(o ** 2), "sp")
        return shard_map(
            body, mesh=mesh,
            in_specs=(P(None, "sp"),) * 3, out_specs=P(),
            check_vma=False)(q, k, v)

    g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_full, g_ring):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-5, atol=5e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_attention_matches_local(causal):
    """All-to-all sequence parallelism over sp=8 equals single-device
    attention (heads 8 % sp 8 == 0)."""
    from cpd_tpu.ops.attention import ulysses_attention

    rng = np.random.RandomState(11)
    q, k, v = _rand_qkv(rng, b=2, t=64, h=8, d=8)
    full = local_attention(q, k, v, causal=causal)

    mesh = make_mesh(sp=8, dp=1)

    def body(ql, kl, vl):
        return ulysses_attention(ql, kl, vl, "sp", causal=causal)

    sharded = jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(P(None, "sp"),) * 3,
        out_specs=P(None, "sp"), check_vma=False))(q, k, v)
    np.testing.assert_allclose(np.asarray(sharded), np.asarray(full),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_attention_grads_match():
    from cpd_tpu.ops.attention import ulysses_attention

    rng = np.random.RandomState(12)
    q, k, v = _rand_qkv(rng, b=1, t=32, h=8, d=8)
    mesh = make_mesh(sp=8, dp=1)

    def loss_full(q, k, v):
        return jnp.sum(local_attention(q, k, v, causal=True) ** 2)

    def loss_uly(q, k, v):
        def body(ql, kl, vl):
            o = ulysses_attention(ql, kl, vl, "sp", causal=True)
            return lax.psum(jnp.sum(o ** 2), "sp")
        return shard_map(
            body, mesh=mesh,
            in_specs=(P(None, "sp"),) * 3, out_specs=P(),
            check_vma=False)(q, k, v)

    g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    g_uly = jax.grad(loss_uly, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_full, g_uly):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-5, atol=5e-5)


def _rand_gqa(rng, b=2, t=64, h=8, hkv=2, d=8):
    q = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    k = jnp.asarray(rng.randn(b, t, hkv, d).astype(np.float32))
    v = jnp.asarray(rng.randn(b, t, hkv, d).astype(np.float32))
    return q, k, v


def test_ring_attention_gqa_unexpanded_parity():
    """GQA K/V ride the ring UNEXPANDED (round 4): the grouped per-step
    contraction computes the same dot products as the expanded ring —
    last-ulp agreement (XLA's batched-matmul layout differs, so not
    bitwise; measured max |diff| 5e-7) — and matches the full-sequence
    grouped oracle."""
    from cpd_tpu.ops.attention import grouped_query_attention

    rng = np.random.RandomState(21)
    q, k, v = _rand_gqa(rng, h=8, hkv=2)
    rep = q.shape[2] // k.shape[2]
    full = grouped_query_attention(q, k, v, causal=True)

    mesh = make_mesh(sp=8, dp=1)

    def run(kk, vv):
        def body(ql, kl, vl):
            return ring_attention(ql, kl, vl, "sp", causal=True)
        return jax.jit(shard_map(
            body, mesh=mesh, in_specs=(P(None, "sp"),) * 3,
            out_specs=P(None, "sp"), check_vma=False))(q, kk, vv)

    unexp = run(k, v)
    np.testing.assert_allclose(np.asarray(unexp), np.asarray(full),
                               rtol=2e-5, atol=2e-5)
    exp = run(jnp.repeat(k, rep, axis=2), jnp.repeat(v, rep, axis=2))
    np.testing.assert_allclose(np.asarray(unexp), np.asarray(exp),
                               rtol=1e-5, atol=1e-6)


def test_ring_attention_gqa_grads_match():
    """Backward through the grouped ring (reshapes + ppermute transpose)
    equals the single-device grouped oracle's gradients."""
    from cpd_tpu.ops.attention import grouped_query_attention

    rng = np.random.RandomState(22)
    q, k, v = _rand_gqa(rng, b=1, t=32, h=4, hkv=2)
    mesh = make_mesh(sp=8, dp=1)

    def loss_full(q, k, v):
        return jnp.sum(grouped_query_attention(q, k, v, causal=True) ** 2)

    def loss_ring(q, k, v):
        def body(ql, kl, vl):
            o = ring_attention(ql, kl, vl, "sp", causal=True)
            return lax.psum(jnp.sum(o ** 2), "sp")
        return shard_map(
            body, mesh=mesh, in_specs=(P(None, "sp"),) * 3, out_specs=P(),
            check_vma=False)(q, k, v)

    g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_full, g_ring):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-5, atol=5e-5)


@pytest.mark.parametrize("hkv,sp", [(4, 4), (2, 4)])
def test_ulysses_attention_gqa(hkv, sp):
    """Ulysses with GQA: hkv=4 % sp=4 == 0 goes through the all_to_all
    UNEXPANDED; hkv=2, sp=4 triggers the minimal internal expansion
    (e=2, not the full rep=4).  Both match the grouped oracle and the
    legacy fully-expanded ulysses (last-ulp: grouped-einsum layout)."""
    from cpd_tpu.ops.attention import (grouped_query_attention,
                                       ulysses_attention)

    rng = np.random.RandomState(23)
    q, k, v = _rand_gqa(rng, h=8, hkv=hkv, t=32)
    rep = q.shape[2] // hkv
    full = grouped_query_attention(q, k, v, causal=True)

    mesh = make_mesh(sp=sp, dp=1, devices=jax.devices()[:sp])

    def run(kk, vv):
        def body(ql, kl, vl):
            return ulysses_attention(ql, kl, vl, "sp", causal=True)
        return jax.jit(shard_map(
            body, mesh=mesh, in_specs=(P(None, "sp"),) * 3,
            out_specs=P(None, "sp"), check_vma=False))(q, kk, vv)

    unexp = run(k, v)
    np.testing.assert_allclose(np.asarray(unexp), np.asarray(full),
                               rtol=2e-5, atol=2e-5)
    exp = run(jnp.repeat(k, rep, axis=2), jnp.repeat(v, rep, axis=2))
    np.testing.assert_allclose(np.asarray(unexp), np.asarray(exp),
                               rtol=1e-5, atol=1e-6)


class TestChunkedAttention:
    """impl='chunked': the pure-XLA online-softmax K/V-block scan must
    match the one-shot softmax to fp32 round-off — uniform and GQA
    heads, causal and not, Tk not a multiple of the block (pad+mask
    path), gradients, offsets, and the ulysses composition."""

    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize("hkv", [4, 2])
    def test_matches_oracle(self, causal, hkv):
        from cpd_tpu.ops.attention import (_chunked_attention,
                                           grouped_query_attention)

        rng = np.random.RandomState(31)
        q, k, v = _rand_gqa(rng, b=2, t=40, h=4, hkv=hkv, d=8)
        want = grouped_query_attention(q, k, v, causal=causal)
        got = _chunked_attention(q, k, v, causal, 0, 0, block=16)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)
        # public routes (default block > T: single padded block)
        via_grouped = grouped_query_attention(q, k, v, causal=causal,
                                              impl="chunked")
        np.testing.assert_allclose(np.asarray(via_grouped),
                                   np.asarray(want), rtol=2e-5, atol=2e-5)

    def test_offsets_match_xla_path(self):
        from cpd_tpu.ops.attention import _chunked_attention, local_attention

        rng = np.random.RandomState(32)
        q, k, v = _rand_qkv(rng, b=1, t=24, h=2, d=8)
        want = local_attention(q, k, v, causal=True, q_offset=24,
                               k_offset=8)
        got = _chunked_attention(q, k, v, True, 24, 8, block=8)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_fully_masked_rows_zero_in_every_impl(self):
        """A causal shard whose keys are ALL in the future (q_offset +
        Tq <= k_offset) has no attendable key; every impl returns 0 —
        the flash convention, pinned impl-interchangeable since round 5
        (ADVICE r4: chunked previously averaged PAD keys into such
        rows, the one-shot softmax fell back to a uniform average)."""
        from cpd_tpu.ops.attention import _chunked_attention, local_attention

        rng = np.random.RandomState(35)
        q, k, v = _rand_qkv(rng, b=1, t=24, h=2, d=8)
        # all 24 query rows sit before key offset 64: fully masked
        one_shot = local_attention(q, k, v, causal=True, q_offset=0,
                                   k_offset=64)
        chunked = _chunked_attention(q, k, v, True, 0, 64, block=16)
        assert np.all(np.asarray(one_shot) == 0.0)
        assert np.all(np.asarray(chunked) == 0.0)
        # sanity: a PARTIALLY masked call still matches the oracle
        part = _chunked_attention(q, k, v, True, 12, 8, block=16)
        want = local_attention(q, k, v, causal=True, q_offset=12,
                               k_offset=8)
        np.testing.assert_allclose(np.asarray(part), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_grads_match(self):
        from cpd_tpu.ops.attention import (_chunked_attention,
                                           local_attention)

        rng = np.random.RandomState(33)
        q, k, v = _rand_qkv(rng, b=1, t=32, h=2, d=8)

        g_ref = jax.grad(lambda a, b_, c: jnp.sum(
            local_attention(a, b_, c, causal=True) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        g_chk = jax.grad(lambda a, b_, c: jnp.sum(
            _chunked_attention(a, b_, c, True, 0, 0, block=8) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g_ref, g_chk):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=5e-5, atol=5e-5)

    @pytest.mark.parametrize("hkv", [2])  # hkv=1 (MQA) rides the slow
    # long-context smoke (test_long_context_ring_chunked_smoke)
    def test_ring_chunked_inner_fold(self, hkv):
        """ring impl='chunked' with block | T_local engages the inner
        sub-block scan and still matches the one-shot grouped oracle —
        and its grads match the plain ring's."""
        from cpd_tpu.ops.attention import (grouped_query_attention,
                                           ring_attention)

        rng = np.random.RandomState(35)
        q, k, v = _rand_gqa(rng, b=1, t=64, h=2, hkv=hkv, d=8)
        full = grouped_query_attention(q, k, v, causal=True)
        mesh = make_mesh(sp=4, dp=1, devices=jax.devices()[:4])
        # T_local = 16; block=4 -> 4 inner folds per ring step
        def body(ql, kl, vl):
            return ring_attention(ql, kl, vl, "sp", causal=True,
                                  impl="chunked", block=4)
        got = jax.jit(shard_map(
            body, mesh=mesh, in_specs=(P(None, "sp"),) * 3,
            out_specs=P(None, "sp"), check_vma=False))(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(full),
                                   rtol=2e-5, atol=2e-5)

        def loss(impl, block):
            def body(ql, kl, vl):
                o = ring_attention(ql, kl, vl, "sp", causal=True,
                                   impl=impl, block=block)
                return lax.psum(jnp.sum(o ** 2), "sp")
            return shard_map(
                body, mesh=mesh, in_specs=(P(None, "sp"),) * 3,
                out_specs=P(), check_vma=False)
        g_ref = jax.grad(lambda a, b_, c: loss("xla", 512)(a, b_, c),
                         argnums=(0, 1, 2))(q, k, v)
        g_chk = jax.grad(lambda a, b_, c: loss("chunked", 4)(a, b_, c),
                         argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g_ref, g_chk):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=5e-5, atol=5e-5)

    def test_ring_chunked_divisor_and_degenerate(self):
        """block ∤ T_local picks the largest divisor (memory bound kept,
        never a silent whole-block fold); a degenerate split (prime
        T_local) raises."""
        from cpd_tpu.ops.attention import local_attention, ring_attention

        rng = np.random.RandomState(36)
        # T=96 over sp=2 -> T_local=48; block=32 ∤ 48 -> divisor 24
        q, k, v = _rand_qkv(rng, b=1, t=96, h=2, d=8)
        full = local_attention(q, k, v, causal=True)
        mesh = make_mesh(sp=2, dp=1, devices=jax.devices()[:2])

        def run(block, t_slice=96):
            def body(ql, kl, vl):
                return ring_attention(ql, kl, vl, "sp", causal=True,
                                      impl="chunked", block=block)
            return jax.jit(shard_map(
                body, mesh=mesh, in_specs=(P(None, "sp"),) * 3,
                out_specs=P(None, "sp"), check_vma=False))(
                    q[:, :t_slice], k[:, :t_slice], v[:, :t_slice])

        got = run(32)
        np.testing.assert_allclose(np.asarray(got), np.asarray(full),
                                   rtol=2e-5, atol=2e-5)
        # T=94 -> T_local=47 (prime): degenerate, loud
        import pytest as _pytest
        with _pytest.raises(ValueError, match="degenerate"):
            run(32, t_slice=94)

    def test_ulysses_chunked_gqa(self):
        from cpd_tpu.ops.attention import (grouped_query_attention,
                                           ulysses_attention)

        rng = np.random.RandomState(34)
        q, k, v = _rand_gqa(rng, b=2, t=32, h=8, hkv=4, d=8)
        want = grouped_query_attention(q, k, v, causal=True)
        mesh = make_mesh(sp=4, dp=1, devices=jax.devices()[:4])

        def body(ql, kl, vl):
            return ulysses_attention(ql, kl, vl, "sp", causal=True,
                                     impl="chunked")

        got = jax.jit(shard_map(
            body, mesh=mesh, in_specs=(P(None, "sp"),) * 3,
            out_specs=P(None, "sp"), check_vma=False))(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)


def test_ulysses_flash_gqa_native_unexpanded(monkeypatch):
    """With impl='flash' and GQA, ulysses hands the UNEXPANDED K/V chunk
    to the GQA-native Pallas kernel (ops/flash_gqa.py, round 5) — no
    rep× re-materialization on either side of the all_to_all.  The
    kernel runs for real here (interpret mode off-TPU); the spy pins the
    ROUTING: grouped heads reach the kernel unexpanded."""
    import sys

    import cpd_tpu.ops.flash_gqa  # noqa: F401 — ensure module is loaded
    # the package re-exports the function under the submodule's name, so
    # reach the MODULE through sys.modules for patching
    fg_mod = sys.modules["cpd_tpu.ops.flash_gqa"]
    from cpd_tpu.ops.attention import (grouped_query_attention,
                                       ulysses_attention)

    calls = {}
    real = fg_mod.flash_gqa

    def spy(q, k, v, causal=True, bwd="chunked"):
        calls["heads"] = (q.shape[2], k.shape[2])
        return real(q, k, v, causal, bwd)

    monkeypatch.setattr(fg_mod, "flash_gqa", spy)
    rng = np.random.RandomState(24)
    q, k, v = _rand_gqa(rng, h=8, hkv=4, t=32)
    full = grouped_query_attention(q, k, v, causal=True)
    mesh = make_mesh(sp=4, dp=1, devices=jax.devices()[:4])

    def body(ql, kl, vl):
        return ulysses_attention(ql, kl, vl, "sp", causal=True,
                                 impl="flash")

    out = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(P(None, "sp"),) * 3,
        out_specs=P(None, "sp"), check_vma=False))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(full),
                               rtol=2e-5, atol=2e-5)
    assert calls["heads"] == (2, 1)  # grouped heads, K/V unexpanded


@pytest.mark.slow
def test_long_context_ring_chunked_smoke():
    """Long-context path at depth: T=2048 over sp=8 ring with the
    chunked inner fold (T_local=256, block=128 -> 2 inner folds x 8
    ring steps).  Forward parity vs the plain ring, and one LM train
    step on the dp1 x sp8 mesh runs finite and seed-deterministic."""
    from cpd_tpu.models import transformer_lm
    from cpd_tpu.ops.attention import ring_attention
    from cpd_tpu.train import (create_train_state, make_lm_train_step,
                               make_optimizer)

    rng = np.random.RandomState(41)
    q = jnp.asarray(rng.randn(1, 2048, 2, 8).astype(np.float32))
    k = jnp.asarray(rng.randn(1, 2048, 1, 8).astype(np.float32))
    v = jnp.asarray(rng.randn(1, 2048, 1, 8).astype(np.float32))
    mesh = make_mesh(sp=8, dp=1)

    def run(impl, block):
        def body(ql, kl, vl):
            return ring_attention(ql, kl, vl, "sp", causal=True,
                                  impl=impl, block=block)
        return jax.jit(shard_map(
            body, mesh=mesh, in_specs=(P(None, "sp"),) * 3,
            out_specs=P(None, "sp"), check_vma=False))(q, k, v)

    plain = run("xla", 512)
    chunked = run("chunked", 128)
    np.testing.assert_allclose(np.asarray(chunked), np.asarray(plain),
                               rtol=2e-5, atol=2e-5)

    # one real train step at T=2048 through the model's chunked-ring path
    toks = jnp.asarray(rng.randint(0, 64, (8, 2048)).astype(np.int32))
    tgts = jnp.asarray(np.roll(np.asarray(toks), -1, axis=1))
    model = transformer_lm(vocab_size=64, d_model=32, n_layers=1,
                           n_heads=2, n_kv_heads=1, d_ff=64,
                           sp_axis="sp", attn_impl="chunked")
    init_model = transformer_lm(vocab_size=64, d_model=32, n_layers=1,
                                n_heads=2, n_kv_heads=1, d_ff=64)
    tx = make_optimizer("sgd", lambda s: jnp.float32(0.05), momentum=0.9)
    state = create_train_state(init_model, tx, toks[:1],
                               jax.random.PRNGKey(0))
    step = make_lm_train_step(model, tx, mesh, donate=False)
    s1, m1 = step(state, toks, tgts)
    assert np.isfinite(float(m1["loss"]))
    s2, m2 = step(state, toks, tgts)
    assert float(m1["loss"]) == float(m2["loss"])


@pytest.mark.slow
def test_lm_dropout():
    """Dropout: eval is identity (same logits as the rate-0 model on the
    same params), the train step is rng-deterministic, and dropping
    actually changes the training loss."""
    from cpd_tpu.train import (create_train_state, make_lm_train_step,
                               make_optimizer)

    rng = np.random.RandomState(61)
    toks = jnp.asarray(rng.randint(0, 64, (8, 16)).astype(np.int32))
    tgts = jnp.roll(toks, -1, axis=1)

    plain = _tiny_lm()
    dropped = _tiny_lm(dropout_rate=0.5)
    params = plain.init(jax.random.PRNGKey(0), toks)["params"]
    # no new params; eval-mode forward identical
    assert (jax.tree_util.tree_structure(params) == jax.tree_util
            .tree_structure(dropped.init(jax.random.PRNGKey(0),
                                         toks)["params"]))
    np.testing.assert_array_equal(
        np.asarray(plain.apply({"params": params}, toks, train=False)),
        np.asarray(dropped.apply({"params": params}, toks, train=False)))

    mesh = make_mesh(dp=2, sp=2, tp=2)
    tx = make_optimizer("sgd", lambda s: 0.0)
    sh = _tiny_lm(dropout_rate=0.5, tp_axis="tp", sp_axis="sp", tp_size=2)
    state = create_train_state(_tiny_lm(dropout_rate=0.5), tx, toks[:1],
                               jax.random.PRNGKey(0))
    step = make_lm_train_step(sh, tx, mesh, donate=False)
    _, m1 = step(state, toks, tgts)
    _, m2 = step(state, toks, tgts)
    assert np.isfinite(float(m1["loss"]))
    # rng deterministic in (seed, step): identical repeat
    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]))
    # and different from the undropped loss (single-device reference —
    # compiling a third dp x sp x tp step just for this comparison cost
    # ~10s of suite budget; the sharded==single-device loss parity is
    # test_lm_train_step_dp_sp_tp's job)
    import optax

    logits0 = plain.apply({"params": state.params}, toks)
    loss0 = optax.softmax_cross_entropy_with_integer_labels(
        logits0, tgts).mean()
    assert abs(float(m1["loss"]) - float(loss0)) > 1e-4

    # composes with scan_layers (the dropout rng must be lifted through
    # nn.scan's split_rngs or apply raises InvalidRngError)
    scan_model = _tiny_lm(dropout_rate=0.5, scan_layers=True)
    scan_state = create_train_state(scan_model, tx, toks[:1],
                                    jax.random.PRNGKey(0))
    mesh_dp = make_mesh(dp=8)
    _, ms = make_lm_train_step(scan_model, tx, mesh_dp, donate=False)(
        scan_state, toks, tgts)
    assert np.isfinite(float(ms["loss"]))

    # invalid rates fail loudly instead of silently zeroing branches
    bad = _tiny_lm(dropout_rate=1.0)
    with pytest.raises(ValueError, match="dropout_rate"):
        bad.init(jax.random.PRNGKey(0), toks)


@pytest.mark.slow  # feature-level LM compile; core LM step stays fast via test_lm_train_step_dp_sp_tp
def test_lm_label_smoothing():
    """Smoothed loss matches the closed form at step level: ls=0 equals
    plain CE; ls>0 loss is finite and differs; invalid ls raises."""
    from cpd_tpu.train import (create_train_state, make_lm_train_step,
                               make_optimizer)

    mesh = make_mesh(dp=len(jax.devices()))
    tx = make_optimizer("sgd", lambda s: 0.0)   # lr 0: loss is pure fwd
    model = _tiny_lm()
    rng = np.random.RandomState(51)
    toks = jnp.asarray(rng.randint(0, 64, (8, 16)).astype(np.int32))
    tgts = jnp.roll(toks, -1, axis=1)
    state = create_train_state(model, tx, toks[:1], jax.random.PRNGKey(0))

    def loss_at(ls):
        step = make_lm_train_step(model, tx, mesh, donate=False,
                                  label_smoothing=ls)
        _, m = step(state, toks, tgts)
        return float(m["loss"])

    plain = loss_at(0.0)
    import optax
    logits = model.apply({"params": jax.device_get(state.params)}, toks)
    want = float(optax.softmax_cross_entropy_with_integer_labels(
        logits, tgts).mean())
    np.testing.assert_allclose(plain, want, rtol=1e-5)

    ls = 0.1
    smoothed = loss_at(ls)
    soft = (jax.nn.one_hot(tgts, 64) * (1 - ls) + ls / 64)
    want_s = float(optax.softmax_cross_entropy(logits, soft).mean())
    np.testing.assert_allclose(smoothed, want_s, rtol=1e-5)

    with pytest.raises(ValueError, match="label_smoothing"):
        make_lm_train_step(model, tx, mesh, label_smoothing=1.5)


def test_lm_remat_grads_match():
    """jax.checkpoint per block changes memory, not math: params and
    gradients identical with and without remat (single device AND the
    sharded dp/sp/tp step path via param-name equality)."""
    import optax

    rng = np.random.RandomState(21)
    toks = jnp.asarray(rng.randint(0, 64, (2, 16)).astype(np.int32))
    tgts = jnp.roll(toks, -1, axis=1)

    plain = _tiny_lm()
    remat = _tiny_lm(remat=True)
    params = plain.init(jax.random.PRNGKey(0), toks)["params"]
    # identical param trees (remat is transparent to naming/shapes)
    r_params = remat.init(jax.random.PRNGKey(0), toks)["params"]
    assert (jax.tree_util.tree_structure(params)
            == jax.tree_util.tree_structure(r_params))

    def loss(m, p):
        logits = m.apply({"params": p}, toks)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, tgts).mean()

    g_plain = jax.grad(lambda p: loss(plain, p))(params)
    g_remat = jax.grad(lambda p: loss(remat, p))(params)
    for (path, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(g_plain)[0],
            jax.tree_util.tree_flatten_with_path(g_remat)[0]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7, err_msg=str(path))


@pytest.mark.slow  # remat value/grad parity stays fast via test_lm_remat_grads_match
def test_lm_remat_sharded_step_runs():
    """remat composes with the full quantized dp x sp x tp train step
    (ring attention's ppermute recomputes inside jax.checkpoint)."""
    from cpd_tpu.train import (create_train_state, make_lm_train_step,
                               make_optimizer)

    mesh = make_mesh(dp=2, sp=2, tp=2)
    model = _tiny_lm(tp_axis="tp", sp_axis="sp", tp_size=2, remat=True)
    tx = make_optimizer("sgd", lambda s: 0.2, momentum=0.9)
    rng = np.random.RandomState(22)
    toks = jnp.asarray(rng.randint(0, 64, (4, 32)).astype(np.int32))
    tgts = jnp.roll(toks, -1, axis=1)
    state = create_train_state(_tiny_lm(), tx, toks[:1],
                               jax.random.PRNGKey(2))
    step = make_lm_train_step(model, tx, mesh, use_aps=True, grad_exp=5,
                              grad_man=2, donate=False)
    state, metrics = step(state, toks, tgts)
    assert np.isfinite(float(metrics["loss"]))


@pytest.mark.parametrize("causal,q_off", [(True, 0), (True, 3),
                                          (False, 0)])
def test_grouped_query_attention_matches_expanded(causal, q_off):
    """The grouped kernel == local_attention over explicitly repeated
    K/V (the expansion it exists to avoid materializing)."""
    from cpd_tpu.ops.attention import grouped_query_attention

    rng = np.random.RandomState(40)
    b, tq, tk, hkv, rep, d = 2, 5, 8, 2, 3, 8
    q = jnp.asarray(rng.randn(b, tq, hkv * rep, d).astype(np.float32))
    k = jnp.asarray(rng.randn(b, tk, hkv, d).astype(np.float32))
    v = jnp.asarray(rng.randn(b, tk, hkv, d).astype(np.float32))

    got = grouped_query_attention(q, k, v, causal=causal, q_offset=q_off)
    ke = jnp.repeat(k, rep, axis=2)
    ve = jnp.repeat(v, rep, axis=2)
    want = local_attention(q, ke, ve, causal=causal, q_offset=q_off)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-6, atol=2e-6)


def test_lm_gqa_sharded_forward_matches_single():
    """GQA (2 kv heads serving 4 q heads) under dp2 x sp2 x tp2 equals
    the single-device forward — the kv-group <-> tp-slice consistency
    oracle."""
    rng = np.random.RandomState(41)
    toks = jnp.asarray(rng.randint(0, 64, (4, 32)).astype(np.int32))

    ref_model = _tiny_lm(n_kv_heads=2)
    params = ref_model.init(jax.random.PRNGKey(1), toks[:1])["params"]
    want = ref_model.apply({"params": params}, toks)

    mesh = make_mesh(dp=2, sp=2, tp=2)
    sh_model = _tiny_lm(n_kv_heads=2, tp_axis="tp", sp_axis="sp",
                        tp_size=2)
    specs = lm_param_specs(params, "tp")
    out = jax.jit(shard_map(
        lambda p, t: sh_model.apply({"params": p}, t),
        mesh=mesh, in_specs=(specs, P("dp", "sp")),
        out_specs=P("dp", "sp"), check_vma=False))(params, toks)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_lm_gqa_decode_matches_full_forward():
    """GQA decode caches the UNEXPANDED kv heads; prefill logits must
    still equal the full causal forward."""
    model = _tiny_lm(n_kv_heads=2)
    toks = jnp.asarray(np.random.RandomState(42).randint(
        0, 64, (2, 10)).astype(np.int32))
    params = model.init(jax.random.PRNGKey(0), toks)["params"]
    full = model.apply({"params": params}, toks)

    dec = model.clone(decode=True)
    cache = dec.init(jax.random.PRNGKey(1), jnp.zeros((2, 16), jnp.int32),
                     train=False)["cache"]
    # the cache holds 2 kv heads, not 4 — the GQA memory win
    assert cache["block0"]["cached_k"].shape[-2] == 2
    pre, _ = dec.apply({"params": params, "cache": cache}, toks,
                       train=False, mutable=["cache"])
    np.testing.assert_allclose(np.asarray(pre), np.asarray(full),
                               rtol=5e-5, atol=5e-5)


def test_lm_scan_layers_matches_unrolled():
    """nn.scan'd block stack == the Python-loop stack: stacking the loop
    model's per-layer params along a leading axis reproduces the scanned
    model's logits exactly."""
    rng = np.random.RandomState(31)
    toks = jnp.asarray(rng.randint(0, 64, (2, 16)).astype(np.int32))

    loop = _tiny_lm()
    scan = _tiny_lm(scan_layers=True)
    lp = loop.init(jax.random.PRNGKey(0), toks)["params"]

    n_layers = 2
    blocks = [lp[f"block{i}"] for i in range(n_layers)]
    stacked = jax.tree.map(lambda *ls: jnp.stack(ls, axis=0), *blocks)
    sp = {"blocks": stacked}
    sp.update({k: v for k, v in lp.items()
               if not k.startswith("block")})
    # structure agreement with a fresh scanned init
    si = scan.init(jax.random.PRNGKey(0), toks)["params"]
    assert (jax.tree_util.tree_structure(si)
            == jax.tree_util.tree_structure(sp))

    want = loop.apply({"params": lp}, toks)
    got = scan.apply({"params": sp}, toks)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_lm_scan_layers_sharded_step_runs():
    """scan_layers composes with remat and the quantized dp x sp x tp
    train step (rank-aware lm_param_specs shard the stacked kernels)."""
    from cpd_tpu.train import (create_train_state, make_lm_train_step,
                               make_optimizer)

    mesh = make_mesh(dp=2, sp=2, tp=2)
    model = _tiny_lm(tp_axis="tp", sp_axis="sp", tp_size=2,
                     scan_layers=True, remat=True)
    tx = make_optimizer("sgd", lambda s: 0.2, momentum=0.9)
    rng = np.random.RandomState(32)
    toks = jnp.asarray(rng.randint(0, 64, (4, 32)).astype(np.int32))
    tgts = jnp.roll(toks, -1, axis=1)
    state = create_train_state(_tiny_lm(scan_layers=True), tx, toks[:1],
                               jax.random.PRNGKey(2))
    step = make_lm_train_step(model, tx, mesh, use_aps=True, grad_exp=5,
                              grad_man=2, donate=False)
    state, metrics = step(state, toks, tgts)
    assert np.isfinite(float(metrics["loss"]))


def test_lm_scan_layers_decode_raises():
    model = _tiny_lm(scan_layers=True, decode=True)
    with pytest.raises(ValueError, match="scan_layers"):
        model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))


def test_lm_unknown_sp_mode_raises():
    model = _tiny_lm(sp_axis="sp", sp_mode="ulysess")  # typo must not
    toks = jnp.zeros((1, 8), jnp.int32)                # silently ring
    mesh = make_mesh(sp=8, dp=1)
    with pytest.raises(ValueError, match="sp_mode"):
        shard_map(
            lambda t: model.init(jax.random.PRNGKey(0), t),
            mesh=mesh, in_specs=P(None, "sp"), out_specs=P(None, "sp"),
            check_vma=False)(toks)


def test_lm_ulysses_forward_matches_single():
    """dp2 x sp2 x tp2 with sp_mode='ulysses' == single-device forward
    (local heads after tp split: 4/2=2, divisible by sp=2)."""
    rng = np.random.RandomState(13)
    toks = jnp.asarray(rng.randint(0, 64, (4, 32)).astype(np.int32))

    ref_model = _tiny_lm()
    params = ref_model.init(jax.random.PRNGKey(1), toks[:1])["params"]
    want = ref_model.apply({"params": params}, toks)

    mesh = make_mesh(dp=2, sp=2, tp=2)
    sh_model = _tiny_lm(tp_axis="tp", sp_axis="sp", tp_size=2,
                        sp_mode="ulysses")
    specs = lm_param_specs(params, "tp")

    out = jax.jit(shard_map(
        lambda p, t: sh_model.apply({"params": p}, t),
        mesh=mesh, in_specs=(specs, P("dp", "sp")),
        out_specs=P("dp", "sp"), check_vma=False))(params, toks)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def _tiny_lm(**kw):
    return transformer_lm(vocab_size=64, d_model=32, n_layers=2, n_heads=4,
                          d_ff=64, **kw)


def test_lm_forward_single_device():
    model = _tiny_lm()
    toks = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 16)))
    params = model.init(jax.random.PRNGKey(0), toks)["params"]
    logits = model.apply({"params": params}, toks)
    assert logits.shape == (2, 16, 64)
    assert np.isfinite(np.asarray(logits)).all()


def test_lm_sharded_forward_matches_single():
    """dp2 x sp2 x tp2 sharded forward == single-device forward."""
    rng = np.random.RandomState(3)
    toks = jnp.asarray(rng.randint(0, 64, (4, 32)).astype(np.int32))

    ref_model = _tiny_lm()
    params = ref_model.init(jax.random.PRNGKey(1), toks[:1])["params"]
    want = ref_model.apply({"params": params}, toks)

    mesh = make_mesh(dp=2, sp=2, tp=2)
    sh_model = _tiny_lm(tp_axis="tp", sp_axis="sp", tp_size=2)
    specs = lm_param_specs(params, "tp")

    def fwd(p, t):
        return sh_model.apply({"params": p}, t)

    out = jax.jit(shard_map(
        fwd, mesh=mesh, in_specs=(specs, P("dp", "sp")),
        out_specs=P("dp", "sp"), check_vma=False))(params, toks)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_lm_train_step_dp_sp_tp():
    """Full quantized train step over dp2 x sp2 x tp2: runs, loss finite,
    params move, loss decreases over repeated steps on one batch."""
    from cpd_tpu.train import (create_train_state, make_lm_train_step,
                               make_optimizer)

    mesh = make_mesh(dp=2, sp=2, tp=2)
    model = _tiny_lm(tp_axis="tp", sp_axis="sp", tp_size=2)
    tx = make_optimizer("sgd", lambda s: 0.2, momentum=0.9)

    rng = np.random.RandomState(4)
    toks = jnp.asarray(rng.randint(0, 64, (4, 32)).astype(np.int32))
    tgts = jnp.roll(toks, -1, axis=1)

    # init params on the single-device module (global shapes)
    init_model = _tiny_lm()
    state = create_train_state(init_model, tx, toks[:1],
                               jax.random.PRNGKey(2))
    step = make_lm_train_step(model, tx, mesh, use_aps=True, grad_exp=5,
                              grad_man=2, mode="faithful", donate=False)
    state1, m1 = step(state, toks, tgts)
    assert np.isfinite(float(m1["loss"]))
    for _ in range(6):
        state1, m = step(state1, toks, tgts)
    assert float(m["loss"]) < float(m1["loss"])


@pytest.mark.slow
def test_lm_train_step_dp_sp_tp_chunked_gqa():
    """The full composition round 4 added, in one step: chunked
    attention (ring inner fold) + unexpanded GQA K/V + Megatron tp +
    quantized dp collective over dp2 x sp2 x tp2 — trains, and matches
    the same step with impl='xla' to fp32 round-off."""
    from cpd_tpu.train import (create_train_state, make_lm_train_step,
                               make_optimizer)

    mesh = make_mesh(dp=2, sp=2, tp=2)
    tx = make_optimizer("sgd", lambda s: 0.2, momentum=0.9)
    rng = np.random.RandomState(5)
    toks = jnp.asarray(rng.randint(0, 64, (4, 32)).astype(np.int32))
    tgts = jnp.roll(toks, -1, axis=1)
    init_model = _tiny_lm(n_kv_heads=2)
    state = create_train_state(init_model, tx, toks[:1],
                               jax.random.PRNGKey(2))

    def run(impl):
        model = _tiny_lm(tp_axis="tp", sp_axis="sp", tp_size=2,
                         n_kv_heads=2, attn_impl=impl)
        step = make_lm_train_step(model, tx, mesh, use_aps=True,
                                  grad_exp=5, grad_man=2,
                                  mode="faithful", donate=False)
        s, m = step(state, toks, tgts)
        return s, float(m["loss"])

    s_c, l_c = run("chunked")
    s_x, l_x = run("xla")
    assert np.isfinite(l_c)
    np.testing.assert_allclose(l_c, l_x, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(s_c.params),
                    jax.tree.leaves(s_x.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_lm_step_rejects_norm_based_optimizer():
    """LARS trust ratios need global norms; the shard-local LM update must
    refuse it rather than silently compute per-shard norms."""
    from cpd_tpu.train import make_lm_train_step, make_optimizer

    mesh = make_mesh(dp=2, sp=2, tp=2)
    tx = make_optimizer("lars", lambda s: 0.1)
    with pytest.raises(ValueError, match="norm-based"):
        make_lm_train_step(_tiny_lm(), tx, mesh)


def test_lm_train_step_emulate_node():
    from cpd_tpu.train import (create_train_state, make_lm_train_step,
                               make_optimizer)

    mesh = make_mesh(dp=2, sp=2, tp=2)
    model = _tiny_lm(tp_axis="tp", sp_axis="sp", tp_size=2)
    tx = make_optimizer("sgd", lambda s: 0.1)

    rng = np.random.RandomState(5)
    toks = jnp.asarray(rng.randint(0, 64, (8, 32)).astype(np.int32))
    tgts = jnp.roll(toks, -1, axis=1)
    state = create_train_state(_tiny_lm(), tx, toks[:1],
                               jax.random.PRNGKey(3))
    step = make_lm_train_step(model, tx, mesh, emulate_node=2, use_aps=True,
                              grad_exp=5, grad_man=2, mode="fast",
                              donate=False)
    state, m = step(state, toks, tgts)
    assert np.isfinite(float(m["loss"]))
    assert 0.0 <= float(m["accuracy"]) <= 1.0


def test_lm_sharded_grads_match_single_device():
    """Regression for the tp-gradient-scaling bug: gradients computed
    through the dp/sp/tp-sharded loss (with the exact reduction path) must
    equal single-device gradients of the same global-mean loss — for every
    parameter, sharded and replicated alike."""
    import optax
    from cpd_tpu.models.transformer import lm_param_specs

    rng = np.random.RandomState(7)
    toks = jnp.asarray(rng.randint(0, 64, (4, 32)).astype(np.int32))
    tgts = jnp.roll(toks, -1, axis=1)

    ref_model = _tiny_lm()
    params = ref_model.init(jax.random.PRNGKey(5), toks[:1])["params"]

    def ref_loss(p):
        logits = ref_model.apply({"params": p}, toks)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, tgts).mean()

    g_ref = jax.grad(ref_loss)(params)

    mesh = make_mesh(dp=2, sp=2, tp=2)
    sh_model = _tiny_lm(tp_axis="tp", sp_axis="sp", tp_size=2)
    specs = lm_param_specs(params, "tp")

    def sharded_grads(p, tk, tg):
        def loss_of(p):
            logits = sh_model.apply({"params": p}, tk)
            ce = optax.softmax_cross_entropy_with_integer_labels(logits, tg)
            n = lax.psum(jnp.float32(ce.size), ("dp", "sp", "tp"))
            return ce.sum() / n
        grads = jax.grad(loss_of)(p)

        def reduce(g, spec):
            g = lax.psum(g, "sp")
            if spec == P():
                g = lax.psum(g, "tp")
            return lax.psum(g, "dp")   # fp32 dp sum (loss pre-divided by n)

        return jax.tree.map(reduce, grads, specs)

    g_sh = jax.jit(shard_map(
        sharded_grads, mesh=mesh,
        in_specs=(specs, P("dp", "sp"), P("dp", "sp")),
        out_specs=specs, check_vma=False))(params, toks, tgts)

    flat_ref = jax.tree_util.tree_leaves_with_path(g_ref)
    flat_sh = dict(jax.tree_util.tree_leaves_with_path(g_sh))
    assert len(flat_ref) == len(flat_sh)
    for path, leaf in flat_ref:
        np.testing.assert_allclose(
            np.asarray(flat_sh[path]), np.asarray(leaf),
            rtol=2e-5, atol=1e-6, err_msg=str(path))


def test_flash_attention_impl_gating():
    """impl='flash' rejects offsets; on a TPU it must match the XLA path
    (skipped elsewhere — the Pallas TPU kernel doesn't run on CPU)."""
    from cpd_tpu.ops.attention import local_attention

    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(1, 128, 4, 128).astype(np.float32))
    with pytest.raises(ValueError, match="offsets"):
        local_attention(q, q, q, impl="flash", q_offset=4)

    if jax.default_backend() != "tpu":
        pytest.skip("Pallas TPU flash kernel needs a TPU")
    want = np.asarray(local_attention(q, q, q, causal=True))
    got = np.asarray(local_attention(q, q, q, causal=True, impl="flash"))
    np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)


def test_block_gqa_flash_pallas_bwd_matches_xla():
    """Block with attn_impl='flash' + GQA + flash_bwd='pallas' (round
    5): forward AND parameter gradients match the attn_impl='xla' block
    on the same params — the model-level composition of the GQA-native
    kernel with its Pallas backward (CLI: --attn-impl flash
    --flash-bwd pallas --n-kv-heads)."""
    from cpd_tpu.models.transformer import Block

    def blk(impl, bwd="chunked"):
        # 4 q heads over 2 kv heads — genuinely grouped, so the flash
        # route lands on the in-repo GQA kernel, not the stock MHA one
        return Block(head_dim=32, d_ff=64, d_model=128, tp_axis=None,
                     sp_axis=None, tp_size=1, dtype=jnp.float32,
                     n_kv_heads=2, attn_impl=impl, flash_bwd=bwd)

    rng = np.random.RandomState(17)
    h = jnp.asarray(rng.randn(1, 64, 128).astype(np.float32))
    pos = jnp.arange(64)
    vb = blk("xla").init(jax.random.PRNGKey(6), h, pos)

    def loss(impl, bwd="chunked"):
        return lambda p: jnp.sum(
            blk(impl, bwd).apply({"params": p}, h, pos) ** 2)

    out_x = blk("xla").apply(vb, h, pos)
    out_f = blk("flash", "pallas").apply(vb, h, pos)
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_x),
                               rtol=2e-5, atol=2e-5)
    gx = jax.grad(loss("xla"))(vb["params"])
    gf = jax.grad(loss("flash", "pallas"))(vb["params"])
    for (path, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(gf)[0],
            jax.tree_util.tree_flatten_with_path(gx)[0]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-5, atol=5e-5,
                                   err_msg=str(path))


def test_lm_decode_cache_overflow_poisons_with_nan():
    """The documented overflow contract (transformer.py decode docstring,
    ADVICE r2): a write past the allocated cache length cannot raise from
    inside jit, so the step's outputs must be all-NaN — never a silently
    clamped write that argmax would turn into plausible tokens."""
    model = _tiny_lm(decode=True)
    toks = jnp.asarray(np.random.RandomState(5).randint(
        0, 64, (1, 6)).astype(np.int32))
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 8), jnp.int32), train=False)
    params, cache = variables["params"], variables["cache"]

    # prefill 6 of 8 slots — well-formed
    logits, vs = model.apply({"params": params, "cache": cache}, toks,
                             train=False, mutable=["cache"])
    assert not np.isnan(np.asarray(logits)).any()
    cache = vs["cache"]

    # two more single-token steps fill slots 6 and 7; the third writes
    # position 8 == t_max and must poison
    tok = jnp.zeros((1, 1), jnp.int32)
    for step in range(3):
        logits, vs = model.apply({"params": params, "cache": cache}, tok,
                                 train=False, mutable=["cache"])
        cache = vs["cache"]
        nans = np.isnan(np.asarray(logits))
        if step == 2:
            assert nans.all(), "overflow step must poison every logit"
        else:
            assert not nans.any(), f"in-bounds step {step} produced NaN"


@pytest.mark.slow  # second full sharded-LM compile; QuantDense mechanics are fast-tier in test_quant_module
def test_lm_quantized_ffn():
    """ffn_exp/ffn_man route the MLP pair through the quantized GEMM:
    same param tree as the unquantized model (checkpoint compatible),
    different logits at e4m3, gradients finite — and the composition
    holds under tp sharding."""
    toks = jnp.asarray(np.random.RandomState(77).randint(
        0, 64, (4, 8)).astype(np.int32))
    plain = _tiny_lm()
    quant = _tiny_lm(ffn_exp=4, ffn_man=3)
    params = plain.init(jax.random.PRNGKey(0), toks)["params"]
    # identical tree: QuantDense keeps Dense's kernel name/layout
    assert (jax.tree_util.tree_structure(params)
            == jax.tree_util.tree_structure(
                quant.init(jax.random.PRNGKey(0), toks)["params"]))

    out_plain = plain.apply({"params": params}, toks)
    out_quant = quant.apply({"params": params}, toks)
    assert np.isfinite(np.asarray(out_quant)).all()
    assert np.abs(np.asarray(out_quant) - np.asarray(out_plain)).max() > 1e-4

    import optax

    def loss(p):
        logits = quant.apply({"params": p}, toks)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, jnp.roll(toks, -1, axis=1)).mean()

    grads = jax.grad(loss)(params)
    for leaf in jax.tree.leaves(grads):
        assert np.isfinite(np.asarray(leaf)).all()

    # tp2 composition: per-shard quantized accumulation + fp32 psum
    from cpd_tpu.train import create_train_state, make_lm_train_step, \
        make_optimizer

    mesh = make_mesh(dp=4, tp=2)
    sh = _tiny_lm(ffn_exp=4, ffn_man=3, tp_axis="tp", tp_size=2)
    tx = make_optimizer("sgd", lambda s: 0.1)
    state = create_train_state(_tiny_lm(ffn_exp=4, ffn_man=3), tx,
                               toks[:1], jax.random.PRNGKey(2))
    step = make_lm_train_step(sh, tx, mesh, donate=False)
    _, m = step(state, toks, jnp.roll(toks, -1, axis=1))
    assert np.isfinite(float(m["loss"]))
