"""Lint fixture: `kahan-ordering` — unordered reductions over values
that just went through an eXmY cast."""

import jax
import jax.numpy as jnp
from jax import lax

from cpd_tpu.quant.numerics import cast_to_format
from cpd_tpu.parallel.dist import quantize_tree_sr


def direct(x):
    q = cast_to_format(x, 5, 2)
    return jnp.sum(q)                       # XLA picks the order


def nested(g, axis_name):
    return lax.psum(cast_to_format(g, 4, 3), axis_name)


def tree_mapped(grads, axis_name, key):
    grads = quantize_tree_sr(grads, 5, 2, key)
    return jax.tree.map(lambda g: lax.psum(g, axis_name), grads)
