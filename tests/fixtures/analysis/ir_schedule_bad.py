"""ir-schedule bad fixture: (1) a DESYNCED TWIN — two programs claiming
bitwise parity where one ships an extra fp32 debug all_gather the other
never emits (their collective multisets differ, so at pod scale one
rank's program waits at a rendezvous its twin never enters); (2) a
transport collective under a DIVERGENT ``lax.cond`` branch — replicas
disagreeing on the predicate deadlock the mesh.  2 pinned findings."""

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from cpd_tpu.compat import shard_map
from cpd_tpu.parallel.mesh import data_parallel_mesh
from cpd_tpu.parallel.ring import ring_quantized_sum

W, N = 8, 64


def _ring(leak):
    def build():
        mesh = data_parallel_mesh()

        def body(x):
            out = ring_quantized_sum(x[0], "dp", 5, 2, world=W)
            if leak:
                # the desync: a debug gather only THIS twin performs
                out = out + lax.all_gather(x[0], "dp", axis=0,
                                           tiled=False).sum(0)
            return out

        fn = shard_map(body, mesh=mesh, in_specs=(P("dp"),),
                       out_specs=P(), check_vma=False)
        return fn, (jax.ShapeDtypeStruct((W, N), jnp.float32),)
    return build


def _cond_collective():
    def build():
        mesh = data_parallel_mesh()

        def body(x):
            flat = x[0]

            def with_gather(v):
                return lax.all_gather(v, "dp", axis=0,
                                      tiled=False).sum(0)

            def without(v):
                return v

            return lax.cond(jnp.sum(flat) > 0, with_gather, without,
                            flat)

        fn = shard_map(body, mesh=mesh, in_specs=(P("dp"),),
                       out_specs=P(), check_vma=False)
        return fn, (jax.ShapeDtypeStruct((W, N), jnp.float32),)
    return build


def ir_programs(reg):
    reg.declare("fixture.twin_a", _ring(leak=False),
                twin="fixture.desync", axis_sizes={"dp": W})
    reg.declare("fixture.twin_b_leaky", _ring(leak=True),
                twin="fixture.desync", axis_sizes={"dp": W})
    reg.declare("fixture.cond_collective", _cond_collective(),
                axis_sizes={"dp": W})
