"""Clean twin for the host-leak rule: with-scoped handles, finally-
scoped closes, ownership transfer, paired profiler windows, canceled
timers, daemon/joined threads, with-scoped locks, class-managed
files."""

import threading


def read_header(path):
    with open(path) as fh:
        return fh.read(16)


def copy_text(src_path):
    fh = open(src_path)
    try:
        return fh.read()
    finally:
        fh.close()


def open_for_caller(path):
    fh = open(path)
    return fh          # ownership transfer: the caller closes


class PairedProfiler:
    """start_trace has a stop_trace in the same class."""

    def __init__(self, profiler):
        self.profiler = profiler
        self.active = False

    def step(self, s):
        if s == 3:
            self.profiler.start_trace("/tmp/trace")
            self.active = True
        elif s == 5 and self.active:
            self.profiler.stop_trace()
            self.active = False

    def close(self):
        if self.active:
            self.profiler.stop_trace()
            self.active = False


class TidyWatchdog:
    """Started Timer with a cancel path."""

    def __init__(self, timeout):
        self.timeout = timeout
        self._timer = None

    def arm(self):
        self._timer = threading.Timer(self.timeout, self._fire)
        self._timer.daemon = True
        self._timer.start()

    def close(self):
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None

    def _fire(self):
        return self.timeout


class JoinedWorker:
    """Non-daemon Thread, joined in close()."""

    def __init__(self):
        self._worker = threading.Thread(target=self._run)

    def start(self):
        self._worker.start()

    def close(self):
        self._worker.join()

    def _run(self):
        return None


class ScopedLock:
    """with-scoped lock use never trips the acquire/release pairing."""

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0

    def bump(self):
        with self._lock:
            self.value += 1


class ManagedFile:
    """self-stored handle with a class-managed close (the ScalarWriter
    shape)."""

    def __init__(self, path):
        self._fh = open(path, "a")

    def write(self, line):
        self._fh.write(line)

    def close(self):
        self._fh.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
