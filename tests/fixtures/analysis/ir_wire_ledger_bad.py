"""ir-wire-ledger bad fixture: the FP32 LEAK ON THE WIRE — a ring
program whose wire contract is the analytic `ring_transport_bytes`, but
which also ships a raw fp32 debug all_gather the ledger never priced.
The jaxpr-counted bytes exceed the table.  1 pinned finding."""

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from cpd_tpu.compat import shard_map
from cpd_tpu.parallel.mesh import data_parallel_mesh
from cpd_tpu.parallel.ring import ring_quantized_sum, ring_transport_bytes

W, N = 8, 64


def _leaky_ring():
    def build():
        mesh = data_parallel_mesh()

        def body(x):
            out = ring_quantized_sum(x[0], "dp", 5, 2, world=W)
            # the leak: (W-1)*N*4 unpriced fp32 bytes per device
            return out + lax.all_gather(x[0], "dp", axis=0,
                                        tiled=False).sum(0)

        fn = shard_map(body, mesh=mesh, in_specs=(P("dp"),),
                       out_specs=P(), check_vma=False)
        return fn, (jax.ShapeDtypeStruct((W, N), jnp.float32),)
    return build


def ir_programs(reg):
    reg.declare("fixture.leaky_ring", _leaky_ring(),
                axis_sizes={"dp": W},
                wire=lambda: ring_transport_bytes(N, W, 5, 2))
