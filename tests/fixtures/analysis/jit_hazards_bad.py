"""Lint fixture: `jit-hazards` — tracing-unsafe Python under @jax.jit."""

import functools

import jax
import numpy as np


@jax.jit
def branch_on_traced(x):
    if x > 0:                      # TracerBoolConversionError at trace
        return -x
    return x


@jax.jit
def loop_on_traced(x):
    while x < 10:                  # same, while form
        x = x + 1
    return x


@functools.partial(jax.jit, static_argnums=(1,))
def unhashable_static(x, cfg=[1, 2]):   # TypeError at first cache lookup
    return np.log(x)                     # host numpy on a traced value


@jax.jit
def host_escapes(x):
    v = x.item()                   # forces host transfer
    return float(x) + v            # concretization of a tracer
