"""Deliberately-bad fixture for the host-leak rule: resources acquired
or started with no with/finally-scoped or class-managed release — 5
findings pinned in tests/test_analysis.py."""

import threading


def read_header(path):
    fh = open(path)                      # finding 1: straight-path
    data = fh.read(16)                   # close only — leaks on a
    fh.close()                           # read() exception
    return data


class WindowProfiler:
    """Opens a profiler window and never closes it."""

    def __init__(self, profiler):
        self.profiler = profiler

    def step(self, s):
        if s == 3:
            self.profiler.start_trace("/tmp/trace")   # finding 2


class ForgetfulWatchdog:
    """A started Timer with no cancel path outlives its owner."""

    def __init__(self, timeout):
        self.timeout = timeout
        self._timer = None

    def arm(self):
        self._timer = threading.Timer(self.timeout, self._fire)  # finding 3
        self._timer.start()

    def _fire(self):
        return self.timeout


class JoinlessWorker:
    """A started non-daemon Thread with no join path."""

    def __init__(self):
        self._worker = threading.Thread(target=self._run)        # finding 4

    def start(self):
        self._worker.start()

    def _run(self):
        return None


class ManualLock:
    """acquire() with no release() anywhere in the class."""

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0

    def bump(self):
        self._lock.acquire()             # finding 5
        self.value += 1
