"""Lint fixture: every statement below must trip `format-bounds`.
Test data only — the tree walker skips fixtures/ directories."""

from cpd_tpu.quant.numerics import cast_to_format, max_finite
from cpd_tpu.quant.quant_function import float_quantize, quant_gemm


def bad(x, a, b, step):
    y = cast_to_format(x, 9, 2)            # exp_bits > 8
    z = float_quantize(x, 5, 24)           # man > 23
    g = quant_gemm(a, b, 2, 0)             # positional (man, exp): exp=0
    m = max_finite(0, 10)                  # exp_bits < 1
    w = cast_to_format(70000.0, 5, 2)      # e5m2 max finite is 57344
    s = step(grad_exp=12, grad_man=2)      # shared kwarg vocabulary
    return y, z, g, m, w, s
