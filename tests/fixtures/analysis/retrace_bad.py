"""Lint fixture: retrace true positives — jit built per-iteration, the
PR 5 half-keyed ladder table (distilled from the pre-fix CLI code), and
an f-string-keyed jitted-step cache."""

import jax

from cpd_tpu.resilience import (PrecisionSupervisor, StepTable,
                                TransportSupervisor)


def train_forever(step_fn, state, batches):
    step = 0
    while step < 1000:
        # BAD: a fresh jit object every iteration — re-traces each step
        fn = jax.jit(step_fn)
        state = fn(state, batches[step])
        step += 1
    return state


def sweep(step_fn, state, batches):
    for i in range(100):
        # BAD: jit-and-call in an unbounded loop, same hazard
        state = jax.jit(step_fn)(state, batches[i])
    return state


def guarded_loop(build_step, state, batch, grad_exp, grad_man):
    # distilled from the PRE-FIX trainer CLI: both ladders live, but the
    # step table is keyed by the transport coordinate alone
    supervisor = TransportSupervisor(start="ring")
    psup = PrecisionSupervisor("e5m2,e5m7")
    steps = StepTable(build_step)
    # BAD: after a precision escalation this serves the step traced at
    # the OLD format — key through ladder_step_key(supervisor, psup)
    step = steps[supervisor.mode]
    return step(state, batch)


def string_keys(make_step, state, batch, exp, man):
    cache = {}
    key = f"e{exp}m{man}"
    cache[key] = jax.jit(make_step(exp, man))
    # BAD: stringified cache key on a jitted-step table
    return cache[f"e{exp}m{man}"](state, batch)


def overlap_blind(make_train_step, ladder_step_key, build, model, tx,
                  mesh, state, batch):
    # distilled from the ISSUE 8 hazard: the run configures the
    # overlapped transport, but the ladder key has no overlap coordinate
    supervisor = TransportSupervisor(start="ring")
    psup = PrecisionSupervisor("e5m2,e5m7")
    make_train_step(model, tx, mesh, overlap_reduce=True,
                    bucket_elems=65536)
    steps = StepTable(build)
    # BAD: a ladder transition serves a step traced for the wrong
    # schedule/bucket layout — pass overlap=(overlap_reduce, bucket_elems)
    step = steps[ladder_step_key(supervisor, psup)]
    return step(state, batch)


def block_blind(make_train_step, ladder_step_key, build, model, tx,
                mesh, state, batch, ov_key):
    # distilled from the ISSUE 12 hazard: the run configures the
    # block-scaled wire, but the ladder key has no block coordinate
    supervisor = TransportSupervisor(start="ring")
    psup = PrecisionSupervisor("e5m2,e5m7")
    make_train_step(model, tx, mesh, mode="ring", block_scale=True,
                    block_size=128)
    steps = StepTable(build)
    # BAD: a ladder transition serves a step traced for the wrong block
    # layout/numerics — pass block=(block_scale, block_size)
    step = steps[ladder_step_key(supervisor, psup, overlap=ov_key)]
    return step(state, batch)
