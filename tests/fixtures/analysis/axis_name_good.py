"""Lint fixture: clean twin of axis_name_bad — axis literals all bound,
and symbolic axis parameters are out of scope by design."""

import jax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

mesh = Mesh(jax.devices(), ("dp", "tp"))
spec = P("dp", "tp")


def grads_mean(x):
    return lax.pmean(x, "dp")


def library_style(x, axis_name):
    # a variable axis is the library idiom; unresolvable statically
    return lax.psum(x, axis_name)


def multi(x):
    return lax.psum(x, ("dp", "tp"))
