"""Lint fixture: `donation` — reading a buffer after donating it."""

import functools

import jax


@functools.partial(jax.jit, donate_argnums=(0,))
def step(state, batch):
    return state + batch


def train(state, batch):
    out = step(state, batch)
    return out + state          # state's buffer was donated above


fast = jax.jit(lambda s, b: s + b, donate_argnums=(0,))


def train2(state, batch):
    out = fast(state, batch)
    print(state)                # same bug via the jit-assignment form
    return out
