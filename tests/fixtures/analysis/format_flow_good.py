"""Lint fixture: clean twin of format_flow_bad — wide-enough rungs on
the ring path, a man<2 ladder that only ever reaches the faithful
gather, straight component order, matching pack/unpack widths, and a
pytest.raises block asserting the rejection (not hitting it)."""

import pytest

from cpd_tpu.parallel.dist import sum_gradients
from cpd_tpu.quant.numerics import (cast_to_format, pack_exmy,
                                    pack_exmy_blocked, unpack_exmy,
                                    unpack_exmy_blocked)


def run_reduce(grads, ladder, mode):
    return sum_gradients(grads, "dp", mode=mode)


def launch(grads, ladder):
    return run_reduce(grads, ladder, mode="ring")


def go(grads):
    # every rung man >= 2: packable all the way up the ladder
    return launch(grads, ladder="e5m2,e5m7,e8m23")


def go_faithful(grads):
    # man<2 rung is fine where no ring sink is reachable: the faithful
    # gather never packs the wire
    return run_reduce(grads, ladder="e5m2,e8m1", mode="faithful")


def test_ring_rejects_narrow_rungs(grads):
    with pytest.raises(ValueError):
        # asserting the argument-time rejection IS the test's point
        launch(grads, ladder="e5m2,e4m1")


def helper(x, exp, man):
    return cast_to_format(x, exp, man)


def round_trip(x):
    wire = pack_exmy(x, 5, 2)
    return unpack_exmy(wire, 5, 2)


def make_wire(x):
    return pack_exmy(x, 5, 7)


def cross_function_round_trip(x):
    payload = make_wire(x)
    return unpack_exmy(payload, 5, 7)


def blocked_round_trip(x, n):
    # matching (format, block) pair: the sidecar lane slices exactly
    # where it was written
    wire = pack_exmy_blocked(x, 4, 3, 128)
    return unpack_exmy_blocked(wire, 4, 3, n, 128)
