"""Deliberately-bad fixture for the host-clock rule: ad-hoc wall-clock
reads outside obs/timing.py — 4 findings pinned in
tests/test_analysis.py."""

import time
from datetime import datetime
from time import perf_counter


def step_duration(step_fn):
    t0 = time.time()                     # finding 1: epoch diffed for
    step_fn()                            # a duration (NTP can step it)
    return time.time() - t0              # finding 2


def tick():
    return perf_counter()                # finding 3: bare from-import


def run_stamp():
    return datetime.now().isoformat()    # finding 4
