"""Lint fixture: clean twin of compat_drift_bad — every version-gated
surface arrives through cpd_tpu.compat (the one sanctioned shim site),
and modern stable spellings replace the removed APIs."""

import jax

from cpd_tpu.compat import multihost_utils, pallas as pl, shard_map


def gather_hosts(x):
    return multihost_utils.process_allgather(x)


def tree_add(a, b):
    return jax.tree.map(lambda x, y: x + y, a, b)


def kernel_spec():
    # pallas reached through the shim: one edit site when it promotes
    from cpd_tpu.compat import pallas_tpu as pltpu
    return pl.BlockSpec(memory_space=pltpu.ANY)
