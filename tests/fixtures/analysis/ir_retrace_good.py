"""ir-retrace clean twin: the same two programs keyed with the full
(mode, format) coordinate — distinct programs, distinct keys.  (The
reverse — distinct keys for IDENTICAL programs — is also fine:
over-keying only costs a retrace, never a stale step.)"""

import jax
import jax.numpy as jnp

from cpd_tpu.quant.numerics import cast_to_format


def _cast(man):
    def build():
        def fn(g):
            return cast_to_format(g, 5, man)

        return fn, (jax.ShapeDtypeStruct((128,), jnp.float32),)
    return build


def ir_programs(reg):
    reg.declare("fixture.ladder[e5m2]", _cast(2),
                retrace_group="fixture.ladder",
                retrace_key=("ring", (5, 2)))
    reg.declare("fixture.ladder[e5m7]", _cast(7),
                retrace_group="fixture.ladder",
                retrace_key=("ring", (5, 7)))
