"""Lint fixture: compat-drift true positives — jax.experimental imports
and attribute chains, plus a removed-API use, all outside compat.py."""

import jax

# BAD: experimental import (the 0.4.x shard_map spelling)
from jax.experimental.shard_map import shard_map

# BAD: experimental module import
import jax.experimental.pallas as pl

# BAD: the same surface through the side door
from jax import experimental


def gather_hosts(x):
    # BAD: experimental attribute chain in expression position
    return jax.experimental.multihost_utils.process_allgather(x)


def tree_add(a, b):
    # BAD: removed API (jax.tree_multimap died in jax 0.4)
    return jax.tree_multimap(lambda x, y: x + y, a, b)
