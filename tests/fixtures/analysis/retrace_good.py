"""Lint fixture: clean twin of retrace_bad — hoisted jit, the memoized
dict idiom, a bounded literal config sweep, and the StepTable keyed
through ladder_step_key (the PR 5 fix)."""

import jax

from cpd_tpu.resilience import (PrecisionSupervisor, StepTable,
                                TransportSupervisor, ladder_step_key)


def train(step_fn, state, batches):
    fn = jax.jit(step_fn)              # hoisted: one trace
    for batch in batches:
        state = fn(state, batch)
    return state


def memoized(step_fn, state, batches):
    cache = {}
    for batch in batches:
        key = jax.tree.structure(state)
        if key not in cache:           # the train/lm.py idiom
            cache[key] = jax.jit(step_fn)
        state = cache[key](state, batch)
    return state


def config_sweep(step_fn, state, batch):
    out = {}
    for donate in (False, True):
        # a bounded literal sweep: each iteration IS a distinct
        # once-traced config, not a retrace hazard
        fn = jax.jit(step_fn, donate_argnums=(0,) if donate else ())
        out[donate] = fn(state, batch)
    return out


def guarded_loop(build_step, state, batch, grad_exp, grad_man):
    supervisor = TransportSupervisor(start="ring")
    psup = PrecisionSupervisor("e5m2,e5m7")
    steps = StepTable(build_step)
    # the PR 5 fix: both supervisors' coordinates in the key (and
    # explicit overlap=None/block=None: this run has no overlap or
    # block surface)
    step = steps[ladder_step_key(supervisor, psup, overlap=None,
                                 block=None)]
    return step(state, batch)


def overlap_keyed(make_train_step, build, model, tx, mesh, state,
                  batch, overlap_reduce, bucket_elems):
    # the ISSUE 8 fix: the overlap/bucket coordinate rides the key, so a
    # ladder transition can never serve a step traced for the wrong
    # schedule
    supervisor = TransportSupervisor(start="ring")
    psup = PrecisionSupervisor("e5m2,e5m7")
    make_train_step(model, tx, mesh, overlap_reduce=overlap_reduce,
                    bucket_elems=bucket_elems)
    steps = StepTable(build)
    step = steps[ladder_step_key(supervisor, psup,
                                 overlap=(overlap_reduce, bucket_elems),
                                 block=None)]
    return step(state, batch)


def block_keyed(make_train_step, build, model, tx, mesh, state, batch,
                block_scale, block_size):
    # the ISSUE 12 fix: the block coordinate rides the key too, so a
    # ladder transition can never serve a step traced for the wrong
    # block layout/numerics
    supervisor = TransportSupervisor(start="ring")
    psup = PrecisionSupervisor("e5m2,e5m7")
    make_train_step(model, tx, mesh, mode="ring",
                    block_scale=block_scale, block_size=block_size)
    steps = StepTable(build)
    step = steps[ladder_step_key(supervisor, psup, overlap=None,
                                 block=(block_scale, block_size))]
    return step(state, batch)
