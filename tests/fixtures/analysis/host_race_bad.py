"""Deliberately-bad fixture for the host-race rule: state shared
between a thread/Timer callback and main-loop methods with a broken
lock discipline — 3 findings pinned in tests/test_analysis.py."""

import threading


class InconsistentWatch:
    """The watchdog defect shape: context armed UNDER the lock by the
    main loop, read LOCK-FREE in the timer callback."""

    def __init__(self):
        self._lock = threading.Lock()
        self._context = {}
        self._timer = None

    def arm(self, step):
        with self._lock:
            if self._timer is not None:
                self._timer.cancel()
            self._context = {"step": step}
            self._timer = threading.Timer(5.0, self._fire)
            self._timer.daemon = True
            self._timer.start()

    def close(self):
        with self._lock:
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None

    def _fire(self):
        ctx = dict(self._context)        # finding 1: lock-free read
        return ctx


class UnlockedCollector:
    """No lock anywhere, and the worker mutates a plain list the main
    loop also drains — structure mutation across the thread boundary."""

    def __init__(self, items):
        self.results = []
        self.done = False
        self._items = list(items)
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def _run(self):
        for item in self._items:
            self.results.append(item)    # finding 2: unlocked append
        self.done = True                 # plain flag rebind: NOT flagged

    def drain(self):
        out = list(self.results)
        self.results.clear()
        return out


class HalfLockedStats:
    """Writes take the lock; the polling thread reads without it."""

    def __init__(self):
        self._lock = threading.Lock()
        self.stats = {}
        self._poller = threading.Thread(target=self._poll, daemon=True)
        self._poller.start()

    def record(self, key, value):
        with self._lock:
            self.stats[key] = value

    def _poll(self):
        return sum(self.stats.values())  # finding 3: lock-free read
