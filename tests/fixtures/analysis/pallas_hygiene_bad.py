"""Lint fixture: `pallas-hygiene` — kernel allocation, off-tile block
shape, missing memory space."""

import jax.numpy as jnp
from jax.experimental import pallas as pl


def _bad_kernel(x_ref, o_ref):
    acc = jnp.zeros((8, 128), jnp.float32)     # fresh alloc in kernel
    o_ref[:] = x_ref[:] + acc


ragged = pl.BlockSpec((16, 100), lambda i: (i, 0))   # 100 % 128 != 0,
                                                     # and no memory_space
odd_sublanes = pl.BlockSpec((12, 128), lambda i: (i, 0))  # 12 % 8 != 0
