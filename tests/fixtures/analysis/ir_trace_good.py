"""ir-trace clean twin: every registered program builds and traces."""

import jax
import jax.numpy as jnp


def _fine():
    def build():
        return (lambda g: g * 2.0,
                (jax.ShapeDtypeStruct((8,), jnp.float32),))
    return build


def ir_programs(reg):
    reg.declare("fixture.healthy", _fine(), bitwise=True)
