"""ir-wire-ledger clean twin: the same ring program without the debug
gather — the jaxpr-counted wire equals `ring_transport_bytes` exactly
(packed code words, (W-1) reduce hops + (W-1) gather hops)."""

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from cpd_tpu.compat import shard_map
from cpd_tpu.parallel.mesh import data_parallel_mesh
from cpd_tpu.parallel.ring import ring_quantized_sum, ring_transport_bytes

W, N = 8, 64


def _clean_ring():
    def build():
        mesh = data_parallel_mesh()

        def body(x):
            return ring_quantized_sum(x[0], "dp", 5, 2, world=W)

        fn = shard_map(body, mesh=mesh, in_specs=(P("dp"),),
                       out_specs=P(), check_vma=False)
        return fn, (jax.ShapeDtypeStruct((W, N), jnp.float32),)
    return build


def ir_programs(reg):
    reg.declare("fixture.clean_ring", _clean_ring(),
                axis_sizes={"dp": W},
                wire=lambda: ring_transport_bytes(N, W, 5, 2))
