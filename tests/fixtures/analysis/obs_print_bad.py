"""Deliberately-bad fixture for the obs-print rule: library-module
prints (no __main__ guard) that bypass the obs MetricsRegistry/event
stream — 3 findings pinned in tests/test_analysis.py."""


class Scrubber:
    def __init__(self):
        self.pages_corrupt = 0

    def scrub(self, bad_pages):
        # an ad-hoc counter narrated to stdout instead of a registry
        # metric — finding 1
        self.pages_corrupt += len(bad_pages)
        print(f"corrupt pages this scrub: {len(bad_pages)}")


def train_loop(steps):
    for it in range(steps):
        loss = 1.0 / (it + 1)
        if it % 10 == 0:
            print("iter", it, "loss", loss)          # finding 2
    print("done", steps, "steps")                    # finding 3
