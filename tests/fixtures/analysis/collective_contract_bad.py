"""Lint fixture: collective-contract true positives — non-bijective
ppermute permutations (literal and comprehension) and a Kahan partial
shipped over the wire without its compensation term."""

import jax.numpy as jnp
from jax import lax


def repeated_dest(x):
    # BAD: two senders target rank 1 — the received value is
    # backend-order dependent
    return lax.ppermute(x, "dp", [(0, 1), (1, 1)])


def strided(x, w):
    # BAD: stride 2 collides ranks whenever w is even
    perm = [(i, (2 * i) % w) for i in range(w)]
    return lax.ppermute(x, "dp", perm)


def constant_dest(x, w):
    # BAD: every rank sends to rank 0 — ppermute needs a bijection
    return lax.ppermute(x, "dp", [(i, 0) for i in range(w)])


def kahan_hop(res, comp, g):
    y = g - comp
    tmp = res + y
    comp = (tmp - res) - y
    return tmp, comp


def ring_step(x, g, w):
    perm = [(i, (i + 1) % w) for i in range(w)]
    res, comp = kahan_hop(jnp.zeros_like(g), jnp.zeros_like(g), g)
    # BAD: the compensation stays home — the next hop's casts lose the
    # compensated bits and Kahan silently degrades to plain accumulation
    return lax.ppermute(res, "dp", perm)
