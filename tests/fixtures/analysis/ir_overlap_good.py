"""ir-overlap clean twin: the declarations match the jaxprs — the
tapped program is declared overlapped, the post-backward monolith is
declared monolithic."""

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from cpd_tpu.compat import shard_map
from cpd_tpu.parallel.dist import sum_gradients
from cpd_tpu.parallel.mesh import data_parallel_mesh
from cpd_tpu.parallel.overlap import BucketPlan, overlapped_grads

W, D = 8, 32


def _monolith():
    def build():
        mesh = data_parallel_mesh()

        def body(x):
            w = {"w1": jnp.ones((D, D), jnp.float32),
                 "w2": jnp.ones((D, D), jnp.float32)}

            def loss(p):
                return jnp.sum((x[0] @ p["w1"]) @ p["w2"])

            grads = jax.grad(loss)(w)
            return sum_gradients(grads, "dp", grad_exp=5, grad_man=2,
                                 mode="ring", bucket_elems=D * D)

        fn = shard_map(body, mesh=mesh, in_specs=(P("dp"),),
                       out_specs=P(), check_vma=False)
        return fn, (jax.ShapeDtypeStruct((W, 4, D), jnp.float32),)
    return build


def _tapped():
    def build():
        mesh = data_parallel_mesh()

        def body(x):
            w = {"w1": jnp.ones((D, D), jnp.float32),
                 "w2": jnp.ones((D, D), jnp.float32)}
            plan = BucketPlan.for_tree(w, D * D)

            def loss(p):
                return jnp.sum((x[0] @ p["w1"]) @ p["w2"]), None

            _, reduced, _ = overlapped_grads(
                loss, w, axis_name="dp", plan=plan,
                reduce_kw=dict(mode="ring", grad_exp=5, grad_man=2))
            return reduced

        fn = shard_map(body, mesh=mesh, in_specs=(P("dp"),),
                       out_specs=P(), check_vma=False)
        return fn, (jax.ShapeDtypeStruct((W, 4, D), jnp.float32),)
    return build


def ir_programs(reg):
    reg.declare("fixture.true_overlap", _tapped(),
                axis_sizes={"dp": W}, overlap=True)
    reg.declare("fixture.true_monolith", _monolith(),
                axis_sizes={"dp": W}, overlap=False)
