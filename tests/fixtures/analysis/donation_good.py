"""Lint fixture: clean twin of donation_bad — the rebind idiom, and
donation-free calls."""

import functools

import jax


@functools.partial(jax.jit, donate_argnums=(0,))
def step(state, batch):
    return state + batch


def train(state, batches):
    for batch in batches:
        state = step(state, batch)   # rebinding over the donated name
    return state


def train_tuple(state, batch, metrics_fn):
    state, metrics = metrics_fn(state), None  # not a donor: untracked
    out = step(state, batch)
    return out, metrics
