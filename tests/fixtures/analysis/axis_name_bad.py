"""Lint fixture: `axis-name` — collectives naming axes the module never
binds.  The mesh declares ("dp", "tp"); "pd" is the classic typo."""

import jax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

mesh = Mesh(jax.devices(), ("dp", "tp"))
spec = P("dp")


def grads_mean(x):
    return lax.pmean(x, "pd")              # typo: no such axis


def gathered(x):
    return lax.all_gather(x, "model", axis=0)   # unbound axis name
