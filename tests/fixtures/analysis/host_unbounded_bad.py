"""Deliberately-bad fixture for the host-unbounded rule: module-
lifetime containers grown on the step/request clock with no cap,
eviction, or prune anywhere — 4 findings pinned in
tests/test_analysis.py."""

from collections import deque


class ReplayLog:
    """The fleet replay-log defect: one entry per request, forever."""

    def __init__(self):
        self.events = []

    def on_request(self, rid):
        self.events.append(rid)          # finding 1


class SessionIndex:
    """Dict element stores on the admit clock; the snapshot-restore
    rebind reads foreign state, which is NOT a prune."""

    def __init__(self):
        self.sessions = {}

    def admit(self, sid, session):
        self.sessions[sid] = session     # finding 2

    def load_state_dict(self, state):
        self.sessions = dict(state["sessions"])


class SeenSet:
    """Dedup sets keyed by an unbounded id space grow forever."""

    def __init__(self):
        self.seen = set()

    def mark(self, key):
        self.seen.add(key)               # finding 3


class Timeline:
    """A deque is only bounded when constructed with maxlen=."""

    def __init__(self):
        self.marks = deque()

    def tick(self, t):
        self.marks.append(t)             # finding 4
