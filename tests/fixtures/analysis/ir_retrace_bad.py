"""ir-retrace bad fixture: the HALF-KEYED RETRACE — two members of one
StepTable family trace to DISTINCT programs (e5m2 vs e5m7 casts) but
the key derivation dropped the format coordinate, so both carry the
bare transport-mode key.  After a precision-ladder transition the table
would serve the stale format's compiled step (the PR 5 bug, verified
dynamically).  1 pinned finding."""

import jax
import jax.numpy as jnp

from cpd_tpu.quant.numerics import cast_to_format


def _cast(man):
    def build():
        def fn(g):
            return cast_to_format(g, 5, man)

        return fn, (jax.ShapeDtypeStruct((128,), jnp.float32),)
    return build


def ir_programs(reg):
    # both keyed by the bare mode string — the format coordinate is
    # missing, exactly the pre-PR-5 CLI shape
    reg.declare("fixture.ladder[e5m2]", _cast(2),
                retrace_group="fixture.ladder", retrace_key="ring")
    reg.declare("fixture.ladder[e5m7]", _cast(7),
                retrace_group="fixture.ladder", retrace_key="ring")
