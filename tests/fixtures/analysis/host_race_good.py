"""Clean twin for the host-race rule: thread-shared state with a
consistent discipline — snapshot under the lock, synchronized handoff
structures, the ``*_locked`` helper convention, and the deliberate
plain-flag carve-out."""

import queue
import threading
from collections import deque


class SnapshotWatch:
    """Both sides hold the same lock; the callback snapshots under it
    and works on the snapshot (the watchdog fix shape)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._context = {}
        self._timer = None

    def arm(self, step):
        with self._lock:
            if self._timer is not None:
                self._timer.cancel()
            self._context = {"step": step}
            self._timer = threading.Timer(5.0, self._fire)
            self._timer.daemon = True
            self._timer.start()

    def close(self):
        with self._lock:
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None

    def _fire(self):
        with self._lock:
            snapshot = dict(self._context)
        self._handle(snapshot)

    def _handle(self, snapshot):
        return snapshot


class QueueHandoff:
    """Synchronized structures (queue.Queue, threading.Event) need no
    extra lock — their methods synchronize internally."""

    def __init__(self, items):
        self._q = queue.Queue(maxsize=4)
        self._stop = threading.Event()
        self._items = list(items)
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def _run(self):
        for item in self._items:
            if self._stop.is_set():
                break
            self._q.put(item)

    def get(self):
        return self._q.get()

    def close(self):
        self._stop.set()


class LockedHelpers:
    """The ``*_locked`` naming convention: helpers assumed to run with
    the lock held, called from inside ``with self._lock:`` blocks."""

    def __init__(self):
        self._lock = threading.Lock()
        self.pending = deque(maxlen=64)
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def submit(self, item):
        with self._lock:
            self.pending.append(item)

    def _run(self):
        with self._lock:
            self._drain_locked()

    def _drain_locked(self):
        while self.pending:
            self.pending.popleft()


class FlagOnly:
    """A bare boolean rebind is CPython-atomic; crossing the thread
    boundary unlocked is deliberately not flagged."""

    def __init__(self):
        self.tripped = False
        self._timer = threading.Timer(1.0, self._fire)
        self._timer.daemon = True
        self._timer.start()

    def _fire(self):
        self.tripped = True

    def seen(self):
        return self.tripped

    def close(self):
        self._timer.cancel()
