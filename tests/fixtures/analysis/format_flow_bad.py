"""Lint fixture: format-flow true positives — a man<2 ladder rung that
reaches the ring wire through a call, (exp, man) swapped across a call
boundary, and pack/unpack width drift (local + through a callee)."""

from cpd_tpu.parallel.dist import sum_gradients
from cpd_tpu.quant.numerics import (cast_to_format, pack_exmy,
                                    pack_exmy_blocked, unpack_exmy,
                                    unpack_exmy_blocked)


def run_reduce(grads, ladder, mode):
    # the ladder's consumer sits on the ring path
    return sum_gradients(grads, "dp", mode=mode)


def launch(grads, ladder):
    return run_reduce(grads, ladder, mode="ring")


def go(grads):
    # BAD: e4m1 (man < 2) escalation rung, ring transport reachable —
    # pack_exmy rejects man<2, so the first escalation dies mid-jit
    return launch(grads, ladder="e5m2,e4m1")


def helper(x, exp, man):
    # BAD: components crossed across the call boundary — both in range,
    # so format-bounds can never see it
    return cast_to_format(x, man, exp)


def local_drift(x):
    wire = pack_exmy(x, 5, 2)
    # BAD: unpacked at a different declared width than it was packed
    return unpack_exmy(wire, 4, 3)


def make_wire(x):
    return pack_exmy(x, 5, 7)


def cross_function_drift(x):
    payload = make_wire(x)
    # BAD: packer (through the callee) says e5m7, unpacker says e5m2
    return unpack_exmy(payload, 5, 2)


def blocked_size_drift(x, n):
    wire = pack_exmy_blocked(x, 4, 3, 128)
    # BAD: same format, WRONG block size — the sidecar lane re-slices
    # at the wrong block boundaries; every element unscales by a wrong
    # 2^k, bitwise-silently
    return unpack_exmy_blocked(wire, 4, 3, n, 64)


def blocked_into_per_tensor(x):
    wire = pack_exmy_blocked(x, 5, 2, 32)
    # BAD: block-scaled wire into the per-tensor unpacker — the sidecar
    # scale lane is decoded as code words and every 2^k is dropped
    return unpack_exmy(wire, 5, 2)


def per_tensor_into_blocked(x, n):
    wire = pack_exmy(x, 5, 2)
    # BAD: per-tensor wire into the blocked unpacker — there is no
    # sidecar lane; the last code bytes are read as scale shifts
    return unpack_exmy_blocked(wire, 5, 2, n, 32)
