"""Clean twin for the obs-print rule: the three sanctioned shapes —
stderr diagnostics, registry metrics, and script-product stdout behind
a __main__ guard."""

import json
import sys


class Scrubber:
    def __init__(self, registry):
        self.registry = registry

    def scrub(self, bad_pages):
        # numbers go to the registry (one home, one name)
        self.registry.inc("cpd_serve_kv_pages_corrupt", len(bad_pages))
        if bad_pages:
            # occurrences the operator should see are stderr's job
            print(f"=> scrub: {len(bad_pages)} corrupt pages repaired",
                  file=sys.stderr)


def main():
    # a script's stdout IS its product (the bench JSON-line protocol)
    print(json.dumps({"metric": "scrubs", "value": 1}))


if __name__ == "__main__":
    main()
