"""Lint fixture: clean twin of collective_contract_bad — cyclic and
reversal bijections, a literal transposition, and Kahan state whose
compensation rides the wire with the partial (ring.py's contract)."""

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh

mesh = Mesh(jax.devices(), ("dp",))   # binds "dp" for the literals below


def rotate(x, w):
    perm = [(i, (i + 1) % w) for i in range(w)]
    return lax.ppermute(x, "dp", perm)


def rotate_back(x, w):
    return lax.ppermute(x, "dp", [(i, (i - 1) % w) for i in range(w)])


def reverse(x, w):
    return lax.ppermute(x, "dp", [(i, w - 1 - i) for i in range(w)])


def swap_pair(x):
    return lax.ppermute(x, "dp", [(0, 1), (1, 0)])


def kahan_hop(res, comp, g):
    y = g - comp
    tmp = res + y
    comp = (tmp - res) - y
    return tmp, comp


def ring_step(x, g, w):
    perm = [(i, (i + 1) % w) for i in range(w)]
    res, comp = kahan_hop(jnp.zeros_like(g), jnp.zeros_like(g), g)
    wire = jnp.stack([res, comp])      # compensation rides the wire
    return lax.ppermute(wire, "dp", perm)


def plain_step(x, g, w):
    # a non-Kahan two-value unpack shipping only its first half is fine
    res, aux = jnp.split(g, 2)
    return lax.ppermute(res, "dp", [(i, (i + 1) % w) for i in range(w)])
