"""ir-trace bad fixture: a registered program whose build crashes —
the analyzer must report it as a finding AND exit 2 (contracts
unverified), never skip it silently.  1 pinned finding."""

import jax
import jax.numpy as jnp


def _broken():
    def build():
        raise RuntimeError("model weights not found: /nonexistent.ckpt")
    return build


def _fine():
    def build():
        return (lambda g: g * 2.0,
                (jax.ShapeDtypeStruct((8,), jnp.float32),))
    return build


def ir_programs(reg):
    reg.declare("fixture.broken_build", _broken(), bitwise=True)
    # a healthy sibling proves the failure does not poison the run
    reg.declare("fixture.healthy", _fine(), bitwise=True)
