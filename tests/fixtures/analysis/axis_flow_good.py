"""Lint fixture: clean twin of axis_flow_bad — library code that takes
its axis as a parameter (the sanctioned idiom), and a literal axis whose
function IS reached by a mesh constructor binding it (the whole-program
check axis-name's module-local exemption cannot do)."""

import jax
from jax import lax
from jax.sharding import Mesh


def library_reduce(x, axis_name):
    # parameter axes are the library idiom: unresolvable statically,
    # bound by whoever calls us from under their mesh
    return lax.psum(x, axis_name)


def helper_on_dp(x):
    # literal axis — but the driver below declares a mesh binding "dp"
    # and reaches this function through the call graph
    return lax.pmean(x, "dp")


def driver(x):
    mesh = Mesh(jax.devices(), ("dp",))
    with mesh:
        return helper_on_dp(library_reduce(x, "dp"))
