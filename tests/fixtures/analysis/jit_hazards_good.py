"""Lint fixture: clean twin of jit_hazards_bad — static metadata
branching, static args, and jnp-only bodies are all allowed."""

import functools

import jax
import jax.numpy as jnp


@jax.jit
def shape_branching(x):
    n = x.size                     # static metadata alias
    if n == 0:
        return x
    if x.ndim != 2 or x.shape[0] > 8:
        return x.reshape(-1)
    return x


@functools.partial(jax.jit, static_argnums=(1, 2))
def static_branching(x, mode, depth=3):
    if mode == "fast":             # static: fine
        return x * depth
    return jnp.where(x > 0, x, -x)  # traced branch, done the right way


@functools.partial(jax.jit, static_argnames=("cfg",))
def static_by_name(x, cfg="a"):
    if cfg == "a":
        return x + 1
    return x
