"""ir-bitwise bad fixture: the BARE ``jnp.exp2`` IN A BITWISE PROGRAM —
an APS-style shift scale computed with the transcendental whose final
ulp is program-dependent on XLA:CPU (the PR 12 bug, pre-fix shape).
Any cross-program bitwise contract riding this scale holds by luck.
1 pinned finding."""

import jax
import jax.numpy as jnp

from cpd_tpu.quant.numerics import cast_to_format


def _aps_scaled_cast():
    def build():
        def fn(g):
            # pre-fix APS: scale by 2^shift via the unstable primitive
            shift = jnp.float32(24.0)
            scaled = g * jnp.exp2(shift)
            return cast_to_format(scaled, 5, 2) / jnp.exp2(shift)

        return fn, (jax.ShapeDtypeStruct((256,), jnp.float32),)
    return build


def ir_programs(reg):
    reg.declare("fixture.exp2_shift", _aps_scaled_cast(), bitwise=True)
