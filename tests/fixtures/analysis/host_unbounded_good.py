"""Clean twin for the host-unbounded rule: every recognized bound —
deque(maxlen=), cap+eviction, comprehension prune, slice truncate,
keyed eviction — plus init-only growth."""

from collections import deque


class RingLog:
    """Bounded by construction."""

    def __init__(self, cap):
        self.events = deque(maxlen=cap)

    def on_request(self, rid):
        self.events.append(rid)


class CappedLog:
    """Explicit cap + oldest-out eviction (the ResultStore shape)."""

    CAP = 1024

    def __init__(self):
        self.entries = []

    def push(self, item):
        self.entries.append(item)
        if len(self.entries) > self.CAP:
            del self.entries[0]


class PrunedPlacement:
    """A rebind that re-reads the attr is a prune (the fleet router's
    comprehension filter)."""

    def __init__(self):
        self.placement = {}

    def assign(self, sid, engine):
        self.placement[sid] = engine

    def sweep(self, live):
        self.placement = {sid: e for sid, e in self.placement.items()
                          if sid in live}


class TruncatedTrace:
    """Slice-truncate rebind: keeps the newest window."""

    def __init__(self):
        self.trace = []

    def record(self, event):
        self.trace.append(event)
        self.trace = self.trace[-256:]


class EvictingCache:
    """Keyed eviction via pop."""

    def __init__(self):
        self.cache = {}

    def put(self, key, value):
        self.cache[key] = value

    def evict(self, key):
        self.cache.pop(key, None)


class StaticTable:
    """Growth only inside __init__ is setup, not step-clock growth."""

    def __init__(self, names):
        self.rows = []
        for name in names:
            self.rows.append((name, 0))

    def lookup(self, name):
        return [r for r in self.rows if r[0] == name]
