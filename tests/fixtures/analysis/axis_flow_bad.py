"""Lint fixture: axis-flow true positives — a library module (no mesh
declared anywhere in it) that HARDCODES collective axis names no mesh
constructor can reach through the call graph."""

from jax import lax


def library_reduce(x):
    # BAD: literal axis in library code with no mesh on any call path
    return lax.psum(x, "dq")


def library_gather(x):
    # BAD: same hole via all_gather; "data" is nobody's axis here
    return lax.all_gather(x, "data", axis=0, tiled=True)


def caller(x):
    # a caller exists, but it binds no mesh either — the literals still
    # trace against nothing
    return library_reduce(x) + library_gather(x).sum()
