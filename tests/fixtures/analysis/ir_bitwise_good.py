"""ir-bitwise clean twin: the same shift scale built with
`aps.exp2_exact` — bit assembly, exact and program-independent by
construction; no unstable primitive appears in the traced jaxpr."""

import jax
import jax.numpy as jnp

from cpd_tpu.parallel.aps import exp2_exact
from cpd_tpu.quant.numerics import cast_to_format


def _aps_scaled_cast():
    def build():
        def fn(g):
            shift = jnp.float32(24.0)
            scaled = g * exp2_exact(shift)
            return cast_to_format(scaled, 5, 2) / exp2_exact(shift)

        return fn, (jax.ShapeDtypeStruct((256,), jnp.float32),)
    return build


def ir_programs(reg):
    reg.declare("fixture.exp2_exact_shift", _aps_scaled_cast(),
                bitwise=True)
