"""Clean twin for the host-clock rule: everything rides the one shared
clock — now()/Stopwatch for durations, epoch() for timestamps — and
the deliberate non-reads (sleep, clock names inside string literals)
stay silent."""

import time

from cpd_tpu.obs.timing import Stopwatch, epoch, now


def step_duration(step_fn):
    t0 = now()
    step_fn()
    return now() - t0


def lap_times(step_fn, n):
    watch = Stopwatch()
    laps = []
    for _ in range(n):
        step_fn()
        laps.append(watch.lap())
    return laps


def run_stamp():
    return epoch()     # the ONE sanctioned epoch read, by name


def backoff(attempt):
    time.sleep(min(0.1 * attempt, 1.0))   # a delay, not a clock read


# clock names inside string literals (subprocess scripts in tests) are
# not calls and stay silent
CHILD_SCRIPT = "import time; time.time()"
