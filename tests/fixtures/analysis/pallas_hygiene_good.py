"""Lint fixture: clean twin of pallas_hygiene_bad — scratch refs, tile
multiples (including via module constants), explicit memory spaces."""

import jax.numpy as jnp
# the raw spellings keep this fixture self-contained (it never runs);
# live code routes these through cpd_tpu.compat — see compat_drift_good
from jax.experimental import pallas as pl      # cpd: disable=compat-drift — fixture, not live code
from jax.experimental.pallas import tpu as pltpu  # cpd: disable=compat-drift — fixture, not live code

_LANES = 128
_ROWS = 512


def _good_kernel(x_ref, o_ref, acc_ref):
    acc_ref[...] = jnp.zeros_like(acc_ref)     # init through the ref
    o_ref[:] = x_ref[:] + acc_ref[...]


aligned = pl.BlockSpec((_ROWS, _LANES), lambda i: (i, 0),
                       memory_space=pltpu.VMEM)
leading_ones = pl.BlockSpec((1, 1, 8, 128), lambda i, j: (i, 0, j, 0),
                            memory_space=pltpu.VMEM)
full_array = pl.BlockSpec(memory_space=pltpu.ANY)
