"""Clean twin for the `swallow` rule: narrow handlers, and broad ones
that do something with the failure."""

import sys


def narrow_pass(path):
    # a named, narrow exception may be passed — the decision is visible
    try:
        with open(path) as f:
            return f.read()
    except OSError:
        pass
    return None


def broad_but_logged(fn):
    try:
        return fn()
    except Exception as e:
        print(f"fallback after {type(e).__name__}: {e}", file=sys.stderr)
        return None


def broad_but_reraised(fn):
    try:
        return fn()
    except Exception:
        fn.cleanup()
        raise


def narrow_tuple(fn):
    try:
        return fn()
    except (ValueError, KeyError):
        pass
    return 0
