"""Deliberately-bad fixture for the `swallow` rule: 4 findings."""


def bare_handler(path):
    try:
        with open(path) as f:
            return f.read()
    except:                       # noqa: E722 — finding 1: bare except
        return None


def broad_pass(fn):
    try:
        fn()
    except Exception:             # finding 2: swallowed
        pass


def broad_ellipsis(fn):
    try:
        fn()
    except BaseException:         # finding 3: swallowed (even broader)
        ...


def tuple_with_broad(fn):
    for _ in range(3):
        try:
            return fn()
        except (ValueError, Exception):   # finding 4: tuple hides Exception
            continue
