"""Lint fixture: clean twin of kahan_ordering_bad — ordered primitives
for quantized data; unordered reductions only over full-precision
values; rebinding to an unquantized value clears the taint."""

import jax.numpy as jnp
from jax import lax

from cpd_tpu.parallel.reduction import quantized_sum
from cpd_tpu.quant.numerics import cast_to_format


def ordered(stacked):
    return quantized_sum(stacked, 5, 2, use_kahan=True)


def full_precision(x, axis_name):
    s = jnp.sum(x)                      # nothing quantized here
    return lax.psum(s, axis_name)


def rebound(x):
    q = cast_to_format(x, 5, 2)
    q = q * 0.0 + x                     # rebound to full precision
    return jnp.sum(q)
