"""ir-schedule clean twin: both members of the parity group move the
identical collective multiset, and the only ``cond`` carries the SAME
collectives in every branch (uniform across replicas — no rendezvous a
rank can miss)."""

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from cpd_tpu.compat import shard_map
from cpd_tpu.parallel.mesh import data_parallel_mesh
from cpd_tpu.parallel.ring import ring_quantized_sum

W, N = 8, 64


def _ring(scale):
    def build():
        mesh = data_parallel_mesh()

        def body(x):
            # twins may differ in elementwise work (scale) — only the
            # collective schedule is the contract
            return ring_quantized_sum(x[0] * scale, "dp", 5, 2,
                                      world=W)

        fn = shard_map(body, mesh=mesh, in_specs=(P("dp"),),
                       out_specs=P(), check_vma=False)
        return fn, (jax.ShapeDtypeStruct((W, N), jnp.float32),)
    return build


def _uniform_cond():
    def build():
        mesh = data_parallel_mesh()

        def body(x):
            flat = x[0]

            def pos(v):
                return lax.all_gather(v, "dp", axis=0,
                                      tiled=False).sum(0)

            def neg(v):
                return lax.all_gather(-v, "dp", axis=0,
                                      tiled=False).sum(0)

            return lax.cond(jnp.sum(flat) > 0, pos, neg, flat)

        fn = shard_map(body, mesh=mesh, in_specs=(P("dp"),),
                       out_specs=P(), check_vma=False)
        return fn, (jax.ShapeDtypeStruct((W, N), jnp.float32),)
    return build


def ir_programs(reg):
    reg.declare("fixture.twin_a", _ring(1.0),
                twin="fixture.clean", axis_sizes={"dp": W})
    reg.declare("fixture.twin_b", _ring(2.0),
                twin="fixture.clean", axis_sizes={"dp": W})
    reg.declare("fixture.uniform_cond", _uniform_cond(),
                axis_sizes={"dp": W})
