"""Lint fixture: clean twin of format_bounds_bad — every call is legal."""

from cpd_tpu.quant.numerics import cast_to_format, max_finite
from cpd_tpu.quant.quant_function import float_quantize, quant_gemm


def good(x, a, b, step, exp, man):
    y = cast_to_format(x, 8, 23)           # fp32 identity format
    z = float_quantize(x, 5, 2)            # e5m2
    g = quant_gemm(a, b, 10, 5)            # fp16-ish accumulator
    m = max_finite(4, 3)
    w = cast_to_format(57344.0, 5, 2)      # exactly e5m2's max finite
    v = cast_to_format(x, exp, man)        # non-literal: out of scope
    s = step(grad_exp=5, grad_man=2)
    return y, z, g, m, w, v, s
