"""ResNet18/CIFAR-10 trainer — parity with the reference flagship entry
`example/ResNet18/tools/mix.py` (flags mix.py:29-44, YAML merge :69-72,
schedule :181-198, loop :224-356), rebuilt on the shared cpd_tpu harness.

Where the reference runs one Python loop per parameter per micro-batch
(SURVEY.md §3.1), here the whole quantized step — emulate-node scan, APS,
low-precision ordered all-reduce, LARS/SGD — is ONE jitted shard_map
program per step (cpd_tpu/train/step.py).

Usage (mirrors README.md:76-79's single-host quick start):
    python examples/resnet18_cifar/train.py --use_APS --grad_exp 5 \
        --grad_man 2 --emulate_node 8
"""

from __future__ import annotations

import argparse
import math
import os
import sys

import numpy as np

# Make the repo importable when run as a script (the reference required a
# manual PYTHONPATH export, README.md:39; here the entry bootstraps itself).
_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from cpd_tpu.obs.timing import now  # noqa: E402  (the one clock; jax-free)


def build_parser() -> argparse.ArgumentParser:
    here = os.path.dirname(os.path.abspath(__file__))
    p = argparse.ArgumentParser(description="cpd_tpu ResNet18/CIFAR10")
    # the reference's surface (mix.py:29-44)
    p.add_argument("--config", default=os.path.join(here, "configs",
                                                    "res18_cifar.yaml"))
    p.add_argument("--dist", action="store_true",
                   help="multi-host: call jax.distributed.initialize()")
    p.add_argument("--load-path", default="", type=str)
    p.add_argument("--init-from-torch", default="", type=str,
                   help="warm-start params+BN stats from a reference "
                        "CPDtorch .pth checkpoint (res_cifar arch; "
                        "cpd_tpu.interop converts the layout)")
    p.add_argument("--export-torch", default="", type=str,
                   help="after the run (train or -e), write params+BN "
                        "stats as a reference-format .pth (state_dict "
                        "wrapper, res_cifar key layout) loadable by the "
                        "torch reference — the reverse migration path")
    p.add_argument("--grad_exp", default=5, type=int)
    p.add_argument("--grad_man", default=2, type=int)
    p.add_argument("--grad-rounding", default="nearest",
                   choices=["nearest", "stochastic"],
                   help="rounding of every cast in the gradient pipeline "
                        "(emulate-node + all-reduce): stochastic = "
                        "unbiased SR, the alternative to APS's exponent "
                        "shifting for sub-ulp gradient survival")
    p.add_argument("--grad-seed", default=0, type=int,
                   help="PRNG seed for --grad-rounding stochastic")
    p.add_argument("--resume-opt", action="store_true")
    p.add_argument("--use_lars", action="store_true")
    p.add_argument("--use_APS", action="store_true")
    p.add_argument("--use_kahan", action="store_true")
    # optimizer-state precision (beyond the reference): hold the SGD
    # momentum buffer in eXmY, the state analog of --grad_exp/--grad_man
    p.add_argument("--opt_exp", default=8, type=int)
    p.add_argument("--opt_man", default=23, type=int)
    p.add_argument("--opt_kahan", action="store_true",
                   help="Kahan-compensate the quantized momentum buffer")
    p.add_argument("--opt-rounding", default="nearest",
                   choices=["nearest", "stochastic"],
                   help="rounding of the eXmY momentum-buffer casts: "
                        "stochastic = unbiased SR (cures sub-ulp/2 update "
                        "stagnation; train/optim.py quant_sgd)")
    p.add_argument("--opt-seed", default=0, type=int,
                   help="PRNG seed for --opt-rounding stochastic")
    p.add_argument("--optimizer", default="auto",
                   choices=["auto", "sgd", "nesterov", "lars",
                            "quant_sgd", "shampoo-lite"],
                   help="optimizer family.  'auto' (default) keeps the "
                        "legacy flag-driven choice (--use_lars / "
                        "--opt_exp&co -> quant_sgd, else sgd).  "
                        "'shampoo-lite' is the second-order optimizer "
                        "riding the quantized ring (ISSUE 15, "
                        "train/optim.py ShampooLite): per-leaf Gram "
                        "statistics through the eXmY Kahan qgemm, "
                        "cross-replica statistics reduced over the "
                        "quantized ring, L^-1/4 G R^-1/4 "
                        "preconditioning grafted to the SGD norm")
    p.add_argument("--shampoo-stat-exp", default=8, type=int,
                   help="eXmY exponent bits of the Shampoo-lite Gram "
                        "statistics (8,23 = fp32 statistics)")
    p.add_argument("--shampoo-stat-man", default=23, type=int,
                   help="eXmY mantissa bits of the Shampoo-lite Gram "
                        "statistics")
    p.add_argument("--shampoo-stat-mode", default="ring",
                   choices=["ring", "gather"],
                   help="transport of the cross-replica statistics "
                        "reduction: quantized ring (default) or "
                        "all_gather + ordered scan")
    p.add_argument("-e", "--evaluate", action="store_true")
    p.add_argument("--emulate_node", default=1, type=int)
    # YAML-backed keys (mix.py:69-72 merges the YAML onto args); a CLI
    # value beats the YAML one, so default=None means "take the YAML's".
    p.add_argument("--arch", default=None, type=str)
    p.add_argument("--batch_size", default=None, type=int)
    p.add_argument("--max_epoch", default=None, type=int)
    p.add_argument("--save_path", default=None, type=str)
    p.add_argument("--val_freq", default=None, type=int)
    p.add_argument("--print_freq", default=None, type=int)
    # new surface (no reference equivalent)
    p.add_argument("--data-root", default=None)
    p.add_argument("--peak-lr", default=None, type=float,
                   help="override the hardcoded 1.6 post-warmup peak LR "
                        "(mix.py:181-198) — small archs/batches need less")
    p.add_argument("--max-iter", default=None, type=int,
                   help="override total iterations (smoke tests)")
    p.add_argument("--clip-grad", default=None, type=float,
                   help="global-norm gradient clipping (applied to the "
                        "fully reduced replicated gradients, so local "
                        "norms are exact)")
    p.add_argument("--profile-dir", default=None,
                   help="write a jax.profiler trace of a few steps here")
    p.add_argument("--tensorboard", action="store_true",
                   help="also write TensorBoard event files next to the "
                        "JSONL scalars (reference mix.py:16,168-171)")
    p.add_argument("--mode", default="faithful",
                   choices=["faithful", "fast", "ring"],
                   help="faithful: bit-ordered quantized reduction; "
                        "fast: quantize->psum->dequantize; ring: ordered "
                        "quantized reduce-scatter/all-gather ring with "
                        "bit-packed eXmY wire (parallel/ring.py)")
    p.add_argument("--zero1", action="store_true",
                   help="ZeRO-1: shard the optimizer state 1/W over dp "
                        "(composes with --use_lars via zero1_lars, "
                        "round 5; parallel/zero.py)")
    p.add_argument("--zero2", action="store_true",
                   help="ZeRO-2: momentum AND the faithful reduction "
                        "sharded (all_to_all reduce-scatter; composes "
                        "with --use_lars).  --zero3 lives on the "
                        "ResNet-50 CLI (portable checkpoint layout)")
    from cpd_tpu.utils.config import (add_obs_flags,
                                      add_resilience_flags,
                                      add_transport_flags)
    add_resilience_flags(p)       # --fault-plan / guard / watchdog
    add_transport_flags(p)        # --overlap-reduce / --bucket-elems
    add_obs_flags(p)              # --obs-dir / --obs-flight
    return p


def main(argv=None) -> dict:
    args = build_parser().parse_args(argv)

    import jax
    import jax.numpy as jnp

    from cpd_tpu.data import CIFAR10Pipeline, load_cifar10
    from cpd_tpu.data.samplers import DistributedGivenIterationSampler
    from cpd_tpu.models import get_model
    from cpd_tpu.parallel.dist import (dist_init, host_batch_to_global,
                                       replicate)
    from cpd_tpu.parallel.mesh import data_parallel_mesh
    from cpd_tpu.train import (CheckpointManager, create_train_state,
                               make_eval_step, make_optimizer,
                               make_train_step, warmup_step_decay)
    from cpd_tpu.utils import (ProgressPrinter, ScalarWriter, StepProfiler,
                               format_validation_line, load_yaml_config,
                               merge_config_into_args)

    rank, world = dist_init() if args.dist else (0, 1)
    explicit = {k: v for k, v in vars(args).items() if v is not None}
    merge_config_into_args(args, load_yaml_config(args.config),
                           cli_overrides=explicit)

    mesh = data_parallel_mesh()
    n_dev = mesh.devices.size
    seed = 24                                   # mix.py:23

    train_x, train_y, test_x, test_y = load_cifar10(args.data_root)
    dataset_len = len(train_y)

    # Schedule shape of mix.py:181-198: warmup 0.1 -> 1.6 over 5 epochs,
    # x0.1 after epochs 40 and 80; iters/epoch counts the emulated cluster.
    iter_per_epoch = math.ceil(
        dataset_len / (n_dev * args.batch_size * args.emulate_node))
    total_iter = args.max_epoch * iter_per_epoch
    if args.max_iter is not None:
        total_iter = args.max_iter
    peak_lr = args.peak_lr if args.peak_lr is not None else 1.6
    schedule = warmup_step_decay(
        peak_lr, 5 * iter_per_epoch,
        [40 * iter_per_epoch, 80 * iter_per_epoch],
        warmup_from=peak_lr / 16.0)

    model = get_model(args.arch)
    quant_opt = (args.opt_exp, args.opt_man) != (8, 23) or args.opt_kahan
    if quant_opt and args.use_lars:
        raise SystemExit("--use_lars and --opt_exp/--opt_man/--opt_kahan "
                         "are exclusive")
    if (args.opt_rounding != "nearest"
            and (args.opt_exp, args.opt_man) == (8, 23)):
        # quant_opt alone is not enough: --opt_kahan with an fp32 buffer
        # would silently drop SR (quant_sgd's (8,23) identity shortcut)
        raise SystemExit("--opt-rounding stochastic needs a quantized "
                         "buffer (--opt_exp/--opt_man below fp32)")
    shampoo_on = args.optimizer == "shampoo-lite"
    if shampoo_on:
        # the ShampooLite updater owns the optimizer math AND the
        # collective (reduce_in_update, like the ZeRO updaters) — the
        # optax-chain knobs cannot ride along
        if args.use_lars or quant_opt:
            raise SystemExit("--optimizer shampoo-lite is exclusive "
                             "with --use_lars and the quantized "
                             "momentum flags (--opt_exp/--opt_man/"
                             "--opt_kahan)")
        if args.clip_grad is not None:
            raise SystemExit("--clip-grad runs inside the optax chain, "
                             "which the ShampooLite updater bypasses")
        if args.overlap_reduce:
            raise SystemExit("--overlap-reduce does not compose with "
                             "--optimizer shampoo-lite (the updater "
                             "owns the collective; only the ZeRO-2 "
                             "updater has a tap hook)")
        if args.bucket_elems is not None:
            raise SystemExit("--bucket-elems does not compose with "
                             "--optimizer shampoo-lite: the step hands "
                             "the updater its quant kwargs without the "
                             "bucket layout, so the requested bucketed "
                             "transport would silently never run")
    if not shampoo_on and (
            (args.shampoo_stat_exp, args.shampoo_stat_man) != (8, 23)
            or args.shampoo_stat_mode != "ring"):
        # same loud-rejection rule as --opt_exp below: statistics-format
        # flags without the optimizer that consumes them must not
        # silently vanish
        raise SystemExit("--shampoo-stat-exp/--shampoo-stat-man/"
                         "--shampoo-stat-mode need --optimizer "
                         "shampoo-lite; any other optimizer would "
                         "silently ignore them")
    if args.optimizer not in ("auto", "shampoo-lite"):
        if args.use_lars and args.optimizer != "lars":
            raise SystemExit("--use_lars contradicts --optimizer "
                             f"{args.optimizer}")
        if quant_opt and args.optimizer != "quant_sgd":
            # under 'auto' these flags SELECT quant_sgd; an explicit
            # other optimizer would silently drop them — the numerics
            # the user asked for must not vanish without a word
            raise SystemExit(f"--opt_exp/--opt_man/--opt_kahan need "
                             f"the quantized momentum buffer; "
                             f"--optimizer {args.optimizer} would "
                             f"ignore them (use quant_sgd or auto)")
    opt_name = (args.optimizer if args.optimizer not in ("auto",
                                                         "shampoo-lite")
                else "lars" if args.use_lars else
                "quant_sgd" if quant_opt else "sgd")
    tx = make_optimizer(opt_name, schedule, momentum=args.momentum,
                        weight_decay=args.weight_decay,
                        opt_exp=args.opt_exp, opt_man=args.opt_man,
                        opt_kahan=args.opt_kahan,
                        opt_rounding=args.opt_rounding,
                        opt_seed=args.opt_seed,
                        clip_norm=args.clip_grad)
    # Resilience stack (docs/RESILIENCE.md).  This trainer wires the
    # in-step defenses (guard + injected gradient faults), the host
    # faults, the watchdog, and the divergence STOP; checkpoint-rollback
    # recovery lives on the LM trainer, whose synchronous batch fetch
    # can rewind (the Prefetcher pipeline here cannot).
    from cpd_tpu.utils.config import build_resilience
    res = build_resilience(args, n_steps=total_iter, rank=rank,
                           world=n_dev)
    if res["wraps_optimizer"] and (args.zero1 or args.zero2
                                   or shampoo_on):
        # watchdog / sentinel / host-level faults compose fine with ZeRO
        # and Shampoo-lite; only the optimizer WRAPPERS (guard,
        # grad-fault injection) don't
        raise SystemExit("--guard-grads / grad_* faults do not compose "
                         "with the ZeRO/ShampooLite updaters (custom "
                         "update_fn owns the optimizer math the guard "
                         "would wrap)")
    if res["verify"] and (args.zero1 or args.zero2 or shampoo_on):
        raise SystemExit("--verify-reduce needs the step's own reduction "
                         "and a donate-free state for discard-and-retry; "
                         "the ZeRO/ShampooLite updaters own the "
                         "collective (reduce_in_update) — run without "
                         "--zero1/--zero2/--optimizer shampoo-lite")
    if res["quant_stats"] and (args.zero1 or args.zero2 or shampoo_on):
        raise SystemExit("--precision-ladder/--quant-telemetry need the "
                         "step's own reduction for the wire telemetry; "
                         "the ZeRO/ShampooLite updaters own the "
                         "collective (reduce_in_update) — run without "
                         "--zero1/--zero2/--optimizer shampoo-lite")
    # ISSUE 12 lifted the PR 8 fail-fasts: --bucket-elems/--overlap-reduce
    # compose with --zero1 (the update slices the step's fully-reduced
    # grads) AND --zero2 (zero2_sgd(bucket_elems=...) adopts the bucketed
    # flat layout and its make_tap_reduce hook runs the per-bucket
    # reduce-scatter inside the backward taps); --overlap-reduce also
    # composes with --emulate_node > 1 (the unrolled micro chain feeds
    # the last micro-batch's taps); --block-scale composes with --zero2
    # (the faithful all_to_all carries the blocked wire).
    if args.block_scale and args.mode != "ring" and not args.zero2:
        raise SystemExit("--block-scale needs --mode ring (or --zero2, "
                         "whose all_to_all carries the blocked wire): "
                         "the per-block scale sidecar rides a packed "
                         "wire")
    if args.block_scale and args.grad_man < 2:
        raise SystemExit(f"--block-scale needs a packable gradient format "
                         f"(man_bits >= 2 for the codec's special codes), "
                         f"got e{args.grad_exp}m{args.grad_man}")
    if res["active"]:
        tx = res["wrap_tx"](tx, axis_name="dp")
    injector, watchdog = res["injector"], res["watchdog"]
    sentinel, meter = res["sentinel"], res["meter"]
    psup = res["precision"]
    esup = res["elastic"]
    # observability spine (docs/OBSERVABILITY.md): pure host-side
    # observation — step outputs bitwise identical with or without
    # --obs-dir (pinned by the obs-smoke gate).  The data span lives on
    # the Prefetcher's producer thread, so this trainer traces only the
    # step/validate/checkpoint phases it runs on the main thread.
    from cpd_tpu.obs import NULL_TRACER
    from cpd_tpu.utils.config import build_obs
    obs = build_obs(args, run="resnet18",
                    meta={"mode": args.mode,
                          "grad_format": [args.grad_exp,
                                          args.grad_man]})
    otr = obs["tracer"] if obs["tracer"] is not None else NULL_TRACER
    oreg, oflight = obs["registry"], obs["flight"]
    if watchdog is not None and oflight is not None:
        watchdog.on_trip = lambda ctx: oflight.dump("watchdog")

    def run_meta():
        # ladder state rides every checkpoint's metadata sidecar so a
        # restart resumes AT the escalated format (docs/RESILIENCE.md
        # "Precision ladder"); the elastic fleet view rides along so a
        # process restart resumes with the same alive set (ISSUE 19)
        meta = {}
        if psup is not None:
            meta["precision"] = psup.state_dict()
        if esup is not None:
            meta["elastic"] = esup.state_dict()
        return meta or None

    state = create_train_state(model, tx, jnp.zeros((2, 32, 32, 3)),
                               jax.random.PRNGKey(seed))
    zero = None
    shampoo = None
    if shampoo_on:
        if args.zero1 or args.zero2:
            raise SystemExit("--optimizer shampoo-lite and --zero1/"
                             "--zero2 are mutually exclusive (one "
                             "custom updater per step)")
        from cpd_tpu.train import shampoo_lite
        shampoo = shampoo_lite(
            schedule, world=n_dev, momentum=args.momentum,
            weight_decay=args.weight_decay,
            stat_exp=args.shampoo_stat_exp,
            stat_man=args.shampoo_stat_man,
            stat_mode=args.shampoo_stat_mode)
        state = state.replace(opt_state=shampoo.init(state.params))
    if args.zero1 and args.zero2:
        raise SystemExit("--zero1/--zero2 are mutually exclusive")
    if args.zero1 or args.zero2:
        if quant_opt:
            raise SystemExit("--zero1/--zero2 do not compose with the "
                             "quantized optimizer state (the ZeRO "
                             "updaters carry fp32 flat momentum)")
        if args.clip_grad is not None:
            raise SystemExit("--clip-grad runs inside the optax chain, "
                             "which the ZeRO updaters bypass")
        if args.zero2 and args.mode != "faithful":
            raise SystemExit("--zero2 shards the faithful reduction; "
                             "--mode fast is not supported with it")
        from cpd_tpu.parallel import zero as zero_mod
        maker = getattr(zero_mod,
                        ("zero1" if args.zero1 else "zero2")
                        + ("_lars" if args.use_lars else "_sgd"))
        # world = the dp axis size (emulate_node replicas live INSIDE a
        # rank's micro-batch scan, same as the resnet50 CLI's wiring).
        # ZeRO-2 adopts the bucketed flat layout when --bucket-elems is
        # set, so the overlap taps and the update consume the SAME
        # per-bucket shards (zero2_sgd's make_tap_reduce, ISSUE 12)
        zkw = dict(momentum=args.momentum,
                   weight_decay=args.weight_decay)
        if args.zero2:
            zkw["bucket_elems"] = args.bucket_elems
        zero = maker(schedule, world=n_dev, **zkw)
        state = state.replace(opt_state=zero.init(state.params))
    ckpt_dir = os.path.abspath(args.save_path)
    manager = CheckpointManager(ckpt_dir, track_best=True,
                                integrity=getattr(args, "ckpt_integrity",
                                                  True))
    start_iter = 0
    if args.init_from_torch and args.load_path:
        raise SystemExit("--init-from-torch and --load-path are exclusive")
    if args.export_torch and args.arch != "res_cifar":
        # fail in milliseconds, not after the training run: only the
        # reference CIFAR ResNet-18 has a torch key map
        raise SystemExit(f"--export-torch supports --arch res_cifar only "
                         f"(got --arch {args.arch})")
    if args.init_from_torch:
        # Migration path: continue training / evaluate a model trained by
        # the torch reference (docs/MIGRATING.md).  Params + BN running
        # stats come from the .pth; optimizer state starts fresh.  Takes
        # the same precedence --load-path has: auto-resume from save_path
        # must NOT silently overwrite an explicitly requested import.
        from cpd_tpu.interop import (assert_compatible,
                                     import_reference_resnet18_cifar,
                                     load_reference_checkpoint)
        sd = load_reference_checkpoint(args.init_from_torch)
        converted = import_reference_resnet18_cifar(sd)
        assert_compatible(converted, {"params": state.params,
                                      "batch_stats": state.batch_stats})
        state = state.replace(params=converted["params"],
                              batch_stats=converted["batch_stats"])
        if rank == 0:
            print(f"=> imported torch checkpoint {args.init_from_torch}")
    elif args.load_path:
        # Warm-start from an explicit checkpoint dir (mix.py --load-path /
        # train_util.load_state:274-318); --resume-opt additionally restores
        # the optimizer state and step counter, else params only.
        from cpd_tpu.train import restore_latest
        tmpl = zero.portable_template(state) if zero else state
        loaded = restore_latest(os.path.abspath(args.load_path), tmpl)
        if loaded is None:
            raise FileNotFoundError(
                f"--load-path {args.load_path}: no checkpoint found")
        if args.resume_opt:
            state = zero.import_state(loaded) if zero else loaded
            start_iter = int(loaded.step)
        else:
            state = state.replace(params=loaded.params,
                                  batch_stats=loaded.batch_stats)
        if rank == 0:
            print(f"=> loaded {args.load_path} "
                  f"(opt {'restored' if args.resume_opt else 'fresh'})")
    elif manager.latest_step() is not None:
        # ZeRO checkpoints are saved in the PORTABLE layout (pad-trimmed
        # momentum), so they restore at any device count
        restored = manager.restore(
            zero.portable_template(state) if zero else state)
        if restored is not None:
            state = zero.import_state(restored) if zero else restored
            start_iter = int(restored.step)
            if rank == 0:
                print(f"=> resumed from iter {start_iter}")
            if psup is not None:
                # resume the ladder where the checkpoint left it — the
                # acceptance contract: a restart mid-escalation runs at
                # the escalated format, not home
                meta = manager.metadata()
                if meta and meta.get("precision"):
                    psup.load_state_dict(meta["precision"])
                    if rank == 0:
                        print(f"=> resumed precision ladder at "
                              f"{psup.name}"
                              + (" (escalated)" if psup.escalated
                                 else ""))
    # orbax restores arrays committed to a single device; the train step's
    # shard_map needs the state laid out over the mesh (replicated, except
    # the ZeRO momentum which is dp-sharded)
    if shampoo is not None:
        state, extra = shampoo.mesh_layout(state, mesh)
        to_ckpt = shampoo.export_state
    elif zero is None:
        state = replicate(state, mesh)
        extra = {}
        to_ckpt = lambda st: st                               # noqa: E731
    else:
        state, extra = zero.mesh_layout(state, mesh)
        to_ckpt = zero.export_state

    from cpd_tpu.utils.config import block_key, overlap_key
    ov_key = overlap_key(args)
    bk_key = block_key(args)
    step_kw = dict(emulate_node=args.emulate_node, use_aps=args.use_APS,
                   use_kahan=args.use_kahan,
                   grad_rounding=args.grad_rounding,
                   grad_seed=args.grad_seed,
                   quant_stats=res["quant_stats"],
                   sat_fault_plan=res["sat_plan"],
                   overlap_reduce=args.overlap_reduce,
                   bucket_elems=args.bucket_elems, **extra)
    supervisor = res["supervisor"]
    resync_fn = None
    if supervisor is not None or psup is not None:
        # one or both ladders (docs/RESILIENCE.md "Degraded transports" /
        # "Precision ladder"): lazily compiled steps keyed by
        # `ladder_step_key` — transport level, eXmY format, or the
        # (level, format) pair when both supervisors run
        from cpd_tpu.resilience import (StepTable, ladder_step_key,
                                        level_reduce_kwargs)
        from cpd_tpu.resilience.precision import resolve_ladder_key
        if supervisor is not None:
            from cpd_tpu.parallel.integrity import make_consensus_fns
            _, resync_fn = make_consensus_fns(mesh, "dp")

        def build_step(key):
            level, fmt = resolve_ladder_key(
                key, transport_on=supervisor is not None,
                precision_on=psup is not None, level=args.mode,
                fmt=(args.grad_exp, args.grad_man),
                overlap_on=ov_key is not None,
                block_on=bk_key is not None)
            if supervisor is not None:
                rkw = level_reduce_kwargs(level, *fmt)
            else:
                rkw = dict(mode=level, grad_exp=fmt[0], grad_man=fmt[1])
            # block scaling only exists on the ring rung at a packable
            # format: a transport downgrade (faithful/fp32) or a
            # precision escalation to (8, 23) retraces WITHOUT the
            # sidecar wire — rung validity beats knob persistence
            blk = (args.block_scale and rkw.get("mode") == "ring"
                   and fmt[1] >= 2 and fmt != (8, 23))
            return make_train_step(
                model, tx, mesh, donate=False,
                verify_reduce=res["verify"],
                wire_fault_plan=(res["wire_plan"] if level == "ring"
                                 else None),
                block_scale=blk, block_size=args.block_size,
                **rkw, **step_kw)

        step_table = StepTable(build_step)
        train_step = step_table[ladder_step_key(supervisor, psup,
                                                overlap=ov_key,
                                                block=bk_key)]
    else:
        # no ladder (verify off, or a non-ladder mode like fast):
        # verification, when on, is detection-only agreement checking
        step_table = None
        train_step = make_train_step(
            model, tx, mesh, grad_exp=args.grad_exp,
            grad_man=args.grad_man, mode=args.mode,
            verify_reduce=res["verify"],
            wire_fault_plan=res["wire_plan"],
            block_scale=args.block_scale, block_size=args.block_size,
            **step_kw)
    eval_step = make_eval_step(model, mesh)

    # Global per-step batch = per-chip batch x chips x emulated nodes
    # (mix.py:123-132 scales max_iter by emulate_node instead; same
    # cluster).  Each host loads its 1/world contiguous slice; the sampler
    # hands out per-host index blocks (train_util.py:212-215) and
    # host_batch_to_global stitches them into the sharded global array.
    global_batch = args.batch_size * n_dev * args.emulate_node
    host_batch = global_batch // world
    pipeline = CIFAR10Pipeline(train_x, train_y, host_batch, augment=True,
                               cutout=0)
    eval_bs = max(n_dev, (min(1000, len(test_y)) // n_dev) * n_dev)
    eval_host = eval_bs // world
    eval_pipe = CIFAR10Pipeline(test_x, test_y, eval_bs, augment=False)

    def validate(step_no: int) -> dict:
        tot = {"loss": 0.0, "top1": 0.0, "top5": 0.0}
        n_batches = 0
        limit = (len(test_y) // eval_bs) * eval_bs
        for lo in range(0, limit, eval_bs):
            sel = np.arange(lo + rank * eval_host,
                            lo + (rank + 1) * eval_host)
            x, y = eval_pipe.batch(sel)
            m = eval_step(state, host_batch_to_global(x, mesh),
                          host_batch_to_global(y, mesh))
            for k in tot:
                tot[k] += float(m[k])
            n_batches += 1
        avg = {k: v / max(n_batches, 1) for k, v in tot.items()}
        if rank == 0:
            print(format_validation_line(avg["loss"], 100 * avg["top1"],
                                         100 * avg["top5"]), flush=True)
        return avg

    def export_torch(state) -> None:
        if not args.export_torch:
            return
        from cpd_tpu.interop import (export_reference_resnet18_cifar,
                                     save_torch_checkpoint)
        host = jax.device_get({"params": state.params,
                               "batch_stats": state.batch_stats})
        try:
            sd = export_reference_resnet18_cifar(host)
        except KeyError as e:
            raise SystemExit(
                f"--export-torch supports the res_cifar layout only "
                f"(--arch {args.arch} has no reference key map): {e}")
        if rank == 0:
            save_torch_checkpoint(sd, args.export_torch)
            print(f"=> exported torch checkpoint {args.export_torch}")

    if args.evaluate:                            # mix.py:-e
        res = validate(start_iter)
        export_torch(state)
        return res

    sampler = DistributedGivenIterationSampler(
        dataset_len, total_iter, host_batch, world_size=world, rank=rank,
        seed=0, last_iter=start_iter - 1)
    writer = ScalarWriter(os.path.join(ckpt_dir, "logs"), rank=rank,
                          tensorboard=args.tensorboard)
    progress = ProgressPrinter(total_iter, args.print_freq, rank=rank)
    best_prec1 = 0.0
    last = {"loss": float("nan"), "accuracy": 0.0}
    step_no = start_iter
    profiler = StepProfiler(args.profile_dir, start=start_iter + 2)
    t0 = now()
    def produced():
        # host-side batch prep (augmentation runs in the native threaded
        # executor) on a background thread, 2 steps ahead of the device
        s = step_no
        for batch_idx in sampler.batches():
            x, y = pipeline.batch(batch_idx, seed=s // iter_per_epoch)
            yield (host_batch_to_global(x, mesh),
                   host_batch_to_global(y, mesh))
            s += 1

    # SIGTERM (spot-VM preemption) → save at the next step boundary and
    # exit; the iteration-based sampler resumes at exactly this step via
    # last_iter (train_util.py:159-222 semantics), so nothing re-trains.
    from cpd_tpu.train import PreemptionGuard, loss_diverged, preempt_save
    from cpd_tpu.resilience.inject import InjectedPreemption
    guard = PreemptionGuard()
    preempted = False
    diverged = False
    prev_batch = None
    # --- elastic training setup (ISSUE 19): detection + drain only —
    # the prefetcher pipeline cannot rewind a batch, so this trainer's
    # recovery doctrine is a clean drain-save and a controlled exit
    # (the in-run shrink lives on the LM trainer and run_elastic)
    elastic_table, elastic_links, last_dt = None, {}, None
    if esup is not None:
        if res["plan"] is not None and res["plan"].elastic_faults():
            from cpd_tpu.resilience.elastic import heartbeat_table
            elastic_table = heartbeat_table(res["plan"],
                                            esup.home_world, total_iter)
            elastic_links = {f.step: (int(f.arg) if f.arg >= 0 else 0,
                                      int(f.arg2) if f.arg2 >= 0 else 1)
                             for f in res["plan"].elastic_faults()
                             if f.kind == "link_flaky"}
    from cpd_tpu.utils.prefetch import Prefetcher
    batches = Prefetcher(produced(), depth=2)
    batch_iter = iter(batches)
    try:
        for gx, gy in batch_iter:
            if watchdog is not None and watchdog.tripped:
                # trip interrupt absorbed by the SIGINT-trapping guard;
                # honor it at the boundary (docs/RESILIENCE.md)
                watchdog.disarm()     # acknowledge: cancels hard-exit
                meter.bump("watchdog_trips")
                preempt_save(manager, step_no, to_ckpt(state), rank,
                             metadata=run_meta(), what="watchdog stop at")
                preempted = True
                break
            if guard.should_stop():      # collective when multi-host
                if oflight is not None:
                    oflight.dump("preempt")
                preempt_save(manager, step_no, to_ckpt(state), rank,
                             metadata=run_meta())
                preempted = True
                break
            profiler.step(step_no)
            # --- elastic supervision (ISSUE 19): one heartbeat row per
            # update (plan-derived in drills, the measured step time
            # standing in for every dp host otherwise); any drain
            # decision -> sealed checkpoint + controlled exit
            if esup is not None:
                if elastic_table is not None:
                    row = (elastic_table[step_no]
                           if step_no < len(elastic_table)
                           else [1.0] * esup.home_world)
                elif last_dt is not None:
                    row = [last_dt] * esup.home_world
                else:
                    row = None
                decision = (esup.on_heartbeats(step_no, row)
                            if row is not None else None)
                meter.counts["elastic_hot_steps"] = \
                    esup.counters["hot_steps"]
                meter.counts["elastic_heartbeat_misses"] = \
                    esup.counters["heartbeat_misses"]
                if decision is None and step_no in elastic_links:
                    host, attempts = elastic_links.pop(step_no)
                    for _ in range(attempts):
                        act = esup.on_link_failure(step_no, host)
                        if act == "shrink":
                            decision = ("shrink", (host,))
                            meter.bump("elastic_link_escalations")
                            break
                        meter.bump("elastic_link_retries")
                    else:
                        esup.on_step_ok(step_no)
                        if rank == 0 and attempts:
                            print(f"=> elastic: flaky link into host "
                                  f"{host} at iter {step_no + 1} "
                                  f"absorbed by {attempts} in-step "
                                  f"retr"
                                  f"{'y' if attempts == 1 else 'ies'}",
                                  file=sys.stderr)
                if decision is not None and decision[0] == "shrink":
                    for _ in decision[1]:
                        meter.bump("elastic_drains")
                    meter.bump("elastic_shrinks")
                    if rank == 0:
                        print(f"=> elastic: host(s) "
                              f"{list(decision[1])} unhealthy at iter "
                              f"{step_no + 1} — draining to a sealed "
                              f"checkpoint and stopping (in-run "
                              f"shrink: LM trainer / run_elastic)",
                              file=sys.stderr)
                    if oflight is not None:
                        oflight.dump("elastic")
                    preempt_save(manager, step_no, to_ckpt(state), rank,
                                 metadata=run_meta(),
                                 what="elastic drain at")
                    preempted = True
                    break
            try:
                if injector is not None:
                    injector.maybe_preempt(step_no)
                    action = injector.batch_action(step_no)
                    if action == "drop":
                        # this batch never arrives; train on the next
                        # one (same semantics as run_guarded / lm)
                        meter.bump("batches_dropped")
                        try:
                            gx, gy = next(batch_iter)
                        except StopIteration:
                            break
                    if action == "dup" and prev_batch is not None:
                        meter.bump("batches_duplicated")
                        gx, gy = prev_batch
                    gx, gy = injector.corrupt_batch(step_no, (gx, gy))
                prev_batch = (gx, gy)
                if watchdog is not None:
                    watchdog.arm(step_no, loss=last.get("loss"))
                if injector is not None:
                    injector.maybe_stall(step_no)
                prev_state = state    # verified-reduce discard target
                t_step = now()
                with otr.span("step", step=step_no + 1):
                    state, metrics = train_step(state, gx, gy)
                    last = {k: float(v)
                            for k, v in metrics.items()}  # sync
                last_dt = now() - t_step
                if esup is not None:
                    esup.on_step_ok(step_no)
                if watchdog is not None:
                    watchdog.disarm()
            except KeyboardInterrupt:
                if watchdog is not None and watchdog.tripped:
                    watchdog.disarm()     # acknowledge: cancels hard-exit
                    meter.bump("watchdog_trips")
                    preempt_save(manager, step_no, to_ckpt(state), rank,
                                 metadata=run_meta(),
                                 what="watchdog stop at")
                    preempted = True
                    break
                raise
            except InjectedPreemption:
                meter.bump("preemptions")
                if oflight is not None:
                    oflight.dump("preempt")
                preempt_save(manager, step_no, to_ckpt(state), rank,
                             metadata=run_meta(), what="injected preemption at")
                preempted = True
                break
            # --- verified-reduce supervision (ISSUE 4) ----------------
            # reduce_ok == 0: this step's reduce failed its checksums /
            # agreement — discard the corrupted update (state rewinds to
            # the pre-step pytree; steps are built donate=False) and let
            # the supervisor walk the ring -> faithful -> fp32 ladder.
            # Unlike run_guarded, the prefetcher pipeline cannot rewind
            # a batch, so a "retry" trains the NEXT batch at the same
            # rung — the update index (state.step) did not advance, so a
            # deterministic injected fault still re-fires and drives the
            # downgrade exactly as in the harness loop.
            if supervisor is None and res["verify"] and float(
                    last.get("reduce_ok", 1.0)) == 0.0:
                # non-ladder mode (fast): detection only — count + warn
                meter.bump("wire_faults_detected")
                if rank == 0:
                    print(f"=> reduce verify FAILED at iter "
                          f"{step_no + 1} (mode {args.mode} has no "
                          f"transport ladder: detection only)",
                          file=sys.stderr)
            if supervisor is not None and float(
                    last.get("reduce_ok", 1.0)) == 0.0:
                meter.bump("wire_faults_detected")
                state = prev_state
                action = supervisor.on_failure(step_no)
                if action == "give_up":
                    if rank == 0:
                        print(f"=> verified reduce failed at the fp32 "
                              f"transport floor (iter {step_no + 1}) — "
                              f"not a wire problem; stopping",
                              file=sys.stderr)
                    diverged = True
                    break
                if action == "downgrade":
                    meter.bump("transport_downgrades")
                    state = resync_fn(state)
                    meter.bump("resyncs")
                    train_step = step_table[ladder_step_key(supervisor,
                                                            psup,
                                                            overlap=ov_key,
                                                            block=bk_key)]
                    if rank == 0:
                        print(f"=> wire fault detected at iter "
                              f"{step_no + 1} (hop_bad "
                              f"{int(last.get('reduce_hop_bad', 0))}, "
                              f"gather_bad "
                              f"{int(last.get('reduce_gather_bad', 0))})"
                              f" — transport downgraded to "
                              f"{supervisor.mode}, replicas re-synced "
                              f"from rank 0", file=sys.stderr)
                else:
                    meter.bump("reduce_retries")
                    if rank == 0:
                        print(f"=> wire fault detected at iter "
                              f"{step_no + 1} — update discarded, "
                              f"retrying on the {supervisor.mode} "
                              f"transport", file=sys.stderr)
                continue
            if supervisor is not None and \
                    supervisor.on_success(step_no) == "upgrade":
                meter.bump("transport_upgrades")
                train_step = step_table[ladder_step_key(supervisor,
                                                            psup,
                                                            overlap=ov_key,
                                                            block=bk_key)]
                if rank == 0:
                    print(f"=> transport probation passed at iter "
                          f"{step_no + 1}: back to {supervisor.mode}",
                          file=sys.stderr)
            step_no += 1
            meter.observe_metrics(last)
            if oreg is not None:
                oreg.absorb_step_metrics(last, step_no)
            if oflight is not None:
                oflight.record("step", step=step_no,
                               loss=last["loss"])
            # --- precision-ladder supervision (ISSUE 5) ---------------
            # host decision on the psum-agreed prec_wire_* telemetry;
            # escalation re-formats the NEXT step (the update that
            # tripped the detector was already guarded in-step)
            if psup is not None:
                from cpd_tpu.resilience import ladder_step_key
                pact = psup.on_metrics(step_no - 1, last)
                if psup.last_hot:
                    meter.bump("sat_hot_steps")
                if pact is not None:
                    meter.bump("precision_escalations"
                               if pact == "escalate"
                               else "precision_deescalations")
                    train_step = step_table[ladder_step_key(supervisor,
                                                            psup,
                                                            overlap=ov_key,
                                                            block=bk_key)]
                    if rank == 0:
                        how = ("escalated" if pact == "escalate"
                               else "probation passed: back")
                        print(f"=> precision ladder {how} to "
                              f"{psup.name} at iter {step_no} "
                              f"(sat {int(last.get('prec_wire_sat', 0))}"
                              f"/{int(last.get('prec_wire_total', 0))}"
                              f" nan "
                              f"{int(last.get('prec_wire_nan', 0))})",
                              file=sys.stderr)
            if injector is not None:
                # step_no - 1 == the 0-based update index this loss came
                # from — the same clock the pre-step hooks above use
                last["loss"] = injector.fault_loss(step_no - 1,
                                                   last["loss"])
            # a guard-skipped step's loss metric may be poisoned by the
            # bad batch/grads; the anomaly was already handled in-step
            guard_ok = float(last.get("guard_ok", 1.0)) != 0.0
            if (sentinel is not None and guard_ok
                    and sentinel.update(last["loss"])):
                # divergence STOP (rollback recovery: LM trainer / the
                # resilience.run_guarded loop)
                if rank == 0:
                    print(f"=> divergence sentinel tripped at iter "
                          f"{step_no} (loss {last['loss']:.4g})",
                          file=sys.stderr)
                diverged = True
                break
            if (sentinel is None and guard_ok
                    and loss_diverged(last["loss"],
                                      f"iter {step_no}", rank)):
                diverged = True
                break
            progress.maybe_print(step_no, _suffix=meter.suffix(),
                                 Loss=last["loss"],
                                 Prec=100 * last["accuracy"],
                                 LR=float(schedule(step_no)))
            writer.add_scalar("train/loss", last["loss"], step_no)
            writer.add_scalar("train/acc", last["accuracy"], step_no)
            if step_no % args.val_freq == 0 or step_no == total_iter:
                with otr.span("validate", step=step_no):
                    val = validate(step_no)
                writer.add_scalar("val/top1", val["top1"], step_no)
                prec1 = 100 * val["top1"]
                best_prec1 = max(best_prec1, prec1)
                with otr.span("checkpoint", step=step_no):
                    manager.save(step_no, to_ckpt(state),
                                 best_metric=prec1,
                                 metadata=run_meta())
                if injector is not None:
                    # the fault must land on the FINAL bytes — without
                    # integrity the save is still async at this point
                    manager.wait()
                if injector is not None and injector.corrupt_checkpoint(
                        step_no, manager.directory) and rank == 0:
                    print(f"=> injected checkpoint corruption at step "
                          f"{step_no}", file=sys.stderr)
    finally:
        guard.uninstall()
        if watchdog is not None:
            watchdog.close()
        batches.close()   # stop the producer even on an exception path
        # close() stops an in-flight jax.profiler trace even when the
        # loop died inside the window (watchdog interrupt, injected
        # fault) — leaking a running trace poisons every later
        # start_trace in this process (ISSUE 11 satellite)
        profiler.close()
    from cpd_tpu.resilience import report_unfired
    if esup is not None and res["plan"] is not None:
        # the elastic harness owns its kinds' accounting: anything
        # scheduled past the last processed update, or aimed outside
        # the fleet, never manifested (mirrors run_elastic / lm)
        leftover = sorted(
            f for f in res["plan"].elastic_faults()
            if f.step >= step_no or int(max(f.arg, 0)) >= esup.home_world)
        if leftover:
            meter.bump("faults_unfired", len(leftover))
            if rank == 0:
                print(f"=> elastic plan: {len(leftover)} spec(s) never "
                      f"fired: {leftover}", file=sys.stderr)
    # wire faults only fire when a ring-mode step baked the table in —
    # a wire_* spec on a gather/psum run must read as UNFIRED, not pass
    report_unfired(injector, n_steps=total_iter, meter=meter, rank=rank,
                   wire_armed=(supervisor.home == "ring"
                               if supervisor is not None
                               else args.mode == "ring"),
                   host_armed=esup is not None)
    manager.wait()
    writer.close()
    if rank == 0 and not (preempted or diverged):  # interrupted != "done"
        print(f"done: {step_no - start_iter} iters in {now()-t0:.1f}s "
              f"best Prec@1 {best_prec1:.2f}")
    manager.close()
    if not (preempted or diverged):
        export_torch(state)
    from cpd_tpu.utils.config import finish_obs
    obs_out = finish_obs(obs, meter=meter, last=last, step_no=step_no,
                         supervisor=supervisor, precision=psup,
                         elastic=esup, rank=rank, preempted=preempted,
                         diverged=diverged)
    return {"step": step_no, "best_prec1": best_prec1,
            "diverged": diverged,
            **({"resilience": meter.as_dict()} if res["active"] else {}),
            **({"obs": obs_out} if obs_out is not None else {}),
            **last}


if __name__ == "__main__":
    res = main()
    sys.exit(3 if res.get("diverged") else 0)
