"""Plot validation-accuracy curves from training logs.

Parity with `example/ResNet18/draw_curve.py:11-29`: greps `tee`'d stdout
logs for the ``* All Loss … Prec@1 …`` summary lines (token index -3 is
Prec@1 — the contract of cpd_tpu.utils.format_validation_line) and plots
one curve per log.  Also understands the ScalarWriter JSONL stream — any input path ending
in ``.jsonl`` is parsed as scalars (``--tag``, default val/top1) — the
richer source the reference lacked.

Usage:
    python examples/draw_curve.py aps.log no_aps.log -o curves.png
    python examples/draw_curve.py ckpt/logs/scalars.jsonl -o curves.png
"""

from __future__ import annotations

import argparse
import json
import os
from typing import List


def parse_stdout_log(path: str) -> List[float]:
    """Prec@1 values from '* All Loss … Prec@1 …' lines
    (draw_curve.py:14-18: split() and take [-3])."""
    vals = []
    with open(path) as f:
        for line in f:
            if "* All Loss" in line:
                vals.append(float(line.split()[-3]))
    return vals


def parse_jsonl(path: str, tag: str = "val/top1") -> List[float]:
    vals = []
    with open(path) as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("tag") == tag:
                vals.append(100.0 * rec["value"])
    return vals


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("logs", nargs="+", help="stdout logs (or .jsonl scalars)")
    p.add_argument("-o", "--output", default="curves.png")
    p.add_argument("--tag", default="val/top1", help="tag for JSONL inputs")
    args = p.parse_args(argv)

    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(1, 1)
    for path in args.logs:
        vals = (parse_jsonl(path, args.tag) if path.endswith(".jsonl")
                else parse_stdout_log(path))
        label = os.path.splitext(os.path.basename(path))[0]
        ax.plot(range(len(vals)), vals, label=label)
    ax.set_xlabel("validation round", fontsize=16)
    ax.set_ylabel("testing accuracy", fontsize=16)
    ax.legend(loc="lower right", fontsize=12)
    fig.savefig(args.output, dpi=120, bbox_inches="tight")
    print(f"wrote {args.output}")


if __name__ == "__main__":
    main()
