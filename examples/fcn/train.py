"""FCN-R50-d8 segmentation trainer — the reference's fourth workload,
in-repo instead of the mmcv-fork hack (README.md:132-150: forks of mmcv
branch APS_support + mmsegmentation, precision toggled by editing
optimizer.py line 27).  Here precision is just flags on the shared trainer,
proving the framework integration point the reference's fork demonstrates:
the quantized all-reduce wraps any model's gradients.

Iteration-based like mmseg (40K iters at crop 769; README.md:133).
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

# Make the repo importable when run as a script (the reference required a
# manual PYTHONPATH export, README.md:39; here the entry bootstraps itself).
_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from cpd_tpu.obs.timing import now  # noqa: E402  (the one clock; jax-free)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="cpd_tpu FCN/Cityscapes")
    p.add_argument("--crop-size", default=769, type=int)
    p.add_argument("--num-classes", default=19, type=int)
    p.add_argument("--batch-size", default=2, type=int,
                   help="per chip (mmseg default: 2 imgs/GPU)")
    p.add_argument("--max-iter", default=40000, type=int)
    p.add_argument("--base-lr", default=0.01, type=float)
    p.add_argument("--momentum", default=0.9, type=float)
    p.add_argument("--wd", default=0.0005, type=float)
    p.add_argument("--print-freq", default=50, type=int)
    p.add_argument("--save-path", default="fcn_ckpt")
    p.add_argument("--val-freq", default=4000, type=int)
    p.add_argument("--ckpt-freq", default=4000, type=int,
                   help="checkpoint interval (mmcv CheckpointHook parity)")
    # precision flags — the reference's edit-a-source-line, as real flags
    p.add_argument("--grad_exp", default=8, type=int)
    p.add_argument("--grad_man", default=23, type=int)
    p.add_argument("--use_APS", action="store_true")
    p.add_argument("--use_kahan", action="store_true")
    p.add_argument("--emulate_node", default=1, type=int)
    p.add_argument("--mode", default="faithful",
                   choices=["faithful", "fast", "ring"])
    p.add_argument("--dist", action="store_true")
    p.add_argument("--data-root", default=None,
                   help="Cityscapes root (leftImg8bit/gtFine); synthetic "
                        "fallback when absent")
    p.add_argument("--synthetic-size", default=256, type=int)
    p.add_argument("--tiny-backbone", action="store_true",
                   help="1-block-per-stage backbone (smoke tests)")
    p.add_argument("--tensorboard", action="store_true",
                   help="also write TensorBoard event files next to the "
                        "JSONL scalars (reference mix.py:16,168-171)")
    p.add_argument("--profile-dir", default=None,
                   help="write a jax.profiler trace of a few steps here")
    p.add_argument("--aux-head", action="store_true",
                   help="auxiliary FCN head on stage-3 features at loss "
                        "weight 0.4 (mmseg fcn_r50-d8 default)")
    p.add_argument("--aux-weight", default=0.4, type=float)
    return p


def main(argv=None) -> dict:
    args = build_parser().parse_args(argv)

    import jax
    import jax.numpy as jnp

    from cpd_tpu.data.segmentation import load_segmentation
    from cpd_tpu.models import fcn_r50_d8
    from cpd_tpu.parallel.dist import dist_init, host_batch_to_global
    from cpd_tpu.parallel.mesh import data_parallel_mesh
    from cpd_tpu.train import (create_train_state, make_optimizer,
                               make_train_step)
    from cpd_tpu.train.step import seg_cross_entropy_loss, seg_loss_with_aux
    from cpd_tpu.train.schedules import piecewise_linear
    from cpd_tpu.utils import ProgressPrinter, ScalarWriter, StepProfiler

    rank, world = dist_init() if args.dist else (0, 1)
    mesh = data_parallel_mesh()
    n_dev = mesh.devices.size

    # real Cityscapes (leftImg8bit/gtFine tree, 769x769 crops — the mmseg
    # fcn_r50-d8 pipeline the reference trains on, README.md:132-150) when
    # --data-root points at one; synthetic stand-in otherwise
    ds = load_segmentation(args.data_root, crop_size=args.crop_size,
                           num_classes=args.num_classes,
                           synthetic_size=args.synthetic_size)
    # validation split: real Cityscapes val/ when present; otherwise (no
    # val/ tree, or fully synthetic data) evaluate on the training
    # distribution at deterministic crops — never mix real train with
    # synthetic val
    try:
        val_ds = load_segmentation(args.data_root, split="val",
                                   crop_size=args.crop_size,
                                   num_classes=args.num_classes,
                                   synthetic_size=args.synthetic_size,
                                   flip=False)
        if type(val_ds) is not type(ds):
            val_ds = ds
    except FileNotFoundError:
        val_ds = ds
    global_batch = args.batch_size * n_dev * args.emulate_node

    # mmseg's poly schedule ~ piecewise-linear decay to lr*0.01 at max_iter
    schedule = piecewise_linear([0, args.max_iter],
                                [args.base_lr, args.base_lr * 0.01])
    tiny = ({"stage_sizes": (1, 1, 1, 1), "head_channels": 64}
            if args.tiny_backbone else {})
    model = fcn_r50_d8(num_classes=args.num_classes, dtype=jnp.bfloat16,
                       aux_head=args.aux_head, **tiny)
    tx = make_optimizer("sgd", schedule, momentum=args.momentum,
                        weight_decay=args.wd)
    state = create_train_state(
        model, tx, jnp.zeros((1, args.crop_size, args.crop_size, 3)),
        jax.random.PRNGKey(0))

    # interval checkpoints + auto-resume — the mmcv runner's
    # CheckpointHook/resume behavior the reference relies on
    # (README.md:132-150); restored arrays are re-replicated over the mesh
    from cpd_tpu.parallel.dist import replicate
    from cpd_tpu.train import CheckpointManager
    manager = CheckpointManager(os.path.abspath(
        os.path.join(args.save_path, "ckpt")), track_best=False)
    start_iter = 0
    restored = manager.restore(state)
    if restored is not None:
        state = restored
        start_iter = int(restored.step)
        if rank == 0:
            print(f"=> resumed from iter {start_iter}")
    state = replicate(state, mesh)

    step = make_train_step(
        model, tx, mesh, emulate_node=args.emulate_node,
        use_aps=args.use_APS, grad_exp=args.grad_exp,
        grad_man=args.grad_man, use_kahan=args.use_kahan, mode=args.mode,
        loss_fn=(seg_loss_with_aux(255, args.aux_weight) if args.aux_head
                 else seg_cross_entropy_loss(ignore_label=255)),
        ignore_label=255, rng_keys=("dropout",))

    writer = ScalarWriter(os.path.join(args.save_path, "logs"), rank=rank,
                          tensorboard=args.tensorboard)
    progress = ProgressPrinter(args.max_iter, args.print_freq, rank=rank)
    # per-host RNG stream: hosts draw disjoint random crops
    rng = np.random.RandomState(rank)
    host_batch = global_batch // world

    # periodic evaluation — pixel accuracy + mIoU over the val split, the
    # mmseg EvalHook the reference's FCN workload relies on
    from cpd_tpu.train import make_seg_eval_step
    seg_eval = make_seg_eval_step(model, mesh,
                                  num_classes=args.num_classes)

    def validate(it: int) -> dict:
        vrng = np.random.RandomState(1234 + rank)  # fixed eval crops
        n_batches = max(1, min(8, len(val_ds) // max(global_batch, 1)))
        tot = None
        for _ in range(n_batches):
            idx = vrng.randint(0, len(val_ds), size=host_batch)
            x, y = val_ds.batch(idx, seed=-1)
            m = seg_eval(state, host_batch_to_global(x, mesh),
                         host_batch_to_global(y, mesh))
            m = {k: np.asarray(v) for k, v in m.items()}
            tot = m if tot is None else {k: tot[k] + m[k] for k in tot}
        union = tot["union"]
        present = union > 0
        miou = float(np.mean(tot["inter"][present] / union[present])) \
            if present.any() else 0.0
        out = {"loss": float(tot["loss_sum"] / max(tot["n_pix"], 1)),
               "pix_acc": float(tot["correct"] / max(tot["n_pix"], 1)),
               "miou": miou}
        if rank == 0:
            print(f"Val [{it}]: loss {out['loss']:.4f} "
                  f"pixAcc {100 * out['pix_acc']:.2f} "
                  f"mIoU {100 * out['miou']:.2f}", flush=True)
        writer.add_scalar("val/loss", out["loss"], it)
        writer.add_scalar("val/pix_acc", out["pix_acc"], it)
        writer.add_scalar("val/miou", out["miou"], it)
        return out
    last = {}
    profiler = StepProfiler(args.profile_dir, start=3)
    # SIGTERM → save at the next step boundary and exit cleanly; resume
    # continues at the saved iteration (same scheme as the other trainers)
    from cpd_tpu.train import PreemptionGuard, loss_diverged, preempt_save
    guard = PreemptionGuard()
    preempted = diverged = False
    step_no = start_iter
    t0 = now()
    def produced():
        # random-crop batch prep two steps ahead of the device
        # (utils/prefetch.py); the rng draws stay on this single
        # producer thread, so the index sequence is unchanged
        for i in range(start_iter + 1, args.max_iter + 1):
            idx = rng.randint(0, len(ds), size=host_batch)
            bx, by = ds.batch(idx, seed=i)
            yield (host_batch_to_global(bx, mesh),
                   host_batch_to_global(by, mesh))

    from cpd_tpu.utils.prefetch import Prefetcher
    batches = Prefetcher(produced(), depth=2)
    try:
        for it, (gx, gy) in enumerate(batches, start=start_iter + 1):
            if guard.should_stop():      # collective when multi-host
                preempt_save(manager, step_no, state, rank)
                preempted = True
                batches.close()
                break
            profiler.step(it)
            state, m = step(state, gx, gy)
            step_no = it
            last = {k: float(v) for k, v in m.items()}
            if loss_diverged(last["loss"], f"iter {it}", rank):
                diverged = True
                batches.close()
                break
            progress.maybe_print(it, Loss=last["loss"],
                                 PixAcc=100 * last["accuracy"])
            writer.add_scalar("train/loss", last["loss"], it)
            if it % args.val_freq == 0 or it == args.max_iter:
                last_val = validate(it)
                last.update({f"val_{k}": v for k, v in last_val.items()})
            if it % args.ckpt_freq == 0 or it == args.max_iter:
                manager.save(it, state)
    finally:
        guard.uninstall()
        batches.close()   # stop the producer even on an exception path
        # stops an in-flight jax.profiler trace even when the loop died
        # inside the window (ISSUE 11 satellite — a leaked running
        # trace poisons every later start_trace in the process)
        profiler.close()
    jax.block_until_ready(state.params)
    manager.wait()
    manager.close()
    if rank == 0 and not (preempted or diverged):
        print(f"done: {args.max_iter} iters in {now()-t0:.1f}s "
              f"final loss {last.get('loss', float('nan')):.4f}")
    writer.close()
    return {"step": step_no, "diverged": diverged, **last}


if __name__ == "__main__":
    res = main()
    sys.exit(3 if res.get("diverged") else 0)
