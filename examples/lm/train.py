"""Transformer-LM trainer over a dp x sp x tp mesh — the long-context /
multi-axis entry point.

No reference counterpart (the reference is CNN-only, SURVEY.md §5); this
CLI demonstrates the framework's full parallelism surface in one command:
ring-attention sequence parallelism, Megatron tensor parallelism, and the
reference's quantized APS gradient all-reduce on the data axis
(--use_APS/--grad_exp/--grad_man/--use_kahan/--emulate_node, same flags as
every other trainer).

    python examples/lm/train.py --dp 2 --sp 2 --tp 2 --seq-len 2048 \
        --use_APS --grad_exp 5 --grad_man 2
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from cpd_tpu.obs.timing import now  # noqa: E402  (the one clock; jax-free)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="cpd_tpu transformer LM")
    p.add_argument("--dp", default=0, type=int,
                   help="data-parallel size (0 = all remaining devices)")
    p.add_argument("--sp", default=1, type=int, help="sequence-parallel")
    p.add_argument("--sp-mode", default="ring",
                   choices=["ring", "ulysses"],
                   help="sequence-parallel attention: ring (ppermute K/V) "
                        "or ulysses (all_to_all heads<->sequence; needs "
                        "local heads divisible by --sp)")
    p.add_argument("--tp", default=1, type=int, help="tensor-parallel")
    p.add_argument("--pp", default=1, type=int,
                   help="pipeline-parallel (GPipe; composes with --tp, "
                   "excludes sp/moe)")
    p.add_argument("--n-microbatches", default=4, type=int,
                   help="pipeline microbatches per step (with --pp)")
    p.add_argument("--vocab-pp", action="store_true",
                   help="shard the tied embed/head table over pp "
                   "(vocab-parallel lookup/logits/CE; with --pp)")
    p.add_argument("--moe", action="store_true",
                   help="Switch-style MoE feed-forward (excludes sp/tp/pp)")
    p.add_argument("--ep", default=1, type=int,
                   help="expert-parallel size (with --moe)")
    p.add_argument("--n-experts", default=4, type=int)
    p.add_argument("--vocab-size", default=256, type=int)
    p.add_argument("--d-model", default=256, type=int)
    p.add_argument("--n-layers", default=4, type=int)
    p.add_argument("--n-heads", default=8, type=int)
    p.add_argument("--n-kv-heads", default=None, type=int,
                   help="GQA: fewer K/V heads than query heads (must "
                        "divide --n-heads; default = MHA)")
    p.add_argument("--seq-len", default=256, type=int)
    p.add_argument("--batch-size", default=8, type=int,
                   help="sequences per dp rank per micro-step")
    p.add_argument("--max-iter", default=200, type=int)
    p.add_argument("--base-lr", default=0.01, type=float)
    p.add_argument("--lr-schedule", default="constant",
                   choices=["constant", "cosine"],
                   help="after warmup: constant (default) or cosine decay "
                        "to 0 at --max-iter")
    p.add_argument("--optimizer", default="sgd",
                   choices=["sgd", "nesterov", "adamw"],
                   help="elementwise optimizers only (shard-local update "
                        "under tp; LARS is guarded off in train/lm.py)")
    p.add_argument("--remat", action="store_true",
                   help="jax.checkpoint each transformer block: recompute "
                        "activations in backward instead of storing them "
                        "(the HBM<->FLOPs trade for deep/long-context "
                        "runs)")
    p.add_argument("--scan-layers", action="store_true",
                   help="nn.scan the block stack: compile the layer body "
                        "once regardless of depth (params gain a leading "
                        "layer axis; checkpoint layout differs from the "
                        "unrolled form)")
    p.add_argument("--warmup-iters", default=20, type=int)
    p.add_argument("--print-freq", default=10, type=int)
    p.add_argument("--save-path", default="lm_ckpt")
    p.add_argument("--val-freq", default=100, type=int)
    p.add_argument("--ckpt-freq", default=500, type=int)
    # the reference-parity precision flags
    p.add_argument("--grad_exp", default=8, type=int)
    p.add_argument("--grad_man", default=23, type=int)
    p.add_argument("--grad-rounding", default="nearest",
                   choices=["nearest", "stochastic"],
                   help="rounding of the gradient-pipeline casts; "
                        "stochastic = unbiased SR (dp path only)")
    p.add_argument("--grad-seed", default=0, type=int)
    p.add_argument("--use_APS", action="store_true")
    p.add_argument("--use_kahan", action="store_true")
    p.add_argument("--emulate_node", default=1, type=int)
    p.add_argument("--mode", default="faithful",
                   choices=["faithful", "fast", "ring"])
    p.add_argument("--dist", action="store_true")
    p.add_argument("--tensorboard", action="store_true",
                   help="also write TensorBoard event files next to the "
                        "JSONL scalars (reference mix.py:16,168-171)")
    p.add_argument("--ffn-exp", default=8, type=int,
                   help="MLP GEMM accumulator exponent bits; when "
                        "(--ffn-exp, --ffn-man) != (8, 23) the blocks' "
                        "wi/wo_mlp run the reference quantized GEMM "
                        "recipe")
    p.add_argument("--ffn-man", default=23, type=int)
    p.add_argument("--ffn-mode", default="faithful",
                   choices=["faithful", "fast"],
                   help="faithful = ordered Kahan accumulation (bit-exact "
                        "reference emulation, the API default); fast = "
                        "cast-and-dot")
    p.add_argument("--flash-bwd", default="chunked",
                   choices=["chunked", "pallas"],
                   help="GQA flash-attention backward: chunked XLA "
                        "recompute (default) or the Pallas flash-"
                        "backward kernels (with --attn-impl flash)")
    p.add_argument("--attn-impl", default="xla",
                   choices=["xla", "flash", "chunked"],
                   help="flash = Pallas flash-attention kernels, O(T) "
                        "memory, non-decode (MHA via the stock TPU "
                        "kernel, GQA via the in-repo GQA-native kernel); "
                        "chunked = pure-XLA online-softmax K/V-block "
                        "scan (flash's memory shape on any backend, "
                        "GQA-native)")
    p.add_argument("--bf16", action="store_true",
                   help="bf16 compute dtype (fp32 master params; the "
                        "MXU-native precision — --half analog of the "
                        "DavidNet trainer)")
    p.add_argument("--label-smoothing", default=0.0, type=float,
                   help="mix one-hot targets with uniform mass in the "
                        "training loss (default dp/sp/tp path)")
    p.add_argument("--dropout", default=0.0, type=float,
                   help="residual-branch dropout rate (train only; "
                        "default dp/sp/tp path)")
    p.add_argument("--sample", default=0, type=int,
                   help="after training, decode this many tokens from a "
                        "data prompt (KV-cache generate; default dp/sp/tp "
                        "path only — pp/moe modules have no decode mode)")
    p.add_argument("--sample-temperature", default=0.0, type=float,
                   help="0 = greedy argmax; >0 samples softmax(l/T)")
    p.add_argument("--sample-top-k", default=None, type=int,
                   help="restrict sampling to the k best tokens "
                        "(needs --sample-temperature > 0)")
    p.add_argument("--sample-top-p", default=None, type=float,
                   help="nucleus sampling mass in (0,1] "
                        "(needs --sample-temperature > 0)")
    p.add_argument("--sample-seed", default=0, type=int,
                   help="rng seed for temperature sampling")
    p.add_argument("--profile-dir", default=None,
                   help="write a jax.profiler trace of a few steps here")
    p.add_argument("--export-torch", default=None, metavar="PATH",
                   help="after training, write a torch state_dict .pth "
                        "of the LM (cpd_tpu.interop.torch_lm; default "
                        "dp/sp/tp path only — pp/moe layouts differ)")
    from cpd_tpu.utils.config import (add_obs_flags,
                                      add_resilience_flags,
                                      add_transport_flags)
    add_resilience_flags(p)       # --fault-plan / guard / watchdog / rollback
    add_transport_flags(p)        # --overlap-reduce / --bucket-elems
    add_obs_flags(p)              # --obs-dir / --obs-flight
    return p


def main(argv=None) -> dict:
    args = build_parser().parse_args(argv)

    import jax
    import jax.numpy as jnp

    from cpd_tpu.data.lm_data import SyntheticText
    from cpd_tpu.models import transformer_lm
    from cpd_tpu.parallel.dist import dist_init
    from cpd_tpu.parallel.mesh import make_mesh
    from cpd_tpu.train import (create_train_state, make_lm_train_step,
                               make_optimizer, warmup_step_decay)
    from cpd_tpu.train.lm import make_lm_eval_step
    from cpd_tpu.utils import ProgressPrinter, ScalarWriter, StepProfiler

    rank, world = dist_init() if args.dist else (0, 1)
    # sampling-flag validation BEFORE training: a bad combination must not
    # surface as a crash after the whole run completed
    if args.sample_temperature == 0 and (args.sample_top_k is not None
                                         or args.sample_top_p is not None):
        raise ValueError("--sample-top-k/--sample-top-p require "
                         "--sample-temperature > 0")
    if args.sample_top_k is not None and args.sample_top_k < 1:
        raise ValueError("--sample-top-k must be >= 1")
    if args.sample_top_p is not None and not 0.0 < args.sample_top_p <= 1.0:
        raise ValueError("--sample-top-p must be in (0, 1]")
    if args.moe and (args.sp > 1 or args.tp > 1):
        raise ValueError("--moe does not compose with sp/tp here")
    if args.pp > 1 and args.sp > 1:
        raise ValueError("--pp does not compose with sp here (ring/"
                         "ulysses need the sequence axis the pipeline "
                         "streams microbatches over)")
    if args.vocab_pp and args.pp <= 1:
        raise ValueError("--vocab-pp needs --pp > 1")
    if args.export_torch and (args.pp > 1 or args.moe):
        raise ValueError("--export-torch supports the default dp/sp/tp "
                         "path only (pp/moe param layouts differ)")
    if args.pp > 1 and args.moe:
        raise ValueError("--pp and --moe are mutually exclusive")
    if (args.pp > 1 or args.moe) and args.emulate_node != 1:
        raise ValueError("--emulate_node is only supported on the "
                         "default dp/sp/tp path")
    if (args.pp > 1 or args.moe) and args.sample > 0:
        raise ValueError("--sample needs the default dp/sp/tp path "
                         "(pp/moe modules have no decode mode)")
    if (args.pp > 1 or args.moe) and (args.remat or args.scan_layers
                                      or args.n_kv_heads is not None
                                      or args.label_smoothing
                                      or args.dropout):
        raise ValueError("--remat/--scan-layers/--n-kv-heads/"
                         "--label-smoothing/--dropout are wired to the "
                         "default dp/sp/tp path only")
    if args.n_kv_heads is not None:
        if args.n_kv_heads < 1:
            raise ValueError(f"n-kv-heads must be >= 1, got "
                             f"{args.n_kv_heads}")
        if args.n_heads % args.n_kv_heads:
            raise ValueError(f"n-heads {args.n_heads} not divisible by "
                             f"n-kv-heads {args.n_kv_heads}")
        if args.n_kv_heads % args.tp:
            raise ValueError(f"n-kv-heads {args.n_kv_heads} not divisible "
                             f"by tp={args.tp}")
    if args.scan_layers and args.sample > 0:
        raise ValueError("--sample (KV-cache decode) does not compose "
                         "with --scan-layers")
    mesh = make_mesh(dp=args.dp, sp=args.sp, tp=args.tp, pp=args.pp,
                     ep=args.ep if args.moe else 1)
    dp = mesh.shape["dp"]

    if args.seq_len % args.sp:
        raise ValueError(f"seq-len {args.seq_len} not divisible by sp={args.sp}")
    if args.n_heads % args.tp:
        raise ValueError(f"n-heads {args.n_heads} not divisible by tp={args.tp}")
    if args.d_model % args.n_heads:
        raise ValueError(f"d-model {args.d_model} not divisible by "
                         f"n-heads {args.n_heads}")
    if (args.d_model // args.n_heads) % 2:
        raise ValueError(f"head dim {args.d_model // args.n_heads} must be "
                         "even (RoPE splits it in half)")

    model_kw = dict(vocab_size=args.vocab_size, d_model=args.d_model,
                    n_layers=args.n_layers, n_heads=args.n_heads,
                    dtype=jnp.bfloat16 if args.bf16 else jnp.float32)
    if args.attn_impl != "xla":
        if args.pp > 1 or args.moe:
            raise ValueError("--attn-impl applies to the default "
                             "dp/sp/tp TransformerLM path only")
        # GQA (--n-kv-heads) + flash is supported EVERYWHERE since the
        # round-5 GQA-native Pallas kernel (ops/flash_gqa.py): plain,
        # ulysses (unexpanded through the all_to_all), decode excluded
        # by the decode path's own gating.  chunked is GQA-native too.
        model_kw.update(attn_impl=args.attn_impl,
                        flash_bwd=args.flash_bwd)
    if args.flash_bwd != "chunked" and not (
            args.attn_impl == "flash" and args.n_kv_heads is not None):
        raise ValueError(
            "--flash-bwd pallas selects the GQA flash-backward kernels, "
            "which only run with --attn-impl flash AND --n-kv-heads "
            "(the MHA flash path uses the stock kernel's own backward) "
            "— without them the flag would be a silent no-op")
    if (args.ffn_exp, args.ffn_man) != (8, 23):
        if args.pp > 1 or args.moe:
            raise ValueError("--ffn-exp/--ffn-man apply to the default "
                             "dp/sp/tp TransformerLM path only")
        model_kw.update(ffn_exp=args.ffn_exp, ffn_man=args.ffn_man,
                        ffn_mode=args.ffn_mode)
    if args.lr_schedule == "cosine":
        from cpd_tpu.train import warmup_cosine
        schedule = warmup_cosine(args.base_lr, args.warmup_iters,
                                 args.max_iter)
    else:
        schedule = warmup_step_decay(args.base_lr, args.warmup_iters,
                                     [args.max_iter * 2], warmup_from=0.0)
    tx = make_optimizer(args.optimizer, schedule, momentum=0.9)
    # resilience stack (docs/RESILIENCE.md): gradient faults + guard are
    # optax wrappers, so they ride inside the jitted step on every path
    # (dp/sp/tp, pp, moe); host faults/watchdog/sentinel wrap the loop.
    from cpd_tpu.resilience import ladder_step_key
    from cpd_tpu.utils.config import build_resilience
    res = build_resilience(args, n_steps=args.max_iter, rank=rank,
                           world=dp)
    esup = res["elastic"]
    if esup is not None:
        # the elastic ladder re-layouts the DATA axis at runtime; the
        # other axes' shardings (and the ladder step tables, which
        # compile against the full-world mesh) don't re-shape that way
        if args.pp > 1 or args.moe or args.sp > 1 or args.tp > 1:
            raise SystemExit("--elastic is wired to the plain dp path "
                             "only (shrinking a sp/tp/pp/moe mesh is "
                             "not a data-axis re-layout)")
        if res["verify"] or res["precision"] is not None:
            raise SystemExit("--elastic does not compose with "
                             "--verify-reduce/--precision-ladder here "
                             "(their step tables compile against the "
                             "full-world mesh; use tools/bench_elastic "
                             "or run_elastic for the composed drills)")
    if res["verify"] and (args.pp > 1 or args.moe):
        raise SystemExit("--verify-reduce is wired to the default "
                         "dp/sp/tp path only (the pp/moe steppers do "
                         "not thread a verification report)")
    if (res["quant_stats"] or res["sat_plan"] is not None) \
            and (args.pp > 1 or args.moe):
        raise SystemExit("--precision-ladder/--quant-telemetry and "
                         "sat_pressure faults are wired to the default "
                         "dp/sp/tp path only (the pp/moe steppers do "
                         "not thread the telemetry / pressure tables)")
    if (args.overlap_reduce or args.bucket_elems is not None) \
            and (args.pp > 1 or args.moe):
        raise SystemExit("--overlap-reduce/--bucket-elems are wired to "
                         "the default dp/sp/tp path only (the pp/moe "
                         "steppers have their own schedules)")
    # ISSUE 12: --overlap-reduce composes with --emulate_node > 1 now
    # (the unrolled micro chain feeds the last micro-batch's taps) —
    # the old fail-fast is gone
    if args.block_scale and args.mode != "ring":
        raise SystemExit("--block-scale needs --mode ring: the per-block "
                         "scale sidecar rides the ring's packed wire")
    if args.block_scale and (args.pp > 1 or args.moe):
        raise SystemExit("--block-scale is wired to the default dp/sp/tp "
                         "path only (the pp/moe steppers do not thread "
                         "the blocked wire)")
    if args.block_scale and args.grad_man < 2:
        raise SystemExit(f"--block-scale needs a packable gradient format "
                         f"(man_bits >= 2 for the codec's special codes), "
                         f"got e{args.grad_exp}m{args.grad_man}")
    if res["active"]:
        # the guard's verdict must be agreed over EVERY mesh axis the
        # update runs under — tp/pp/ep-sharded leaves legitimately hold
        # different gradients per shard, so a dp-only psum would let
        # model shards take different skip branches (guard.py docstring)
        tx = res["wrap_tx"](tx, axis_name=tuple(mesh.axis_names))
    injector, watchdog = res["injector"], res["watchdog"]
    sentinel, meter = res["sentinel"], res["meter"]
    supervisor, step_table, resync_fn = res["supervisor"], None, None
    psup = res["precision"]
    # observability spine (docs/OBSERVABILITY.md): tracer spans on the
    # step clock, the metrics registry, and the crash flight recorder —
    # all pure host-side observation, so step outputs are bitwise
    # identical with or without --obs-dir (the obs-smoke gate pins it)
    from cpd_tpu.obs import NULL_TRACER
    from cpd_tpu.utils.config import build_obs
    obs = build_obs(args, run="lm",
                    meta={"max_iter": args.max_iter, "mode": args.mode,
                          "grad_format": [args.grad_exp,
                                          args.grad_man]})
    otr = obs["tracer"] if obs["tracer"] is not None else NULL_TRACER
    oreg, oflight = obs["registry"], obs["flight"]
    if watchdog is not None and oflight is not None:
        # dump the ring at FIRE time, on the timer thread — even a
        # wedge that ends in the hard-exit path leaves it on disk
        watchdog.on_trip = lambda ctx: oflight.dump("watchdog")

    def run_meta():
        # ladder state rides every checkpoint's metadata sidecar so a
        # restart/rollback resumes AT the escalated format; the elastic
        # fleet view rides along so a process restart resumes with the
        # same alive set (ISSUE 19)
        meta = {}
        if psup is not None:
            meta["precision"] = psup.state_dict()
        if esup is not None:
            meta["elastic"] = esup.state_dict()
        return meta or None

    ds = SyntheticText(n=4096, seq_len=args.seq_len,
                       vocab_size=args.vocab_size)
    sample = jnp.zeros((1, args.seq_len), jnp.int32)
    from cpd_tpu.utils.config import block_key, overlap_key
    ov_key = overlap_key(args)
    bk_key = block_key(args)
    quant_kw = dict(use_aps=args.use_APS, grad_exp=args.grad_exp,
                    grad_man=args.grad_man, use_kahan=args.use_kahan,
                    mode=args.mode, grad_rounding=args.grad_rounding,
                    grad_seed=args.grad_seed)
    if not (args.pp > 1 or args.moe):
        # the overlapped transport (and the block-scaled ring wire)
        # ride the default dp/sp/tp step only
        quant_kw.update(overlap_reduce=args.overlap_reduce,
                        bucket_elems=args.bucket_elems,
                        block_scale=args.block_scale,
                        block_size=args.block_size)

    if args.pp > 1:
        # GPipe pipeline path (parallel/pipeline.py, train/pp.py)
        from cpd_tpu.models import pipelined_lm
        from cpd_tpu.train import make_pp_eval_step, make_pp_train_step
        from cpd_tpu.train.pp import pp_state_specs
        from cpd_tpu.train.state import TrainState
        pp_model = pipelined_lm(**model_kw, pp_axis="pp", pp_size=args.pp,
                                tp_axis="tp" if args.tp > 1 else None,
                                tp_size=args.tp, vocab_pp=args.vocab_pp)
        variables = pipelined_lm(**model_kw).init(jax.random.PRNGKey(0),
                                                  sample)
        state = TrainState(step=jnp.zeros([], jnp.int32),
                           params=variables["params"], batch_stats={},
                           opt_state=tx.init(variables["params"]))
        step = make_pp_train_step(pp_model, tx, mesh,
                                  n_microbatches=args.n_microbatches,
                                  **quant_kw)
        eval_step = make_pp_eval_step(pp_model, mesh,
                                      n_microbatches=args.n_microbatches)
        specs_fn = (lambda st: pp_state_specs(st, vocab_pp=True)
                    ) if args.vocab_pp else pp_state_specs
        global_batch = args.batch_size * dp
    elif args.moe:
        # expert-parallel path (models/moe.py, train/moe.py)
        from cpd_tpu.models import moe_lm
        from cpd_tpu.train import make_moe_eval_step, make_moe_train_step
        from cpd_tpu.train.moe import moe_state_specs
        from cpd_tpu.train.state import TrainState
        ep = mesh.shape["ep"]
        moe_kw = dict(**model_kw, n_experts=args.n_experts)
        moe_model = moe_lm(**moe_kw, ep_axis="ep" if ep > 1 else None,
                           ep_size=ep)
        variables = moe_lm(**moe_kw).init(jax.random.PRNGKey(0), sample)
        state = TrainState(step=jnp.zeros([], jnp.int32),
                           params=variables["params"], batch_stats={},
                           opt_state=tx.init(variables["params"]))
        step = make_moe_train_step(moe_model, tx, mesh, **quant_kw)
        eval_step = make_moe_eval_step(moe_model, mesh)
        specs_fn = moe_state_specs
        global_batch = args.batch_size * dp * ep
    else:
        from cpd_tpu.train.lm import lm_state_specs
        model = transformer_lm(tp_axis="tp" if args.tp > 1 else None,
                               sp_axis="sp" if args.sp > 1 else None,
                               tp_size=args.tp, sp_mode=args.sp_mode,
                               remat=args.remat,
                               scan_layers=args.scan_layers,
                               n_kv_heads=args.n_kv_heads,
                               dropout_rate=args.dropout, **model_kw)
        # init model: global shapes, but the SAME param-tree layout
        init_model = transformer_lm(scan_layers=args.scan_layers,
                                    n_kv_heads=args.n_kv_heads,
                                    dropout_rate=args.dropout, **model_kw)
        state = create_train_state(init_model, tx, sample,
                                   jax.random.PRNGKey(0))
        tele_kw = dict(quant_stats=res["quant_stats"],
                       sat_fault_plan=res["sat_plan"])
        if supervisor is not None or psup is not None:
            # one or both ladders (docs/RESILIENCE.md): lazily compiled
            # steps keyed by `ladder_step_key` — transport level, eXmY
            # format, or the (level, format) pair; donate=False so a
            # failed verify can discard
            from cpd_tpu.resilience import (StepTable,
                                            level_reduce_kwargs)
            from cpd_tpu.resilience.precision import resolve_ladder_key
            if supervisor is not None:
                from cpd_tpu.parallel.integrity import make_consensus_fns
                _, resync_fn = make_consensus_fns(mesh, "dp")
            lvl_kw = {k: v for k, v in quant_kw.items()
                      if k not in ("mode", "grad_exp", "grad_man",
                                   "block_scale", "block_size")}

            def build_step(key):
                level, fmt = resolve_ladder_key(
                    key, transport_on=supervisor is not None,
                    precision_on=psup is not None, level=args.mode,
                    fmt=(args.grad_exp, args.grad_man),
                    overlap_on=ov_key is not None,
                    block_on=bk_key is not None)
                if supervisor is not None:
                    rkw = level_reduce_kwargs(level, *fmt)
                else:
                    rkw = dict(mode=level, grad_exp=fmt[0],
                               grad_man=fmt[1])
                # block scaling only exists on the ring rung at a
                # packable format (see the resnet18 CLI's gating)
                blk = (args.block_scale and rkw.get("mode") == "ring"
                       and fmt[1] >= 2 and fmt != (8, 23))
                return make_lm_train_step(
                    model, tx, mesh, emulate_node=args.emulate_node,
                    label_smoothing=args.label_smoothing, donate=False,
                    verify_reduce=res["verify"],
                    wire_fault_plan=(res["wire_plan"]
                                     if level == "ring" else None),
                    block_scale=blk, block_size=args.block_size,
                    **rkw, **lvl_kw, **tele_kw)

            step_table = StepTable(build_step)
            step = step_table[ladder_step_key(supervisor, psup,
                                              overlap=ov_key,
                                              block=bk_key)]
        else:
            # no ladder (verify off, or a non-ladder mode like fast):
            # verification, when on, is detection-only agreement checking
            def build_plain_step(m):
                # mesh-parametrized so the elastic path can rebuild the
                # SAME step at a shrunken/regrown world (ISSUE 19)
                return make_lm_train_step(
                    model, tx, m, emulate_node=args.emulate_node,
                    label_smoothing=args.label_smoothing,
                    verify_reduce=res["verify"],
                    wire_fault_plan=res["wire_plan"],
                    **quant_kw, **tele_kw)
            step = build_plain_step(mesh)
        eval_step = make_lm_eval_step(model, mesh)
        specs_fn = lm_state_specs
        global_batch = args.batch_size * dp * args.emulate_node

    # checkpoints of the SHARDED state: orbax saves the global arrays; on
    # restore the state is re-laid-out with the path's PartitionSpecs
    from jax.sharding import NamedSharding, PartitionSpec
    from cpd_tpu.train import CheckpointManager
    manager = CheckpointManager(os.path.abspath(
        os.path.join(args.save_path, "ckpt")), track_best=False,
        integrity=getattr(args, "ckpt_integrity", True))
    start_iter = 0
    restored = manager.restore(state)
    if restored is not None:
        state = restored
        start_iter = int(restored.step)
        if rank == 0:
            print(f"=> resumed from iter {start_iter}")
        if psup is not None:
            # a restart mid-escalation resumes AT the escalated format
            # (the acceptance contract) — the ladder state was saved in
            # the checkpoint's metadata sidecar
            meta = manager.metadata()
            if meta and meta.get("precision"):
                psup.load_state_dict(meta["precision"])
                step = step_table[ladder_step_key(supervisor, psup, overlap=ov_key, block=bk_key)]
                if rank == 0:
                    print(f"=> resumed precision ladder at {psup.name}"
                          + (" (escalated)" if psup.escalated else ""))
    def relayout(st):
        # orbax restores arrays committed to a single device; the step's
        # shard_map needs the path's PartitionSpec layout (also re-run
        # after every rollback restore)
        return jax.device_put(
            st, jax.tree.map(lambda s: NamedSharding(mesh, s),
                             specs_fn(st),
                             is_leaf=lambda s: isinstance(s, PartitionSpec)))

    state = relayout(state)
    # held-out tail of the synthetic corpus for validation (sized to the
    # eval step's data sharding: dp, dp x ep, ... depending on path)
    val_bs = global_batch // args.emulate_node
    val_idx = np.arange(len(ds) - val_bs, len(ds))
    val_toks, val_tgts = ds.batch(val_idx, seed=-1)

    def validate(it):
        m = eval_step(state, jnp.asarray(val_toks), jnp.asarray(val_tgts))
        if rank == 0:
            print(f"Val [{it}]: loss {float(m['loss']):.4f} "
                  f"acc {100 * float(m['accuracy']):.2f}", flush=True)
        writer.add_scalar("val/loss", float(m["loss"]), it)
        return m

    writer = ScalarWriter(os.path.join(args.save_path, "logs"), rank=rank,
                          tensorboard=args.tensorboard)
    progress = ProgressPrinter(args.max_iter, args.print_freq, rank=rank)
    rng = np.random.RandomState(0)
    last = {}
    t0 = now()
    # training indices exclude the held-out validation tail
    train_n = len(ds) - len(val_idx)
    profiler = StepProfiler(args.profile_dir, start=3)
    # SIGTERM → save at the next step boundary and exit cleanly; resume
    # continues at the saved iteration (same scheme as the other trainers)
    from cpd_tpu.train import PreemptionGuard, loss_diverged, preempt_save
    from cpd_tpu.resilience.inject import InjectedPreemption
    guard = PreemptionGuard()
    preempted = diverged = False
    step_no = start_iter
    rollbacks = reseed = 0
    prev_batch = None
    # --- elastic training setup (ISSUE 19, docs/RESILIENCE.md) --------
    elastic_table, elastic_links, last_dt = None, {}, None
    if esup is not None:
        if res["plan"] is not None and res["plan"].elastic_faults():
            # drill mode: heartbeat rows derive from the plan — a pure
            # function of it, no wall clock — so a drill replays its
            # shrink/regrow event sequence exactly
            from cpd_tpu.resilience.elastic import heartbeat_table
            elastic_table = heartbeat_table(res["plan"],
                                            esup.home_world,
                                            args.max_iter)
            elastic_links = {f.step: (int(f.arg) if f.arg >= 0 else 0,
                                      int(f.arg2) if f.arg2 >= 0 else 1)
                             for f in res["plan"].elastic_faults()
                             if f.kind == "link_flaky"}

        def rebuild_elastic(w):
            # re-layout the data axis at runtime: a new mesh over the
            # first w alive hosts' devices rebuilds the compiled step
            # and with it every per-mesh closure (ring/hierarchical
            # transports, reduce caches) at the new world
            nonlocal mesh, step, eval_step, global_batch
            devs = [jax.devices()[h] for h in esup.active_hosts()]
            mesh = make_mesh(dp=w, devices=devs)
            step = build_plain_step(mesh)
            eval_step = make_lm_eval_step(model, mesh)
            global_batch = args.batch_size * w * args.emulate_node

    def batch_for(i):
        # default path: the run-sequential RNG stream (unchanged
        # behavior — watchdog/guard-only runs keep the baseline's exact
        # batch order); rollback path: per-(retry, iter) seeding so a
        # replay draws a DIFFERENT batch order (the re-seeded recovery
        # of docs/RESILIENCE.md), identically on every host
        with otr.span("data", step=i):
            if sentinel is not None:
                r = np.random.RandomState((reseed * 1000003 + i)
                                          % (2 ** 31))
                idx = r.randint(0, train_n, size=global_batch)
            else:
                idx = rng.randint(0, train_n, size=global_batch)
            return ds.batch(idx, seed=i)

    def watchdog_stop():
        watchdog.disarm()     # acknowledge the trip: cancels hard-exit
        meter.bump("watchdog_trips")
        preempt_save(manager, step_no, state, rank,
                     metadata=run_meta(), what="watchdog stop at")

    try:
        it = start_iter + 1
        while it <= args.max_iter:
            if watchdog is not None and watchdog.tripped:
                # the trip's interrupt was absorbed by the SIGINT-trapping
                # PreemptionGuard; honor it at the step boundary
                watchdog_stop()
                preempted = True
                break
            if guard.should_stop():      # collective when multi-host
                if oflight is not None:
                    oflight.dump("preempt")
                preempt_save(manager, step_no, state, rank,
                             metadata=run_meta())
                preempted = True
                break
            profiler.step(it)
            # host faults key on the 0-based optimizer-UPDATE index, the
            # same clock with_fault_injection's grad schedule runs on, so
            # one plan stays in sync across its two executors (and across
            # run_guarded, whose `it` is that index already).  Checkpoint
            # faults are the exception: they key on the saved step's name.
            upd = it - 1
            # --- elastic supervision (ISSUE 19): one heartbeat row per
            # update, BEFORE the step — the evidence is the previous
            # step's per-host timing (plan-derived in drills, the
            # measured step time stood in for every dp host otherwise)
            if esup is not None:
                if elastic_table is not None:
                    row = (elastic_table[upd] if upd < len(elastic_table)
                           else [1.0] * esup.home_world)
                elif last_dt is not None:
                    row = [last_dt] * esup.home_world
                else:
                    row = None
                decision = (esup.on_heartbeats(upd, row)
                            if row is not None else None)
                meter.counts["elastic_hot_steps"] = \
                    esup.counters["hot_steps"]
                meter.counts["elastic_heartbeat_misses"] = \
                    esup.counters["heartbeat_misses"]
                if decision is None and upd in elastic_links:
                    # the in-step collective retry ladder for a flaky
                    # wire into one host (popped: one-shot per spec)
                    host, attempts = elastic_links.pop(upd)
                    for _ in range(attempts):
                        act = esup.on_link_failure(upd, host)
                        if act == "shrink":
                            decision = ("shrink", (host,))
                            meter.bump("elastic_link_escalations")
                            break
                        meter.bump("elastic_link_retries")
                    else:
                        esup.on_step_ok(upd)
                        if rank == 0 and attempts:
                            print(f"=> elastic: flaky link into host "
                                  f"{host} at iter {it} absorbed by "
                                  f"{attempts} in-step retr"
                                  f"{'y' if attempts == 1 else 'ies'}",
                                  file=sys.stderr)
                if decision is not None:
                    what, hosts_ch = decision
                    if what == "shrink":
                        for _ in hosts_ch:
                            meter.bump("elastic_drains")
                        meter.bump("elastic_shrinks")
                        new_w = esup.world
                        rolled = (manager.restore_latest_valid(
                                      state, rank=rank, world=new_w)
                                  if new_w >= 1 else None)
                        if rolled is None:
                            if rank == 0:
                                print(f"=> elastic: host(s) "
                                      f"{list(hosts_ch)} lost at iter "
                                      f"{it} and no world to shrink "
                                      f"onto — stopping", file=sys.stderr)
                            if oflight is not None:
                                oflight.dump("elastic")
                            preempted = True
                            break
                        rebuild_elastic(new_w)
                        state = relayout(rolled.state)
                        meter.bump("restores")
                        step_no = int(rolled.step)
                        it = step_no + 1
                        if rank == 0:
                            print(f"=> elastic: drained host(s) "
                                  f"{list(hosts_ch)}, world -> {new_w} "
                                  f"(hosts {list(esup.active_hosts())})"
                                  f", resumed from iter {step_no}",
                                  file=sys.stderr)
                        if oflight is not None:
                            oflight.record("elastic_shrink",
                                           step=step_no)
                        continue
                    # regrow: the live state is healthy — seal it, then
                    # rebuild UP onto the returning host (zero steps
                    # lost by construction)
                    meter.bump("elastic_regrows")
                    manager.save(step_no, state, force=True,
                                 metadata=run_meta())
                    manager.wait()
                    rebuild_elastic(esup.world)
                    state = relayout(state)
                    if rank == 0:
                        print(f"=> elastic: host(s) {list(hosts_ch)} "
                              f"rejoined after probation, world -> "
                              f"{esup.world}", file=sys.stderr)
            try:
                if injector is not None:
                    injector.maybe_preempt(upd)
                    action = injector.batch_action(upd)
                else:
                    action = None
                if action == "dup" and prev_batch is not None:
                    meter.bump("batches_duplicated")
                    toks, tgts = prev_batch
                elif action == "drop":
                    meter.bump("batches_dropped")
                    toks, tgts = batch_for(it + args.max_iter)
                else:
                    toks, tgts = batch_for(it)
                if injector is not None:
                    # batch_scale touches float leaves only (a no-op on
                    # int token batches); batch_nan raises loudly there —
                    # LM gradient faults belong to the grad_* kinds
                    toks, tgts = injector.corrupt_batch(upd, (toks, tgts))
                prev_batch = (toks, tgts)
                if watchdog is not None:
                    watchdog.arm(it, loss=last.get("loss"))
                if injector is not None:
                    injector.maybe_stall(upd)
                prev_state = state    # verified-reduce discard target
                t_step = now()
                with otr.span("step", step=it):
                    # the whole jitted fwd+bwd+reduce+optimizer program
                    # plus the metric device-sync; per-bucket reduce
                    # detail rides the reduce_* metrics (registry)
                    state, m = step(state, jnp.asarray(toks),
                                    jnp.asarray(tgts))
                    last = {k: float(v) for k, v in m.items()}  # sync
                last_dt = now() - t_step
                if esup is not None:
                    esup.on_step_ok(upd)
                if watchdog is not None:
                    watchdog.disarm()
            except KeyboardInterrupt:
                if watchdog is not None and watchdog.tripped:
                    watchdog_stop()
                    preempted = True
                    break
                raise
            except InjectedPreemption:
                if oflight is not None:
                    oflight.dump("preempt")
                preempt_save(manager, step_no, state, rank,
                             metadata=run_meta(),
                             what="injected preemption at")
                meter.bump("preemptions")
                preempted = True
                break
            # --- verified-reduce supervision (ISSUE 4) ----------------
            # reduce_ok == 0: the reduce failed its checksums/agreement.
            # Discard the corrupted update (donate=False keeps the
            # pre-step state alive) and walk the transport ladder; the
            # `continue` leaves `it` unchanged, so the retry replays the
            # SAME update index — a deterministic injected wire fault
            # re-fires and forces the downgrade, exactly as in
            # run_guarded.
            if supervisor is None and res["verify"] and float(
                    last.get("reduce_ok", 1.0)) == 0.0:
                # non-ladder mode (fast): detection only — count + warn
                meter.bump("wire_faults_detected")
                if rank == 0:
                    print(f"=> reduce verify FAILED at iter {it} (mode "
                          f"{args.mode} has no transport ladder: "
                          f"detection only)", file=sys.stderr)
            if supervisor is not None and float(
                    last.get("reduce_ok", 1.0)) == 0.0:
                meter.bump("wire_faults_detected")
                state = prev_state
                action = supervisor.on_failure(upd)
                if action == "give_up":
                    if rank == 0:
                        print(f"=> verified reduce failed at the fp32 "
                              f"transport floor (iter {it}) — not a "
                              f"wire problem; stopping", file=sys.stderr)
                    diverged = True
                    break
                if action == "downgrade":
                    meter.bump("transport_downgrades")
                    state = resync_fn(state)
                    meter.bump("resyncs")
                    step = step_table[ladder_step_key(supervisor, psup, overlap=ov_key, block=bk_key)]
                    if rank == 0:
                        print(f"=> wire fault detected at iter {it} "
                              f"(hop_bad "
                              f"{int(last.get('reduce_hop_bad', 0))}, "
                              f"gather_bad "
                              f"{int(last.get('reduce_gather_bad', 0))})"
                              f" — transport downgraded to "
                              f"{supervisor.mode}, replicas re-synced "
                              f"from rank 0", file=sys.stderr)
                else:
                    meter.bump("reduce_retries")
                    if rank == 0:
                        print(f"=> wire fault detected at iter {it} — "
                              f"update discarded, retrying on the "
                              f"{supervisor.mode} transport",
                              file=sys.stderr)
                continue
            if supervisor is not None and \
                    supervisor.on_success(upd) == "upgrade":
                meter.bump("transport_upgrades")
                step = step_table[ladder_step_key(supervisor, psup, overlap=ov_key, block=bk_key)]
                if rank == 0:
                    print(f"=> transport probation passed at iter {it}: "
                          f"back to {supervisor.mode}", file=sys.stderr)
            step_no = it
            if meter is not None:
                meter.observe_metrics(last)
            if oreg is not None:
                oreg.absorb_step_metrics(last, it)
            if oflight is not None:
                oflight.record("step", step=it, loss=last["loss"])
            # --- precision-ladder supervision (ISSUE 5) ---------------
            # host decision on the psum-agreed prec_wire_* telemetry;
            # escalation re-formats the NEXT step (this update was
            # already guarded in-step if its values went non-finite)
            if psup is not None:
                pact = psup.on_metrics(upd, last)
                if psup.last_hot:
                    meter.bump("sat_hot_steps")
                if pact is not None:
                    meter.bump("precision_escalations"
                               if pact == "escalate"
                               else "precision_deescalations")
                    step = step_table[ladder_step_key(supervisor, psup, overlap=ov_key, block=bk_key)]
                    if rank == 0:
                        how = ("escalated" if pact == "escalate"
                               else "probation passed: back")
                        print(f"=> precision ladder {how} to "
                              f"{psup.name} at iter {it} (sat "
                              f"{int(last.get('prec_wire_sat', 0))}/"
                              f"{int(last.get('prec_wire_total', 0))} "
                              f"nan "
                              f"{int(last.get('prec_wire_nan', 0))})",
                              file=sys.stderr)
            if injector is not None:
                last["loss"] = injector.fault_loss(upd, last["loss"])
            # a guard-skipped step's loss metric may be poisoned by the
            # bad batch/grads; the anomaly was already handled in-step
            guard_ok = float(last.get("guard_ok", 1.0)) != 0.0
            if sentinel is not None:
                if guard_ok and sentinel.update(last["loss"]):
                    if rank == 0:
                        print(f"=> divergence sentinel tripped at iter "
                              f"{it} (loss {last['loss']:.4g})",
                              file=sys.stderr)
                    rolled = None
                    if rollbacks < args.max_rollbacks:
                        rolled = manager.restore_latest_valid(state,
                                                              rank=rank)
                    if rolled is None:
                        diverged = True
                        break
                    for _bad in rolled.skipped:
                        meter.bump("ckpts_invalid")
                    if psup is not None and (rolled.metadata or {}
                                             ).get("precision"):
                        # replaying at home would re-diverge into the
                        # saturation the escalation escaped
                        psup.load_state_dict(rolled.metadata["precision"])
                        step = step_table[ladder_step_key(supervisor,
                                                          psup,
                                                          overlap=ov_key,
                                                          block=bk_key)]
                    state = relayout(rolled.state)
                    step_no = int(rolled.step)
                    it = step_no + 1
                    rollbacks += 1
                    reseed = rollbacks
                    meter.bump("rollbacks")
                    meter.bump("restores")
                    if oflight is not None:
                        oflight.record("rollback", step=step_no)
                        oflight.dump("rollback")
                    sentinel.reset()
                    if rank == 0:
                        print(f"=> rolled back to iter {step_no} "
                              f"(retry {rollbacks}/{args.max_rollbacks}, "
                              f"re-seeded data order)", file=sys.stderr)
                    if args.rollback_backoff > 0:
                        time.sleep(args.rollback_backoff
                                   * (2 ** (rollbacks - 1)))
                    continue
            elif guard_ok and loss_diverged(last["loss"], f"iter {it}",
                                            rank):
                diverged = True
                break
            progress.maybe_print(it, _suffix=meter.suffix(),
                                 Loss=last["loss"],
                                 Acc=100 * last["accuracy"],
                                 TokPerSec=global_batch * args.seq_len * it
                                 / max(now() - t0, 1e-9))
            writer.add_scalar("train/loss", last["loss"], it)
            if it % args.val_freq == 0 or it == args.max_iter:
                with otr.span("validate", step=it):
                    validate(it)
            if it % args.ckpt_freq == 0 or it == args.max_iter:
                # force under resilience: a rollback replay must be able
                # to overwrite the stale/corrupt copy of this step
                with otr.span("checkpoint", step=it):
                    manager.save(it, state, force=res["active"],
                                 metadata=run_meta())
                if injector is not None:
                    # the fault must land on the FINAL bytes — without
                    # integrity the save is still async at this point
                    manager.wait()
                if injector is not None and injector.corrupt_checkpoint(
                        it, manager.directory):
                    if rank == 0:
                        print(f"=> injected checkpoint corruption at "
                              f"step {it}", file=sys.stderr)
            it += 1
    finally:
        guard.uninstall()
        if watchdog is not None:
            watchdog.close()
        # close() stops an in-flight jax.profiler trace even when the
        # loop died inside the window (watchdog interrupt, injected
        # fault) — leaking a running trace poisons every later
        # start_trace in this process (ISSUE 11 satellite)
        profiler.close()
    from cpd_tpu.resilience import report_unfired
    if esup is not None and res["plan"] is not None:
        # the elastic harness owns its kinds' accounting (mirrors
        # run_elastic): anything scheduled past the last processed
        # update, or aimed at a host outside the fleet, never manifested
        leftover = sorted(
            f for f in res["plan"].elastic_faults()
            if f.step >= step_no or int(max(f.arg, 0)) >= esup.home_world)
        if leftover:
            meter.bump("faults_unfired", len(leftover))
            if rank == 0:
                print(f"=> elastic plan: {len(leftover)} spec(s) never "
                      f"fired: {leftover}", file=sys.stderr)
    # wire faults only fire when the default path baked a ring-mode
    # table in — a wire_* spec on any other run must read as UNFIRED
    report_unfired(injector, n_steps=args.max_iter, meter=meter, rank=rank,
                   wire_armed=(not (args.pp > 1 or args.moe)
                               and (supervisor.home == "ring"
                                    if supervisor is not None
                                    else args.mode == "ring")),
                   # sat tables only ride the default-path steppers (a
                   # pp/moe run with sat specs exits up front, but keep
                   # the accounting honest regardless)
                   sat_armed=not (args.pp > 1 or args.moe),
                   host_armed=esup is not None)
    jax.block_until_ready(state.params)
    manager.wait()
    manager.close()
    dt = now() - t0
    ran = step_no - start_iter
    if rank == 0 and not (preempted or diverged):
        if last:
            # count only the iters THIS run executed — a partial resume
            # must not overstate the throughput
            print(f"done: {ran} iters in {dt:.1f}s "
                  f"({ran * global_batch * args.seq_len / dt:.0f}"
                  f" tok/s) final loss {last['loss']:.4f}")
        else:
            # resumed at/past max_iter: no step ran — say so instead of
            # printing a placeholder nan that reads like divergence
            print(f"done: resumed at iter {start_iter}, nothing left to "
                  f"train (max_iter {args.max_iter})")
    sampled = None
    if args.sample > 0 and not (preempted or diverged):
        from jax.sharding import NamedSharding, PartitionSpec
        from cpd_tpu.models import generate
        toks, _ = ds.batch(np.arange(1), seed=0)
        prompt = jnp.asarray(toks[:, :min(8, args.seq_len)], jnp.int32)
        # params were laid out per lm_state_specs (tp-sharded leaves when
        # tp>1); re-lay them out fully replicated — a compiled all-gather
        # that is multi-host safe, unlike device_get on a sharded Array —
        # then decode single-device
        gather = jax.jit(lambda p: p,
                         out_shardings=NamedSharding(mesh, PartitionSpec()))
        out = generate(init_model, jax.device_get(gather(state.params)),
                       prompt, max_new_tokens=args.sample,
                       temperature=args.sample_temperature,
                       top_k=args.sample_top_k, top_p=args.sample_top_p,
                       rng=(jax.random.PRNGKey(args.sample_seed)
                            if args.sample_temperature > 0 else None))
        sampled = np.asarray(out)[0].tolist()
        if rank == 0:
            how = ("greedy" if args.sample_temperature == 0 else
                   f"T={args.sample_temperature} k={args.sample_top_k} "
                   f"p={args.sample_top_p}")
            print(f"sample ({how}, {args.sample} new tokens): {sampled}")
    if args.export_torch and not (preempted or diverged):
        from jax.sharding import NamedSharding, PartitionSpec
        from cpd_tpu.interop import (export_transformer_lm,
                                     save_torch_checkpoint)
        # same multi-host-safe re-layout as the sample path above:
        # compiled all-gather to replicated, then host copies; only rank
        # 0 writes (every host holds the same gathered values)
        gather = jax.jit(lambda p: p,
                         out_shardings=NamedSharding(mesh, PartitionSpec()))
        params_host = jax.device_get(gather(state.params))
        if rank == 0:
            sd = export_transformer_lm({"params": params_host})
            save_torch_checkpoint(sd, args.export_torch,
                                  wrapper="state_dict")
            print(f"=> exported torch state_dict {args.export_torch}")
    writer.close()
    from cpd_tpu.utils.config import finish_obs
    obs_out = finish_obs(obs, meter=meter, last=last, step_no=step_no,
                         supervisor=supervisor, precision=psup,
                         elastic=esup, rank=rank, preempted=preempted,
                         diverged=diverged)
    return {"step": step_no, "diverged": diverged,
            **({"resilience": meter.as_dict()} if res["active"] else {}),
            **({"obs": obs_out} if obs_out is not None else {}),
            **({"sample": sampled} if sampled is not None else {}), **last}


if __name__ == "__main__":
    res = main()
    sys.exit(3 if res.get("diverged") else 0)
