"""The golden APS accuracy experiment — the reference's artifact claim,
reproduced end-to-end on the virtual 8-device mesh.

The reference repo's entire evaluation is "train with and without APS and
compare accuracy curves" (reference README.md:70-79,153-154: "using APS, we
can improve the testing accuracies of training with low-precision
gradients").  This script runs that experiment on the cpd_tpu stack: a
fixed-seed CIFAR-10-shaped workload (real CIFAR-10 if on disk, else the
learnable synthetic set, data/cifar.py), trained at full fp32 gradients and
at low-precision gradient formats with APS off and on, through the faithful
rank-ordered quantized all-reduce over dp=8 x emulate_node=2 (a 16-rank
emulated cluster, README.md:76-79's quick-start shape).

Outputs (default docs/golden/):
    results.json   — final Prec@1 per config + the asserted orderings
    curves.png     — train-loss curves + final-accuracy bars

Expected ordering (checked, exit 1 on violation):
    aps >= noaps + margin   and   aps ≈ fp32     for each low-prec format
A short CI version runs in tests/test_golden.py.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import shutil
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


CONFIGS = [
    # tag, grad_exp, grad_man, use_aps
    ("fp32", 8, 23, False),
    ("e4m3_noaps", 4, 3, False),
    ("e4m3_aps", 4, 3, True),
    ("e3m4_noaps", 3, 4, False),
    ("e3m4_aps", 3, 4, True),
    # SR gradient pipeline (beyond-reference): unbiased rounding instead
    # of exponent shifting — far above the RTNE collapse, below APS.
    # Committed run: 92.84 (vs noaps 31.72, aps 94.93).  The margin is
    # conservative (+15) because SR trades bias for noise; note the
    # PRE-rank-decorrelation code measured 74.6-90.1 across seeds, so a
    # result back in that range suggests the coherent-rounding regression
    # (parallel/dist.py k_pre), not ordinary seed variance.
    ("e3m4_sr_noaps", 3, 4, False, ("--grad-rounding", "stochastic")),
]

# Second arm (capability beyond the reference): momentum buffer held in
# eXmY (train/optim.py quant_sgd).  Same claim shape as APS: naive
# low-precision state loses accuracy, the quantized Kahan residual
# recovers it.  Gradients stay fp32 so the effect isolates the optimizer.
OPT_CONFIGS = [
    # tag, extra CLI flags
    ("opt_fp32", []),
    ("opt_e4m3_naive", ["--opt_exp", "4", "--opt_man", "3"]),
    ("opt_e4m3_kahan", ["--opt_exp", "4", "--opt_man", "3",
                        "--opt_kahan"]),
    # stochastic rounding: the OTHER cure for low-precision update
    # stagnation — unbiased random round direction instead of a
    # deterministic residual.  Exploration (seeds 0 and 7: 95.20 / 94.80
    # vs naive 92.97) sits between naive and Kahan, as theory predicts.
    ("opt_e4m3_sr", ["--opt_exp", "4", "--opt_man", "3",
                     "--opt-rounding", "stochastic"]),
]


def _run_tagged(tagged_flags, iters: int, save_root: str, batch_size: int,
                emulate_node: int, peak_lr: float, data_root, arch: str,
                mode: str, quiet: bool) -> dict:
    """Shared runner: train each (tag, extra_flags) config through the
    ResNet-18 CLI; returns {tag: {"prec1": float, "loss": [(step, v)]}}."""
    from resnet18_cifar.train import main

    out = {}
    for tag, extra in tagged_flags:
        save = os.path.join(save_root, tag)
        # from-scratch experiment: a stale checkpoint from a previous run
        # would auto-resume at max_iter and train nothing
        shutil.rmtree(save, ignore_errors=True)
        argv = ["--arch", arch, "--batch_size", str(batch_size),
                "--max-iter", str(iters), "--val_freq", str(iters),
                "--print_freq", "100000" if quiet else "50",
                "--peak-lr", str(peak_lr), "--save_path", save,
                "--emulate_node", str(emulate_node), "--mode", mode] + extra
        if data_root:
            argv += ["--data-root", data_root]
        res = main(argv)
        losses = []
        jsonl = os.path.join(save, "logs", "scalars.jsonl")
        if os.path.isfile(jsonl):
            with open(jsonl) as f:
                for line in f:
                    rec = json.loads(line)
                    if rec.get("tag") == "train/loss":
                        losses.append((rec["step"], rec["value"]))
        out[tag] = {"prec1": res["best_prec1"], "loss": losses,
                    "diverged": bool(res.get("diverged"))}
        note = "  [DIVERGED]" if res.get("diverged") else ""
        print(f"== {tag}: Prec@1 {res['best_prec1']:.2f}{note}", flush=True)
    return out


def run_experiment(iters: int, save_root: str, batch_size: int = 16,
                   emulate_node: int = 2, peak_lr: float = 0.4,
                   configs=CONFIGS, data_root=None, arch: str = "tiny",
                   mode: str = "fast", quiet: bool = True) -> dict:
    """Train every gradient-precision config.

    `mode="fast"` uses quantize->psum->requantize; the ordered faithful
    path is bit-covered by tests/test_parallel.py — for the accuracy-
    ordering claim both modes carry the same precision at the wire, and
    fast keeps the experiment CPU-affordable."""
    tagged = [(tag, ["--grad_exp", str(ge), "--grad_man", str(gm)]
               + (["--use_APS"] if aps else [])
               + [f for flags in extra for f in flags])
              for tag, ge, gm, aps, *extra in configs]
    return _run_tagged(tagged, iters, save_root, batch_size, emulate_node,
                       peak_lr, data_root, arch, mode, quiet)


def run_opt_experiment(iters: int, save_root: str, batch_size: int = 16,
                       emulate_node: int = 2, peak_lr: float = 0.4,
                       configs=OPT_CONFIGS, data_root=None,
                       arch: str = "tiny", mode: str = "fast",
                       quiet: bool = True) -> dict:
    """Train every optimizer-precision config; {tag: {"prec1": ...}}."""
    return _run_tagged(list(configs), iters, save_root, batch_size,
                       emulate_node, peak_lr, data_root, arch, mode, quiet)


# Third arm (capability beyond the reference): the transformer LM under
# the same APS claim — at an aggressive gradient format the un-scaled
# quantized all-reduce stalls training, APS recovers it.  Loss (lower
# better) replaces Prec@1 as the metric.
LM_CONFIGS = [
    ("lm_fp32", 8, 23, False),
    ("lm_e3m4_noaps", 3, 4, False),
    ("lm_e3m4_aps", 3, 4, True),
    # SR gradient pipeline on the LM: unbiased rounding alone recovers
    # most of the no-APS stall (exploration seeds 0/7: 2.699 / 2.722 vs
    # noaps 4.056, aps 2.604)
    ("lm_e3m4_sr_noaps", 3, 4, False, ("--grad-rounding", "stochastic")),
]


def run_lm_experiment(iters: int, save_root: str, configs=LM_CONFIGS,
                      quiet: bool = True) -> dict:
    """Train each gradient-precision config through the LM CLI on the
    8-device mesh; returns {tag: {"loss": float, "accuracy": float}}."""
    from lm.train import main

    out = {}
    for tag, ge, gm, aps, *extra in configs:
        save = os.path.join(save_root, tag)
        shutil.rmtree(save, ignore_errors=True)   # see _run_tagged
        argv = ["--seq-len", "32", "--d-model", "32", "--n-layers", "2",
                "--n-heads", "4", "--vocab-size", "64", "--batch-size",
                "2", "--max-iter", str(iters), "--base-lr", "0.05",
                "--print-freq", "100000" if quiet else "50",
                "--val-freq", str(iters), "--mode", "fast",
                "--grad_exp", str(ge), "--grad_man", str(gm),
                "--save-path", save]
        if aps:
            argv.append("--use_APS")
        for flags in extra:
            argv.extend(flags)
        res = main(argv)
        out[tag] = {"loss": res["loss"], "accuracy": res["accuracy"],
                    "diverged": bool(res.get("diverged"))}
        print(f"== {tag}: loss {res['loss']:.4f} "
              f"acc {100 * res['accuracy']:.1f}", flush=True)
    return out


def check_lm_ordering(results: dict, margin: float = 0.5,
                      recover: float = 0.3) -> list[str]:
    """APS recovers the LM loss the naive low-precision reduce loses.

    A diverged (or NaN) no-APS arm counts as infinitely bad — divergence
    at the aggressive format is the strongest form of the claim's
    premise, not a harness failure.  A diverged APS or fp32 arm IS a
    failure."""
    def loss_of(tag, bad_is_inf):
        rec = results[tag]
        v = rec["loss"]
        if rec.get("diverged") or not math.isfinite(v):
            return float("inf") if bad_is_inf else float("nan")
        return v

    fp32 = loss_of("lm_fp32", bad_is_inf=False)
    noaps = loss_of("lm_e3m4_noaps", bad_is_inf=True)
    aps = loss_of("lm_e3m4_aps", bad_is_inf=False)
    ok_gain = aps <= noaps - margin
    ok_recover = aps <= fp32 + recover
    checks = [
        f"lm e3m4: aps loss {aps:.3f} <= noaps {noaps:.3f} - {margin} -> "
        f"{'OK' if ok_gain else 'VIOLATED'}",
        f"lm e3m4: aps loss {aps:.3f} <= fp32 {fp32:.3f} + {recover} -> "
        f"{'OK' if ok_recover else 'VIOLATED'}",
    ]
    if "lm_e3m4_sr_noaps" in results:
        # the SR rescue on the LM (exploration: 2.70/2.72 across seeds vs
        # the 4.06 stall); 0.5 recover margin absorbs SR's seed noise
        sr = loss_of("lm_e3m4_sr_noaps", bad_is_inf=False)
        ok_sr = (sr <= noaps - margin) and (sr <= fp32 + 0.5)
        checks.append(
            f"lm e3m4: sr_noaps loss {sr:.3f} <= noaps {noaps:.3f} - "
            f"{margin} and <= fp32 {fp32:.3f} + 0.5 -> "
            f"{'OK' if ok_sr else 'VIOLATED'}")
    return checks


def check_opt_ordering(results: dict, margin: float = 1.0,
                       recover: float = 2.0) -> list[str]:
    """Kahan-compensated eXmY momentum recovers what naive loses; so does
    unbiased stochastic rounding (by a smaller, noisier margin)."""
    fp32 = results["opt_fp32"]["prec1"]
    naive = results["opt_e4m3_naive"]["prec1"]
    kahan = results["opt_e4m3_kahan"]["prec1"]
    ok_gain = kahan >= naive + margin
    ok_recover = kahan >= fp32 - recover
    checks = [
        f"opt e4m3: kahan {kahan:.2f} >= naive {naive:.2f} + {margin} -> "
        f"{'OK' if ok_gain else 'VIOLATED'}",
        f"opt e4m3: kahan {kahan:.2f} >= fp32 {fp32:.2f} - {recover} -> "
        f"{'OK' if ok_recover else 'VIOLATED'}",
    ]
    if "opt_e4m3_sr" in results:
        sr = results["opt_e4m3_sr"]["prec1"]
        ok_sr = sr >= naive + margin
        checks.append(
            f"opt e4m3: sr {sr:.2f} >= naive {naive:.2f} + {margin} -> "
            f"{'OK' if ok_sr else 'VIOLATED'}")
    return checks


def check_ordering(results: dict, margin: float = 2.0) -> list[str]:
    """The artifact claim: APS recovers the accuracy low-precision loses."""
    checks = []
    fp32 = results["fp32"]["prec1"]
    for fmt in ("e4m3", "e3m4"):
        noaps = results.get(f"{fmt}_noaps")
        aps = results.get(f"{fmt}_aps")
        if noaps is None or aps is None:
            continue
        ok_gain = aps["prec1"] >= noaps["prec1"] + margin
        ok_recover = aps["prec1"] >= fp32 - 5.0
        checks.append(f"{fmt}: aps {aps['prec1']:.2f} >= noaps "
                      f"{noaps['prec1']:.2f} + {margin} -> "
                      f"{'OK' if ok_gain else 'VIOLATED'}")
        checks.append(f"{fmt}: aps {aps['prec1']:.2f} >= fp32 {fp32:.2f} - 5 "
                      f"-> {'OK' if ok_recover else 'VIOLATED'}")
    if "e3m4_sr_noaps" in results and "e3m4_noaps" in results:
        # SR rescue: unbiased rounding alone recovers most of what the
        # un-APS'd RTNE reduction loses.  Conservative +15 margin: SR is
        # noisy by construction (observed 74.6-90.1 across seeds vs the
        # 31.7 collapse); APS's deterministic shifting remains the best
        # arm and is asserted above.
        sr = results["e3m4_sr_noaps"]["prec1"]
        noaps = results["e3m4_noaps"]["prec1"]
        ok_sr = sr >= noaps + 15.0
        checks.append(f"e3m4: sr_noaps {sr:.2f} >= noaps {noaps:.2f} + 15 "
                      f"-> {'OK' if ok_sr else 'VIOLATED'}")
    return checks


def plot(results: dict, path: str) -> None:
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(11, 4))
    for tag, rec in results.items():
        if rec["loss"]:
            steps, vals = zip(*rec["loss"])
            ax1.plot(steps, vals, label=tag)
    ax1.set_xlabel("iteration")
    ax1.set_ylabel("train loss")
    ax1.set_title("training loss")
    ax1.legend()
    tags = list(results)
    ax2.bar(range(len(tags)), [results[t]["prec1"] for t in tags])
    ax2.set_xticks(range(len(tags)), tags, rotation=30, ha="right")
    ax2.set_ylabel("final Prec@1 (%)")
    ax2.set_title("APS recovers low-precision accuracy")
    fig.tight_layout()
    fig.savefig(path, dpi=110)
    print(f"wrote {path}")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--iters", type=int, default=400)
    p.add_argument("--out", default=os.path.join(_REPO, "docs", "golden"))
    p.add_argument("--save-root", default="/tmp/cpd_tpu_golden")
    p.add_argument("--data-root", default=None)
    p.add_argument("--margin", type=float, default=2.0,
                   help="APS-arm min accuracy gain (aps vs noaps)")
    p.add_argument("--opt-margin", type=float, default=1.0,
                   help="optimizer-arm min gain (kahan vs naive)")
    p.add_argument("--lm-iters", type=int, default=150,
                   help="LM-arm iterations (separation shows by ~150)")
    p.add_argument("--lm-margin", type=float, default=0.5,
                   help="LM-arm min loss gain (aps vs noaps)")
    p.add_argument("--lm-recover", type=float, default=0.3,
                   help="LM-arm max loss gap to fp32")
    args = p.parse_args(argv)

    results = run_experiment(args.iters, args.save_root,
                             data_root=args.data_root)
    checks = check_ordering(results, args.margin)
    opt_results = run_opt_experiment(args.iters,
                                     os.path.join(args.save_root, "opt"),
                                     data_root=args.data_root)
    opt_checks = check_opt_ordering(opt_results,
                                    margin=args.opt_margin)
    checks += opt_checks
    lm_results = run_lm_experiment(args.lm_iters,
                                   os.path.join(args.save_root, "lm"))
    checks += check_lm_ordering(lm_results, margin=args.lm_margin,
                                recover=args.lm_recover)
    os.makedirs(args.out, exist_ok=True)
    payload = {
        "iters": args.iters,
        "lm_iters": args.lm_iters,
        "workload": "CIFAR-10-shaped, tiny CNN, dp=8 x emulate_node=2 "
                    "(16-rank emulated cluster), faithful-precision wire; "
                    "LM arm: 2L transformer, dp=8, Markov token stream",
        "prec1": {t: r["prec1"] for t, r in results.items()},
        "opt_prec1": {t: r["prec1"] for t, r in opt_results.items()},
        "lm_loss": {t: r["loss"] for t, r in lm_results.items()},
        "checks": checks,
    }
    with open(os.path.join(args.out, "results.json"), "w") as f:
        json.dump(payload, f, indent=2)
    plot(results, os.path.join(args.out, "curves.png"))
    for c in checks:
        print(c)
    return 1 if any("VIOLATED" in c for c in checks) else 0


if __name__ == "__main__":
    # The documented workload is the 8-device VIRTUAL CPU mesh (the JAX
    # emulate-node analog, SURVEY.md §4c) — force it before jax imports.
    # Without this, the axon TPU plugin grabs the backend and the
    # experiment crawls through the tunnel on 1 real chip (~25 ms per
    # device round-trip x 400 iters x 8 configs).
    import re

    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                    os.environ.get("XLA_FLAGS", ""))
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    raise SystemExit(main())
