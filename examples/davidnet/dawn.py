"""DavidNet / CIFAR-10 DAWNBench trainer — parity with
`example/DavidNet/dawn.py` (flags :11-26, schedule+opt :65-79, epoch loop
via train_utils/utils train() :391-436), on the shared cpd_tpu harness.

Reference semantics kept: PiecewiseLinear LR 0 -> 0.4*lr_scale at epoch 5
-> 0 at epoch `--epoch` (dawn.py:65), nesterov SGD with weight decay
5e-4 * batch_size (dawn.py:73-79), crop/flip/cutout-8 augmentation
(dawn.py:66), `--half` as bf16 compute (TPU's half precision — the MXU
dtype), `--loss_scale` multiplied into the loss and never unscaled
(utils.py:332-334), TSV/Table loggers (dawn.py:37-47, utils.py:44-56).

`--arch davidnet_graph` trains the dict-graph-defined form of the network
(models/davidnet_graph.py — the reference's TorchGraph definition style,
utils.py:258-292); forward-parity with `--arch davidnet` is pinned by
tests/test_graph.py.
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

# Make the repo importable when run as a script (the reference required a
# manual PYTHONPATH export, README.md:39; here the entry bootstraps itself).
_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="cpd_tpu DavidNet DAWNBench")
    # reference surface (dawn.py:11-26)
    p.add_argument("--dist", default=0, type=int)
    p.add_argument("--epoch", default=24, type=int)
    p.add_argument("--warm_up_epoch", default=5, type=int)
    p.add_argument("-b", "--batch_size", default=512, type=int)
    p.add_argument("--momentum", default=0.9, type=float)
    p.add_argument("--workers", default=4,
                   help="accepted for reference CLI parity (dawn.py:15, "
                        "DataLoader workers); unused here — batches are "
                        "built by the vectorized pipeline + native "
                        "executor, no worker pool")
    p.add_argument("--half", default=0, type=int)
    p.add_argument("--lr_scale", default=1.0, type=float)
    p.add_argument("--seed", default=0, type=int)
    p.add_argument("--grad_exp", default=8, type=int)
    p.add_argument("--grad_man", default=23, type=int)
    p.add_argument("--use_APS", action="store_true")
    p.add_argument("--use_kahan", action="store_true")
    p.add_argument("--loss_scale", default="1",
                   help="static scale int (reference dawn.py:24, never "
                        "unscaled) or 'dynamic' for GradScaler-style "
                        "scaling (train/scaling.py; beyond-reference)")
    # new surface
    p.add_argument("--arch", default="davidnet")
    p.add_argument("--data-root", default=None)
    p.add_argument("--max-batches-per-epoch", default=None, type=int,
                   help="truncate epochs (smoke tests)")
    p.add_argument("--emulate_node", default=1, type=int)
    p.add_argument("--profile-dir", default=None,
                   help="write a jax.profiler trace of a few steps here")
    p.add_argument("--mode", default="faithful",
                   choices=["faithful", "fast"])
    return p


def main(argv=None) -> dict:
    args = build_parser().parse_args(argv)

    import jax
    import jax.numpy as jnp

    from cpd_tpu.data import CIFAR10Pipeline, load_cifar10
    from cpd_tpu.models import get_model
    from cpd_tpu.parallel.dist import dist_init, host_batch_to_global
    from cpd_tpu.parallel.mesh import data_parallel_mesh
    from cpd_tpu.train import (Timer, create_train_state,
                               loss_diverged, make_eval_step,
                               make_optimizer, make_train_step,
                               piecewise_linear)
    from cpd_tpu.utils import StepProfiler, TableLogger, TSVLogger

    rank, world = dist_init() if args.dist else (0, 1)
    mesh = data_parallel_mesh()
    n_dev = mesh.devices.size

    train_x, train_y, test_x, test_y = load_cifar10(args.data_root)
    dataset_len = len(train_y)
    global_batch = args.batch_size * n_dev * args.emulate_node
    iters_per_epoch = dataset_len // global_batch
    if args.max_batches_per_epoch:
        iters_per_epoch = min(iters_per_epoch, args.max_batches_per_epoch)

    # dawn.py:65 knots are epochs; the step-based schedule scales them.
    schedule = piecewise_linear(
        [0, args.warm_up_epoch * iters_per_epoch,
         args.epoch * iters_per_epoch],
        [0.0, 0.4 * args.lr_scale, 0.0])
    # dawn.py:73-79: nesterov SGD, wd = 5e-4 * batch_size
    tx = make_optimizer("nesterov", schedule, momentum=args.momentum,
                        weight_decay=5e-4 * args.batch_size)
    dynamic_scale = str(args.loss_scale).strip().lower() == "dynamic"
    if dynamic_scale:
        from cpd_tpu.train.scaling import with_dynamic_loss_scale
        tx = with_dynamic_loss_scale(tx)
    loss_scale = "dynamic" if dynamic_scale else float(args.loss_scale)

    dtype = jnp.bfloat16 if args.half else jnp.float32
    model = get_model(args.arch, dtype=dtype)
    state = create_train_state(model, tx, jnp.zeros((2, 32, 32, 3)),
                               jax.random.PRNGKey(args.seed))

    train_step = make_train_step(
        model, tx, mesh, emulate_node=args.emulate_node,
        use_aps=args.use_APS, grad_exp=args.grad_exp,
        grad_man=args.grad_man, use_kahan=args.use_kahan,
        loss_scale=loss_scale, mode=args.mode)
    eval_step = make_eval_step(model, mesh)

    host_batch = global_batch // world
    pipeline = CIFAR10Pipeline(train_x, train_y, host_batch, augment=True,
                               cutout=8)
    eval_bs = max(n_dev, (min(1000, len(test_y)) // n_dev) * n_dev)
    eval_host = eval_bs // world
    eval_pipe = CIFAR10Pipeline(test_x, test_y, eval_bs, augment=False)

    table = TableLogger(rank=rank)
    tsv = TSVLogger()
    timer = Timer()
    profiler = StepProfiler(args.profile_dir, start=3)
    global_step = 0
    result = {}
    diverged = False
    try:
        for epoch in range(1, args.epoch + 1):
            rng = np.random.RandomState(args.seed + epoch)
            # same epoch permutation on every host; each takes its contiguous
            # 1/world block of every global batch
            order = rng.permutation(dataset_len)[:iters_per_epoch * global_batch]
            train_loss = train_acc = 0.0
            n = 0
            def produced(order=order, epoch=epoch):
                # batch prep (native threaded augmentation + device transfer)
                # two steps ahead of the device (utils/prefetch.py) — matters
                # most here: DAWNBench is a wall-clock speed run
                for lo in range(0, len(order), global_batch):
                    sel = order[lo + rank * host_batch:
                                lo + (rank + 1) * host_batch]
                    bx, by = pipeline.batch(sel, seed=epoch)
                    yield (host_batch_to_global(bx, mesh),
                           host_batch_to_global(by, mesh))

            from cpd_tpu.utils.prefetch import Prefetcher
            batches = Prefetcher(produced(), depth=2)
            try:
                for gx, gy in batches:
                    global_step += 1
                    profiler.step(global_step)
                    state, m = train_step(state, gx, gy)
                    step_loss = float(m["loss"])
                    if loss_diverged(step_loss, f"step {global_step}", rank,
                                     hint="lower --loss_scale / try "
                                          "--use_APS"):
                        diverged = True
                        break
                    train_loss += step_loss
                    train_acc += float(m["accuracy"])
                    n += 1
            finally:
                batches.close()   # stop the producer on any exit path
            if diverged:
                break
            jax.block_until_ready(state.params)
            train_time = timer()                 # counts toward total

            test_loss = test_acc = 0.0
            k = 0
            limit = (len(test_y) // eval_bs) * eval_bs
            for lo in range(0, limit, eval_bs):
                sel = np.arange(lo + rank * eval_host,
                                lo + (rank + 1) * eval_host)
                x, y = eval_pipe.batch(sel)
                m = eval_step(state, host_batch_to_global(x, mesh),
                              host_batch_to_global(y, mesh))
                test_loss += float(m["loss"])
                test_acc += float(m["top1"])
                k += 1
            # test time excluded from DAWNBench total (dawn.py's
            # test_time_in_total=False).
            test_time = timer(include_in_total=False)
            total = timer.total_time

            result = {
                "epoch": epoch,
                "lr": float(schedule(epoch * iters_per_epoch)),
                "train time": train_time, "train loss": train_loss / max(n, 1),
                "train acc": train_acc / max(n, 1),
                "test time": test_time, "test loss": test_loss / max(k, 1),
                "test acc": test_acc / max(k, 1),
                "total time": total,
            }
            table.append(result)
            tsv.append(result)
    finally:
        # stops an in-flight jax.profiler trace even when the
        # loop died inside the window (ISSUE 11 satellite -- a
        # leaked running trace poisons every later start_trace
        # in the process)
        profiler.close()
    if rank == 0:
        print(tsv)
    result["diverged"] = diverged
    return result


if __name__ == "__main__":
    res = main()
    sys.exit(3 if res.get("diverged") else 0)
