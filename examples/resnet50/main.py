"""ResNet-50 / ImageNet trainer — parity with `example/ResNet50/main.py`
(flags :21-55, warmup schedule :237-252, BN-without-wd param groups
:123-131, per-epoch checkpoint + auto-resume :70-75,134-138,261-269,
emulate-node sub-batch accumulation :160-202) on the shared cpd_tpu
harness.

The headline workload (SURVEY.md §6): ResNet-50, batch 32/chip, e5m2 APS
gradient all-reduce.
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

# Make the repo importable when run as a script (the reference required a
# manual PYTHONPATH export, README.md:39; here the entry bootstraps itself).
_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from cpd_tpu.obs.timing import now  # noqa: E402  (the one clock; jax-free)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="cpd_tpu ImageNet Example",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    # reference surface (main.py:21-55)
    p.add_argument("--train-dir", default=None,
                   help="ImageNet root with train/ and val/ (synthetic "
                        "stand-in when absent)")
    p.add_argument("--log-dir", default="./logs")
    p.add_argument("--checkpoint-dir", default="./checkpoints",
                   help="per-epoch checkpoints + auto-resume (the "
                        "checkpoint-{epoch}.pth.tar scan of main.py:70-75)")
    # underscore aliases keep the reference's flag spellings working
    # (mix.py/main.py use --emulate_node/--use_APS/--use_kahan)
    p.add_argument("--emulate-node", "--emulate_node", type=int, default=1)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--val-batch-size", type=int, default=32)
    p.add_argument("--epochs", type=int, default=90)
    p.add_argument("--base-lr", type=float, default=0.0125,
                   help="learning rate for a single chip")
    p.add_argument("--warmup-epochs", type=float, default=5)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--wd", type=float, default=0.0001)
    p.add_argument("--use-APS", "--use_APS", action="store_true")
    p.add_argument("--use-kahan", "--use_kahan", action="store_true")
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--grad_exp", type=int, default=8)
    p.add_argument("--grad_man", type=int, default=23)
    # new surface
    p.add_argument("--arch", default="resnet50")
    p.add_argument("--init-from-torch", default="", type=str,
                   help="warm-start params+BN stats from a torchvision-"
                        "style .pth checkpoint (cpd_tpu.interop converts "
                        "the layout)")
    p.add_argument("--num-classes", default=1000, type=int)
    p.add_argument("--dist", action="store_true")
    p.add_argument("--max-batches-per-epoch", default=None, type=int)
    p.add_argument("--image-size", default=224, type=int)
    p.add_argument("--mode", default="faithful",
                   choices=["faithful", "fast", "ring"],
                   help="faithful: bit-ordered quantized reduction; "
                        "fast: quantize->psum->dequantize; ring: ordered "
                        "quantized reduce-scatter/all-gather ring with "
                        "bit-packed eXmY wire (parallel/ring.py)")
    p.add_argument("--sync-bn", action="store_true",
                   help="compute BN batch statistics across the dp axis "
                        "(per-replica stats, the reference behavior, when "
                        "off)")
    p.add_argument("--zero2", action="store_true",
                   help="ZeRO-2: momentum AND the faithful quantized "
                        "reduction sharded over dp (parallel/zero.py)")
    p.add_argument("--zero3", action="store_true",
                   help="ZeRO-3: params, momentum AND the reduction all "
                        "sharded over dp; params gathered transiently "
                        "per step (parallel/zero.py)")
    p.add_argument("--zero1", action="store_true",
                   help="ZeRO-1: shard the SGD momentum buffer 1/N over "
                        "the dp axis (parallel/zero.py)")
    p.add_argument("--grad-rounding", default="nearest",
                   choices=["nearest", "stochastic"],
                   help="rounding for every gradient-pipeline cast "
                        "(emulate-node + all-reduce — incl. the ZeRO-2/3 "
                        "sharded reduce-scatter, whose offset-indexed SR "
                        "bits match the replicated draw): stochastic = "
                        "unbiased SR (beyond-reference)")
    p.add_argument("--grad-seed", type=int, default=0,
                   help="PRNG seed for --grad-rounding stochastic")
    p.add_argument("--tensorboard", action="store_true",
                   help="also write TensorBoard event files next to the "
                        "JSONL scalars (reference mix.py:16,168-171)")
    p.add_argument("--clip-grad", default=None, type=float,
                   help="global-norm gradient clipping (applied to the "
                        "fully reduced replicated gradients, so local "
                        "norms are exact)")
    p.add_argument("--profile-dir", default=None,
                   help="write a jax.profiler trace of a few steps here")
    return p


def bn_and_bias_no_wd(params):
    """wd_mask: True = apply weight decay.  BN scale/bias and all biases
    are excluded — the param-group split of main.py:123-131."""
    import jax

    def decide(path, leaf):
        names = [getattr(k, "key", getattr(k, "name", "")) for k in path]
        is_bn = any("BatchNorm" in str(n) or str(n) == "batch_stats"
                    for n in names)
        is_bias = names and str(names[-1]) in ("bias", "scale")
        return not (is_bn or is_bias)

    return jax.tree_util.tree_map_with_path(decide, params)


def main(argv=None) -> dict:
    args = build_parser().parse_args(argv)

    import jax
    import jax.numpy as jnp

    from cpd_tpu.data.imagenet import load_imagenet
    from cpd_tpu.data.samplers import DistributedEpochSampler
    from cpd_tpu.models import get_model
    from cpd_tpu.parallel.dist import (dist_init, host_batch_to_global,
                                       replicate)
    from cpd_tpu.parallel.mesh import data_parallel_mesh
    from cpd_tpu.train import (CheckpointManager, PreemptionGuard,
                               create_train_state, loss_diverged,
                               make_eval_step, make_optimizer,
                               make_train_step, preempt_save,
                               warmup_step_decay)
    from cpd_tpu.utils import (ScalarWriter, StepProfiler,
                               format_validation_line)

    rank, world = dist_init() if args.dist else (0, 1)
    mesh = data_parallel_mesh()
    n_dev = mesh.devices.size

    train_ds, val_ds = load_imagenet(args.train_dir, size=args.image_size,
                                     num_classes=args.num_classes)
    global_batch = args.batch_size * n_dev * args.emulate_node
    iters_per_epoch = len(train_ds) // global_batch
    if args.max_batches_per_epoch:
        iters_per_epoch = min(iters_per_epoch, args.max_batches_per_epoch)
    if iters_per_epoch == 0:
        raise ValueError(f"dataset of {len(train_ds)} too small for global "
                         f"batch {global_batch}")

    # main.py:237-252: lr 3.2-style linear-scaled base with 5-epoch warmup
    # from 0.1x, /10 after epochs 30/60/80.  base-lr is per-chip
    # (main.py:38-39 scales by world size x emulate_node).
    scaled_lr = args.base_lr * n_dev * args.emulate_node
    schedule = warmup_step_decay(
        scaled_lr, int(args.warmup_epochs * iters_per_epoch),
        [30 * iters_per_epoch, 60 * iters_per_epoch, 80 * iters_per_epoch],
        warmup_from=scaled_lr / 10.0)

    model = get_model(args.arch, num_classes=args.num_classes,
                      dtype=jnp.bfloat16,
                      **({"bn_axis": "dp"} if args.sync_bn else {}))
    tx = make_optimizer("sgd", schedule, momentum=args.momentum,
                        weight_decay=args.wd, wd_mask=bn_and_bias_no_wd,
                        clip_norm=args.clip_grad)
    state = create_train_state(
        model, tx, jnp.zeros((2, args.image_size, args.image_size, 3)),
        jax.random.PRNGKey(args.seed))
    if args.init_from_torch:
        # Migration path: params + BN stats from a torchvision-style .pth
        # (the reference trains torchvision.models.resnet50(), main.py:67;
        # layout conversion in cpd_tpu.interop, docs/MIGRATING.md)
        from cpd_tpu.interop import (assert_compatible,
                                     import_torchvision_resnet,
                                     load_reference_checkpoint)
        converted = import_torchvision_resnet(
            load_reference_checkpoint(args.init_from_torch))
        assert_compatible(converted, {"params": state.params,
                                      "batch_stats": state.batch_stats})
        state = state.replace(params=converted["params"],
                              batch_stats=converted["batch_stats"])
        if rank == 0:
            print(f"=> imported torch checkpoint {args.init_from_torch}")
    zero = None
    if sum((args.zero1, args.zero2, args.zero3)) > 1:
        raise ValueError("--zero1/--zero2/--zero3 are mutually exclusive")
    if (args.zero2 or args.zero3) and args.mode != "faithful":
        raise ValueError("--zero2/--zero3 shard the faithful reduction; "
                         "--mode fast is not supported with them")
    if args.clip_grad is not None and (args.zero1 or args.zero2
                                       or args.zero3):
        raise ValueError("--clip-grad runs inside the optax chain, which "
                         "the ZeRO updaters bypass — unsupported together")
    if args.zero1:
        from cpd_tpu.parallel.zero import zero1_sgd
        zero = zero1_sgd(schedule, world=n_dev, momentum=args.momentum,
                         weight_decay=args.wd, wd_mask=bn_and_bias_no_wd)
        state = state.replace(opt_state=zero.init(state.params))
    elif args.zero2:
        from cpd_tpu.parallel.zero import zero2_sgd
        zero = zero2_sgd(schedule, world=n_dev, momentum=args.momentum,
                         weight_decay=args.wd, wd_mask=bn_and_bias_no_wd)
        state = state.replace(opt_state=zero.init(state.params))
    elif args.zero3:
        from cpd_tpu.parallel.zero import zero3_sgd
        zero = zero3_sgd(schedule, world=n_dev, template=state.params,
                         momentum=args.momentum, weight_decay=args.wd,
                         wd_mask=bn_and_bias_no_wd)
        # state stays in the pytree layout until after restore; checkpoints
        # are saved/restored in zero.export_state's PORTABLE layout so they
        # survive world-size changes and stay readable without --zero3

    manager = CheckpointManager(os.path.abspath(args.checkpoint_dir),
                                track_best=True)
    start_epoch = 0
    start_it = 0
    # Auto-resume must not silently overwrite an explicitly requested torch
    # import — an explicit --init-from-torch run starts from the .pth
    # ALL ZeRO stages checkpoint in the portable layout (round 5 for
    # zero1/2: pad-trimmed momentum restores at any device count)
    restored = None if args.init_from_torch else manager.restore(
        zero.portable_template(state) if zero else state)
    if restored is not None:                 # auto-resume (main.py:70-75)
        # import_state is idempotent-safe for every stage (for --zero3
        # the params are still the pytree here; make_state repacks)
        state = zero.import_state(restored) if zero else restored
        meta = manager.metadata()
        if meta is not None and "resume_it" in meta:
            # preemption checkpoint: continue the interrupted epoch at the
            # exact iteration (the epoch-seeded sampler order is
            # deterministic, so no batch is trained twice or skipped).
            # Exactness requires the SAME iteration geometry — if batch
            # size / device count / --max-batches-per-epoch changed, the
            # saved iteration indexes different samples, so restart the
            # interrupted epoch from 0 instead (re-training part of it,
            # like the reference's per-epoch resume, main.py:70-75).
            start_epoch = int(meta["epoch"])
            same_geometry = (
                int(meta.get("iters_per_epoch", -1)) == iters_per_epoch
                and int(meta.get("global_batch", -1)) == global_batch
                and int(meta.get("world", -1)) == world)
            if same_geometry:
                start_it = int(meta["resume_it"])
            elif rank == 0:
                print("=> iteration geometry changed since preemption; "
                      "restarting the interrupted epoch from iter 0")
        elif meta is not None and "epoch" in meta:
            # exact epoch from checkpoint metadata — robust to batch size /
            # device count / --max-batches-per-epoch changing between runs
            start_epoch = int(meta["epoch"]) + 1
        else:
            # no sidecar: derive from the iteration counter inside the
            # restored state itself — never from how the checkpoint file
            # happened to be numbered (mis-guessing the numbering scheme
            # resumed at the wrong epoch; round-2 review finding)
            start_epoch = int(restored.step) // max(iters_per_epoch, 1)
        if rank == 0:
            at = f" iter {start_it}" if start_it else ""
            print(f"=> auto-resumed from epoch {start_epoch}{at}")
    # orbax restores arrays committed to a single device; the train step's
    # shard_map needs the state laid out over the mesh (replicated, except
    # the ZeRO-1 momentum which is dp-sharded)
    if zero is None:
        state = replicate(state, mesh)
        extra = {}
    elif args.zero3:
        # packs params, re-pads a restored portable momentum (or zeros a
        # fresh one), and lays the whole state out dp-sharded
        state = zero.make_state(state, mesh)
        extra = {"update_fn": zero.update_fn,
                 "opt_state_spec": zero.state_spec(),
                 "params_spec": zero.param_spec(),
                 "unpack_params": zero.unpack,
                 "reduce_in_update": True}
    else:
        state, extra = zero.mesh_layout(state, mesh)

    train_step = make_train_step(
        model, tx, mesh, emulate_node=args.emulate_node,
        use_aps=args.use_APS, grad_exp=args.grad_exp,
        grad_man=args.grad_man, use_kahan=args.use_kahan, mode=args.mode,
        grad_rounding=args.grad_rounding, grad_seed=args.grad_seed,
        **extra)
    # checkpoints always persist the portable layout under any ZeRO stage
    to_ckpt = zero.export_state if zero else (lambda s: s)
    eval_step = make_eval_step(model, mesh)
    if args.zero3:
        # eval consumes the pytree layout; one jitted unflatten per
        # validation pass rebuilds it from the flat shards
        _unpack_eval = jax.jit(zero.to_pytree)
        eval_view = lambda s: s.replace(params=_unpack_eval(s.params))  # noqa: E731
    else:
        eval_view = lambda s: s                                         # noqa: E731

    writer = ScalarWriter(args.log_dir, rank=rank,
                          tensorboard=args.tensorboard)
    # Per-host epoch-seeded shuffle: each host draws its strided 1/world of
    # the epoch permutation (main.py:111-120's DistributedSampler contract).
    sampler = DistributedEpochSampler(len(train_ds), world_size=world,
                                      rank=rank)
    host_batch = global_batch // world
    val_bs = args.val_batch_size * n_dev
    val_host = val_bs // world
    result = {}
    profiler = StepProfiler(args.profile_dir, start=3)
    # SIGTERM (spot-VM preemption / maintenance) → checkpoint at the next
    # step boundary with the exact (epoch, iteration) and exit cleanly;
    # auto-resume above continues mid-epoch without re-training a batch.
    guard = PreemptionGuard()
    preempted = False
    diverged = False
    global_it = 0
    try:
        for epoch in range(start_epoch, args.epochs):
            sampler.set_epoch(epoch)
            order = np.fromiter(iter(sampler), np.int64)
            t0 = now()
            train_loss = train_acc = 0.0
            epoch_start = start_it if epoch == start_epoch else 0
            n_done = 0
            def produced(epoch=epoch, epoch_start=epoch_start, order=order):
                # host-side batch prep (the augmentation runs in the
                # native threaded executor) on a background thread, two
                # steps ahead of the device — the torch-DataLoader-worker
                # analog (main.py:111-120), same recipe as the CIFAR
                # trainer
                for i in range(epoch_start, iters_per_epoch):
                    idx = order[i * host_batch:(i + 1) * host_batch]
                    bx, by = train_ds.batch(idx, seed=epoch)
                    yield (host_batch_to_global(bx.astype(np.float32),
                                                mesh),
                           host_batch_to_global(by, mesh))

            from cpd_tpu.utils.prefetch import Prefetcher
            batches = Prefetcher(produced(), depth=2)
            for it, (gx, gy) in enumerate(batches, start=epoch_start):
                if guard.should_stop():      # collective when multi-host
                    preempt_save(
                        manager, state.step, to_ckpt(state), rank,
                        what="step",
                        metadata={"epoch": epoch, "resume_it": it,
                                  "iters_per_epoch": iters_per_epoch,
                                  "global_batch": global_batch,
                                  "world": world})
                    if rank == 0:
                        print(f"   (epoch {epoch} iter {it})")
                    preempted = True
                    batches.close()
                    break
                global_it += 1
                profiler.step(global_it)
                state, m = train_step(state, gx, gy)
                step_loss = float(m["loss"])
                if loss_diverged(step_loss, f"epoch {epoch} iter {it}",
                                 rank, hint="try --use-APS / more "
                                            "mantissa bits"):
                    diverged = True
                    batches.close()
                    break
                train_loss += step_loss
                train_acc += float(m["accuracy"])
                n_done += 1
            if preempted or diverged:
                break
            jax.block_until_ready(state.params)
            dt = now() - t0
            n_done = max(n_done, 1)
            imgs_per_sec = n_done * global_batch / dt

            # validate (main.py:215-235)
            val_loss = val_top1 = val_top5 = 0.0
            k = 0
            n_val = (len(val_ds) // val_bs) * val_bs
            eval_state = eval_view(state)
            for lo in range(0, n_val, val_bs):
                sel = np.arange(lo + rank * val_host, lo + (rank + 1) * val_host)
                x, y = val_ds.batch(sel)
                m = eval_step(eval_state,
                              host_batch_to_global(x.astype(np.float32), mesh),
                              host_batch_to_global(y, mesh))
                val_loss += float(m["loss"])
                val_top1 += float(m["top1"])
                val_top5 += float(m["top5"])
                k += 1
            k = max(k, 1)
            result = {
                "epoch": epoch, "train_loss": train_loss / n_done,
                "train_acc": train_acc / n_done,
                "val_loss": val_loss / k, "val_top1": val_top1 / k,
                "val_top5": val_top5 / k, "img_per_sec": imgs_per_sec,
            }
            if rank == 0:
                print(f"Epoch {epoch}: loss {result['train_loss']:.4f} "
                      f"acc {100*result['train_acc']:.2f} "
                      f"({imgs_per_sec:.1f} img/s)")
                print(format_validation_line(result["val_loss"],
                                             100 * result["val_top1"],
                                             100 * result["val_top5"]))
            writer.add_scalar("train/loss", result["train_loss"], epoch)
            writer.add_scalar("val/top1", result["val_top1"], epoch)
            # per-epoch checkpoint keyed by the TRUE global step: monotonic no
            # matter how earlier checkpoints in the directory were numbered, so
            # a resumed run can never be shadowed by a stale higher-numbered
            # file.  The reference's epoch-named files (checkpoint-{epoch}
            # .pth.tar, main.py:261-269) are matched in behavior — one
            # checkpoint per epoch, auto-resume — with the epoch recorded in
            # sidecar metadata instead of the filename.
            manager.save(int(state.step), to_ckpt(state),
                         best_metric=100 * result["val_top1"],
                         metadata={"epoch": epoch,
                                   "iters_per_epoch": iters_per_epoch})
    finally:
        guard.uninstall()
        if "batches" in locals():
            batches.close()   # stop the producer on any exception path
        # stops an in-flight jax.profiler trace even when the loop died
        # inside the window (ISSUE 11 satellite — a leaked running
        # trace poisons every later start_trace in the process)
        profiler.close()
    manager.wait()
    manager.close()
    writer.close()
    result["diverged"] = diverged
    return result


if __name__ == "__main__":
    res = main()
    sys.exit(3 if res.get("diverged") else 0)
