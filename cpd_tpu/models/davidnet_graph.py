"""DavidNet defined through the dict-graph API — reference definition parity.

The reference builds DavidNet as a nested dict of nodes (reference:
example/DavidNet/davidnet.py:19-63 — ``conv_bn`` / ``residual`` /
``basic_net`` / ``net``) plus a losses dict (davidnet.py:66-69), executed
by TorchGraph.  `cpd_tpu.models.davidnet.DavidNet` is the idiomatic-Flax
form of the same network; this module reproduces the *definition style*
itself on top of :mod:`cpd_tpu.utils.graph`, so users porting reference
code that composes nets as dicts (extra_layers, res_layers, custom heads)
keep that workflow.

Architecture identity with ``DavidNet`` is asserted in
tests/test_graph.py (same param count, same logit shape, trains under the
standard harness via ``GraphClassifier``).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Mapping

import flax.linen as nn
import jax.numpy as jnp

from ..utils.graph import (Add, Correct, CrossEntropySum, Flatten,
                           GraphClassifier, GraphModule, Identity, Mul,
                           rel_path, union)
from .davidnet import (BN_EPSILON, BN_MOMENTUM, DEFAULT_CHANNELS,
                       LOGIT_WEIGHT)

__all__ = ["conv_bn", "residual", "basic_net", "davidnet_net",
           "davidnet_losses", "graph_davidnet"]


class _GraphBatchNorm(nn.Module):
    """BN node taking the executor's ``train`` flag (batch_norm,
    reference utils.py:214-226: weight init + momentum/eps defaults)."""

    bn_weight_init: float = 1.0
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        return nn.BatchNorm(
            use_running_average=not train, momentum=BN_MOMENTUM,
            epsilon=BN_EPSILON, dtype=self.dtype,
            param_dtype=self.param_dtype,
            scale_init=nn.initializers.constant(self.bn_weight_init))(x)


def conv_bn(c_out: int, bn_weight_init: float = 1.0,
            dtype=jnp.float32, param_dtype=jnp.float32) -> dict:
    """conv3x3(no bias) -> bn -> relu as three graph nodes
    (davidnet.py:19-24)."""
    return {
        "conv": nn.Conv(c_out, (3, 3), padding=1, use_bias=False,
                        dtype=dtype, param_dtype=param_dtype,
                        kernel_init=nn.initializers.kaiming_normal()),
        "bn": _GraphBatchNorm(bn_weight_init=bn_weight_init, dtype=dtype,
                              param_dtype=param_dtype),
        "relu": nn.relu,
    }


def residual(c: int, **kw) -> dict:
    """identity + two conv_bn blocks + add (davidnet.py:27-33)."""
    return {
        "in": Identity(),
        "res1": conv_bn(c, **kw),
        "res2": conv_bn(c, **kw),
        "add": (Add(), [rel_path("in"), rel_path("res2", "relu")]),
    }


def basic_net(channels: Mapping[str, int], weight: float, pool,
              **kw) -> dict:
    """Prep + three pooled stages + classifier head (davidnet.py:36-48)."""
    return {
        "prep": conv_bn(channels["prep"], **kw),
        "layer1": dict(conv_bn(channels["layer1"], **kw), pool=pool),
        "layer2": dict(conv_bn(channels["layer2"], **kw), pool=pool),
        "layer3": dict(conv_bn(channels["layer3"], **kw), pool=pool),
        "classifier": {
            "pool": partial(nn.max_pool, window_shape=(4, 4),
                            strides=(4, 4)),
            "flatten": Flatten(),
            # fp32 head regardless of compute dtype — DavidNet parity
            # (davidnet.py: Dense dtype=fp32 + final fp32 cast), so bf16
            # graph models still emit fp32 logits for the loss.
            "linear": nn.Dense(10, use_bias=False, dtype=jnp.float32,
                               param_dtype=kw.get("param_dtype",
                                                  jnp.float32)),
            "logits": Mul(weight),
        },
    }


def davidnet_net(channels: Mapping[str, int] | None = None,
                 weight: float = LOGIT_WEIGHT, pool=None, extra_layers=(),
                 res_layers=("layer1", "layer3"), **kw) -> dict:
    """The full DavidNet nested dict (davidnet.py:51-63): residual blocks
    on layer1/layer3, optional extra conv_bn blocks per stage."""
    channels = channels or DEFAULT_CHANNELS
    pool = pool or partial(nn.max_pool, window_shape=(2, 2), strides=(2, 2))
    n = basic_net(channels, weight, pool, **kw)
    for layer in res_layers:
        n[layer]["residual"] = residual(channels[layer], **kw)
    for layer in extra_layers:
        n[layer]["extra"] = conv_bn(channels[layer], **kw)
    return n


def davidnet_losses() -> dict:
    """Loss/metric nodes living in the graph (davidnet.py:66-69)."""
    return {
        "loss": (CrossEntropySum(),
                 [("classifier", "logits"), ("target",)]),
        "correct": (Correct(), [("classifier", "logits"), ("target",)]),
    }


def graph_davidnet(with_losses: bool = False, dtype=jnp.float32,
                   **net_kw) -> nn.Module:
    """DavidNet built from the dict-graph definition.

    with_losses=False returns a ``GraphClassifier`` (logits out — drops
    into ``make_train_step`` like any zoo model); with_losses=True returns
    the raw ``GraphModule`` whose call yields the full cache including
    ``loss``/``correct`` nodes, the reference's TorchGraph usage shape.
    """
    def build():
        net = davidnet_net(dtype=dtype, **net_kw)
        return union(net, davidnet_losses()) if with_losses else net

    if with_losses:
        return GraphModule(build)
    return GraphClassifier(build, output="classifier_logits")
