"""DavidNet — the DAWNBench fast-CIFAR10 network.

Capability parity with reference `example/DavidNet/davidnet.py`: prep
conv-bn-relu at 64ch, three stages at 128/256/512 each = conv-bn-relu +
2x2 max-pool, residual (two conv-bn-relu) on layers 1 and 3, classifier =
4x4 max-pool -> flatten -> 512->10 linear (no bias) -> x0.125 logit scale
(davidnet.py:19-62).

The reference expresses this as a nested-dict dataflow graph executed
topologically by `TorchGraph` (utils.py:258-292); SURVEY.md §7.6 notes the
dict-graph executor is incidental, not a capability — here it is a plain
Flax module, which XLA fuses better anyway.  BatchNorm weight-init and the
fixed 0.125 logit multiplier are preserved (davidnet.py:20,33).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Mapping

import flax.linen as nn
import jax.numpy as jnp

__all__ = ["DavidNet", "davidnet", "DEFAULT_CHANNELS", "BN_MOMENTUM",
           "BN_EPSILON", "LOGIT_WEIGHT"]

# Shared with the dict-graph definition (models/davidnet_graph.py) so the
# two forms of the same network cannot drift apart.
DEFAULT_CHANNELS = {"prep": 64, "layer1": 128, "layer2": 256, "layer3": 512}
BN_MOMENTUM = 0.9
BN_EPSILON = 1e-5
LOGIT_WEIGHT = 0.125  # davidnet.py:52 (weight=0.125)


class ConvBN(nn.Module):
    """conv3x3(no bias) + BN(+optional weight init) + ReLU (davidnet.py:19-24)."""
    channels: int
    bn_weight_init: float = 1.0
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(self.channels, (3, 3), padding=1, use_bias=False,
                    dtype=self.dtype, param_dtype=self.param_dtype,
                    kernel_init=nn.initializers.kaiming_normal())(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=BN_MOMENTUM,
                         epsilon=BN_EPSILON, dtype=self.dtype,
                         param_dtype=self.param_dtype,
                         scale_init=nn.initializers.constant(
                             self.bn_weight_init))(x)
        return nn.relu(x)


class Residual(nn.Module):
    """x + conv_bn(conv_bn(x)) (davidnet.py:27-33)."""
    channels: int
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        cb = partial(ConvBN, self.channels, dtype=self.dtype,
                     param_dtype=self.param_dtype)
        y = cb(name="res1")(x, train=train)
        y = cb(name="res2")(y, train=train)
        return x + y


class DavidNet(nn.Module):
    """Input NHWC (B, 32, 32, 3); returns scaled logits (B, 10)."""
    num_classes: int = 10
    channels: Mapping[str, int] = None
    logit_weight: float = LOGIT_WEIGHT
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        ch = self.channels or DEFAULT_CHANNELS
        cb = partial(ConvBN, dtype=self.dtype, param_dtype=self.param_dtype)
        pool = partial(nn.max_pool, window_shape=(2, 2), strides=(2, 2))

        x = cb(ch["prep"], name="prep")(x, train=train)
        x = pool(cb(ch["layer1"], name="layer1")(x, train=train))
        x = Residual(ch["layer1"], dtype=self.dtype,
                     param_dtype=self.param_dtype,
                     name="layer1_residual")(x, train=train)
        x = pool(cb(ch["layer2"], name="layer2")(x, train=train))
        x = pool(cb(ch["layer3"], name="layer3")(x, train=train))
        x = Residual(ch["layer3"], dtype=self.dtype,
                     param_dtype=self.param_dtype,
                     name="layer3_residual")(x, train=train)

        x = nn.max_pool(x, (4, 4), strides=(4, 4))
        x = x.reshape(x.shape[0], -1)
        x = nn.Dense(self.num_classes, use_bias=False, dtype=jnp.float32,
                     param_dtype=self.param_dtype, name="linear")(x)
        return (x * self.logit_weight).astype(jnp.float32)


def davidnet(dtype=jnp.float32) -> DavidNet:
    return DavidNet(dtype=dtype)
