"""Autoregressive generation for the transformer LM (KV-cache decode).

Inference capability beyond the reference (CNN-only, SURVEY.md §5).
TPU-first shape discipline: the KV cache is allocated once at the full
``prompt + max_new_tokens`` length, prefill is ONE forward over the whole
prompt (one MXU-friendly batch matmul, not a Python loop), and the decode
loop is a single ``lax.scan`` of one-token steps — the whole thing traces
into one jitted program with static shapes.

Usage:

    model = transformer_lm(vocab_size=..., ...)          # trained as usual
    params = state.params
    out = generate(model, params, prompt_tokens, max_new_tokens=32,
                   temperature=0.0, rng=jax.random.PRNGKey(0))
    # out: (B, T_prompt + max_new_tokens) int32

``temperature=0`` is greedy argmax; ``temperature>0`` samples from
``softmax(logits / temperature)`` (requires ``rng``), optionally
restricted by ``top_k`` (k highest-logit tokens) and/or ``top_p``
(smallest nucleus whose probability mass reaches p) — both applied as
static masks inside the jitted program.  ``eos_id`` freezes a sequence
once it emits that token (subsequent positions repeat ``eos_id``; the
scan still runs to static length, as TPU shapes demand).  Decode is
single-device (the training-time sp/tp shardings do not apply; pass the
plain unsharded module).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.cache import LRUCache

__all__ = ["generate", "filter_logits"]

_NEG_INF = jnp.float32(-1e30)


def filter_logits(logits: jnp.ndarray, top_k: Optional[int] = None,
                  top_p: Optional[float] = None) -> jnp.ndarray:
    """Mask logits (..., V) to the top-k set and/or the top-p nucleus.

    top-k: keep the k highest logits.  top-p: keep the SMALLEST prefix of
    the probability-sorted vocabulary whose cumulative mass reaches p
    (the standard nucleus rule — the token that crosses the threshold is
    kept).  Masked entries become -1e30, so a later softmax/categorical
    assigns them zero probability.  Pure and jit-safe; k and p are
    trace-time constants."""
    if top_k is not None:
        if top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {top_k}")
        if top_k < logits.shape[-1]:
            kth = lax.top_k(logits, top_k)[0][..., -1, None]
            logits = jnp.where(logits < kth, _NEG_INF, logits)
    if top_p is not None:
        if not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        if top_p < 1.0:
            # one full descending sort (top_k(V)); when top_k also ran,
            # its mask is already folded into `logits`, so the nucleus is
            # taken within the top-k set (the standard composition)
            sorted_desc = lax.top_k(logits, logits.shape[-1])[0]
            probs = jax.nn.softmax(sorted_desc, axis=-1)
            # exclusive cumulative mass BEFORE each sorted token; a token
            # is kept while that mass is still < p (so the crossing token
            # stays in)
            before = jnp.cumsum(probs, axis=-1) - probs
            keep = before < top_p
            # cutoff = smallest kept logit; everything below is masked
            cutoff = jnp.min(jnp.where(keep, sorted_desc, jnp.inf),
                             axis=-1, keepdims=True)
            logits = jnp.where(logits < cutoff, _NEG_INF, logits)
    return logits


# Bounded host-side caches (utils/cache.LRUCache, the
# make_sum_gradients_fn precedent): the old functools.lru_cache pair
# held strong references to decoder modules AND their jitted closures
# forever — a serving process cycling through model/sampling configs
# leaked every one of them.  Eviction just drops a compiled program;
# the next call with that config re-traces.
_SHAPE_CACHE = LRUCache(maxsize=32)
_RUN_CACHE = LRUCache(maxsize=32)


def _cache_shapes(decoder, b: int, t_max: int):
    """Shapes/dtypes of the decoder's cache collection, via eval_shape —
    memoized so repeat generate() calls skip the host-side init retrace
    (the arrays themselves are rebuilt per call; their contents are the
    defined zero state)."""
    return _SHAPE_CACHE.get_or_create(
        (decoder, b, t_max),
        lambda: jax.eval_shape(
            lambda t: decoder.init(jax.random.PRNGKey(0), t, train=False),
            jax.ShapeDtypeStruct((b, t_max), jnp.int32))["cache"])


def _make_run(decoder, max_new_tokens: int, temperature: float,
              top_k: Optional[int], top_p: Optional[float],
              eos_id: Optional[int]):
    """Build the jitted prefill+scan program once per (module, length,
    sampling config) — flax modules hash by their field values, so repeat
    generate() calls hit the bounded run cache instead of recompiling."""

    def build():
        def sample(logits_last, key):
            if temperature == 0:
                if top_k is not None or top_p is not None:
                    raise ValueError(
                        "top_k/top_p require temperature > 0 (greedy "
                        "argmax is unaffected by the filtered tail)")
                return jnp.argmax(logits_last, axis=-1).astype(jnp.int32)
            logits = filter_logits(logits_last / jnp.float32(temperature),
                                   top_k, top_p)
            return jax.random.categorical(key, logits,
                                          axis=-1).astype(jnp.int32)

        def freeze(tok, done):
            """Once a sequence emitted eos, it keeps emitting eos."""
            if eos_id is None:
                return tok, (jnp.zeros(tok.shape, bool)
                             if done is None else done)
            done = ((tok == eos_id) if done is None
                    else done | (tok == eos_id))
            return jnp.where(done, jnp.int32(eos_id), tok), done

        @jax.jit
        def run(params, cache, prompt, rng):
            # one-pass prefill over the whole prompt
            logits, mut = decoder.apply({"params": params, "cache": cache},
                                        prompt, train=False,
                                        mutable=["cache"])
            key0, rng = jax.random.split(rng)
            first, done = freeze(sample(logits[:, -1], key0), None)

            def step(carry, _):
                cache, tok, done, rng = carry
                key, rng = jax.random.split(rng)
                logits, mut = decoder.apply(
                    {"params": params, "cache": cache}, tok[:, None],
                    train=False, mutable=["cache"])
                nxt, done = freeze(sample(logits[:, -1], key), done)
                return (mut["cache"], nxt, done, rng), tok

            # each step emits its input token and computes the next; the
            # final carry token is the max_new-th generated token
            (_, last, _, _), toks = lax.scan(
                step, (mut["cache"], first, done, rng), None,
                length=max_new_tokens - 1)
            new = jnp.concatenate([toks.transpose(1, 0), last[:, None]],
                                  axis=1)
            return jnp.concatenate([prompt, new], axis=1)

        return run

    return _RUN_CACHE.get_or_create(
        (decoder, max_new_tokens, temperature, top_k, top_p, eos_id),
        build)


def generate(model, params, prompt: jnp.ndarray, max_new_tokens: int,
             temperature: float = 0.0, top_k: Optional[int] = None,
             top_p: Optional[float] = None, eos_id: Optional[int] = None,
             rng: Optional[jax.Array] = None,
             t_max: Optional[int] = None) -> jnp.ndarray:
    """Generate ``max_new_tokens`` continuations of ``prompt`` (B, T_p).

    Returns (B, T_p + max_new_tokens) int32 — prompt included.  With
    ``eos_id``, positions after a sequence's first eos all hold eos_id.

    ``t_max`` is an optional deployment capacity (the longest sequence
    the caller's model/memory budget allows): when given, a request
    whose ``prompt + max_new_tokens`` exceeds it raises ValueError HERE
    — fail-fast at the API boundary, not a silent mid-scan
    clip/NaN-poison from the cache layer (the serving engine applies
    the same rule at `submit`, scheduler.validate).
    """
    if temperature < 0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if temperature > 0 and rng is None:
        raise ValueError("temperature > 0 requires an rng key")
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    # validate eagerly (filter_logits re-checks at trace time)
    if top_k is not None and top_k < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k}")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if temperature == 0 and (top_k is not None or top_p is not None):
        raise ValueError("top_k/top_p require temperature > 0")
    prompt = jnp.asarray(prompt, jnp.int32)
    b, t_p = prompt.shape
    t_total = t_p + max_new_tokens
    if t_max is not None and t_total > t_max:
        raise ValueError(
            f"prompt length ({t_p}) + max_new_tokens ({max_new_tokens}) "
            f"= {t_total} exceeds t_max ({t_max})")

    decoder = model.clone(decode=True, sp_axis=None, tp_axis=None,
                          tp_size=1)
    # allocate the cache at full length (Block._cached_attention takes its
    # cache shape from the init call) WITHOUT running the forward:
    # eval_shape (memoized) gives the cache pytree's shapes/dtypes for
    # free, and the initial cache contents are defined zeros
    shapes = _cache_shapes(decoder, b, t_total)
    cache0 = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)
    # carry needs an array either way; greedy sampling ignores it
    rng = jax.random.PRNGKey(0) if rng is None else rng

    run = _make_run(decoder, max_new_tokens, float(temperature),
                    top_k, top_p, eos_id)
    return run(params, cache0, prompt, rng)
