"""Autoregressive generation for the transformer LM (KV-cache decode).

Inference capability beyond the reference (CNN-only, SURVEY.md §5).
TPU-first shape discipline: the KV cache is allocated once at the full
``prompt + max_new_tokens`` length, prefill is ONE forward over the whole
prompt (one MXU-friendly batch matmul, not a Python loop), and the decode
loop is a single ``lax.scan`` of one-token steps — the whole thing traces
into one jitted program with static shapes.

Usage:

    model = transformer_lm(vocab_size=..., ...)          # trained as usual
    params = state.params
    out = generate(model, params, prompt_tokens, max_new_tokens=32,
                   temperature=0.0, rng=jax.random.PRNGKey(0))
    # out: (B, T_prompt + max_new_tokens) int32

``temperature=0`` is greedy argmax; ``temperature>0`` samples from
``softmax(logits / temperature)`` (requires ``rng``).  Decode is
single-device (the training-time sp/tp shardings do not apply; pass the
plain unsharded module).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["generate"]


@functools.lru_cache(maxsize=32)
def _cache_shapes(decoder, b: int, t_max: int):
    """Shapes/dtypes of the decoder's cache collection, via eval_shape —
    memoized so repeat generate() calls skip the host-side init retrace
    (the arrays themselves are rebuilt per call; their contents are the
    defined zero state)."""
    return jax.eval_shape(
        lambda t: decoder.init(jax.random.PRNGKey(0), t, train=False),
        jax.ShapeDtypeStruct((b, t_max), jnp.int32))["cache"]


@functools.lru_cache(maxsize=32)
def _make_run(decoder, max_new_tokens: int, temperature: float):
    """Build the jitted prefill+scan program once per (module, length,
    temperature) — flax modules hash by their field values, so repeat
    generate() calls hit jit's trace cache instead of recompiling."""

    def sample(logits_last, key):
        if temperature == 0:
            return jnp.argmax(logits_last, axis=-1).astype(jnp.int32)
        return jax.random.categorical(
            key, logits_last / jnp.float32(temperature), axis=-1
        ).astype(jnp.int32)

    @jax.jit
    def run(params, cache, prompt, rng):
        # one-pass prefill over the whole prompt
        logits, mut = decoder.apply({"params": params, "cache": cache},
                                    prompt, train=False, mutable=["cache"])
        key0, rng = jax.random.split(rng)
        first = sample(logits[:, -1], key0)

        def step(carry, _):
            cache, tok, rng = carry
            key, rng = jax.random.split(rng)
            logits, mut = decoder.apply(
                {"params": params, "cache": cache}, tok[:, None],
                train=False, mutable=["cache"])
            nxt = sample(logits[:, -1], key)
            return (mut["cache"], nxt, rng), tok

        # each step emits its input token and computes the next; the final
        # carry token is the max_new-th generated token
        (_, last, _), toks = lax.scan(
            step, (mut["cache"], first, rng), None,
            length=max_new_tokens - 1)
        new = jnp.concatenate([toks.transpose(1, 0), last[:, None]], axis=1)
        return jnp.concatenate([prompt, new], axis=1)

    return run


def generate(model, params, prompt: jnp.ndarray, max_new_tokens: int,
             temperature: float = 0.0,
             rng: Optional[jax.Array] = None) -> jnp.ndarray:
    """Generate ``max_new_tokens`` continuations of ``prompt`` (B, T_p).

    Returns (B, T_p + max_new_tokens) int32 — prompt included.
    """
    if temperature < 0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if temperature > 0 and rng is None:
        raise ValueError("temperature > 0 requires an rng key")
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    prompt = jnp.asarray(prompt, jnp.int32)
    b, t_p = prompt.shape
    t_max = t_p + max_new_tokens

    decoder = model.clone(decode=True, sp_axis=None, tp_axis=None,
                          tp_size=1)
    # allocate the cache at full length (Block._cached_attention takes its
    # cache shape from the init call) WITHOUT running the forward:
    # eval_shape (memoized) gives the cache pytree's shapes/dtypes for
    # free, and the initial cache contents are defined zeros
    shapes = _cache_shapes(decoder, b, t_max)
    cache0 = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)
    # carry needs an array either way; greedy sampling ignores it
    rng = jax.random.PRNGKey(0) if rng is None else rng

    run = _make_run(decoder, max_new_tokens, float(temperature))
    return run(params, cache0, prompt, rng)
