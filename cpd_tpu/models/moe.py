"""Mixture-of-Experts LM with expert parallelism (the `ep` mesh axis).

New capability beyond the reference (SURVEY.md §2: EP "Absent"; round-1
review: the ep axis was a placeholder).  Switch-Transformer-style top-1
routing with static shapes throughout — the TPU constraint that shapes be
known at compile time is met with the classic capacity trick:

    capacity C = ceil(capacity_factor * local_tokens / n_experts)
    each expert accepts at most C tokens per rank; overflow tokens pass
    through the residual unchanged (their gate contribution is dropped).

Parallel layout (mesh dp x ep):

* tokens are sharded over BOTH dp and ep for every layer — ep doubles as
  a data axis outside the expert computation;
* expert weights are stacked (n_experts, ...) and sharded P("ep", ...):
  each ep rank owns n_experts/ep consecutive experts;
* dispatch: tokens are binned into per-expert capacity buffers on every
  rank, then ONE `lax.all_to_all` over ep ships each expert's buffers to
  its owner; the owner applies its local experts (a vmapped batched
  matmul — one big MXU-friendly einsum, not a loop); a reverse
  all_to_all brings results home for the gated combine.

Router/attention/norm params are replicated over ep; their gradients need
a `psum` over ep (train/moe.py), while expert-weight gradients are already
complete on the owning rank.
"""

from __future__ import annotations

import functools
import math
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .transformer import Block

__all__ = ["MoEFeedForward", "MoETransformerLM", "moe_lm", "moe_param_specs"]


class MoEFeedForward(nn.Module):
    """Top-1 routed expert MLP.  Input/output: (B, T, d_model)."""
    d_model: int
    d_ff: int
    n_experts: int          # GLOBAL expert count
    ep_axis: Optional[str]
    ep_size: int            # 1 at init; the mesh's ep size inside shard_map
    capacity_factor: float = 2.0
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        b, t, d = x.shape
        s = b * t                      # local tokens
        e_local = self.n_experts // self.ep_size
        # stacked expert weights; ep slices the leading axis
        wi = self.param("wi", nn.initializers.lecun_normal(),
                        (e_local, d, self.d_ff), self.param_dtype)
        wo = self.param("wo", nn.initializers.lecun_normal(),
                        (e_local, self.d_ff, d), self.param_dtype)

        tokens = x.reshape(s, d)
        # router is replicated; computed over the GLOBAL expert range
        logits = nn.Dense(self.n_experts, use_bias=False, dtype=self.dtype,
                          name="router")(tokens)
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        expert = jnp.argmax(probs, axis=-1)               # (S,)
        gate = jnp.max(probs, axis=-1)                    # (S,)

        capacity = max(1, math.ceil(self.capacity_factor * s
                                    / self.n_experts))
        onehot = jax.nn.one_hot(expert, self.n_experts,
                                dtype=jnp.float32)        # (S, E)
        # position of each token within its expert's buffer (0-based)
        pos = (jnp.cumsum(onehot, axis=0) * onehot).sum(axis=1) - 1.0
        keep = pos < capacity
        slot = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                              dtype=jnp.float32)          # (S, C)
        # (S, E, C) dispatch tensor: token s -> (expert e, slot c)
        dispatch = onehot[:, :, None] * slot[:, None, :] \
            * keep[:, None, None]
        buffers = jnp.einsum("sec,sd->ecd", dispatch,
                             tokens.astype(jnp.float32)).astype(self.dtype)

        if self.ep_axis and self.ep_size > 1:
            # (E, C, D) -> (E/P, P*C, D): every rank ends up with ITS
            # experts' buffers from all ep ranks
            buffers = lax.all_to_all(buffers, self.ep_axis, split_axis=0,
                                     concat_axis=1, tiled=True)

        h = jnp.einsum("ecd,edf->ecf", buffers, wi.astype(self.dtype))
        h = nn.gelu(h)
        out = jnp.einsum("ecf,efd->ecd", h, wo.astype(self.dtype))

        if self.ep_axis and self.ep_size > 1:
            # reverse: (E/P, P*C, D) -> (E, C, D)
            out = lax.all_to_all(out, self.ep_axis, split_axis=1,
                                 concat_axis=0, tiled=True)

        combine = dispatch * gate[:, None, None]          # (S, E, C)
        y = jnp.einsum("sec,ecd->sd", combine, out.astype(jnp.float32))
        # auxiliary load-balancing loss (Switch eq. 4): mean gate mass per
        # expert x fraction of tokens routed there, scaled by E
        density = onehot.mean(axis=0)
        density_proxy = probs.mean(axis=0)
        self.sow("intermediates", "aux_loss",
                 jnp.sum(density * density_proxy) * self.n_experts)
        return y.reshape(b, t, d).astype(self.dtype)


class MoETransformerLM(nn.Module):
    """Decoder-only MoE LM.  (B, T_local) int32 -> (B, T_local, vocab)."""
    vocab_size: int = 32000
    d_model: int = 256
    n_layers: int = 2
    n_heads: int = 4
    d_ff: int = 512
    n_experts: int = 4
    ep_axis: Optional[str] = None
    ep_size: int = 1
    capacity_factor: float = 2.0
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, tokens, train: bool = True):
        del train
        positions = jnp.arange(tokens.shape[1])
        emb = nn.Embed(self.vocab_size, self.d_model, dtype=self.dtype,
                       param_dtype=self.param_dtype, name="embed")
        x = emb(tokens)
        # reuse transformer.Block's attention half wholesale; only the MLP
        # is swapped for the routed experts (Block.mlp factory)
        moe_factory = functools.partial(
            MoEFeedForward, d_model=self.d_model, d_ff=self.d_ff,
            n_experts=self.n_experts, ep_axis=self.ep_axis,
            ep_size=self.ep_size, capacity_factor=self.capacity_factor,
            dtype=self.dtype, name="moe")
        for i in range(self.n_layers):
            x = Block(head_dim=self.d_model // self.n_heads,
                      d_ff=self.d_ff, d_model=self.d_model,
                      tp_axis=None, sp_axis=None, tp_size=1,
                      dtype=self.dtype, mlp=moe_factory,
                      name=f"block{i}")(x, positions)
        x = nn.LayerNorm(dtype=self.dtype, name="ln_f")(x)
        logits = emb.attend(x.astype(self.param_dtype))
        return logits.astype(jnp.float32)


def moe_lm(vocab_size: int = 32000, d_model: int = 256, n_layers: int = 2,
           n_heads: int = 4, d_ff: Optional[int] = None, n_experts: int = 4,
           **kw) -> MoETransformerLM:
    return MoETransformerLM(vocab_size=vocab_size, d_model=d_model,
                            n_layers=n_layers, n_heads=n_heads,
                            d_ff=d_ff or 2 * d_model, n_experts=n_experts,
                            **kw)


def moe_param_specs(params, ep_axis: str = "ep"):
    """PartitionSpecs: expert weight stacks ('wi'/'wo' under an 'moe'
    scope) ep-sharded on the leading expert axis, everything else
    replicated."""

    def spec(path, leaf):
        names = [str(getattr(k, "key", k)) for k in path]
        if "moe" in names and names[-1] in ("wi", "wo"):
            return P(ep_axis)
        return P()

    return jax.tree_util.tree_map_with_path(spec, params)
