"""Pipeline-parallel decoder LM — layer-stacked params over the `pp` axis.

New capability beyond the reference (SURVEY.md §2: PP "Absent"; round-1
review: pp axis was a placeholder).  The design is the TPU-native "stack
of identical layers" form:

* block parameters live as ONE pytree whose leaves have a leading layer
  axis (L, ...) — built by vmapping `Block.init` over per-layer rngs;
* on a mesh, that leading axis is sharded `P("pp", ...)`: each pipeline
  stage holds its contiguous L/pp slice, exactly as tensor parallelism
  shards feature axes;
* the forward pass is `lax.scan` over the local layer slice; across
  stages, activations stream via `parallel.pipeline.pipeline_spmd`
  (rotating ppermute, GPipe schedule);
* embedding, final LayerNorm and the tied head are replicated — their
  gradients need a `psum` over pp (stage-local block grads are already
  complete, each stage being the only owner of its layers).  With
  ``vocab_pp=True`` (round 5, VERDICT r4 ask #4) the tied table is
  instead VOCAB-SHARDED over pp — P("pp", None) on its leading (V, d)
  axis — removing the replicated-head cap: for large-vocab LMs the
  embedding is often the single biggest tensor, and replicating it put a
  floor under per-device memory no matter how deep the pipeline.  The
  lookup masks+psums partial embeddings (each rank looks up only its
  vocab slice); the head broadcasts the last stage's activations over pp
  (one psum) and each rank emits its (B, T, V/pp) logits slice, consumed
  by `vocab_parallel_ce` — logits never materialize unsharded anywhere,
  so peak activation memory also drops by pp on the head.  Each rank's
  table-slice gradient is complete (sole owner) — no pp psum.  Only the
  (tiny) ln_f stays replicated.

`PipelinedLM` is intentionally NOT an nn.Module: flax modules cannot be
re-applied inside `lax.scan` pipeline ticks, but a pure `Block.apply`
over stacked params can.  The class mirrors the `init/apply` surface the
trainers use, and composes with tensor parallelism (Block's tp psums)
unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

import flax.linen as nn

from ..parallel.pipeline import pipeline_spmd
from .transformer import Block

__all__ = ["PipelinedLM", "pipelined_lm", "pp_param_specs",
           "vocab_parallel_ce"]


@dataclass(frozen=True)
class PipelinedLM:
    """Decoder-only LM with layer-stacked block params.

    pp_axis/pp_size and tp_axis/tp_size describe the APPLY-time mesh
    context (shard_map slices the params); init always builds the full
    global stack with pp_size=1-style shapes.
    """
    vocab_size: int = 32000
    d_model: int = 256
    n_layers: int = 4
    n_heads: int = 4
    d_ff: int = 1024
    pp_axis: Optional[str] = None
    pp_size: int = 1
    tp_axis: Optional[str] = None
    tp_size: int = 1
    vocab_pp: bool = False      # shard the tied embed/head table over pp
                                # (module docstring); apply_pipelined then
                                # returns VOCAB-SHARDED logits (B,T,V/pp),
                                # valid on every pp rank, for
                                # vocab_parallel_ce
    remat_stages: bool = True   # checkpoint each pipeline stage: backward
                                # memory flat in n_microbatches (see
                                # parallel/pipeline.py docstring);
                                # value-neutral
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    def _block(self) -> Block:
        return Block(head_dim=self.d_model // self.n_heads, d_ff=self.d_ff,
                     d_model=self.d_model, tp_axis=self.tp_axis,
                     sp_axis=None, tp_size=self.tp_size, dtype=self.dtype)

    def _embed(self) -> nn.Embed:
        return nn.Embed(self.vocab_size, self.d_model, dtype=self.dtype,
                        param_dtype=self.param_dtype)

    def _lnf(self) -> nn.LayerNorm:
        return nn.LayerNorm(dtype=self.dtype)

    def init(self, rng, tokens, train: bool = True) -> dict:
        """Full (global) parameter pytree: embed / ln_f replicated shapes,
        blocks stacked on a leading (n_layers, ...) axis."""
        del train
        t = tokens.shape[1]
        k_embed, k_blocks, k_ln = jax.random.split(rng, 3)
        embed_vars = self._embed().init(k_embed, tokens)
        x0 = jnp.zeros((tokens.shape[0], t, self.d_model), self.dtype)
        positions = jnp.arange(t)
        block = self._block()
        keys = jax.random.split(k_blocks, self.n_layers)
        stacked = jax.vmap(
            lambda k: block.init(k, x0, positions)["params"])(keys)
        ln_vars = self._lnf().init(k_ln, x0)
        return {"params": {"embed": embed_vars["params"],
                           "blocks": stacked,
                           "ln_f": ln_vars["params"]}}

    def _apply_stack(self, stacked_params, x, positions):
        block = self._block()

        def body(h, p):
            return block.apply({"params": p}, h, positions), None

        h, _ = lax.scan(body, x, stacked_params)
        return h

    def apply(self, variables: dict, tokens: jnp.ndarray,
              train: bool = True) -> jnp.ndarray:
        """(B, T) int32 -> (B, T, vocab) fp32 logits.

        Without a pp context this is an ordinary sequential LM (the
        single-device oracle the tests compare against).  Inside shard_map
        with pp_size > 1, `tokens` must already be the per-rank batch and
        the caller uses `apply_pipelined` (microbatch streaming).
        """
        del train
        params = variables["params"]
        positions = jnp.arange(tokens.shape[1])
        x = self._embed().apply({"params": params["embed"]}, tokens)
        h = self._apply_stack(params["blocks"], x, positions)
        return self._head(params, h)

    def _head(self, params, h):
        h = self._lnf().apply({"params": params["ln_f"]}, h)
        logits = self._embed().apply(
            {"params": params["embed"]}, h.astype(self.param_dtype),
            method="attend")
        return logits.astype(jnp.float32)

    def apply_pipelined(self, variables: dict, tokens: jnp.ndarray,
                        n_microbatches: int) -> jnp.ndarray:
        """Pipelined forward inside shard_map over (pp_axis).

        tokens: (B_local, T); returns (B_local, T, vocab) logits VALID ON
        THE LAST pp STAGE ONLY (mask downstream with axis_index == last).
        """
        params = variables["params"]
        m = n_microbatches
        b, t = tokens.shape
        if b < m or b % m:
            raise ValueError(
                f"per-rank batch {b} must be a positive multiple of "
                f"n_microbatches={m} (each dp rank's batch is split into "
                f"pipeline microbatches)")
        positions = jnp.arange(t)
        toks = tokens.reshape(m, b // m, t)
        if self.vocab_pp:
            x = self._vp_embed(params, toks)
        else:
            x = self._embed().apply({"params": params["embed"]}, toks)

        def stage_fn(act):
            return self._apply_stack(params["blocks"], act, positions)

        outs = pipeline_spmd(stage_fn, x, self.pp_axis, self.pp_size,
                             remat_stages=self.remat_stages)
        h = outs.reshape(b, t, -1).astype(self.dtype)
        if self.vocab_pp:
            # broadcast the last stage's finished activations over pp
            # (mask+psum — everyone else holds schedule garbage), then
            # each rank emits its vocab slice of the tied-head logits;
            # the (B, T, V) tensor never exists unsharded
            is_last = lax.axis_index(self.pp_axis) == self.pp_size - 1
            h = lax.psum(jnp.where(is_last, h, 0), self.pp_axis)
            h = self._lnf().apply({"params": params["ln_f"]}, h)
            tab = params["embed"]["embedding"]          # (V/pp, d) slice
            # compute in self.dtype like the replicated head (nn.Embed
            # attend promotes to the module dtype), fp32 logits out
            return (h.astype(self.dtype)
                    @ tab.T.astype(self.dtype)).astype(jnp.float32)
        return self._head(params, h)

    def _vshard(self) -> int:
        if self.vocab_size % self.pp_size:
            raise ValueError(
                f"vocab_pp needs vocab_size {self.vocab_size} divisible "
                f"by pp_size {self.pp_size}")
        if self.pp_axis is None:
            raise ValueError("vocab_pp requires a pp_axis mesh context")
        return self.vocab_size // self.pp_size

    def _vp_embed(self, params, toks):
        """Vocab-parallel lookup: each rank resolves only the token ids
        inside its vocab slice; the psum assembles full embeddings (one
        (M, B/M, T, d) all-reduce — d-sized, cheap next to the V-sized
        traffic sharding avoids)."""
        vshard = self._vshard()
        offset = lax.axis_index(self.pp_axis) * vshard
        # lookup + psum in self.dtype, like the replicated nn.Embed
        # (dtype promotion happens at lookup) — under bf16 the psum also
        # moves half the wire bytes fp32 would
        tab = params["embed"]["embedding"].astype(self.dtype)
        local = toks - offset
        valid = (local >= 0) & (local < vshard)
        e = jnp.take(tab, jnp.clip(local, 0, vshard - 1), axis=0)
        e = jnp.where(valid[..., None], e, 0)
        return lax.psum(e, self.pp_axis)


def pipelined_lm(vocab_size: int = 32000, d_model: int = 256,
                 n_layers: int = 4, n_heads: int = 4,
                 d_ff: Optional[int] = None, **kw) -> PipelinedLM:
    return PipelinedLM(vocab_size=vocab_size, d_model=d_model,
                       n_layers=n_layers, n_heads=n_heads,
                       d_ff=d_ff or 4 * d_model, **kw)


def pp_param_specs(params, pp_axis: str = "pp", tp_axis: str = "tp",
                   vocab_pp: bool = False):
    """PartitionSpecs: block leaves pp-sharded on their leading layer axis
    (composed with the Megatron tp rules on the trailing axes); embed
    vocab-sharded over pp when `vocab_pp` else replicated; ln_f
    replicated (tiny)."""
    from .transformer import megatron_shard_kind

    def spec(path, leaf):
        names = [str(getattr(k, "key", k)) for k in path]
        if names and names[0] == "blocks":
            # Megatron rule on the per-layer (trailing) axes, then prepend
            # the layer axis sharded over pp
            kind = megatron_shard_kind(names)
            if kind == "col":
                return P(pp_axis, None, tp_axis)
            if kind == "row":
                return P(pp_axis, tp_axis, None)
            return P(pp_axis)
        if vocab_pp and names and names[0] == "embed":
            return P(pp_axis, None)     # (V, d) table split on vocab rows
        return P()

    return jax.tree_util.tree_map_with_path(spec, params)


def vocab_parallel_ce(logits: jnp.ndarray, targets: jnp.ndarray,
                      axis: str):
    """Cross-entropy + argmax over a VOCAB-SHARDED logits tensor, inside
    shard_map.

    logits: (..., V/W) — rank r holds global vocab rows
    [r·V/W, (r+1)·V/W) (the `vocab_pp` head layout); targets: (...)
    global int ids.  Returns (ce, pred), both (...) and identical on
    every rank of `axis`: the log-sum-exp runs on all_gather'd row
    maxima + psum'd exp partials and the target logit is assembled with
    a masked psum — the (..., V) tensor never materializes.  Gradient-correct: d ce / d logits =
    softmax − onehot lands on each rank's slice through the psum
    transposes (the max is stop_gradient'ed, the standard LSE trick).
    `pred` is the smallest global index attaining the max (ties broken
    like a sequential argmax scanning rank order)."""
    vshard = logits.shape[-1]
    offset = lax.axis_index(axis) * vshard
    # per-rank row maxima gathered to every rank (W scalars per row —
    # tiny); all_gather is differentiable where pmax has no JVP rule,
    # and the max itself is stop_gradient'ed (standard LSE trick)
    local_max = logits.max(-1)
    vals = lax.all_gather(local_max, axis)               # (W, ...)
    zmax = lax.stop_gradient(vals.max(0))
    sumexp = lax.psum(jnp.exp(logits - zmax[..., None]).sum(-1), axis)
    lse = jnp.log(sumexp) + zmax
    tl = targets - offset
    tvalid = (tl >= 0) & (tl < vshard)
    tlocal = jnp.take_along_axis(
        logits, jnp.clip(tl, 0, vshard - 1)[..., None], axis=-1)[..., 0]
    tlogit = lax.psum(jnp.where(tvalid, tlocal, 0.0), axis)
    ce = lse - tlogit
    local_arg = jnp.argmax(logits, -1).astype(jnp.int32) + offset
    args = lax.all_gather(local_arg, axis)               # (W, ...)
    w = jnp.argmax(vals, axis=0)            # lowest rank wins ties ==
    pred = jnp.take_along_axis(args, w[None], axis=0)[0]  # sequential
    return ce, pred
