"""Pipeline-parallel decoder LM — layer-stacked params over the `pp` axis.

New capability beyond the reference (SURVEY.md §2: PP "Absent"; round-1
review: pp axis was a placeholder).  The design is the TPU-native "stack
of identical layers" form:

* block parameters live as ONE pytree whose leaves have a leading layer
  axis (L, ...) — built by vmapping `Block.init` over per-layer rngs;
* on a mesh, that leading axis is sharded `P("pp", ...)`: each pipeline
  stage holds its contiguous L/pp slice, exactly as tensor parallelism
  shards feature axes;
* the forward pass is `lax.scan` over the local layer slice; across
  stages, activations stream via `parallel.pipeline.pipeline_spmd`
  (rotating ppermute, GPipe schedule);
* embedding, final LayerNorm and the tied head are replicated — their
  gradients need a `psum` over pp (stage-local block grads are already
  complete, each stage being the only owner of its layers).

`PipelinedLM` is intentionally NOT an nn.Module: flax modules cannot be
re-applied inside `lax.scan` pipeline ticks, but a pure `Block.apply`
over stacked params can.  The class mirrors the `init/apply` surface the
trainers use, and composes with tensor parallelism (Block's tp psums)
unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

import flax.linen as nn

from ..parallel.pipeline import pipeline_spmd
from .transformer import Block

__all__ = ["PipelinedLM", "pipelined_lm", "pp_param_specs"]


@dataclass(frozen=True)
class PipelinedLM:
    """Decoder-only LM with layer-stacked block params.

    pp_axis/pp_size and tp_axis/tp_size describe the APPLY-time mesh
    context (shard_map slices the params); init always builds the full
    global stack with pp_size=1-style shapes.
    """
    vocab_size: int = 32000
    d_model: int = 256
    n_layers: int = 4
    n_heads: int = 4
    d_ff: int = 1024
    pp_axis: Optional[str] = None
    pp_size: int = 1
    tp_axis: Optional[str] = None
    tp_size: int = 1
    remat_stages: bool = True   # checkpoint each pipeline stage: backward
                                # memory flat in n_microbatches (see
                                # parallel/pipeline.py docstring);
                                # value-neutral
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    def _block(self) -> Block:
        return Block(head_dim=self.d_model // self.n_heads, d_ff=self.d_ff,
                     d_model=self.d_model, tp_axis=self.tp_axis,
                     sp_axis=None, tp_size=self.tp_size, dtype=self.dtype)

    def _embed(self) -> nn.Embed:
        return nn.Embed(self.vocab_size, self.d_model, dtype=self.dtype,
                        param_dtype=self.param_dtype)

    def _lnf(self) -> nn.LayerNorm:
        return nn.LayerNorm(dtype=self.dtype)

    def init(self, rng, tokens, train: bool = True) -> dict:
        """Full (global) parameter pytree: embed / ln_f replicated shapes,
        blocks stacked on a leading (n_layers, ...) axis."""
        del train
        t = tokens.shape[1]
        k_embed, k_blocks, k_ln = jax.random.split(rng, 3)
        embed_vars = self._embed().init(k_embed, tokens)
        x0 = jnp.zeros((tokens.shape[0], t, self.d_model), self.dtype)
        positions = jnp.arange(t)
        block = self._block()
        keys = jax.random.split(k_blocks, self.n_layers)
        stacked = jax.vmap(
            lambda k: block.init(k, x0, positions)["params"])(keys)
        ln_vars = self._lnf().init(k_ln, x0)
        return {"params": {"embed": embed_vars["params"],
                           "blocks": stacked,
                           "ln_f": ln_vars["params"]}}

    def _apply_stack(self, stacked_params, x, positions):
        block = self._block()

        def body(h, p):
            return block.apply({"params": p}, h, positions), None

        h, _ = lax.scan(body, x, stacked_params)
        return h

    def apply(self, variables: dict, tokens: jnp.ndarray,
              train: bool = True) -> jnp.ndarray:
        """(B, T) int32 -> (B, T, vocab) fp32 logits.

        Without a pp context this is an ordinary sequential LM (the
        single-device oracle the tests compare against).  Inside shard_map
        with pp_size > 1, `tokens` must already be the per-rank batch and
        the caller uses `apply_pipelined` (microbatch streaming).
        """
        del train
        params = variables["params"]
        positions = jnp.arange(tokens.shape[1])
        x = self._embed().apply({"params": params["embed"]}, tokens)
        h = self._apply_stack(params["blocks"], x, positions)
        return self._head(params, h)

    def _head(self, params, h):
        h = self._lnf().apply({"params": params["ln_f"]}, h)
        logits = self._embed().apply(
            {"params": params["embed"]}, h.astype(self.param_dtype),
            method="attend")
        return logits.astype(jnp.float32)

    def apply_pipelined(self, variables: dict, tokens: jnp.ndarray,
                        n_microbatches: int) -> jnp.ndarray:
        """Pipelined forward inside shard_map over (pp_axis).

        tokens: (B_local, T); returns (B_local, T, vocab) logits VALID ON
        THE LAST pp STAGE ONLY (mask downstream with axis_index == last).
        """
        params = variables["params"]
        m = n_microbatches
        b, t = tokens.shape
        if b < m or b % m:
            raise ValueError(
                f"per-rank batch {b} must be a positive multiple of "
                f"n_microbatches={m} (each dp rank's batch is split into "
                f"pipeline microbatches)")
        positions = jnp.arange(t)
        toks = tokens.reshape(m, b // m, t)
        x = self._embed().apply({"params": params["embed"]}, toks)

        def stage_fn(act):
            return self._apply_stack(params["blocks"], act, positions)

        outs = pipeline_spmd(stage_fn, x, self.pp_axis, self.pp_size,
                             remat_stages=self.remat_stages)
        logits = self._head(params, outs.reshape(b, t, -1).astype(self.dtype))
        return logits


def pipelined_lm(vocab_size: int = 32000, d_model: int = 256,
                 n_layers: int = 4, n_heads: int = 4,
                 d_ff: Optional[int] = None, **kw) -> PipelinedLM:
    return PipelinedLM(vocab_size=vocab_size, d_model=d_model,
                       n_layers=n_layers, n_heads=n_heads,
                       d_ff=d_ff or 4 * d_model, **kw)


def pp_param_specs(params, pp_axis: str = "pp", tp_axis: str = "tp"):
    """PartitionSpecs: block leaves pp-sharded on their leading layer axis
    (composed with the Megatron tp rules on the trailing axes), embed and
    ln_f replicated."""
    from .transformer import megatron_shard_kind

    def spec(path, leaf):
        names = [str(getattr(k, "key", k)) for k in path]
        if names and names[0] == "blocks":
            # Megatron rule on the per-layer (trailing) axes, then prepend
            # the layer axis sharded over pp
            kind = megatron_shard_kind(names)
            if kind == "col":
                return P(pp_axis, None, tp_axis)
            if kind == "row":
                return P(pp_axis, tp_axis, None)
            return P(pp_axis)
        return P()

    return jax.tree_util.tree_map_with_path(spec, params)
