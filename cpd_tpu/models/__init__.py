"""Model zoo — parity with the reference's example models plus a registry.

The reference selects models by dict key (`models['res_cifar']`,
reference: example/ResNet18/tools/mix.py:82); `get_model(name)` is the same
idea for all families.
"""

from .resnet_cifar import ResNetCIFAR, resnet18_cifar
from .davidnet import DavidNet, davidnet
from .resnet import ResNet, resnet18, resnet34, resnet50, resnet101
from .fcn import FCN, FCNHead, fcn_r50_d8
from .tiny import TinyCNN, tiny_cnn
from .transformer import TransformerLM, lm_param_specs, transformer_lm
from .pipeline_lm import PipelinedLM, pipelined_lm, pp_param_specs
from .moe import MoETransformerLM, moe_lm, moe_param_specs
from .davidnet_graph import graph_davidnet
from .generate import generate
from .vit import ViT, vit

_REGISTRY = {
    "res_cifar": resnet18_cifar,      # reference name (mix.py:82)
    "resnet18_cifar": resnet18_cifar,
    "davidnet": davidnet,
    "resnet18": resnet18,
    "resnet34": resnet34,
    "resnet50": resnet50,
    "resnet101": resnet101,
    "fcn_r50_d8": fcn_r50_d8,
    "tiny": tiny_cnn,                 # smoke-test model (models/tiny.py)
    "transformer_lm": transformer_lm,
    "pipelined_lm": pipelined_lm,
    "moe_lm": moe_lm,
    "davidnet_graph": graph_davidnet,  # dict-graph definition (TorchGraph)
    "vit": vit,                       # RoPE-ViT encoder (models/vit.py)
}


def get_model(name: str, **kwargs):
    """Instantiate a model by registry name."""
    if name not in _REGISTRY:
        raise KeyError(f"unknown model {name!r}; have {sorted(_REGISTRY)}")
    return _REGISTRY[name](**kwargs)


__all__ = ["ResNetCIFAR", "resnet18_cifar", "DavidNet", "davidnet",
           "ResNet", "resnet18", "resnet34", "resnet50", "resnet101",
           "FCN", "FCNHead", "fcn_r50_d8", "TinyCNN", "tiny_cnn",
           "TransformerLM", "transformer_lm", "lm_param_specs",
           "PipelinedLM", "pipelined_lm", "pp_param_specs",
           "MoETransformerLM", "moe_lm", "moe_param_specs",
           "graph_davidnet", "generate", "get_model"]
