"""ImageNet ResNets (ResNet-50 flagship) — torchvision-parity architecture.

The reference's ImageNet example instantiates `torchvision.models.resnet50()`
(reference: example/ResNet50/main.py:67).  This module provides the same
architecture family (ResNet-v1 with bottleneck blocks: 7x7/2 stem, 3x3/2
max-pool, stages [3,4,6,3] at 256/512/1024/2048, global avg-pool, fc) built
TPU-first: NHWC, bf16 compute / fp32 params, kaiming-normal conv init and
zero-init for the final BN scale of each block (the torchvision
`zero_init_residual` option; off by default for strict parity).

Also exposes `resnet50_backbone` features for the FCN head (models/fcn.py),
replacing the reference's out-of-repo mmcv/mmsegmentation fork
(README.md:132-150).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

__all__ = ["ResNet", "resnet50", "resnet18", "resnet101"]


class Bottleneck(nn.Module):
    """1x1 reduce -> 3x3 -> 1x1 expand(x4), stride on the 3x3 (torchvision
    v1.5 convention, which torchvision.models.resnet50 uses)."""
    channels: int  # bottleneck width; output is channels * 4
    stride: int = 1
    dilation: int = 1
    bn_axis: Any = None
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       param_dtype=self.param_dtype,
                       kernel_init=nn.initializers.kaiming_normal())
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype,
                       param_dtype=self.param_dtype,
                       axis_name=self.bn_axis if train else None)
        out_ch = self.channels * 4

        y = conv(self.channels, (1, 1), name="conv1")(x)
        y = nn.relu(norm(name="bn1")(y))
        y = conv(self.channels, (3, 3),
                 strides=(self.stride, self.stride),
                 kernel_dilation=(self.dilation, self.dilation),
                 padding=self.dilation, name="conv2")(y)
        y = nn.relu(norm(name="bn2")(y))
        y = conv(out_ch, (1, 1), name="conv3")(y)
        y = norm(name="bn3")(y)

        if self.stride != 1 or x.shape[-1] != out_ch:
            x = conv(out_ch, (1, 1), strides=(self.stride, self.stride),
                     name="downsample_conv")(x)
            x = norm(name="downsample_bn")(x)
        return nn.relu(y + x)


class BasicBlockV1(nn.Module):
    """Two 3x3 convs (for resnet18/34 ImageNet variants)."""
    channels: int
    stride: int = 1
    dilation: int = 1
    bn_axis: Any = None
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       param_dtype=self.param_dtype,
                       kernel_init=nn.initializers.kaiming_normal())
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype,
                       param_dtype=self.param_dtype,
                       axis_name=self.bn_axis if train else None)
        y = conv(self.channels, (3, 3), strides=(self.stride, self.stride),
                 padding=1, name="conv1")(x)
        y = nn.relu(norm(name="bn1")(y))
        y = conv(self.channels, (3, 3), padding=1, name="conv2")(y)
        y = norm(name="bn2")(y)
        if self.stride != 1 or x.shape[-1] != self.channels:
            x = conv(self.channels, (1, 1),
                     strides=(self.stride, self.stride),
                     name="downsample_conv")(x)
            x = norm(name="downsample_bn")(x)
        return nn.relu(y + x)


class ResNet(nn.Module):
    """ResNet-v1 for 224x224 NHWC inputs.

    `output_stride` < 32 switches trailing stages to dilated convs (stride 1,
    growing dilation) — the "-d8" trick FCN needs (see models/fcn.py).
    `features_only` returns the stage-4 feature map instead of logits;
    `feature_stages` (1-indexed, e.g. (3, 4)) returns a tuple of those
    stages' feature maps instead — the multi-stage mode FCN's auxiliary
    head needs (mmseg's fcn_r50-d8 taps layer3).
    `bn_axis` names a mesh axis to compute batch statistics over
    (sync-BN): only usable when training runs inside shard_map with that
    axis bound; None (default) keeps the reference's per-replica stats.
    """
    stage_sizes: Sequence[int] = (3, 4, 6, 3)
    block: Any = Bottleneck
    widths: Sequence[int] = (64, 128, 256, 512)  # per-stage block width
    num_classes: int = 1000
    output_stride: int = 32
    features_only: bool = False
    feature_stages: Sequence[int] = ()
    bn_axis: Any = None
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(64, (7, 7), strides=(2, 2), padding=3, use_bias=False,
                    dtype=self.dtype, param_dtype=self.param_dtype,
                    kernel_init=nn.initializers.kaiming_normal(),
                    name="stem_conv")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-5, dtype=self.dtype,
                         param_dtype=self.param_dtype,
                         axis_name=self.bn_axis if train else None,
                         name="stem_bn")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))

        stride_so_far = 4
        dilation = 1
        widths = self.widths
        stage_feats = {}
        for stage, blocks in enumerate(self.stage_sizes):
            want_stride = 1 if stage == 0 else 2
            if want_stride == 2 and stride_so_far >= self.output_stride:
                dilation *= 2       # dilate instead of stride (FCN -d8)
                want_stride = 1
            else:
                stride_so_far *= want_stride
            for block in range(blocks):
                x = self.block(widths[stage],
                               stride=want_stride if block == 0 else 1,
                               dilation=dilation, bn_axis=self.bn_axis,
                               dtype=self.dtype,
                               param_dtype=self.param_dtype,
                               name=f"layer{stage + 1}_block{block}")(
                                   x, train=train)
            stage_feats[stage + 1] = x

        if self.feature_stages:
            return tuple(stage_feats[s] for s in self.feature_stages)
        if self.features_only:
            return x
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     param_dtype=self.param_dtype, name="fc")(x)
        return x.astype(jnp.float32)


def resnet50(num_classes: int = 1000, dtype=jnp.float32, **kw) -> ResNet:
    """torchvision.models.resnet50 equivalent (main.py:67)."""
    return ResNet(stage_sizes=(3, 4, 6, 3), block=Bottleneck,
                  num_classes=num_classes, dtype=dtype, **kw)


def resnet101(num_classes: int = 1000, dtype=jnp.float32, **kw) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 23, 3), block=Bottleneck,
                  num_classes=num_classes, dtype=dtype, **kw)


def resnet18(num_classes: int = 1000, dtype=jnp.float32, **kw) -> ResNet:
    return ResNet(stage_sizes=(2, 2, 2, 2), block=BasicBlockV1,
                  num_classes=num_classes, dtype=dtype, **kw)


def resnet34(num_classes: int = 1000, dtype=jnp.float32, **kw) -> ResNet:
    """torchvision.models.resnet34 equivalent (BasicBlock, 3-4-6-3)."""
    return ResNet(stage_sizes=(3, 4, 6, 3), block=BasicBlockV1,
                  num_classes=num_classes, dtype=dtype, **kw)
