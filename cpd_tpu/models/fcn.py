"""FCN-R50-d8 semantic segmentation — in-repo, replacing the mmcv-fork hack.

The reference delivers FCN/Cityscapes only as out-of-repo forks of mmcv +
mmsegmentation v0.5.0, with precision toggled by editing a source line
(reference: README.md:132-150).  Here the same capability — FCN head on a
dilated-stride-8 ResNet-50 backbone, 769x769 crops, 19 Cityscapes classes —
is a first-class model config of the shared trainer.

Architecture parity with mmseg's `fcn_r50-d8`: backbone ResNet-50 with
stages 3/4 dilated (output stride 8), decode head = 2x (conv3x3-BN-ReLU) at
512 channels + dropout(0.1) + 1x1 classifier, bilinear upsample to input
resolution; auxiliary FCN head off stage 3 at weight 0.4 is exposed via
`aux_head=True`.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from .resnet import ResNet, Bottleneck

__all__ = ["FCNHead", "FCN", "fcn_r50_d8"]


class FCNHead(nn.Module):
    """num_convs x (3x3 conv-BN-ReLU) -> dropout -> 1x1 classifier."""
    num_classes: int
    channels: int = 512
    num_convs: int = 2
    dropout_rate: float = 0.1
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        for i in range(self.num_convs):
            x = nn.Conv(self.channels, (3, 3), padding=1, use_bias=False,
                        dtype=self.dtype, param_dtype=self.param_dtype,
                        kernel_init=nn.initializers.kaiming_normal(),
                        name=f"conv{i}")(x)
            x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                             epsilon=1e-5, dtype=self.dtype,
                             param_dtype=self.param_dtype,
                             name=f"bn{i}")(x)
            x = nn.relu(x)
        if self.dropout_rate > 0:
            x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.Conv(self.num_classes, (1, 1), dtype=jnp.float32,
                    param_dtype=self.param_dtype, name="classifier")(x)
        return x


class FCN(nn.Module):
    """Backbone + FCN decode head; logits upsampled to input size (NHWC)."""
    num_classes: int = 19  # Cityscapes
    aux_head: bool = False
    stage_sizes: tuple = (3, 4, 6, 3)   # R50; smaller for smoke tests
    widths: tuple = (64, 128, 256, 512)  # backbone widths; ditto
    head_channels: int = 512
    aux_channels: int = 256  # mmseg fcn_r50-d8 aux head width
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        h, w = x.shape[1], x.shape[2]
        backbone = ResNet(stage_sizes=self.stage_sizes, block=Bottleneck,
                          widths=self.widths,
                          output_stride=8, feature_stages=(3, 4),
                          dtype=self.dtype, param_dtype=self.param_dtype,
                          name="backbone")
        # stage-3 (1024ch) feeds the auxiliary head, stage-4 (2048ch) the
        # decode head — mmseg's fcn_r50-d8 attaches aux to layer3.
        feats3, feats4 = backbone(x, train=train)

        logits = FCNHead(self.num_classes, channels=self.head_channels,
                         dtype=self.dtype, param_dtype=self.param_dtype,
                         name="decode_head")(feats4, train=train)
        logits = jax.image.resize(
            logits.astype(jnp.float32), (logits.shape[0], h, w,
                                         self.num_classes), "bilinear")
        if not self.aux_head:
            return logits
        aux = FCNHead(self.num_classes, channels=self.aux_channels,
                      num_convs=1, dtype=self.dtype,
                      param_dtype=self.param_dtype,
                      name="aux_head")(feats3, train=train)
        aux = jax.image.resize(
            aux.astype(jnp.float32), (aux.shape[0], h, w, self.num_classes),
            "bilinear")
        return logits, aux


def fcn_r50_d8(num_classes: int = 19, dtype=jnp.float32, **kw) -> FCN:
    return FCN(num_classes=num_classes, dtype=dtype, **kw)
