"""Tiny CNN — a smoke-test model, not a reference-parity one.

The full zoo models (ResNet-18/50, DavidNet, FCN) cost minutes of XLA
compile time on the 8-virtual-device CPU mesh; CI-style smoke tests of the
trainer entry points need the identical harness path (BN stats, scan,
quantized collectives, optimizer) at a fraction of the graph size.  That is
this model's only job.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

__all__ = ["TinyCNN", "tiny_cnn"]


class TinyCNN(nn.Module):
    """conv-BN-relu -> conv-BN-relu -> pool -> dense."""
    num_classes: int = 10
    width: int = 16
    bn_axis: Any = None
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        for i, stride in enumerate(((2, 2), (2, 2))):
            x = nn.Conv(self.width * (i + 1), (3, 3), strides=stride,
                        use_bias=False, dtype=self.dtype,
                        param_dtype=self.param_dtype, name=f"conv{i}")(x)
            x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                             epsilon=1e-5, dtype=self.dtype,
                             param_dtype=self.param_dtype,
                             axis_name=self.bn_axis if train else None,
                             name=f"bn{i}")(x)
            x = nn.relu(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     param_dtype=self.param_dtype, name="fc")(x)
        return x.astype(jnp.float32)


def tiny_cnn(num_classes: int = 10, dtype=jnp.float32, **kw) -> TinyCNN:
    return TinyCNN(num_classes=num_classes, dtype=dtype, **kw)
