"""Vision Transformer classifier — the transformer stack applied to the
reference's vision workloads.

New capability beyond the reference (its models are CNNs only, SURVEY.md
§2); exists so the quantized training harness covers both major vision
architecture families with ONE block implementation: the encoder layers
ARE `transformer.Block` (non-causal), so everything Block supports —
Megatron tp sharding, remat, dropout, the quantized-accumulator FFN
(ffn_exp/ffn_man) — applies to image classification unchanged.

TPU-first choices:
* patchify = one Conv with stride=patch (a single strided matmul on the
  MXU), NHWC in, (B, N_patches, d) out;
* rotary position encoding over the flattened patch index (the Block's
  built-in RoPE — no separate learned position table) + mean-pool head
  (no CLS token: pooling keeps the sequence length a clean power of two
  for the MXU and drops a special-cased row);
* pre-LN blocks, bf16-friendly (dtype/param_dtype split as everywhere).
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from .transformer import Block

__all__ = ["ViT", "vit"]


class ViT(nn.Module):
    """(B, H, W, C) images -> (B, num_classes) fp32 logits."""
    num_classes: int = 1000
    patch: int = 16
    d_model: int = 384
    n_layers: int = 6
    n_heads: int = 6
    d_ff: Optional[int] = None
    dropout_rate: float = 0.0
    tp_axis: Optional[str] = None
    tp_size: int = 1
    remat: bool = False
    ffn_exp: int = 8
    ffn_man: int = 23
    ffn_mode: str = "faithful"
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        if x.shape[1] % self.patch or x.shape[2] % self.patch:
            raise ValueError(f"image {x.shape[1]}x{x.shape[2]} not divisible "
                             f"by patch {self.patch}")
        x = nn.Conv(self.d_model, (self.patch, self.patch),
                    strides=(self.patch, self.patch), padding=0,
                    dtype=self.dtype, param_dtype=self.param_dtype,
                    name="patch_embed")(x)
        b, gh, gw, _ = x.shape
        x = x.reshape(b, gh * gw, self.d_model)

        d_ff = self.d_ff or 4 * self.d_model
        block_cls = nn.remat(Block) if self.remat else Block
        for i in range(self.n_layers):
            x = block_cls(head_dim=self.d_model // self.n_heads,
                          d_ff=d_ff, d_model=self.d_model,
                          tp_axis=self.tp_axis, sp_axis=None,
                          tp_size=self.tp_size, dtype=self.dtype,
                          causal=False, dropout_rate=self.dropout_rate,
                          deterministic=not train, ffn_exp=self.ffn_exp,
                          ffn_man=self.ffn_man, ffn_mode=self.ffn_mode,
                          name=f"block{i}")(x, jnp.arange(gh * gw))
        x = nn.LayerNorm(dtype=self.dtype, name="ln_f")(x)
        x = x.mean(axis=1)
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     param_dtype=self.param_dtype, name="head")(x)
        return x.astype(jnp.float32)


def vit(num_classes: int = 1000, dtype=jnp.float32, **kw) -> ViT:
    return ViT(num_classes=num_classes, dtype=dtype, **kw)
