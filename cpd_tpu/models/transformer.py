"""Transformer LM with tensor- and sequence-parallelism built in.

New capability beyond the reference (whose workloads are CNNs only,
SURVEY.md §5): a decoder-only LM whose forward pass is written to run
unchanged in two regimes —

* single device (``tp_axis=None, sp_axis=None``): plain local attention;
* inside ``shard_map`` over a ("dp","sp","tp") mesh: Megatron-style tensor
  parallelism (qkv/wi column-sharded, wo row-sharded, one `psum` over tp
  per projection pair) and sequence parallelism over the sp axis —
  ``sp_mode="ring"`` (K/V rotate via ppermute) or ``"ulysses"``
  (all_to_all heads<->sequence); both in ops/attention.py.

TPU-first choices: RoPE positions are computed from the sp rank's global
offset (no position-embedding table to shard); all Dense layers are
bias-free so the tp `psum` needs no bias correction; head count and ff
width are derived from the *runtime kernel shapes*, so the same module
code handles full (init-time) and per-rank (apply-time, shard_map-sliced)
parameter shapes.

`lm_param_specs` maps a param pytree to PartitionSpecs (the tp sharding
rules); train/lm.py consumes it for the whole-step shard_map.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..ops.attention import (grouped_query_attention, local_attention,
                             ring_attention, ulysses_attention)

__all__ = ["TransformerLM", "transformer_lm", "lm_param_specs"]


def _rope(x: jnp.ndarray, positions: jnp.ndarray) -> jnp.ndarray:
    """Rotary embedding on (B, T, H, D) with (T,) global positions."""
    d = x.shape[-1]
    half = d // 2
    freqs = jnp.exp(-jnp.arange(0, half, dtype=jnp.float32)
                    * (jnp.log(10000.0) / half))
    angles = positions.astype(jnp.float32)[:, None] * freqs[None, :]  # (T, half)
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], -1)
    return out.astype(x.dtype)


class Block(nn.Module):
    head_dim: int
    d_ff: int           # GLOBAL ff width; local = d_ff // tp_size
    d_model: int
    tp_axis: Optional[str]
    sp_axis: Optional[str]
    tp_size: int        # 1 at init (global shapes); the mesh's tp size when
                        # applied inside shard_map (flax validates declared
                        # vs stored shapes, so features must be local)
    dtype: Any
    sp_mode: str = "ring"   # "ring" (ppermute K/V) | "ulysses" (all_to_all
                            # heads<->sequence; local heads % sp size == 0)
    decode: bool = False    # KV-cache autoregressive mode (single device)
    mlp: Optional[Any] = None   # factory () -> nn.Module replacing the
                                # dense pair (e.g. MoE experts); a custom
                                # mlp owns its own collectives — Block's tp
                                # psum applies only to the built-in pair
    scan_pair: bool = False     # return (x, None) — the (carry, out)
                                # shape nn.scan's body contract requires
    n_kv_heads: Optional[int] = None    # GQA: fewer K/V heads than query
                                        # heads (None = MHA, wqkv layout)
    dropout_rate: float = 0.0   # residual-branch dropout (after the attn
                                # and mlp projections, post-tp-psum so the
                                # mask applies to the full summed value —
                                # every tp rank must draw the SAME mask,
                                # which the stepper ensures by NOT folding
                                # the tp index into the rng)
    deterministic: bool = True  # False during training (LM threads its
                                # train flag here)
    ffn_exp: int = 8            # eXmY-accumulator GEMMs for the MLP pair
    ffn_man: int = 23           # (wi/wo_mlp) when != (8, 23): the
                                # reference's quantized forward/backward
                                # recipe (quant_module.py:30-52) composed
                                # into the LM.  Param layout stays Dense-
                                # compatible (QuantDense), so checkpoints
                                # and tp specs are unchanged.
    ffn_mode: str = "faithful"
    causal: bool = True         # False = bidirectional attention (ViT
                                # encoder use, models/vit.py); decode and
                                # sp paths require causal
    flash_bwd: str = "chunked"  # GQA flash backward: "chunked" (XLA
                                # recompute, default) | "pallas" (flash-
                                # backward kernels; ops/flash_gqa.py)
    attn_impl: str = "xla"      # "flash" = Pallas TPU flash-attention
                                # kernel for the non-decode single-
                                # sequence path (O(T) memory; MHA only);
                                # hardware-validated by
                                # tools/pallas_check.py.  "chunked" =
                                # pure-XLA online-softmax K/V-block scan
                                # (flash's memory shape, any backend,
                                # GQA-native; ops/attention.py)

    def _psum_tp(self, x):
        return lax.psum(x, self.tp_axis) if self.tp_axis else x

    def _cached_attention(self, q, k, v, positions):
        """KV-cache attention (decode=True).

        The cache is created on the FIRST call (flax init) with this
        call's (B, T, H, D) shapes — so initialize with a dummy input of
        the MAXIMUM sequence length.  Every later call writes its k/v
        block at ``positions[0]`` and attends q over the whole cache with
        the mask ``key_pos <= query_pos`` — one code path serves both
        one-pass prefill (T = prompt length) and single-token decode
        (T = 1).

        OVERFLOW CONTRACT: writing past the allocated cache length cannot
        raise from inside jit (positions are traced values), so the layer
        poisons the ENTIRE output block with NaN instead — argmax/sampling
        over NaN logits would otherwise silently emit token 0.  `generate()`
        sizes the cache so this never triggers there; callers driving
        ``decode=True`` with their own cache management must either respect
        ``prompt_len + steps <= cache length`` or check outputs for NaN
        (``jnp.isnan(logits).any()``) after a step that might overflow
        (ADVICE r2)."""
        is_init = self.has_variable("cache", "cached_k")
        cache_k = self.variable("cache", "cached_k", jnp.zeros, k.shape,
                                k.dtype)
        cache_v = self.variable("cache", "cached_v", jnp.zeros, v.shape,
                                v.dtype)
        if not is_init:
            # init trace: caches get their (B, T_max, H_kv, D) zero
            # shapes; run plain causal attention so init outputs are
            # well-formed (grouped handles GQA head counts)
            return grouped_query_attention(q, k, v, causal=True)
        start = positions[0]
        cache_k.value = lax.dynamic_update_slice(
            cache_k.value, k.astype(cache_k.value.dtype), (0, start, 0, 0))
        cache_v.value = lax.dynamic_update_slice(
            cache_v.value, v.astype(cache_v.value.dtype), (0, start, 0, 0))
        # keys sit at global positions 0..T_max-1, queries at `positions`;
        # the q_offset mask (q_off+i >= ki) is exactly key_pos <=
        # query_pos, and also hides the unwritten cache tail.  GQA caches
        # the UNEXPANDED kv heads and the grouped kernel contracts
        # against them directly — no rep× expansion is ever materialized
        # (that would negate the cache-memory win; see ops/attention.py).
        out = grouped_query_attention(q, cache_k.value, cache_v.value,
                                      causal=True, q_offset=start)
        # capacity guard: past the allocated length dynamic_update_slice
        # silently clamps the write (corrupting the last slot), so poison
        # the output with NaN to fail loudly instead
        t_max = cache_k.value.shape[1]
        return jnp.where(positions[-1] < t_max, out, jnp.nan)

    @nn.compact
    def __call__(self, x, positions):
        # ---- attention ----
        h = nn.LayerNorm(dtype=self.dtype, name="ln1")(x)
        if self.n_kv_heads is None:
            # MHA: fused projection.  Layout is HEAD-major — (n_heads, 3,
            # head_dim) in the feature dim — so a tp column-slice keeps
            # whole heads with their q,k,v together; local head count
            # comes from the runtime kernel shape.
            qkv = nn.Dense(3 * self.d_model // self.tp_size,
                           use_bias=False, dtype=self.dtype,
                           name="wqkv")(h)
            n_local = qkv.shape[-1] // (3 * self.head_dim)
            qkv = qkv.reshape(*qkv.shape[:-1], n_local, 3, self.head_dim)
            q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
        else:
            # GQA: separate q and kv projections (fewer kv heads), both
            # head-major so tp column slices keep whole heads
            qp = nn.Dense(self.d_model // self.tp_size, use_bias=False,
                          dtype=self.dtype, name="wq")(h)
            kvp = nn.Dense(
                2 * self.n_kv_heads * self.head_dim // self.tp_size,
                use_bias=False, dtype=self.dtype, name="wkv")(h)
            n_local = qp.shape[-1] // self.head_dim
            nkv_local = kvp.shape[-1] // (2 * self.head_dim)
            if n_local % nkv_local:
                raise ValueError(
                    f"n_heads ({n_local} local) must be a multiple of "
                    f"n_kv_heads ({nkv_local} local)")
            q = qp.reshape(*qp.shape[:-1], n_local, self.head_dim)
            kvp = kvp.reshape(*kvp.shape[:-1], nkv_local, 2, self.head_dim)
            k, v = kvp[..., 0, :], kvp[..., 1, :]
        q = _rope(q, positions)
        k = _rope(k, positions)
        if self.sp_mode not in ("ring", "ulysses"):
            raise ValueError(f"unknown sp_mode {self.sp_mode!r}; "
                             "expected 'ring' or 'ulysses'")
        if not self.causal and (self.decode or self.sp_axis):
            raise ValueError("causal=False (bidirectional encoder) does "
                             "not compose with decode or sp paths")
        if self.attn_impl not in ("xla", "flash", "chunked"):
            raise ValueError(f"unknown attn_impl {self.attn_impl!r}; "
                             "expected 'xla', 'flash' or 'chunked'")
        if self.flash_bwd not in ("chunked", "pallas"):
            # validated here, not only inside flash_gqa: a typo'd value
            # on a non-flash path would otherwise ride along silently
            # until the user flips attn_impl mid-experiment
            raise ValueError(f"unknown flash_bwd {self.flash_bwd!r}; "
                             "expected 'chunked' or 'pallas'")
        if (self.attn_impl == "flash" and self.sp_axis
                and self.sp_mode == "ring"):
            raise ValueError("attn_impl='flash' does not compose with "
                             "ring sequence parallelism (the ring's "
                             "online-softmax accumulation is its own "
                             "schedule); use sp_mode='ulysses'")
        if self.decode:
            attn = self._cached_attention(q, k, v, positions)
        elif self.sp_axis:
            # sequence-parallel paths take UNEXPANDED GQA kv: the ring
            # rotates H_kv-headed blocks and ulysses all_to_alls them
            # (expanding internally only when H_kv doesn't divide the sp
            # size) — rep x fewer ICI bytes than expanding first
            # (ops/attention.py)
            if self.sp_mode == "ulysses":
                attn = ulysses_attention(q, k, v, self.sp_axis,
                                         causal=True, impl=self.attn_impl,
                                         flash_bwd=self.flash_bwd)
            else:
                # ring accepts impl='chunked' (inner sub-block fold, for
                # T_local >> block); 'flash' was rejected above
                attn = ring_attention(q, k, v, self.sp_axis, causal=True,
                                      impl=("chunked"
                                            if self.attn_impl == "chunked"
                                            else "xla"))
        else:
            attn = grouped_query_attention(q, k, v, causal=self.causal,
                                           impl=self.attn_impl,
                                           flash_bwd=self.flash_bwd)
        attn = attn.reshape(*attn.shape[:-2], n_local * self.head_dim)
        proj = nn.Dense(self.d_model, use_bias=False, dtype=self.dtype,
                        name="wo")(attn)
        x = x + self._dropout(self._psum_tp(proj))

        # ---- mlp ----
        h = nn.LayerNorm(dtype=self.dtype, name="ln2")(x)
        if self.mlp is not None:
            out = x + self.mlp()(h)
        else:
            if (self.ffn_exp, self.ffn_man) != (8, 23):
                from ..quant.quant_module import QuantDense
                dense = partial(QuantDense, exp=self.ffn_exp,
                                man=self.ffn_man, mode=self.ffn_mode)
            else:
                dense = partial(nn.Dense, use_bias=False, dtype=self.dtype)
            h = dense(self.d_ff // self.tp_size, name="wi")(h)
            h = nn.gelu(h)
            h = dense(self.d_model, name="wo_mlp")(h)
            # psum BEFORE any downcast: the quant path's per-shard fp32
            # accumulator results must reduce in fp32 (QuantDense's
            # documented contract); the plain path's h is already dtype
            out = x + self._dropout(self._psum_tp(h).astype(x.dtype))
        return (out, None) if self.scan_pair else out

    def _dropout(self, x):
        if not self.dropout_rate:
            return x
        if not 0.0 < self.dropout_rate < 1.0:
            # 1.0 would silently zero every residual branch (flax returns
            # zeros_like at rate==1); out-of-range rates mis-scale
            raise ValueError(f"dropout_rate must be in [0, 1), got "
                             f"{self.dropout_rate}")
        return nn.Dropout(self.dropout_rate,
                          deterministic=self.deterministic)(x)


class TransformerLM(nn.Module):
    """Decoder-only LM.  Input: (B, T_local) int32 tokens; output:
    (B, T_local, vocab) fp32 logits."""
    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: Optional[int] = None   # GQA; None = MHA
    dropout_rate: float = 0.0
    d_ff: int = 2048
    tp_axis: Optional[str] = None
    sp_axis: Optional[str] = None
    tp_size: int = 1
    sp_mode: str = "ring"
    decode: bool = False
    remat: bool = False     # jax.checkpoint each block: activations are
                            # recomputed in backward instead of stored —
                            # O(sqrt) activation memory for deep stacks,
                            # the standard TPU HBM<->FLOPs trade
    scan_layers: bool = False   # ONE nn.scan'd block instead of a Python
                                # loop: layer body traced/compiled once
                                # regardless of depth; params gain a
                                # leading (n_layers,) axis (a different
                                # checkpoint layout — lm_param_specs is
                                # rank-aware for it)
    ffn_exp: int = 8        # quantized-accumulator MLP GEMMs when !=
    ffn_man: int = 23       # (8, 23) — see Block.ffn_exp
    ffn_mode: str = "faithful"
    attn_impl: str = "xla"  # "flash" = Pallas TPU kernel (see Block)
    flash_bwd: str = "chunked"  # GQA flash backward path (see Block)
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, tokens, train: bool = True):
        t_local = tokens.shape[1]
        if self.decode:
            if self.sp_axis or self.tp_axis:
                raise ValueError("decode=True (KV cache) is single-device; "
                                 "unset sp_axis/tp_axis")
            # running position: init at 0 when the cache is created, then
            # advance by this call's token count (prefill or one token)
            is_init = self.has_variable("cache", "position")
            pos_var = self.variable("cache", "position",
                                    lambda: jnp.zeros((), jnp.int32))
            offset = pos_var.value if is_init else 0
            if is_init:
                pos_var.value = pos_var.value + t_local
        elif self.sp_axis:
            offset = lax.axis_index(self.sp_axis) * t_local
        else:
            offset = 0
        positions = offset + jnp.arange(t_local)

        emb = nn.Embed(self.vocab_size, self.d_model,
                       dtype=self.dtype, param_dtype=self.param_dtype,
                       name="embed")
        x = emb(tokens)
        head_dim = self.d_model // self.n_heads
        # nn.remat wraps the module class so flax keeps param/cache
        # bookkeeping intact under jax.checkpoint; decode is cache-mutating
        # (no backward pass), so remat is train-path only.  Under nn.scan
        # the scan itself provides the staging checkpoint needs, so CSE
        # barriers are unnecessary (jax.checkpoint docs: prevent_cse=False
        # inside scan) — keeping them would wedge optimization-barrier ops
        # into the one scanned layer body.
        if self.remat and not self.decode:
            block_cls = nn.remat(Block, prevent_cse=not self.scan_layers)
        else:
            block_cls = Block
        block_kw = dict(head_dim=head_dim, d_ff=self.d_ff,
                        d_model=self.d_model, tp_axis=self.tp_axis,
                        sp_axis=self.sp_axis, tp_size=self.tp_size,
                        dtype=self.dtype, sp_mode=self.sp_mode,
                        decode=self.decode, n_kv_heads=self.n_kv_heads,
                        dropout_rate=self.dropout_rate,
                        deterministic=not train, ffn_exp=self.ffn_exp,
                        ffn_man=self.ffn_man, ffn_mode=self.ffn_mode,
                        attn_impl=self.attn_impl,
                        flash_bwd=self.flash_bwd)
        if self.scan_layers:
            if self.decode:
                raise ValueError("scan_layers does not compose with "
                                 "decode (per-layer caches need the "
                                 "unrolled blocks)")
            scan = nn.scan(block_cls, variable_axes={"params": 0},
                           # dropout must be listed or lift.pack filters
                           # the rng out of the scanned scope entirely
                           # (InvalidRngError at the first train step);
                           # True = a distinct mask per layer, matching
                           # the unrolled stack's per-block make_rng
                           split_rngs={"params": True, "dropout": True},
                           in_axes=nn.broadcast, length=self.n_layers)
            x, _ = scan(**block_kw, scan_pair=True, name="blocks")(
                x, positions)
        else:
            for i in range(self.n_layers):
                x = block_cls(**block_kw, name=f"block{i}")(x, positions)
        x = nn.LayerNorm(dtype=self.dtype, name="ln_f")(x)
        logits = emb.attend(x.astype(self.param_dtype))  # tied head
        return logits.astype(jnp.float32)


def transformer_lm(vocab_size: int = 32000, d_model: int = 512,
                   n_layers: int = 4, n_heads: int = 8,
                   d_ff: Optional[int] = None, dtype=jnp.float32,
                   **kw) -> TransformerLM:
    return TransformerLM(vocab_size=vocab_size, d_model=d_model,
                         n_layers=n_layers, n_heads=n_heads,
                         d_ff=d_ff or 4 * d_model, dtype=dtype, **kw)


def megatron_shard_kind(names) -> Optional[str]:
    """The Megatron rule for a param path (list of name strings):
    'col' = output dim tp-sharded (wqkv/wi kernels), 'row' = input dim
    tp-sharded (wo/wo_mlp kernels), None = replicated.  Exact layer-name
    matching (not substring): a future param whose path merely *contains*
    "wo" must not silently get row-sharded.  Shared by lm_param_specs and
    models/pipeline_lm.pp_param_specs."""
    if len(names) >= 2 and names[-1] == "kernel":
        if names[-2] in ("wqkv", "wq", "wkv", "wi"):
            return "col"
        if names[-2] in ("wo", "wo_mlp"):
            return "row"
    return None


def lm_param_specs(params, tp_axis: str = "tp"):
    """PartitionSpec pytree for the Megatron sharding rules: qkv and wi
    kernels column-sharded (out dim on tp), wo kernels row-sharded (in
    dim on tp), everything else replicated.  Rank-aware so the rules
    apply to both layouts — per-layer (in, out) kernels and the
    scan_layers stacked (n_layers, in, out) kernels (leading layer axis
    stays unsharded)."""

    def spec(path, leaf):
        kind = megatron_shard_kind([str(getattr(k, "key", k))
                                    for k in path])
        nd = jnp.ndim(leaf)
        if kind == "col":
            return P(*([None] * (nd - 1)), tp_axis)
        if kind == "row":
            return P(*([None] * (nd - 2)), tp_axis, None)
        return P()

    return jax.tree_util.tree_map_with_path(spec, params)
