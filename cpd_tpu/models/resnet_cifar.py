"""ResNet18 for 32x32 CIFAR — the reference's flagship model.

Capability parity with reference `example/ResNet18/models/resnet18_cifar.py`
(architecture: 3x3 stem without max-pool, 4 stages of 2 BasicBlocks at
64/128/256/512 channels, strides 1/2/2/2, 4x4 avg-pool, 512->num_classes fc
head — resnet18_cifar.py:48-87), re-designed TPU-first:

* NHWC layout (TPU-native; the reference is NCHW because cuDNN prefers it).
* Separate `param_dtype` (fp32 master weights) and `dtype` (bf16 compute) so
  the MXU runs bf16 matmuls/convs while the optimizer sees fp32 — subsuming
  the reference's manual master-weight copies (mix.py:53-63).
* BatchNorm carries running stats in the `batch_stats` collection; scale
  init 1, bias 0, momentum 0.9, eps 1e-5 (torch defaults the reference
  inherits via nn.BatchNorm2d).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

__all__ = ["ResNetCIFAR", "resnet18_cifar"]


class BasicBlock(nn.Module):
    """Two 3x3 convs + identity/projection shortcut (resnet18_cifar.py:7-45)."""
    channels: int
    stride: int = 1
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       param_dtype=self.param_dtype,
                       kernel_init=nn.initializers.kaiming_normal())
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype,
                       param_dtype=self.param_dtype)

        y = conv(self.channels, (3, 3), strides=(self.stride, self.stride),
                 padding=1, name="conv1")(x)
        y = norm(name="bn1")(y)
        y = nn.relu(y)
        y = conv(self.channels, (3, 3), padding=1, name="conv2")(y)
        y = norm(name="bn2")(y)

        if self.stride != 1 or x.shape[-1] != self.channels:
            x = conv(self.channels, (1, 1),
                     strides=(self.stride, self.stride),
                     name="shortcut_conv")(x)
            x = norm(name="shortcut_bn")(x)
        return nn.relu(y + x)


class ResNetCIFAR(nn.Module):
    """CIFAR-sized ResNet (resnet18_cifar.py:48-87). Input NHWC (B,32,32,3)."""
    num_classes: int = 10
    stage_sizes: Sequence[int] = (2, 2, 2, 2)
    stage_channels: Sequence[int] = (64, 128, 256, 512)
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(64, (3, 3), padding=1, use_bias=False, dtype=self.dtype,
                    param_dtype=self.param_dtype,
                    kernel_init=nn.initializers.kaiming_normal(),
                    name="stem_conv")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-5, dtype=self.dtype,
                         param_dtype=self.param_dtype, name="stem_bn")(x)
        x = nn.relu(x)

        for stage, (blocks, channels) in enumerate(
                zip(self.stage_sizes, self.stage_channels)):
            for block in range(blocks):
                stride = 2 if (stage > 0 and block == 0) else 1
                x = BasicBlock(channels, stride, dtype=self.dtype,
                               param_dtype=self.param_dtype,
                               name=f"layer{stage + 1}_block{block}")(
                                   x, train=train)

        # 4x4 avg-pool on the 4x4 feature map == global mean (mix ref :81).
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     param_dtype=self.param_dtype, name="fc")(x)
        return x.astype(jnp.float32)


def resnet18_cifar(num_classes: int = 10, dtype=jnp.float32) -> ResNetCIFAR:
    """Factory matching reference `models['res_cifar']` (mix.py:82-84)."""
    return ResNetCIFAR(num_classes=num_classes, dtype=dtype)
