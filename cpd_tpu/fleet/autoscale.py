"""Autoscaler — step-clock-deterministic elastic fleet policy (ISSUE 17).

The fleet's width becomes a POLICY OUTPUT instead of a constructor
constant: every `Fleet.step` the autoscaler observes the same live
signals the router already prices (page utilization, queue depth, the
shed counters) and decides — on the shared step clock, from step-clock
state only — whether to spawn an engine, drain one down, or hold.

Determinism is the whole design: the observation is a pure function of
(engine state, counters) and the hysteresis state is plain integers
advanced once per fleet step, so two runs of the same (model, trace,
plans, policy) produce the identical sequence of scaling decisions —
`Fleet.shape_log` records it and the soak gate pins it ×2.  No wall
clock anywhere (the PR 16 ``host-clock`` rule applies to this class).

Policy shape (docs/SERVING.md "Elastic fleet" has the table):

* **scale-up** — any accepting engine at/over ``up_page_util`` page
  pressure or ``up_queue`` backlog, or fleet-scope shed counters
  advancing, is a HOT step; ``up_patience`` consecutive hot steps spawn
  one engine (`Fleet.spawn_engine` — joins the fleet clock mid-run).
* **scale-down** — every accepting engine at/under ``down_page_util``
  with empty queues and no shedding is a COLD step; ``down_patience``
  consecutive cold steps drain the least-loaded accepting engine
  (`Fleet.scale_down` — the PR 13 `drain_engine` + capsule-migration
  path, so scale-down loses zero sessions and the survivors' decode
  stays bitwise identical).
* **hysteresis** — ``cooldown_steps`` after any action both streaks
  restart from zero, so pressure oscillating around a threshold cannot
  thrash spawn/drain cycles.
* **floor repair** — whenever fewer than ``min_engines`` engines
  accept work (a kill wave just went through), replacements spawn
  IMMEDIATELY, bypassing patience and cooldown: restoring the
  configured floor is recovery, not scaling.

The hysteresis state round-trips through `state_dict` /
`load_state_dict` (plain ints, JSON-ready) so a control-plane restart
resumes the policy exactly where it left off.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

__all__ = ["AutoscalePolicy", "Autoscaler"]

_SCALE_COUNTERS = ("ups", "downs", "floor_repairs", "hot_steps",
                   "cold_steps")


@dataclasses.dataclass(frozen=True)
class AutoscalePolicy:
    """The knobs (module docstring).  Frozen: a policy is part of the
    run's identity — mutating it mid-run would silently fork the
    deterministic decision sequence."""

    min_engines: int = 1
    max_engines: int = 4
    up_page_util: float = 0.85
    up_queue: int = 4
    up_patience: int = 3
    down_page_util: float = 0.30
    down_patience: int = 8
    cooldown_steps: int = 12

    def __post_init__(self):
        if self.min_engines < 1:
            raise ValueError(f"min_engines must be >= 1, got "
                             f"{self.min_engines}")
        if self.max_engines < self.min_engines:
            raise ValueError(
                f"max_engines ({self.max_engines}) < min_engines "
                f"({self.min_engines})")
        if not (0.0 <= self.down_page_util <= self.up_page_util <= 1.0):
            raise ValueError(
                f"need 0 <= down_page_util <= up_page_util <= 1, got "
                f"({self.down_page_util}, {self.up_page_util})")
        if min(self.up_patience, self.down_patience) < 1:
            raise ValueError("patience values must be >= 1")
        if self.cooldown_steps < 0:
            raise ValueError("cooldown_steps must be >= 0")


class Autoscaler:
    """Hysteresis state + decision procedure.  One instance per fleet,
    handed to `Fleet(autoscaler=...)`; the fleet calls `observe` once
    per step (after fleet faults fire, before the engines step, so a
    kill wave's floor repair lands inside the same step)."""

    def __init__(self, policy: Optional[AutoscalePolicy] = None):
        self.policy = policy or AutoscalePolicy()
        self.counters = {k: 0 for k in _SCALE_COUNTERS}
        self.hot_streak = 0
        self.cold_streak = 0
        # first step at which a non-repair action is allowed again
        self.cooldown_until = 0
        self._prev_shed = 0

    # -- signals ----------------------------------------------------------

    def _shed_total(self, fleet) -> int:
        """Monotone fleet-wide shed pressure: engine admission/purge
        sheds plus fleet-scope sheds (counters, not stores — exact
        regardless of eviction)."""
        total = fleet.counters["fleet_shed"]
        for i in fleet.live_engines():
            total += fleet.engines[i].counters.get("shed", 0)
        return int(total)

    def classify(self, fleet) -> str:
        """``"hot"`` / ``"cold"`` / ``"warm"`` for the current step —
        a pure read of step-clock state (module docstring)."""
        shed_now = self._shed_total(fleet)
        shedding = shed_now > self._prev_shed
        self._prev_shed = shed_now
        utils, queues = [], []
        for i, e in enumerate(fleet.engines):
            if not fleet.accepting[i]:
                continue
            utils.append(e.sched.page_utilization())
            queues.append(len(e.sched.queue))
        if not utils:
            return "hot"                  # nobody accepting: pressure
        p = self.policy
        if (shedding or max(utils) >= p.up_page_util
                or max(queues) >= p.up_queue):
            return "hot"
        if max(utils) <= p.down_page_util and sum(queues) == 0 \
                and not shedding:
            return "cold"
        return "warm"

    # -- the per-step decision --------------------------------------------

    def observe(self, fleet, step: int) -> Optional[str]:
        """Advance the hysteresis one step and act through the fleet's
        scaling hooks.  Returns the action taken (``"up"`` / ``"down"``
        / ``"floor"``) or None."""
        p = self.policy
        accepting = sum(fleet.accepting)
        if accepting < p.min_engines:
            # recovery, not scaling: bypass patience and cooldown, and
            # restart the streaks — post-repair pressure readings start
            # from a fresh fleet shape
            for _ in range(p.min_engines - accepting):
                fleet.spawn_engine()
            self.counters["floor_repairs"] += p.min_engines - accepting
            self.hot_streak = 0
            self.cold_streak = 0
            self.cooldown_until = step + p.cooldown_steps
            return "floor"
        state = self.classify(fleet)
        if state == "hot":
            self.counters["hot_steps"] += 1
            self.hot_streak += 1
            self.cold_streak = 0
        elif state == "cold":
            self.counters["cold_steps"] += 1
            self.cold_streak += 1
            self.hot_streak = 0
        else:
            self.hot_streak = 0
            self.cold_streak = 0
        if step < self.cooldown_until:
            return None
        if self.hot_streak >= p.up_patience and accepting < p.max_engines:
            fleet.spawn_engine()
            self.counters["ups"] += 1
            self.hot_streak = 0
            self.cooldown_until = step + p.cooldown_steps
            return "up"
        if self.cold_streak >= p.down_patience \
                and accepting > p.min_engines:
            victim = self._victim(fleet)
            if victim is not None:
                fleet.scale_down(victim)
                self.counters["downs"] += 1
                self.cold_streak = 0
                self.cooldown_until = step + p.cooldown_steps
                return "down"
        return None

    def _victim(self, fleet) -> Optional[int]:
        """Least-loaded accepting engine; exact ties retire the HIGHEST
        index (the newest spare), so the fleet contracts in the reverse
        order it grew.  Deterministic like every other routing choice."""
        best = None
        for i, e in enumerate(fleet.engines):
            if not fleet.accepting[i]:
                continue
            key = (e.sched.page_utilization(), len(e.sched.queue), -i)
            if best is None or key < best[0]:
                best = (key, i)
        return None if best is None else best[1]

    # -- persistence ------------------------------------------------------

    def state_dict(self) -> dict:
        return {
            "counters": dict(self.counters),
            "hot_streak": self.hot_streak,
            "cold_streak": self.cold_streak,
            "cooldown_until": self.cooldown_until,
            "prev_shed": self._prev_shed,
        }

    def load_state_dict(self, state: dict) -> None:
        self.counters = {k: int(v) for k, v
                         in state["counters"].items()}
        self.hot_streak = int(state["hot_streak"])
        self.cold_streak = int(state["cold_streak"])
        self.cooldown_until = int(state["cooldown_until"])
        self._prev_shed = int(state["prev_shed"])
