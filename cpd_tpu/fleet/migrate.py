"""Live session migration — digest-sealed capsules, bitwise resume.

A `SessionCapsule` is ONE request's complete serving state, extracted
from a `ServeEngine` slot and restorable into a FREE slot of another
engine (or the same one), built on the PR 10 snapshot doctrine applied
at request granularity:

* the slot's KV pages ride as **exact packed bytes** — the bit-packed
  eXmY code words (shift sidecars included, since the blocked layout
  stores them inside the page) sliced straight out of the u8 pool —
  plus their per-page digests;
* the host-side session state rides as JSON: the `Request`, the token
  history (prompt + generated so far), ``fed``/``next_token``, the
  first-token/progress clocks, and the source engine's RNG state and
  config fingerprint;
* the whole capsule is **sealed** with a sha256 over a canonical byte
  serialization; `restore_capsule` verifies the seal and the config
  compatibility BEFORE touching the target engine — a tampered capsule
  or a mismatched cache layout (different ``kv_block_size``, page
  size, format...) raises with zero pages written.

Because quantize-on-append makes page bytes a pure function of the
token prefix, and per-slot attention reads only the slot's own pages,
the restored session's remaining decode stream is **bitwise identical**
to the unmigrated run at (8, 23) — whatever slot index or page ids the
target assigns (the page table is indirection, not numerics).  Gated in
tests/test_fleet.py and the fleet-smoke.

Clock convention: capsules record the source engine's step index; on
restore the deadline-bearing fields (``arrival``, ``first_token_step``)
shift by the clock offset so SLA expiry keeps meaning on the target.
In a lockstep fleet the offset is zero.  Migration is a control-plane
operation — call it between engine steps, never mid-step.
"""

from __future__ import annotations

import dataclasses
import hashlib
import io
import json
import os

import jax.numpy as jnp
import numpy as np

from ..serve.scheduler import DECODE, FREE, PREFILL, Request

__all__ = ["SessionCapsule", "extract_capsule", "restore_capsule",
           "migrate_session", "can_adopt"]

# the KVCacheConfig fields a capsule's pages are only meaningful under —
# restore fails fast on ANY mismatch (a (4,3) block-24 page scattered
# into a block-32 pool would not corrupt loudly, it would decode garbage).
# ``tp`` is layout (ISSUE 18): a tp=2 capsule's pages carry a 2-shard
# axis a tp=4 pool cannot scatter — the fingerprint refuses BEFORE any
# page write, like every other mismatch
_CFG_FIELDS = ("n_layers", "n_kv_heads", "head_dim", "page_size",
               "exp_bits", "man_bits", "raw", "block_scale", "block_size",
               "tp")

_CAP_STATE, _CAP_POOL, _CAP_DIGESTS = "state.json", "pages.npy", \
    "digests.npy"


@dataclasses.dataclass
class SessionCapsule:
    """One migrated session (module docstring).  ``state`` is the
    JSON-able host record, ``pool_pages``/``page_digests`` the exact
    device bytes, ``seal`` the sha256 over the canonical serialization
    (`SessionCapsule.seal_bytes`)."""
    state: dict
    pool_pages: np.ndarray
    page_digests: np.ndarray
    seal: str = ""

    @property
    def rid(self) -> int:
        return int(self.state["req"]["rid"])

    @property
    def n_pages(self) -> int:
        return int(self.pool_pages.shape[1])

    def seal_bytes(self) -> str:
        """sha256 over the canonical byte serialization: the sorted
        state JSON, then each array's dtype/shape descriptor and raw
        bytes — any flipped byte, resized array or edited field changes
        the digest."""
        h = hashlib.sha256()
        h.update(json.dumps(self.state, sort_keys=True,
                            separators=(",", ":")).encode())
        for arr in (self.pool_pages, self.page_digests):
            h.update(str(arr.dtype).encode())
            h.update(repr(tuple(arr.shape)).encode())
            h.update(np.ascontiguousarray(arr).tobytes())
        return h.hexdigest()

    def sealed(self) -> "SessionCapsule":
        self.seal = self.seal_bytes()
        return self

    def verify(self) -> None:
        """Raise ValueError unless the seal matches the contents —
        ALWAYS the first thing `restore_capsule` does."""
        actual = self.seal_bytes()
        if not self.seal or actual != self.seal:
            raise ValueError(
                f"session capsule (rid {self.state.get('req', {}).get('rid')}"
                f"): seal mismatch ({actual[:12]}… != "
                f"{(self.seal or '<unsealed>')[:12]}…) — refusing to "
                "restore a tampered capsule")

    # -- durable form (drain-to-disk, cross-process migration) ------------

    def _blobs(self) -> dict:
        """The ONE capsule serialization body (three byte blobs),
        shared by `to_dir` and the durable-store `to_store` — the bytes
        on disk are identical either way."""
        buf = io.BytesIO()
        np.save(buf, self.pool_pages)
        pool_blob = buf.getvalue()
        buf = io.BytesIO()
        np.save(buf, self.page_digests)
        return {
            _CAP_POOL: pool_blob,
            _CAP_DIGESTS: buf.getvalue(),
            _CAP_STATE: json.dumps({"state": self.state,
                                    "seal": self.seal}).encode(),
        }

    @classmethod
    def _from_blobs(cls, blobs: dict) -> "SessionCapsule":
        doc = json.loads(blobs[_CAP_STATE].decode())
        return cls(state=doc["state"],
                   pool_pages=np.load(io.BytesIO(blobs[_CAP_POOL])),
                   page_digests=np.load(io.BytesIO(blobs[_CAP_DIGESTS])),
                   seal=doc["seal"])

    def to_dir(self, path: str) -> str:
        os.makedirs(path, exist_ok=True)
        for name, blob in self._blobs().items():
            with open(os.path.join(path, name), "wb") as fh:
                fh.write(blob)
        return path

    @classmethod
    def from_dir(cls, path: str) -> "SessionCapsule":
        blobs = {}
        for name in (_CAP_STATE, _CAP_POOL, _CAP_DIGESTS):
            with open(os.path.join(path, name), "rb") as fh:
                blobs[name] = fh.read()
        return cls._from_blobs(blobs)

    def to_store(self, store, *, step=None, meta=None,
                 writer=None):
        """Publish this capsule as ONE sealed generation of a
        `cpd_tpu.store.DurableStore` (ISSUE 20) — the capsule log's
        append operation.  Before the store plane, `to_dir` wrote plain
        files with NO atomicity story at all: a crash mid-write left a
        torn capsule that `from_dir` would crash on.  A generation is
        fsynced, sealed, digest-covered and atomic; a torn one lands in
        quarantine instead of being adopted.  ``meta`` rides the sealed
        manifest (the fleet records src/dst/step and the parked flag
        there).  Returns the published `GenerationInfo`."""
        m = dict(meta or {})
        m.setdefault("surface", "capsule")
        m["rid"] = self.rid
        return store.publish(self._blobs(), step=step, meta=m,
                             writer=writer)

    @classmethod
    def from_store(cls, store, token=None) -> "SessionCapsule":
        """Load a capsule from the newest valid generation (or exact
        ``token``) of a capsule store.  The store quarantines torn
        generations during the scan; the capsule seal is verified again
        by `restore_capsule` — two independent integrity fences."""
        info = (store.newest_valid() if token is None
                else store.lookup(token))
        if info is None:
            raise FileNotFoundError(
                f"no valid capsule generation in {store.root}")
        return cls._from_blobs(store.load(info))


def _cfg_fingerprint(cfg) -> dict:
    return {f: getattr(cfg, f) for f in _CFG_FIELDS}


def extract_capsule(engine, rid: int) -> SessionCapsule:
    """Extract ``rid``'s live slot into a sealed capsule and REMOVE it
    from ``engine`` (pages released, rid leaves the engine's in-flight
    set WITHOUT resolving — the capsule now carries the zero-silent-
    drops obligation; the caller must restore it somewhere).  Queued
    requests move with `ServeEngine.withdraw` instead; resolved rids
    are already final and raise here."""
    slot = engine.slot_of_rid(rid)
    if slot is None:
        raise ValueError(
            f"rid {rid} has no live slot on this engine (queued "
            "requests move via withdraw(); resolved ones are final)")
    pages = list(slot.pages)
    idx = np.asarray(pages, np.int32)
    pool_pages = np.asarray(engine._pool)[:, idx]
    page_digests = np.asarray(engine._digests)[:, idx]
    state = {
        "version": 1,
        "req": dataclasses.asdict(slot.req),
        "state": slot.state,
        "fed": int(slot.fed),
        "next_token": int(slot.next_token),
        "generated": [int(t) for t in slot.generated],
        "first_token_step": int(slot.first_token_step),
        "src_step": int(engine.step_index),
        "cfg": _cfg_fingerprint(engine.cfg),
        "rng": engine._rng.bit_generator.state,
        "temperature": float(engine._temperature),
    }
    capsule = SessionCapsule(state=state, pool_pages=pool_pages,
                             page_digests=page_digests).sealed()
    # removal — after the capsule is sealed, so a failure above leaves
    # the engine untouched
    engine._stalled.discard(slot.index)
    engine.counters["pages_freed"] += engine.sched.evict(slot)
    engine._inflight.discard(rid)
    engine.counters["sessions_out"] += 1
    engine._event("migrate_out", rid, engine.step_index,
                  pages=len(pages))
    return capsule


def can_adopt(engine, n_pages: int) -> bool:
    """True when ``engine`` can restore a capsule of ``n_pages`` right
    now: a FREE slot, a page-table row wide enough, and enough free (or
    cache-reclaimable) pages.  Reclaimable counts only cache-held pages
    whose SOLE reference is the cache — evicting an entry whose page a
    live slot also shares releases a reference but frees nothing, so
    counting those would over-promise and crash the adopt."""
    if not any(sl.state == FREE for sl in engine.sched.slots):
        return False
    if n_pages > engine.sched.max_pages:
        return False
    reclaimable = 0
    if engine.prefix_cache is not None:
        reclaimable = sum(
            1 for p in engine.prefix_cache.held_pages
            if engine.sched.page_refs.get(p, 0) == 1)
    return len(engine.sched.free_pages) + reclaimable >= n_pages


def restore_capsule(engine, capsule: SessionCapsule, *,
                    adopt_rng: bool = False):
    """Restore a capsule into a FREE slot of ``engine`` and resume —
    decode bitwise-identical to the unmigrated run at (8, 23) (module
    docstring).  Verification order is load-bearing: the seal, then the
    config compatibility, then capacity — ALL before any page is
    written, so a failed restore leaves the target untouched.

    ``adopt_rng=True`` additionally overwrites the target engine's
    sampling RNG with the capsule's (single-tenant engine handoff);
    the default leaves the target's stream alone — the bitwise-resume
    contract is for greedy decode, where no RNG is drawn."""
    capsule.verify()
    want = capsule.state["cfg"]
    have = _cfg_fingerprint(engine.cfg)
    if want != have:
        diff = {k: (want[k], have[k]) for k in _CFG_FIELDS
                if want[k] != have[k]}
        raise ValueError(
            f"capsule (rid {capsule.rid}) is incompatible with this "
            f"engine's cache layout — capsule vs engine: {diff}; "
            "restore onto a matching engine (pages are raw packed "
            "bytes, they cannot be transcoded here)")
    if capsule.state["state"] not in (PREFILL, DECODE):
        # up here with the other checks: the seal is not a secret (a
        # foreign tool can reseal an edited capsule), and a bad state
        # discovered after the page scatter would leak reserved pages
        # and wedge the target slot
        raise ValueError(f"capsule (rid {capsule.rid}) carries slot "
                         f"state {capsule.state['state']!r}")
    if capsule.n_pages > engine.sched.max_pages:
        # max_pages is per-ENGINE sizing, not part of the cache-layout
        # fingerprint — an oversized capsule would pass every byte
        # check, then blow up the first page_row render post-write
        raise ValueError(
            f"capsule (rid {capsule.rid}) holds {capsule.n_pages} "
            f"pages but this engine's page-table rows are "
            f"{engine.sched.max_pages} wide (max_seq too small)")
    slot = next((sl for sl in engine.sched.slots if sl.state == FREE),
                None)
    if slot is None:
        raise RuntimeError(f"no FREE slot to adopt rid {capsule.rid}")
    need = capsule.n_pages
    engine._make_room(need)
    if len(engine.sched.free_pages) < need:
        raise RuntimeError(
            f"cannot adopt rid {capsule.rid}: needs {need} pages, "
            f"{len(engine.sched.free_pages)} free")
    new_pages = engine.sched.reserve_pages(need)
    idx = jnp.asarray(np.asarray(new_pages, np.int32))
    engine._pool = engine._pool.at[:, idx].set(
        jnp.asarray(capsule.pool_pages))
    engine._digests = engine._digests.at[:, idx].set(
        jnp.asarray(capsule.page_digests))
    st = capsule.state
    offset = engine.step_index - int(st["src_step"])
    req = dict(st["req"])
    req["prompt"] = tuple(req["prompt"])
    req["arrival"] = int(req["arrival"]) + offset
    slot.req = Request(**req)
    slot.pages = new_pages
    slot.state = st["state"]
    slot.fed = int(st["fed"])
    slot.next_token = int(st["next_token"])
    slot.generated = [int(t) for t in st["generated"]]
    slot.seq = engine.sched._admit_seq
    engine.sched._admit_seq += 1
    ft = int(st["first_token_step"])
    slot.first_token_step = ft + offset if ft >= 0 else -1
    slot.last_progress = engine.step_index
    if adopt_rng:
        engine._rng.bit_generator.state = st["rng"]
    engine._inflight.add(capsule.rid)
    engine.counters["sessions_in"] += 1
    engine.counters["pages_reserved"] += need
    engine._event("migrate_in", capsule.rid, engine.step_index,
                  pages=need)
    return slot


def migrate_session(src, dst, rid: int,
                    adopt_rng: bool = False) -> SessionCapsule:
    """Extract ``rid`` from ``src`` and restore it into ``dst`` — the
    one-call live migration.  The destination is vetted (`can_adopt`)
    BEFORE extraction; if the restore still fails, the capsule is put
    back into the source so the session is never stranded."""
    slot = src.slot_of_rid(rid)
    if slot is None:
        raise ValueError(f"rid {rid} has no live slot to migrate")
    if not can_adopt(dst, len(slot.pages)):
        raise RuntimeError(
            f"destination cannot adopt rid {rid} "
            f"({len(slot.pages)} pages): no free slot or pages")
    capsule = extract_capsule(src, rid)
    try:
        restore_capsule(dst, capsule, adopt_rng=adopt_rng)
    except Exception:
        restore_capsule(src, capsule, adopt_rng=False)
        raise
    return capsule
