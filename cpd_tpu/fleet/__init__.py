"""cpd_tpu.fleet — multi-engine serving fleet (L6, ISSUE 13).

The layer above `cpd_tpu.serve` (ROADMAP item 1c): N `ServeEngine`s
behind one front door, stepped in lockstep on one shared step clock so
every per-engine determinism and zero-silent-drops guarantee lifts to
fleet scope unchanged.

* `router.Fleet` — SLA-class-aware routing over the PR 10 admission
  signals (structural TTFT bound, page pressure, supervisor rung),
  bounded retry-on-SHED, fleet-scope resolution accounting, periodic
  snapshots + deterministic replay-log recovery from the
  ``engine_kill`` chaos kind, and drain/scale-in.
* `migrate.SessionCapsule` — one request's slot state (token history,
  KV pages as exact packed bytes + shift sidecars, RNG, per-page
  digests) digest-sealed for live migration; the restored session's
  remaining decode is BITWISE identical to the unmigrated run at
  (8, 23).
* `autoscale.Autoscaler` / `AutoscalePolicy` — step-clock-
  deterministic elastic width (ISSUE 17): sustained page-pressure /
  queue / shed signals spawn engines (`Fleet.spawn_engine`, joining
  the fleet clock mid-run), sustained idleness drains the least-loaded
  one down through the capsule-migration path (zero sessions lost,
  survivors bitwise unchanged), with patience + cooldown hysteresis
  and immediate floor repair after kill waves; `Fleet.shape_log`
  records every decision and the soak gate pins the sequence ×2.
* `prefix.PrefixCache` — content-addressed prefix caching: full
  prompt-prefix pages indexed by token digest, shared copy-on-write
  across requests (refcounted through the scheduler), every digest hit
  byte-confirmed so a Fletcher collision can never leak KV bytes
  across tenants; cache hits skip prefill chunks and leave sampled
  logits bitwise identical to the cold path.

Harness: `serve.loadgen.run_fleet_trace` / `shared_prefix_trace`,
``tools/bench_serve.py --fleet / --fleet-smoke``, the ``cpd_fleet_*``
metric family (`obs.MetricsRegistry.absorb_fleet_counters`) and the
merged per-engine Chrome-trace lanes
(`obs.export.merge_chrome_traces`).  See docs/SERVING.md "Fleet".
"""

from .autoscale import AutoscalePolicy, Autoscaler
from .migrate import (SessionCapsule, can_adopt, extract_capsule,
                      migrate_session, restore_capsule)
from .prefix import PrefixCache, token_digest
from .router import Fleet

__all__ = ["Fleet", "Autoscaler", "AutoscalePolicy", "SessionCapsule",
           "extract_capsule", "restore_capsule", "migrate_session",
           "can_adopt", "PrefixCache", "token_digest"]
