"""Fleet — N `ServeEngine`s behind one front door (ISSUE 13 tentpole).

The fleet steps its member engines in LOCKSTEP on one shared step
clock, so everything the serving stack already guarantees per engine —
deterministic replay, exact counters, the zero-silent-drops contract —
lifts to fleet scope unchanged: two runs of the same (model, trace,
plans) produce identical fleet AND per-engine counters.

**Routing** (`Fleet.submit`): requests are scored against live
per-engine signals — exactly the quantities the PR 10 admission
machinery already computes:

| signal            | source                                | meaning |
|-------------------|---------------------------------------|---------|
| ``rung_sheds``    | `ServeSupervisor.rung.shed_class_above` | the engine's degradation rung would SHED this class |
| ``ttft_bound``    | `Scheduler.ttft_bound_steps(req)`     | structural lower bound on first-token dispatches |
| ``prefix_hits``   | `PrefixCache.lookup(..., peek=True)`  | full prefix pages already resident (affinity) |
| ``page_util``     | `Scheduler.page_utilization()`        | pool pressure |
| ``queue_len``     | ``len(Scheduler.queue)``              | backlog depth |

Per-SLA-class policy (docs/SERVING.md "Fleet" has the table):
class 0 (premium) routes **least-TTFT-bound** — (rung_sheds,
ttft_bound, -prefix_hits, page_util, queue_len, index); best-effort
(class >= 1) routes **load-spread with prefix affinity** —
(rung_sheds, -prefix_hits, page_util, queue_len, ttft_bound, index).
Ties fall to the engine index, so routing is deterministic.

A SHED verdict triggers **bounded retry** on the next-best engine
(``retry_limit``, default: every engine once); only when every tried
engine sheds is the rid resolved at FLEET scope (``Fleet.shed`` store,
``fleet_shed`` counter) — `Fleet.unresolved()` is therefore empty on a
drained fleet: every submitted rid resolved FINISHED/SHED/DEADLINE_MISS
*somewhere*, across routing retries, migration and engine kills.

**Recovery** (the ``engine_kill@s:e`` fleet fault kind): the fleet
keeps, per engine, the last periodic digest-sealed snapshot
(`ServeEngine.snapshot`, every ``snapshot_every`` steps plus one at
construction) and a **replay log** of every control-plane operation
since (submissions — shed attempts included — capsule adoptions,
extractions, queue withdrawals, in order).  A killed engine is rebuilt
by restoring the snapshot and re-applying the log while stepping back
up to the fleet clock — deterministically identical state to the
moment of death, because every engine step is a pure function of
(state, submissions) — and is then **drained**: admissions close,
queued work re-routes to the survivors, live sessions migrate out
where capacity allows (`fleet.migrate`), and whatever cannot move
finishes locally.  Zero silent drops, counters exact across runs (the
fleet-smoke drill pins it, ×2).

Scale-in and engine replacement reuse the same two primitives:
`Fleet.drain_engine` (migrate + re-route + close admissions) and
`Fleet.migrate` (one session, bitwise resume).
"""

from __future__ import annotations

import os
from collections import deque
from typing import Optional

from ..resilience.inject import FLEET_KINDS, FaultPlan
from ..serve.engine import ResultStore, ServeEngine
from ..serve.scheduler import FREE, SHED
from .migrate import can_adopt, extract_capsule, migrate_session, \
    restore_capsule
from .prefix import PrefixCache

__all__ = ["Fleet"]

_FLEET_COUNTERS = ("submitted", "routed", "router_retries", "fleet_shed",
                   "migrations", "requeued", "engine_kills",
                   "sessions_recovered", "drains",
                   "fleet_faults_unfired")


class Fleet:
    """N engines, one front door (module docstring).

    Parameters
    ----------
    model, params : shared by every engine (the fleet serves ONE
        model; jitted step programs are shared through the serve-side
        step cache, so N engines compile once).
    n_engines : fleet width.
    engine_kw : `ServeEngine` keyword dict applied to every engine
        (n_slots, max_seq, kv_format, ...).
    prefix_cache_pages : when set, every engine gets its own
        `PrefixCache(capacity_pages=...)` — per-engine, because page
        ids are pool-local; the router's affinity signal steers
        shared-prefix traffic back to the engine holding the pages.
    fault_plan : fleet-clock chaos (`FLEET_KINDS`: ``engine_kill``).
        Requires ``snapshot_every`` > 0 and ``snapshot_dir`` — a kill
        without a snapshot to recover from would be a guaranteed drop,
        so it fails fast here instead.
    engine_plans : optional per-engine `FaultPlan` list (the serving
        chaos kinds, aimed at individual engines).
    tracers : optional per-engine `obs.Tracer` list — each engine's
        timeline becomes its own process lane in the merged Chrome
        trace (`obs.export.merge_chrome_traces`).
    retry_limit : max engines tried per submission (default: all).
    snapshot_every : periodic per-engine snapshot cadence in fleet
        steps (0 = never; then engine kills cannot be recovered).
    snapshot_dir : directory for ``engine<i>`` snapshot subdirs.
    """

    def __init__(self, model, params, n_engines: int = 2, *,
                 engine_kw: Optional[dict] = None,
                 prefix_cache_pages: Optional[int] = None,
                 fault_plan: Optional[FaultPlan] = None,
                 engine_plans: Optional[list] = None,
                 tracers: Optional[list] = None,
                 retry_limit: Optional[int] = None,
                 snapshot_every: int = 0,
                 snapshot_dir: Optional[str] = None,
                 finished_cap: int = 4096):
        if n_engines < 1:
            raise ValueError(f"n_engines must be >= 1, got {n_engines}")
        if engine_plans is not None and len(engine_plans) != n_engines:
            raise ValueError(f"engine_plans must have one entry per "
                             f"engine ({n_engines}), got "
                             f"{len(engine_plans)}")
        if tracers is not None and len(tracers) != n_engines:
            raise ValueError(f"tracers must have one entry per engine "
                             f"({n_engines}), got {len(tracers)}")
        self._kills = list(fault_plan.fleet_faults()) if fault_plan \
            else []
        if fault_plan is not None:
            other = [f for f in fault_plan.faults
                     if f.kind not in FLEET_KINDS]
            if other:
                # "counted, never silent": the fleet consumes ONLY the
                # fleet-clock kinds — engine-clock specs riding this
                # plan would neither fire nor surface in any unfired
                # report, which is exactly the hole report_unfired
                # exists to close
                raise ValueError(
                    f"fleet fault_plan carries non-fleet kinds "
                    f"{sorted({f.kind for f in other})} — aim engine-"
                    f"clock chaos at individual engines via "
                    f"engine_plans=[...]")
        if self._kills and (snapshot_every < 1 or not snapshot_dir):
            raise ValueError(
                "engine_kill in the fault plan needs snapshot_every >= 1 "
                "and a snapshot_dir — a kill with no snapshot to recover "
                "from is a guaranteed silent drop, refused up front")
        self.model = model
        self.params = params
        self.n_engines = int(n_engines)
        self._engine_kw = dict(engine_kw or {})
        self._cache_pages = prefix_cache_pages
        self.retry_limit = retry_limit
        self.snapshot_every = int(snapshot_every)
        self.snapshot_dir = snapshot_dir
        self.engines = []
        for i in range(n_engines):
            kw = dict(self._engine_kw)
            if prefix_cache_pages is not None:
                kw["prefix_cache"] = PrefixCache(prefix_cache_pages)
            if engine_plans is not None:
                kw["fault_plan"] = engine_plans[i]
            if tracers is not None:
                kw["tracer"] = tracers[i]
            self.engines.append(ServeEngine(model, params, **kw))
        self.accepting = [True] * n_engines
        # rid -> engine index, pruned to LIVE rids every step (resolved
        # placements age out — the fleet must not regrow the unbounded
        # dict the PR 10 ResultStore killed)
        self.placement: dict = {}
        self.shed = ResultStore(finished_cap)   # fleet-scope sheds
        self.counters = {k: 0 for k in _FLEET_COUNTERS}
        # bounded like the engine event log (~few events per incident)
        self.events = deque(maxlen=8 * finished_cap)
        self.step_index = 0
        # per-engine control-plane replay logs since the last snapshot:
        # (step, op, payload) with op in submit/adopt/extract/withdraw.
        # Recorded ONLY when snapshotting is on — replay exists solely
        # for engine_kill recovery, and without snapshots the log would
        # retain every Request forever
        self._replay_enabled = bool(self.snapshot_every
                                    and self.snapshot_dir)
        self._logs: list = [[] for _ in range(n_engines)]
        if self._replay_enabled:
            for i in range(n_engines):
                self._snapshot_engine(i)

    # -- routing ----------------------------------------------------------

    def _signals(self, i: int, req) -> tuple:
        """One engine's routing score components for ``req``."""
        e = self.engines[i]
        sup = e.supervisor
        rung_sheds = int(sup is not None
                         and sup.rung.shed_class_above is not None
                         and req.sla_class >= sup.rung.shed_class_above)
        bound = e.sched.ttft_bound_steps(req)
        hits = 0
        if e.prefix_cache is not None:
            max_share = (len(req.prompt) - 1) // e.sched.page_size
            if max_share >= 1:
                hits = len(e.prefix_cache.lookup(
                    req.prompt, e.sched.page_size,
                    max_pages=max_share, peek=True))
        return (rung_sheds, bound, hits,
                e.sched.page_utilization(), len(e.sched.queue))

    def rank_engines(self, req, exclude: tuple = ()) -> list:
        """Engine indices best-first for ``req`` under the per-SLA-class
        policy (module docstring table).  Deterministic: every
        tiebreak ends at the engine index."""
        keyed = []
        for i in range(self.n_engines):
            if i in exclude or not self.accepting[i]:
                continue
            rung_sheds, bound, hits, util, qlen = self._signals(i, req)
            if req.sla_class == 0:
                key = (rung_sheds, bound, -hits, util, qlen, i)
            else:
                key = (rung_sheds, -hits, util, qlen, bound, i)
            keyed.append((key, i))
        return [i for _key, i in sorted(keyed)]

    def _log(self, idx: int, op: str, payload) -> None:
        if self._replay_enabled:
            self._logs[idx].append((self.step_index, op, payload))

    def _place(self, req, order: list, shed_reason: str) -> tuple:
        """The ONE try-engines-best-first loop behind `submit` and the
        drain requeue — same bounded retry budget on both paths."""
        limit = len(order) if self.retry_limit is None \
            else min(self.retry_limit, len(order))
        for pos, idx in enumerate(order[:limit]):
            verdict = self.engines[idx].submit(req)
            self._log(idx, "submit", req)
            if verdict != SHED:
                self.placement[req.rid] = idx
                return verdict, idx
            if pos + 1 < limit:
                self.counters["router_retries"] += 1
        self.shed.put(req.rid, shed_reason)
        self.counters["fleet_shed"] += 1
        self.events.append(("fleet_shed", self.step_index, req.rid))
        return SHED, -1

    def submit(self, req) -> tuple:
        """Route one request: try engines best-first, bounded
        retry-on-SHED, fleet-scope SHED when every tried engine sheds.
        Returns ``(verdict, engine_index)`` (index -1 on fleet shed).

        Validation runs BEFORE the submitted counter moves
        (`ServeEngine.submit`'s phantom rule, fleet edition): an
        impossible request raising out of an engine after the count
        would read as a permanent fleet-scope silent drop.  Engines
        share one config, so any scheduler speaks for all."""
        self.engines[0].sched.validate(req)
        self.counters["submitted"] += 1
        verdict, idx = self._place(req, self.rank_engines(req),
                                   "fleet-admission")
        if idx >= 0:
            self.counters["routed"] += 1
        return verdict, idx

    # -- the fleet step ---------------------------------------------------

    def _kill_fireable(self, f) -> bool:
        """A kill spec can still fire iff its target engine is still
        accepting — drained engines never re-open, so a spec aimed at
        one is permanently unfireable WHATEVER its step (running the
        clock toward it would step a drained fleet for nothing).  It
        stays pending only for `report_unfired`."""
        return self.accepting[max(int(f.arg), 0) % self.n_engines]

    def has_pending_faults(self) -> bool:
        """True while ``engine_kill`` specs can still fire — the fleet
        load generator keeps the step clock running toward them (the
        `req_burst` convention lifted to fleet scope).  Unfireable
        specs (target already drained) are excluded, so a double-kill
        plan cannot livelock `run_fleet_trace`; they surface through
        `report_unfired` instead."""
        return any(self._kill_fireable(f) for f in self._kills)

    def step(self) -> None:
        s = self.step_index
        self._fire_fleet_faults(s)
        for e in self.engines:
            e.step()
        if self._replay_enabled and (s + 1) % self.snapshot_every == 0:
            for i in range(self.n_engines):
                self._snapshot_engine(i)
        # resolved placements age out (bounded control-plane state):
        # only rids still in flight somewhere need their routing home
        self.placement = {rid: i for rid, i in self.placement.items()
                          if rid in self.engines[i]._inflight}
        self.step_index += 1

    def drained(self) -> bool:
        return all(e.drained() for e in self.engines)

    def run_until_drained(self, max_steps: int = 100000) -> None:
        while not self.drained():
            if self.step_index >= max_steps:
                busy = [i for i, e in enumerate(self.engines)
                        if not e.drained()]
                raise RuntimeError(
                    f"fleet not drained after {max_steps} steps "
                    f"(busy engines: {busy})")
            self.step()

    def unresolved(self) -> list:
        """Submitted rids not yet resolved anywhere in the fleet —
        empty on a drained fleet (the fleet-scope zero-silent-drops
        acceptance check; migrations move the obligation with the
        session, fleet sheds resolve it here)."""
        out: set = set()
        for e in self.engines:
            out.update(e.unresolved())
        return sorted(out)

    def report_unfired(self) -> list:
        """Fleet fault specs that never fired (e.g. an ``engine_kill``
        scheduled past the end of the trace) — counted, never silent;
        the fleet twin of `ServeEngine.report_unfired` (which every
        member engine still runs for its own kinds)."""
        for e in self.engines:
            e.report_unfired()
        self.counters["fleet_faults_unfired"] = len(self._kills)
        return sorted(self._kills)

    def aggregate_counters(self) -> dict:
        """Sum of every engine's counter dict (per-engine truth stays
        on the engines; this is the fleet roll-up the metrics and the
        ``cpd_fleet_*`` family report)."""
        out: dict = {}
        for e in self.engines:
            for k, v in e.counters.items():
                out[k] = out.get(k, 0) + v
        return out

    # -- chaos: engine kill -> snapshot+replay recovery -> drain ----------

    def _fire_fleet_faults(self, s: int) -> None:
        still = []
        for f in self._kills:
            if f.step > s:
                still.append(f)
                continue
            target = max(int(f.arg), 0) % self.n_engines
            if not self.accepting[target]:
                still.append(f)      # held: already dead/draining
                continue
            self._kill_engine(target, s)
        self._kills = still

    def _snapshot_engine(self, i: int) -> None:
        path = os.path.join(self.snapshot_dir, f"engine{i}")
        self.engines[i].snapshot(path)
        self._logs[i] = []

    def _kill_engine(self, idx: int, s: int) -> None:
        """The ``engine_kill`` handler (module docstring): rebuild the
        engine from its last snapshot + the deterministic replay log,
        then drain it onto the survivors."""
        self.counters["engine_kills"] += 1
        self.events.append(("engine_kill", s, idx))
        dead = self.engines[idx]
        path = os.path.join(self.snapshot_dir, f"engine{idx}")
        # capacity is adopted from the snapshot blob on load; the
        # constructor arg is a placeholder
        cache = (PrefixCache(self._cache_pages or 1)
                 if dead.prefix_cache is not None else None)
        restored = ServeEngine.restore(self.model, self.params, path,
                                       prefix_cache=cache)
        self.engines[idx] = restored
        log = self._logs[idx]
        for fs in range(restored.step_index, s):
            self._replay_ops(idx, log, fs)
            restored.step()
        self._replay_ops(idx, log, s)
        # the obs lane re-attaches AFTER the replay — the dead engine's
        # tracer already holds the pre-kill timeline, and replaying
        # into it would duplicate every event
        restored.tracer = dead.tracer
        restored.flight = dead.flight
        self.counters["sessions_recovered"] += (
            sum(sl.state != FREE for sl in restored.sched.slots)
            + len(restored.sched.queue))
        self.drain_engine(idx)

    def _replay_ops(self, idx: int, log: list, fs: int) -> None:
        eng = self.engines[idx]
        for step, op, payload in log:
            if step != fs:
                continue
            if op == "submit":
                eng.submit(payload)
            elif op == "adopt":
                restore_capsule(eng, payload)
            elif op == "extract":
                extract_capsule(eng, payload)
            elif op == "withdraw":
                eng.withdraw(payload)

    def drain_engine(self, idx: int) -> dict:
        """Close engine ``idx`` to new work and move what can move:
        queued requests re-route through the router (excluding the
        drained engine), live sessions migrate out where a survivor
        can adopt them; the remainder completes locally (the engine
        keeps stepping with admissions closed).  Returns the drain
        summary.  Also the scale-in primitive."""
        self.counters["drains"] += 1
        self.accepting[idx] = False
        e = self.engines[idx]
        moved_q = moved_s = stayed = 0
        for q in list(e.sched.queue):
            req = e.withdraw(q.rid)
            self._log(idx, "withdraw", q.rid)
            self.placement.pop(q.rid, None)
            self._requeue(req, exclude=(idx,))
            moved_q += 1
        for sl in list(e.sched.slots):
            if sl.state == FREE:
                continue
            rid = sl.req.rid
            target = self._adopt_target(len(sl.pages), exclude=(idx,))
            if target is None:
                stayed += 1
                continue
            self.migrate(rid, target)
            moved_s += 1
        self.events.append(("drain", self.step_index, idx,
                            moved_q, moved_s, stayed))
        return {"requeued": moved_q, "migrated": moved_s,
                "stayed": stayed}

    def _requeue(self, req, exclude: tuple) -> tuple:
        """Re-place a withdrawn request (already counted submitted) on
        another engine — same `_place` loop and retry budget as the
        front door; all-shed resolves at fleet scope like submit."""
        verdict, idx = self._place(
            req, self.rank_engines(req, exclude=exclude), "fleet-drain")
        if idx >= 0:
            self.counters["requeued"] += 1
            self.events.append(("requeue", self.step_index, req.rid,
                                idx))
        return verdict, idx

    # -- migration --------------------------------------------------------

    def _adopt_target(self, n_pages: int,
                      exclude: tuple = ()) -> Optional[int]:
        """Least-loaded accepting engine that can adopt ``n_pages``
        right now (None when nobody can) — deterministic tiebreak on
        the index."""
        best = None
        for i, e in enumerate(self.engines):
            if i in exclude or not self.accepting[i]:
                continue
            if not can_adopt(e, n_pages):
                continue
            key = (e.sched.page_utilization(), len(e.sched.queue), i)
            if best is None or key < best[0]:
                best = (key, i)
        return None if best is None else best[1]

    def migrate(self, rid: int, dst: Optional[int] = None) -> int:
        """Live-migrate ``rid`` to engine ``dst`` (default: the best
        adoptable target).  The session's remaining decode is bitwise
        identical to the unmigrated run (fleet-smoke gate).  Returns
        the destination index."""
        src = self.placement.get(rid)
        if src is None:
            raise ValueError(f"rid {rid} is not placed on this fleet")
        slot = self.engines[src].slot_of_rid(rid)
        if slot is None:
            raise ValueError(f"rid {rid} has no live slot on engine "
                             f"{src} (queued or already resolved)")
        if dst is None:
            dst = self._adopt_target(len(slot.pages), exclude=(src,))
            if dst is None:
                raise RuntimeError(
                    f"no engine can adopt rid {rid} "
                    f"({len(slot.pages)} pages) right now")
        capsule = migrate_session(self.engines[src], self.engines[dst],
                                  rid)
        self._log(src, "extract", rid)
        self._log(dst, "adopt", capsule)
        self.placement[rid] = dst
        self.counters["migrations"] += 1
        self.events.append(("migrate", self.step_index, rid, src, dst))
        return dst
