"""Fleet — N `ServeEngine`s behind one front door (ISSUE 13 tentpole,
elastic since ISSUE 17).

The fleet steps its member engines in LOCKSTEP on one shared step
clock, so everything the serving stack already guarantees per engine —
deterministic replay, exact counters, the zero-silent-drops contract —
lifts to fleet scope unchanged: two runs of the same (model, trace,
plans) produce identical fleet AND per-engine counters.

**Routing** (`Fleet.submit`): requests are scored against live
per-engine signals — exactly the quantities the PR 10 admission
machinery already computes:

| signal            | source                                | meaning |
|-------------------|---------------------------------------|---------|
| ``rung_sheds``    | `ServeSupervisor.rung.shed_class_above` | the engine's degradation rung would SHED this class |
| ``ttft_bound``    | `Scheduler.ttft_bound_steps(req)`     | structural lower bound on first-token dispatches |
| ``prefix_hits``   | `PrefixCache.lookup(..., peek=True)`  | full prefix pages already resident (affinity) |
| ``page_util``     | `Scheduler.page_utilization()`        | pool pressure |
| ``queue_len``     | ``len(Scheduler.queue)``              | backlog depth |

Per-SLA-class policy (docs/SERVING.md "Fleet" has the table):
class 0 (premium) routes **least-TTFT-bound** — (rung_sheds,
ttft_bound, -prefix_hits, page_util, queue_len, index); best-effort
(class >= 1) routes **load-spread with prefix affinity** —
(rung_sheds, -prefix_hits, page_util, queue_len, ttft_bound, index).
Ties fall to the engine index, so routing is deterministic.

A SHED verdict triggers **bounded retry** on the next-best engine
(``retry_limit``, default: every engine once); only when every tried
engine sheds is the rid resolved at FLEET scope (``Fleet.shed`` store,
``fleet_shed`` counter) — `Fleet.unresolved()` is therefore empty on a
drained fleet: every submitted rid resolved FINISHED/SHED/DEADLINE_MISS
*somewhere*, across routing retries, migration, engine kills and
scaling.

**Recovery** (the ``engine_kill@s:e`` fleet fault kind): the fleet
keeps, per engine, the last periodic digest-sealed snapshot
(`ServeEngine.snapshot`, every ``snapshot_every`` steps plus one at
construction) and a **replay log** of every control-plane operation
since (submissions — shed attempts included — capsule adoptions,
extractions, queue withdrawals, in order).  A killed engine is rebuilt
by restoring the snapshot and re-applying the log while stepping back
up to the fleet clock — deterministically identical state to the
moment of death, because every engine step is a pure function of
(state, submissions) — and is then **drained**: admissions close,
queued work re-routes to the survivors, live sessions migrate out
where capacity allows (`fleet.migrate`), and whatever cannot move
finishes locally.  Zero silent drops, counters exact across runs (the
fleet-smoke drill pins it, ×2).  ``kill_wave@s:{count}`` is the
coordinated multi-engine version: the wave closes admissions on every
victim FIRST (so drain migration lands only on true survivors), then
runs the same recover-and-drain per victim — always leaving at least
one accepting engine; any shortfall is counted
(``kill_wave_shortfall``), never silent.

**Durability** (ISSUE 20): pass ``store=`` (a
`cpd_tpu.store.DurableStore`) and the whole persistence story moves
onto the crash-consistent store plane.  Engine snapshots publish as
sealed generations of per-engine sub-stores (``engine<i>``), the
fleet's own control state (flags, counters, shape log, and the engine
snapshot tokens of the round) publishes to the ``fleet`` sub-store
AFTER every engine of the round — so the newest valid ``fleet``
generation always names a **consistent cut**: a complete snapshot
round, never a half-written one.  Migrations write through a durable
**capsule log** (``capsules`` sub-store): the capsule is parked as a
sealed generation before the destination restore, and a claim record
is appended once the session lands — park without claim is exactly
the crash window where an in-memory fleet loses the session.
`Fleet.cold_restore` rebuilds the whole fleet after total process
death from that cut: every engine restores bitwise from its named
generation, placement is rebuilt from the restored in-flight sets,
and unclaimed parked capsules re-adopt **exactly once** (a parked rid
already live in a restored snapshot is superseded — claimed, never
duplicated).  Resumed sessions decode bitwise at (8, 23); the
store-smoke drill pins restore-vs-uninterrupted byte equality and
exact counters ×2.

**Elasticity** (ISSUE 17): `spawn_engine` adds capacity mid-run (the
new engine joins the shared step clock AT the current fleet step) and
`scale_down` retires it through the SAME drain + capsule-migration
path as recovery, so scale-down loses zero sessions and the migrated
sessions' remaining decode stays bitwise identical.  Engine rows are
slot-stable: a retired engine keeps its index (historical events and
counters stay addressable) until `spawn_engine` RECYCLES the row —
reuse-first keeps the per-engine control-plane arrays bounded at the
fleet's peak concurrent width (``AutoscalePolicy.max_engines`` under
the autoscaler) however long the scale churn runs.  A recycled row's
final counters fold into an accumulator first, so
`aggregate_counters` stays exact across arbitrary churn.  Scaling
decisions, kills and retirements append to the bounded ``shape_log`` —
two runs of the same inputs produce the identical shape history (the
soak gate pins it ×2).
"""

from __future__ import annotations

import json
import os
from collections import deque
from typing import Optional

from ..resilience.inject import FLEET_KINDS, FaultPlan
from ..serve.engine import ResultStore, ServeEngine
from ..serve.scheduler import FREE, SHED
from .migrate import SessionCapsule, can_adopt, extract_capsule, \
    migrate_session, restore_capsule
from .prefix import PrefixCache

__all__ = ["Fleet"]

_FLEET_COUNTERS = ("submitted", "routed", "router_retries", "fleet_shed",
                   "migrations", "requeued", "engine_kills",
                   "sessions_recovered", "drains",
                   "fleet_faults_unfired", "kill_waves",
                   "kill_wave_shortfall", "engines_spawned",
                   "engines_retired", "capsules_parked",
                   "capsules_claimed", "cold_restores")

_FLEET_STATE = "fleet.json"


def _detuple(x):
    """JSON round-trips tuples as lists; shape-log entries are tuples
    (nested, for kill_wave victims) and the ×2 determinism drills
    compare them structurally — re-tuple on the way back in."""
    return tuple(_detuple(v) for v in x) if isinstance(x, list) else x


class Fleet:
    """N engines, one front door (module docstring).

    Parameters
    ----------
    model, params : shared by every engine (the fleet serves ONE
        model; jitted step programs are shared through the serve-side
        step cache, so N engines compile once).
    n_engines : initial fleet width (the live width changes under
        `spawn_engine` / `scale_down` / the autoscaler).
    engine_kw : `ServeEngine` keyword dict applied to every engine
        (n_slots, max_seq, kv_format, ...) — including engines spawned
        later.
    prefix_cache_pages : when set, every engine gets its own
        `PrefixCache(capacity_pages=...)` — per-engine, because page
        ids are pool-local; the router's affinity signal steers
        shared-prefix traffic back to the engine holding the pages.
    fault_plan : fleet-clock chaos (`FLEET_KINDS`: ``engine_kill``,
        ``kill_wave``).  Requires ``snapshot_every`` > 0 and
        ``snapshot_dir`` — a kill without a snapshot to recover from
        would be a guaranteed drop, so it fails fast here instead.
    engine_plans : optional per-engine `FaultPlan` list (the serving
        chaos kinds, aimed at individual engines).  Applies to the
        INITIAL engines; spawned engines carry no plan.
    tracers : optional per-engine `obs.Tracer` list — each engine's
        timeline becomes its own process lane in the merged Chrome
        trace (`obs.export.merge_chrome_traces`).  Initial engines
        only, like ``engine_plans``.
    retry_limit : max engines tried per submission (default: all).
    snapshot_every : periodic per-engine snapshot cadence in fleet
        steps (0 = never; then engine kills cannot be recovered).
    snapshot_dir : directory for ``engine<i>`` snapshot subdirs
        (legacy path — superseded by ``store`` when both are given).
    store : optional `cpd_tpu.store.DurableStore` — the durable state
        plane (module docstring "Durability").  Engine snapshots,
        fleet control state and the migration capsule log all publish
        through it as sealed, fenced, crash-consistent generations;
        `Fleet.cold_restore` rebuilds the fleet from it after total
        process death.  With a store, ``snapshot_dir`` is unnecessary.
    autoscaler : optional `cpd_tpu.fleet.autoscale.Autoscaler` —
        observed once per step (after fleet faults fire), drives
        `spawn_engine` / `scale_down` deterministically.
    """

    def __init__(self, model, params, n_engines: int = 2, *,
                 engine_kw: Optional[dict] = None,
                 prefix_cache_pages: Optional[int] = None,
                 fault_plan: Optional[FaultPlan] = None,
                 engine_plans: Optional[list] = None,
                 tracers: Optional[list] = None,
                 retry_limit: Optional[int] = None,
                 snapshot_every: int = 0,
                 snapshot_dir: Optional[str] = None,
                 store=None,
                 finished_cap: int = 4096,
                 autoscaler=None):
        if n_engines < 1:
            raise ValueError(f"n_engines must be >= 1, got {n_engines}")
        if engine_plans is not None and len(engine_plans) != n_engines:
            raise ValueError(f"engine_plans must have one entry per "
                             f"engine ({n_engines}), got "
                             f"{len(engine_plans)}")
        if tracers is not None and len(tracers) != n_engines:
            raise ValueError(f"tracers must have one entry per engine "
                             f"({n_engines}), got {len(tracers)}")
        if autoscaler is not None:
            p = autoscaler.policy
            if not (p.min_engines <= n_engines <= p.max_engines):
                raise ValueError(
                    f"n_engines={n_engines} outside the autoscaler's "
                    f"[{p.min_engines}, {p.max_engines}] band — the "
                    f"policy would fight the construction width on "
                    f"step 0")
        self._kills = list(fault_plan.fleet_faults()) if fault_plan \
            else []
        if fault_plan is not None:
            other = [f for f in fault_plan.faults
                     if f.kind not in FLEET_KINDS]
            if other:
                # "counted, never silent": the fleet consumes ONLY the
                # fleet-clock kinds — engine-clock specs riding this
                # plan would neither fire nor surface in any unfired
                # report, which is exactly the hole report_unfired
                # exists to close
                raise ValueError(
                    f"fleet fault_plan carries non-fleet kinds "
                    f"{sorted({f.kind for f in other})} — aim engine-"
                    f"clock chaos at individual engines via "
                    f"engine_plans=[...]")
        if self._kills and (snapshot_every < 1
                            or not (snapshot_dir or store)):
            raise ValueError(
                "engine_kill/kill_wave in the fault plan needs "
                "snapshot_every >= 1 and a snapshot_dir or store — a "
                "kill with no snapshot to recover from is a guaranteed "
                "silent drop, refused up front")
        self.model = model
        self.params = params
        self._engine_kw = dict(engine_kw or {})
        self._cache_pages = prefix_cache_pages
        self.retry_limit = retry_limit
        self.snapshot_every = int(snapshot_every)
        self.snapshot_dir = snapshot_dir
        self.store = store
        # the fencing epoch comes from the `fleet` sub-store (it gets a
        # publish every snapshot round, so its epochs see every writer
        # this fleet tree ever had); a predecessor's stale epoch is
        # refused at every sub-store from here on
        self._store_writer = (store.sub("fleet").acquire_writer()
                              if store is not None else None)
        self.autoscaler = autoscaler
        self.engines = []
        for i in range(n_engines):
            kw = dict(self._engine_kw)
            if prefix_cache_pages is not None:
                kw["prefix_cache"] = PrefixCache(prefix_cache_pages)
            if engine_plans is not None:
                kw["fault_plan"] = engine_plans[i]
            if tracers is not None:
                kw["tracer"] = tracers[i]
            self.engines.append(ServeEngine(model, params, **kw))
        self.accepting = [True] * n_engines
        self.draining = [False] * n_engines
        self.retired = [False] * n_engines
        # rid -> engine index, pruned to LIVE rids every step (resolved
        # placements age out — the fleet must not regrow the unbounded
        # dict the PR 10 ResultStore killed)
        self.placement: dict = {}
        self.shed = ResultStore(finished_cap)   # fleet-scope sheds
        self.counters = {k: 0 for k in _FLEET_COUNTERS}
        # bounded like the engine event log (~few events per incident)
        self.events = deque(maxlen=8 * finished_cap)
        # the fleet-shape history the ×2 determinism drills compare:
        # one entry per lifecycle change, bounded (shape changes are
        # rare next to requests)
        self.shape_log = deque(maxlen=256)
        self.shape_log.append(("init", 0, n_engines))
        # counters of engines whose row was RECYCLED (their objects are
        # gone); folded via whole-dict rebind, fixed key set
        self._retired_counters: dict = {}
        self.step_index = 0
        # per-engine control-plane replay logs since the last snapshot:
        # (step, op, payload) with op in submit/adopt/extract/withdraw.
        # Recorded ONLY when snapshotting is on — replay exists solely
        # for engine_kill recovery, and without snapshots the log would
        # retain every Request forever
        self._replay_enabled = bool(self.snapshot_every
                                    and (self.snapshot_dir
                                         or self.store is not None))
        self._logs: list = [[] for _ in range(n_engines)]
        # per-row token of the last snapshot generation published to
        # the store (rides fleet.json so cold_restore reads a
        # consistent cut instead of racing newest_valid per engine)
        self._snap_tokens: list = [None] * n_engines
        if self._replay_enabled:
            for i in range(n_engines):
                self._snapshot_engine(i)
            if self.store is not None:
                self._publish_fleet_state()

    @property
    def n_engines(self) -> int:
        """Engine ROWS (retired rows included until recycled) — the
        historical addressing width.  ``sum(accepting)`` is the live
        serving width."""
        return len(self.engines)

    def live_engines(self) -> list:
        """Indices of non-retired engines (stepping, draining or
        accepting)."""
        return [i for i in range(len(self.engines))
                if not self.retired[i]]

    # -- routing ----------------------------------------------------------

    def _signals(self, i: int, req) -> tuple:
        """One engine's routing score components for ``req``."""
        e = self.engines[i]
        sup = e.supervisor
        rung_sheds = int(sup is not None
                         and sup.rung.shed_class_above is not None
                         and req.sla_class >= sup.rung.shed_class_above)
        bound = e.sched.ttft_bound_steps(req)
        hits = 0
        if e.prefix_cache is not None:
            max_share = (len(req.prompt) - 1) // e.sched.page_size
            if max_share >= 1:
                hits = len(e.prefix_cache.lookup(
                    req.prompt, e.sched.page_size,
                    max_pages=max_share, peek=True))
        return (rung_sheds, bound, hits,
                e.sched.page_utilization(), len(e.sched.queue))

    def rank_engines(self, req, exclude: tuple = ()) -> list:
        """Engine indices best-first for ``req`` under the per-SLA-class
        policy (module docstring table).  Deterministic: every
        tiebreak ends at the engine index."""
        keyed = []
        for i in range(len(self.engines)):
            if i in exclude or not self.accepting[i]:
                continue
            rung_sheds, bound, hits, util, qlen = self._signals(i, req)
            if req.sla_class == 0:
                key = (rung_sheds, bound, -hits, util, qlen, i)
            else:
                key = (rung_sheds, -hits, util, qlen, bound, i)
            keyed.append((key, i))
        return [i for _key, i in sorted(keyed)]

    def _log(self, idx: int, op: str, payload) -> None:
        if self._replay_enabled:
            self._logs[idx].append((self.step_index, op, payload))

    def _place(self, req, order: list, shed_reason: str) -> tuple:
        """The ONE try-engines-best-first loop behind `submit` and the
        drain requeue — same bounded retry budget on both paths."""
        limit = len(order) if self.retry_limit is None \
            else min(self.retry_limit, len(order))
        for pos, idx in enumerate(order[:limit]):
            verdict = self.engines[idx].submit(req)
            self._log(idx, "submit", req)
            if verdict != SHED:
                self.placement[req.rid] = idx
                return verdict, idx
            if pos + 1 < limit:
                self.counters["router_retries"] += 1
        self.shed.put(req.rid, shed_reason)
        self.counters["fleet_shed"] += 1
        self.events.append(("fleet_shed", self.step_index, req.rid))
        return SHED, -1

    def submit(self, req) -> tuple:
        """Route one request: try engines best-first, bounded
        retry-on-SHED, fleet-scope SHED when every tried engine sheds.
        Returns ``(verdict, engine_index)`` (index -1 on fleet shed).

        Validation runs BEFORE the submitted counter moves
        (`ServeEngine.submit`'s phantom rule, fleet edition): an
        impossible request raising out of an engine after the count
        would read as a permanent fleet-scope silent drop.  Engines
        share one config, so any scheduler speaks for all."""
        self.engines[0].sched.validate(req)
        self.counters["submitted"] += 1
        verdict, idx = self._place(req, self.rank_engines(req),
                                   "fleet-admission")
        if idx >= 0:
            self.counters["routed"] += 1
        return verdict, idx

    # -- elasticity: spawn / scale-down / retire --------------------------

    def spawn_engine(self) -> int:
        """Add one engine mid-run: fresh state, the shared model/params
        (no new compilation — the serve-side step cache already holds
        the programs) and the FLEET's step clock, so lockstep and the
        replay-log recovery invariants hold for it like any founding
        member.  Recycles the lowest retired row first (class
        docstring: reuse-first is what bounds the per-engine arrays at
        the fleet's peak width); only when no row is free does the
        fleet widen.  Returns the engine index."""
        kw = dict(self._engine_kw)
        if self._cache_pages is not None:
            kw["prefix_cache"] = PrefixCache(self._cache_pages)
        eng = ServeEngine(self.model, self.params, **kw)
        # join the shared clock AT the current step: deadlines, scrub
        # cadence and the kill-replay window all assume engine step ==
        # fleet step
        eng.step_index = self.step_index
        idx = next((i for i, r in enumerate(self.retired) if r), None)
        if idx is not None:
            self._fold_retired_row(idx)
            self.engines[idx] = eng
            self.accepting[idx] = True
            self.draining[idx] = False
            self.retired[idx] = False
            self._logs[idx] = []
            self._snap_tokens[idx] = None
        else:
            idx = len(self.engines)
            # rebind-extend, not append: with reuse-first above, these
            # parallel rows only ever widen to the fleet's PEAK
            # concurrent width (max_engines under the autoscaler) —
            # scale churn recycles rows instead of growing them
            self.engines = self.engines + [eng]
            self.accepting = self.accepting + [True]
            self.draining = self.draining + [False]
            self.retired = self.retired + [False]
            self._logs = self._logs + [[]]
            self._snap_tokens = self._snap_tokens + [None]
        self.counters["engines_spawned"] += 1
        self.events.append(("spawn", self.step_index, idx))
        self.shape_log.append(("spawn", self.step_index, idx))
        if self._replay_enabled:
            self._snapshot_engine(idx)
            if self.store is not None:
                # the new row must be durably visible NOW: a cold
                # restore from the previous round's cut would silently
                # forget the spawn
                self._publish_fleet_state()
        return idx

    def scale_down(self, idx: int) -> dict:
        """Retire engine ``idx`` through the drain path: admissions
        close, queued work re-routes, live sessions migrate out via
        capsules (bitwise resume — zero sessions lost), the remainder
        completes locally; once drained the row retires (next `step`).
        Refuses to drop the last accepting engine."""
        if self.retired[idx]:
            raise ValueError(f"engine {idx} is already retired")
        if self.accepting[idx] and sum(self.accepting) <= 1:
            raise ValueError(
                "cannot scale down the last accepting engine — the "
                "fleet would refuse all traffic (kill chaos holds the "
                "same floor)")
        summary = self.drain_engine(idx)
        self.draining[idx] = True
        self.events.append(("scale_down", self.step_index, idx))
        self.shape_log.append(("scale_down", self.step_index, idx))
        return summary

    def _fold_retired_row(self, idx: int) -> None:
        """Fold a retired engine's final counters into the accumulator
        before its row is recycled — `aggregate_counters` must stay
        exact across arbitrary churn."""
        merged = dict(self._retired_counters)
        for k, v in self.engines[idx].counters.items():
            merged[k] = merged.get(k, 0) + int(v)
        self._retired_counters = merged

    def _finish_retirements(self) -> None:
        """Draining engines that have fully drained retire: they stop
        stepping and snapshotting, but keep their row (events, counters
        and any unfired-fault accounting stay addressable) until
        `spawn_engine` recycles it."""
        for i in range(len(self.engines)):
            if not self.draining[i] or self.retired[i]:
                continue
            if not self.engines[i].drained():
                continue
            self.retired[i] = True
            self.draining[i] = False
            self.counters["engines_retired"] += 1
            self.events.append(("retire", self.step_index, i))
            self.shape_log.append(("retire", self.step_index, i))

    # -- the fleet step ---------------------------------------------------

    def _kill_fireable(self, f) -> bool:
        """Can this spec still fire?  ``engine_kill``: its target row
        must EXIST and still accept — an index the fleet shape never
        grew to is exactly as unfireable as a drained engine (the
        autoscaled-shape hole ISSUE 17 closes: the old ``% n_engines``
        wrap silently re-aimed such specs at whatever engine the
        modulo landed on).  ``kill_wave``: needs >= 2 accepting engines
        (the wave must leave a survivor).  Unfireable specs stay
        pending only for `report_unfired`."""
        if f.kind == "kill_wave":
            return sum(self.accepting) >= 2
        target = max(int(f.arg), 0)
        return target < len(self.engines) and self.accepting[target]

    def has_pending_faults(self) -> bool:
        """True while fleet fault specs can still fire — the fleet
        load generator keeps the step clock running toward them (the
        `req_burst` convention lifted to fleet scope).  Unfireable
        specs (target drained, never-existing index, no wave quorum)
        are excluded, so a double-kill plan cannot livelock
        `run_fleet_trace`; they surface through `report_unfired`
        instead."""
        return any(self._kill_fireable(f) for f in self._kills)

    def step(self) -> None:
        s = self.step_index
        self._fire_fleet_faults(s)
        if self.autoscaler is not None:
            # after the faults: a kill wave's capacity hole is repaired
            # inside the same step (floor repair bypasses hysteresis)
            self.autoscaler.observe(self, s)
        for i, e in enumerate(self.engines):
            if not self.retired[i]:
                e.step()
        snap_round = (self._replay_enabled
                      and (s + 1) % self.snapshot_every == 0)
        if snap_round:
            for i in range(len(self.engines)):
                if not self.retired[i]:
                    self._snapshot_engine(i)
        self._finish_retirements()
        # resolved placements age out (bounded control-plane state):
        # only rids still in flight somewhere need their routing home
        self.placement = {rid: i for rid, i in self.placement.items()
                          if rid in self.engines[i]._inflight}
        self.step_index += 1
        if snap_round and self.store is not None:
            # the control-state generation lands AFTER every engine of
            # the round: the newest valid fleet.json therefore always
            # names a COMPLETE snapshot round (the consistent cut
            # cold_restore rebuilds from)
            self._publish_fleet_state()

    def drained(self) -> bool:
        return all(self.engines[i].drained()
                   for i in self.live_engines())

    def run_until_drained(self, max_steps: int = 100000) -> None:
        while not self.drained():
            if self.step_index >= max_steps:
                busy = [i for i in self.live_engines()
                        if not self.engines[i].drained()]
                raise RuntimeError(
                    f"fleet not drained after {max_steps} steps "
                    f"(busy engines: {busy})")
            self.step()

    def unresolved(self) -> list:
        """Submitted rids not yet resolved anywhere in the fleet —
        empty on a drained fleet (the fleet-scope zero-silent-drops
        acceptance check; migrations move the obligation with the
        session, fleet sheds resolve it here)."""
        out: set = set()
        for e in self.engines:
            out.update(e.unresolved())
        return sorted(out)

    def report_unfired(self) -> list:
        """Fleet fault specs that never fired — an ``engine_kill``
        scheduled past the end of the trace, aimed at a drained engine,
        or aimed at an index the (possibly autoscaled) fleet shape
        never contained; a ``kill_wave`` that never found two accepting
        engines.  Counted, never silent; the fleet twin of
        `ServeEngine.report_unfired` (which every member engine still
        runs for its own kinds)."""
        for e in self.engines:
            e.report_unfired()
        self.counters["fleet_faults_unfired"] = len(self._kills)
        return sorted(self._kills)

    def aggregate_counters(self) -> dict:
        """Sum of every engine's counter dict — including engines whose
        row was recycled by scale churn (their final counters live in
        the fold accumulator), so the roll-up the metrics and the
        ``cpd_fleet_*`` family report is exact across arbitrary
        spawn/retire history."""
        out = dict(self._retired_counters)
        for e in self.engines:
            for k, v in e.counters.items():
                out[k] = out.get(k, 0) + v
        return out

    # -- chaos: engine kill -> snapshot+replay recovery -> drain ----------

    def _fire_fleet_faults(self, s: int) -> None:
        still = []
        for f in self._kills:
            if f.step > s:
                still.append(f)
                continue
            if f.kind == "kill_wave":
                if sum(self.accepting) < 2:
                    still.append(f)  # held until the fleet regrows
                    continue
                self._kill_wave(f, s)
                continue
            target = max(int(f.arg), 0)
            if target >= len(self.engines) \
                    or not self.accepting[target]:
                # held: already dead/draining, or aimed at a row the
                # fleet shape never grew to (no modulo wrap — a kill
                # must hit the engine it names or surface as unfired)
                still.append(f)
                continue
            self._kill_engine(target, s)
        self._kills = still

    def _kill_wave(self, f, s: int) -> None:
        """``kill_wave@s:{count}``: kill up to ``count`` accepting
        engines at once, lowest indices first, ALWAYS leaving at least
        one accepting survivor.  Victim admissions close before any
        drain runs, so wave-drain migration lands only on engines that
        outlive the wave.  A shortfall (count > available victims) is
        counted, never silent."""
        count = int(f.arg) if f.arg > 0 else 2
        acc = [i for i, a in enumerate(self.accepting) if a]
        victims = acc[:min(count, len(acc) - 1)]
        self.counters["kill_waves"] += 1
        if count > len(victims):
            self.counters["kill_wave_shortfall"] += count - len(victims)
        self.events.append(("kill_wave", s, count, len(victims)))
        self.shape_log.append(("kill_wave", s, tuple(victims)))
        for v in victims:
            self.accepting[v] = False
        for v in victims:
            self._kill_engine(v, s)

    def _snapshot_engine(self, i: int) -> None:
        if self.store is not None:
            sub = self.store.sub(f"engine{i}")
            info = self.engines[i].snapshot_store(
                sub, writer=self._store_writer)
            self._snap_tokens[i] = list(info.token)
            # keep=2: the newest fleet.json names tokens at most one
            # round old (it publishes right after this round), so two
            # retained generations per engine always cover the cut
            sub.gc(keep=2)
        else:
            path = os.path.join(self.snapshot_dir, f"engine{i}")
            self.engines[i].snapshot(path)
        self._logs[i] = []

    def _publish_fleet_state(self) -> None:
        """Publish the fleet's control state as one sealed generation
        of the ``fleet`` sub-store — everything `cold_restore` needs
        that is not inside an engine snapshot, including the engine
        snapshot tokens of the round (the consistent cut)."""
        doc = {
            "version": 1,
            "step_index": self.step_index,
            "accepting": list(self.accepting),
            "draining": list(self.draining),
            "retired": list(self.retired),
            "counters": dict(self.counters),
            "retired_counters": dict(self._retired_counters),
            "shape_log": [list(x) for x in self.shape_log],
            "snapshot_every": self.snapshot_every,
            "retry_limit": self.retry_limit,
            "engine_tokens": list(self._snap_tokens),
        }
        sub = self.store.sub("fleet")
        sub.publish(
            {_FLEET_STATE: json.dumps(doc, sort_keys=True).encode()},
            step=self.step_index, meta={"surface": "fleet"},
            writer=self._store_writer)
        sub.gc(keep=4)

    def _kill_engine(self, idx: int, s: int) -> None:
        """The ``engine_kill`` handler (module docstring): rebuild the
        engine from its last snapshot + the deterministic replay log,
        then drain it onto the survivors.  The drained engine finishes
        its unmigratable local work and RETIRES (ISSUE 17) — replaced
        capacity comes from the autoscaler's floor repair, not from
        re-opening the dead row."""
        self.counters["engine_kills"] += 1
        self.events.append(("engine_kill", s, idx))
        dead = self.engines[idx]
        # capacity is adopted from the snapshot blob on load; the
        # constructor arg is a placeholder
        cache = (PrefixCache(self._cache_pages or 1)
                 if dead.prefix_cache is not None else None)
        if self.store is not None:
            restored = ServeEngine.restore_store(
                self.model, self.params, self.store.sub(f"engine{idx}"),
                prefix_cache=cache)
        else:
            path = os.path.join(self.snapshot_dir, f"engine{idx}")
            restored = ServeEngine.restore(self.model, self.params,
                                           path, prefix_cache=cache)
        self.engines[idx] = restored
        log = self._logs[idx]
        for fs in range(restored.step_index, s):
            self._replay_ops(idx, log, fs)
            restored.step()
        self._replay_ops(idx, log, s)
        # the obs lane re-attaches AFTER the replay — the dead engine's
        # tracer already holds the pre-kill timeline, and replaying
        # into it would duplicate every event
        restored.tracer = dead.tracer
        restored.flight = dead.flight
        self.counters["sessions_recovered"] += (
            sum(sl.state != FREE for sl in restored.sched.slots)
            + len(restored.sched.queue))
        self.drain_engine(idx)
        self.draining[idx] = True

    def _replay_ops(self, idx: int, log: list, fs: int) -> None:
        eng = self.engines[idx]
        for step, op, payload in log:
            if step != fs:
                continue
            if op == "submit":
                eng.submit(payload)
            elif op == "adopt":
                restore_capsule(eng, payload)
            elif op == "extract":
                extract_capsule(eng, payload)
            elif op == "withdraw":
                eng.withdraw(payload)

    def drain_engine(self, idx: int) -> dict:
        """Close engine ``idx`` to new work and move what can move:
        queued requests re-route through the router (excluding the
        drained engine), live sessions migrate out where a survivor
        can adopt them; the remainder completes locally (the engine
        keeps stepping with admissions closed).  Returns the drain
        summary.  Also the scale-in primitive (`scale_down` adds the
        retirement bookkeeping)."""
        self.counters["drains"] += 1
        self.accepting[idx] = False
        e = self.engines[idx]
        moved_q = moved_s = stayed = 0
        for q in list(e.sched.queue):
            req = e.withdraw(q.rid)
            self._log(idx, "withdraw", q.rid)
            self.placement.pop(q.rid, None)
            self._requeue(req, exclude=(idx,))
            moved_q += 1
        for sl in list(e.sched.slots):
            if sl.state == FREE:
                continue
            rid = sl.req.rid
            target = self._adopt_target(len(sl.pages), exclude=(idx,))
            if target is None:
                stayed += 1
                continue
            self.migrate(rid, target)
            moved_s += 1
        self.events.append(("drain", self.step_index, idx,
                            moved_q, moved_s, stayed))
        return {"requeued": moved_q, "migrated": moved_s,
                "stayed": stayed}

    def _requeue(self, req, exclude: tuple) -> tuple:
        """Re-place a withdrawn request (already counted submitted) on
        another engine — same `_place` loop and retry budget as the
        front door; all-shed resolves at fleet scope like submit."""
        verdict, idx = self._place(
            req, self.rank_engines(req, exclude=exclude), "fleet-drain")
        if idx >= 0:
            self.counters["requeued"] += 1
            self.events.append(("requeue", self.step_index, req.rid,
                                idx))
        return verdict, idx

    # -- migration --------------------------------------------------------

    def _adopt_target(self, n_pages: int,
                      exclude: tuple = ()) -> Optional[int]:
        """Least-loaded accepting engine that can adopt ``n_pages``
        right now (None when nobody can) — deterministic tiebreak on
        the index."""
        best = None
        for i, e in enumerate(self.engines):
            if i in exclude or not self.accepting[i]:
                continue
            if not can_adopt(e, n_pages):
                continue
            key = (e.sched.page_utilization(), len(e.sched.queue), i)
            if best is None or key < best[0]:
                best = (key, i)
        return None if best is None else best[1]

    def migrate(self, rid: int, dst: Optional[int] = None) -> int:
        """Live-migrate ``rid`` to engine ``dst`` (default: the best
        adoptable target).  The session's remaining decode is bitwise
        identical to the unmigrated run (fleet-smoke gate).  Returns
        the destination index."""
        src = self.placement.get(rid)
        if src is None:
            raise ValueError(f"rid {rid} is not placed on this fleet")
        slot = self.engines[src].slot_of_rid(rid)
        if slot is None:
            raise ValueError(f"rid {rid} has no live slot on engine "
                             f"{src} (queued or already resolved)")
        if dst is None:
            dst = self._adopt_target(len(slot.pages), exclude=(src,))
            if dst is None:
                raise RuntimeError(
                    f"no engine can adopt rid {rid} "
                    f"({len(slot.pages)} pages) right now")
        if self.store is None:
            capsule = migrate_session(self.engines[src],
                                      self.engines[dst], rid)
        else:
            capsule = self._migrate_logged(src, dst, rid)
        self._log(src, "extract", rid)
        self._log(dst, "adopt", capsule)
        self.placement[rid] = dst
        self.counters["migrations"] += 1
        self.events.append(("migrate", self.step_index, rid, src, dst))
        return dst

    # -- the durable capsule log (store mode) -----------------------------

    def _cap_store(self):
        return self.store.sub("capsules")

    def _claim(self, token, engine: int, reason: str) -> None:
        """Append a claim record for a parked capsule generation —
        the exactly-once fence: an unclaimed park is precisely the
        crash window `cold_restore` must repair, a claimed one must
        never be adopted again."""
        rec = {"claim": list(token), "engine": int(engine),
               "reason": reason}
        self._cap_store().publish(
            {"claim.json": json.dumps(rec, sort_keys=True).encode()},
            step=self.step_index,
            meta={"surface": "claim", "claim": list(token)},
            writer=self._store_writer)
        self.counters["capsules_claimed"] += 1

    def _migrate_logged(self, src: int, dst: int, rid: int):
        """`migrate_session` written through the durable capsule log:
        park (sealed generation) BEFORE the destination restore, claim
        AFTER the session lands — a crash anywhere in between leaves a
        parked-unclaimed generation that `cold_restore` re-adopts
        instead of a lost session.  The failed-restore path also
        claims (back onto the source), so the log never double-counts
        a session that was put back."""
        s_eng, d_eng = self.engines[src], self.engines[dst]
        slot = s_eng.slot_of_rid(rid)
        if slot is None:
            raise ValueError(f"rid {rid} has no live slot to migrate")
        if not can_adopt(d_eng, len(slot.pages)):
            raise RuntimeError(
                f"destination cannot adopt rid {rid} "
                f"({len(slot.pages)} pages): no free slot or pages")
        capsule = extract_capsule(s_eng, rid)
        info = capsule.to_store(
            self._cap_store(), step=self.step_index,
            meta={"parked": True, "src": src, "dst": dst},
            writer=self._store_writer)
        self.counters["capsules_parked"] += 1
        try:
            restore_capsule(d_eng, capsule)
        except Exception:
            restore_capsule(s_eng, capsule)
            self._claim(info.token, src, "restore-failed")
            raise
        self._claim(info.token, dst, "migrated")
        return capsule

    def park_session(self, rid: int):
        """Extract ``rid`` into the durable capsule log WITHOUT
        restoring it anywhere — the deliberate park (drain with no
        adoptive capacity, operator handoff, pre-shutdown stash).  The
        session's zero-silent-drops obligation now rides the sealed
        generation; `adopt_parked` (or the next `cold_restore`)
        re-adopts it exactly once.  Returns the parked
        `GenerationInfo`."""
        if self.store is None:
            raise RuntimeError("park_session needs a fleet store "
                               "(construct the Fleet with store=)")
        src = self.placement.get(rid)
        if src is None:
            raise ValueError(f"rid {rid} is not placed on this fleet")
        capsule = extract_capsule(self.engines[src], rid)
        self._log(src, "extract", rid)
        info = capsule.to_store(
            self._cap_store(), step=self.step_index,
            meta={"parked": True, "src": src},
            writer=self._store_writer)
        self.counters["capsules_parked"] += 1
        self.placement.pop(rid, None)
        self.events.append(("park", self.step_index, rid, src))
        return info

    def parked_unclaimed(self) -> list:
        """Parked capsule generations with no claim record, oldest
        first (adoption order is deterministic).  Torn log entries are
        quarantined by the scan, never misread."""
        claimed, parked = set(), []
        for info in self._cap_store().valid_generations():
            meta = info.meta
            if meta.get("claim"):
                claimed.add(tuple(meta["claim"]))
            elif meta.get("parked"):
                parked.append(info)
        return [i for i in sorted(parked, key=lambda g: g.token)
                if i.token not in claimed]

    def adopt_parked(self) -> list:
        """Re-adopt every unclaimed parked capsule an engine can take
        right now, exactly once each (a claim record lands per
        adoption).  A parked rid already live somewhere — the park's
        extraction happened AFTER the snapshot cut a cold restore
        rewound to — is superseded: claimed without adoption, because
        the in-engine copy IS the consistent one.  Capsules nobody can
        hold yet stay parked for the next call.  Returns adopted
        rids."""
        adopted = []
        for info in self.parked_unclaimed():
            capsule = SessionCapsule.from_store(self._cap_store(),
                                                token=info.token)
            rid = capsule.rid
            if any(rid in self.engines[i]._inflight
                   for i in self.live_engines()):
                self._claim(info.token, -1, "superseded")
                self.events.append(("park_superseded", self.step_index,
                                    rid))
                continue
            dst = self._adopt_target(capsule.n_pages)
            if dst is None:
                self.events.append(("park_stayed", self.step_index,
                                    rid))
                continue
            restore_capsule(self.engines[dst], capsule)
            self._log(dst, "adopt", capsule)
            self.placement[rid] = dst
            self._claim(info.token, dst, "adopted")
            self.events.append(("adopt_parked", self.step_index, rid,
                                dst))
            adopted.append(rid)
        return adopted

    # -- whole-fleet cold restore (store mode) ----------------------------

    @classmethod
    def cold_restore(cls, model, params, store, *,
                     engine_kw: Optional[dict] = None,
                     prefix_cache_pages: Optional[int] = None,
                     retry_limit: Optional[int] = None,
                     finished_cap: int = 4096,
                     autoscaler=None) -> "Fleet":
        """Rebuild a whole fleet after TOTAL process death from its
        durable store (module docstring "Durability").  The newest
        valid ``fleet`` generation names the engine snapshot tokens of
        the last COMPLETE round (the consistent cut — it publishes
        only after every engine of the round); each engine restores
        bitwise from its named generation, placement rebuilds from the
        restored in-flight sets, and unclaimed parked capsules
        re-adopt exactly once.  Resumed sessions decode bitwise at
        (8, 23).  A fresh writer epoch is acquired, so the dead
        fleet's writer is fenced from here on."""
        fleet_store = store.sub("fleet")
        info = fleet_store.newest_valid()
        if info is None:
            raise FileNotFoundError(
                f"no valid fleet state generation under "
                f"{fleet_store.root} — nothing to cold-restore")
        doc = json.loads(fleet_store.read(info, _FLEET_STATE).decode())
        n = len(doc["accepting"])
        self = cls.__new__(cls)
        self.model = model
        self.params = params
        self._engine_kw = dict(engine_kw or {})
        self._cache_pages = prefix_cache_pages
        self.retry_limit = (retry_limit if retry_limit is not None
                            else doc.get("retry_limit"))
        self.snapshot_every = int(doc["snapshot_every"])
        self.snapshot_dir = None
        self.store = store
        self._store_writer = fleet_store.acquire_writer()
        self.autoscaler = autoscaler
        self._kills = []
        self.engines = []
        for i in range(n):
            cache = (PrefixCache(prefix_cache_pages)
                     if prefix_cache_pages is not None else None)
            tok = doc["engine_tokens"][i]
            self.engines.append(ServeEngine.restore_store(
                model, params, store.sub(f"engine{i}"),
                prefix_cache=cache,
                token=tuple(tok) if tok else None))
        self.accepting = [bool(a) for a in doc["accepting"]]
        self.draining = [bool(d) for d in doc["draining"]]
        self.retired = [bool(r) for r in doc["retired"]]
        self.shed = ResultStore(finished_cap)
        self.counters = {k: 0 for k in _FLEET_COUNTERS}
        self.counters.update(
            {k: int(v) for k, v in doc["counters"].items()})
        self.events = deque(maxlen=8 * finished_cap)
        self.shape_log = deque((_detuple(e) for e in doc["shape_log"]),
                               maxlen=256)
        self._retired_counters = {
            k: int(v) for k, v in doc["retired_counters"].items()}
        self.step_index = int(doc["step_index"])
        self._replay_enabled = bool(self.snapshot_every)
        self._logs = [[] for _ in range(n)]
        self._snap_tokens = [list(t) if t else None
                             for t in doc["engine_tokens"]]
        self.placement = {}
        for i in self.live_engines():
            for rid in sorted(self.engines[i]._inflight):
                self.placement[rid] = i
        self.counters["cold_restores"] += 1
        self.events.append(("cold_restore", self.step_index, n))
        self.shape_log.append(("cold_restore", self.step_index, n))
        self.adopt_parked()
        return self
