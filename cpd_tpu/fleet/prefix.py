"""Content-addressed prefix cache — shared prompt prefixes, CoW pages.

Identical prompt prefixes are everywhere in real serving traffic
(system prompts, few-shot preambles, multi-turn history), and the paged
KV cache already makes their K/V bytes *identical by construction*:
quantize-on-append (serve/model.py) writes page bytes as a pure
function of the token prefix and the params, independent of when or in
which slot the positions were computed.  So a FULL prefill page — all
``page_size`` positions fed, all of them prompt tokens — can be shared
copy-on-write across every request whose prompt starts with the same
tokens, and the shared read is **bitwise identical** to a cold prefill
(gated in tests/test_fleet.py and the fleet-smoke).

Index discipline (the collision-confirmation rule, ISSUE 13):

* entries are keyed by a position-weighted Fletcher digest of the
  TOKEN prefix (`token_digest` — the same mod-65521 family as the page
  digests in `parallel.integrity`), so lookup is content-addressed;
* a digest hit is only ever shared after a full **byte comparison** of
  the stored token prefix against the query — a Fletcher collision
  (16+16 bits cannot be injective) must NEVER leak one tenant's KV
  bytes into another tenant's attention window.  The crafted-collision
  test pins this: two different prefixes with equal digests do not
  share.

Copy-on-write mechanics (the engine side, serve/engine.py):

* only FULL prompt pages are indexed — appends always land past them,
  so a shared page is never written by a tenant (seal-on-share is
  structural, not a flag);
* sharing is refcounted through the ONE scheduler allocation
  discipline (`Scheduler.retain`/`release`): the cache holds its own
  reference, so shared K/V outlives the request that computed it, and
  a page returns to the pool exactly when its last reference drops;
* paths that must WRITE (watchdog re-prefill, capsule adoption) first
  move the slot onto fresh private pages (copy-before-append);
  corruption repair recomputes in place — identical prefixes write
  identical bytes, so the rewrite restores the shared page for every
  reader;
* a corrupt cache-held page with no live reader is invalidated
  (`invalidate_page`), never re-blessed and served to a future tenant.

The cache is bounded (``capacity_pages``); past it the LRU entry is
evicted and its page reference released.  All state is host-side and
deterministic — `state_dict` rides the engine snapshot so a restored
engine resumes with the identical index, held pages and LRU order.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Optional, Sequence

__all__ = ["PrefixCache", "token_digest"]

_MOD = 65521   # largest prime < 2^16 — the repo's Fletcher modulus


def token_digest(tokens: Sequence[int]) -> int:
    """Position-weighted Fletcher digest of a token sequence (mod
    65521, the `parallel.integrity` family): ``s1`` sums the tokens,
    ``s2`` sums the running sums — so position matters — and the
    +1 offset keeps leading zero tokens from vanishing.  32 bits of
    digest cannot be injective over token sequences, which is exactly
    why `PrefixCache.lookup` byte-confirms every hit."""
    s1 = s2 = 0
    for t in tokens:
        s1 = (s1 + int(t) + 1) % _MOD
        s2 = (s2 + s1) % _MOD
    return (s2 << 16) | s1


class PrefixCache:
    """Bounded digest-indexed, byte-confirmed prefix-page index
    (module docstring).  The cache owns NO pages itself — the engine
    performs every `Scheduler.retain`/`release` on its behalf, driven
    by the return values here, so allocation stays in one place.

    Parameters
    ----------
    capacity_pages : bound on indexed pages; past it the LRU entry is
        evicted (`register` returns the displaced page ids for the
        engine to release).
    """

    def __init__(self, capacity_pages: int = 256):
        if capacity_pages < 1:
            raise ValueError(f"capacity_pages must be >= 1, got "
                             f"{capacity_pages}")
        self.capacity_pages = int(capacity_pages)
        # token-prefix tuple -> page id, in LRU order (oldest first)
        self._entries: OrderedDict = OrderedDict()
        # digest -> [token-prefix tuple, ...] collision chains
        self._index: dict = {}
        self.lookups = 0
        self.confirmed_hits = 0
        self.collisions_rejected = 0

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def held_pages(self) -> list:
        """Page ids the cache currently references (index order)."""
        return list(self._entries.values())

    # -- the read path ----------------------------------------------------

    def _find(self, prefix: tuple) -> Optional[tuple]:
        """Digest lookup + the byte confirmation (module docstring).
        Returns the stored key on a CONFIRMED hit, None otherwise —
        and counts a digest hit whose bytes differ (the collision a
        32-bit Fletcher cannot rule out)."""
        chain = self._index.get(token_digest(prefix))
        if not chain:
            return None
        for key in chain:
            if key == prefix:            # full byte comparison
                return key
        self.collisions_rejected += 1
        return None

    def lookup(self, prompt: Sequence[int], page_size: int, *,
               max_pages: Optional[int] = None,
               peek: bool = False) -> list:
        """Longest confirmed run of full prefix pages for ``prompt``:
        page ids for pages 0..k-1 where every page's token prefix is
        byte-confirmed in the index (pages may come from different
        registrations — any page registered under the exact prefix
        holds identical bytes).  ``max_pages`` caps the run (the engine
        always leaves at least one prompt token to feed); ``peek=True``
        skips the LRU touch AND the hit statistics (router affinity
        probes — one per engine per submission — must perturb neither
        the deterministic eviction order nor the hit-rate numbers).

        The Fletcher sums are prefix-extendable, so the scan carries
        (s1, s2) across pages instead of re-hashing each prefix from
        scratch, and only materializes the prefix tuple (for the byte
        confirmation) when the digest chain is non-empty — a miss
        costs O(page_size) per page, not O(prefix)."""
        if not peek:
            self.lookups += 1
        limit = len(prompt) // page_size
        if max_pages is not None:
            limit = min(limit, max_pages)
        pages = []
        s1 = s2 = 0
        for j in range(limit):
            for t in prompt[j * page_size:(j + 1) * page_size]:
                s1 = (s1 + int(t) + 1) % _MOD
                s2 = (s2 + s1) % _MOD
            chain = self._index.get((s2 << 16) | s1)
            if not chain:
                break
            prefix = tuple(int(t) for t in prompt[:(j + 1) * page_size])
            key = next((k for k in chain if k == prefix), None)
            if key is None:
                self.collisions_rejected += 1
                break
            if not peek:
                self._entries.move_to_end(key)
                self.confirmed_hits += 1
            pages.append(self._entries[key])
        return pages

    # -- the write path ---------------------------------------------------

    def register(self, prefix: Sequence[int], page_id: int) -> tuple:
        """Index ``page_id`` as holding the K/V of exactly ``prefix``.
        Returns ``(fresh, evicted_page_ids)``: ``fresh`` is False when
        an identical prefix is already indexed (the caller keeps its
        reference count unchanged); ``evicted_page_ids`` are LRU
        entries displaced past capacity — the caller releases each."""
        prefix = tuple(int(t) for t in prefix)
        if not prefix:
            raise ValueError("cannot register an empty prefix")
        if self._find(prefix) is not None:
            return False, []
        self._entries[prefix] = int(page_id)
        self._index.setdefault(token_digest(prefix), []).append(prefix)
        evicted = []
        while len(self._entries) > self.capacity_pages:
            pid = self.evict_lru()
            if pid is not None:
                evicted.append(pid)
        return True, evicted

    def evict_lru(self) -> Optional[int]:
        """Drop the least-recently-used entry; returns its page id for
        the caller to release (None when empty).  Capacity bounding —
        refcounts are irrelevant there, the index must stay bounded."""
        if not self._entries:
            return None
        key, pid = self._entries.popitem(last=False)
        self._unindex(key)
        return pid

    def evict_where(self, pred) -> Optional[int]:
        """Drop the OLDEST entry whose page id satisfies ``pred`` and
        return it (None when no entry qualifies).  The make-room path
        uses this with a sole-reference predicate: evicting an entry
        whose page a live slot still shares releases a reference but
        frees nothing, so those entries are skipped — they stay useful
        and the caller's free-list target stays honest."""
        for key, pid in self._entries.items():
            if pred(pid):
                del self._entries[key]
                self._unindex(key)
                return pid
        return None

    def invalidate_page(self, page_id: int) -> bool:
        """Drop every entry referencing ``page_id`` (a corrupt page
        must never be served to a future tenant).  Returns True when
        something was dropped — the caller then releases the cache's
        reference once."""
        victims = [k for k, p in self._entries.items() if p == page_id]
        for k in victims:
            del self._entries[k]
            self._unindex(k)
        return bool(victims)

    def _unindex(self, key: tuple) -> None:
        d = token_digest(key)
        chain = self._index.get(d, [])
        if key in chain:
            chain.remove(key)
        if not chain:
            self._index.pop(d, None)

    # -- snapshot persistence ---------------------------------------------

    def state_dict(self) -> dict:
        """JSON-able snapshot — entries in LRU order so a restored
        engine resumes with identical eviction behaviour."""
        return {"capacity_pages": self.capacity_pages,
                "entries": [{"tokens": list(k), "page_id": p}
                            for k, p in self._entries.items()]}

    def load_state_dict(self, state: dict) -> "PrefixCache":
        self.capacity_pages = int(state["capacity_pages"])
        self._entries = OrderedDict()
        self._index = {}
        for ent in state["entries"]:
            key = tuple(int(t) for t in ent["tokens"])
            self._entries[key] = int(ent["page_id"])
            self._index.setdefault(token_digest(key), []).append(key)
        return self

    def __repr__(self) -> str:
        return (f"PrefixCache(entries={len(self._entries)}, "
                f"capacity={self.capacity_pages}, "
                f"hits={self.confirmed_hits}, "
                f"collisions_rejected={self.collisions_rejected})")
