"""DurableStore — the one crash-consistent persistence plane.

Before this layer, the repo had THREE hand-rolled persistence paths
with three different atomicity stories: orbax's tmp-then-rename for
trainer checkpoints (plus a non-fsynced metadata sidecar), the
``.tmp``/``.old`` directory dance of `ServeEngine.snapshot`, and
`SessionCapsule.to_dir`'s plain writes (no atomicity at all).  Every
drilled defense — rollback, engine restore, session migration —
bottomed out in a filesystem write nothing ever attacked.  This module
is the single answer all three surfaces migrate onto.

A store is a directory of immutable **generations**.  One publish is::

    .tmp-gen-E-S/           mkdir
      <artifact>            write + fsync, one pair per artifact
      MANIFEST.json         write + fsync  (sealed; per-artifact sha256)
    fsync(.tmp-gen-E-S)     pin the directory entries
    rename -> gen-E-S       the commit point (atomic on POSIX)
    fsync(root)             pin the rename

Every one of those steps goes through `cpd_tpu.store.faultfs.FaultFS`,
so a crash (or injected EIO/ENOSPC) at ANY boundary leaves either the
fully sealed new generation or no trace of it — the crash matrix in
tools/bench_store.py kills a subprocess at every op and proves restore
always lands on a sealed, digest-valid generation.

Contracts:

* **Writer fencing** — generations are named by a monotonic
  ``(epoch, seq)`` token.  `acquire_writer` hands out ``max epoch + 1``;
  a publish from epoch *e* is refused (`FencedWriterError`) once any
  generation — valid, quarantined, or half-written — carries an epoch
  ``> e``.  A stale elastic-restart writer therefore cannot clobber or
  out-name the successor that replaced it.
* **Deterministic retry** — transient ``EIO`` / ``ENOSPC`` during a
  publish is retried up to ``retries`` times with an exponential
  *step-clock* backoff (counted in ``backoff_steps``, never slept:
  wall-clock sleeps are banned host-side, and the drills must be
  bitwise reproducible).  Non-transient ``OSError`` propagates at once.
* **Quarantine** — a generation that fails validation (torn artifact,
  flipped byte, unparsable or unsealed manifest, missing/extra file)
  is renamed into ``_quarantine/`` and counted.  Never silently
  deleted (it is evidence), never adopted (nothing reads quarantine).
* **Retention GC** — `gc(keep)` deletes only VALID generations beyond
  the ``keep`` newest and by construction can never touch the newest
  valid one (``keep >= 1`` is enforced; invalid generations met along
  the way are quarantined, not collected).

Chaos enters through the `FaultPlan` grammar (STORE_KINDS in
resilience/inject.py): ``store_eio@s:n`` / ``store_enospc@s:n`` fire on
the nth write op of publish number *s* (the store's own publish clock),
``store_torn@s:k`` / ``store_flip@s:k`` corrupt the generation publish
*s* sealed, at byte *k* — through the same `corrupt_file` body the
legacy checkpoint drills use.  `report_unfired` keeps the run honest in
both directions, exactly like every other fault family.

This module is deliberately pure stdlib (no numpy/jax) so the crash
matrix can fork subprocesses in ~0.1 s.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .faultfs import FaultFS, TRANSIENT_ERRNOS, corrupt_file

MANIFEST = "MANIFEST.json"
QUARANTINE = "_quarantine"

_GEN_RE = re.compile(r"^gen-(\d{8})-(\d{8})$")
_TMP_PREFIX = ".tmp-gen-"

# counter names, one spelling (mirrored by MetricsRegistry as
# ``cpd_store_*`` — see obs/registry.py `absorb_store_counters`)
STORE_COUNTERS = (
    "publishes", "publish_retries", "io_errors", "backoff_steps",
    "quarantined", "tmp_swept", "gc_collected", "restores",
    "fence_refusals", "torn_fired", "flip_fired", "eio_fired",
    "enospc_fired", "read_rejects",
)


class FencedWriterError(RuntimeError):
    """A stale writer (older epoch) tried to publish after a newer
    writer's generation appeared — refused, never clobbered."""


@dataclass
class GenerationInfo:
    """One generation directory, parsed from its name.  ``manifest`` is
    populated once the generation has been validated."""
    epoch: int
    seq: int
    path: str
    manifest: Optional[dict] = field(default=None, repr=False)

    @property
    def token(self):
        return (self.epoch, self.seq)

    @property
    def name(self) -> str:
        return os.path.basename(self.path)

    @property
    def step(self):
        return None if self.manifest is None else self.manifest.get("step")

    @property
    def meta(self) -> dict:
        return {} if self.manifest is None else dict(
            self.manifest.get("meta") or {})


def _seal(body: dict) -> str:
    blob = json.dumps(body, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()


def _check_artifact_name(name: str) -> str:
    if (not name or name == MANIFEST or name.startswith(".")
            or os.sep in name or "/" in name):
        raise ValueError(f"DurableStore: bad artifact name {name!r}")
    return name


class DurableStore:
    """Crash-consistent generation store rooted at ``root``.

    Args:
        root: directory holding ``gen-*`` generations (created if
            absent).  Sub-stores (`sub`) nest their roots inside it.
        fs: the `FaultFS` boundary; one is created if not given.  All
            sub-stores share it (one op clock per store tree).
        retries: max transient-error retries per publish.
        backoff_base: first retry's step-clock backoff; doubles per
            attempt (pure accounting — nothing sleeps).
        fault_plan: optional `resilience.inject.FaultPlan` (duck-typed:
            anything with ``store_faults()``); its STORE_KINDS specs
            arm this store tree's chaos.
    """

    def __init__(self, root: str, *, fs: Optional[FaultFS] = None,
                 retries: int = 3, backoff_base: int = 1,
                 fault_plan=None, _shared=None):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        if _shared is not None:
            # a sub-store: one fs / counters / clock / pending-fault
            # pool for the whole tree, so chaos and accounting span
            # every surface that hangs off the parent
            self.fs, self.counters, self._clock, self._pending = _shared
            self.retries = retries
            self.backoff_base = backoff_base
            return
        self.fs = fs if fs is not None else FaultFS()
        self.retries = int(retries)
        self.backoff_base = int(backoff_base)
        self.counters: Dict[str, int] = {k: 0 for k in STORE_COUNTERS}
        self._clock = {"publish_calls": 0}
        self._pending: list = []
        if fault_plan is not None:
            self._pending.extend(fault_plan.store_faults())

    # -- tree --------------------------------------------------------------

    def sub(self, name: str) -> "DurableStore":
        """A nested store at ``root/name`` sharing this tree's FaultFS,
        counters, publish clock and pending chaos — one accounting
        plane however many surfaces ride it."""
        if _GEN_RE.match(name) or name in (QUARANTINE,) or "/" in name \
                or os.sep in name or name.startswith("."):
            raise ValueError(f"DurableStore.sub: bad surface name {name!r}")
        return DurableStore(
            os.path.join(self.root, name), retries=self.retries,
            backoff_base=self.backoff_base,
            _shared=(self.fs, self.counters, self._clock, self._pending))

    # -- listing -----------------------------------------------------------

    def _entries(self, sub: str = "") -> list:
        path = os.path.join(self.root, sub) if sub else self.root
        if not os.path.isdir(path):
            return []
        return self.fs.listdir(path)

    def generations(self) -> List[GenerationInfo]:
        """All published generations, newest token first (validity
        unknown until `validate`)."""
        out = []
        for name in self._entries():
            m = _GEN_RE.match(name)
            if m:
                out.append(GenerationInfo(int(m.group(1)), int(m.group(2)),
                                          os.path.join(self.root, name)))
        return sorted(out, key=lambda g: g.token, reverse=True)

    def _max_token(self):
        """Highest (epoch, seq) visible anywhere — published,
        quarantined, or a crash-leftover tmp dir.  Fencing and epoch
        allocation must see them all: a quarantined epoch-9 generation
        still proves an epoch-9 writer existed."""
        toks = [g.token for g in self.generations()]
        for name in self._entries(QUARANTINE):
            stem = name.split(".quarantined")[0]
            if stem.startswith(_TMP_PREFIX):
                stem = "gen-" + stem[len(_TMP_PREFIX):]
            m = _GEN_RE.match(stem)
            if m:
                toks.append((int(m.group(1)), int(m.group(2))))
        for name in self._entries():
            if name.startswith(_TMP_PREFIX):
                m = _GEN_RE.match("gen-" + name[len(_TMP_PREFIX):])
                if m:
                    toks.append((int(m.group(1)), int(m.group(2))))
        return max(toks) if toks else None

    # -- fencing -----------------------------------------------------------

    def acquire_writer(self) -> int:
        """Claim the next writer epoch (monotonic over everything this
        store has ever seen).  Hold it for the process lifetime; pass
        it to every `publish`."""
        top = self._max_token()
        return (top[0] if top else 0) + 1

    # -- publish -----------------------------------------------------------

    def publish(self, artifacts: Dict[str, bytes], *, step=None,
                meta: Optional[dict] = None,
                writer: Optional[int] = None) -> GenerationInfo:
        """Atomically publish one generation of ``artifacts`` (flat
        name → bytes).  Returns its `GenerationInfo` (manifest loaded).

        ``writer`` is a fencing epoch from `acquire_writer`; omitted,
        the publish runs as a one-shot writer (fresh epoch, cannot be
        fenced).  ``step`` and ``meta`` ride the sealed manifest.
        """
        for name in artifacts:
            _check_artifact_name(name)
        clock = self._clock["publish_calls"]
        self._clock["publish_calls"] += 1

        if writer is None:
            top = self._max_token()
            epoch, seq = ((top[0] if top else 0) + 1, 0)
        else:
            epoch = int(writer)
            top = self._max_token()
            if top is not None and top[0] > epoch:
                self.counters["fence_refusals"] += 1
                raise FencedWriterError(
                    f"stale writer epoch {epoch}: generation "
                    f"{top} already published by a newer writer")
            seq = top[1] + 1 if (top is not None and top[0] == epoch) else 0

        transient = [f for f in self._pending
                     if f.kind in ("store_eio", "store_enospc")
                     and f.step == clock]
        info = None
        for attempt in range(self.retries + 1):
            for spec in transient:
                if spec in self._pending:
                    code = (TRANSIENT_ERRNOS[0] if spec.kind == "store_eio"
                            else TRANSIENT_ERRNOS[1])
                    self.fs.arm(self.fs.ops + max(int(spec.arg), 0),
                                code, spec)
            try:
                info = self._publish_once(epoch, seq, step, meta, artifacts)
                self.fs.disarm_all()
                break
            except OSError as e:
                for tag in self.fs.drain_fired():
                    if tag in self._pending:
                        self._pending.remove(tag)
                        self.counters["eio_fired" if tag.kind == "store_eio"
                                      else "enospc_fired"] += 1
                self.fs.disarm_all()
                self._scrub_tmp(epoch, seq)
                if e.errno not in TRANSIENT_ERRNOS or attempt == self.retries:
                    raise
                self.counters["io_errors"] += 1
                self.counters["publish_retries"] += 1
                # step-clock exponential backoff: pure accounting, no
                # sleeping — determinism over realism
                self.counters["backoff_steps"] += self.backoff_base << attempt
        self.counters["publishes"] += 1
        self._fire_corruption(clock, info)
        return info

    def _publish_once(self, epoch, seq, step, meta, artifacts):
        name = f"gen-{epoch:08d}-{seq:08d}"
        tmp = os.path.join(self.root, _TMP_PREFIX + name[len("gen-"):])
        if os.path.isdir(tmp):
            # leftover from a failed attempt of THIS token — raw
            # cleanup, not an op (the op clock counts forward progress)
            shutil.rmtree(tmp)
        body = {"version": 1, "epoch": epoch, "seq": seq, "step": step,
                "meta": dict(meta or {}), "artifacts": {}}
        self.fs.mkdir(tmp)
        for aname in sorted(artifacts):
            blob = artifacts[aname]
            if not isinstance(blob, (bytes, bytearray)):
                raise TypeError(f"artifact {aname!r}: bytes required, "
                                f"got {type(blob).__name__}")
            apath = os.path.join(tmp, aname)
            self.fs.write(apath, bytes(blob))
            self.fs.fsync(apath)
            body["artifacts"][aname] = {
                "bytes": len(blob),
                "sha256": hashlib.sha256(bytes(blob)).hexdigest()}
        sealed = dict(body, seal=_seal(body))
        mpath = os.path.join(tmp, MANIFEST)
        self.fs.write(mpath, json.dumps(sealed, sort_keys=True).encode())
        self.fs.fsync(mpath)
        self.fs.fsync_dir(tmp)
        final = os.path.join(self.root, name)
        self.fs.rename(tmp, final)       # the commit point
        self.fs.fsync_dir(self.root)
        return GenerationInfo(epoch, seq, final, manifest=sealed)

    def _scrub_tmp(self, epoch, seq) -> None:
        tmp = os.path.join(self.root,
                           f"{_TMP_PREFIX}{epoch:08d}-{seq:08d}")
        if os.path.isdir(tmp):
            shutil.rmtree(tmp, ignore_errors=True)

    def _fire_corruption(self, clock: int, info: GenerationInfo) -> None:
        for spec in [f for f in self._pending
                     if f.kind in ("store_torn", "store_flip")
                     and f.step == clock]:
            self._pending.remove(spec)
            names = [n for n in info.manifest["artifacts"]]
            victim = max(names, key=lambda n:
                         (info.manifest["artifacts"][n]["bytes"], n))
            arg = int(spec.arg)
            if spec.kind == "store_torn":
                corrupt_file(os.path.join(info.path, victim), torn_at=arg)
                self.counters["torn_fired"] += 1
            else:
                corrupt_file(os.path.join(info.path, victim), flip_at=arg)
                self.counters["flip_fired"] += 1

    # -- validation / quarantine / recovery --------------------------------

    def validate(self, info: GenerationInfo) -> Optional[dict]:
        """Full integrity check of one generation: manifest parses, its
        seal matches, its token matches the directory name, every
        artifact is present with exact size and sha256, and no foreign
        file hides in the directory.  Returns the manifest, or None."""
        try:
            raw = self.fs.read(os.path.join(info.path, MANIFEST))
            man = json.loads(raw.decode())
            body = {k: v for k, v in man.items() if k != "seal"}
            if man.get("seal") != _seal(body):
                return None
            if (int(man["epoch"]), int(man["seq"])) != info.token:
                return None
            files = [n for n in self.fs.listdir(info.path) if n != MANIFEST]
            if sorted(files) != sorted(man["artifacts"]):
                return None
            for aname, rec in man["artifacts"].items():
                apath = os.path.join(info.path, aname)
                blob = self.fs.read(apath)
                if len(blob) != int(rec["bytes"]):
                    return None
                if hashlib.sha256(blob).hexdigest() != rec["sha256"]:
                    return None
            return man
        except (OSError, ValueError, KeyError, TypeError):
            return None

    def _quarantine(self, info: GenerationInfo) -> None:
        qdir = os.path.join(self.root, QUARANTINE)
        os.makedirs(qdir, exist_ok=True)
        dst = os.path.join(qdir, info.name)
        n = 0
        while os.path.exists(dst):
            n += 1
            dst = os.path.join(qdir, f"{info.name}.quarantined{n}")
        os.rename(info.path, dst)
        self.counters["quarantined"] += 1

    def quarantined(self) -> list:
        """Names under ``_quarantine/`` (evidence, never adopted)."""
        return list(self._entries(QUARANTINE))

    def sweep_tmp(self) -> int:
        """Move crash-leftover ``.tmp-gen-*`` dirs into quarantine (an
        unsealed half-publish is evidence too, never adopted, never
        silently deleted).  Returns how many were swept."""
        n = 0
        for name in self._entries():
            if name.startswith(_TMP_PREFIX):
                qdir = os.path.join(self.root, QUARANTINE)
                os.makedirs(qdir, exist_ok=True)
                dst = os.path.join(qdir, name)
                k = 0
                while os.path.exists(dst):
                    k += 1
                    dst = os.path.join(qdir, f"{name}.quarantined{k}")
                os.rename(os.path.join(self.root, name), dst)
                self.counters["tmp_swept"] += 1
                n += 1
        return n

    def newest_valid(self) -> Optional[GenerationInfo]:
        """Recovery scan: newest generation that passes `validate`.
        Invalid generations met on the way down are quarantined (and
        counted) — the next scan never re-trips on them.  Leftover tmp
        dirs are swept first.  Returns None when nothing valid exists."""
        self.sweep_tmp()
        for info in self.generations():
            man = self.validate(info)
            if man is not None:
                info.manifest = man
                self.counters["restores"] += 1
                return info
            self._quarantine(info)
        return None

    def valid_generations(self) -> List[GenerationInfo]:
        """Every generation that validates, newest token first —
        invalid ones met during the scan are quarantined exactly like
        `newest_valid` (this is its whole-log twin; the fleet capsule
        log reads its park/claim history through it)."""
        self.sweep_tmp()
        out = []
        for info in self.generations():
            man = self.validate(info)
            if man is None:
                self._quarantine(info)
            else:
                info.manifest = man
                out.append(info)
        return out

    def lookup(self, token) -> Optional[GenerationInfo]:
        """The generation with exactly this (epoch, seq) token, if it
        exists AND validates (quarantined on failure)."""
        for info in self.generations():
            if info.token == tuple(token):
                man = self.validate(info)
                if man is None:
                    self._quarantine(info)
                    return None
                info.manifest = man
                return info
        return None

    # -- reading -----------------------------------------------------------

    def read(self, info: GenerationInfo, name: str) -> bytes:
        """One artifact's bytes, digest-checked at read time (a
        generation torn AFTER its validating scan is still refused)."""
        if info.manifest is None:
            man = self.validate(info)
            if man is None:
                self.counters["read_rejects"] += 1
                raise ValueError(f"generation {info.name} fails validation")
            info.manifest = man
        rec = info.manifest["artifacts"].get(name)
        if rec is None:
            raise KeyError(f"generation {info.name}: no artifact {name!r}")
        blob = self.fs.read(os.path.join(info.path, name))
        if (len(blob) != int(rec["bytes"])
                or hashlib.sha256(blob).hexdigest() != rec["sha256"]):
            self.counters["read_rejects"] += 1
            raise ValueError(
                f"artifact {name!r} of {info.name}: digest mismatch at "
                "read time — refusing torn bytes")
        return blob

    def load(self, info: GenerationInfo) -> Dict[str, bytes]:
        """Every artifact of a generation, digest-checked."""
        if info.manifest is None and self.validate(info) is None:
            self.counters["read_rejects"] += 1
            raise ValueError(f"generation {info.name} fails validation")
        return {name: self.read(info, name)
                for name in info.manifest["artifacts"]}

    # -- retention ---------------------------------------------------------

    def gc(self, keep: int) -> int:
        """Collect valid generations beyond the ``keep`` newest.  The
        newest valid generation is structurally uncollectable: the
        survivor set is filled newest-first BEFORE anything is deleted,
        and ``keep >= 1`` is enforced.  Invalid generations met during
        the scan are quarantined, never counted against ``keep`` and
        never deleted.  Returns the number collected."""
        if keep < 1:
            raise ValueError("DurableStore.gc: keep must be >= 1 — the "
                             "newest valid generation is not collectable")
        survivors, victims = [], []
        for info in self.generations():
            if self.validate(info) is None:
                self._quarantine(info)
            elif len(survivors) < keep:
                survivors.append(info)
            else:
                victims.append(info)
        for info in victims:
            self.fs.remove_tree(info.path)
            self.counters["gc_collected"] += 1
        return len(victims)

    # -- chaos accounting --------------------------------------------------

    def report_unfired(self) -> list:
        """STORE_KINDS specs still pending — the storage half of the
        end-of-run honesty check (`resilience.inject.report_unfired`
        flags the same specs when NO store consumed them)."""
        return list(self._pending)
