"""cpd_tpu.store — the durable state plane (ISSUE 20).

One crash-consistent `DurableStore` that the three persistence
surfaces (trainer checkpoints, `ServeEngine` snapshots, migration
capsules) publish through, one `FaultFS` boundary that storage chaos
(`store_torn` / `store_flip` / `store_eio` / `store_enospc`) enters
through, and one shared `corrupt_file` body behind both the legacy
checkpoint drills and the new storage kinds.

Pure stdlib on purpose: the crash matrix (tools/bench_store.py
``--crash-matrix``) forks a subprocess per write-boundary stratum and
must not pay a jax import for each.
"""

from .durable import (DurableStore, FencedWriterError, GenerationInfo,
                      MANIFEST, QUARANTINE, STORE_COUNTERS)
from .faultfs import (CRASH_EXIT, FaultFS, TRANSIENT_ERRNOS, WRITE_OPS,
                      corrupt_file)

__all__ = [
    "DurableStore", "FencedWriterError", "GenerationInfo", "MANIFEST",
    "QUARANTINE", "STORE_COUNTERS", "CRASH_EXIT", "FaultFS",
    "TRANSIENT_ERRNOS", "WRITE_OPS", "corrupt_file",
]
