"""FaultFS — the store's I/O boundary, and the door storage chaos enters.

Every byte `cpd_tpu.store.DurableStore` moves crosses ONE wrapper around
the handful of POSIX primitives a crash-consistent publish needs
(mkdir / write / per-file fsync / rename / directory fsync / subtree
remove).  Funnelling them through a single object buys two things:

* **Determinism** — a monotonically counted *op clock* over the
  write-class primitives.  The nth write op is the same op on every
  run, so `store_eio@s:n` / `store_enospc@s:n` specs and the crash
  matrix's kill-at-op-n strata (tools/bench_store.py) aim at exact
  write boundaries instead of wall-clock races.
* **Chaos** — one-shot transient ``EIO`` / ``ENOSPC`` injection
  (consumed when fired, so the store's deterministic retry provably
  absorbs it) and simulated power loss (``crash_at_op`` →
  ``os._exit``: nothing buffered after the boundary survives, exactly
  like the plug being pulled).

Read-class helpers (`read` / `listdir` / `exists`) are NOT on the op
clock: a crash "during a read" is not a write boundary, and the store's
recovery scan must be free to probe a wounded tree without advancing
the clock the faults aim at.

Post-publish corruption (`store_torn@s:k` / `store_flip@s:k`) also
bypasses the clock — a torn or flipped generation is an adversary
editing sealed bytes behind the store's back.  It shares ONE injection
body, `corrupt_file`, with PR 2's legacy host one-shots
(`ckpt_truncate` / `ckpt_bitflip` in resilience/inject.py), so the old
checkpoint drills and the new storage drills corrupt bytes the exact
same way.
"""

from __future__ import annotations

import errno
import os
import shutil
from typing import List, Optional, Tuple

# transient errnos the store retries; anything else propagates
TRANSIENT_ERRNOS = (errno.EIO, errno.ENOSPC)

# the crash matrix recognises this exit code as "simulated power loss"
CRASH_EXIT = 73

# write-class primitive names, in no particular order (docs/tests)
WRITE_OPS = ("mkdir", "write", "fsync", "rename", "fsync_dir", "remove")


class FaultFS:
    """Counted, injectable wrapper over the store's POSIX write path.

    Args:
        crash_at_op: when set, the process exits with ``CRASH_EXIT``
            *before executing* write op number ``crash_at_op`` (0-based
            absolute op clock) — ops ``0 .. crash_at_op-1`` hit disk,
            nothing after.  The crash matrix sweeps this over every
            boundary of a publish.
    """

    def __init__(self, *, crash_at_op: Optional[int] = None):
        self.ops = 0                      # absolute write-op clock
        self.crash_at_op = crash_at_op
        self._armed: List[Tuple[int, int, object]] = []  # (op, errno, tag)
        self.fired: List[object] = []     # tags of faults that fired

    # -- arming ------------------------------------------------------------

    def arm(self, at_op: int, errno_code: int, tag=None) -> None:
        """One-shot: raise ``OSError(errno_code)`` instead of executing
        absolute op ``at_op``.  ``tag`` (e.g. the FaultSpec) is recorded
        in ``fired`` when it goes off, for exact-counter accounting."""
        if errno_code not in TRANSIENT_ERRNOS:
            raise ValueError(f"FaultFS.arm: unsupported errno {errno_code}")
        self._armed.append((int(at_op), int(errno_code), tag))

    def disarm_all(self) -> list:
        """Drop every still-armed fault, returning their tags (the
        store re-pends them so `report_unfired` stays honest)."""
        tags = [tag for _, _, tag in self._armed]
        self._armed = []
        return tags

    def drain_fired(self) -> list:
        """Return and clear the tags of faults that fired."""
        out, self.fired = self.fired, []
        return out

    # -- the gate ----------------------------------------------------------

    def _gate(self, path: str) -> None:
        idx = self.ops
        self.ops += 1
        if self.crash_at_op is not None and idx == self.crash_at_op:
            # simulated power loss: no flush, no atexit, no cleanup —
            # whatever fsync already pinned is all that survives
            os._exit(CRASH_EXIT)
        for entry in self._armed:
            if entry[0] == idx:
                self._armed.remove(entry)
                self.fired.append(entry[2])
                raise OSError(entry[1], os.strerror(entry[1]), path)

    # -- write-class primitives (on the op clock) --------------------------

    def mkdir(self, path: str) -> None:
        self._gate(path)
        os.makedirs(path)

    def write(self, path: str, data: bytes) -> None:
        self._gate(path)
        with open(path, "wb") as fh:
            fh.write(data)

    def fsync(self, path: str) -> None:
        self._gate(path)
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)

    def fsync_dir(self, path: str) -> None:
        self._gate(path)
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)

    def rename(self, src: str, dst: str) -> None:
        self._gate(dst)
        os.rename(src, dst)

    def remove_tree(self, path: str) -> None:
        self._gate(path)
        shutil.rmtree(path)

    # -- read-class helpers (NOT on the op clock) --------------------------

    def read(self, path: str) -> bytes:
        with open(path, "rb") as fh:
            return fh.read()

    def listdir(self, path: str) -> list:
        return sorted(os.listdir(path))

    def exists(self, path: str) -> bool:
        return os.path.exists(path)


def corrupt_file(path: str, *, torn_at: Optional[int] = None,
                 flip_at: Optional[int] = None) -> str:
    """The ONE corruption body shared by the legacy checkpoint one-shots
    (`Injector.corrupt_checkpoint`: ``ckpt_truncate`` / ``ckpt_bitflip``)
    and the new store kinds (``store_torn@s:k`` / ``store_flip@s:k``).

    ``torn_at=k`` truncates the file at byte ``k`` (``k < 0`` → the
    legacy half-size cut, ``max(size // 2, 1)``); ``flip_at=k`` XORs
    the byte at offset ``k`` with 0xFF (``k < 0`` → the legacy midpoint
    ``size // 2``).  Returns a short description for event logs."""
    if (torn_at is None) == (flip_at is None):
        raise ValueError("corrupt_file: exactly one of torn_at / flip_at")
    size = os.path.getsize(path)
    if torn_at is not None:
        cut = max(size // 2, 1) if torn_at < 0 else min(int(torn_at), size)
        with open(path, "r+b") as fh:
            fh.truncate(cut)
        return f"torn@{cut}"
    off = size // 2 if flip_at < 0 else int(flip_at)
    if size == 0:
        raise ValueError(f"corrupt_file: {path} is empty, nothing to flip")
    off = min(off, size - 1)
    with open(path, "r+b") as fh:
        fh.seek(off)
        byte = fh.read(1)
        fh.seek(off)
        fh.write(bytes([byte[0] ^ 0xFF]))
    return f"flip@{off}"
