"""Failure injection + guarded training — the two halves test each other.

Low-precision distributed training fails in characteristic ways long
before it hits accuracy limits: eXmY overflow/underflow turns a gradient
non-finite, a corrupted quantized all-reduce leaves replicas holding
*different* sums, pod-scale runs eat preemptions and stragglers, and
storage flakes truncate checkpoints (PAPERS.md: EQuARX, MLPerf on
TPU-v3 pods).  The seed had the happy-path pieces (orbax checkpointing,
GradScaler-style skip); this package adds

* **inject** — a deterministic, seed-driven :class:`FaultPlan` plus the
  host-side :class:`Injector` and the jit-level
  :func:`with_fault_injection` optax wrapper, so every defense can be
  exercised on purpose, in tests and via ``--fault-plan`` on trainers;
* **guard** — :func:`with_grad_guard`: jit-compatible non-finite + spike
  detection with per-tensor culprit reporting and a cross-replica
  agreement check, composing with the dynamic loss scale;
* **watchdog** — :class:`StepWatchdog`: a hung/straggling step turns
  into a diagnostic dump and a clean checkpoint-and-exit, not a silent
  wedge;
* **sentinel** — :class:`DivergenceSentinel`: rolling-window loss
  blow-up detection;
* **transport** — :class:`TransportSupervisor`: the degraded-transport
  ladder (ring -> faithful -> fp32) driven by the self-verifying
  reduce's checksums (parallel/integrity.py), with bounded same-step
  retries and probation back up;
* **precision** — :class:`PrecisionSupervisor`: the eXmY
  format-escalation ladder driven by the in-jit numeric-health
  counters (quant.numerics.quant_health via
  ``sum_gradients(stats=True)``): sustained saturation/NaN at a quant
  site escalates the format one configured rung (re-traced via the
  same StepTable machinery), quiet steps probation back down, and the
  ladder state persists in checkpoints so restarts resume escalated;
* **loop** — :func:`run_guarded`: the defenses composed around any
  ``(state, x, y) -> (state, metrics)`` step, with integrity-checked
  checkpoint rollback, bounded re-seeded retries, verified-reduce
  supervision and periodic replica-consensus repair;
* **elastic** — :class:`ElasticSupervisor` + :func:`run_elastic`: the
  whole-host recovery ladder (ISSUE 19) — heartbeat/straggler
  detection, in-step link retries, deterministic mesh shrink W -> W'
  through the digest-sealed checkpoints, probationary regrow.

The defense matrix (fault -> detector -> recovery) is documented in
docs/RESILIENCE.md.
"""

from .inject import (ELASTIC_KINDS, FaultPlan, FaultSpec,
                     InjectedPreemption, Injector, report_unfired,
                     with_fault_injection)
from .guard import (GradGuardState, describe_culprit, find_guard,
                    guard_metrics, with_grad_guard)
from .sentinel import DivergenceSentinel
from .transport import StepTable, TransportSupervisor, level_reduce_kwargs
from .precision import (PrecisionSupervisor, format_name, ladder_step_key,
                        parse_format, parse_ladder)
from .watchdog import StepWatchdog
from .loop import GuardedReport, run_guarded
from .elastic import (ElasticReport, ElasticSupervisor, HeartbeatMonitor,
                      heartbeat_table, run_elastic, shrink_world)

__all__ = [
    "FaultPlan", "FaultSpec", "Injector", "InjectedPreemption",
    "with_fault_injection", "report_unfired", "ELASTIC_KINDS",
    "GradGuardState", "with_grad_guard", "guard_metrics", "find_guard",
    "describe_culprit",
    "DivergenceSentinel", "StepWatchdog",
    "TransportSupervisor", "StepTable", "level_reduce_kwargs",
    "PrecisionSupervisor", "parse_format", "parse_ladder", "format_name",
    "ladder_step_key",
    "run_guarded", "GuardedReport",
    "ElasticSupervisor", "HeartbeatMonitor", "run_elastic",
    "ElasticReport", "heartbeat_table", "shrink_world",
]
