"""Deterministic fault injection — the chaos half of the resilience story.

A :class:`FaultPlan` is an immutable, seed-reproducible schedule of
:class:`FaultSpec` entries.  Two consumers execute it:

* :func:`with_fault_injection` — an optax wrapper that corrupts the
  gradients *inside* the jitted step (NaN / Inf / exponent blow-up,
  optionally on a single data-parallel shard via ``lax.axis_index`` to
  model one rank's corrupted quantized-reduce output).  The schedule is
  baked into the compiled program as a constant table indexed by the
  wrapper's own update counter, so injection is jit-compatible and
  bit-reproducible.  Note the counter lives in the optimizer state: a
  rollback that restores an old state REPLAYS the same faults — by
  design (same plan, same timeline).
* :class:`Injector` — the host-side driver for everything that is not a
  gradient: poisoning a float batch, dropping/duplicating a batch,
  stalling the host thread (straggler), truncating / bit-flipping a
  checkpoint file, raising mid-step (preemption), and inflating the
  observed loss (divergence-sentinel drill).  Host faults are
  **one-shot**: each spec fires once and is consumed, so a
  rollback-and-replay recovers instead of re-tripping forever.

Grammar for ``--fault-plan`` (also accepts a path to a JSON file written
by :meth:`FaultPlan.to_json`):

    kind@step[:arg[:arg2]][;kind@step[:arg[:arg2]]...]

e.g. ``grad_nan@3;stall@5:1.5;ckpt_truncate@6;loss_spike@8:1e6``.
``arg`` means: shard index for ``grad_*`` (-1 = every shard, the
default), RANK for ``wire_*`` (-1 = rank 0), the log2 scale factor for
``sat_pressure`` (-1 = 24, i.e. ×2^24), seconds for ``stall``,
multiplier for ``loss_spike`` / ``batch_scale``; ignored elsewhere.
``arg2`` only exists for the two-argument elastic kinds below (-1 =
kind-specific default).

A third executor consumes the ``wire_*`` kinds (``wire_flip@s:k``,
``wire_stale@s:k``, ``wire_drop@s:k``): the ring transport itself
(parallel/ring.py), which corrupts the bit-packed hop payload inside
its scan body and the all-gather wire on rank ``k`` at step ``s`` —
deterministic (same seed/plan ⇒ same corruption), detected by the
integrity checksums (parallel/integrity.py) when the reduce runs with
``verify=True``.  :meth:`FaultPlan.wire_schedule` compiles them into
the dense (codes, ranks) table the step builders bake in.

A fourth executor consumes ``sat_pressure@s:k`` (the scale-blowup
attack of the precision ladder, resilience/precision.py): the step
builders bake :meth:`FaultPlan.sat_schedule`'s dense exponent table
into the program and scale step ``s``'s LOCAL post-backward gradients
by ``2^k`` (default k=24) BEFORE the emulate-node reduce and the
quantized collective — an exact power-of-two, identical on every rank,
that deterministically drives the reduce-wire cast into saturation.
Schedule ``patience`` consecutive specs to force an escalation; the
same plan without the ladder is the degradation baseline (the grad
guard skips the saturated steps, or the loss blows up).

A fifth executor consumes ``kv_flip@s:k`` (the serving-side corruption
attack): the serving engine (cpd_tpu/serve/engine.py) flips one byte in
request slot ``k``'s first KV-cache page at ENGINE step ``s`` (held
until the slot holds cached K/V) — detected by the per-page digests and
repaired by recomputation without dropping the request
(docs/SERVING.md).  The engine does its own unfired accounting.

The same executor consumes the serving-chaos kinds (``SERVE_KINDS``,
ISSUE 10 — all on the serving engine's step clock):

* ``kv_storm@s:k`` — flip one byte in each of up to ``k`` (default 3)
  DISTINCT live KV pages at engine step ``s`` (held until at least one
  live page exists): multi-page corruption wide enough that the
  `ServeSupervisor` degradation ladder, not just the scrubber, has to
  react.
* ``slot_stall@s:k`` — request slot ``k`` stops making token progress
  from engine step ``s`` (held until the slot is decoding): a wedged
  decode lane, caught by the engine's no-progress watchdog, which
  evicts the slot's pages and re-prefills its cache from the host-held
  token history without dropping the request.
* ``req_burst@s:k`` — a flash crowd of ``k`` (default 4) extra requests
  arrives at engine step ``s``; the LOAD GENERATOR is the consumer
  (`serve.loadgen.run_trace(burst_factory=...)` pops the due specs via
  `ServeEngine.take_due_bursts`), so the burst is keyed into the plan
  and replays deterministically like every other fault.

A sixth executor consumes the elastic-training kinds (``ELASTIC_KINDS``,
ISSUE 19 — whole-host faults on the optimizer-update clock, consumed by
`cpd_tpu.resilience.elastic.run_elastic` / the trainers' ``--elastic``
path, which do their own one-shot + unfired accounting):

* ``host_kill@s:h[:r]`` — host ``h``'s heartbeat disappears at step
  ``s``; with ``arg2`` = ``r`` >= 0 it reappears ``r`` steps later (the
  regrow drill), -1 (default) = never.  The `ElasticSupervisor` drains
  the dead host and shrinks the mesh W -> W' deterministically.
* ``straggler@s:h:f`` — host ``h``'s step time at step ``s`` reads as
  inflated by factor ``f`` (arg2, -1 -> 4.0).  One spec = one slow
  heartbeat; schedule ``patience`` consecutive steps to force the
  detector hot (the ``sat_pressure`` idiom).
* ``link_flaky@s:h:p`` — the reduce wire into host ``h`` fails ``p``
  (arg2, -1 -> 1) consecutive attempts at step ``s``, plan-keyed
  deterministic; absorbed by the in-step collective retry when ``p``
  <= the supervisor's ``max_retries``, escalated to a drain+shrink
  otherwise.

A seventh executor consumes the storage-chaos kinds (``STORE_KINDS``,
ISSUE 20 — on the `cpd_tpu.store.DurableStore` PUBLISH clock, consumed
by any store built with ``fault_plan=``, which owns their one-shot +
unfired accounting):

* ``store_eio@s:n`` / ``store_enospc@s:n`` — transient EIO / ENOSPC
  instead of the nth write-class I/O op of publish number ``s``,
  absorbed by the store's deterministic retry-with-backoff.
* ``store_torn@s:k`` / ``store_flip@s:k`` — the generation publish
  ``s`` sealed is truncated at byte ``k`` / byte-flipped at offset
  ``k`` (-1 -> the legacy half-size / midpoint defaults), through the
  same `store.faultfs.corrupt_file` body as ``ckpt_truncate`` /
  ``ckpt_bitflip``; detected by the manifest digests, quarantined,
  never adopted.

``step`` convention: the 0-based optimizer-UPDATE index — one clock for
both executors, so ``grad_nan@3`` and ``stall@3`` hit the same physical
step in every entry point (run_guarded and both trainer CLIs).  The
``ckpt_*`` kinds are the exception: their step is the saved
checkpoint's own step number (what ``restore_latest_valid`` sees),
because that is the name the corruption must land on; ``kv_flip``'s
step is the serving engine's step clock.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import time
from typing import Any, Iterable, NamedTuple, Optional

import numpy as np

__all__ = ["FaultSpec", "FaultPlan", "Injector", "InjectedPreemption",
           "with_fault_injection", "report_unfired", "GRAD_KINDS",
           "HOST_KINDS", "WIRE_KINDS", "SAT_KINDS", "KV_KINDS",
           "SERVE_KINDS", "FLEET_KINDS", "ELASTIC_KINDS", "STORE_KINDS",
           "SAT_PRESSURE_DEFAULT_EXP"]

# jit-level kinds -> corruption opcode in the compiled fault table
GRAD_KINDS = {"grad_nan": 1, "grad_inf": 2, "grad_blowup": 3}
# wire-level kinds -> corruption opcode inside ring_quantized_sum
# (parallel/ring.py _apply_hop_fault / the gather-wire fault)
WIRE_KINDS = {"wire_flip": 1, "wire_stale": 2, "wire_drop": 3}
# saturation-pressure kind, executed by the step builders' baked 2^k
# gradient-scale table (train/step.py, train/lm.py sat_fault_plan) —
# the attack the precision ladder is exercised against
SAT_KINDS = frozenset({"sat_pressure"})
SAT_PRESSURE_DEFAULT_EXP = 24          # arg -1 -> scale by 2^24
# KV-cache corruption kind, executed by the serving engine
# (serve/engine.py): ``kv_flip@s:k`` flips one byte in request slot
# ``k``'s first KV page at engine step ``s`` (held until that slot holds
# cached K/V) — the corruption class the per-page digests detect and the
# repair-by-recompute ladder absorbs without dropping the request.
# ``step`` here is the ENGINE-step clock, not the optimizer-update clock.
KV_KINDS = frozenset({"kv_flip"})
# serving-chaos kinds (ISSUE 10), all on the serving engine's step
# clock: ``kv_storm@s:k`` (byte flips in up to k DISTINCT live pages —
# wide enough to exercise the ServeSupervisor degradation ladder, not
# just the scrubber), ``slot_stall@s:k`` (slot k stops making token
# progress until the engine's no-progress watchdog evicts and
# re-prefills it from history), and ``req_burst@s:k`` (k extra requests
# arrive at step s — consumed by the load generator through
# `ServeEngine.take_due_bursts`, so the flash crowd is keyed into the
# plan and replays deterministically).
SERVE_KINDS = frozenset({"kv_storm", "slot_stall", "req_burst"})
# fleet-chaos kinds (ISSUE 13, 17), on the FLEET step clock (which is
# also every member engine's step clock — the fleet steps them in
# lockstep):
# ``engine_kill@s:e`` kills engine ``e`` of a `cpd_tpu.fleet.Fleet` at
# fleet step ``s`` — the fleet recovers the engine's state from its
# last periodic snapshot plus the deterministic submission replay log,
# then DRAINS it (queued work re-routed, live sessions migrated out
# where capacity allows, the rest completing locally with admissions
# closed) with zero silent drops.  A kill aimed at an index the fleet
# shape never contained (possible under autoscaling) is held, never
# re-aimed, and surfaces through `Fleet.report_unfired`.
# ``kill_wave@s:c`` (ISSUE 17) is the coordinated multi-engine kill: up
# to ``c`` (default 2) accepting engines die at fleet step ``s`` —
# admissions close on every victim before any drain migration runs, at
# least one accepting survivor always remains, and any shortfall is
# counted (``kill_wave_shortfall``), never silent.  The fleet does its
# own unfired accounting (`Fleet.report_unfired`); in a plain training
# or single-engine serving plan these kinds can never fire and
# `report_unfired` flags them unless ``fleet_armed=True``.
FLEET_KINDS = frozenset({"engine_kill", "kill_wave"})
# elastic-training kinds (ISSUE 19), on the optimizer-update clock like
# the grad/wire kinds — but consumed by the ELASTIC harness
# (resilience/elastic.py run_elastic, or a trainer's ``--elastic``
# path), never by the plain Injector hooks: ``host_kill@s:h[:r]``
# (host h's heartbeat disappears at step s, reappearing r steps later
# when arg2 >= 0), ``straggler@s:h:f`` (host h's step time at step s
# inflated by f — one slow heartbeat per spec), ``link_flaky@s:h:p``
# (the reduce wire into host h fails p consecutive attempts at step s,
# absorbed by the in-step retry when p <= max_retries).  The harness
# does its own one-shot + unfired accounting; `report_unfired` flags
# these kinds in any run without an elastic consumer
# (``host_armed=False``, the default).
ELASTIC_KINDS = frozenset({"host_kill", "straggler", "link_flaky"})
# storage-chaos kinds (ISSUE 20), on the DurableStore's own PUBLISH
# clock (`cpd_tpu.store` counts publish calls across the whole store
# tree): ``store_eio@s:n`` / ``store_enospc@s:n`` raise a transient
# EIO / ENOSPC instead of executing the nth write-class I/O op of
# publish number ``s`` (one-shot — the store's deterministic
# retry-with-backoff must absorb it), ``store_torn@s:k`` truncates the
# largest artifact of the generation publish ``s`` sealed at byte ``k``
# (-1 -> the legacy half-size cut) and ``store_flip@s:k`` XOR-flips its
# byte ``k`` (-1 -> midpoint) — both through the SAME `corrupt_file`
# body as the legacy ``ckpt_truncate`` / ``ckpt_bitflip`` one-shots
# below, so the old checkpoint drills and the new storage drills share
# one injection body.  Only a `DurableStore` built with
# ``fault_plan=`` consumes these (it owns their one-shot + unfired
# accounting, `DurableStore.report_unfired`); in any run without a
# store attached they can never fire and `report_unfired` flags them
# unless ``store_armed=True``.
STORE_KINDS = frozenset({"store_torn", "store_flip", "store_eio",
                         "store_enospc"})
# host-level kinds, executed by the Injector around the step call
HOST_KINDS = frozenset({
    "batch_nan",       # poison one element of the first float batch leaf
    "batch_scale",     # multiply the float batch by `arg` (loss blow-up)
    "data_drop",       # this step's batch never arrives; use the next one
    "data_dup",        # the previous batch is delivered again
    "stall",           # sleep `arg` seconds mid-step (straggler)
    "preempt",         # raise InjectedPreemption before the step
    "ckpt_truncate",   # truncate the newest checkpoint's largest file
    "ckpt_bitflip",    # flip one byte in the newest checkpoint
    "loss_spike",      # multiply the observed loss metric by `arg`
})
_ALL_KINDS = (frozenset(GRAD_KINDS) | HOST_KINDS | frozenset(WIRE_KINDS)
              | SAT_KINDS | KV_KINDS | SERVE_KINDS | FLEET_KINDS
              | ELASTIC_KINDS | STORE_KINDS)


class InjectedPreemption(BaseException):
    """Simulated SIGTERM-mid-step.  Derives from BaseException so generic
    ``except Exception`` recovery code cannot accidentally swallow the
    preemption it is being tested against."""


@dataclasses.dataclass(frozen=True, order=True)
class FaultSpec:
    """One scheduled fault.  ``arg`` is kind-dependent (module
    docstring); ``arg2`` only carries the second argument of the
    two-argument elastic kinds (straggler factor, link attempt count,
    host-rejoin delay) and stays -1.0 everywhere else."""
    step: int
    kind: str
    arg: float = -1.0
    arg2: float = -1.0

    def __post_init__(self):
        if self.kind not in _ALL_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; know "
                             f"{sorted(_ALL_KINDS)}")
        if self.step < 0:
            raise ValueError(f"fault step must be >= 0, got {self.step}")


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """An immutable schedule of faults; equality/ordering is structural,
    so 'same seed + config => identical plan' is testable directly."""
    faults: tuple = ()
    seed: int = 0

    def __post_init__(self):
        object.__setattr__(self, "faults",
                           tuple(sorted(self.faults)))

    def __len__(self) -> int:
        return len(self.faults)

    # -- constructors -----------------------------------------------------

    @classmethod
    def parse(cls, text: str, seed: int = 0) -> "FaultPlan":
        """Parse the compact ``kind@step[:arg[:arg2]]`` grammar, or load
        a JSON file if ``text`` names one (the ``--fault-plan`` flag
        accepts both)."""
        text = text.strip()
        if not text:
            return cls((), seed)
        if os.path.exists(text):
            with open(text) as f:
                return cls.from_json(f.read())
        faults = []
        for part in text.replace(",", ";").split(";"):
            part = part.strip()
            if not part:
                continue
            try:
                kind, rest = part.split("@", 1)
                fields = rest.split(":", 2)
                if len(fields) > 2 and kind.strip() not in ELASTIC_KINDS:
                    raise ValueError(
                        f"arg2 only exists for the elastic kinds "
                        f"{sorted(ELASTIC_KINDS)}")
                step_s = fields[0]
                arg = float(fields[1]) if len(fields) > 1 else -1.0
                arg2 = float(fields[2]) if len(fields) > 2 else -1.0
                faults.append(FaultSpec(int(step_s), kind.strip(), arg,
                                        arg2))
            except ValueError as e:
                raise ValueError(
                    f"bad fault spec {part!r} (want "
                    f"kind@step[:arg[:arg2]]): {e}") from e
        return cls(tuple(faults), seed)

    @classmethod
    def random(cls, seed: int, n_steps: int,
               rates: Optional[dict] = None) -> "FaultPlan":
        """Seed-deterministic random plan: each kind fires independently
        per step with probability ``rates[kind]`` (default: a light mix
        of gradient corruption and stalls)."""
        rates = rates or {"grad_nan": 0.02, "grad_blowup": 0.02,
                          "stall": 0.01}
        rng = random.Random(seed)
        faults = []
        for step in range(n_steps):
            for kind in sorted(rates):
                if rng.random() < rates[kind]:
                    arg = (rng.uniform(0.2, 1.0) if kind == "stall"
                           else -1.0)
                    faults.append(FaultSpec(step, kind, arg))
        return cls(tuple(faults), seed)

    @classmethod
    def from_json(cls, blob: str) -> "FaultPlan":
        doc = json.loads(blob)
        return cls(tuple(FaultSpec(f["step"], f["kind"],
                                   float(f.get("arg", -1.0)),
                                   float(f.get("arg2", -1.0)))
                         for f in doc["faults"]),
                   int(doc.get("seed", 0)))

    def to_json(self) -> str:
        return json.dumps({"seed": self.seed,
                           "faults": [dataclasses.asdict(f)
                                      for f in self.faults]}, indent=2)

    # -- consumers --------------------------------------------------------

    def counts(self) -> dict:
        out: dict = {}
        for f in self.faults:
            out[f.kind] = out.get(f.kind, 0) + 1
        return out

    def grad_faults(self) -> tuple:
        return tuple(f for f in self.faults if f.kind in GRAD_KINDS)

    def wire_faults(self) -> tuple:
        return tuple(f for f in self.faults if f.kind in WIRE_KINDS)

    def sat_faults(self) -> tuple:
        return tuple(f for f in self.faults if f.kind in SAT_KINDS)

    def kv_faults(self) -> tuple:
        """The serving engine's KV-page corruption specs (``arg`` is the
        target slot index, -1 -> slot 0)."""
        return tuple(f for f in self.faults if f.kind in KV_KINDS)

    def serve_faults(self) -> tuple:
        """The serving-chaos specs (`SERVE_KINDS`): ``kv_storm`` /
        ``slot_stall`` / ``req_burst`` — all on the serving engine's
        step clock (module docstring)."""
        return tuple(f for f in self.faults if f.kind in SERVE_KINDS)

    def fleet_faults(self) -> tuple:
        """The fleet-chaos specs (`FLEET_KINDS`): ``engine_kill@s:e``
        (``arg`` is the target engine index, -1 -> engine 0) and
        ``kill_wave@s:c`` (``arg`` is the victim count, -1 -> 2), both
        on the fleet step clock — consumed by
        `cpd_tpu.fleet.Fleet.step`."""
        return tuple(f for f in self.faults if f.kind in FLEET_KINDS)

    def elastic_faults(self) -> tuple:
        """The elastic-training specs (`ELASTIC_KINDS`):
        ``host_kill@s:h[:r]`` / ``straggler@s:h:f`` /
        ``link_flaky@s:h:p``, all on the optimizer-update clock —
        consumed by the elastic harness
        (`cpd_tpu.resilience.elastic.run_elastic` or a trainer's
        ``--elastic`` path), which owns their one-shot and unfired
        accounting."""
        return tuple(f for f in self.faults if f.kind in ELASTIC_KINDS)

    def store_faults(self) -> tuple:
        """The storage-chaos specs (`STORE_KINDS`):
        ``store_eio@s:n`` / ``store_enospc@s:n`` /
        ``store_torn@s:k`` / ``store_flip@s:k``, all on the
        `cpd_tpu.store.DurableStore` publish clock — consumed by a
        store built with ``fault_plan=``, which owns their one-shot and
        unfired accounting (`DurableStore.report_unfired`)."""
        return tuple(f for f in self.faults if f.kind in STORE_KINDS)

    def host_faults(self) -> dict:
        """step -> [FaultSpec] for the host-level kinds."""
        out: dict = {}
        for f in self.faults:
            if f.kind in HOST_KINDS:
                out.setdefault(f.step, []).append(f)
        return out

    def grad_schedule(self, n_steps: int):
        """Dense (codes, shards) int32 tables for the jit wrapper; entry
        ``i`` drives optimizer update ``i``.  At most one gradient fault
        per step (the last spec wins)."""
        codes = np.zeros((max(n_steps, 1),), np.int32)
        shards = np.full((max(n_steps, 1),), -1, np.int32)
        for f in self.grad_faults():
            if f.step < n_steps:
                codes[f.step] = GRAD_KINDS[f.kind]
                shards[f.step] = int(f.arg)
        return codes, shards

    def wire_schedule(self, n_steps: int):
        """Dense (codes, ranks) int32 tables for the ring transport's
        in-jit wire faults; entry ``i`` drives optimizer update ``i``
        (the same clock as `grad_schedule`).  ``arg`` is the target
        rank (-1 -> rank 0); at most one wire fault per step (the last
        spec wins).

        Bucketed / overlapped transports (``bucket_elems`` /
        ``overlap_reduce``, ISSUE 8): the table is still indexed by the
        optimizer-update clock — NOT by ring-call count — because the
        step builders bake ONE lookup per step and `sum_gradients`
        applies the fault to bucket 0 only (and, on a multi-axis mesh,
        to the single stage-0 ring whose other-axes indices are zero).
        A step's fault therefore fires exactly once however many
        per-bucket rings the schedule launches, keeping the chaos
        drills' exact counter expectations (one flip -> hop_bad == 1)
        and `report_unfired`'s fired/unfired accounting layout-free
        (covered in tests/test_overlap.py)."""
        codes = np.zeros((max(n_steps, 1),), np.int32)
        ranks = np.zeros((max(n_steps, 1),), np.int32)
        for f in self.wire_faults():
            if f.step < n_steps:
                codes[f.step] = WIRE_KINDS[f.kind]
                ranks[f.step] = max(int(f.arg), 0)
        return codes, ranks

    def sat_schedule(self, n_steps: int):
        """Dense int32 log2-scale table for the step builders' baked
        saturation-pressure attack (``sat_fault_plan=``); entry ``i``
        scales optimizer update ``i``'s local gradients by ``2^exps[i]``
        (0 = off — an exact no-op).  ``arg`` is the exponent (-1 ->
        `SAT_PRESSURE_DEFAULT_EXP`); at most one pressure per step (the
        last spec wins)."""
        exps = np.zeros((max(n_steps, 1),), np.int32)
        for f in self.sat_faults():
            if f.step < n_steps:
                exps[f.step] = (SAT_PRESSURE_DEFAULT_EXP if f.arg < 0
                                else int(f.arg))
        return exps


def sat_pressure_factor(table, step):
    """The 2^k gradient scale for optimizer update ``step`` from a dense
    `FaultPlan.sat_schedule` table — jit-safe, the ONE lookup shared by
    the step builders (train/step.py, train/lm.py) so the clip/where
    indexing cannot drift between them.  Entry 0 -> 2^0 == 1.0, an
    exact fp32 no-op; steps past the table are unpressured."""
    import jax.numpy as jnp

    from ..parallel.aps import exp2_exact
    exps = jnp.asarray(table, jnp.int32)
    idx = jnp.clip(step, 0, exps.shape[0] - 1)
    e = jnp.where(step < exps.shape[0], exps[idx], 0)
    # exp2_exact, not jnp.exp2: the factor must be the EXACT power of
    # two the attack documents (XLA:CPU's exp2 is off by an ulp for
    # most negative integers — parallel/aps.py)
    return exp2_exact(e.astype(jnp.float32))


# ---------------------------------------------------------------------------
# jit-level gradient corruption (optax wrapper)
# ---------------------------------------------------------------------------

class FaultInjectState(NamedTuple):
    step: Any       # i32 update counter (drives the schedule table)
    injected: Any   # i32 faults fired so far
    inner: Any


def with_fault_injection(tx, plan: FaultPlan, n_steps: int, *,
                         axis_name: Optional[str] = None):
    """Wrap ``tx`` so incoming gradients are corrupted per ``plan``.

    Wrap OUTSIDE every defense under test
    (``with_fault_injection(with_grad_guard(...))``) so the corruption
    enters the pipeline exactly where a bad quantized reduce would.  With
    ``axis_name`` (inside shard_map) and a fault ``arg`` >= 0, only that
    shard's copy is corrupted — replicas now *disagree*, which is the
    failure mode the guard's cross-replica agreement check exists for.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    # a tuple (the guard's multi-axis agreement form) gates the shard
    # index on its FIRST axis — by convention the data axis, the one a
    # corrupted quantized reduce is per-replica over
    if isinstance(axis_name, (tuple, list)):
        axis_name = axis_name[0] if axis_name else None

    codes_np, shards_np = plan.grad_schedule(n_steps)

    def init(params):
        return FaultInjectState(jnp.zeros([], jnp.int32),
                                jnp.zeros([], jnp.int32), tx.init(params))

    def update(grads, state, params=None):
        codes = jnp.asarray(codes_np)
        shards = jnp.asarray(shards_np)
        idx = jnp.clip(state.step, 0, codes.shape[0] - 1)
        in_range = state.step < codes.shape[0]
        code = jnp.where(in_range, codes[idx], 0)
        shard = shards[idx]
        on = code > 0
        if axis_name is not None:
            me = lax.axis_index(axis_name).astype(jnp.int32)
            on = on & ((shard < 0) | (me == shard))

        def corrupt(g):
            flat = jnp.ravel(g).astype(g.dtype)
            nan_p = flat.at[0].set(jnp.nan)
            inf_p = flat.at[0].set(jnp.inf)
            blown = flat * jnp.asarray(2.0 ** 60, g.dtype)
            out = jnp.where(code == 1, nan_p,
                            jnp.where(code == 2, inf_p,
                                      jnp.where(code == 3, blown, flat)))
            return jnp.where(on, out, flat).reshape(g.shape)

        bad = jax.tree.map(corrupt, grads)
        updates, new_inner = tx.update(bad, state.inner, params)
        return updates, FaultInjectState(
            state.step + 1,
            state.injected + (code > 0).astype(jnp.int32),
            new_inner)

    import optax
    wrapped = optax.GradientTransformation(init, update)
    if getattr(tx, "norm_based", False):
        from ..train.optim import NormBasedTransformation
        wrapped = NormBasedTransformation(init, update)
    return wrapped


# ---------------------------------------------------------------------------
# host-level faults
# ---------------------------------------------------------------------------

def _poison_first_float_leaf(batch, value: float):
    """Return ``batch`` with element [0...] of its first float leaf set to
    ``value`` (NaN-poisoning a data batch — reference for how real bad
    records reach the loss).  Integer leaves (LM tokens, labels) are left
    alone."""
    import jax
    import numpy as np_  # local alias: keep module numpy pristine

    done = False

    def poke(leaf):
        nonlocal done
        arr = np_.asarray(leaf)
        if not done and np_.issubdtype(arr.dtype, np_.floating):
            arr = arr.copy()
            arr.reshape(-1)[0] = value
            done = True
            return arr
        return leaf

    out = jax.tree.map(poke, batch)
    if not done:
        raise ValueError("batch_nan fault: batch has no float leaf to "
                         "poison (LM token batches need a grad_* fault "
                         "instead)")
    return out


def _scale_float_leaves(batch, factor: float):
    import jax
    import numpy as np_

    def scale(leaf):
        arr = np_.asarray(leaf)
        if np_.issubdtype(arr.dtype, np_.floating):
            return arr * arr.dtype.type(factor)
        return leaf

    return jax.tree.map(scale, batch)


class Injector:
    """Executes a plan's host-level faults around a training loop.

    Each spec fires exactly once (consumed on fire) and is counted in
    ``fired``; ``log`` records the deterministic event sequence for the
    reproducibility assertion.  All decisions are pure functions of the
    plan — no wall clock, no RNG — so the same plan replays identically.
    """

    def __init__(self, plan: FaultPlan, rank: int = 0):
        self.plan = plan
        self.rank = rank
        self._pending = {step: list(specs)
                         for step, specs in plan.host_faults().items()}
        self.fired: dict = {}
        self.log: list = []

    def unfired(self) -> list:
        """Specs that never fired (scheduled past the end of the run, or
        on a hook the loop doesn't wire).  Loops report these at exit —
        a chaos run that silently skipped a fault proves nothing."""
        return sorted(f for specs in self._pending.values() for f in specs)

    def _take(self, step: int, kinds: Iterable[str]) -> Optional[FaultSpec]:
        specs = self._pending.get(step, [])
        for i, f in enumerate(specs):
            if f.kind in kinds:
                del specs[i]
                # each spec fires exactly once, so both records are
                # bounded by the static plan size (kind vocabulary /
                # one log entry per planned fault)
                self.fired[f.kind] = self.fired.get(f.kind, 0) + 1  # cpd: disable=host-unbounded -- keyed by the static fault-kind vocabulary
                self.log.append((f.kind, step))  # cpd: disable=host-unbounded -- one entry per planned fault; plans are finite by construction
                return f
        return None

    # -- hooks, in loop order --------------------------------------------

    def maybe_preempt(self, step: int) -> None:
        if self._take(step, ("preempt",)) is not None:
            raise InjectedPreemption(f"injected preemption at step {step}")

    def batch_action(self, step: int) -> Optional[str]:
        """'drop' / 'dup' / None — the loop owns the actual data motion."""
        f = self._take(step, ("data_drop", "data_dup"))
        if f is None:
            return None
        return "drop" if f.kind == "data_drop" else "dup"

    def corrupt_batch(self, step: int, batch):
        f = self._take(step, ("batch_nan", "batch_scale"))
        if f is None:
            return batch
        if f.kind == "batch_nan":
            return _poison_first_float_leaf(batch, float("nan"))
        return _scale_float_leaves(batch, f.arg if f.arg > 0 else 1e6)

    def maybe_stall(self, step: int) -> float:
        f = self._take(step, ("stall",))
        if f is None:
            return 0.0
        secs = f.arg if f.arg > 0 else 1.0
        time.sleep(secs)
        return secs

    def fault_loss(self, step: int, loss: float) -> float:
        f = self._take(step, ("loss_spike",))
        if f is None:
            return loss
        return loss * (f.arg if f.arg > 0 else 1e6)

    def corrupt_checkpoint(self, step: int, directory: str) -> bool:
        """Truncate or bit-flip the just-saved step's largest data file.
        Called by the loop right after a (finished) save at ``step``."""
        f = self._take(step, ("ckpt_truncate", "ckpt_bitflip"))
        if f is None:
            return False
        # ONE injection body for old and new storage drills (ISSUE 20):
        # the byte-level damage is `cpd_tpu.store.faultfs.corrupt_file`,
        # exactly what the `store_torn` / `store_flip` kinds use.
        from ..store.faultfs import corrupt_file
        step_dir = os.path.join(directory, str(step))
        if not os.path.isdir(step_dir):
            # a store-backed CheckpointManager keeps no per-step dir:
            # its checkpoints are DurableStore generations.  Aim at the
            # generation whose sealed manifest records this step.
            step_dir = self._store_generation_dir(directory, step)
        victim, size = None, -1
        for root, _, files in os.walk(step_dir):
            for name in sorted(files):
                p = os.path.join(root, name)
                s = os.path.getsize(p)
                if s > size:
                    victim, size = p, s
        if victim is None:
            raise FileNotFoundError(
                f"{f.kind} fault at step {step}: no checkpoint files "
                f"under {step_dir}")
        if f.kind == "ckpt_truncate":
            corrupt_file(victim, torn_at=-1)
        else:
            corrupt_file(victim, flip_at=-1)
        return True

    @staticmethod
    def _store_generation_dir(directory: str, step: int) -> str:
        """The ``gen-*`` directory of a `DurableStore`-backed checkpoint
        root whose manifest records ``step`` (newest first)."""
        best = os.path.join(directory, str(step))   # reported on miss
        for name in sorted(os.listdir(directory), reverse=True):
            if not name.startswith("gen-"):
                continue
            mpath = os.path.join(directory, name, "MANIFEST.json")
            try:
                with open(mpath) as fh:
                    if json.load(fh).get("step") == step:
                        return os.path.join(directory, name)
            except (OSError, ValueError):
                continue
        return best


def report_unfired(injector: Optional["Injector"], *, n_steps: Optional[int]
                   = None, meter=None, rank: int = 0,
                   wire_armed: bool = True,
                   sat_armed: bool = True,
                   kv_armed: bool = False,
                   serve_armed: bool = False,
                   fleet_armed: bool = False,
                   host_armed: bool = False,
                   store_armed: bool = False) -> list:
    """The ONE end-of-run check every loop calls: which planned faults
    never fired?  A chaos run that silently skipped a fault proves
    nothing — the usual causes are a plan step beyond the run's
    ``n_steps`` and a fault kind on a hook the run never wired, both
    silent user errors until this surfaces them.

    Covers the host-level one-shots (``Injector.unfired()``), the
    jit-level grad/wire/sat specs scheduled past the end of the compiled
    fault table (when ``n_steps`` is given — the schedule builders drop
    those without a sound), and — when the caller passes
    ``wire_armed=False`` / ``sat_armed=False`` — EVERY wire / sat spec,
    because the run's step never baked the corresponding table in
    (e.g. ``wire_flip`` planned for a faithful-mode run, or
    ``sat_pressure`` planned for a pp/moe run whose stepper takes no
    ``sat_fault_plan``; the trainers compute both from their config).
    ``kv_armed`` defaults False: the ``kv_flip`` kind only exists on the
    serving engine's clock (which does its OWN unfired accounting,
    `ServeEngine.report_unfired`), so a kv spec in a TRAINING plan is
    always a never-fires user error and is surfaced here.
    ``serve_armed`` defaults False for exactly the same reason: the
    `SERVE_KINDS` (``kv_storm``/``slot_stall``/``req_burst``, ISSUE 10)
    also live on the serving engine's clock and do their own unfired
    accounting there — in a training plan they can never fire and are
    flagged here.  ``fleet_armed`` likewise covers `FLEET_KINDS`
    (``engine_kill``/``kill_wave``, ISSUE 13/17): only a
    `cpd_tpu.fleet.Fleet` consumes them (its own `Fleet.report_unfired`
    owns armed accounting — including kills aimed at engine indices the
    autoscaled fleet shape never contained), so in any other plan they
    are flagged.  ``host_armed`` covers `ELASTIC_KINDS`
    (``host_kill``/``straggler``/``link_flaky``, ISSUE 19): only an
    elastic consumer (`resilience.elastic.run_elastic`, or a trainer
    run with ``--elastic``) executes them and owns their one-shot +
    unfired accounting, so in a non-elastic run — the default — they
    can never fire and are flagged here.  ``store_armed`` covers
    `STORE_KINDS` (``store_torn``/``store_flip``/``store_eio``/
    ``store_enospc``, ISSUE 20): only a `cpd_tpu.store.DurableStore`
    built with ``fault_plan=`` consumes them (its own
    `DurableStore.report_unfired` owns the armed direction — a spec
    aimed at a publish number the run never reached stays pending
    there), so in any run without a store attached they are flagged.
    Bumps the meter's ``faults_unfired`` counter and warns on rank 0;
    returns the sorted leftover list (empty = every planned fault
    fired)."""
    if injector is None:
        return []
    leftover = list(injector.unfired())
    for f in (injector.plan.grad_faults() + injector.plan.wire_faults()
              + injector.plan.sat_faults() + injector.plan.kv_faults()
              + injector.plan.serve_faults()
              + injector.plan.fleet_faults()
              + injector.plan.elastic_faults()
              + injector.plan.store_faults()):
        if f.kind in KV_KINDS or f.kind in SERVE_KINDS \
                or f.kind in FLEET_KINDS or f.kind in ELASTIC_KINDS \
                or f.kind in STORE_KINDS:
            # engine/fleet/elastic-consumer kinds: the training
            # ``n_steps`` budget says nothing about them.  Unarmed ->
            # can never fire, flagged; armed -> the consumer's own
            # accounting owns them.
            armed = (kv_armed if f.kind in KV_KINDS
                     else serve_armed if f.kind in SERVE_KINDS
                     else fleet_armed if f.kind in FLEET_KINDS
                     else store_armed if f.kind in STORE_KINDS
                     else host_armed)
            if not armed:
                leftover.append(f)
            continue
        past = n_steps is not None and f.step >= n_steps
        unwired = ((not wire_armed and f.kind in WIRE_KINDS)
                   or (not sat_armed and f.kind in SAT_KINDS))
        if past or unwired:
            leftover.append(f)
    leftover = sorted(set(leftover))
    if leftover:
        if meter is not None:
            meter.bump("faults_unfired", len(leftover))
        if rank == 0:
            import sys
            print(f"=> fault plan: {len(leftover)} spec(s) never fired "
                  f"(scheduled past the end of the run, or on a hook "
                  f"this loop does not wire): {leftover}",
                  file=sys.stderr)
    return leftover
