"""Degraded-transport supervision for the verified quantized reduce.

When ``sum_gradients(..., verify=True)`` reports a failed step (hop
checksum mismatch, gather-row mismatch, or cross-replica disagreement —
parallel/integrity.py), something between the replicas is lying.  The
response ladder, encoded here as a host-side state machine:

    ring ──(retries exhausted)──> faithful ──(again)──> fp32
      ^                               |                   |
      └──── N clean steps ────────────┴──── N clean ──────┘

* **retry** — the step is re-run on the SAME batch and state (a
  transient wire fault clears; a deterministic injected one does not,
  which is what forces the next rung).  Bounded by ``max_retries``.
* **downgrade** — one rung down the transport ladder: the ring's custom
  wire is abandoned for the faithful gather (XLA's own all_gather, no
  eXmY hop payloads), and the faithful gather for a plain fp32 psum —
  each rung trades wire efficiency for a simpler, harder-to-corrupt
  transport while keeping the run ALIVE.
* **probation** — after ``probation`` consecutive clean verified steps
  at a degraded level, move one rung back up; a healthy wire earns its
  fast transport back.
* **give_up** — a failure at the bottom rung (fp32 psum disagreeing
  across replicas) is not a transport problem; the loop aborts.

The supervisor is pure host state — no RNG, no wall clock — so a run
under a deterministic ``FaultPlan`` replays its exact transition
sequence (asserted in tests/test_resilience.py).  `run_guarded`
(resilience/loop.py) drives it; the example trainers wire the same
ladder around their own loops; every transition is counted in
``ResilienceMeter`` and printed as a trainer log line.
"""

from __future__ import annotations

from typing import Callable, Optional

__all__ = ["TransportSupervisor", "StepTable", "level_reduce_kwargs"]


class TransportSupervisor:
    """The ring -> faithful -> fp32 degradation ladder (module docstring).

    ``on_failure(step)`` -> "retry" | "downgrade" | "give_up";
    ``on_success(step)`` -> "upgrade" | None.  ``mode`` names the level
    whose step function the loop should run next; ``transitions`` is the
    deterministic (step, from, to) log the chaos tests assert on.
    """

    LEVELS = ("ring", "faithful", "fp32")

    # transition-log cap: keep the newest entries, drop the oldest
    TRANSITION_CAP = 4096

    def __init__(self, start: str = "ring", max_retries: int = 1,
                 probation: int = 8):
        if start not in self.LEVELS:
            raise ValueError(f"unknown transport level {start!r}; know "
                             f"{self.LEVELS}")
        if max_retries < 0:
            raise ValueError(f"max_retries must be >= 0, got {max_retries}")
        if probation < 1:
            raise ValueError(f"probation must be >= 1, got {probation}")
        self._home = self.LEVELS.index(start)   # the configured level:
        self._level = self._home                # probation returns HERE,
        self.max_retries = max_retries          # never above it
        self.probation = probation
        self.retries = 0          # consecutive failures at this step
        self.clean = 0            # consecutive clean steps at this level
        # (step, from_level, to_level); newest TRANSITION_CAP entries —
        # a flapping transport must not grow this forever (host-unbounded)
        self.transitions: list = []

    @property
    def mode(self) -> str:
        return self.LEVELS[self._level]

    @property
    def home(self) -> str:
        """The level the run was configured to use — the probation
        ceiling (a faithful-mode run must never be 'upgraded' onto the
        ring transport the user did not ask for)."""
        return self.LEVELS[self._home]

    @property
    def degraded(self) -> bool:
        return self._level > self._home

    def on_failure(self, step: int) -> str:
        """A verified reduce failed at `step`: decide retry / downgrade /
        give_up.  Resets the probation streak either way."""
        self.clean = 0
        if self.retries < self.max_retries:
            self.retries += 1
            return "retry"
        self.retries = 0
        if self._level + 1 < len(self.LEVELS):
            old = self.mode
            self._level += 1
            self._record(step, old)
            return "downgrade"
        return "give_up"

    def on_success(self, step: int) -> Optional[str]:
        """A verified reduce passed at `step`: advance probation, and
        return "upgrade" when the streak earns a rung back."""
        self.retries = 0
        self.clean += 1
        if self._level > self._home and self.clean >= self.probation:
            old = self.mode
            self._level -= 1
            self.clean = 0
            self._record(step, old)
            return "upgrade"
        return None

    def _record(self, step: int, old: str) -> None:
        self.transitions.append((step, old, self.mode))
        if len(self.transitions) > self.TRANSITION_CAP:
            del self.transitions[0]


def level_reduce_kwargs(level: str, grad_exp: int, grad_man: int) -> dict:
    """The `sum_gradients` precision/mode kwargs for one ladder rung —
    the ONE mapping from supervisor level to reduction config, shared by
    run_guarded harness code, the trainers, and the tests."""
    if level == "ring":
        return dict(mode="ring", grad_exp=grad_exp, grad_man=grad_man)
    if level == "faithful":
        return dict(mode="faithful", grad_exp=grad_exp, grad_man=grad_man)
    if level == "fp32":
        # plain psum at the identity format — the reference's own fp32
        # shortcut; no custom wire left to corrupt
        return dict(mode="fast", grad_exp=8, grad_man=23)
    raise ValueError(f"unknown transport level {level!r}; know "
                     f"{TransportSupervisor.LEVELS}")


class StepTable:
    """Lazily-built ``level -> jitted step`` mapping.

    Building a step means an XLA trace+compile, so the degraded rungs
    are only paid for when a downgrade actually reaches them; entries
    are cached, so flapping between levels compiles each rung once."""

    def __init__(self, build: Callable[[str], Callable]):
        self._build = build
        self._cache: dict = {}

    def __getitem__(self, level: str) -> Callable:
        if level not in self._cache:
            self._cache[level] = self._build(level)  # cpd: disable=host-unbounded -- keyed by the static level/rung vocabulary (LEVELS / ladder rungs), not the step clock
        return self._cache[level]

    def __contains__(self, level: str) -> bool:
        return True      # any level is buildable; cache fills on demand
