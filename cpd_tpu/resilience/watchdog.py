"""Step watchdog — a hung step becomes a diagnosis, not a silent wedge.

Pod-scale reality: a step that normally takes 300ms occasionally never
returns — a wedged collective, a straggler host, a dead interconnect
tunnel.  The blocking call cannot time itself out, so a background timer
thread does: on expiry it (1) dumps the last-known context and every
thread's stack to stderr, (2) marks itself ``tripped``, and (3) sends
the process a real SIGINT (``os.kill`` — an actual OS signal, which
wakes a blocked ``time.sleep``/select immediately; NOT
``_thread.interrupt_main``, whose simulated flag is only noticed at the
main thread's next bytecode, i.e. never while it is blocked).  With the
default handler that raises ``KeyboardInterrupt``; the training loop
catches it, sees ``tripped``, checkpoints the last *good* state, and
exits cleanly — distinguishable from a real Ctrl-C, which it re-raises.

The interrupt path has two honest limitations.  (1) A PreemptionGuard
traps SIGINT, so the watchdog's signal sets ITS flag
instead of raising — the trainers therefore also check
``watchdog.tripped`` at the step boundary.  (2) A step wedged inside
native code (a dead collective rendezvous, a hung device sync) never
returns to the interpreter at all, so NO Python-level signal can
unblock it.  ``hard_exit_after`` covers both: if the trip is not
acknowledged (disarm/boundary) within that many extra seconds, the
watchdog prints a final line and ``os._exit(124)``s — the run dies
with diagnostics and the last periodic checkpoint intact instead of
hanging forever; the cluster supervisor restarts it.

Arm/disarm around the blocking region only (the step call + the metric
device-sync); host-side data loading gets its own budget if needed.
"""

from __future__ import annotations

import faulthandler
import os
import signal
import sys
import threading
from typing import Optional

__all__ = ["StepWatchdog"]


class StepWatchdog:
    def __init__(self, timeout: float, *, rank: int = 0,
                 interrupt: bool = True,
                 hard_exit_after: Optional[float] = None,
                 on_trip=None):
        if timeout <= 0:
            raise ValueError(f"watchdog timeout must be > 0, got {timeout}")
        if hard_exit_after is not None and hard_exit_after <= 0:
            raise ValueError(f"hard_exit_after must be > 0, got "
                             f"{hard_exit_after}")
        self.timeout = float(timeout)
        self.rank = rank
        self.interrupt = interrupt
        self.hard_exit_after = hard_exit_after
        # ``on_trip(context_dict)`` runs on the timer thread at fire
        # time, BEFORE the interrupt is sent — the obs flight recorder
        # hooks its dump here so even a wedge that ends in the
        # hard-exit path leaves the recent-event ring on disk
        # (cpd_tpu/obs/flight.py).  Best-effort: a failing hook must
        # not stop the interrupt.
        self.on_trip = on_trip
        self.tripped = False
        self.trips = 0
        self._timer: Optional[threading.Timer] = None
        self._exit_timer: Optional[threading.Timer] = None
        self._context: dict = {}
        self._lock = threading.Lock()

    def arm(self, step: int, **context) -> None:
        """Start (or restart) the countdown for ``step``.  ``context`` is
        whatever the loop knows (last metrics, phase) — it goes verbatim
        into the diagnostic dump.

        Arming CLEARS ``tripped``: a fresh deadline is a fresh verdict.
        Without this a loop that recovers and continues (a guarded
        rollback, an elastic shrink) would see the PREVIOUS step's stale
        trip at its next boundary check and abort a perfectly healthy
        recovery step (ISSUE 19 bugfix).  A trip fired DURING a step
        stays visible at that step's boundary — arm precedes the step —
        and the cumulative ``trips`` total is never reset."""
        with self._lock:
            self._cancel_locked()
            self.tripped = False
            self._context = {"step": step, **context}
            self._timer = threading.Timer(self.timeout, self._fire)
            self._timer.daemon = True
            self._timer.start()

    def disarm(self) -> None:
        with self._lock:
            self._cancel_locked()

    close = disarm

    def _cancel_locked(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        if self._exit_timer is not None:
            # the trip was acknowledged in time: call off the hard exit
            self._exit_timer.cancel()
            self._exit_timer = None

    def _fire(self) -> None:
        with self._lock:
            # trip verdict AND context snapshot under the same lock
            # arm() holds while clearing `tripped` / swapping _context
            # in — this timer thread races the main loop re-arming for
            # the next step (host-race, ISSUE 16); everything below
            # works on the snapshot
            self.tripped = True
            self.trips += 1
            context = dict(self._context)
        ctx = dict(context)
        print(f"=> watchdog: step {ctx.pop('step', '?')} exceeded "
              f"{self.timeout:.1f}s; last known: {ctx}", file=sys.stderr,
              flush=True)
        try:
            faulthandler.dump_traceback(file=sys.stderr, all_threads=True)
        except Exception as e:
            # diagnostics are best-effort; the interrupt below must
            # still fire even when stderr is a closed pipe
            print(f"=> watchdog: stack dump failed: {e}", file=sys.stderr)
        if self.hard_exit_after is not None:
            # armed BEFORE the on_trip hook: a hook that BLOCKS (its
            # dump path living on the same hung filesystem that wedged
            # the step) must not defeat the backstop — the try/except
            # below only covers a raising hook, not a hanging one
            with self._lock:
                self._exit_timer = threading.Timer(self.hard_exit_after,
                                                   self._hard_exit)
                self._exit_timer.daemon = True
                self._exit_timer.start()
        if self.on_trip is not None:
            try:
                self.on_trip(dict(context))
            except Exception as e:
                print(f"=> watchdog: on_trip hook failed: {e}",
                      file=sys.stderr)
        if self.interrupt:
            # a REAL SIGINT (not _thread.interrupt_main, which only sets
            # a flag the main thread notices at its next bytecode — i.e.
            # never, while it is blocked): the OS signal wakes a blocked
            # time.sleep/select immediately, exactly like a Ctrl-C
            os.kill(os.getpid(), signal.SIGINT)

    def _hard_exit(self) -> None:
        # the interrupt was never honored: the main thread is wedged in
        # native code (or a SIGINT-trapping guard absorbed the signal
        # and the boundary never came).  Dying loudly with the last
        # periodic checkpoint intact beats hanging forever.
        print(f"=> watchdog: trip unacknowledged after "
              f"{self.hard_exit_after:.1f}s — hard exit (124)",
              file=sys.stderr, flush=True)
        os._exit(124)
