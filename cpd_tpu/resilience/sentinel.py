"""Divergence sentinel — rolling-window loss blow-up detection.

`loss_diverged` (train/metrics.py) only catches the terminal symptom: a
loss that is already NaN/Inf.  Low-precision runs usually *announce* the
divergence first — the loss jumps orders of magnitude above its recent
history while still finite, at which point the parameters are often
already damaged and the only honest recovery is a rollback.  The
sentinel keeps a window of recent finite losses and trips when the new
loss exceeds ``factor`` x the window median (median, not mean: one
earlier spike must not inflate the baseline and mask the next one).

``mode="ema"`` (beyond the PR-2 default) is the *drift* detector: the
median mode is blind to a SLOW upward creep — the signature of quiet
saturation/underflow at a too-narrow eXmY format, where each step loses
a little gradient mass and the loss ratchets up gently — because the
creep drags the window median up with it and the factor-x-median bar is
never cleared.  EMA mode keeps two exponential averages of the loss, a
fast one (span ``min_history``) tracking "now" and a slow windowed one
(span ``window``) tracking "recently", and trips when fast >
``factor`` x slow: a drift opens a persistent gap between the two long
before any single step looks like a spike.  Pick a smaller ``factor``
for this mode (the gap between two EMAs of a drifting series is
bounded by the drift rate, not by the blow-up size) — the trainers
expose it as ``--divergence-mode ema``.  The default stays "median":
existing runs keep the PR-2 behavior bit-for-bit.

The verdict is host-side and replicated-input (the loss metric is
all-reduced), so every host trips at the same step.  The loop owns the
recovery: restore the newest *valid* checkpoint, re-seed the data order,
bounded retries with backoff (resilience/loop.py).
"""

from __future__ import annotations

import math
import statistics
from collections import deque

__all__ = ["DivergenceSentinel"]


class DivergenceSentinel:
    def __init__(self, window: int = 20, factor: float = 10.0,
                 min_history: int = 5, mode: str = "median"):
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        if factor <= 1.0:
            raise ValueError(f"factor must be > 1, got {factor}")
        if mode not in ("median", "ema"):
            raise ValueError(f"unknown sentinel mode {mode!r}; know "
                             f"('median', 'ema')")
        self.window = window
        self.factor = factor
        self.mode = mode
        # a min_history the window can never reach would silently disarm
        # the sentinel (len(deque(maxlen=w)) <= w)
        self.min_history = min(min_history, window)
        self.losses: deque = deque(maxlen=window)
        # ema state (mode="ema"): standard span -> alpha = 2/(span+1)
        self._a_fast = 2.0 / (self.min_history + 1)
        self._a_slow = 2.0 / (self.window + 1)
        self._fast = 0.0
        self._slow = 0.0
        self._count = 0

    def update(self, loss: float) -> bool:
        """Record ``loss``; True when it signals divergence.  A diverged
        loss is NOT added to the history — the baseline stays honest for
        the post-rollback replay."""
        loss = float(loss)
        if not math.isfinite(loss):
            return True
        if self.mode == "ema":
            return self._update_ema(loss)
        if (len(self.losses) >= self.min_history
                and loss > self.factor * statistics.median(self.losses)):
            return True
        self.losses.append(loss)
        return False

    def _update_ema(self, loss: float) -> bool:
        if self._count == 0:
            self._fast = self._slow = loss
            self._count = 1
            return False
        fast_next = self._fast + self._a_fast * (loss - self._fast)
        # positive-loss contract (same as factor-x-median): a ratio
        # test needs a positive baseline; until the slow EMA is, the
        # drift check stays disarmed (non-finite still trips above)
        if (self._count >= self.min_history and self._slow > 0.0
                and fast_next > self.factor * self._slow):
            return True
        self._fast = fast_next
        self._slow = self._slow + self._a_slow * (loss - self._slow)
        self._count += 1
        return False

    def reset(self) -> None:
        """Forget the history (after a rollback: the restored model's
        losses are the new baseline)."""
        self.losses.clear()
        self._fast = self._slow = 0.0
        self._count = 0
