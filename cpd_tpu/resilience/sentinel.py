"""Divergence sentinel — rolling-window loss blow-up detection.

`loss_diverged` (train/metrics.py) only catches the terminal symptom: a
loss that is already NaN/Inf.  Low-precision runs usually *announce* the
divergence first — the loss jumps orders of magnitude above its recent
history while still finite, at which point the parameters are often
already damaged and the only honest recovery is a rollback.  The
sentinel keeps a window of recent finite losses and trips when the new
loss exceeds ``factor`` x the window median (median, not mean: one
earlier spike must not inflate the baseline and mask the next one).

The verdict is host-side and replicated-input (the loss metric is
all-reduced), so every host trips at the same step.  The loop owns the
recovery: restore the newest *valid* checkpoint, re-seed the data order,
bounded retries with backoff (resilience/loop.py).
"""

from __future__ import annotations

import math
import statistics
from collections import deque

__all__ = ["DivergenceSentinel"]


class DivergenceSentinel:
    def __init__(self, window: int = 20, factor: float = 10.0,
                 min_history: int = 5):
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        if factor <= 1.0:
            raise ValueError(f"factor must be > 1, got {factor}")
        self.window = window
        self.factor = factor
        # a min_history the window can never reach would silently disarm
        # the sentinel (len(deque(maxlen=w)) <= w)
        self.min_history = min(min_history, window)
        self.losses: deque = deque(maxlen=window)

    def update(self, loss: float) -> bool:
        """Record ``loss``; True when it signals divergence.  A diverged
        loss is NOT added to the history — the baseline stays honest for
        the post-rollback replay."""
        loss = float(loss)
        if not math.isfinite(loss):
            return True
        if (len(self.losses) >= self.min_history
                and loss > self.factor * statistics.median(self.losses)):
            return True
        self.losses.append(loss)
        return False

    def reset(self) -> None:
        """Forget the history (after a rollback: the restored model's
        losses are the new baseline)."""
        self.losses.clear()
