"""Precision supervision — the eXmY format-escalation ladder.

The paper's premise is that a *well-chosen* eXmY format trains
accurately; every format in this framework is chosen once at launch.
But a long run visits regimes the launch-time choice never saw: gradient
magnitudes drift, the reduce wire starts saturating to ±Inf or flushing
to zero, and by the time the loss (or the grad guard) notices, the
damage is steps old.  PR 4 built the reflex for the *transport*
(`transport.TransportSupervisor`'s ring → faithful → fp32 ladder); this
module is the same reflex for *precision itself*:

    e4m3 ──(sat/NaN rate hot for K steps)──> e5m7 ──(again)──> e8m23
      ^                                        |                 |
      └────── probation: N quiet steps ────────┴──── N quiet ────┘

* **sense** — the in-jit numeric-health counters
  (`quant.numerics.quant_health`, threaded through
  `sum_gradients(stats=True)` into the step metrics as
  ``prec_wire_sat`` / ``prec_wire_nan`` / ``prec_wire_underflow`` /
  ``prec_wire_total`` / ``prec_aps_bad``).  They are psum-agreed across
  replicas, so every host sees the same verdict and escalates in
  lockstep.
* **escalate** — when the agreed saturation+NaN rate exceeds
  ``threshold`` for ``patience`` consecutive steps (or APS reports
  non-finite gradient leaves), move one rung up the configured format
  schedule.  The loop re-traces the train step at the new format via
  the same `StepTable` machinery the transport ladder uses.
* **probation** — after ``probation`` consecutive quiet steps at an
  escalated rung, move one rung back down — never below the configured
  home format (rung 0): the run earns its cheap format back, it is
  never silently migrated to a format the user did not configure.
* **persist** — `state_dict()` is JSON-able and rides the checkpoint
  metadata sidecar (`CheckpointManager.save(metadata=...)`), so a
  restart resumes AT the escalated format instead of re-diverging from
  the home format (`load_state_dict`, fed from
  `RestoreResult.metadata` / `CheckpointManager.metadata()`).

The supervisor is pure host state — no RNG, no wall clock — so a run
under a deterministic ``FaultPlan`` (the ``sat_pressure`` attack,
resilience/inject.py) replays its exact transition sequence (asserted
in tests/test_precision.py).  `run_guarded` (resilience/loop.py) drives
it; the lm and resnet18 CLIs wire the same ladder via
``--precision-ladder`` / ``--sat-threshold`` / ``--sat-patience`` /
``--precision-probation``.

Escalation is *forward-looking*: the step that tripped the detector
already ran at the old format, and its update is kept (when the values
actually went non-finite, the grad guard's skip — a separate, composing
defense — already zeroed it).  The ladder changes what the NEXT steps
pay, which is the honest contract: telemetry cannot un-round a cast
that already happened.
"""

from __future__ import annotations

import re
from typing import Any, Optional, Sequence

__all__ = ["PrecisionSupervisor", "parse_format", "parse_ladder",
           "format_name", "ladder_step_key", "resolve_ladder_key"]

_FMT_RE = re.compile(r"^e(\d{1,2})m(\d{1,2})$")


def parse_format(text) -> tuple:
    """'eXmY' (or an (exp, man) pair) -> validated (exp, man) tuple."""
    if isinstance(text, (tuple, list)):
        exp, man = int(text[0]), int(text[1])
    else:
        m = _FMT_RE.match(str(text).strip().lower())
        if not m:
            raise ValueError(f"bad eXmY format spec {text!r} (want e.g. "
                             f"'e4m3', 'e8m23')")
        exp, man = int(m.group(1)), int(m.group(2))
    if not (1 <= exp <= 8):
        raise ValueError(f"exp_bits must be in [1, 8], got {exp} "
                         f"(in {text!r})")
    if not (0 <= man <= 23):
        raise ValueError(f"man_bits must be in [0, 23], got {man} "
                         f"(in {text!r})")
    return (exp, man)


def format_name(fmt) -> str:
    """(exp, man) -> 'eXmY'."""
    return f"e{int(fmt[0])}m{int(fmt[1])}"


def parse_ladder(text) -> tuple:
    """'e4m3,e5m7,e8m23' (or a sequence of specs) -> tuple of (exp, man).

    Rung 0 is the HOME format; each subsequent rung must strictly widen
    the representable range (`numerics.max_finite`) — an escalation that
    cannot hold larger values would be a lateral move the saturation
    detector re-trips on forever."""
    from ..quant.numerics import max_finite
    parts = ([p for p in str(text).replace(";", ",").split(",")
              if p.strip()] if isinstance(text, str) else list(text))
    if len(parts) < 2:
        raise ValueError(f"a precision ladder needs >= 2 rungs (home + at "
                         f"least one escalation), got {text!r}")
    fmts = tuple(parse_format(p) for p in parts)
    for lo, hi in zip(fmts, fmts[1:]):
        if max_finite(*hi) <= max_finite(*lo):
            raise ValueError(
                f"ladder rung {format_name(hi)} does not widen the "
                f"range over {format_name(lo)} (max_finite "
                f"{max_finite(*hi):.4g} <= {max_finite(*lo):.4g}); "
                f"order rungs from home to widest")
    return fmts


def ladder_step_key(transport=None, precision=None, overlap=None,
                    block=None, fused=None):
    """The ONE `StepTable` key derivation shared by `run_guarded` and
    the trainer CLIs, covering every supervisor combination:

      transport only          -> the level name (PR-4 compatible)
      precision only          -> the (exp, man) format tuple
      both                    -> (level, (exp, man))
      neither                 -> None (caller uses its fixed step)

    ``overlap``, when given, is a ``(overlap_reduce, bucket_elems)``
    pair appended as an explicit key coordinate (ISSUE 8): a step traced
    with the overlapped transport / one bucket layout must never be
    served to a configuration without it after a ladder transition — the
    PR 5 half-keyed-table bug class, extended to the transport schedule.
    Callers whose run has NO overlap surface pass None and keep the
    PR 4/5-compatible key shapes.

    ``block``, when given, is a ``(block_scale, block_size)`` pair
    appended the same way (ISSUE 9): the block-scaled ring wire is a
    DIFFERENT documented accumulation numerics (and a different wire
    layout) than the per-tensor cast, so a step traced with one block
    coordinate must never be served after a transport/precision ladder
    transition to a run configured with another — the transport ladder
    retraces through the blocked rung, the precision ladder re-derives
    per-block shifts at the new format.  Runs that never touch the
    block surface pass None and keep the PR 8-compatible key shapes.

    ``fused``, when given, is the serving engine's ``fused_attn`` flag
    appended the same way (ISSUE 18): the fused gather→unpack→attention
    kernel and the XLA composition are DIFFERENT compiled programs over
    the same decode contract, so a ladder transition must never serve a
    step traced with one read path to a configuration running the
    other.  Runs without the serving surface pass None and keep the
    prior key shapes."""
    if transport is not None and precision is not None:
        base = (transport.mode, precision.fmt)
    elif precision is not None:
        base = precision.fmt
    elif transport is not None:
        base = transport.mode
    else:
        base = None
    if overlap is not None:
        base = (base, ("overlap",) + tuple(overlap))
    if block is not None:
        base = (base, ("block",) + tuple(block))
    if fused is not None:
        base = (base, ("fused", bool(fused)))
    return base


def resolve_ladder_key(key, *, transport_on: bool, precision_on: bool,
                       level: str, fmt: tuple,
                       overlap_on: bool = False,
                       block_on: bool = False,
                       fused_on: bool = False) -> tuple:
    """Inverse of `ladder_step_key` for StepTable build functions: map a
    table key back to ``(transport_level, (exp, man))``, filling the
    coordinate a missing supervisor pins from the run's static config
    (``level`` = the configured --mode, ``fmt`` = the configured
    gradient format).  The ONE unpacking shared by the trainer CLIs so
    the three-way branch cannot drift between them.  ``overlap_on`` /
    ``block_on`` / ``fused_on`` strip the key's ``("overlap", ...)`` /
    ``("block", ...)`` / ``("fused", ...)`` coordinates first — in
    reverse append order, fused outermost (the builder reads the
    overlap/block/fused config from its static flags — the coordinates
    exist to split the CACHE, not to carry data)."""
    if fused_on:
        key = key[0]
    if block_on:
        key = key[0]
    if overlap_on:
        key = key[0]
    if transport_on and precision_on:
        return key
    if transport_on:
        return key, fmt
    if precision_on:
        return level, key
    return level, fmt


class PrecisionSupervisor:
    """The format-escalation state machine (module docstring).

    ``on_metrics(step, metrics)`` -> None | "escalate" | "deescalate";
    ``fmt`` is the (exp, man) the loop should build/fetch the next step
    for (`ladder_step_key` + `StepTable`); ``transitions`` is the
    deterministic (step, from, to) log the chaos tests assert on;
    ``last_hot`` is the verdict of the most recent observation (the
    loop's ``sat_hot_steps`` counter feed).
    """

    # transition-log cap: keep the newest entries, drop the oldest
    TRANSITION_CAP = 4096

    def __init__(self, ladder, *, threshold: float = 1e-3,
                 patience: int = 2, probation: int = 16,
                 site: str = "wire"):
        self.ladder = parse_ladder(ladder)
        if not 0.0 <= threshold < 1.0:
            raise ValueError(f"threshold is a rate in [0, 1), got "
                             f"{threshold}")
        if patience < 1:
            raise ValueError(f"patience must be >= 1, got {patience}")
        if probation < 1:
            raise ValueError(f"probation must be >= 1, got {probation}")
        self.threshold = float(threshold)
        self.patience = int(patience)
        self.probation = int(probation)
        self.site = site
        self._level = 0        # index into ladder; 0 == home
        self.hot = 0           # consecutive hot observations
        self.quiet = 0         # consecutive quiet observations
        self.last_hot = False
        # (step, from_name, to_name); newest TRANSITION_CAP entries — a
        # flapping ladder must not grow this forever (host-unbounded)
        self.transitions: list = []

    # -- introspection ----------------------------------------------------

    @property
    def fmt(self) -> tuple:
        """The (exp, man) the next step should run at."""
        return self.ladder[self._level]

    @property
    def home(self) -> tuple:
        """Rung 0 — the configured format; probation never goes below."""
        return self.ladder[0]

    @property
    def name(self) -> str:
        return format_name(self.fmt)

    @property
    def escalated(self) -> bool:
        return self._level > 0

    # -- the state machine ------------------------------------------------

    def observe(self, sat: float, nan: float, total: float,
                aps_bad: float = 0.0) -> bool:
        """Raw-counter form of the hot/quiet verdict: True when the
        agreed saturation+NaN rate exceeds the threshold, or APS saw
        non-finite gradient leaves (`aps_shift_factors_checked`)."""
        rate = (float(sat) + float(nan)) / max(float(total), 1.0)
        return rate > self.threshold or float(aps_bad) > 0.0

    def on_metrics(self, step: int, metrics: dict) -> Optional[str]:
        """Feed one accepted step's metric dict (the ``prec_<site>_*``
        replicated scalars the step builders emit); returns "escalate" /
        "deescalate" when the ladder moves, else None.  Metrics without
        the telemetry keys (telemetry off) read as quiet."""
        p = f"prec_{self.site}_"
        hot = self.observe(metrics.get(p + "sat", 0.0),
                           metrics.get(p + "nan", 0.0),
                           metrics.get(p + "total", 0.0),
                           metrics.get("prec_aps_bad", 0.0))
        self.last_hot = hot
        if hot:
            self.quiet = 0
            self.hot += 1
            if self.hot >= self.patience and \
                    self._level + 1 < len(self.ladder):
                old = self.name
                self._level += 1
                self.hot = 0
                self._record(step, old)
                return "escalate"
            return None
        self.hot = 0
        self.quiet += 1
        if self._level > 0 and self.quiet >= self.probation:
            old = self.name
            self._level -= 1
            self.quiet = 0
            self._record(step, old)
            return "deescalate"
        return None

    def _record(self, step: int, old: str) -> None:
        self.transitions.append((step, old, self.name))
        if len(self.transitions) > self.TRANSITION_CAP:
            del self.transitions[0]

    # -- checkpoint persistence -------------------------------------------

    def state_dict(self) -> dict:
        """JSON-able snapshot for the checkpoint metadata sidecar: a
        restart resumes AT the escalated format (acceptance criterion)
        instead of re-diverging from home."""
        return {
            "ladder": [list(f) for f in self.ladder],
            "site": self.site,
            "level": self._level,
            "hot": self.hot,
            "quiet": self.quiet,
            "transitions": [list(t) for t in self.transitions],
        }

    def load_state_dict(self, state: dict) -> "PrecisionSupervisor":
        """Restore a `state_dict` snapshot (returns self).  The saved
        ladder must match the configured one — resuming level 2 of a
        DIFFERENT schedule would silently run an unintended format; a
        reconfigured run should start the new ladder from home
        (and gets told so explicitly here)."""
        saved = tuple(tuple(f) for f in state["ladder"])
        if saved != self.ladder:
            raise ValueError(
                f"checkpointed precision ladder "
                f"{[format_name(f) for f in saved]} does not match the "
                f"configured {[format_name(f) for f in self.ladder]}; "
                f"restart with the same --precision-ladder, or drop the "
                f"flag's saved state by starting a fresh run directory")
        self._level = min(max(int(state["level"]), 0),
                          len(self.ladder) - 1)
        self.hot = int(state.get("hot", 0))
        self.quiet = int(state.get("quiet", 0))
        self.transitions = [tuple(t) for t in state.get("transitions", [])]
        return self
