"""GradGuard — jit-compatible gradient anomaly detection + skip.

An optax wrapper (the composition pattern of train/scaling.py) that
inspects every incoming gradient tree for

* **non-finite values** — per-leaf counts, so the *culprit tensor* is
  reported, not just "something was NaN";
* **spikes** — a finite global grad-norm far above its running EMA (the
  blow-up precursor a NaN check misses);
* **cross-replica disagreement** — with ``axis_name`` (inside
  shard_map), verdict bits are ``psum``'d: if some replicas see a bad
  gradient and others don't, the *reduce itself* is corrupt (the EQuARX
  failure mode) and every replica skips in lockstep, keeping params
  bitwise replicated.

On an anomalous step the update is zeroed and the inner optimizer state
is preserved — with one deliberate exception: when a
``with_dynamic_loss_scale`` wrapper sits inside, non-finite gradients
are passed THROUGH to it so its backoff policy (halve scale, reset
streak) still executes; the guard then only adds its own accounting and
the spike/agreement checks the scaler cannot do.  Composition order:

    with_fault_injection(with_grad_guard(with_dynamic_loss_scale(tx)))

Under ``--use_APS`` dynamic scaling is redundant (scaling.py docstring)
but the guard is not: APS shifts exponents, it does not detect a
corrupted reduce or a loss blow-up.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..train.scaling import DynamicScaleState

__all__ = ["GradGuardState", "with_grad_guard", "guard_metrics",
           "find_guard", "describe_culprit", "leaf_names"]


class GradGuardState(NamedTuple):
    ema_norm: Any        # f32 EMA of the (unscaled) global grad norm
    seen: Any            # i32 finite steps observed (spike warmup)
    skipped: Any         # i32 total anomalous (skipped) steps
    overflows: Any       # i32 non-finite anomalies
    spikes: Any          # i32 finite-but-spiking anomalies
    disagreements: Any   # i32 cross-replica verdict mismatches
    last_ok: Any         # i32 1/0 — previous step's verdict
    culprit: Any         # i32 leaf index of last non-finite (-1 = none)
    inner: Any


def _find(opt_state, klass):
    def is_node(n):
        return isinstance(n, klass)
    for node in jax.tree.leaves(opt_state, is_leaf=is_node):
        if is_node(node):
            return node
    return None


def find_guard(opt_state) -> Optional[GradGuardState]:
    """The GradGuardState nested anywhere in ``opt_state``, or None."""
    return _find(opt_state, GradGuardState)


def guard_metrics(opt_state) -> dict:
    """Step-metric view of the guard (and fault-injection) counters.

    Safe to call from inside a jitted step on any opt state — returns {}
    when no wrapper is present, so the steppers can merge it
    unconditionally.  All values are replicated scalars (the guard's
    verdicts are ``psum``-agreed when it has an ``axis_name``)."""
    out: dict = {}
    g = find_guard(opt_state)
    if g is not None:
        f32 = jnp.float32
        out.update(guard_ok=g.last_ok.astype(f32),
                   guard_skipped=g.skipped.astype(f32),
                   guard_overflows=g.overflows.astype(f32),
                   guard_spikes=g.spikes.astype(f32),
                   guard_disagreements=g.disagreements.astype(f32),
                   guard_culprit=g.culprit.astype(f32))
    from .inject import FaultInjectState
    fi = _find(opt_state, FaultInjectState)
    if fi is not None:
        out["faults_injected"] = fi.injected.astype(jnp.float32)
    return out


def leaf_names(tree) -> list:
    """Stable human-readable leaf labels, index-aligned with the guard's
    ``culprit`` (both use jax.tree flattening order)."""
    from jax.tree_util import keystr, tree_flatten_with_path
    flat, _ = tree_flatten_with_path(tree)
    return [keystr(path) for path, _ in flat]


def describe_culprit(opt_state, params) -> Optional[str]:
    """Leaf label of the last non-finite gradient, or None."""
    g = find_guard(opt_state)
    if g is None:
        return None
    idx = int(g.culprit)
    if idx < 0:
        return None
    names = leaf_names(params)
    return names[idx] if idx < len(names) else f"<leaf {idx}>"


def with_grad_guard(tx, *, spike_factor: float = 10.0,
                    ema_decay: float = 0.99, warmup_steps: int = 10,
                    axis_name: Optional[str] = None):
    """Wrap ``tx`` with anomaly detection + skip (module docstring).

    ``spike_factor``: a finite step whose unscaled global grad norm
    exceeds ``spike_factor * EMA`` (after ``warmup_steps`` finite steps)
    is skipped.  ``axis_name``: REQUIRED when the update runs inside a
    sharded step and faults/corruption can differ per shard — the psum'd
    verdict is what keeps every replica taking the same branch.  Pass
    EVERY mesh axis the update runs under (a name or a tuple — e.g.
    ``("dp","sp","tp")`` for the LM step): model-sharded leaves (tp/pp/
    ep) legitimately hold different gradient values per shard, so a
    verdict agreed over dp alone would let tp-rank-0 freeze its layer
    shard while tp-rank-1 applies its half of the update.
    """
    if spike_factor <= 1.0:
        raise ValueError(f"spike_factor must be > 1, got {spike_factor}")
    if not 0.0 < ema_decay < 1.0:
        raise ValueError(f"ema_decay must be in (0, 1), got {ema_decay}")
    axes = ((axis_name,) if isinstance(axis_name, str)
            else tuple(axis_name) if axis_name is not None else None)

    def init(params):
        # one fresh buffer per field: sharing a single zeros array across
        # fields makes the state pytree alias itself, which a donating
        # jitted step rejects ("donate the same buffer twice")
        return GradGuardState(
            ema_norm=jnp.zeros([], jnp.float32),
            seen=jnp.zeros([], jnp.int32),
            skipped=jnp.zeros([], jnp.int32),
            overflows=jnp.zeros([], jnp.int32),
            spikes=jnp.zeros([], jnp.int32),
            disagreements=jnp.zeros([], jnp.int32),
            last_ok=jnp.ones([], jnp.int32),
            culprit=jnp.full([], -1, jnp.int32),
            inner=tx.init(params))

    def update(grads, state, params=None):
        leaves = jax.tree.leaves(grads)
        if not leaves:
            updates, new_inner = tx.update(grads, state.inner, params)
            return updates, state._replace(inner=new_inner)
        # per-leaf non-finite counts -> culprit index + global verdict
        bad_vec = jnp.stack([jnp.sum(~jnp.isfinite(l)).astype(jnp.int32)
                             for l in leaves])
        # norm in f64-free fp32; non-finite leaves poison it, but the
        # spike branch is only consulted when everything is finite
        sq = jnp.stack([jnp.sum(jnp.square(l.astype(jnp.float32)))
                        for l in leaves])
        norm = jnp.sqrt(jnp.sum(sq))
        local_bad = jnp.sum(bad_vec) > 0
        if axes is not None:
            world = lax.psum(jnp.float32(1.0), axes)
            bad_replicas = lax.psum(local_bad.astype(jnp.float32), axes)
            finite = bad_replicas == 0.0
            agree = (bad_replicas == 0.0) | (bad_replicas == world)
            bad_vec = lax.psum(bad_vec, axes)
            # pmean so every replica computes the identical spike verdict
            # even when one replica's copy of the grads is corrupt
            norm = lax.pmean(jnp.where(jnp.isfinite(norm), norm, 0.0),
                             axes)
        else:
            finite = ~local_bad
            agree = jnp.bool_(True)

        # unscale the norm when a dynamic loss scale sits inside, so the
        # EMA tracks the TRUE gradient magnitude across scale changes
        dyn = _find(state.inner, DynamicScaleState)
        if dyn is not None:
            norm = norm / dyn.scale
        warmed = state.seen >= warmup_steps
        ref = jnp.maximum(state.ema_norm, jnp.float32(1e-30))
        spike = finite & warmed & (norm > spike_factor * ref)
        ok = finite & ~spike

        # non-finite grads pass through to a nested dynamic scaler (its
        # backoff must run); without one they are zeroed before the inner
        # update so Inf/NaN never reaches optimizer arithmetic.  The
        # scaler's own all_finite check is replica-LOCAL, so on a
        # single-shard corruption the grads handed to it must be made
        # bad on EVERY replica — the psum'd verdict decides, and all
        # scalers take the identical skip+backoff branch (params and
        # scale stay bitwise replicated).
        handled = dyn is not None
        if handled:
            safe = jax.tree.map(
                lambda g: jnp.where(finite, g,
                                    jnp.full_like(g, jnp.nan)), grads)
        else:
            safe = jax.tree.map(
                lambda g: jnp.where(finite, g, jnp.zeros_like(g)), grads)
        updates, new_inner = tx.update(safe, state.inner, params)
        # zero the update / freeze inner state on every skip the inner
        # chain did not already handle itself
        suppress = (~finite & jnp.bool_(not handled)) | spike
        updates = jax.tree.map(
            lambda u: jnp.where(suppress, jnp.zeros_like(u), u), updates)
        new_inner = jax.tree.map(
            lambda n, o: jnp.where(suppress, o, n), new_inner, state.inner)

        ema = jnp.where(
            ok,
            jnp.where(state.seen == 0, norm,
                      ema_decay * state.ema_norm + (1 - ema_decay) * norm),
            state.ema_norm)
        i32 = lambda b: b.astype(jnp.int32)    # noqa: E731
        culprit = jnp.where(jnp.sum(bad_vec) > 0,
                            jnp.argmax(bad_vec).astype(jnp.int32),
                            state.culprit)
        new_state = GradGuardState(
            ema_norm=ema,
            seen=state.seen + i32(ok),
            skipped=state.skipped + i32(~ok),
            overflows=state.overflows + i32(~finite),
            spikes=state.spikes + i32(spike),
            disagreements=state.disagreements + i32(~agree),
            last_ok=i32(ok),
            culprit=culprit,
            inner=new_inner)
        return updates, new_state

    import optax
    wrapped = optax.GradientTransformation(init, update)
    if getattr(tx, "norm_based", False):
        from ..train.optim import NormBasedTransformation
        wrapped = NormBasedTransformation(init, update)
    return wrapped
