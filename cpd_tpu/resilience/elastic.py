"""Elastic training — survive whole-host faults mid-run (ISSUE 19).

The resilience doctrine so far covers wire bits (transport ladder,
ISSUE 4), numeric formats (precision ladder, ISSUE 5) and the serving
fleet (ISSUES 10/13/17) — but the trainer still died with its slowest
or unluckiest host.  This module ports the fleet-style supervision to
training, on the substrate the repo already has: the ZeRO flat layouts
re-flatten bitwise across world sizes (`parallel.ring.pad_to_world` /
`reflatten_to_world`), the checkpoint layer restores at any world
(`CheckpointManager.restore_latest_valid(world=W')`), and a new world
is just a new mesh — the ring/hierarchical transports and the
`make_sum_gradients_fn` caches are per-mesh closures, so rebuilding the
step at W' rebuilds them all.

Three pieces, same ladder shape as Transport/PrecisionSupervisor:

* :class:`HeartbeatMonitor` — the per-host step-time detector.  Every
  host's step time feeds an EMA; a beat slower than ``factor`` x its
  own EMA is *slow* (and deliberately NOT folded into the EMA — a
  detector must not learn the anomaly as the new normal); ``patience``
  consecutive slow beats make the host *hot*.  A missing beat feeds a
  miss streak; ``kill_patience`` consecutive misses make it *dead*.
  The monitor never reads a clock — the caller passes measured
  durations in (`cpd_tpu.obs.timing.now()` pairs in real runs, the
  plan-derived synthetic table in drills), which is what keeps every
  detection decision a pure function of its inputs (the v4 host-clock
  rule) and the drills step-clock-deterministic.

* :class:`ElasticSupervisor` — the recovery ladder coordinator:

      in-step collective retry ──(retries exhausted)──> drain + shrink
      W -> W'  ──(host healthy again for `probation` beats)──> regrow

  ``on_heartbeats(step, dts)`` classifies every host and decides
  ``("shrink", hosts)`` / ``("regrow", hosts)`` / None;
  ``on_link_failure(step, host)`` is the per-attempt retry/escalate
  decision for a flaky reduce wire into one host.  Pure host state: no
  RNG, no wall clock, fixed-size per-host tables, capped transition
  log — the same host-contract discipline the v4 analysis rules pin on
  the other supervisors.

* :func:`run_elastic` — the guarded loop that can CHANGE WORLD SIZE.
  The caller provides world-parametrized builders (``build_world``)
  and a world-aware batch function; on a shrink the loop drains the
  dead host, rebuilds the step at the new world, and resumes from the
  last digest-sealed checkpoint restored at W' (the ZeRO momentum
  re-flattened through `pad_to_world`); on a regrow it seals a fresh
  checkpoint and rebuilds back up.  Zero steps are lost beyond the
  checkpoint cadence, and the post-shrink trajectory is BITWISE equal
  to a fresh run started from the same checkpoint at W' — the same
  gating contract as every other transport (tools/bench_elastic.py
  asserts it x2 in the elastic-smoke CI gate).

Shrink policy: the compute world is the largest power of two <= the
number of alive hosts (``pow2=True``, the default) — power-of-two
worlds keep every transport layout and batch divisibility assumption
intact, so killing 1 host of 8 shrinks to W'=4 with 3 healthy hosts
idling as warm spares.  ``pow2=False`` uses every alive host (the
checkpoint layer handles non-divisible re-flattens like 8 -> 3
bitwise; tests pin that edge directly).

Fault kinds (grammar in resilience/inject.py): ``host_kill@s:h[:r]``,
``straggler@s:h:f``, ``link_flaky@s:h:p``.  The elastic harness
consumes them directly from the plan (like the ring consumes wire
kinds and the fleet consumes fleet kinds) and owns their one-shot +
unfired accounting; `report_unfired(host_armed=...)` covers the
unarmed direction.
"""

from __future__ import annotations

import dataclasses
import sys
from typing import Callable, Optional

from .inject import ELASTIC_KINDS, InjectedPreemption

__all__ = ["HeartbeatMonitor", "ElasticSupervisor", "ElasticReport",
           "run_elastic", "shrink_world", "heartbeat_table",
           "STRAGGLER_DEFAULT_FACTOR"]

STRAGGLER_DEFAULT_FACTOR = 4.0     # straggler arg2 -1 -> x4 step time


def shrink_world(alive: int, pow2: bool = True) -> int:
    """The compute world for ``alive`` healthy hosts: largest power of
    two <= alive (default), or alive itself (``pow2=False``)."""
    if alive < 1:
        return 0
    if not pow2:
        return alive
    w = 1
    while w * 2 <= alive:
        w *= 2
    return w


class HeartbeatMonitor:
    """Per-host step-time EMA + miss-streak detector (module docstring).

    All per-host state lives in fixed-size lists allocated up front and
    indexed by host — nothing grows on the step clock (host-unbounded),
    no thread ever touches it but the caller's (host-race), and no
    clock is read here (host-clock): durations are passed IN, measured
    by the caller through `cpd_tpu.obs.timing.now()` or synthesized
    from the fault plan in drills.
    """

    def __init__(self, world: int, *, patience: int = 3,
                 factor: float = 2.0, smoothing: float = 0.25,
                 warmup: int = 2, kill_patience: int = 1):
        if world < 1:
            raise ValueError(f"world must be >= 1, got {world}")
        if patience < 1 or kill_patience < 1:
            raise ValueError("patience/kill_patience must be >= 1")
        if factor <= 1.0:
            raise ValueError(f"factor must be > 1, got {factor}")
        if not 0.0 < smoothing <= 1.0:
            raise ValueError(f"smoothing must be in (0, 1], got "
                             f"{smoothing}")
        self.world = world
        self.patience = int(patience)
        self.factor = float(factor)
        self.smoothing = float(smoothing)
        self.warmup = max(int(warmup), 1)
        self.kill_patience = int(kill_patience)
        # fixed-size per-host tables, indexed by host id < world
        self.ema = [0.0] * world
        self.beats = [0] * world      # healthy beats folded into the EMA
        self.slow = [0] * world       # consecutive slow beats
        self.miss = [0] * world       # consecutive missing beats

    def beat(self, host: int, dt: float) -> str:
        """Feed one host's measured step time; returns "ok" | "slow" |
        "hot" (slow streak reached ``patience``)."""
        self.miss[host] = 0
        if self.beats[host] >= self.warmup and \
                dt > self.factor * self.ema[host]:
            # slow: count it, but do NOT fold it into the EMA — the
            # detector must keep the healthy baseline, or a sustained
            # straggler drags its own threshold up and escapes
            self.slow[host] += 1
            return "hot" if self.slow[host] >= self.patience else "slow"
        self.slow[host] = 0
        self.ema[host] = (dt if self.beats[host] == 0 else
                          (1.0 - self.smoothing) * self.ema[host]
                          + self.smoothing * dt)
        self.beats[host] += 1
        return "ok"

    def absent(self, host: int) -> bool:
        """Feed one missing heartbeat; True when the miss streak says
        the host is dead (``kill_patience`` consecutive misses)."""
        self.miss[host] += 1
        return self.miss[host] >= self.kill_patience

    def reset(self, host: int) -> None:
        """Forget one host's history (it was drained, or it rejoined —
        either way its old baseline is meaningless now)."""
        self.ema[host] = 0.0
        self.beats[host] = 0
        self.slow[host] = 0
        self.miss[host] = 0

    def state_dict(self) -> dict:
        return {"ema": list(self.ema), "beats": list(self.beats),
                "slow": list(self.slow), "miss": list(self.miss)}

    def load_state_dict(self, state: dict) -> "HeartbeatMonitor":
        for key in ("ema", "beats", "slow", "miss"):
            vals = state[key]
            if len(vals) != self.world:
                raise ValueError(
                    f"heartbeat state for {len(vals)} hosts cannot load "
                    f"into a world-{self.world} monitor")
            getattr(self, key)[:] = vals
        return self


class ElasticSupervisor:
    """The shrink/regrow coordinator (module docstring).

    ``on_heartbeats(step, dts)`` -> ("shrink", hosts) | ("regrow",
    hosts) | None; ``on_link_failure(step, host)`` -> "retry" |
    "shrink"; ``on_step_ok(step)`` closes a healthy step (resets the
    link-retry streak).  ``world`` is the compute world the loop should
    run the next step at; ``active_hosts()`` names the hosts carrying
    shards; ``transitions`` is the deterministic (step, from_world,
    to_world) log the drills assert on.
    """

    # transition-log cap: keep the newest entries, drop the oldest
    TRANSITION_CAP = 4096

    def __init__(self, world: int, *, patience: int = 3,
                 factor: float = 2.0, smoothing: float = 0.25,
                 warmup: int = 2, kill_patience: int = 1,
                 max_retries: int = 1, probation: int = 8,
                 pow2: bool = True):
        if max_retries < 0:
            raise ValueError(f"max_retries must be >= 0, got "
                             f"{max_retries}")
        if probation < 1:
            raise ValueError(f"probation must be >= 1, got {probation}")
        self.home_world = int(world)
        self.monitor = HeartbeatMonitor(world, patience=patience,
                                        factor=factor,
                                        smoothing=smoothing,
                                        warmup=warmup,
                                        kill_patience=kill_patience)
        self.max_retries = int(max_retries)
        self.probation = int(probation)
        self.pow2 = bool(pow2)
        # fixed-size per-host state, indexed by host id < home_world
        self.alive = [True] * self.home_world
        self.rejoin = [0] * self.home_world   # healthy-beat streak of
        #                                       drained hosts (probation)
        self.link_retries = 0       # consecutive failed attempts, this step
        # fixed counter vocabulary — the drills' exact-count assertions
        self.counters = {
            "drains": 0, "rejoins": 0, "shrinks": 0, "regrows": 0,
            "hot_steps": 0, "heartbeat_misses": 0,
            "link_retries": 0, "link_escalations": 0,
        }
        # (step, from_world, to_world); newest TRANSITION_CAP entries
        self.transitions: list = []

    # -- introspection ----------------------------------------------------

    @property
    def world(self) -> int:
        """The compute world for the CURRENT alive set."""
        return shrink_world(sum(self.alive), self.pow2)

    @property
    def degraded(self) -> bool:
        return self.world < self.home_world

    def active_hosts(self) -> tuple:
        """The hosts carrying real shards: the first ``world`` alive
        ones, in host order (drained hosts and warm spares idle)."""
        out = []
        w = self.world
        for h in range(self.home_world):
            if self.alive[h]:
                out.append(h)
                if len(out) == w:
                    break
        return tuple(out)

    # -- the state machine ------------------------------------------------

    def _drain(self, host: int) -> None:
        self.alive[host] = False
        self.rejoin[host] = 0
        self.monitor.reset(host)
        self.counters["drains"] += 1

    def on_heartbeats(self, step: int, dts) -> Optional[tuple]:
        """Feed one step's per-host heartbeat row (``dts[h]`` = host
        h's measured step seconds, None = no heartbeat arrived).  At
        most one decision per call; a shrink takes priority over a
        regrow (rejoin streaks keep and commit on a later step)."""
        if len(dts) != self.home_world:
            raise ValueError(f"heartbeat row has {len(dts)} hosts; the "
                             f"supervisor watches {self.home_world}")
        old_active = self.active_hosts()
        drained, rejoined = [], []
        for h in range(self.home_world):
            dt = dts[h]
            if self.alive[h]:
                if dt is None:
                    self.counters["heartbeat_misses"] += 1
                    if self.monitor.absent(h):
                        self._drain(h)
                        drained.append(h)
                else:
                    verdict = self.monitor.beat(h, dt)
                    if verdict in ("slow", "hot"):
                        self.counters["hot_steps"] += 1
                    if verdict == "hot":
                        self._drain(h)
                        drained.append(h)
            else:
                # a drained host earns its shards back with `probation`
                # consecutive healthy beats; a miss or a slow beat
                # resets the streak (monitor history was reset at the
                # drain, so "slow" here is vs the post-drain baseline)
                if dt is None or self.monitor.beat(h, dt) != "ok":
                    self.rejoin[h] = 0
                else:
                    self.rejoin[h] += 1
                    if self.rejoin[h] >= self.probation:
                        rejoined.append(h)
        if drained:
            self._record(step, old_active)
            self.counters["shrinks"] += 1
            return ("shrink", tuple(drained))
        if rejoined:
            for h in rejoined:
                self.alive[h] = True
                self.rejoin[h] = 0
                self.monitor.reset(h)
                self.counters["rejoins"] += 1
            self._record(step, old_active)
            self.counters["regrows"] += 1
            return ("regrow", tuple(rejoined))
        return None

    def on_link_failure(self, step: int, host: int) -> str:
        """A collective attempt into ``host`` failed (a verify/retry
        escalation from the PR 4 path): "retry" while the in-step
        budget lasts, then drain the host and "shrink"."""
        if self.link_retries < self.max_retries:
            self.link_retries += 1
            self.counters["link_retries"] += 1
            return "retry"
        self.link_retries = 0
        old_active = self.active_hosts()
        if self.alive[host]:
            self._drain(host)
        self.counters["link_escalations"] += 1
        self._record(step, old_active)
        self.counters["shrinks"] += 1
        return "shrink"

    def on_step_ok(self, step: int) -> None:
        """A step completed cleanly: the link-retry streak resets (the
        retry budget is per-step, like the transport ladder's)."""
        self.link_retries = 0

    def _record(self, step: int, old_active: tuple) -> None:
        self.transitions.append(
            (step, len(old_active), self.world))
        if len(self.transitions) > self.TRANSITION_CAP:
            del self.transitions[0]

    # -- checkpoint persistence -------------------------------------------

    def state_dict(self) -> dict:
        """JSON-able snapshot for the checkpoint metadata sidecar.  A
        PROCESS RESTART loads it to resume with the same alive set and
        detector history; the in-run shrink path deliberately keeps the
        live supervisor instead (loading the pre-shrink sidecar would
        resurrect the host that just died)."""
        return {
            "home_world": self.home_world,
            "alive": [bool(a) for a in self.alive],
            "rejoin": list(self.rejoin),
            "counters": dict(self.counters),
            "monitor": self.monitor.state_dict(),
            "transitions": [list(t) for t in self.transitions],
        }

    def load_state_dict(self, state: dict) -> "ElasticSupervisor":
        if int(state["home_world"]) != self.home_world:
            raise ValueError(
                f"checkpointed elastic state is for home world "
                f"{state['home_world']}, not {self.home_world}; restart "
                f"with the same fleet shape or a fresh run directory")
        self.alive[:] = [bool(a) for a in state["alive"]]
        self.rejoin[:] = [int(r) for r in state["rejoin"]]
        # rebuild over the FIXED counter vocabulary — unknown saved
        # keys are dropped, missing ones keep their current value
        saved = state.get("counters", {})
        self.counters = {key: int(saved.get(key, val))
                         for key, val in self.counters.items()}
        self.monitor.load_state_dict(state["monitor"])
        self.transitions = [tuple(t) for t in
                            state.get("transitions", [])]
        return self


# ---------------------------------------------------------------------------
# plan-derived synthetic signals (the drills' deterministic clock)
# ---------------------------------------------------------------------------

def heartbeat_table(plan, world: int, n_steps: int,
                    base_dt: float = 1.0) -> list:
    """The drills' synthetic heartbeat rows: ``table[step][host]`` is
    host's step time at ``step`` (None = no heartbeat).  A pure
    function of the plan — no wall clock anywhere — which is what makes
    an elastic drill replay event-for-event:

    * every host beats at ``base_dt``;
    * ``straggler@s:h:f`` inflates host h's beat at step s by f
      (arg2 < 0 -> `STRAGGLER_DEFAULT_FACTOR`);
    * ``host_kill@s:h[:r]`` blanks host h's beats from step s on,
      returning after r steps when r (arg2) >= 0.

    Real runs skip this entirely and feed measured
    `cpd_tpu.obs.timing.now()` durations to `run_elastic` instead.
    """
    table = [[base_dt] * world for _ in range(n_steps)]
    for f in plan.elastic_faults():
        host = int(f.arg) if f.arg >= 0 else 0
        if host >= world:
            continue      # aimed past the fleet: held, surfaced unfired
        if f.kind == "straggler":
            if f.step < n_steps:
                factor = (f.arg2 if f.arg2 > 0
                          else STRAGGLER_DEFAULT_FACTOR)
                table[f.step][host] = base_dt * factor
        elif f.kind == "host_kill":
            until = (f.step + int(f.arg2) if f.arg2 >= 0 else n_steps)
            for s in range(f.step, min(until, n_steps)):
                table[s][host] = None
    return table


def _link_plan(plan) -> dict:
    """step -> (host, attempts) for the link_flaky specs (last wins)."""
    out = {}
    for f in plan.elastic_faults():
        if f.kind == "link_flaky":
            out[f.step] = (int(f.arg) if f.arg >= 0 else 0,
                           int(f.arg2) if f.arg2 >= 0 else 1)
    return out


# ---------------------------------------------------------------------------
# the elastic guarded loop
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ElasticReport:
    completed: bool
    final_step: int
    aborted: Optional[str]   # None | watchdog | preempted | elastic
    world: int               # the compute world the run ended at
    home_world: int
    counters: dict           # ResilienceMeter.as_dict()
    events: list             # deterministic (what, step, ...) log


def run_elastic(build_world: Callable, state, next_batch: Callable,
                n_steps: int, *, supervisor: ElasticSupervisor,
                manager, plan=None, injector=None, watchdog=None,
                meter=None, precision=None, ckpt_every: int = 2,
                rank: int = 0, heartbeats: Optional[Callable] = None,
                base_dt: float = 1.0,
                on_step: Optional[Callable] = None,
                max_recoveries: int = 8):
    """Drive a world-parametrized step to ``n_steps`` under the elastic
    recovery ladder (module docstring).

    build_world: ``(world, hosts) -> dict`` with keys ``"step"`` (the
        jitted ``(state, *batch) -> (state, metrics)`` for that world,
        built ``donate=False``), ``"template"`` (the restore template —
        for ZeRO states, built with the NEW world's updater so the
        momentum re-flatten has its target length), and optionally
        ``"relayout"`` (``state -> state`` onto the new mesh — elastic
        restores materialize unsharded).  ``hosts`` is the active host
        tuple; called once per distinct membership (cached here).
    next_batch: ``(step, world) -> tuple`` — a PURE function of both,
        so the post-shrink replay and a fresh run at W' see identical
        data (the bitwise contract's data half).
    heartbeats: ``step -> row`` of per-host step times (None = missing).
        Defaults to the plan-derived `heartbeat_table` (the drills);
        real runs pass measured `obs.timing` durations.
    ckpt_every: the save cadence (> 0 — elastic recovery IS a restore
        from the last sealed checkpoint, so a cadence of 0 would turn
        the first fault into an abort).
    max_recoveries: hard cap on shrink/regrow rebuilds — a plan that
        faults faster than checkpoints seal would otherwise livelock.

    Returns ``(state, ElasticReport)``.  The supervisor's state rides
    every checkpoint's metadata sidecar (key ``"elastic"``) next to the
    precision ladder's, so a PROCESS restart can resume the fleet view;
    the in-run shrink keeps the live supervisor (see
    `ElasticSupervisor.state_dict`).
    """
    from ..train.metrics import ResilienceMeter
    from .inject import report_unfired
    if ckpt_every < 1:
        raise ValueError("run_elastic needs ckpt_every >= 1: elastic "
                         "recovery resumes from the last sealed "
                         "checkpoint")
    if manager is None:
        raise ValueError("run_elastic needs a CheckpointManager — the "
                         "shrink path restores through it")
    meter = meter if meter is not None else ResilienceMeter()
    events: list = []
    it = int(state.step)

    # plan-driven signals (all pure functions of the plan)
    the_plan = plan if plan is not None else getattr(injector, "plan",
                                                     None)
    if the_plan is not None:
        pending = {}      # step -> [elastic specs]; popped on first visit
        for f in the_plan.elastic_faults():
            pending.setdefault(f.step, []).append(f)
        links = _link_plan(the_plan)
        if heartbeats is None:
            table = heartbeat_table(the_plan, supervisor.home_world,
                                    n_steps, base_dt)
            heartbeats = lambda s: table[s]          # noqa: E731
    else:
        pending, links = {}, {}
    if heartbeats is None:
        raise ValueError("run_elastic needs heartbeats (measured "
                         "per-host step times) or a plan to derive the "
                         "drill table from")
    fired: dict = {"host_kill": 0, "straggler": 0, "link_flaky": 0}

    bundles: dict = {}       # active-host tuple -> build_world output

    def bundle():
        hosts = supervisor.active_hosts()
        if hosts not in bundles:
            if len(bundles) >= 8:
                # a flapping fleet must not accumulate compiled steps
                # forever; evict the oldest membership (re-entering it
                # re-traces, which is the cheap direction of the trade)
                del bundles[next(iter(bundles))]
            bundles[hosts] = build_world(len(hosts), hosts)
        return bundles[hosts]

    def save(step, tag):
        meta = {"elastic": supervisor.state_dict()}
        if precision is not None:
            meta["precision"] = precision.state_dict()
        manager.save(step, state, force=True, metadata=meta)
        manager.wait()
        events.append((tag, step))
        if injector is not None and injector.corrupt_checkpoint(
                step, manager.directory):
            events.append(("ckpt_corrupted", step))

    recoveries = 0

    def recover(step, tag):
        """Rebuild at the supervisor's CURRENT world and resume from
        the newest sealed checkpoint restored at it.  Returns the new
        (state, it) or None when recovery is impossible."""
        nonlocal recoveries
        recoveries += 1
        if recoveries > max_recoveries:
            return None
        b = bundle()
        w = supervisor.world
        res = manager.restore_latest_valid(b["template"], rank=rank,
                                           world=w)
        if res is None:
            return None
        for bad in res.skipped:
            meter.bump("ckpts_invalid")
            events.append(("ckpt_invalid", bad))
        if res.verified is None:
            meter.bump("ckpts_unverified")
            events.append(("ckpt_unverified", res.step))
        if precision is not None and (res.metadata or {}
                                      ).get("precision"):
            # the format ladder resumes where the checkpoint left it
            # (mid-escalation included) — the elastic block is NOT
            # loaded here: the live supervisor knows the host just
            # died; the sidecar's view predates the death
            precision.load_state_dict(res.metadata["precision"])
            events.append(("precision_restored", res.step,
                           precision.name))
        new_state = res.state
        if b.get("relayout") is not None:
            new_state = b["relayout"](new_state)
        if getattr(manager, "store", None) is not None:
            # store-backed: the recovered run is a NEW writer — take a
            # fresh fencing epoch so the pre-death writer (possibly
            # still mid-publish somewhere) can never out-name or
            # clobber the post-recovery checkpoints
            manager.refence()
        meter.bump("restores")
        events.append((tag, step, supervisor.world,
                       supervisor.active_hosts()))
        return new_state, int(res.step)

    def finish(aborted):
        # unfired accounting, both directions: the harness owns the
        # elastic kinds (anything still pending never manifested); the
        # injector covers every other family (host_armed=True keeps it
        # from double-flagging ours)
        leftover = sorted(f for specs in pending.values() for f in specs)
        if leftover:
            meter.bump("faults_unfired", len(leftover))
            if rank == 0:
                print(f"=> elastic plan: {len(leftover)} spec(s) never "
                      f"fired (scheduled past the end of the run): "
                      f"{leftover}", file=sys.stderr)
        report_unfired(injector, n_steps=n_steps, meter=meter,
                       rank=rank, host_armed=True)
        return state, ElasticReport(
            completed=aborted is None and it >= n_steps,
            final_step=it, aborted=aborted, world=supervisor.world,
            home_world=supervisor.home_world,
            counters=meter.as_dict(), events=events)

    while it < n_steps:
        # --- elastic spec consumption (one-shot accounting; the
        # heartbeat table carries the actual effect, so a post-shrink
        # replay of this step sees a CONSISTENT fleet view without
        # double-counting the fault) -----------------------------------
        due = pending.pop(it, ())
        for f in due:
            fired[f.kind] += 1
            events.append((f.kind, it, int(f.arg) if f.arg >= 0 else 0))

        # --- detection: one heartbeat row per step --------------------
        decision = supervisor.on_heartbeats(it, heartbeats(it))
        if decision is not None:
            what, hosts = decision
            if what == "shrink":
                for _ in hosts:
                    meter.bump("elastic_drains")
                meter.bump("elastic_shrinks")
                events.append(("elastic_shrink", it, hosts,
                               supervisor.world))
                got = recover(it, "elastic_resume")
                if got is None:
                    return finish("elastic")
                state, it = got
                continue
            # regrow: the current state is live and healthy — seal it,
            # then rebuild UP and restore the very checkpoint we just
            # wrote (the re-flatten in the growing direction); zero
            # steps lost by construction
            meter.bump("elastic_regrows")
            events.append(("elastic_regrow", it, hosts,
                           supervisor.world))
            save(it, "ckpt_pre_regrow")
            got = recover(it, "elastic_resume")
            if got is None:
                return finish("elastic")
            state, it = got
            continue

        # --- link-flaky: the in-step collective retry ladder ----------
        lf = None
        for f in due:
            if f.kind == "link_flaky":
                lf = (int(f.arg) if f.arg >= 0 else 0,
                      int(f.arg2) if f.arg2 >= 0 else 1)
        if lf is not None:
            host, attempts = lf
            escalated = False
            for _ in range(attempts):
                act = supervisor.on_link_failure(it, host)
                if act == "shrink":
                    escalated = True
                    break
                meter.bump("elastic_link_retries")
                events.append(("link_retry", it, host))
            if escalated:
                meter.bump("elastic_link_escalations")
                meter.bump("elastic_drains")
                meter.bump("elastic_shrinks")
                events.append(("elastic_shrink", it, (host,),
                               supervisor.world))
                got = recover(it, "elastic_resume")
                if got is None:
                    return finish("elastic")
                state, it = got
                continue

        try:
            if injector is not None:
                injector.maybe_preempt(it)
            batch = next_batch(it, supervisor.world)
            if watchdog is not None:
                # arm() also clears any stale trip from a PREVIOUS
                # step — a recovery above must not read as a hang here
                watchdog.arm(it, world=supervisor.world,
                             counters=meter.as_dict())
            if injector is not None:
                injector.maybe_stall(it)
            new_state, metrics = bundle()["step"](state, *batch)
            loss = float(metrics["loss"])          # device sync
            if watchdog is not None:
                watchdog.disarm()
                if watchdog.tripped:
                    raise KeyboardInterrupt
        except KeyboardInterrupt:
            if watchdog is not None and watchdog.tripped:
                watchdog.disarm()
                meter.bump("watchdog_trips")
                events.append(("watchdog", it))
                save(it, "ckpt_on_watchdog")
                return finish("watchdog")
            raise
        except InjectedPreemption:
            meter.bump("preemptions")
            events.append(("preempted", it))
            save(it, "ckpt_on_preempt")
            return finish("preempted")

        supervisor.on_step_ok(it)
        meter.observe_metrics(metrics)
        # mirror the supervisor's own tallies into the run meter (the
        # supervisor holds per-decision truth; the meter is the report)
        meter.counts["elastic_hot_steps"] = \
            supervisor.counters["hot_steps"]
        meter.counts["elastic_heartbeat_misses"] = \
            supervisor.counters["heartbeat_misses"]
        if injector is not None:
            loss = injector.fault_loss(it, loss)
        if on_step is not None:
            on_step(it, {**metrics, "loss": loss})
        state = new_state
        it += 1
        if it % ckpt_every == 0 and it < n_steps:
            save(it, "ckpt")

    save(it, "ckpt_final")
    return finish(None)
