"""run_guarded — the defenses composed around any jitted train step.

The generic guarded loop used by the chaos tests and available to
harness code (notebooks, sweeps).  The example trainers wire the same
defenses natively around their own validation/checkpoint cadence
(examples/lm/train.py carries the full stack including rollback) — keep
the recovery semantics here and there in lockstep.  One iteration:

    preempt? -> batch (drop/dup/poison) -> [watchdog armed: stall? ->
    step -> metric device-sync] -> counters -> loss fault -> sentinel
    -> (rollback | advance) -> periodic integrity-checked save
    -> post-save checkpoint corruption

Recovery policies, in the order they can fire:

* **watchdog trip** — the timer thread dumped diagnostics and
  interrupted the main thread; the loop checkpoints the last GOOD state
  and exits cleanly (``aborted='watchdog'``).
* **injected preemption** — same checkpoint-and-exit contract as the
  SIGTERM PreemptionGuard path (``aborted='preempted'``).
* **divergence** — the sentinel tripped: restore the newest *valid*
  checkpoint (integrity digests consulted; corrupt steps are skipped
  and counted), re-seed the data order so the replay does not march
  into the identical batch sequence, back off, and retry — at most
  ``max_rollbacks`` times, then ``aborted='diverged'``.

Anomalous gradient steps (non-finite / spike / replica disagreement)
never reach this file: the GradGuard optax wrapper already skipped them
inside the step; the loop just mirrors its counters into the meter.

Every decision is a pure function of (plan, seeds, step outputs), so a
run under a FaultPlan is reproducible event-for-event — asserted in
tests/test_resilience.py.
"""

from __future__ import annotations

import dataclasses
import sys
import time
from typing import Callable, Optional

from .inject import InjectedPreemption

__all__ = ["run_guarded", "GuardedReport"]


@dataclasses.dataclass
class GuardedReport:
    completed: bool
    final_step: int
    aborted: Optional[str]          # None | watchdog | preempted | diverged
    counters: dict                  # ResilienceMeter.as_dict()
    events: list                    # deterministic (what, step, ...) log


def run_guarded(step_fn: Callable, state, next_batch: Callable,
                n_steps: int, *, manager=None, injector=None,
                sentinel=None, watchdog=None, meter=None,
                ckpt_every: int = 0, max_rollbacks: int = 2,
                backoff_secs: float = 0.0, rank: int = 0,
                on_step: Optional[Callable] = None):
    """Drive ``step_fn`` to ``n_steps`` under the defense stack.

    step_fn: jitted ``(state, *batch) -> (state, metrics)`` with a
        ``loss`` metric.  Build it with ``donate=False`` — a rollback
        needs the pre-step state alive, and the restore template must
        outlive the step call.
    next_batch: ``(step, reseed) -> tuple`` — ``reseed`` increments on
        every rollback so the replayed data order differs (same step on
        retry k yields a different batch, the re-seeded recovery the
        sentinel docstring promises).
    manager: CheckpointManager (integrity on) — required for
        ``ckpt_every`` and for rollback; without it a divergence aborts.
    on_step: optional ``(step, metrics) -> None`` observer (logging).

    Returns ``(state, GuardedReport)``; the report's ``events`` list is
    the determinism witness.
    """
    from ..train.metrics import ResilienceMeter
    meter = meter if meter is not None else ResilienceMeter()
    events: list = []
    rollbacks = 0
    reseed = 0
    prev_batch = None
    it = int(state.step)

    def save(step, tag):
        if manager is None:
            return
        manager.save(step, state, force=True)
        manager.wait()
        events.append((tag, step))
        if injector is not None and injector.corrupt_checkpoint(
                step, manager.directory):
            events.append(("ckpt_corrupted", step))

    def finish(aborted):
        if injector is not None and rank == 0:
            leftover = injector.unfired()
            if leftover:
                # a chaos run that silently skipped a fault proves
                # nothing — make the gap visible (expected when the run
                # aborted early, suspicious otherwise)
                print(f"=> fault plan: {len(leftover)} spec(s) never "
                      f"fired: {leftover}", file=sys.stderr)
        return state, GuardedReport(
            completed=aborted is None and it >= n_steps,
            final_step=it, aborted=aborted, counters=meter.as_dict(),
            events=events)

    while it < n_steps:
        try:
            if injector is not None:
                injector.maybe_preempt(it)

            # --- data motion, with drop/dup faults -------------------
            action = (injector.batch_action(it)
                      if injector is not None else None)
            if action == "dup" and prev_batch is not None:
                batch = prev_batch
                meter.bump("batches_duplicated")
                events.append(("dup", it))
            elif action == "drop":
                # this batch never arrives; train on the next one
                meter.bump("batches_dropped")
                events.append(("drop", it))
                batch = next_batch(it + n_steps, reseed)
            else:
                batch = next_batch(it, reseed)
            if injector is not None:
                batch = injector.corrupt_batch(it, batch)
            prev_batch = batch

            # --- the blocking region, under the watchdog --------------
            if watchdog is not None:
                watchdog.arm(it, counters=meter.as_dict())
            if injector is not None:
                injector.maybe_stall(it)
            new_state, metrics = step_fn(state, *batch)
            loss = float(metrics["loss"])      # device sync
            if watchdog is not None:
                watchdog.disarm()
                if watchdog.tripped:
                    # the interrupt landed between bytecodes that
                    # swallowed it (e.g. inside a sleeping stall that
                    # resumed); honor the trip at the boundary
                    raise KeyboardInterrupt

        except KeyboardInterrupt:
            if watchdog is not None and watchdog.tripped:
                watchdog.disarm()     # acknowledges: cancels hard-exit
                meter.bump("watchdog_trips")
                events.append(("watchdog", it))
                save(it, "ckpt_on_watchdog")
                return finish("watchdog")
            raise
        except InjectedPreemption:
            meter.bump("preemptions")
            events.append(("preempted", it))
            save(it, "ckpt_on_preempt")
            return finish("preempted")

        meter.observe_metrics(metrics)
        if injector is not None:
            loss = injector.fault_loss(it, loss)
        if on_step is not None:
            on_step(it, {**metrics, "loss": loss})

        # A guard-skipped step's loss metric is naturally poisoned (the
        # forward pass saw the bad batch); the anomaly was already
        # handled in-step, so it must not ALSO count as divergence.
        guard_ok = float(metrics.get("guard_ok", 1.0)) != 0.0

        # --- divergence -> integrity-checked rollback -----------------
        if sentinel is not None and guard_ok and sentinel.update(loss):
            events.append(("diverged", it, round(loss, 6)))
            if manager is None or rollbacks >= max_rollbacks:
                return finish("diverged")
            res = manager.restore_latest_valid(new_state, rank=rank)
            if res is None:
                return finish("diverged")
            for bad in res.skipped:
                meter.bump("ckpts_invalid")
                events.append(("ckpt_invalid", bad))
            state = res.state
            it = int(res.step)
            rollbacks += 1
            reseed = rollbacks
            meter.bump("rollbacks")
            meter.bump("restores")
            sentinel.reset()
            events.append(("rollback", it))
            if backoff_secs > 0:
                time.sleep(backoff_secs * (2 ** (rollbacks - 1)))
            continue

        state = new_state
        it += 1
        if ckpt_every and it % ckpt_every == 0 and it < n_steps:
            save(it, "ckpt")

    if manager is not None and ckpt_every:
        save(it, "ckpt_final")
    return finish(None)
