"""run_guarded — the defenses composed around any jitted train step.

The generic guarded loop used by the chaos tests and available to
harness code (notebooks, sweeps).  The example trainers wire the same
defenses natively around their own validation/checkpoint cadence
(examples/lm/train.py carries the full stack including rollback) — keep
the recovery semantics here and there in lockstep.  One iteration:

    preempt? -> batch (drop/dup/poison) -> [watchdog armed: stall? ->
    step -> metric device-sync] -> verified-reduce supervision
    (retry/downgrade/re-sync) -> counters -> loss fault -> sentinel
    -> (rollback | advance) -> periodic parameter consensus
    -> periodic integrity-checked save -> post-save ckpt corruption

Recovery policies, in the order they can fire:

* **watchdog trip** — the timer thread dumped diagnostics and
  interrupted the main thread; the loop checkpoints the last GOOD state
  and exits cleanly (``aborted='watchdog'``).
* **injected preemption** — same checkpoint-and-exit contract as the
  SIGTERM PreemptionGuard path (``aborted='preempted'``).
* **wire fault** — the step's verified reduce reported ``reduce_ok ==
  0`` (hop checksum / gather-row / replica-agreement failure,
  parallel/integrity.py): the corrupted update is DISCARDED (the
  pre-step state is still good — build steps with ``donate=False``)
  and the `TransportSupervisor` decides: bounded retry on the same
  batch, or a transport downgrade (ring -> faithful -> fp32) with a
  rank-0 replica re-sync before the retry, or — failing at the bottom
  rung — ``aborted='transport'``.  Probation upgrades ride the same
  hook on clean steps.
* **divergence** — the sentinel tripped: restore the newest *valid*
  checkpoint (integrity digests consulted; corrupt steps are skipped
  and counted; a restore with NO recorded digest is counted as
  ``ckpts_unverified``), re-seed the data order so the replay does not
  march into the identical batch sequence, back off, and retry — at
  most ``max_rollbacks`` times, then ``aborted='diverged'``.
* **replica drift** — every ``consensus_every`` accepted steps the
  cheap parameter-consensus digest runs; a mismatch re-syncs the state
  from rank 0 (bitwise) and counts ``resyncs``.
* **numeric-health escalation** — the step's ``prec_wire_*`` telemetry
  (quantization saturation/underflow/NaN at the reduce wire,
  `sum_gradients(stats=True)`) feeds the `PrecisionSupervisor`
  (resilience/precision.py): a sustained hot sat+NaN rate escalates the
  eXmY format one rung up the configured ladder (the next iteration
  fetches the re-traced step from ``step_for_level``), quiet steps
  probation back down — never below home.  Escalation is
  forward-looking: the tripping step's update is KEPT (if its values
  went non-finite the grad guard already skipped it) — the ladder
  changes what the NEXT steps pay.  The supervisor's state rides every
  checkpoint's metadata sidecar and is restored on rollback, so a
  replay resumes at the escalated format.

Anomalous gradient steps (non-finite / spike / replica disagreement)
never reach this file: the GradGuard optax wrapper already skipped them
inside the step; the loop just mirrors its counters into the meter.

Every decision is a pure function of (plan, seeds, step outputs), so a
run under a FaultPlan is reproducible event-for-event — asserted in
tests/test_resilience.py.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Optional

from .inject import InjectedPreemption

__all__ = ["run_guarded", "GuardedReport"]


@dataclasses.dataclass
class GuardedReport:
    completed: bool
    final_step: int
    aborted: Optional[str]   # None | watchdog | preempted | diverged
                             # | transport
    counters: dict                  # ResilienceMeter.as_dict()
    events: list                    # deterministic (what, step, ...) log


def run_guarded(step_fn: Callable, state, next_batch: Callable,
                n_steps: int, *, manager=None, injector=None,
                sentinel=None, watchdog=None, meter=None,
                ckpt_every: int = 0, max_rollbacks: int = 2,
                backoff_secs: float = 0.0, rank: int = 0,
                on_step: Optional[Callable] = None,
                supervisor=None, step_for_level=None,
                resync_fn: Optional[Callable] = None,
                consensus_fn: Optional[Callable] = None,
                consensus_every: int = 0,
                precision=None, tracer=None, flight=None):
    """Drive ``step_fn`` to ``n_steps`` under the defense stack.

    step_fn: jitted ``(state, *batch) -> (state, metrics)`` with a
        ``loss`` metric.  Build it with ``donate=False`` — a rollback
        needs the pre-step state alive, and the restore template must
        outlive the step call.
    next_batch: ``(step, reseed) -> tuple`` — ``reseed`` increments on
        every rollback so the replayed data order differs (same step on
        retry k yields a different batch, the re-seeded recovery the
        sentinel docstring promises).
    manager: CheckpointManager (integrity on) — required for
        ``ckpt_every`` and for rollback; without it a divergence aborts.
    on_step: optional ``(step, metrics) -> None`` observer (logging).
    supervisor: resilience.transport.TransportSupervisor — enables the
        degraded-transport ladder; requires ``step_for_level``, a
        ``level -> step_fn`` mapping (transport.StepTable) whose steps
        were built with ``verify_reduce=True`` and ``donate=False``
        (the discard-and-retry needs the pre-step buffers alive).
    resync_fn: jitted ``state -> state`` rank-0 broadcast
        (parallel.integrity.make_consensus_fns) — run after every
        transport downgrade and on consensus mismatch, so replicas are
        bitwise identical before the retry.
    consensus_fn / consensus_every: the periodic parameter-consensus
        digest check (``state -> int32 agree``) and its cadence in
        accepted steps (0 = off; requires resync_fn).
    tracer: optional obs.Tracer — per-iteration data/step spans on the
        step clock (pure host-side observation; step outputs are
        bitwise identical with or without it, pinned in tests).
    flight: optional obs.FlightRecorder — one ring event per accepted
        step, dumped on every rollback and on any abort.
    precision: resilience.precision.PrecisionSupervisor — enables the
        eXmY format-escalation ladder; requires ``step_for_level``,
        whose keys follow `precision.ladder_step_key` (the (exp, man)
        tuple alone, or ``(transport_level, (exp, man))`` when composed
        with a TransportSupervisor).  Steps must be built with
        ``quant_stats=True`` so the prec_wire_* metrics exist (a
        telemetry-less step reads as permanently quiet).

    Returns ``(state, GuardedReport)``; the report's ``events`` list is
    the determinism witness.
    """
    from ..obs.trace import NULL_TRACER
    from ..train.metrics import ResilienceMeter
    from .precision import ladder_step_key
    meter = meter if meter is not None else ResilienceMeter()
    tr = tracer if tracer is not None else NULL_TRACER
    if supervisor is not None and step_for_level is None:
        raise ValueError("supervisor requires step_for_level (a level -> "
                         "step mapping, e.g. transport.StepTable)")
    if precision is not None and step_for_level is None:
        raise ValueError("precision requires step_for_level (a format -> "
                         "step mapping, e.g. transport.StepTable keyed "
                         "by precision.ladder_step_key)")
    if consensus_every and (consensus_fn is None or resync_fn is None):
        raise ValueError("consensus_every needs both consensus_fn and "
                         "resync_fn")
    events: list = []
    rollbacks = 0
    reseed = 0
    prev_batch = None
    retry_batch = None       # set when a verify failure replays a step
    it = int(state.step)

    def save(step, tag):
        if manager is None:
            return
        # supervisor state rides the metadata sidecar so a restore (the
        # rollback below, or a later restart) resumes the ladder where
        # it stood — e.g. mid-escalation — instead of re-diverging
        meta = ({"precision": precision.state_dict()}
                if precision is not None else None)
        manager.save(step, state, force=True, metadata=meta)
        manager.wait()
        events.append((tag, step))
        if injector is not None and injector.corrupt_checkpoint(
                step, manager.directory):
            events.append(("ckpt_corrupted", step))

    def finish(aborted):
        # a chaos run that silently skipped a fault proves nothing —
        # count + warn (expected when the run aborted early, a silent
        # user error otherwise); the jit-level specs past n_steps are
        # covered too (inject.report_unfired)
        from .inject import report_unfired
        report_unfired(injector, n_steps=n_steps, meter=meter, rank=rank)
        if flight is not None and aborted is not None:
            flight.record("abort", step=it, reason=aborted)
            flight.dump(aborted)
        return state, GuardedReport(
            completed=aborted is None and it >= n_steps,
            final_step=it, aborted=aborted, counters=meter.as_dict(),
            events=events)

    while it < n_steps:
        try:
            if retry_batch is not None:
                # a verify-failed step replays on the SAME batch; the
                # host injector hooks already fired for it (one-shot)
                batch = retry_batch
                retry_batch = None
            else:
                if injector is not None:
                    injector.maybe_preempt(it)

                # --- data motion, with drop/dup faults ---------------
                with tr.span("data", step=it):
                    action = (injector.batch_action(it)
                              if injector is not None else None)
                    if action == "dup" and prev_batch is not None:
                        batch = prev_batch
                        meter.bump("batches_duplicated")
                        events.append(("dup", it))
                    elif action == "drop":
                        # this batch never arrives; train on the next
                        meter.bump("batches_dropped")
                        events.append(("drop", it))
                        batch = next_batch(it + n_steps, reseed)
                    else:
                        batch = next_batch(it, reseed)
                    if injector is not None:
                        batch = injector.corrupt_batch(it, batch)
                    prev_batch = batch

            # --- the blocking region, under the watchdog --------------
            if watchdog is not None:
                watchdog.arm(it, counters=meter.as_dict())
            if injector is not None:
                injector.maybe_stall(it)
            lkey = ladder_step_key(supervisor, precision)
            fn = step_for_level[lkey] if lkey is not None else step_fn
            with tr.span("step", step=it):
                # forward+backward+optimizer (one jitted program) plus
                # the metric device-sync — the host cannot see inside
                # the compiled step; per-bucket reduce detail rides the
                # reduce_* metrics into the registry instead
                new_state, metrics = fn(state, *batch)
                loss = float(metrics["loss"])      # device sync
            if watchdog is not None:
                watchdog.disarm()
                if watchdog.tripped:
                    # the interrupt landed between bytecodes that
                    # swallowed it (e.g. inside a sleeping stall that
                    # resumed); honor the trip at the boundary
                    raise KeyboardInterrupt

        except KeyboardInterrupt:
            if watchdog is not None and watchdog.tripped:
                watchdog.disarm()     # acknowledges: cancels hard-exit
                meter.bump("watchdog_trips")
                events.append(("watchdog", it))
                save(it, "ckpt_on_watchdog")
                return finish("watchdog")
            raise
        except InjectedPreemption:
            meter.bump("preemptions")
            events.append(("preempted", it))
            save(it, "ckpt_on_preempt")
            return finish("preempted")

        # --- verified-reduce supervision (ISSUE 4) --------------------
        # reduce_ok is the step's replicated integrity verdict (hop
        # checksums + gather rows + replica agreement).  On failure the
        # update in new_state came from a corrupted reduce: DISCARD it
        # (state is the untouched pre-step pytree) and let the
        # supervisor pick retry / downgrade / give-up.  Detection is by
        # checksum at the faulted step itself — never by watching the
        # loss diverge later.
        if supervisor is not None:
            if float(metrics.get("reduce_ok", 1.0)) == 0.0:
                meter.bump("wire_faults_detected")
                events.append(("wire_fault", it, supervisor.mode,
                               int(float(metrics.get("reduce_hop_bad",
                                                     0.0))),
                               int(float(metrics.get("reduce_gather_bad",
                                                     0.0)))))
                action = supervisor.on_failure(it)
                if action == "give_up":
                    # fp32 psum disagreeing is not a transport problem
                    return finish("transport")
                if action == "downgrade":
                    meter.bump("transport_downgrades")
                    events.append(("transport_down", it, supervisor.mode))
                    if resync_fn is not None:
                        # a divergent replica may have leaked (gather-
                        # site corruption); make replication bitwise
                        # again before the retry
                        state = resync_fn(state)
                        meter.bump("resyncs")
                        events.append(("resync", it))
                else:
                    meter.bump("reduce_retries")
                    events.append(("reduce_retry", it))
                retry_batch = batch
                continue
            if supervisor.on_success(it) == "upgrade":
                meter.bump("transport_upgrades")
                events.append(("transport_up", it, supervisor.mode))

        meter.observe_metrics(metrics)
        if flight is not None:
            flight.record("step", step=it, loss=loss)
        # --- precision-ladder supervision (ISSUE 5) -------------------
        # runs only on ACCEPTED steps (a wire-fault discard above never
        # reaches here — its telemetry came from a corrupted reduce).
        # The update is kept either way; the ladder re-formats the NEXT
        # step (precision.py: escalation is forward-looking).
        if precision is not None:
            pact = precision.on_metrics(it, metrics)
            if precision.last_hot:
                meter.bump("sat_hot_steps")
            if pact == "escalate":
                meter.bump("precision_escalations")
                events.append(("precision_up", it, precision.name))
            elif pact == "deescalate":
                meter.bump("precision_deescalations")
                events.append(("precision_down", it, precision.name))
        if injector is not None:
            loss = injector.fault_loss(it, loss)
        if on_step is not None:
            on_step(it, {**metrics, "loss": loss})

        # A guard-skipped step's loss metric is naturally poisoned (the
        # forward pass saw the bad batch); the anomaly was already
        # handled in-step, so it must not ALSO count as divergence.
        guard_ok = float(metrics.get("guard_ok", 1.0)) != 0.0

        # --- divergence -> integrity-checked rollback -----------------
        if sentinel is not None and guard_ok and sentinel.update(loss):
            events.append(("diverged", it, round(loss, 6)))
            if manager is None or rollbacks >= max_rollbacks:
                return finish("diverged")
            res = manager.restore_latest_valid(new_state, rank=rank)
            if res is None:
                return finish("diverged")
            for bad in res.skipped:
                meter.bump("ckpts_invalid")
                events.append(("ckpt_invalid", bad))
            if res.verified is None:
                # restored, but nothing could vouch for the bytes —
                # the silent-integrity gap, made loud
                meter.bump("ckpts_unverified")
                events.append(("ckpt_unverified", res.step))
            if precision is not None and (res.metadata or {}
                                          ).get("precision"):
                # resume the ladder where the checkpoint left it (e.g.
                # mid-escalation) — replaying at home would re-diverge
                # into the exact saturation the escalation escaped
                precision.load_state_dict(res.metadata["precision"])
                events.append(("precision_restored", res.step,
                               precision.name))
            state = res.state
            it = int(res.step)
            rollbacks += 1
            reseed = rollbacks
            meter.bump("rollbacks")
            meter.bump("restores")
            sentinel.reset()
            events.append(("rollback", it))
            if flight is not None:
                flight.record("rollback", step=it)
                flight.dump("rollback")
            if backoff_secs > 0:
                time.sleep(backoff_secs * (2 ** (rollbacks - 1)))
            if watchdog is not None:
                # re-arm on rollback completion (ISSUE 19 bugfix): the
                # restore + backoff ran on the tripped-out step's old
                # clock; the replay step gets a FRESH deadline and a
                # fresh verdict (arm clears a stale `tripped`), so a
                # fire that landed mid-rollback cannot abort the
                # slow-but-healthy recovery step at its boundary check
                watchdog.arm(it, counters=meter.as_dict())
            continue

        state = new_state
        it += 1
        if consensus_every and it % consensus_every == 0 and it < n_steps:
            # cheap periodic drift repair: one digest collective; the
            # broadcast only runs when replicas actually disagree
            if int(consensus_fn(state)) == 0:
                state = resync_fn(state)
                meter.bump("resyncs")
                events.append(("consensus_resync", it))
        if ckpt_every and it % ckpt_every == 0 and it < n_steps:
            save(it, "ckpt")

    if manager is not None and ckpt_every:
        save(it, "ckpt_final")
    return finish(None)
