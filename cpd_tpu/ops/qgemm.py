"""Pallas quantized-Kahan-accumulator GEMM — the native analog of the
reference's `tvm_gemm` CUDA kernel.

Reference: float_kernel.cu:103-340 — a tiled SGEMM whose inner product is
Kahan-compensated with EVERY intermediate re-cast to eXmY (multiply, y, t,
and the double-cast c; :181-195).  The K dimension is visited strictly in
ascending order, so the semantics are an ordered sequential reduction.

TPU-native design: grid over (M/128, N/128) output tiles; per tile, a
`fori_loop` walks K in order performing a rank-1 (outer-product) update of
the (128,128) accumulator with the quantized Kahan recurrence on the VPU.
The MXU cannot requantize mid-dot — the same fidelity/throughput trade the
reference made by not using tensor cores (SURVEY.md §7.2).  A is passed
transposed (K, M) so the K index walks the sublane dimension, which Mosaic
slices efficiently.

K is never padded: a padded zero step is NOT a Kahan no-op when the
compensation term is nonzero, so zero-padding K would change the numerics.
M/N padding only adds discarded output rows/cols.

Bit-parity: the kernel reuses `cast_body` — the same code as the XLA path —
so `qgemm_pallas == quant_gemm(mode='faithful')` exactly.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from ..compat import pallas as pl, pallas_tpu as pltpu

from ..quant.numerics import _validate, cast_body

__all__ = ["qgemm_pallas"]

_TILE = 128


def _qgemm_kernel(at_ref, b_ref, o_ref, s_ref, c_ref, *, exp_bits: int,
                  man_bits: int, k_steps: int):
    q = lambda t: cast_body(t, exp_bits, man_bits)
    s_ref[...] = jnp.zeros_like(s_ref)
    c_ref[...] = jnp.zeros_like(c_ref)

    def body(k, _):
        a_col = at_ref[k, :]          # (TILE_M,)
        b_row = b_ref[k, :]           # (TILE_N,)
        tmp = q(a_col[:, None] * b_row[None, :])      # float_kernel.cu:181
        s = s_ref[...]
        c = c_ref[...]
        y = q(tmp - c)                                # :185
        t = q(s + y)                                  # :188
        c_ref[...] = q(q(t - s) - y)                  # :191-194 (double cast)
        s_ref[...] = t
        return 0

    lax.fori_loop(0, k_steps, body, 0)
    o_ref[...] = s_ref[...]


@functools.partial(jax.jit, static_argnums=(2, 3, 4))
def qgemm_pallas(a: jnp.ndarray, b: jnp.ndarray, exp_bits: int,
                 man_bits: int, interpret: bool = False) -> jnp.ndarray:
    """(M,K) @ (K,N) with the quantized-Kahan eXmY accumulator, via Pallas.

    Bit-identical to `quant_gemm(..., mode='faithful')`
    (quant/quant_function.py)."""
    _validate(exp_bits, man_bits)
    if a.ndim != 2 or b.ndim != 2 or a.shape[1] != b.shape[0]:
        raise ValueError(f"qgemm expects (M,K)x(K,N); got {a.shape} x {b.shape}")
    a = jnp.asarray(a, jnp.float32)
    b = jnp.asarray(b, jnp.float32)
    m, k = a.shape
    n = b.shape[1]

    mp = -(-m // _TILE) * _TILE
    np_ = -(-n // _TILE) * _TILE
    at = jnp.pad(a.T, ((0, 0), (0, mp - m)))          # (K, Mp)
    bp = jnp.pad(b, ((0, 0), (0, np_ - n)))           # (K, Np)

    out = pl.pallas_call(
        functools.partial(_qgemm_kernel, exp_bits=exp_bits,
                          man_bits=man_bits, k_steps=k),
        out_shape=jax.ShapeDtypeStruct((mp, np_), jnp.float32),
        grid=(mp // _TILE, np_ // _TILE),
        in_specs=[
            pl.BlockSpec((k, _TILE), lambda i, j: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k, _TILE), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((_TILE, _TILE), lambda i, j: (i, j),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((_TILE, _TILE), jnp.float32),
            pltpu.VMEM((_TILE, _TILE), jnp.float32),
        ],
        interpret=interpret,
    )(at, bp)
    return out[:m, :n]
