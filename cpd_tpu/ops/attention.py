"""Attention ops: fused local attention + ring attention for sequence/context
parallelism.

The reference has no attention at all (SURVEY.md §5 "long-context: absent" —
its workloads are CNNs), so this is new capability, built TPU-first:

* `local_attention` — plain blockwise softmax attention on one device;
  fp32 logits/softmax (MXU matmuls in the input dtype, accumulation fp32).
* `ring_attention` — sequence-parallel attention inside `shard_map`: Q
  stays resident, K/V blocks rotate around the `sp` axis ring via
  `lax.ppermute` while an online-softmax accumulator (running max m,
  normalizer l, output o) folds in one block per step.  Communication is
  W-1 ppermutes of the local K/V — the ICI-friendly pattern of Ring
  Attention (Liu et al.; see PAPERS.md) — and peak memory is O(T_local^2)
  per device instead of O(T^2).
* `ulysses_attention` — the all-to-all alternative (DeepSpeed-Ulysses
  pattern; see PAPERS.md): one all_to_all turns sequence sharding into
  head sharding, each device runs *full-sequence* attention on H/W heads,
  a second all_to_all restores sequence sharding.  Two collectives total
  (vs W-1 permute rounds), at the price of requiring heads % W == 0 and
  O((T_global)^2) score memory per device — the right trade when W is
  modest and heads are plentiful; composable with `impl="flash"` to drop
  the score-matrix memory.
* `grouped_query_attention` — GQA on UNEXPANDED K/V (H_kv heads serving
  H = rep*H_kv query heads, kv head j ↔ q heads [j*rep, (j+1)*rep)):
  the query head axis is reshaped to (H_kv, rep) and contracted against
  the small K/V directly, so neither HBM nor the score computation ever
  materializes the repeated copies — this is what makes the GQA KV-cache
  memory win real at decode time.  The sequence-parallel paths carry the
  SAME unexpanded K/V through their collectives (round 4): the ring
  rotates (B, T_local, H_kv, D) blocks — rep× fewer ICI bytes than the
  expanded path, the point of GQA under sp — and ulysses all_to_alls
  H_kv-headed K/V whenever H_kv divides the axis size, expanding by the
  minimal factor (worst case to H) only when it does not.

Causality with a sharded sequence: rank r holds tokens
[r*T_local, (r+1)*T_local); at ring step s it receives the K/V block of
rank (r-s) mod W.  Blocks from lower-ranked sources attend fully, the own
block (s=0) uses the triangular mask, and blocks from higher-ranked
sources are skipped (masked to -inf; their compute overlaps the permute).
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["local_attention", "ring_attention", "ulysses_attention",
           "grouped_query_attention"]

_NEG_INF = -1e30  # large-negative instead of -inf: keeps softmax NaN-free
                  # when a full row is masked (the all-masked ring step)

# Fully-masked query rows (a causal shard whose every key is in the
# future, e.g. q_offset + Tq <= k_offset) return ZERO in every impl —
# the flash-attention convention (round 5, ADVICE r4): the one-shot
# softmax's uniform-average fallback and the online-softmax paths'
# pad-key pollution both produced arbitrary, impl-dependent values for
# rows with no attendable key; zero is the one answer all schedules
# (one-shot, chunked, ring, Pallas flash_gqa) can agree on exactly.


def _causal_mask(tq: int, tk: int, q_off, k_off) -> jnp.ndarray:
    """(tq, tk) bool mask: query global position >= key global position."""
    qi = q_off + jnp.arange(tq)[:, None]
    ki = k_off + jnp.arange(tk)[None, :]
    return qi >= ki


def local_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    causal: bool = True,
                    q_offset=0, k_offset=0,
                    impl: str = "xla") -> jnp.ndarray:
    """Softmax attention for (B, T, H, D) tensors on one device.

    fp32 softmax; returns q.dtype.  Offsets give the tokens' global
    positions (used by ring steps and by tests comparing shard vs full).

    impl="flash" opts into the Pallas TPU flash-attention kernel
    (jax.experimental.pallas.ops.tpu) — O(T) memory instead of the
    materialized (T, T) score matrix.  Explicit opt-in, not autodetected:
    the kernel has TPU-generation/shape constraints (sequence multiples
    of the block size, supported head dims) that should fail loudly at
    the call site, not silently downgrade mid-training.

    impl="chunked" is the pure-XLA flash-style fallback: an online-
    softmax `lax.scan` over K/V blocks — same O(T·block) memory shape as
    flash without the Pallas constraints, any backend, offsets
    supported.  Use when the Pallas kernel's shape rules bite (or off
    TPU); ~the same FLOPs as "xla", traded against score-matrix HBM."""
    if impl == "flash":
        return _flash_attention(q, k, v, causal, q_offset, k_offset)
    if impl == "chunked":
        return _chunked_attention(q, k, v, causal, q_offset, k_offset)
    if impl != "xla":
        raise ValueError(f"unknown attention impl {impl!r}; "
                         "expected 'xla', 'flash' or 'chunked'")
    scale = 1.0 / jnp.sqrt(jnp.float32(q.shape[-1]))
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    mask = None
    if causal:
        mask = _causal_mask(q.shape[1], k.shape[1], q_offset, k_offset)
        logits = jnp.where(mask[None, None], logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    if mask is not None:
        # zero fully-masked rows (softmax fell back to a uniform average)
        out = jnp.where(mask.any(-1)[None, :, None, None], out, 0.0)
    return out.astype(q.dtype)


def _flash_attention(q, k, v, causal, q_offset, k_offset):
    """Pallas TPU flash kernel on (B, T, H, D) inputs (kernel layout is
    (B, H, T, D)); nonzero offsets are not supported — the ring wrapper
    handles global positions itself."""
    if q_offset != 0 or k_offset != 0:
        raise ValueError("impl='flash' does not support q/k offsets; "
                         "use the default impl inside ring steps")
    from ..compat import flash_attention_import
    flash_attention = flash_attention_import()

    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    out = flash_attention(qt, kt, vt, causal=causal,
                          sm_scale=1.0 / float(q.shape[-1]) ** 0.5)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


_CHUNK = 512  # K/V block length of the chunked scan (MXU-friendly, and
              # small enough that (B,H,Tq,_CHUNK) fp32 logits stay modest)


def _fold_segment(o, m, l, qg, k_cur, v_cur, valid, scale):
    """One online-softmax fold: merge a K/V segment into the (o, m, l)
    accumulator — the flash recurrence, shared verbatim by the chunked
    scan, the ring per-step fold, and the ring's chunked inner loop.

    qg: (B, Tq, H_kv, rep, D) grouped queries (GQA-native contraction);
    k_cur/v_cur: (B, S, H_kv, D); valid: (Tq, S) bool mask or None."""
    b, tq, hkv, rep, d = qg.shape
    h = hkv * rep
    s = k_cur.shape[1]
    logits = jnp.einsum(
        "bqgrd,bkgd->bgrqk", qg, k_cur,
        preferred_element_type=jnp.float32).reshape(b, h, tq, s) * scale
    if valid is not None:
        logits = jnp.where(valid[None, None], logits, _NEG_INF)
    m_new = jnp.maximum(m, logits.max(axis=-1))          # (B,H,Tq)
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(logits - m_new[..., None])               # (B,H,Tq,S)
    if valid is not None:
        # explicit zero, not exp(_NEG_INF - m): when the whole row is
        # still masked m_new == _NEG_INF and exp(0) == 1 would count
        # every masked/pad key into l (ADVICE r4 — degenerate rows now
        # yield l == 0 -> output 0, matching the one-shot path's zeroed
        # fully-masked rows)
        p = jnp.where(valid[None, None], p, 0.0)
    l_new = l * alpha + p.sum(axis=-1)
    pv = jnp.einsum(
        "bgrqk,bkgd->bqgrd",
        p.astype(v_cur.dtype).reshape(b, hkv, rep, tq, s),
        v_cur, preferred_element_type=jnp.float32).reshape(
            b, tq, h, v_cur.shape[-1])
    return o * alpha.transpose(0, 2, 1)[..., None] + pv, m_new, l_new


def _chunked_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                       causal, q_offset, k_offset,
                       block: int = _CHUNK) -> jnp.ndarray:
    """Flash-style attention in pure XLA: online softmax over K/V blocks.

    Supports GQA natively — q (B, Tq, H, D) against k/v (B, Tk, H_kv, D)
    with H_kv | H — via the same grouped contraction as
    `grouped_query_attention`, so no expansion is materialized either.
    Peak score memory is (B, H, Tq, block) instead of (B, H, Tq, Tk) —
    in the BACKWARD pass too: the scan body is `jax.checkpoint`ed, so AD
    stores only the per-block (o, m, l) carries (O(Tq·D) each, smaller
    than a block of scores whenever D < block) and recomputes the block
    softmax in the reverse sweep, the flash-backward recipe.  Tk is
    padded to a block multiple (block itself is clamped to ~Tk rounded
    up to the 128-lane width, so short sequences don't pay for a full
    default block of masked pad); pad keys are masked out by their
    global position, so results match the one-shot softmax to fp32
    round-off (same recurrence as `ring_attention`'s fold).
    """
    b, tq, h, d = q.shape
    tk = k.shape[1]
    hkv = k.shape[2]
    rep = _gqa_rep(q, k)
    scale = 1.0 / jnp.sqrt(jnp.float32(d))
    qg = q.reshape(b, tq, hkv, rep, d)

    block = min(block, max(128, -(-tk // 128) * 128))
    n_blocks = -(-tk // block)
    pad = n_blocks * block - tk
    kp = jnp.pad(k.astype(q.dtype), ((0, 0), (0, pad), (0, 0), (0, 0)))
    vp = jnp.pad(v.astype(q.dtype), ((0, 0), (0, pad), (0, 0), (0, 0)))
    # (N, B, block, H_kv, D) — scan carries one block at a time
    kb = kp.reshape(b, n_blocks, block, hkv, d).transpose(1, 0, 2, 3, 4)
    vb = vp.reshape(b, n_blocks, block, hkv, d).transpose(1, 0, 2, 3, 4)
    qi = q_offset + jnp.arange(tq)[:, None]            # (tq, 1)

    def step(carry, xs):
        o, m, l, i = carry
        k_cur, v_cur = xs
        ki = k_offset + i * block + jnp.arange(block)[None, :]
        valid = (ki - k_offset) < tk                   # pad keys out
        if causal:
            valid = valid & (qi >= ki)
        o, m, l = _fold_segment(o, m, l, qg, k_cur, v_cur, valid, scale)
        return (o, m, l, i + 1), None

    o0 = jnp.zeros((b, tq, h, v.shape[-1]), jnp.float32)
    m0 = jnp.full((b, h, tq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, tq), jnp.float32)
    (o, m, l, _), _ = lax.scan(
        jax.checkpoint(step), (o0, m0, l0, jnp.zeros([], jnp.int32)),
        (kb, vb))
    out = o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def _gqa_rep(q: jnp.ndarray, k: jnp.ndarray) -> int:
    """Query-heads-per-kv-head factor, validated (1 = MHA)."""
    h, hkv = q.shape[2], k.shape[2]
    if h % hkv:
        raise ValueError(f"q heads {h} not a multiple of kv heads {hkv}")
    return h // hkv


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   axis_name: str, causal: bool = True,
                   impl: str = "xla", block: int = _CHUNK) -> jnp.ndarray:
    """Sequence-parallel attention; call inside shard_map with the sequence
    dim sharded over `axis_name`.

    q: (B, T_local, H, D); k, v: (B, T_local, H_kv, D) with H_kv | H — GQA
    K/V ride the ring UNEXPANDED (rep× fewer ppermute bytes; the per-step
    contraction groups the query heads instead — the same dot products as
    the expanded ring, agreeing to the last ulp of the fp32 softmax chain;
    XLA's batched-matmul layout for the grouped einsum differs, so not
    bitwise).  Returns (B, T_local, H, D).  Differentiable (ppermute
    transposes to the reverse permute, so the backward pass is itself a
    ring).

    impl="chunked" folds each received K/V block through an inner
    checkpointed sub-block scan (the same `_fold_segment` recurrence):
    per-step score memory drops from (B, H, T_local, T_local) to
    (B, H, T_local, block) — forward and backward — which is what keeps
    very long per-device shards (T_local ≫ block) inside HBM.  When
    block does not divide T_local, the largest divisor of T_local that
    is ≤ block is used instead (the memory bound is preserved or
    bettered, never silently dropped); a DEGENERATE split (divisor
    < min(block, 128), e.g. prime T_local) raises rather than scanning
    element-by-element or materializing the full block.
    """
    if impl not in ("xla", "chunked"):
        raise ValueError(f"unknown ring impl {impl!r}; "
                         "expected 'xla' or 'chunked'")
    axis_size = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    b, t_local, h, d = q.shape
    hkv = k.shape[2]
    rep = _gqa_rep(q, k)
    scale = 1.0 / jnp.sqrt(jnp.float32(d))
    q_off = my * t_local
    # grouped layout: head index h == g*rep + r, so reshaping (H,) to
    # (H_kv, rep) keeps kv head g serving q heads [g*rep, (g+1)*rep)
    qg = q.reshape(b, t_local, hkv, rep, d)
    if impl == "chunked" and t_local > block:
        # largest divisor of T_local <= block: the opted-into memory
        # bound must hold, so never fall back to one whole-block fold
        div = max(f for f in range(1, block + 1) if t_local % f == 0)
        # refuse only when the REQUESTED block couldn't be honored and
        # the best divisor is tiny (e.g. prime T_local -> div == 1); an
        # explicit small block that divides exactly is always accepted
        if div != block and div < max(8, block // 16):
            raise ValueError(
                f"ring impl='chunked' cannot split T_local={t_local} "
                f"into sub-blocks <= {block}: largest divisor is {div} "
                f"(degenerate).  Pick a per-device sequence length "
                f"divisible by the block (multiples of 128 recommended) "
                f"or pass an explicit block= that divides it")
        block = div
        n_inner = t_local // block
    else:
        n_inner, block = 1, t_local

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    qi = q_off + jnp.arange(t_local)[:, None]

    def step(carry, s):
        o, m, l, k_cur, v_cur = carry
        src = (my - s) % axis_size           # whose K/V block we hold
        k_off = src * t_local

        def fold(inner_carry, xs):
            o_i, m_i, l_i, j = inner_carry
            k_seg, v_seg = xs
            valid = None
            if causal:
                ki = k_off + j * block + jnp.arange(block)[None, :]
                valid = qi >= ki
            o_i, m_i, l_i = _fold_segment(o_i, m_i, l_i, qg, k_seg,
                                          v_seg, valid, scale)
            return (o_i, m_i, l_i, j + 1), None

        if n_inner == 1:
            (o_new, m_new, l_new, _), _ = fold(
                (o, m, l, jnp.zeros([], jnp.int32)), (k_cur, v_cur))
        else:
            ks = k_cur.reshape(b, n_inner, block, hkv, d).transpose(
                1, 0, 2, 3, 4)
            vs = v_cur.reshape(b, n_inner, block, hkv, d).transpose(
                1, 0, 2, 3, 4)
            (o_new, m_new, l_new, _), _ = lax.scan(
                jax.checkpoint(fold),
                (o, m, l, jnp.zeros([], jnp.int32)), (ks, vs))

        # rotate K/V to the next rank (skip after the last fold: the scan
        # body is uniform, so we permute every step; the final permute
        # restores the original placement, which XLA can DCE if unused)
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (o_new, m_new, l_new, k_nxt, v_nxt), None

    o0 = jnp.zeros(q.shape[:2] + (q.shape[2], v.shape[-1]), jnp.float32)
    m0 = jnp.full((q.shape[0], q.shape[2], t_local), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((q.shape[0], q.shape[2], t_local), jnp.float32)
    (o, m, l, _, _), _ = lax.scan(
        step, (o0, m0, l0, k.astype(q.dtype), v.astype(q.dtype)),
        jnp.arange(axis_size))
    out = o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def grouped_query_attention(q: jnp.ndarray, k: jnp.ndarray,
                            v: jnp.ndarray, causal: bool = True,
                            q_offset=0, impl: str = "xla",
                            flash_bwd: str = "chunked") -> jnp.ndarray:
    """GQA softmax attention without materializing the K/V expansion.

    q: (B, Tq, H, D) with H = rep * H_kv; k, v: (B, Tk, H_kv, D).
    Numerically identical to expanding K/V over each query group and
    calling `local_attention` (fp32 logits/softmax, same mask), tested
    bitwise-close against that oracle.  rep == 1 falls through to
    `local_attention` itself.

    impl="flash" routes MHA (H == H_kv) to the stock TPU flash-attention
    kernel and GQA to the in-repo GQA-native Pallas kernel
    (`ops/flash_gqa.py`, round 5) which consumes the unexpanded K/V
    directly; both hardware-validated by tools/pallas_check.py.
    impl="chunked" runs the grouped contraction through the
    online-softmax K/V-block scan (`_chunked_attention`) — GQA-native,
    O(Tq·block) score memory, any backend.
    """
    b, tq, h, d = q.shape
    hkv = k.shape[2]
    if impl == "flash" and h != hkv:
        # GQA-native Pallas kernel (round 5): grouped queries against the
        # UNEXPANDED K/V — nothing rep-sized is materialized in HBM
        if q_offset != 0:
            raise ValueError("impl='flash' does not support q offsets; "
                             "use the default impl inside ring steps")
        from .flash_gqa import flash_gqa
        return flash_gqa(q, k, v, causal, flash_bwd)
    if impl == "chunked":
        return _chunked_attention(q, k, v, causal, q_offset, 0)
    if h == hkv:
        return local_attention(q, k, v, causal=causal, q_offset=q_offset,
                               impl=impl)
    if h % hkv:
        raise ValueError(f"q heads {h} not a multiple of kv heads {hkv}")
    rep = h // hkv
    scale = 1.0 / jnp.sqrt(jnp.float32(d))
    qg = q.reshape(b, tq, hkv, rep, d)
    logits = jnp.einsum("bqgrd,bkgd->bgrqk", qg, k,
                        preferred_element_type=jnp.float32) * scale
    mask = None
    if causal:
        mask = _causal_mask(tq, k.shape[1], q_offset, 0)
        logits = jnp.where(mask[None, None, None], logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bgrqk,bkgd->bqgrd", probs.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    if mask is not None:
        out = jnp.where(mask.any(-1)[None, :, None, None, None], out, 0.0)
    return out.reshape(b, tq, h, d).astype(q.dtype)


def ulysses_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                      axis_name: str, causal: bool = True,
                      impl: str = "xla",
                      flash_bwd: str = "chunked") -> jnp.ndarray:
    """All-to-all sequence-parallel attention; call inside shard_map with
    the sequence dim sharded over `axis_name`.

    q, k, v: (B, T_local, H, D) local shards with H the device-local head
    count (after any tensor-parallel split); H must be divisible by the
    `axis_name` mesh size (all_to_all enforces this).  Returns
    (B, T_local, H, D).  Differentiable: all_to_all transposes to the
    reverse all_to_all.

    GQA K/V (H_kv < H heads) go through the all_to_all UNEXPANDED whenever
    H_kv is divisible by the axis size — rep× fewer ICI bytes — and the
    full-sequence middle step runs the grouped kernel on each device's
    contiguous head chunk (chunk w's q heads [w·H/W, (w+1)·H/W) are served
    exactly by its kv heads [w·H_kv/W, (w+1)·H_kv/W), since H/W =
    rep·H_kv/W).  When H_kv % W != 0 the K/V are expanded by the MINIMAL
    factor e (the smallest divisor of rep making H_kv·e % W == 0; worst
    case e = rep, the fully-expanded legacy behavior).

    ``impl`` is forwarded to the full-sequence middle step ("flash" =
    Pallas kernel on the gathered sequence).  With GQA the middle step
    runs the GQA-native flash kernel (`ops/flash_gqa.py`) directly on the
    unexpanded K/V chunk — since round 5 neither the wire NOR device-local
    HBM pays the rep× (the pre-round-5 path re-materialized the expansion
    after the all_to_all).
    """
    axis_size = lax.psum(1, axis_name)
    rep = _gqa_rep(q, k)
    if q.shape[2] % axis_size:
        raise ValueError(f"ulysses needs q heads {q.shape[2]} divisible "
                         f"by the {axis_name} axis size {axis_size}")
    if k.shape[2] % axis_size:
        # minimal grouping-preserving expansion: kv head j repeated e×
        # keeps q head h served by expanded head h // (rep/e), which the
        # contiguous all_to_all chunking preserves iff e | rep
        e = next(f for f in range(1, rep + 1)
                 if rep % f == 0 and (k.shape[2] * f) % axis_size == 0)
        k = jnp.repeat(k, e, axis=2)
        v = jnp.repeat(v, e, axis=2)

    def seq_to_heads(x):
        # (B, T_local, H, D) -> (B, T_global, H/W, D)
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    out = grouped_query_attention(qh, kh, vh, causal=causal, impl=impl,
                                  flash_bwd=flash_bwd)
    return heads_to_seq(out)
