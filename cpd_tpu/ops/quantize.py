"""Pallas elementwise eXmY quantize kernel — the native analog of the
reference's CUDA quantize kernel.

Reference: `float_kernel_nearest` launches one CUDA thread per element
(CPDtorch/quant/quant_cuda/float_kernel.cu:94-101, quant.cu:14-25).  The
TPU-native shape of the same op is a VPU kernel over (8,128)-tiled VMEM
blocks: each grid step streams one block HBM->VMEM, applies the bit-exact
cast body (quant/numerics.py `cast_body` — shared with the XLA path, so the
kernel *is* the oracle) and streams it back.  Unlike the CUDA kernel this is
pure: no in-place mutation (quant.cu:22-23's aliasing trap disappears).

XLA already fuses `cast_to_format` into surrounding elementwise work, so the
kernel's value is (a) demonstrating the native path end-to-end, (b) avoiding
fusion-boundary materialization for very large standalone quantize calls,
and (c) being the template the quantized-GEMM kernel builds on.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from ..compat import pallas as pl, pallas_tpu as pltpu

from ..quant.numerics import (_scale_pow2, _validate, _validate_wire,
                              cast_body, cast_body_sr,
                              format_max_exponent, max_finite, pack_code,
                              sidecar_bytes, unpack_code, wire_bytes)

__all__ = ["quantize_pallas", "quantize_pallas_sr", "quantize_add_pallas",
           "quantize_add_pallas_bits", "hop_pack_pallas",
           "quantize_pack_pallas", "digest_rows_pallas",
           "fletcher_mod65521"]

_LANES = 128
_BLOCK_ROWS = 512  # (512, 128) fp32 block = 256 KiB of VMEM in + out
_DIGEST_ROWS = 2048  # (2048, 128) u8 block = 256 KiB (digest kernel)


def _quantize_kernel(x_ref, o_ref, *, exp_bits: int, man_bits: int):
    o_ref[:] = cast_body(x_ref[:], exp_bits, man_bits)


def _quantize_sr_kernel(x_ref, r_ref, o_ref, *, exp_bits: int, man_bits: int):
    o_ref[:] = cast_body_sr(x_ref[:], exp_bits, man_bits, r_ref[:])


def _to_blocks(x: jnp.ndarray):
    """Flatten + zero-pad an array to (grid*_BLOCK_ROWS, _LANES) tiles."""
    n = x.size
    rows = -(-n // _LANES)
    pad = rows * _LANES - n
    flat = jnp.pad(x.reshape(-1), (0, pad))
    grid = -(-rows // _BLOCK_ROWS)
    padded_rows = grid * _BLOCK_ROWS
    flat = jnp.pad(flat.reshape(rows, _LANES),
                   ((0, padded_rows - rows), (0, 0)))
    return flat, grid, padded_rows


def _block_spec():
    return pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)


@functools.partial(jax.jit, static_argnums=(1, 2, 3))
def quantize_pallas(x: jnp.ndarray, exp_bits: int, man_bits: int,
                    interpret: bool = False) -> jnp.ndarray:
    """eXmY cast of an arbitrary-shape fp32 array via a Pallas TPU kernel.

    Bit-identical to `cast_to_format` (same body).  `interpret=True` runs
    the kernel in the Pallas interpreter for CPU testing."""
    _validate(exp_bits, man_bits)
    x = jnp.asarray(x, jnp.float32)
    shape = x.shape
    n = x.size
    if n == 0:
        return x
    flat, grid, padded_rows = _to_blocks(x)

    out = pl.pallas_call(
        functools.partial(_quantize_kernel, exp_bits=exp_bits,
                          man_bits=man_bits),
        out_shape=jax.ShapeDtypeStruct((padded_rows, _LANES), jnp.float32),
        grid=(grid,),
        in_specs=[_block_spec()],
        out_specs=_block_spec(),
        interpret=interpret,
    )(flat)
    return out.reshape(-1)[:n].reshape(shape)


def _quantize_add_kernel(x_ref, y_ref, o_ref, *, exp_bits: int,
                         man_bits: int):
    o_ref[:] = cast_body(x_ref[:] + y_ref[:], exp_bits, man_bits)


def _quantize_add_sr_kernel(x_ref, y_ref, r_ref, o_ref, *, exp_bits: int,
                            man_bits: int):
    o_ref[:] = cast_body_sr(x_ref[:] + y_ref[:], exp_bits, man_bits,
                            r_ref[:])


@functools.partial(jax.jit, static_argnums=(2, 3, 4))
def quantize_add_pallas(x: jnp.ndarray, y: jnp.ndarray, exp_bits: int,
                        man_bits: int,
                        interpret: bool = False) -> jnp.ndarray:
    """Fused quantize-accumulate: ``cast(x + y)`` in ONE VPU kernel — the
    per-hop body of the ring reduce-scatter (parallel/ring.py), where the
    add and the cast would otherwise be separate HBM round-trips per hop.
    Bit-identical to ``cast_to_format(x + y)`` (same `cast_body`)."""
    _validate(exp_bits, man_bits)
    x = jnp.asarray(x, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    if x.shape != y.shape:
        raise ValueError(f"shape mismatch {x.shape} vs {y.shape}")
    shape, n = x.shape, x.size
    if n == 0:
        return x
    xf, grid, padded_rows = _to_blocks(x)
    yf, _, _ = _to_blocks(y)
    out = pl.pallas_call(
        functools.partial(_quantize_add_kernel, exp_bits=exp_bits,
                          man_bits=man_bits),
        out_shape=jax.ShapeDtypeStruct((padded_rows, _LANES), jnp.float32),
        grid=(grid,),
        in_specs=[_block_spec(), _block_spec()],
        out_specs=_block_spec(),
        interpret=interpret,
    )(xf, yf)
    return out.reshape(-1)[:n].reshape(shape)


@functools.partial(jax.jit, static_argnums=(2, 3, 5))
def quantize_add_pallas_bits(x: jnp.ndarray, y: jnp.ndarray, exp_bits: int,
                             man_bits: int, rbits: jnp.ndarray,
                             interpret: bool = False) -> jnp.ndarray:
    """Stochastic-rounding fused quantize-accumulate: ``cast_sr(x + y)``
    with EXPLICIT uint32 round bits streamed in as an operand (the ring
    hop passes offset-indexed `sr_bits_at` bits, so the kernel stays
    bit-identical to the XLA path and transport-invariant).  Bit-identical
    to ``cast_body_sr(x + y, ..., rbits)``."""
    _validate(exp_bits, man_bits)
    x = jnp.asarray(x, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    if x.shape != y.shape:
        raise ValueError(f"shape mismatch {x.shape} vs {y.shape}")
    shape, n = x.shape, x.size
    if n == 0:
        return x
    rbits = jnp.broadcast_to(jnp.asarray(rbits, jnp.uint32), shape)
    xf, grid, padded_rows = _to_blocks(x)
    yf, _, _ = _to_blocks(y)
    rf, _, _ = _to_blocks(rbits)
    out = pl.pallas_call(
        functools.partial(_quantize_add_sr_kernel, exp_bits=exp_bits,
                          man_bits=man_bits),
        out_shape=jax.ShapeDtypeStruct((padded_rows, _LANES), jnp.float32),
        grid=(grid,),
        in_specs=[_block_spec(), _block_spec(), _block_spec()],
        out_specs=_block_spec(),
        interpret=interpret,
    )(xf, yf, rf)
    return out.reshape(-1)[:n].reshape(shape)


@functools.partial(jax.jit, static_argnums=(1, 2, 4))
def quantize_pallas_sr(x: jnp.ndarray, exp_bits: int, man_bits: int,
                       key: jax.Array, interpret: bool = False) -> jnp.ndarray:
    """Stochastically-rounded eXmY cast via a Pallas TPU kernel.

    Random bits are generated with the host-side JAX PRNG and streamed into
    the kernel as a second operand (rather than seeding an on-chip PRNG), so
    this is bit-identical to `cast_to_format_sr(x, exp, man, key)` — the
    kernel and the XLA path consume the SAME bitstream and tests can assert
    exact equality between them."""
    _validate(exp_bits, man_bits)
    x = jnp.asarray(x, jnp.float32)
    shape = x.shape
    n = x.size
    if n == 0:
        return x
    rbits = jax.random.bits(key, shape, jnp.uint32)
    flat, grid, padded_rows = _to_blocks(x)
    rflat, _, _ = _to_blocks(rbits)

    out = pl.pallas_call(
        functools.partial(_quantize_sr_kernel, exp_bits=exp_bits,
                          man_bits=man_bits),
        out_shape=jax.ShapeDtypeStruct((padded_rows, _LANES), jnp.float32),
        grid=(grid,),
        in_specs=[_block_spec(), _block_spec()],
        out_specs=_block_spec(),
        interpret=interpret,
    )(flat, rflat)
    return out.reshape(-1)[:n].reshape(shape)


# ---------------------------------------------------------------------------
# Fused wire kernels (ISSUE 9): the ENTIRE per-hop ring wire path —
# unpack the received code words, accumulate the local contribution,
# (block-)scale, quantize, re-pack, and Fletcher-digest BOTH wire
# buffers — in ONE Pallas kernel.
#
# Why: the self-verifying transport used to run its digests as a
# separate XLA pass over the packed words (docs/PERF.md measured it at
# +449-566% of the clean reduce), and pack/unpack themselves were
# separate HBM round-trips around the quantize-accumulate kernel.  Here
# one kernel streams the received bytes and the local gradients through
# VMEM once and emits the new partial (fp32), the new code words, and
# the (s1, s2) Fletcher partial sums of both buffers — so `verify=True`
# costs a few VPU ops per element instead of extra passes.
#
# Bitwise contract: every stage reuses the SAME un-jitted bodies as the
# XLA path (`cast_body`/`cast_body_sr`, `pack_code`/`unpack_code`), and
# the in-kernel mod-65521 arithmetic (`fletcher_mod65521` — shift/add
# only, no integer division, Mosaic-safe) is exact, so the kernel's
# digest word equals `integrity.wire_digest` on the same buffer and the
# kernel's partial equals the XLA hop bit-for-bit (gated in
# tests/test_ops_pallas.py and CI's reduce-smoke).
#
# Block-scaled hops (`block_size=`) are fused when the block is a
# multiple of 128 lanes dividing the 64k-element kernel tile (the
# default 128 qualifies): blocks are then whole kernel rows, so the
# per-block max is a row reduction.  The 1-byte-per-block shift sidecar
# is assembled (and its few bytes digested) in XLA and combined with
# the kernel's code-lane digest via `integrity.digest_concat`.
# ---------------------------------------------------------------------------

_DIGEST_MOD = 65521  # == integrity.DIGEST_MOD (import-leaf; pinned in
#                      tests/test_integrity.py)


def fletcher_mod65521(x: jnp.ndarray) -> jnp.ndarray:
    """x % 65521 for uint32 inputs using only shifts/masks/adds
    (2^16 ≡ 15 mod 65521), exact for the full uint32 range — the
    Mosaic-safe modulus of the in-kernel Fletcher digest.  Pinned
    against `%` in tests."""
    f = jnp.uint32(15)
    x = (x & jnp.uint32(0xFFFF)) + (x >> 16) * f      # < 2^20
    x = (x & jnp.uint32(0xFFFF)) + (x >> 16) * f      # < 65761
    m = jnp.uint32(_DIGEST_MOD)
    return jnp.where(x >= m, x - m, x)


def _tile_fletcher(bytes_u32: jnp.ndarray, byte_pos: jnp.ndarray) -> tuple:
    """Partial Fletcher sums (s1, s2) of one (R, 128) tile of byte
    values at absolute byte positions `byte_pos` (uint32).  Zero pad
    bytes contribute nothing, so no masking is needed.  Overflow-safe:
    Σ bytes <= 65536·255 < 2^24; per-lane products < 2^8·2^16 = 2^24,
    row sums of 128 < 2^31, mod'd row partials sum < 512·2^16."""
    s1 = fletcher_mod65521(jnp.sum(bytes_u32))
    posm = fletcher_mod65521(byte_pos) + jnp.uint32(1)
    rows = fletcher_mod65521(jnp.sum(bytes_u32 * posm, axis=1))
    s2 = fletcher_mod65521(jnp.sum(rows))
    return s1, s2


def _exp_field(x: jnp.ndarray) -> jnp.ndarray:
    return ((jax.lax.bitcast_convert_type(x, jnp.uint32) >> 23)
            & jnp.uint32(0xFF)).astype(jnp.int32)


def _flush_low_kernel(x: jnp.ndarray) -> jnp.ndarray:
    low = _exp_field(x) == 0
    return jnp.where(low, jnp.float32(0.0), x)


def _make_wire_kernel(exp_bits: int, man_bits: int, wb: int, *,
                      first: bool, sr: bool, blocked, want_digest: bool):
    """Build the fused hop kernel body.  Ref order: [wb in-planes +
    k_in plane (mid-hop only)], g, [rbits], then outputs: res, wb
    out-planes, [k_out plane (blocked)], [digest (1, 4) SMEM]."""
    emax = format_max_exponent(exp_bits)
    mf = float(max_finite(exp_bits, man_bits))

    def kernel(*refs):
        i = 0
        in_planes = k_in_ref = None
        if not first:
            in_planes = refs[:wb]
            i = wb
            if blocked is not None:
                k_in_ref = refs[i]
                i += 1
        g_ref = refs[i]
        i += 1
        r_ref = None
        if sr:
            r_ref = refs[i]
            i += 1
        res_ref = refs[i]
        i += 1
        out_planes = refs[i:i + wb]
        i += wb
        k_out_ref = None
        if blocked is not None:
            k_out_ref = refs[i]
            i += 1
        dig_ref = refs[i] if want_digest else None

        # -- unpack + accumulate ----------------------------------------
        code_in = None
        if first:
            s = g_ref[:]
        else:
            code_in = in_planes[0][:].astype(jnp.uint32)
            for k in range(1, wb):
                code_in = code_in | (in_planes[k][:].astype(jnp.uint32)
                                     << (8 * k))
            prev = unpack_code(code_in, exp_bits, man_bits)
            if blocked is not None:
                k_in = k_in_ref[:]
                flush = (jnp.isfinite(prev) & (prev != 0)
                         & (_exp_field(prev) - 127 + k_in <= -127))
                prev = _flush_low_kernel(
                    jnp.where(flush, jnp.float32(0.0),
                              _scale_pow2(prev, k_in)))
            s = prev + g_ref[:]

        # -- (block-)scale + quantize -----------------------------------
        if blocked is None:
            q = (cast_body_sr(s, exp_bits, man_bits, r_ref[:]) if sr
                 else cast_body(s, exp_bits, man_bits))
            res_ref[:] = q
        else:
            rows, lanes = s.shape
            c = blocked // lanes           # rows per block (>= 1)
            s = _flush_low_kernel(s)
            mag = jnp.where(jnp.isfinite(s), jnp.abs(s), 0.0)
            rmax = jnp.max(mag, axis=1, keepdims=True)      # (rows, 1)
            if c > 1:
                gmax = jnp.max(rmax.reshape(rows // c, c), axis=1,
                               keepdims=True)
                rmax = jnp.broadcast_to(gmax, (rows // c, c)).reshape(
                    rows, 1)
            bmax = jnp.broadcast_to(rmax, (rows, lanes))
            k_blk = jnp.where(bmax > 0, _exp_field(bmax) - 127 - emax, 0)
            k_blk = jnp.clip(k_blk, -128, 127)
            tiny = (jnp.isfinite(s) & (s != 0)
                    & (_exp_field(s) - 127 - k_blk <= -127))
            s = jnp.where(tiny, jnp.float32(0.0), s)
            y = _scale_pow2(s, -k_blk)
            q = (cast_body_sr(y, exp_bits, man_bits, r_ref[:]) if sr
                 else cast_body(y, exp_bits, man_bits))
            carry = jnp.isfinite(y) & (jnp.abs(q) > jnp.float32(mf))
            q = jnp.where(carry,
                          jnp.where(q > 0, jnp.float32(mf),
                                    jnp.float32(-mf)), q)
            out_flush = (jnp.isfinite(q) & (q != 0)
                         & (_exp_field(q) - 127 + k_blk <= -127))
            res_ref[:] = _flush_low_kernel(
                jnp.where(out_flush, jnp.float32(0.0),
                          _scale_pow2(q, k_blk)))
            k_out_ref[:] = k_blk
            # canonicalize the wire: values the unscale flushes (and
            # ±0.0) encode as code 0, exactly what the XLA path's
            # re-pack of the flushed partial emits — the two paths'
            # wire BYTES, not just their decoded values, must agree
            q = jnp.where(out_flush | (q == 0), jnp.float32(0.0), q)

        # -- pack + digest ----------------------------------------------
        code = pack_code(q, exp_bits, man_bits)
        for k in range(wb):
            out_planes[k][:] = ((code >> (8 * k))
                                & jnp.uint32(0xFF)).astype(jnp.uint8)
        if want_digest:
            pid = pl.program_id(0)
            rows, lanes = res_ref.shape
            elem = (jnp.uint32(rows * lanes) * pid.astype(jnp.uint32)
                    + lax.broadcasted_iota(jnp.uint32, (rows, lanes), 0)
                    * jnp.uint32(lanes)
                    + lax.broadcasted_iota(jnp.uint32, (rows, lanes), 1))

            def plane_sums(code_words):
                s1 = jnp.uint32(0)
                s2 = jnp.uint32(0)
                for k in range(wb):
                    b = (code_words >> (8 * k)) & jnp.uint32(0xFF)
                    p1, p2 = _tile_fletcher(
                        b, elem * jnp.uint32(wb) + jnp.uint32(k))
                    s1 = fletcher_mod65521(s1 + p1)
                    s2 = fletcher_mod65521(s2 + p2)
                return s1, s2

            o1, o2 = plane_sums(code)
            i1 = i2 = jnp.uint32(0)
            if not first:
                i1, i2 = plane_sums(code_in)

            @pl.when(pid == 0)
            def _():
                for j in range(4):
                    dig_ref[0, j] = jnp.uint32(0)

            for j, v in enumerate((i1, i2, o1, o2)):
                dig_ref[0, j] = fletcher_mod65521(dig_ref[0, j] + v)

    return kernel


def _assemble_wire(planes, n: int, wb: int) -> jnp.ndarray:
    """Byte planes back to the (n, wb) uint8 wire layout of pack_exmy."""
    return jnp.stack([p.reshape(-1)[:n] for p in planes], axis=-1)


def _wire_call(codes_in, k_in, sidecar_in, g, exp_bits, man_bits, rbits,
               block_size, want_digest, interpret):
    """Shared pallas_call assembly for the first-hop and mid-hop fused
    wire kernels.  Returns (res (n,), wire, [digest_in, digest_out]) —
    the wire in EXACTLY the layout the XLA path ships (``(n, wb)`` code
    words, or the flat blocked buffer with its sidecar lane), and the
    digests bitwise equal to `integrity.wire_digest` of those buffers."""
    _validate_wire(exp_bits, man_bits)
    wb = wire_bytes(exp_bits, man_bits)
    n = g.size
    first = codes_in is None
    sr = rbits is not None
    blocked = block_size is not None
    if blocked and (block_size % _LANES != 0
                    or (_BLOCK_ROWS * _LANES) % block_size != 0):
        raise ValueError(
            f"fused blocked hop needs block_size a multiple of {_LANES} "
            f"dividing {_BLOCK_ROWS * _LANES}, got {block_size} — the "
            f"XLA path (parallel/ring.py) handles other sizes")
    g = jnp.asarray(g, jnp.float32).reshape(-1)
    gf, grid, padded_rows = _to_blocks(g)
    operands = []
    in_specs = []
    if not first:
        for k in range(wb):
            pf, _, _ = _to_blocks(codes_in[:, k])
            operands.append(pf)
            in_specs.append(_block_spec())
        if blocked:
            kf, _, _ = _to_blocks(k_in.astype(jnp.int32))
            operands.append(kf)
            in_specs.append(_block_spec())
    operands.append(gf)
    in_specs.append(_block_spec())
    if sr:
        rf, _, _ = _to_blocks(jnp.asarray(rbits, jnp.uint32))
        operands.append(rf)
        in_specs.append(_block_spec())

    out_shape = [jax.ShapeDtypeStruct((padded_rows, _LANES), jnp.float32)]
    out_specs = [_block_spec()]
    for _ in range(wb):
        out_shape.append(jax.ShapeDtypeStruct((padded_rows, _LANES),
                                              jnp.uint8))
        out_specs.append(_block_spec())
    if blocked:
        out_shape.append(jax.ShapeDtypeStruct((padded_rows, _LANES),
                                              jnp.int32))
        out_specs.append(_block_spec())
    if want_digest:
        out_shape.append(jax.ShapeDtypeStruct((1, 4), jnp.uint32))
        # 4 running digest scalars in SMEM — the lane-multiple tiling
        # rule is about VMEM vector blocks; SMEM is word-addressed
        out_specs.append(pl.BlockSpec(  # cpd: disable=pallas-hygiene
            (1, 4), lambda i: (0, 0), memory_space=pltpu.SMEM))

    kernel = _make_wire_kernel(exp_bits, man_bits, wb, first=first,
                               sr=sr, blocked=block_size if blocked
                               else None, want_digest=want_digest)
    outs = pl.pallas_call(
        kernel,
        out_shape=tuple(out_shape),
        grid=(grid,),
        in_specs=in_specs,
        out_specs=tuple(out_specs),
        interpret=interpret,
    )(*operands)

    res = outs[0].reshape(-1)[:n]
    planes = outs[1:1 + wb]
    idx = 1 + wb
    if not blocked:
        wire = _assemble_wire(planes, n, wb)
        if not want_digest:
            return res, wire
        dig = outs[idx]
        d_out = (dig[0, 3] << 16) | dig[0, 2]
        d_in = (dig[0, 1] << 16) | dig[0, 0]
        return res, wire, d_in, d_out

    # blocked: append the sidecar lane, combine its digest contribution
    from ..parallel.integrity import digest_concat, wire_digest
    k_plane = outs[idx]
    idx += 1
    nb = sidecar_bytes(n, block_size)
    rows_per_block = block_size // _LANES
    # block b's shift sits in rows [b*rpb, (b+1)*rpb), any lane
    k_rows = k_plane[:, 0]                      # (padded_rows,)
    shifts = k_rows[::rows_per_block][:nb]
    sidecar = (shifts + 128).astype(jnp.uint8)
    codes_flat = _assemble_wire(planes, n, wb).reshape(-1)
    wire = jnp.concatenate([codes_flat, sidecar])
    if not want_digest:
        return res, wire
    dig = outs[idx]
    d_out_codes = (dig[0, 3] << 16) | dig[0, 2]
    d_out = digest_concat(d_out_codes, n * wb, wire_digest(sidecar))
    d_in_codes = (dig[0, 1] << 16) | dig[0, 0]
    d_in = (digest_concat(d_in_codes, n * wb, wire_digest(sidecar_in))
            if not first else jnp.uint32(0))
    return res, wire, d_in, d_out


def hop_pack_pallas(wire_in: jnp.ndarray, g: jnp.ndarray, exp_bits: int,
                    man_bits: int, *, rbits=None,
                    block_size=None, want_digest: bool = False,
                    interpret: bool = False):
    """One fused ring hop over the packed wire: unpack `wire_in`, add
    the local contribution `g`, (block-)quantize, re-pack, and (with
    ``want_digest``) Fletcher-digest both wire buffers — a single
    Pallas kernel pass (module block comment).

    Returns ``(res, wire_out)`` or ``(res, wire_out, digest_in,
    digest_out)``; `res` is the fp32 partial (bitwise the XLA hop's),
    `wire_out` the exact byte layout `ring_quantized_sum`'s to_wire
    ships, and the digests equal `integrity.wire_digest` of the full
    received/emitted buffers (sidecar lane included)."""
    n = g.size
    if block_size is None:
        codes_in = wire_in.reshape(n, wire_bytes(exp_bits, man_bits))
        k_in = sidecar_in = None
    else:
        wb = wire_bytes(exp_bits, man_bits)
        nb = sidecar_bytes(n, block_size)
        codes_in = wire_in[:n * wb].reshape(n, wb)
        sidecar_in = wire_in[n * wb:n * wb + nb]
        k_in = jnp.repeat(sidecar_in.astype(jnp.int32) - 128,
                          block_size)[:n]
    return _wire_call(codes_in, k_in, sidecar_in, g, exp_bits, man_bits,
                      rbits, block_size, want_digest, interpret)


def _digest_rows_kernel(b_ref, o_ref, *, w: int, sub_per_row: int):
    """One grid step digests tile ``j`` of EVERY row at once: the block
    stacks, for each of the ``w`` rows, ``sub_per_row`` sublanes of its
    j-th tile — per-row Fletcher partials come out of masked reductions
    over the sublane axis, so a whole W-row gather wire costs T grid
    steps (not W·T; one step for the common one-tile case, which is
    what keeps the interpret-mode CPU emulation honest).

    Overflow audit (uint32): per-sublane byte sums <= 128·255 < 2^15;
    per-sublane weighted sums: byte·(pos mod 65521 + 1) < 2^24, 128
    lanes -> < 2^31, mod'd immediately; masked per-row sums over
    sub_per_row <= 2048 sublanes of values < 65521 -> < 2^27."""
    j = pl.program_id(0)
    bytes_u32 = b_ref[:].astype(jnp.uint32)
    rows, lanes = b_ref.shape                  # rows = w * sub_per_row
    idx0 = lax.broadcasted_iota(jnp.uint32, (rows, lanes), 0)
    sub = idx0 % jnp.uint32(sub_per_row)       # sublane within the row
    pos = (j.astype(jnp.uint32)
           * jnp.uint32(sub_per_row * lanes)
           + sub * jnp.uint32(lanes)
           + lax.broadcasted_iota(jnp.uint32, (rows, lanes), 1))
    posm = fletcher_mod65521(pos) + jnp.uint32(1)
    c1 = jnp.sum(bytes_u32, axis=1)                        # (rows,)
    c2 = fletcher_mod65521(jnp.sum(bytes_u32 * posm, axis=1))
    row_id = idx0[:, 0] // jnp.uint32(sub_per_row)         # (rows,)

    @pl.when(j == 0)
    def _():
        for r in range(w):
            o_ref[r, 0] = jnp.uint32(0)
            o_ref[r, 1] = jnp.uint32(0)

    for r in range(w):
        m = row_id == jnp.uint32(r)
        p1 = fletcher_mod65521(jnp.sum(jnp.where(m, c1, 0)))
        p2 = fletcher_mod65521(jnp.sum(jnp.where(m, c2, 0)))
        o_ref[r, 0] = fletcher_mod65521(o_ref[r, 0] + p1)
        o_ref[r, 1] = fletcher_mod65521(o_ref[r, 1] + p2)


@functools.partial(jax.jit, static_argnums=(1,))
def digest_rows_pallas(rows: jnp.ndarray,
                       interpret: bool = False) -> jnp.ndarray:
    """Per-row Fletcher digest of a (W, n_bytes) uint8 buffer in ONE
    Pallas pass — bitwise equal to ``jax.vmap(integrity.wire_digest)``
    over the rows (pinned in tests/test_ops_pallas.py).

    This is the LAST fused digest of ISSUE 12 leg 4: the verified ring's
    all-gather row check used to hash the received rows XLA-side
    (`wire_digest` per row) — the one wire digest left outside the pack
    kernels.  With this kernel the fused verified arm emits every hop
    digest from `hop_pack_pallas` and every gather-row digest from here,
    so no XLA-side wire digest remains on that arm.  Zero pad bytes
    contribute nothing to either Fletcher sum, so rows pad freely to
    the tile grid."""
    rows = jnp.asarray(rows, jnp.uint8)
    if rows.ndim != 2:
        raise ValueError(f"digest_rows_pallas wants (W, n_bytes) uint8, "
                         f"got shape {rows.shape}")
    w, nb = rows.shape
    if nb == 0 or w == 0:
        return jnp.zeros((w,), jnp.uint32)
    # sublanes of one row per grid step: cap the whole block (all W
    # rows' tiles) near 2 MiB of VMEM, and cap per-row sublanes at 2048
    # (the masked-sum overflow bound above)
    sub_per_row = max(1, min(2048, 16384 // max(w, 1)))
    tile = sub_per_row * _LANES
    t = -(-nb // tile)
    padded = jnp.pad(rows, ((0, 0), (0, t * tile - nb)))
    # (w, t, sub, 128) -> (t, w·sub, 128): tile j of every row is one
    # contiguous block the grid walks in j order
    stacked = (padded.reshape(w, t, sub_per_row, _LANES)
               .transpose(1, 0, 2, 3)
               .reshape(t * w * sub_per_row, _LANES))

    # 2 running digest scalars per row in SMEM — the lane-multiple
    # tiling rule is about VMEM vector blocks; SMEM is word-addressed
    dig_spec = pl.BlockSpec(  # cpd: disable=pallas-hygiene
        (w, 2), lambda j: (0, 0), memory_space=pltpu.SMEM)
    out = pl.pallas_call(
        functools.partial(_digest_rows_kernel, w=w,
                          sub_per_row=sub_per_row),
        out_shape=jax.ShapeDtypeStruct((w, 2), jnp.uint32),
        grid=(t,),
        in_specs=[pl.BlockSpec((w * sub_per_row, _LANES),
                               lambda j: (j, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=dig_spec,
        interpret=interpret,
    )(stacked)
    return (out[:, 1] << 16) | out[:, 0]


def quantize_pack_pallas(g: jnp.ndarray, exp_bits: int, man_bits: int, *,
                         rbits=None, block_size=None,
                         want_digest: bool = False,
                         interpret: bool = False):
    """The ring's hop-0 wire emit, fused: (block-)quantize the local
    chunk and pack it (plus digest) in one kernel — `hop_pack_pallas`
    without a received wire.  Returns ``(res, wire)`` or ``(res, wire,
    digest)``."""
    out = _wire_call(None, None, None, g, exp_bits, man_bits, rbits,
                     block_size, want_digest, interpret)
    if want_digest:
        res, wire, _, d_out = out
        return res, wire, d_out
    return out
