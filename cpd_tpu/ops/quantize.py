"""Pallas elementwise eXmY quantize kernel — the native analog of the
reference's CUDA quantize kernel.

Reference: `float_kernel_nearest` launches one CUDA thread per element
(CPDtorch/quant/quant_cuda/float_kernel.cu:94-101, quant.cu:14-25).  The
TPU-native shape of the same op is a VPU kernel over (8,128)-tiled VMEM
blocks: each grid step streams one block HBM->VMEM, applies the bit-exact
cast body (quant/numerics.py `cast_body` — shared with the XLA path, so the
kernel *is* the oracle) and streams it back.  Unlike the CUDA kernel this is
pure: no in-place mutation (quant.cu:22-23's aliasing trap disappears).

XLA already fuses `cast_to_format` into surrounding elementwise work, so the
kernel's value is (a) demonstrating the native path end-to-end, (b) avoiding
fusion-boundary materialization for very large standalone quantize calls,
and (c) being the template the quantized-GEMM kernel builds on.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from ..compat import pallas as pl, pallas_tpu as pltpu

from ..quant.numerics import _validate, cast_body, cast_body_sr

__all__ = ["quantize_pallas", "quantize_pallas_sr", "quantize_add_pallas",
           "quantize_add_pallas_bits"]

_LANES = 128
_BLOCK_ROWS = 512  # (512, 128) fp32 block = 256 KiB of VMEM in + out


def _quantize_kernel(x_ref, o_ref, *, exp_bits: int, man_bits: int):
    o_ref[:] = cast_body(x_ref[:], exp_bits, man_bits)


def _quantize_sr_kernel(x_ref, r_ref, o_ref, *, exp_bits: int, man_bits: int):
    o_ref[:] = cast_body_sr(x_ref[:], exp_bits, man_bits, r_ref[:])


def _to_blocks(x: jnp.ndarray):
    """Flatten + zero-pad an array to (grid*_BLOCK_ROWS, _LANES) tiles."""
    n = x.size
    rows = -(-n // _LANES)
    pad = rows * _LANES - n
    flat = jnp.pad(x.reshape(-1), (0, pad))
    grid = -(-rows // _BLOCK_ROWS)
    padded_rows = grid * _BLOCK_ROWS
    flat = jnp.pad(flat.reshape(rows, _LANES),
                   ((0, padded_rows - rows), (0, 0)))
    return flat, grid, padded_rows


def _block_spec():
    return pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)


@functools.partial(jax.jit, static_argnums=(1, 2, 3))
def quantize_pallas(x: jnp.ndarray, exp_bits: int, man_bits: int,
                    interpret: bool = False) -> jnp.ndarray:
    """eXmY cast of an arbitrary-shape fp32 array via a Pallas TPU kernel.

    Bit-identical to `cast_to_format` (same body).  `interpret=True` runs
    the kernel in the Pallas interpreter for CPU testing."""
    _validate(exp_bits, man_bits)
    x = jnp.asarray(x, jnp.float32)
    shape = x.shape
    n = x.size
    if n == 0:
        return x
    flat, grid, padded_rows = _to_blocks(x)

    out = pl.pallas_call(
        functools.partial(_quantize_kernel, exp_bits=exp_bits,
                          man_bits=man_bits),
        out_shape=jax.ShapeDtypeStruct((padded_rows, _LANES), jnp.float32),
        grid=(grid,),
        in_specs=[_block_spec()],
        out_specs=_block_spec(),
        interpret=interpret,
    )(flat)
    return out.reshape(-1)[:n].reshape(shape)


def _quantize_add_kernel(x_ref, y_ref, o_ref, *, exp_bits: int,
                         man_bits: int):
    o_ref[:] = cast_body(x_ref[:] + y_ref[:], exp_bits, man_bits)


def _quantize_add_sr_kernel(x_ref, y_ref, r_ref, o_ref, *, exp_bits: int,
                            man_bits: int):
    o_ref[:] = cast_body_sr(x_ref[:] + y_ref[:], exp_bits, man_bits,
                            r_ref[:])


@functools.partial(jax.jit, static_argnums=(2, 3, 4))
def quantize_add_pallas(x: jnp.ndarray, y: jnp.ndarray, exp_bits: int,
                        man_bits: int,
                        interpret: bool = False) -> jnp.ndarray:
    """Fused quantize-accumulate: ``cast(x + y)`` in ONE VPU kernel — the
    per-hop body of the ring reduce-scatter (parallel/ring.py), where the
    add and the cast would otherwise be separate HBM round-trips per hop.
    Bit-identical to ``cast_to_format(x + y)`` (same `cast_body`)."""
    _validate(exp_bits, man_bits)
    x = jnp.asarray(x, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    if x.shape != y.shape:
        raise ValueError(f"shape mismatch {x.shape} vs {y.shape}")
    shape, n = x.shape, x.size
    if n == 0:
        return x
    xf, grid, padded_rows = _to_blocks(x)
    yf, _, _ = _to_blocks(y)
    out = pl.pallas_call(
        functools.partial(_quantize_add_kernel, exp_bits=exp_bits,
                          man_bits=man_bits),
        out_shape=jax.ShapeDtypeStruct((padded_rows, _LANES), jnp.float32),
        grid=(grid,),
        in_specs=[_block_spec(), _block_spec()],
        out_specs=_block_spec(),
        interpret=interpret,
    )(xf, yf)
    return out.reshape(-1)[:n].reshape(shape)


@functools.partial(jax.jit, static_argnums=(2, 3, 5))
def quantize_add_pallas_bits(x: jnp.ndarray, y: jnp.ndarray, exp_bits: int,
                             man_bits: int, rbits: jnp.ndarray,
                             interpret: bool = False) -> jnp.ndarray:
    """Stochastic-rounding fused quantize-accumulate: ``cast_sr(x + y)``
    with EXPLICIT uint32 round bits streamed in as an operand (the ring
    hop passes offset-indexed `sr_bits_at` bits, so the kernel stays
    bit-identical to the XLA path and transport-invariant).  Bit-identical
    to ``cast_body_sr(x + y, ..., rbits)``."""
    _validate(exp_bits, man_bits)
    x = jnp.asarray(x, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    if x.shape != y.shape:
        raise ValueError(f"shape mismatch {x.shape} vs {y.shape}")
    shape, n = x.shape, x.size
    if n == 0:
        return x
    rbits = jnp.broadcast_to(jnp.asarray(rbits, jnp.uint32), shape)
    xf, grid, padded_rows = _to_blocks(x)
    yf, _, _ = _to_blocks(y)
    rf, _, _ = _to_blocks(rbits)
    out = pl.pallas_call(
        functools.partial(_quantize_add_sr_kernel, exp_bits=exp_bits,
                          man_bits=man_bits),
        out_shape=jax.ShapeDtypeStruct((padded_rows, _LANES), jnp.float32),
        grid=(grid,),
        in_specs=[_block_spec(), _block_spec(), _block_spec()],
        out_specs=_block_spec(),
        interpret=interpret,
    )(xf, yf, rf)
    return out.reshape(-1)[:n].reshape(shape)


@functools.partial(jax.jit, static_argnums=(1, 2, 4))
def quantize_pallas_sr(x: jnp.ndarray, exp_bits: int, man_bits: int,
                       key: jax.Array, interpret: bool = False) -> jnp.ndarray:
    """Stochastically-rounded eXmY cast via a Pallas TPU kernel.

    Random bits are generated with the host-side JAX PRNG and streamed into
    the kernel as a second operand (rather than seeding an on-chip PRNG), so
    this is bit-identical to `cast_to_format_sr(x, exp, man, key)` — the
    kernel and the XLA path consume the SAME bitstream and tests can assert
    exact equality between them."""
    _validate(exp_bits, man_bits)
    x = jnp.asarray(x, jnp.float32)
    shape = x.shape
    n = x.size
    if n == 0:
        return x
    rbits = jax.random.bits(key, shape, jnp.uint32)
    flat, grid, padded_rows = _to_blocks(x)
    rflat, _, _ = _to_blocks(rbits)

    out = pl.pallas_call(
        functools.partial(_quantize_sr_kernel, exp_bits=exp_bits,
                          man_bits=man_bits),
        out_shape=jax.ShapeDtypeStruct((padded_rows, _LANES), jnp.float32),
        grid=(grid,),
        in_specs=[_block_spec(), _block_spec()],
        out_specs=_block_spec(),
        interpret=interpret,
    )(flat, rflat)
    return out.reshape(-1)[:n].reshape(shape)
