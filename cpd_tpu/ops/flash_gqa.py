"""GQA-native Pallas flash attention — grouped queries against UNEXPANDED
(B, T, H_kv, D) K/V.

The stock `jax.experimental.pallas.ops.tpu.flash_attention` kernel takes
uniform heads only, so the GQA paths either fell back to the pure-XLA
chunked scan or re-materialized the rep× K/V expansion after the Ulysses
all_to_all (round-4 verdict weak #5) — paying in HBM exactly what
`grouped_query_attention` exists to avoid.  This kernel closes that gap:
one (B, H_kv, q-block) program holds ALL `rep` query heads of its kv head
in VMEM and runs the flash online-softmax recurrence against each K/V
block ONCE — K/V HBM traffic is 1/rep of the expanded path's, and nothing
rep-sized is ever materialized anywhere.

The reference (drcut/CPD) has no attention at all (SURVEY.md §5); this is
new-capability code, TPU-first.

Design notes:
  * grid (B, H_kv, Tq/bq, Tk/bk), K innermost; the (o, m, l) accumulator
    lives in VMEM scratch, which persists across the innermost grid steps
    (the standard Pallas TPU flash pattern).  Output is written once, at
    the final K step.
  * the q block is (rep, bq, D): logits are ONE (rep·bq, D)x(D, bk) MXU
    contraction via dot_general — no per-head loop, no reshape.
  * masking zeroes p directly (p = where(valid, exp(s - m), 0)), so pad
    keys and fully-masked rows contribute 0 to l — a fully-masked row
    yields o = 0 rather than a pad-key average (the degenerate-row edge
    the ADVICE round-4 note flags for `_chunked_attention`).
  * causal K blocks strictly above the diagonal skip their compute via
    `pl.when` (their DMA still runs — Pallas fetches per the BlockSpec —
    but the MXU work, the dominant cost, is elided).
  * fp32 logits/softmax; p is cast to the V dtype for the PV matmul —
    the same precision recipe as `_fold_segment` (attention.py).

Backward: `jax.custom_vjp` — the forward runs this kernel; the backward
recomputes through `_chunked_attention`'s checkpointed scan (same
recurrence, same O(Tq·block) score memory in reverse) and takes ITS
gradient.  That keeps the hot forward on the MXU kernel while the
backward stays pure-XLA — a valid gradient of softmax attention to fp32
round-off, bit-independent of which forward produced the output.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .attention import _NEG_INF, _gqa_rep  # attention imports us lazily

__all__ = ["flash_gqa"]

_BQ = 128   # query rows per program (pre-rep); MXU/sublane aligned
_BK = 128   # K/V block; == the lane width so (.., bk) masks are one tile


def _flash_gqa_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                      causal: bool, scale: float, tq: int, tk: int,
                      bq: int, bk: int, n_k: int):
    i = pl.program_id(2)          # q block index
    j = pl.program_id(3)          # k block index

    @pl.when(j == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # causal: the whole K block is above the diagonal iff its first key
    # position exceeds the block's last query position
    compute = (j * bk <= i * bq + (bq - 1)) if causal else True

    @pl.when(compute)
    def _():
        q = q_ref[0, 0]           # (rep, bq, D)
        k = k_ref[0, 0]           # (bk, D)
        v = v_ref[0, 0]           # (bk, D)
        s = lax.dot_general(
            q, k, (((2,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # (rep, bq, bk)

        qpos = i * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        kpos = j * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        valid = kpos < tk                                  # pad keys out
        if causal:
            valid = valid & (qpos >= kpos)
        valid = valid[None]                                # (1, bq, bk)

        m_prev = m_ref[...]                                # (rep, bq, 128)
        l_prev = l_ref[...]
        s = jnp.where(valid, s, _NEG_INF)
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        # p is zeroed by the mask, not by exp(-inf): when every key so far
        # is masked m_new is still _NEG_INF and exp(s - m_new) would be 1
        p = jnp.where(valid, jnp.exp(s - m_new), 0.0)      # (rep, bq, bk)
        alpha = jnp.exp(m_prev - m_new)                    # (rep, bq, 128)
        l_ref[...] = l_prev * alpha + p.sum(axis=-1, keepdims=True)
        pv = lax.dot_general(
            p.astype(v.dtype), v, (((2,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # (rep, bq, D)
        acc_ref[...] = acc_ref[...] * alpha[..., :1] + pv
        m_ref[...] = m_new

    @pl.when(j == n_k - 1)
    def _():
        l = l_ref[..., :1]                                 # (rep, bq, 1)
        o_ref[0, 0] = (acc_ref[...] /
                       jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnums=(3, 4))
def _flash_gqa_fwd_call(q, k, v, causal: bool, interpret: bool):
    b, tq, h, d = q.shape
    tk, hkv = k.shape[1], k.shape[2]
    rep = h // hkv
    scale = 1.0 / float(d) ** 0.5

    bq, bk = min(_BQ, max(8, -(-tq // 8) * 8)), _BK
    tq_p = -(-tq // bq) * bq
    tk_p = -(-tk // bk) * bk
    d_p = max(128, -(-d // 128) * 128)

    # layouts: q -> (B, H_kv, rep, Tq, D); k/v -> (B, H_kv, Tk, D).
    # D zero-pad changes no logit (q·k unaffected) and only adds zero
    # output columns, sliced off below; pad keys are masked by position.
    qt = jnp.pad(q.reshape(b, tq, hkv, rep, d).transpose(0, 2, 3, 1, 4),
                 ((0, 0), (0, 0), (0, 0), (0, tq_p - tq), (0, d_p - d)))
    kt = jnp.pad(k.transpose(0, 2, 1, 3),
                 ((0, 0), (0, 0), (0, tk_p - tk), (0, d_p - d)))
    vt = jnp.pad(v.transpose(0, 2, 1, 3),
                 ((0, 0), (0, 0), (0, tk_p - tk), (0, d_p - d)))

    n_q, n_k = tq_p // bq, tk_p // bk
    out = pl.pallas_call(
        functools.partial(_flash_gqa_kernel, causal=causal, scale=scale,
                          tq=tq, tk=tk, bq=bq, bk=bk, n_k=n_k),
        out_shape=jax.ShapeDtypeStruct((b, hkv, rep, tq_p, d_p), q.dtype),
        grid=(b, hkv, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, 1, rep, bq, d_p),
                         lambda bi, g, i, j: (bi, g, 0, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bk, d_p),
                         lambda bi, g, i, j: (bi, g, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bk, d_p),
                         lambda bi, g, i, j: (bi, g, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, rep, bq, d_p),
                               lambda bi, g, i, j: (bi, g, 0, i, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((rep, bq, d_p), jnp.float32),
            pltpu.VMEM((rep, bq, 128), jnp.float32),
            pltpu.VMEM((rep, bq, 128), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt)
    # (B, H_kv, rep, Tq_p, D_p) -> (B, Tq, H, D)
    return out[:, :, :, :tq, :d].transpose(0, 3, 1, 2, 4).reshape(
        b, tq, h, d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def flash_gqa(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
              causal: bool = True) -> jnp.ndarray:
    """Flash attention with GQA-native unexpanded K/V, on the MXU.

    q: (B, Tq, H, D); k, v: (B, Tk, H_kv, D) with H_kv | H (kv head g
    serves q heads [g·rep, (g+1)·rep), the `grouped_query_attention`
    convention).  rep == 1 is plain MHA.  Tq/Tk/D need no alignment —
    padding is handled internally (masked, never averaged in).  Returns
    (B, Tq, H, D) in q.dtype; fp32 softmax.

    Matches `_chunked_attention` / `grouped_query_attention` to fp32
    round-off (different contraction order — not bitwise).  Runs in
    interpret mode automatically off-TPU so tests and CPU smoke runs
    exercise the same code path; `tools/pallas_check.py` proves the real
    Mosaic lowering on hardware.
    """
    _gqa_rep(q, k)  # validate H_kv | H (shared contract, attention.py)
    interpret = jax.devices()[0].platform != "tpu"
    return _flash_gqa_fwd_call(q, k, v, causal, interpret)


def _fwd(q, k, v, causal):
    return flash_gqa(q, k, v, causal), (q, k, v)


def _bwd(causal, res, g):
    q, k, v = res
    from .attention import _chunked_attention

    _, vjp = jax.vjp(
        lambda q_, k_, v_: _chunked_attention(q_, k_, v_, causal, 0, 0),
        q, k, v)
    return vjp(g)


flash_gqa.defvjp(_fwd, _bwd)
