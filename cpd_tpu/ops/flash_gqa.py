"""GQA-native Pallas flash attention — grouped queries against UNEXPANDED
(B, T, H_kv, D) K/V.

The stock `jax.experimental.pallas.ops.tpu.flash_attention` kernel takes
uniform heads only, so the GQA paths either fell back to the pure-XLA
chunked scan or re-materialized the rep× K/V expansion after the Ulysses
all_to_all (round-4 verdict weak #5) — paying in HBM exactly what
`grouped_query_attention` exists to avoid.  This kernel closes that gap:
one (B, H_kv, q-block) program holds ALL `rep` query heads of its kv head
in VMEM and runs the flash online-softmax recurrence against each K/V
block ONCE — K/V HBM traffic is 1/rep of the expanded path's, and nothing
rep-sized is ever materialized anywhere.

The reference (drcut/CPD) has no attention at all (SURVEY.md §5); this is
new-capability code, TPU-first.

Design notes:
  * grid (B, H_kv, Tq/bq, Tk/bk), K innermost; the (o, m, l) accumulator
    lives in VMEM scratch, which persists across the innermost grid steps
    (the standard Pallas TPU flash pattern).  Output is written once, at
    the final K step.
  * the q block is (rep, bq, D): logits are ONE (rep·bq, D)x(D, bk) MXU
    contraction via dot_general — no per-head loop, no reshape.
  * masking zeroes p directly (p = where(valid, exp(s - m), 0)), so pad
    keys and fully-masked rows contribute 0 to l — a fully-masked row
    yields o = 0 rather than a pad-key average (the degenerate-row edge
    the ADVICE round-4 note flags for `_chunked_attention`).
  * causal K blocks strictly above the diagonal skip their compute via
    `pl.when` (their DMA still runs — Pallas fetches per the BlockSpec —
    but the MXU work, the dominant cost, is elided).
  * fp32 logits/softmax; p is cast to the V dtype for the PV matmul —
    the same precision recipe as `_fold_segment` (attention.py).

Backward: `jax.custom_vjp` with two selectable paths (``bwd=``).  The
default "chunked" recomputes through `_chunked_attention`'s
checkpointed scan (same recurrence, O(Tq·block) score memory in
reverse) and takes ITS gradient — pure XLA, the conservative choice
while the Mosaic lowering has only interpret-mode evidence.  "pallas"
(round 5) runs the flash-backward recipe on the MXU: the forward also
emits the per-row LSE, and two kernels — dq (K innermost) and fused
dk/dv (Q innermost, the GQA group-sums folded into (rep, bq)
contractions) — re-exponentiate p = exp(s − lse) per block.  Both are
valid gradients of softmax attention to fp32 round-off, tested against
each other and the XLA AD oracle.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from ..compat import pallas as pl, pallas_tpu as pltpu

from .attention import _NEG_INF, _gqa_rep  # attention imports us lazily

__all__ = ["flash_gqa"]

_BQ = 128   # query rows per program (pre-rep); MXU/sublane aligned
_BK = 128   # K/V block; == the lane width so (.., bk) masks are one tile


def _flash_gqa_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref,
                      m_ref, l_ref, *,
                      causal: bool, scale: float, tq: int, tk: int,
                      bq: int, bk: int, n_k: int):
    i = pl.program_id(2)          # q block index
    j = pl.program_id(3)          # k block index

    @pl.when(j == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # causal: the whole K block is above the diagonal iff its first key
    # position exceeds the block's last query position
    compute = (j * bk <= i * bq + (bq - 1)) if causal else True

    @pl.when(compute)
    def _():
        q = q_ref[0, 0]           # (rep, bq, D)
        k = k_ref[0, 0]           # (bk, D)
        v = v_ref[0, 0]           # (bk, D)
        s = lax.dot_general(
            q, k, (((2,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # (rep, bq, bk)

        qpos = i * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        kpos = j * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        valid = kpos < tk                                  # pad keys out
        if causal:
            valid = valid & (qpos >= kpos)
        valid = valid[None]                                # (1, bq, bk)

        m_prev = m_ref[...]                                # (rep, bq, 128)
        l_prev = l_ref[...]
        s = jnp.where(valid, s, _NEG_INF)
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        # p is zeroed by the mask, not by exp(-inf): when every key so far
        # is masked m_new is still _NEG_INF and exp(s - m_new) would be 1
        p = jnp.where(valid, jnp.exp(s - m_new), 0.0)      # (rep, bq, bk)
        alpha = jnp.exp(m_prev - m_new)                    # (rep, bq, 128)
        l_ref[...] = l_prev * alpha + p.sum(axis=-1, keepdims=True)
        pv = lax.dot_general(
            p.astype(v.dtype), v, (((2,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # (rep, bq, D)
        acc_ref[...] = acc_ref[...] * alpha[..., :1] + pv
        m_ref[...] = m_new

    @pl.when(j == n_k - 1)
    def _():
        l = l_ref[..., :1]                                 # (rep, bq, 1)
        o_ref[0, 0] = (acc_ref[...] /
                       jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        # log-sum-exp per row, consumed by the Pallas backward (a
        # fully-masked row keeps lse ~ -1e30; its p re-exponentiates
        # to 0 there via the same validity mask)
        lse_ref[0, 0] = (m_ref[..., :1]
                         + jnp.log(jnp.maximum(l, 1e-30)))[..., 0]


def _dims(q, k):
    b, tq, h, d = q.shape
    tk, hkv = k.shape[1], k.shape[2]
    rep = h // hkv
    bq, bk = min(_BQ, max(8, -(-tq // 8) * 8)), _BK
    tq_p = -(-tq // bq) * bq
    tk_p = -(-tk // bk) * bk
    d_p = max(128, -(-d // 128) * 128)
    return b, tq, h, d, tk, hkv, rep, bq, bk, tq_p, tk_p, d_p


def _q_layout(x, hkv, rep, tq_p, d_p):
    """(B, Tq, H, D) -> padded (B, H_kv, rep, Tq_p, D_p)."""
    b, tq, _, d = x.shape
    return jnp.pad(x.reshape(b, tq, hkv, rep, d).transpose(0, 2, 3, 1, 4),
                   ((0, 0), (0, 0), (0, 0), (0, tq_p - tq),
                    (0, d_p - d)))


def _kv_layout(x, tk_p, d_p):
    """(B, Tk, H_kv, D) -> padded (B, H_kv, Tk_p, D_p)."""
    return jnp.pad(x.transpose(0, 2, 1, 3),
                   ((0, 0), (0, 0), (0, tk_p - x.shape[1]),
                    (0, d_p - x.shape[-1])))


@functools.partial(jax.jit, static_argnums=(3, 4))
def _flash_gqa_fwd_call(q, k, v, causal: bool, interpret: bool):
    """Returns ((B, Tq, H, D) out, (B, H_kv, rep, Tq_p) lse)."""
    (b, tq, h, d, tk, hkv, rep, bq, bk, tq_p, tk_p, d_p) = _dims(q, k)
    scale = 1.0 / float(d) ** 0.5
    # layouts: q -> (B, H_kv, rep, Tq, D); k/v -> (B, H_kv, Tk, D).
    # D zero-pad changes no logit (q·k unaffected) and only adds zero
    # output columns, sliced off below; pad keys are masked by position.
    qt = _q_layout(q, hkv, rep, tq_p, d_p)
    kt = _kv_layout(k, tk_p, d_p)
    vt = _kv_layout(v, tk_p, d_p)

    n_q, n_k = tq_p // bq, tk_p // bk
    out, lse = pl.pallas_call(
        functools.partial(_flash_gqa_kernel, causal=causal, scale=scale,
                          tq=tq, tk=tk, bq=bq, bk=bk, n_k=n_k),
        out_shape=(
            jax.ShapeDtypeStruct((b, hkv, rep, tq_p, d_p), q.dtype),
            jax.ShapeDtypeStruct((b, hkv, rep, tq_p), jnp.float32),
        ),
        grid=(b, hkv, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, 1, rep, bq, d_p),
                         lambda bi, g, i, j: (bi, g, 0, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bk, d_p),
                         lambda bi, g, i, j: (bi, g, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bk, d_p),
                         lambda bi, g, i, j: (bi, g, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((1, 1, rep, bq, d_p),
                         lambda bi, g, i, j: (bi, g, 0, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, rep, bq),
                         lambda bi, g, i, j: (bi, g, 0, i),
                         memory_space=pltpu.VMEM),
        ),
        scratch_shapes=[
            pltpu.VMEM((rep, bq, d_p), jnp.float32),
            pltpu.VMEM((rep, bq, 128), jnp.float32),
            pltpu.VMEM((rep, bq, 128), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt)
    # (B, H_kv, rep, Tq_p, D_p) -> (B, Tq, H, D)
    out = out[:, :, :, :tq, :d].transpose(0, 3, 1, 2, 4).reshape(
        b, tq, h, d)
    return out, lse


def _bwd_p_ds(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, i, j, *,
              causal, scale, tk, bq, bk):
    """Shared flash-backward block recompute: (p, ds) for q block i vs
    k block j — the numerically delicate mask/re-exponentiation recipe,
    ONE copy consumed by both backward kernels (only their final
    contractions differ)."""
    q = q_ref[0, 0]                                   # (rep, bq, D)
    k = k_ref[0, 0]                                   # (bk, D)
    v = v_ref[0, 0]
    do = do_ref[0, 0]                                 # (rep, bq, D)
    lse = lse_ref[0, 0][..., None]                    # (rep, bq, 1)
    delta = delta_ref[0, 0][..., None]                # (rep, bq, 1)
    s = lax.dot_general(
        q, k, (((2,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale   # (rep, bq, bk)
    qpos = i * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    kpos = j * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    valid = kpos < tk
    if causal:
        valid = valid & (qpos >= kpos)
    p = jnp.where(valid[None], jnp.exp(s - lse), 0.0)
    dp = lax.dot_general(
        do, v, (((2,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)           # (rep, bq, bk)
    ds = p * (dp - delta) * scale
    return p, ds


def _flash_gqa_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref,
                             delta_ref, dq_ref, acc_ref, *,
                             causal: bool, scale: float, tk: int,
                             bq: int, bk: int, n_k: int):
    i = pl.program_id(2)          # q block index
    j = pl.program_id(3)          # k block index (innermost)

    @pl.when(j == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    compute = (j * bk <= i * bq + (bq - 1)) if causal else True

    @pl.when(compute)
    def _():
        _, ds = _bwd_p_ds(q_ref, k_ref, v_ref, do_ref, lse_ref,
                          delta_ref, i, j, causal=causal, scale=scale,
                          tk=tk, bq=bq, bk=bk)
        k = k_ref[0, 0]
        acc_ref[...] += lax.dot_general(
            ds.astype(k.dtype), k, (((2,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # (rep, bq, D)

    @pl.when(j == n_k - 1)
    def _():
        dq_ref[0, 0] = acc_ref[...].astype(dq_ref.dtype)


def _flash_gqa_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref,
                              delta_ref, dk_ref, dv_ref, dk_acc, dv_acc,
                              *, causal: bool, scale: float, tk: int,
                              bq: int, bk: int, n_q: int):
    j = pl.program_id(2)          # k block index
    i = pl.program_id(3)          # q block index (innermost)

    @pl.when(i == 0)
    def _():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    # causal: a q block strictly above the k block contributes nothing
    compute = (i * bq + (bq - 1) >= j * bk) if causal else True

    @pl.when(compute)
    def _():
        p, ds = _bwd_p_ds(q_ref, k_ref, v_ref, do_ref, lse_ref,
                          delta_ref, i, j, causal=causal, scale=scale,
                          tk=tk, bq=bq, bk=bk)
        q = q_ref[0, 0]
        do = do_ref[0, 0]
        # dv += Σ_rep p^T do ; dk += Σ_rep ds^T q  (one contraction each
        # over the (rep, bq) axes — the GQA group sums fall out of the
        # dot_general, nothing rep-sized is materialized)
        dv_acc[...] += lax.dot_general(
            p.astype(do.dtype), do, (((0, 1), (0, 1)), ((), ())),
            preferred_element_type=jnp.float32)           # (bk, D)
        dk_acc[...] += lax.dot_general(
            ds.astype(q.dtype), q, (((0, 1), (0, 1)), ((), ())),
            preferred_element_type=jnp.float32)           # (bk, D)

    @pl.when(i == n_q - 1)
    def _():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


@functools.partial(jax.jit, static_argnums=(6, 7))
def _flash_gqa_bwd_call(q, k, v, out, lse, do, causal: bool,
                        interpret: bool):
    """Pallas flash backward: (dq, dk, dv) in the input shapes/dtypes."""
    (b, tq, h, d, tk, hkv, rep, bq, bk, tq_p, tk_p, d_p) = _dims(q, k)
    scale = 1.0 / float(d) ** 0.5
    qt = _q_layout(q, hkv, rep, tq_p, d_p)
    kt = _kv_layout(k, tk_p, d_p)
    vt = _kv_layout(v, tk_p, d_p)
    dot = _q_layout(do, hkv, rep, tq_p, d_p)
    ot = _q_layout(out, hkv, rep, tq_p, d_p)
    # delta_i = Σ_d dO_id · O_id (the flash-backward row constant); pad
    # rows are all-zero -> delta 0
    delta = (dot.astype(jnp.float32) * ot.astype(jnp.float32)).sum(-1)

    n_q, n_k = tq_p // bq, tk_p // bk
    qspec = pl.BlockSpec((1, 1, rep, bq, d_p),
                         lambda bi, g, i, j: (bi, g, 0, i, 0),
                         memory_space=pltpu.VMEM)
    rowspec = pl.BlockSpec((1, 1, rep, bq),
                           lambda bi, g, i, j: (bi, g, 0, i),
                           memory_space=pltpu.VMEM)
    kvspec = pl.BlockSpec((1, 1, bk, d_p),
                          lambda bi, g, i, j: (bi, g, j, 0),
                          memory_space=pltpu.VMEM)
    dq = pl.pallas_call(
        functools.partial(_flash_gqa_bwd_dq_kernel, causal=causal,
                          scale=scale, tk=tk, bq=bq, bk=bk, n_k=n_k),
        out_shape=jax.ShapeDtypeStruct((b, hkv, rep, tq_p, d_p), q.dtype),
        grid=(b, hkv, n_q, n_k),
        in_specs=[qspec, kvspec, kvspec, qspec, rowspec, rowspec],
        out_specs=qspec,
        scratch_shapes=[pltpu.VMEM((rep, bq, d_p), jnp.float32)],
        interpret=interpret,
    )(qt, kt, vt, dot, lse, delta)

    # k-major grid: the q-block index is innermost for the accumulators
    qspec_kmaj = pl.BlockSpec((1, 1, rep, bq, d_p),
                              lambda bi, g, j, i: (bi, g, 0, i, 0),
                              memory_space=pltpu.VMEM)
    rowspec_kmaj = pl.BlockSpec((1, 1, rep, bq),
                                lambda bi, g, j, i: (bi, g, 0, i),
                                memory_space=pltpu.VMEM)
    kvspec_kmaj = pl.BlockSpec((1, 1, bk, d_p),
                               lambda bi, g, j, i: (bi, g, j, 0),
                               memory_space=pltpu.VMEM)
    dk, dv = pl.pallas_call(
        functools.partial(_flash_gqa_bwd_dkv_kernel, causal=causal,
                          scale=scale, tk=tk, bq=bq, bk=bk, n_q=n_q),
        out_shape=(
            jax.ShapeDtypeStruct((b, hkv, tk_p, d_p), k.dtype),
            jax.ShapeDtypeStruct((b, hkv, tk_p, d_p), v.dtype),
        ),
        grid=(b, hkv, n_k, n_q),
        in_specs=[qspec_kmaj, kvspec_kmaj, kvspec_kmaj, qspec_kmaj,
                  rowspec_kmaj, rowspec_kmaj],
        out_specs=(kvspec_kmaj, kvspec_kmaj),
        scratch_shapes=[pltpu.VMEM((bk, d_p), jnp.float32),
                        pltpu.VMEM((bk, d_p), jnp.float32)],
        interpret=interpret,
    )(qt, kt, vt, dot, lse, delta)

    dq = dq[:, :, :, :tq, :d].transpose(0, 3, 1, 2, 4).reshape(
        b, tq, h, d)
    dk = dk[:, :, :tk, :d].transpose(0, 2, 1, 3)
    dv = dv[:, :, :tk, :d].transpose(0, 2, 1, 3)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_gqa(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
              causal: bool = True, bwd: str = "chunked") -> jnp.ndarray:
    """Flash attention with GQA-native unexpanded K/V, on the MXU.

    q: (B, Tq, H, D); k, v: (B, Tk, H_kv, D) with H_kv | H (kv head g
    serves q heads [g·rep, (g+1)·rep), the `grouped_query_attention`
    convention).  rep == 1 is plain MHA.  Tq/Tk/D need no alignment —
    padding is handled internally (masked, never averaged in).  Returns
    (B, Tq, H, D) in q.dtype; fp32 softmax.

    Matches `_chunked_attention` / `grouped_query_attention` to fp32
    round-off (different contraction order — not bitwise).  Runs in
    interpret mode automatically off-TPU so tests and CPU smoke runs
    exercise the same code path; `tools/pallas_check.py` proves the real
    Mosaic lowering on hardware.

    ``bwd`` selects the gradient path: "chunked" (default) recomputes
    through `_chunked_attention`'s checkpointed scan — pure XLA, the
    conservative choice while the Pallas kernels' Mosaic lowering has
    only interpret-mode evidence; "pallas" runs the flash-backward
    recipe as two Pallas kernels (dq with K innermost; fused dk/dv with
    Q innermost, the GQA group-sums folded into the (rep, bq)
    contractions) against the forward's saved LSE — O(1) extra memory,
    the full fwd+bwd on the MXU.  Both are valid gradients of softmax
    attention to fp32 round-off and are tested against each other and
    the XLA AD oracle; pallas_check stages the "pallas" path for
    hardware validation.
    """
    _validate_call(q, k, bwd)
    interpret = jax.devices()[0].platform != "tpu"
    out, _ = _flash_gqa_fwd_call(q, k, v, causal, interpret)
    return out


def _validate_call(q, k, bwd):
    # shared by the primal AND _fwd: custom_vjp bypasses the primal
    # under jax.grad, so validation only there would silently accept a
    # bad bwd string / head ratio in exactly the differentiated case
    _gqa_rep(q, k)  # H_kv | H (shared contract, attention.py)
    if bwd not in ("chunked", "pallas"):
        raise ValueError(f"unknown bwd {bwd!r}; 'chunked' or 'pallas'")


def _fwd(q, k, v, causal, bwd):
    _validate_call(q, k, bwd)
    interpret = jax.devices()[0].platform != "tpu"
    out, lse = _flash_gqa_fwd_call(q, k, v, causal, interpret)
    res = (q, k, v, out, lse) if bwd == "pallas" else (q, k, v)
    return out, res


def _bwd(causal, bwd, res, g):
    if bwd == "pallas":
        q, k, v, out, lse = res
        interpret = jax.devices()[0].platform != "tpu"
        return _flash_gqa_bwd_call(q, k, v, out, lse, g, causal,
                                   interpret)
    q, k, v = res
    from .attention import _chunked_attention

    _, vjp = jax.vjp(
        lambda q_, k_, v_: _chunked_attention(q_, k_, v_, causal, 0, 0),
        q, k, v)
    return vjp(g)


flash_gqa.defvjp(_fwd, _bwd)
