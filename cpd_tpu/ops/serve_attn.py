"""Fused gather→unpack→attention Pallas kernel for the paged eXmY KV
cache — the serving hot path as ONE pass (ISSUE 18 tentpole, leg b).

The XLA decode path reads the cache in three materialized stages per
layer: page-row gather (``pool[layer][page_rows]``), eXmY unpack
(`kvcache.unpack_kv` — incl. the blocked sidecar), then the masked GQA
contraction (`serve.model._paged_attention`).  Each stage round-trips
the whole (S, max_pages · page_size, H_kv, D) capacity window through
HBM.  This kernel runs all three inside one `pallas_call`, and — the
`digest_rows_pallas` precedent (PR 12) — emits the per-gathered-page
Fletcher digest as a SECOND output of the same pass, so the read-path
integrity check costs no extra traversal of the page bytes.

Bitwise contract: the kernel body calls the EXACT unpack and attention
functions the XLA composition uses — they arrive as closures
(``unpack_fn`` / ``attend_fn``) from `serve/model.py`, so there is one
implementation, not a copy that can drift — and the digest is
`parallel.integrity.wire_digest` itself.  tests/test_serve_tp.py gates
kernel == XLA bitwise in interpret mode over GQA page shapes including
odd tail pages × odd blocks; `tools/pallas_check.py` check 8 re-runs
the gate compiled on real chips.

Composition with tensor parallelism: the caller hands in a SHARD-LOCAL
pool slice (legacy tp=1 layout) with the shard-view config's unpack
closure — the kernel is shard-oblivious, exactly like every other
kvcache function.

The fp32 oracle cache (``raw=True``) keeps the XLA path: fusing a
no-codec gather buys nothing and the oracle must stay the reference,
so `make_decode_step` rejects ``fused`` + ``raw``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..compat import pallas as pl  # noqa: F401  (kernel home: ..compat)
from ..parallel.integrity import wire_digest

__all__ = ["fused_gather_attention"]


def fused_gather_attention(pool_layer: jnp.ndarray,
                           q: jnp.ndarray,
                           page_rows: jnp.ndarray,
                           positions: jnp.ndarray,
                           last_pos: jnp.ndarray,
                           *, page_size: int,
                           unpack_fn, attend_fn,
                           interpret: bool = False) -> tuple:
    """One decode batch's paged attention in a single Pallas pass.

    pool_layer: ONE layer's page pool slice — (n_pages, 2, page_size,
    H_kv, D, WB) uint8 packed, or (n_pages, 2, page_size, row_bytes)
    blocked; q: (S, T, H, D) fp32 queries (T == 1 on the decode path);
    page_rows: (S, max_pages) int32 trash-padded page tables;
    positions: (S, T) int32 query positions; last_pos: (S,) newest live
    position per slot.

    ``unpack_fn``: gathered (S, MP, 2, page, ...) wire bytes ->
    (S, MP, 2, page, H_kv, D) fp32 — `kvcache.unpack_kv` under the
    caller's config.  ``attend_fn``: the masked GQA contraction —
    `serve.model._paged_attention`.

    Returns ``(attn, page_digests)``: attn (S, T, H, D) fp32 — bitwise
    what the XLA composition produces — and page_digests (S, max_pages)
    uint32, `wire_digest` of every gathered page's bytes as READ, for
    the engine's read-path integrity verdict."""
    s_count, max_pages = page_rows.shape
    t = q.shape[1]
    h, d = q.shape[2], q.shape[3]

    kernel = functools.partial(
        _fused_kernel, s_count=s_count, max_pages=max_pages,
        page_size=page_size, unpack_fn=unpack_fn, attend_fn=attend_fn)
    return pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((s_count, t, h, d), jnp.float32),
            jax.ShapeDtypeStruct((s_count, max_pages), jnp.uint32),
        ),
        interpret=interpret,
    )(pool_layer, page_rows, q, positions, last_pos)


def _fused_kernel(pool_ref, rows_ref, q_ref, pos_ref, last_ref,
                  attn_ref, dig_ref, *, s_count: int, max_pages: int,
                  page_size: int, unpack_fn, attend_fn):
    """Kernel body: static (slot, page) gather loop, digest, unpack,
    attend — one traversal of the gathered bytes."""
    pool = pool_ref[:]
    rows = rows_ref[:]
    # page-row gather: the (S, MP) loop is static (jit-stable shapes);
    # each row index is a traced scalar from the page table
    kv = jnp.stack([
        jnp.stack([lax.dynamic_index_in_dim(pool, rows[s, p], axis=0,
                                            keepdims=False)
                   for p in range(max_pages)])
        for s in range(s_count)])            # (S, MP, 2, page, ...)
    # the read-path digest rides the pass: hash the bytes AS GATHERED,
    # before any decode touches them — what the engine compares against
    # the stored per-page digests
    dig_ref[:] = jax.vmap(jax.vmap(wire_digest))(kv)
    un = unpack_fn(kv)                       # (S, MP, 2, page, H, D)
    t_cap = max_pages * page_size
    hkv, hd = un.shape[-2], un.shape[-1]
    k = un[:, :, 0].reshape(s_count, t_cap, hkv, hd)
    v = un[:, :, 1].reshape(s_count, t_cap, hkv, hd)
    attn_ref[:] = attend_fn(q_ref[:], k, v, pos_ref[:], last_ref[:])
