"""Native kernel layer (Pallas/Mosaic) — the TPU analog of the reference's
CUDA extension (CPDtorch/quant/quant_cuda/).  See also quant/ for the XLA
implementations these are bit-identical to."""

from .quantize import quantize_pallas, quantize_pallas_sr
from .qgemm import qgemm_pallas
from .flash_gqa import flash_gqa
from .serve_attn import fused_gather_attention

__all__ = ["quantize_pallas", "quantize_pallas_sr", "qgemm_pallas",
           "flash_gqa", "fused_gather_attention"]
