"""Optimizers with torch-faithful semantics: SGD(+Nesterov) and LARS.

The reference uses torch.optim.SGD over fp32 master params
(example/ResNet18/tools/mix.py:94-96, example/DavidNet/dawn.py:73-79,
example/ResNet50/main.py:123-131) and a hand-written LARS update
(mix.py:297-310).  optax's built-in `sgd` scales the momentum buffer
differently from torch (torch accumulates raw grads in the buffer and
multiplies by lr at apply time; optax's trace folds lr in), which changes
trajectories when lr varies per step — so `sgd` here reproduces torch's
update rule exactly:

    buf   = momentum * buf + (g + wd * w)                 # torch sgd
    step  = g + momentum * buf  (nesterov)  |  buf
    w    -= lr * step

and `lars` reproduces mix.py:297-310 exactly:

    local_lr = ||w|| / (||g|| + wd * ||w||) * 0.001
    buf      = momentum * buf + lr * local_lr * (g + wd * w)
    w       -= buf

Both take a `Schedule` (step -> lr) so the whole update stays inside jit.
Master-weight handling (mix.py:53-63,292-294,313-314) is structural here:
params are always fp32; bf16 is a compute dtype inside the model, so the
"master copy" is just the params pytree itself.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

__all__ = ["sgd", "lars", "quant_sgd", "make_optimizer", "shampoo_lite",
           "ShampooLite", "ShampooLiteState"]


class TorchSGDState(NamedTuple):
    step: jnp.ndarray
    momentum_buf: optax.Updates


def sgd(schedule: Callable, momentum: float = 0.9, weight_decay: float = 0.0,
        nesterov: bool = False,
        wd_mask: Optional[Callable] = None) -> optax.GradientTransformation:
    """torch.optim.SGD-semantics transformation.

    `wd_mask(params)` -> pytree of bools selecting which leaves get weight
    decay — the BN-params-without-wd grouping of main.py:123-131.
    Returned updates are the *negative* delta (optax convention:
    new_p = p + update)."""

    def init(params):
        return TorchSGDState(jnp.zeros([], jnp.int32),
                             jax.tree.map(jnp.zeros_like, params))

    def update(grads, state, params):
        if params is None:
            raise ValueError("sgd requires params")
        lr = schedule(state.step)
        mask = (wd_mask(params) if wd_mask is not None
                else jax.tree.map(lambda _: True, params))

        def one(g, w, buf, use_wd):
            d = g + (weight_decay * w if (weight_decay and use_wd) else 0.0)
            new_buf = momentum * buf + d
            step_dir = d + momentum * new_buf if nesterov else new_buf
            return -lr * step_dir, new_buf

        flat = jax.tree.map(one, grads, params, state.momentum_buf, mask)
        updates, bufs = _unzip(flat, 2)
        return updates, TorchSGDState(state.step + 1, bufs)

    return optax.GradientTransformation(init, update)


class NormBasedTransformation(optax.GradientTransformation):
    """GradientTransformation whose update needs *global* parameter/gradient
    norms (LARS trust ratios).  Shard-local steppers (train/lm.py) check this
    flag and refuse, instead of silently computing per-shard norms."""
    norm_based = True


def lars(schedule: Callable, momentum: float = 0.9,
         weight_decay: float = 0.0, coefficient: float = 0.001,
         ) -> optax.GradientTransformation:
    """The reference's manual LARS (mix.py:297-310), exactly — including its
    quirks: trust ratio computed on the *un-decayed* gradient norm, the fixed
    0.001 coefficient, and lr folded into the momentum buffer (unlike torch
    SGD).  Zero-norm params fall back to local_lr = coefficient·0 = 0 guard
    via the epsilon-free reference formula (||g||+wd·||w|| in the
    denominator; all-zero grads give local_lr = 1/wd... matching reference
    float math)."""

    def init(params):
        return TorchSGDState(jnp.zeros([], jnp.int32),
                             jax.tree.map(jnp.zeros_like, params))

    def update(grads, state, params):
        if params is None:
            raise ValueError("lars requires params")
        lr = schedule(state.step)

        def one(g, w, buf):
            w_norm = jnp.linalg.norm(w.reshape(-1))
            g_norm = jnp.linalg.norm(g.reshape(-1))
            local_lr = w_norm / (g_norm + weight_decay * w_norm) * coefficient
            new_buf = momentum * buf + lr * local_lr * (g + weight_decay * w)
            return -new_buf, new_buf

        flat = jax.tree.map(one, grads, params, state.momentum_buf)
        updates, bufs = _unzip(flat, 2)
        return updates, TorchSGDState(state.step + 1, bufs)

    return NormBasedTransformation(init, update)


class QuantSGDState(NamedTuple):
    step: jnp.ndarray
    momentum_buf: optax.Updates
    comp: optax.Updates    # Kahan residuals; () (leafless) w/o use_kahan
    key: optax.Updates = ()  # PRNG key iff rounding='stochastic', else ()


def _unzip(flat, n):
    """Split a pytree of n-tuples into n pytrees (shared by the
    optimizers here)."""
    is_t = lambda t: isinstance(t, tuple)  # noqa: E731
    return tuple(jax.tree.map(lambda t: t[i], flat, is_leaf=is_t)
                 for i in range(n))


def quant_sgd(schedule: Callable, momentum: float = 0.9,
              weight_decay: float = 0.0, exp: int = 8, man: int = 23,
              use_kahan: bool = False, nesterov: bool = False,
              wd_mask: Optional[Callable] = None,
              rounding: str = "nearest", seed: int = 0,
              ) -> optax.GradientTransformation:
    """torch-SGD semantics with the momentum buffer held in eXmY.

    New capability beyond the reference, built from its own numerics
    doctrine: the reference quantizes gradients around the all-reduce
    (dist_util.py:35-37) and keeps every Kahan intermediate quantized
    (dist_util.py:82-88); this applies the same discipline to the
    *optimizer state* — the momentum buffer lives in the (exp, man)
    value set, every intermediate of its update is re-quantized, and an
    optional quantized Kahan residual recovers the small gradients that
    a naive low-precision accumulation would flush (the classic 8-bit-
    optimizer memory/accuracy trade, emulated exactly like the rest of
    CPD).  Params stay fp32 masters.

    With (8,23) the cast is the identity; use_kahan=False then walks
    `sgd`'s trajectory bitwise.  use_kahan=True still runs the Kahan
    arithmetic (fp32 compensation changes rounding, so only ~ulp-close
    to `sgd`) — the same shortcut asymmetry the reference's fp32 Kahan
    all-reduce has (dist_util.py:55-59 vs :72-89, preserved in
    parallel/reduction.py).

        d    = g + wd*w
        s    = Q(momentum * buf)
        naive:  buf' = Q(s + d)
        kahan:  y = Q(d - Q(momentum*c));  buf' = Q(s + y)
                c' = Q(Q(buf' - s) - y)
        step = d + momentum*buf' (nesterov) | buf'
        w   -= lr * step

    rounding='stochastic' (beyond-reference, Gupta et al. 2015's recipe)
    replaces every eXmY cast in the buffer update with the unbiased
    stochastic cast: small contributions smaller than ulp/2 then survive
    *in expectation* instead of being flushed by RTNE — the standard cure
    for low-precision update stagnation.  Bits are drawn per (step, leaf,
    cast-site) from a PRNG key carried in the optimizer state, so the
    trajectory is deterministic given `seed`.  With rounding='nearest'
    (default) the state tree is unchanged from before (key=() has no
    leaves) and the trajectory is bit-identical to the documented RTNE
    semantics above.
    """
    if rounding not in ("nearest", "stochastic"):
        raise ValueError(f"unknown rounding mode: {rounding!r}")
    stochastic = rounding == "stochastic" and (exp, man) != (8, 23)
    if (exp, man) == (8, 23):
        def q(x, _k=None):
            return x
    elif stochastic:
        from ..quant.numerics import cast_to_format_sr

        def q(x, k):
            return cast_to_format_sr(x, exp, man, k)
    else:
        from ..quant.numerics import cast_to_format

        def q(x, _k=None):
            return cast_to_format(x, exp, man)

    def init(params):
        # no dead residual tree without Kahan: () has no leaves, so the
        # quantized-optimizer state stays one buffer per param
        comp = (jax.tree.map(jnp.zeros_like, params) if use_kahan else ())
        key = jax.random.PRNGKey(seed) if stochastic else ()
        return QuantSGDState(jnp.zeros([], jnp.int32),
                             jax.tree.map(jnp.zeros_like, params), comp, key)

    def update(grads, state, params):
        if params is None:
            raise ValueError("quant_sgd requires params")
        lr = schedule(state.step)
        mask = (wd_mask(params) if wd_mask is not None
                else jax.tree.map(lambda _: True, params))

        if stochastic:
            # one independent subkey per leaf for this step; each cast
            # site inside the leaf update folds in its own site index
            step_key = jax.random.fold_in(state.key, state.step)
            treedef = jax.tree.structure(params)
            leaf_keys = jax.tree.unflatten(
                treedef, list(jax.random.split(step_key,
                                               treedef.num_leaves)))
        else:
            # dummy leaves (ignored by q) so all mapped trees share the
            # params structure; None would be an empty pytree node
            leaf_keys = jax.tree.map(lambda _: 0, params)
        site = (lambda k, i: jax.random.fold_in(k, i)) if stochastic \
            else (lambda k, i: None)

        def decayed(g, w, use_wd):
            return g + (weight_decay * w
                        if (weight_decay and use_wd) else 0.0)

        def step_dir(d, new_buf):
            return d + momentum * new_buf if nesterov else new_buf

        if use_kahan:
            def one(g, w, buf, c, k, use_wd):
                d = decayed(g, w, use_wd)
                s = q(momentum * buf, site(k, 0))
                y = q(d - q(momentum * c, site(k, 1)), site(k, 2))
                new_buf = q(s + y, site(k, 3))
                new_c = q(q(new_buf - s, site(k, 4)) - y, site(k, 5))
                return -lr * step_dir(d, new_buf), new_buf, new_c

            flat = jax.tree.map(one, grads, params, state.momentum_buf,
                                state.comp, leaf_keys, mask)
            updates, bufs, comp = _unzip(flat, 3)
        else:
            def one(g, w, buf, k, use_wd):
                d = decayed(g, w, use_wd)
                new_buf = q(q(momentum * buf, site(k, 0)) + d, site(k, 1))
                return -lr * step_dir(d, new_buf), new_buf

            flat = jax.tree.map(one, grads, params, state.momentum_buf,
                                leaf_keys, mask)
            updates, bufs = _unzip(flat, 2)
            comp = ()
        return updates, QuantSGDState(state.step + 1, bufs, comp, state.key)

    return optax.GradientTransformation(init, update)


class ShampooLiteState(NamedTuple):
    step: jnp.ndarray
    momentum_buf: optax.Updates
    stats_l: tuple      # per-precondable-leaf (p, p) left Gram sums
    stats_r: tuple      # per-precondable-leaf (q, q) right Gram sums


class ShampooLite:
    """Shampoo-lite: a second-order optimizer riding the quantized ring
    (ISSUE 15 tentpole leg c).

    Per 2D-reshapeable leaf ``G`` (collapsed ``(prod(shape[:-1]),
    shape[-1])``), the update keeps running Gram statistics

        L += G_r G_r^T,   R += G_r^T G_r      (summed over replicas r)

    and preconditions the REDUCED gradient as ``L^{-1/4} G R^{-1/4}``
    (`linalg.eigen.inv_root_psd` — fp32 eigh + sqrt chain, never
    ``pow``), grafted back to the raw gradient's norm so the stats'
    scale cancels; 1D / oversized leaves fall back to the plain
    direction.  Momentum is the torch-SGD rule (`sgd`), every product
    fenced through `linalg.eigen` ``fence32`` so the trajectory is
    cross-program bitwise-deterministic (the FMA-contraction class the
    linalg oracle gates found).

    The quantized substrate, exactly per the issue:

    * every Gram accumulation runs through `qgemm`'s eXmY Kahan
      accumulator at ``(stat_exp, stat_man)`` — the statistics live in
      that format's value set (running sums re-cast after every add);
    * the CROSS-REPLICA statistics reduction rides the quantized ring
      (``stat_mode="ring"``: `ring_quantized_sum` of the concatenated
      stats vector — the same transport, rotation order and oracle as
      the gradient wire; ``"gather"``: all_gather + the rank-ordered
      scan), while the gradient itself keeps the step's own
      `sum_gradients` composition (``reduce_in_update=True`` hands
      this updater the rank-LOCAL grads plus the step's quant kwargs,
      exactly like the ZeRO updaters);
    * the preconditioner application also runs through `qgemm` at
      (8, 23) — the Kahan scan is the one cross-program-stable
      accumulator in the repo, so no raw ``dot_general`` sits on the
      bitwise-gated path.

    `oracle_update` replays one update on a single device from the
    stacked per-replica grads — the replicated fp32-statistics
    monolith the acceptance gate compares against at (8, 23).
    """

    requires_reduce_in_update = True

    def __init__(self, schedule: Callable, world: int,
                 momentum: float = 0.9, weight_decay: float = 0.0, *,
                 stat_exp: int = 8, stat_man: int = 23,
                 stat_mode: str = "ring", stat_kahan: bool = False,
                 eps: float = 1e-6, max_precond_dim: int = 256,
                 wd_mask: Optional[Callable] = None,
                 axis_name: str = "dp"):
        if stat_mode not in ("ring", "gather"):
            raise ValueError(f"unknown stat_mode {stat_mode!r} "
                             f"(ring | gather)")
        if stat_mode == "ring" and stat_man < 2:
            raise ValueError(
                f"stat_mode='ring' needs a packable statistics format "
                f"(man >= 2), got e{stat_exp}m{stat_man}")
        self.schedule = schedule
        self.world = int(world)
        self.momentum = momentum
        self.weight_decay = weight_decay
        self.stat_exp, self.stat_man = stat_exp, stat_man
        self.stat_mode = stat_mode
        self.stat_kahan = stat_kahan
        self.eps = eps
        self.max_precond_dim = max_precond_dim
        self.wd_mask = wd_mask
        self.axis_name = axis_name

    # -- leaf classification ---------------------------------------------

    def _precondable(self, shape) -> bool:
        if len(shape) < 2:
            return False
        p = 1
        for s in shape[:-1]:
            p *= s
        q = shape[-1]
        return (1 < p <= self.max_precond_dim
                and 1 < q <= self.max_precond_dim)

    @staticmethod
    def _mat2d(g):
        return g.reshape(-1, g.shape[-1])

    # -- state ------------------------------------------------------------

    def init(self, params) -> ShampooLiteState:
        leaves = jax.tree_util.tree_leaves(params)
        ls, rs = [], []
        for g in leaves:
            if self._precondable(g.shape):
                g2 = self._mat2d(g)
                ls.append(jnp.zeros((g2.shape[0], g2.shape[0]),
                                    jnp.float32))
                rs.append(jnp.zeros((g2.shape[1], g2.shape[1]),
                                    jnp.float32))
        return ShampooLiteState(
            jnp.zeros([], jnp.int32),
            jax.tree.map(jnp.zeros_like, params), tuple(ls), tuple(rs))

    def mesh_layout(self, state, mesh):
        """CLI hook mirroring the ZeRO updaters': lay the TrainState out
        replicated (stats are replicated — they are reduced, like the
        grads) and return the `make_train_step` kwargs."""
        from ..parallel.dist import replicate
        return replicate(state, mesh), {"update_fn": self.update_fn,
                                        "reduce_in_update": True}

    def export_state(self, state):
        """Checkpoint hook (`to_ckpt`): the state is replicated plain
        arrays — nothing to re-layout."""
        return state

    def portable_template(self, state):
        return state

    # -- the quantized Gram statistics -----------------------------------

    def _local_gram_flat(self, local_grads) -> jnp.ndarray:
        """Concatenated flat (L, R) Gram contributions of THIS replica's
        local grads, every GEMM through the eXmY Kahan accumulator."""
        from ..quant.quant_function import qgemm
        parts = []
        for g in jax.tree_util.tree_leaves(local_grads):
            if not self._precondable(g.shape):
                continue
            g2 = self._mat2d(jnp.asarray(g, jnp.float32))
            parts.append(qgemm(g2, g2.T, exp=self.stat_exp,
                               man=self.stat_man).reshape(-1))
            parts.append(qgemm(g2.T, g2, exp=self.stat_exp,
                               man=self.stat_man).reshape(-1))
        if not parts:
            return jnp.zeros((0,), jnp.float32)
        return jnp.concatenate(parts)

    def _split_gram_flat(self, flat, template):
        """Invert `_local_gram_flat`'s concatenation: (ls, rs) tuples
        shaped like the state's stats."""
        ls, rs = [], []
        off = 0
        for g in jax.tree_util.tree_leaves(template):
            if not self._precondable(g.shape):
                continue
            g2s = self._mat2d(g).shape
            nl, nr = g2s[0] * g2s[0], g2s[1] * g2s[1]
            ls.append(flat[off:off + nl].reshape(g2s[0], g2s[0]))
            off += nl
            rs.append(flat[off:off + nr].reshape(g2s[1], g2s[1]))
            off += nr
        return tuple(ls), tuple(rs)

    def _reduce_stats(self, flat, axis_name):
        """The cross-replica statistics reduction — the quantized ring
        (or gather + ordered scan), at the statistics format."""
        from jax import lax

        from ..parallel.reduction import quantized_sum
        from ..parallel.ring import ring_quantized_sum
        if flat.shape[0] == 0:
            return flat
        if self.stat_mode == "ring":
            return ring_quantized_sum(
                flat, axis_name, self.stat_exp, self.stat_man,
                use_kahan=self.stat_kahan, world=self.world)
        stacked = lax.all_gather(flat, axis_name, axis=0, tiled=False)
        return quantized_sum(stacked, self.stat_exp, self.stat_man,
                             use_kahan=self.stat_kahan)

    def _oracle_reduce_stats(self, stacked_flat):
        """Single-device twin of `_reduce_stats` (ring_oracle_sum /
        the same ordered scan)."""
        from ..parallel.reduction import quantized_sum
        from ..parallel.ring import ring_oracle_sum
        if stacked_flat.shape[-1] == 0:
            return stacked_flat[0]
        if self.stat_mode == "ring":
            return ring_oracle_sum(stacked_flat, self.stat_exp,
                                   self.stat_man,
                                   use_kahan=self.stat_kahan)
        return quantized_sum(stacked_flat, self.stat_exp, self.stat_man,
                             use_kahan=self.stat_kahan)

    # -- the shared apply core -------------------------------------------

    def _stat_cast(self, x):
        from ..quant.numerics import cast_to_format
        return cast_to_format(x, self.stat_exp, self.stat_man)

    def _apply(self, reduced, state, stats_sum_flat):
        """One optimizer step from the REDUCED grads + REDUCED Gram
        contributions — pure replicated math, shared bit-for-bit by the
        distributed update and the monolith oracle."""
        from ..linalg.eigen import det_norm, fence32, inv_root_psd
        from ..quant.quant_function import qgemm
        opt: ShampooLiteState = state.opt_state
        params = state.params
        lr = self.schedule(opt.step)
        mask = (self.wd_mask(params) if self.wd_mask is not None
                else jax.tree.map(lambda _: True, params))

        new_l, new_r = self._split_gram_flat(stats_sum_flat, params)
        # running sums re-cast to the statistics format after every add
        # (the value set the wire carried; identity+canonicalize at
        # (8, 23))
        upd_l = tuple(self._stat_cast(a + b)
                      for a, b in zip(opt.stats_l, new_l))
        upd_r = tuple(self._stat_cast(a + b)
                      for a, b in zip(opt.stats_r, new_r))

        g_leaves = jax.tree_util.tree_leaves(reduced)
        p_leaves = jax.tree_util.tree_leaves(params)
        b_leaves = jax.tree_util.tree_leaves(opt.momentum_buf)
        m_leaves = jax.tree_util.tree_leaves(mask)
        treedef = jax.tree_util.tree_structure(params)

        new_p, new_b = [], []
        si = 0
        for g, w, buf, use_wd in zip(g_leaves, p_leaves, b_leaves,
                                     m_leaves):
            g = jnp.asarray(g, jnp.float32)
            if self._precondable(g.shape):
                l, r = upd_l[si], upd_r[si]
                si += 1
                g2 = self._mat2d(g)
                pl = inv_root_psd(l, p=4, eps=self.eps)
                pr = inv_root_psd(r, p=4, eps=self.eps)
                # preconditioner application through the (8, 23) Kahan
                # gemm — the cross-program-stable accumulator (no raw
                # dot_general on the bitwise-gated path)
                pg = qgemm(qgemm(pl, g2), pr)
                gn, pn = det_norm(g2), det_norm(pg)
                scale = jnp.where(pn > 0, gn / pn, jnp.float32(1.0))
                d = fence32(pg * scale).reshape(g.shape)
            else:
                d = g
            if self.weight_decay:
                d = d + fence32(
                    jnp.float32(self.weight_decay) * w) * jnp.where(
                        use_wd, jnp.float32(1.0), jnp.float32(0.0))
            nb = fence32(jnp.float32(self.momentum) * buf) + d
            new_b.append(nb)
            new_p.append(w - fence32(lr * nb))
        new_state = ShampooLiteState(
            opt.step + 1,
            jax.tree_util.tree_unflatten(treedef, new_b), upd_l, upd_r)
        return jax.tree_util.tree_unflatten(treedef, new_p), new_state

    # -- entry points -----------------------------------------------------

    def update_fn(self, local_grads, state, axis_name: str, **quant_kw):
        """`make_train_step(update_fn=..., reduce_in_update=True)` hook:
        reduces the grads with the step's own `sum_gradients`
        composition, reduces the local Gram contributions over the
        quantized ring, applies the shared core.  Returns
        ``(new_params, new_opt_state)``."""
        from ..parallel.dist import sum_gradients
        if not quant_kw:
            raise ValueError(
                "ShampooLite folds the collective into the update: "
                "build the step with make_train_step(..., "
                "reduce_in_update=True)")
        reduced = sum_gradients(local_grads, axis_name, **quant_kw)
        stats = self._reduce_stats(self._local_gram_flat(local_grads),
                                   axis_name)
        return self._apply(reduced, state, stats)

    # the gradient-reduce coordinates oracle_update can replay.  It
    # models sum_gradients' FAITHFUL per-leaf gather+scan only —
    # accepting (and ignoring) ring/fast/SR/APS/blocked kwargs would
    # make the "bitwise == monolith" gate silently compare against an
    # oracle that does not model the run, so anything else is rejected.
    _ORACLE_KW = {"grad_exp", "grad_man", "use_kahan", "mode"}

    def oracle_update(self, stacked_grads, state, **quant_kw):
        """The replicated fp32-statistics monolith oracle: one device,
        stacked per-replica local grads ``(W, *leaf)`` per leaf.  The
        gradient reduce replays the step's faithful composition
        (`quantized_sum` per leaf — `sum_gradients`' gather path), the
        stats reduce replays `_reduce_stats`' transport oracle, and
        `_apply` is shared — at (8, 23)/(8, 23) the distributed step
        must match BITWISE.  Kwargs the replay cannot model (ring/fast
        transport, SR keys, APS, block scaling, bucketing) are a loud
        error, never a silently-wrong oracle."""
        from ..parallel.reduction import quantized_sum
        unsupported = set(quant_kw) - self._ORACLE_KW
        if unsupported or quant_kw.get("mode", "faithful") != "faithful":
            raise ValueError(
                f"oracle_update replays only the faithful RTNE gather "
                f"composition (grad_exp/grad_man/use_kahan); got "
                f"unsupported kwargs "
                f"{sorted(unsupported) or [('mode', quant_kw['mode'])]}"
                f" — a monolith that ignored them would gate the "
                f"distributed update against the wrong numerics")
        grad_exp = quant_kw.get("grad_exp", 8)
        grad_man = quant_kw.get("grad_man", 23)
        use_kahan = quant_kw.get("use_kahan", False)
        reduced = jax.tree.map(
            lambda st: quantized_sum(st, grad_exp, grad_man,
                                     use_kahan=use_kahan), stacked_grads)
        grams = []
        for w in range(self.world):
            local = jax.tree.map(lambda st: st[w], stacked_grads)
            grams.append(self._local_gram_flat(local))
        stats = self._oracle_reduce_stats(jnp.stack(grams))
        return self._apply(reduced, state, stats)


def shampoo_lite(schedule: Callable, world: int, momentum: float = 0.9,
                 weight_decay: float = 0.0, **kw) -> ShampooLite:
    """Factory mirroring `zero1_sgd` & co: the Shampoo-lite updater for
    `make_train_step(update_fn=..., reduce_in_update=True)` — see
    `ShampooLite`."""
    return ShampooLite(schedule, world, momentum, weight_decay, **kw)


def make_optimizer(name: str, schedule: Callable, momentum: float = 0.9,
                   weight_decay: float = 0.0, nesterov: bool = False,
                   wd_mask: Optional[Callable] = None, opt_exp: int = 8,
                   opt_man: int = 23, opt_kahan: bool = False,
                   clip_norm: Optional[float] = None,
                   opt_rounding: str = "nearest", opt_seed: int = 0,
                   ) -> optax.GradientTransformation:
    """Registry used by trainer configs:
    'sgd' | 'nesterov' | 'lars' | 'quant_sgd' | 'adamw'.

    opt_exp/opt_man/opt_kahan apply to 'quant_sgd' (eXmY momentum
    buffer; the optimizer-state analog of --grad_exp/--grad_man).
    'adamw' (no reference counterpart — the transformer-era default,
    elementwise so shard-local-safe under tp) reuses `momentum` as b1 and
    applies `wd_mask` to its decoupled decay.

    clip_norm prepends global-norm gradient clipping.  The result is
    marked norm-based: the clip needs the GLOBAL gradient norm, so the
    shard-local LM stepper refuses it under tp (same contract as LARS);
    the CNN steppers clip the fully-reduced replicated gradients, where
    local norms ARE global."""
    if name == "adamw":
        tx = optax.adamw(schedule, b1=momentum, weight_decay=weight_decay,
                         mask=wd_mask)
    elif name == "sgd":
        tx = sgd(schedule, momentum, weight_decay, nesterov=nesterov,
                 wd_mask=wd_mask)
    elif name == "nesterov":
        tx = sgd(schedule, momentum, weight_decay, nesterov=True,
                 wd_mask=wd_mask)
    elif name == "lars":
        tx = lars(schedule, momentum, weight_decay)
    elif name == "quant_sgd":
        tx = quant_sgd(schedule, momentum, weight_decay, exp=opt_exp,
                       man=opt_man, use_kahan=opt_kahan,
                       nesterov=nesterov, wd_mask=wd_mask,
                       rounding=opt_rounding, seed=opt_seed)
    else:
        raise ValueError(f"unknown optimizer {name!r}")
    if clip_norm is not None:
        if clip_norm <= 0:
            raise ValueError(f"clip_norm must be > 0, got {clip_norm}")
        chained = optax.chain(optax.clip_by_global_norm(clip_norm), tx)
        return NormBasedTransformation(chained.init, chained.update)
    return tx
