"""Optimizers with torch-faithful semantics: SGD(+Nesterov) and LARS.

The reference uses torch.optim.SGD over fp32 master params
(example/ResNet18/tools/mix.py:94-96, example/DavidNet/dawn.py:73-79,
example/ResNet50/main.py:123-131) and a hand-written LARS update
(mix.py:297-310).  optax's built-in `sgd` scales the momentum buffer
differently from torch (torch accumulates raw grads in the buffer and
multiplies by lr at apply time; optax's trace folds lr in), which changes
trajectories when lr varies per step — so `sgd` here reproduces torch's
update rule exactly:

    buf   = momentum * buf + (g + wd * w)                 # torch sgd
    step  = g + momentum * buf  (nesterov)  |  buf
    w    -= lr * step

and `lars` reproduces mix.py:297-310 exactly:

    local_lr = ||w|| / (||g|| + wd * ||w||) * 0.001
    buf      = momentum * buf + lr * local_lr * (g + wd * w)
    w       -= buf

Both take a `Schedule` (step -> lr) so the whole update stays inside jit.
Master-weight handling (mix.py:53-63,292-294,313-314) is structural here:
params are always fp32; bf16 is a compute dtype inside the model, so the
"master copy" is just the params pytree itself.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

__all__ = ["sgd", "lars", "make_optimizer"]


class TorchSGDState(NamedTuple):
    step: jnp.ndarray
    momentum_buf: optax.Updates


def sgd(schedule: Callable, momentum: float = 0.9, weight_decay: float = 0.0,
        nesterov: bool = False,
        wd_mask: Optional[Callable] = None) -> optax.GradientTransformation:
    """torch.optim.SGD-semantics transformation.

    `wd_mask(params)` -> pytree of bools selecting which leaves get weight
    decay — the BN-params-without-wd grouping of main.py:123-131.
    Returned updates are the *negative* delta (optax convention:
    new_p = p + update)."""

    def init(params):
        return TorchSGDState(jnp.zeros([], jnp.int32),
                             jax.tree.map(jnp.zeros_like, params))

    def update(grads, state, params):
        if params is None:
            raise ValueError("sgd requires params")
        lr = schedule(state.step)
        mask = (wd_mask(params) if wd_mask is not None
                else jax.tree.map(lambda _: True, params))

        def one(g, w, buf, use_wd):
            d = g + (weight_decay * w if (weight_decay and use_wd) else 0.0)
            new_buf = momentum * buf + d
            step_dir = d + momentum * new_buf if nesterov else new_buf
            return -lr * step_dir, new_buf

        flat = jax.tree.map(one, grads, params, state.momentum_buf, mask)
        updates = jax.tree.map(lambda t: t[0], flat,
                               is_leaf=lambda t: isinstance(t, tuple))
        bufs = jax.tree.map(lambda t: t[1], flat,
                            is_leaf=lambda t: isinstance(t, tuple))
        return updates, TorchSGDState(state.step + 1, bufs)

    return optax.GradientTransformation(init, update)


class NormBasedTransformation(optax.GradientTransformation):
    """GradientTransformation whose update needs *global* parameter/gradient
    norms (LARS trust ratios).  Shard-local steppers (train/lm.py) check this
    flag and refuse, instead of silently computing per-shard norms."""
    norm_based = True


def lars(schedule: Callable, momentum: float = 0.9,
         weight_decay: float = 0.0, coefficient: float = 0.001,
         ) -> optax.GradientTransformation:
    """The reference's manual LARS (mix.py:297-310), exactly — including its
    quirks: trust ratio computed on the *un-decayed* gradient norm, the fixed
    0.001 coefficient, and lr folded into the momentum buffer (unlike torch
    SGD).  Zero-norm params fall back to local_lr = coefficient·0 = 0 guard
    via the epsilon-free reference formula (||g||+wd·||w|| in the
    denominator; all-zero grads give local_lr = 1/wd... matching reference
    float math)."""

    def init(params):
        return TorchSGDState(jnp.zeros([], jnp.int32),
                             jax.tree.map(jnp.zeros_like, params))

    def update(grads, state, params):
        if params is None:
            raise ValueError("lars requires params")
        lr = schedule(state.step)

        def one(g, w, buf):
            w_norm = jnp.linalg.norm(w.reshape(-1))
            g_norm = jnp.linalg.norm(g.reshape(-1))
            local_lr = w_norm / (g_norm + weight_decay * w_norm) * coefficient
            new_buf = momentum * buf + lr * local_lr * (g + weight_decay * w)
            return -new_buf, new_buf

        flat = jax.tree.map(one, grads, params, state.momentum_buf)
        updates = jax.tree.map(lambda t: t[0], flat,
                               is_leaf=lambda t: isinstance(t, tuple))
        bufs = jax.tree.map(lambda t: t[1], flat,
                            is_leaf=lambda t: isinstance(t, tuple))
        return updates, TorchSGDState(state.step + 1, bufs)

    return NormBasedTransformation(init, update)


def make_optimizer(name: str, schedule: Callable, momentum: float = 0.9,
                   weight_decay: float = 0.0, nesterov: bool = False,
                   wd_mask: Optional[Callable] = None,
                   ) -> optax.GradientTransformation:
    """Registry used by trainer configs: 'sgd' | 'nesterov' | 'lars'."""
    if name == "sgd":
        return sgd(schedule, momentum, weight_decay, nesterov=nesterov,
                   wd_mask=wd_mask)
    if name == "nesterov":
        return sgd(schedule, momentum, weight_decay, nesterov=True,
                   wd_mask=wd_mask)
    if name == "lars":
        return lars(schedule, momentum, weight_decay)
    raise ValueError(f"unknown optimizer {name!r}")
