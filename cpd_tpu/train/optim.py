"""Optimizers with torch-faithful semantics: SGD(+Nesterov) and LARS.

The reference uses torch.optim.SGD over fp32 master params
(example/ResNet18/tools/mix.py:94-96, example/DavidNet/dawn.py:73-79,
example/ResNet50/main.py:123-131) and a hand-written LARS update
(mix.py:297-310).  optax's built-in `sgd` scales the momentum buffer
differently from torch (torch accumulates raw grads in the buffer and
multiplies by lr at apply time; optax's trace folds lr in), which changes
trajectories when lr varies per step — so `sgd` here reproduces torch's
update rule exactly:

    buf   = momentum * buf + (g + wd * w)                 # torch sgd
    step  = g + momentum * buf  (nesterov)  |  buf
    w    -= lr * step

and `lars` reproduces mix.py:297-310 exactly:

    local_lr = ||w|| / (||g|| + wd * ||w||) * 0.001
    buf      = momentum * buf + lr * local_lr * (g + wd * w)
    w       -= buf

Both take a `Schedule` (step -> lr) so the whole update stays inside jit.
Master-weight handling (mix.py:53-63,292-294,313-314) is structural here:
params are always fp32; bf16 is a compute dtype inside the model, so the
"master copy" is just the params pytree itself.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

__all__ = ["sgd", "lars", "quant_sgd", "make_optimizer"]


class TorchSGDState(NamedTuple):
    step: jnp.ndarray
    momentum_buf: optax.Updates


def sgd(schedule: Callable, momentum: float = 0.9, weight_decay: float = 0.0,
        nesterov: bool = False,
        wd_mask: Optional[Callable] = None) -> optax.GradientTransformation:
    """torch.optim.SGD-semantics transformation.

    `wd_mask(params)` -> pytree of bools selecting which leaves get weight
    decay — the BN-params-without-wd grouping of main.py:123-131.
    Returned updates are the *negative* delta (optax convention:
    new_p = p + update)."""

    def init(params):
        return TorchSGDState(jnp.zeros([], jnp.int32),
                             jax.tree.map(jnp.zeros_like, params))

    def update(grads, state, params):
        if params is None:
            raise ValueError("sgd requires params")
        lr = schedule(state.step)
        mask = (wd_mask(params) if wd_mask is not None
                else jax.tree.map(lambda _: True, params))

        def one(g, w, buf, use_wd):
            d = g + (weight_decay * w if (weight_decay and use_wd) else 0.0)
            new_buf = momentum * buf + d
            step_dir = d + momentum * new_buf if nesterov else new_buf
            return -lr * step_dir, new_buf

        flat = jax.tree.map(one, grads, params, state.momentum_buf, mask)
        updates, bufs = _unzip(flat, 2)
        return updates, TorchSGDState(state.step + 1, bufs)

    return optax.GradientTransformation(init, update)


class NormBasedTransformation(optax.GradientTransformation):
    """GradientTransformation whose update needs *global* parameter/gradient
    norms (LARS trust ratios).  Shard-local steppers (train/lm.py) check this
    flag and refuse, instead of silently computing per-shard norms."""
    norm_based = True


def lars(schedule: Callable, momentum: float = 0.9,
         weight_decay: float = 0.0, coefficient: float = 0.001,
         ) -> optax.GradientTransformation:
    """The reference's manual LARS (mix.py:297-310), exactly — including its
    quirks: trust ratio computed on the *un-decayed* gradient norm, the fixed
    0.001 coefficient, and lr folded into the momentum buffer (unlike torch
    SGD).  Zero-norm params fall back to local_lr = coefficient·0 = 0 guard
    via the epsilon-free reference formula (||g||+wd·||w|| in the
    denominator; all-zero grads give local_lr = 1/wd... matching reference
    float math)."""

    def init(params):
        return TorchSGDState(jnp.zeros([], jnp.int32),
                             jax.tree.map(jnp.zeros_like, params))

    def update(grads, state, params):
        if params is None:
            raise ValueError("lars requires params")
        lr = schedule(state.step)

        def one(g, w, buf):
            w_norm = jnp.linalg.norm(w.reshape(-1))
            g_norm = jnp.linalg.norm(g.reshape(-1))
            local_lr = w_norm / (g_norm + weight_decay * w_norm) * coefficient
            new_buf = momentum * buf + lr * local_lr * (g + weight_decay * w)
            return -new_buf, new_buf

        flat = jax.tree.map(one, grads, params, state.momentum_buf)
        updates, bufs = _unzip(flat, 2)
        return updates, TorchSGDState(state.step + 1, bufs)

    return NormBasedTransformation(init, update)


class QuantSGDState(NamedTuple):
    step: jnp.ndarray
    momentum_buf: optax.Updates
    comp: optax.Updates    # Kahan residuals; () (leafless) w/o use_kahan
    key: optax.Updates = ()  # PRNG key iff rounding='stochastic', else ()


def _unzip(flat, n):
    """Split a pytree of n-tuples into n pytrees (shared by the
    optimizers here)."""
    is_t = lambda t: isinstance(t, tuple)  # noqa: E731
    return tuple(jax.tree.map(lambda t: t[i], flat, is_leaf=is_t)
                 for i in range(n))


def quant_sgd(schedule: Callable, momentum: float = 0.9,
              weight_decay: float = 0.0, exp: int = 8, man: int = 23,
              use_kahan: bool = False, nesterov: bool = False,
              wd_mask: Optional[Callable] = None,
              rounding: str = "nearest", seed: int = 0,
              ) -> optax.GradientTransformation:
    """torch-SGD semantics with the momentum buffer held in eXmY.

    New capability beyond the reference, built from its own numerics
    doctrine: the reference quantizes gradients around the all-reduce
    (dist_util.py:35-37) and keeps every Kahan intermediate quantized
    (dist_util.py:82-88); this applies the same discipline to the
    *optimizer state* — the momentum buffer lives in the (exp, man)
    value set, every intermediate of its update is re-quantized, and an
    optional quantized Kahan residual recovers the small gradients that
    a naive low-precision accumulation would flush (the classic 8-bit-
    optimizer memory/accuracy trade, emulated exactly like the rest of
    CPD).  Params stay fp32 masters.

    With (8,23) the cast is the identity; use_kahan=False then walks
    `sgd`'s trajectory bitwise.  use_kahan=True still runs the Kahan
    arithmetic (fp32 compensation changes rounding, so only ~ulp-close
    to `sgd`) — the same shortcut asymmetry the reference's fp32 Kahan
    all-reduce has (dist_util.py:55-59 vs :72-89, preserved in
    parallel/reduction.py).

        d    = g + wd*w
        s    = Q(momentum * buf)
        naive:  buf' = Q(s + d)
        kahan:  y = Q(d - Q(momentum*c));  buf' = Q(s + y)
                c' = Q(Q(buf' - s) - y)
        step = d + momentum*buf' (nesterov) | buf'
        w   -= lr * step

    rounding='stochastic' (beyond-reference, Gupta et al. 2015's recipe)
    replaces every eXmY cast in the buffer update with the unbiased
    stochastic cast: small contributions smaller than ulp/2 then survive
    *in expectation* instead of being flushed by RTNE — the standard cure
    for low-precision update stagnation.  Bits are drawn per (step, leaf,
    cast-site) from a PRNG key carried in the optimizer state, so the
    trajectory is deterministic given `seed`.  With rounding='nearest'
    (default) the state tree is unchanged from before (key=() has no
    leaves) and the trajectory is bit-identical to the documented RTNE
    semantics above.
    """
    if rounding not in ("nearest", "stochastic"):
        raise ValueError(f"unknown rounding mode: {rounding!r}")
    stochastic = rounding == "stochastic" and (exp, man) != (8, 23)
    if (exp, man) == (8, 23):
        def q(x, _k=None):
            return x
    elif stochastic:
        from ..quant.numerics import cast_to_format_sr

        def q(x, k):
            return cast_to_format_sr(x, exp, man, k)
    else:
        from ..quant.numerics import cast_to_format

        def q(x, _k=None):
            return cast_to_format(x, exp, man)

    def init(params):
        # no dead residual tree without Kahan: () has no leaves, so the
        # quantized-optimizer state stays one buffer per param
        comp = (jax.tree.map(jnp.zeros_like, params) if use_kahan else ())
        key = jax.random.PRNGKey(seed) if stochastic else ()
        return QuantSGDState(jnp.zeros([], jnp.int32),
                             jax.tree.map(jnp.zeros_like, params), comp, key)

    def update(grads, state, params):
        if params is None:
            raise ValueError("quant_sgd requires params")
        lr = schedule(state.step)
        mask = (wd_mask(params) if wd_mask is not None
                else jax.tree.map(lambda _: True, params))

        if stochastic:
            # one independent subkey per leaf for this step; each cast
            # site inside the leaf update folds in its own site index
            step_key = jax.random.fold_in(state.key, state.step)
            treedef = jax.tree.structure(params)
            leaf_keys = jax.tree.unflatten(
                treedef, list(jax.random.split(step_key,
                                               treedef.num_leaves)))
        else:
            # dummy leaves (ignored by q) so all mapped trees share the
            # params structure; None would be an empty pytree node
            leaf_keys = jax.tree.map(lambda _: 0, params)
        site = (lambda k, i: jax.random.fold_in(k, i)) if stochastic \
            else (lambda k, i: None)

        def decayed(g, w, use_wd):
            return g + (weight_decay * w
                        if (weight_decay and use_wd) else 0.0)

        def step_dir(d, new_buf):
            return d + momentum * new_buf if nesterov else new_buf

        if use_kahan:
            def one(g, w, buf, c, k, use_wd):
                d = decayed(g, w, use_wd)
                s = q(momentum * buf, site(k, 0))
                y = q(d - q(momentum * c, site(k, 1)), site(k, 2))
                new_buf = q(s + y, site(k, 3))
                new_c = q(q(new_buf - s, site(k, 4)) - y, site(k, 5))
                return -lr * step_dir(d, new_buf), new_buf, new_c

            flat = jax.tree.map(one, grads, params, state.momentum_buf,
                                state.comp, leaf_keys, mask)
            updates, bufs, comp = _unzip(flat, 3)
        else:
            def one(g, w, buf, k, use_wd):
                d = decayed(g, w, use_wd)
                new_buf = q(q(momentum * buf, site(k, 0)) + d, site(k, 1))
                return -lr * step_dir(d, new_buf), new_buf

            flat = jax.tree.map(one, grads, params, state.momentum_buf,
                                leaf_keys, mask)
            updates, bufs = _unzip(flat, 2)
            comp = ()
        return updates, QuantSGDState(state.step + 1, bufs, comp, state.key)

    return optax.GradientTransformation(init, update)


def make_optimizer(name: str, schedule: Callable, momentum: float = 0.9,
                   weight_decay: float = 0.0, nesterov: bool = False,
                   wd_mask: Optional[Callable] = None, opt_exp: int = 8,
                   opt_man: int = 23, opt_kahan: bool = False,
                   clip_norm: Optional[float] = None,
                   opt_rounding: str = "nearest", opt_seed: int = 0,
                   ) -> optax.GradientTransformation:
    """Registry used by trainer configs:
    'sgd' | 'nesterov' | 'lars' | 'quant_sgd' | 'adamw'.

    opt_exp/opt_man/opt_kahan apply to 'quant_sgd' (eXmY momentum
    buffer; the optimizer-state analog of --grad_exp/--grad_man).
    'adamw' (no reference counterpart — the transformer-era default,
    elementwise so shard-local-safe under tp) reuses `momentum` as b1 and
    applies `wd_mask` to its decoupled decay.

    clip_norm prepends global-norm gradient clipping.  The result is
    marked norm-based: the clip needs the GLOBAL gradient norm, so the
    shard-local LM stepper refuses it under tp (same contract as LARS);
    the CNN steppers clip the fully-reduced replicated gradients, where
    local norms ARE global."""
    if name == "adamw":
        tx = optax.adamw(schedule, b1=momentum, weight_decay=weight_decay,
                         mask=wd_mask)
    elif name == "sgd":
        tx = sgd(schedule, momentum, weight_decay, nesterov=nesterov,
                 wd_mask=wd_mask)
    elif name == "nesterov":
        tx = sgd(schedule, momentum, weight_decay, nesterov=True,
                 wd_mask=wd_mask)
    elif name == "lars":
        tx = lars(schedule, momentum, weight_decay)
    elif name == "quant_sgd":
        tx = quant_sgd(schedule, momentum, weight_decay, exp=opt_exp,
                       man=opt_man, use_kahan=opt_kahan,
                       nesterov=nesterov, wd_mask=wd_mask,
                       rounding=opt_rounding, seed=opt_seed)
    else:
        raise ValueError(f"unknown optimizer {name!r}")
    if clip_norm is not None:
        if clip_norm <= 0:
            raise ValueError(f"clip_norm must be > 0, got {clip_norm}")
        chained = optax.chain(optax.clip_by_global_norm(clip_norm), tx)
        return NormBasedTransformation(chained.init, chained.update)
    return tx
