"""Dynamic loss scaling — the torch.cuda.amp.GradScaler counterpart.

The reference's loss scaling is a static multiplier that is never unscaled
(C17: DavidNet utils.py:332-334, `--loss_scale`); `train/step.py` keeps that
faithful path.  This module adds the modern dynamic variant as an optax
wrapper: the loss is multiplied by a *state-carried* scale, the wrapper
unscales the incoming (scaled) gradients, skips the update when any gradient
is non-finite, halves the scale on overflow and doubles it after
`growth_interval` consecutive finite steps — exactly GradScaler's policy
(growth 2.0, backoff 0.5, interval 2000 by default).

Composition notes:

* Scale values are powers of two, so unscaling (multiply by ``1/scale``) is
  exact in fp32 — with a finite trajectory the wrapped optimizer walks
  bit-identically to the unwrapped one fed raw gradients (tested).
* Under `--use_APS` dynamic scaling is redundant by construction: APS
  already shifts every gradient tensor's exponent range to the top of the
  eXmY format (parallel/aps.py), which is *per-tensor* loss scaling with a
  provably optimal factor.  The wrapper exists for the non-APS configs
  (plain bf16/quantized training) where a global scale is the standard
  remedy.
* Like GradScaler, a skipped step does not roll back BatchNorm running
  stats — the forward pass already updated them.  The step counter and the
  inner optimizer state are untouched on skip.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

__all__ = ["DynamicScaleState", "with_dynamic_loss_scale", "all_finite",
           "current_scale", "find_dynamic_scale"]


class DynamicScaleState(NamedTuple):
    scale: jnp.ndarray       # f32 scalar — multiply the loss by this
    good_steps: jnp.ndarray  # i32 consecutive finite steps since last change
    inner: Any               # wrapped transformation's state


def all_finite(tree: Any) -> jnp.ndarray:
    """Scalar bool: every element of every leaf is finite."""
    leaves = jax.tree.leaves(tree)
    if not leaves:
        return jnp.bool_(True)
    return jnp.stack([jnp.all(jnp.isfinite(l)) for l in leaves]).all()


def find_dynamic_scale(opt_state: Any) -> Any:
    """The DynamicScaleState node nested anywhere in ``opt_state``, or
    None.  A structural (pytree) search, so it sees through wrappers
    like optax.chain or resilience.with_grad_guard that nest the scale
    state one level down."""
    def is_dyn(n):
        return isinstance(n, DynamicScaleState)
    for node in jax.tree.leaves(opt_state, is_leaf=is_dyn):
        if is_dyn(node):
            return node
    return None


def current_scale(opt_state: Any) -> jnp.ndarray:
    """The live scale scalar from a `with_dynamic_loss_scale` opt state.
    Raises if the optimizer is not wrapped (trainers pass this to the loss)."""
    node = find_dynamic_scale(opt_state)
    if node is None:
        raise TypeError(
            "dynamic loss scaling needs the optimizer wrapped with "
            "with_dynamic_loss_scale(tx); got opt state "
            f"{type(opt_state).__name__}")
    return node.scale


def with_dynamic_loss_scale(tx: optax.GradientTransformation,
                            init_scale: float = 2.0 ** 15,
                            growth_factor: float = 2.0,
                            backoff_factor: float = 0.5,
                            growth_interval: int = 2000,
                            max_scale: float = 2.0 ** 24,
                            min_scale: float = 1.0,
                            ) -> optax.GradientTransformation:
    """Wrap `tx` so it consumes gradients of a `scale`-multiplied loss.

    update() expects grads that were computed from ``loss * state.scale``;
    it unscales them, runs the inner update, and zeroes the whole update
    (keeping the inner state) when any incoming gradient is non-finite.
    """
    if not (growth_factor > 1.0 and 0.0 < backoff_factor < 1.0):
        raise ValueError("need growth_factor > 1 and 0 < backoff_factor < 1")

    def init(params):
        return DynamicScaleState(jnp.float32(init_scale),
                                 jnp.zeros([], jnp.int32), tx.init(params))

    def update(grads, state, params=None):
        finite = all_finite(grads)
        inv = jnp.float32(1.0) / state.scale
        # zero the grads BEFORE multiplying: inf * 0 would manufacture NaN
        safe = jax.tree.map(
            lambda g: jnp.where(finite, g, jnp.zeros_like(g)) * inv, grads)
        updates, new_inner = tx.update(safe, state.inner, params)
        updates = jax.tree.map(
            lambda u: jnp.where(finite, u, jnp.zeros_like(u)), updates)
        new_inner = jax.tree.map(lambda n, o: jnp.where(finite, n, o),
                                 new_inner, state.inner)

        good = jnp.where(finite, state.good_steps + 1,
                         jnp.zeros([], jnp.int32))
        grow = good >= growth_interval
        new_scale = jnp.where(
            finite,
            jnp.where(grow,
                      jnp.minimum(state.scale * growth_factor,
                                  jnp.float32(max_scale)),
                      state.scale),
            jnp.maximum(state.scale * backoff_factor,
                        jnp.float32(min_scale)))
        good = jnp.where(grow, jnp.zeros([], jnp.int32), good)
        return updates, DynamicScaleState(new_scale, good, new_inner)

    wrapped = optax.GradientTransformation(init, update)
    if getattr(tx, "norm_based", False):
        from .optim import NormBasedTransformation
        wrapped = NormBasedTransformation(init, update)
    return wrapped
