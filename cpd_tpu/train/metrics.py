"""Host-side metric plumbing: meters, top-k accuracy, timers.

Parity with the reference's harness utilities (duplicated there across
example/ResNet18/utils/train_util.py and example/DavidNet/utils.py;
SURVEY.md C21 — one copy here):
  * AverageMeter with a sliding window (train_util.py:21-48)
  * accuracy(output, target, topk) (train_util.py:51-65)
  * Timer (DavidNet/utils.py:28-38)
"""

from __future__ import annotations

import math
import sys
import time
from collections import deque
from typing import Sequence

import numpy as np

__all__ = ["AverageMeter", "accuracy", "Timer", "loss_diverged"]


def loss_diverged(loss: float, where: str, rank: int,
                  hint: str = "try --use_APS / more mantissa bits") -> bool:
    """True (with a rank-0 verdict line on stderr) when `loss` is
    non-finite.  Trainers break their loop on it and report
    diverged=True — a controlled stop, not an exception, so in-process
    harnesses (aps_golden, tests) record the divergence instead of
    dying.  The loss metric is replicated across hosts, so every host
    takes the same branch.

    Lives here (not checkpoint.py) so trainers without checkpointing —
    DavidNet, whose reference has none — don't pay the orbax import."""
    if math.isfinite(loss):
        return False
    if rank == 0:
        print(f"=> non-finite loss {loss} at {where} — diverged "
              f"({hint})", file=sys.stderr)
    return True


class AverageMeter:
    """Tracks current value, windowed average and global average.

    length > 0 → sliding window of that many updates (the reference stores a
    history list and averages the tail, train_util.py:27-41); length == 0 →
    running sum/count average (train_util.py:33,43-48)."""

    def __init__(self, length: int = 0):
        self.length = length
        self.reset()

    def reset(self):
        self.history = deque(maxlen=self.length or None)
        self.val = 0.0
        self.sum = 0.0
        self.count = 0
        self.avg = 0.0

    def update(self, val: float, n: int = 1):
        val = float(val)
        self.val = val
        if self.length > 0:
            self.history.append(val)
            self.avg = sum(self.history) / len(self.history)
        else:
            self.sum += val * n
            self.count += n
            self.avg = self.sum / max(self.count, 1)


def accuracy(output, target, topk: Sequence[int] = (1,)):
    """Top-k precision over a batch, as percentages (train_util.py:51-65).

    output: (B, C) logits/scores; target: (B,) int labels.  Returns one
    float per k."""
    output = np.asarray(output)
    target = np.asarray(target)
    maxk = max(topk)
    pred = np.argsort(-output, axis=1)[:, :maxk]          # (B, maxk)
    correct = pred == target[:, None]
    batch = target.shape[0]
    return [100.0 * correct[:, :k].any(axis=1).sum() / batch for k in topk]


class Timer:
    """Incremental wall-clock timer (DavidNet/utils.py:28-38): each call

    returns the time since the previous call and accumulates total time."""

    def __init__(self):
        self.times = [time.perf_counter()]
        self.total_time = 0.0

    def __call__(self, include_in_total: bool = True) -> float:
        self.times.append(time.perf_counter())
        delta = self.times[-1] - self.times[-2]
        if include_in_total:
            self.total_time += delta
        return delta
