"""Host-side metric plumbing: meters, top-k accuracy, timers.

Parity with the reference's harness utilities (duplicated there across
example/ResNet18/utils/train_util.py and example/DavidNet/utils.py;
SURVEY.md C21 — one copy here):
  * AverageMeter with a sliding window (train_util.py:21-48)
  * accuracy(output, target, topk) (train_util.py:51-65)
  * Timer (DavidNet/utils.py:28-38)
"""

from __future__ import annotations

import math
import sys
from collections import deque
from typing import Sequence

import numpy as np

# the ONE wall-clock timer implementation now lives in cpd_tpu.obs
# (ISSUE 11 satellite: this module, loadgen and the bench tools each
# carried their own); re-exported here for the established import path
from ..obs.timing import Timer

__all__ = ["AverageMeter", "accuracy", "Timer", "loss_diverged",
           "ResilienceMeter"]


class ResilienceMeter:
    """Run-level resilience counters, one place, one spelling.

    Two kinds of field: *absolute* counters mirrored from the jitted
    guard/injection state (``observe_metrics`` overwrites them from the
    step's metric dict — the device holds the truth), and *host* counters
    the loop bumps itself (``bump``).  ``suffix()`` renders the non-zero
    ones for the per-step log line; ``as_dict`` feeds bench.py /
    trainer return values so BENCH_* can track skip-rate across PRs.
    """

    # device-mirrored (metric key -> field)
    MIRRORED = {"guard_skipped": "steps_skipped",
                "guard_overflows": "overflows",
                "guard_spikes": "spikes",
                "guard_disagreements": "disagreements",
                "faults_injected": "faults_injected"}
    HOST = ("rollbacks", "restores", "watchdog_trips", "preemptions",
            "batches_dropped", "batches_duplicated", "ckpts_invalid",
            # verified-reduce / degraded-transport accounting (ISSUE 4):
            # detections and ladder moves are host decisions (the loop
            # reads the step's replicated reduce_ok scalar), so they are
            # host counters, not device mirrors
            "wire_faults_detected", "reduce_retries",
            "transport_downgrades", "transport_upgrades", "resyncs",
            "ckpts_unverified", "faults_unfired",
            # precision-ladder accounting (ISSUE 5): hot steps (agreed
            # sat+NaN rate over the supervisor's threshold) and ladder
            # moves, decided host-side from the prec_wire_* metrics
            "sat_hot_steps", "precision_escalations",
            "precision_deescalations",
            # elastic-training accounting (ISSUE 19): detection and
            # shrink/regrow moves are host decisions of the
            # ElasticSupervisor (resilience/elastic.py); the loop bumps
            # these as it executes them
            "elastic_shrinks", "elastic_regrows", "elastic_drains",
            "elastic_hot_steps", "elastic_heartbeat_misses",
            "elastic_link_retries", "elastic_link_escalations")
    FIELDS = tuple(MIRRORED.values()) + HOST

    def __init__(self):
        self.counts = {f: 0 for f in self.FIELDS}

    def observe_metrics(self, metrics: dict) -> None:
        """Mirror the cumulative device-side counters from one step's
        metrics (keys absent when no guard/injector is wired — no-op)."""
        for key, field in self.MIRRORED.items():
            if key in metrics:
                self.counts[field] = int(metrics[key])

    def bump(self, field: str, n: int = 1) -> None:
        if field not in self.counts:
            raise KeyError(f"unknown resilience counter {field!r}; know "
                           f"{sorted(self.counts)}")
        self.counts[field] += n

    def __getitem__(self, field: str) -> int:
        return self.counts[field]

    def as_dict(self) -> dict:
        return dict(self.counts)

    def suffix(self) -> str:
        """' skip 2 ovf 1 rollback 1' — only the non-zero counters, so
        a healthy run's log lines stay exactly as they were."""
        short = {"steps_skipped": "skip", "overflows": "ovf",
                 "spikes": "spike", "disagreements": "disagree",
                 "faults_injected": "inj", "rollbacks": "rollback",
                 "restores": "restore", "watchdog_trips": "wdog",
                 "preemptions": "preempt", "batches_dropped": "drop",
                 "batches_duplicated": "dup", "ckpts_invalid": "badckpt",
                 "wire_faults_detected": "wire",
                 "reduce_retries": "retry",
                 "transport_downgrades": "down",
                 "transport_upgrades": "up", "resyncs": "resync",
                 "ckpts_unverified": "unvckpt",
                 "faults_unfired": "unfired",
                 "sat_hot_steps": "hot",
                 "precision_escalations": "esc",
                 "precision_deescalations": "deesc",
                 "elastic_shrinks": "shrink",
                 "elastic_regrows": "regrow",
                 "elastic_drains": "drain",
                 "elastic_hot_steps": "ehot",
                 "elastic_heartbeat_misses": "miss",
                 "elastic_link_retries": "lretry",
                 "elastic_link_escalations": "lesc"}
        parts = [f"{short[f]} {v}" for f, v in self.counts.items() if v]
        return (" " + " ".join(parts)) if parts else ""


def loss_diverged(loss: float, where: str, rank: int,
                  hint: str = "try --use_APS / more mantissa bits") -> bool:
    """True (with a rank-0 verdict line on stderr) when `loss` is
    non-finite.  Trainers break their loop on it and report
    diverged=True — a controlled stop, not an exception, so in-process
    harnesses (aps_golden, tests) record the divergence instead of
    dying.  The loss metric is replicated across hosts, so every host
    takes the same branch.

    Lives here (not checkpoint.py) so trainers without checkpointing —
    DavidNet, whose reference has none — don't pay the orbax import."""
    if math.isfinite(loss):
        return False
    if rank == 0:
        print(f"=> non-finite loss {loss} at {where} — diverged "
              f"({hint})", file=sys.stderr)
    return True


class AverageMeter:
    """Tracks current value, windowed average and global average.

    length > 0 → sliding window of that many updates (the reference stores a
    history list and averages the tail, train_util.py:27-41); length == 0 →
    running sum/count average (train_util.py:33,43-48)."""

    def __init__(self, length: int = 0):
        self.length = length
        self.reset()

    def reset(self):
        self.history = deque(maxlen=self.length or None)
        self.val = 0.0
        self.sum = 0.0
        self.count = 0
        self.avg = 0.0

    def update(self, val: float, n: int = 1):
        val = float(val)
        self.val = val
        if self.length > 0:
            self.history.append(val)
            self.avg = sum(self.history) / len(self.history)
        else:
            self.sum += val * n
            self.count += n
            self.avg = self.sum / max(self.count, 1)


def accuracy(output, target, topk: Sequence[int] = (1,)):
    """Top-k precision over a batch, as percentages (train_util.py:51-65).

    output: (B, C) logits/scores; target: (B,) int labels.  Returns one
    float per k."""
    output = np.asarray(output)
    target = np.asarray(target)
    maxk = max(topk)
    pred = np.argsort(-output, axis=1)[:, :maxk]          # (B, maxk)
    correct = pred == target[:, None]
    batch = target.shape[0]
    return [100.0 * correct[:, :k].any(axis=1).sum() / batch for k in topk]
