"""Training harness: state, steps, optimizers, schedules, metrics, ckpt."""

from .state import TrainState, create_train_state
from .step import (cross_entropy_loss, make_eval_step,
                   make_seg_eval_step, make_train_step,
                   seg_cross_entropy_loss)
from .optim import (ShampooLite, lars, make_optimizer, quant_sgd, sgd,
                    shampoo_lite)
from .schedules import (iter_table, piecewise_linear, warmup_cosine,
                        warmup_step_decay)
from .metrics import (AverageMeter, ResilienceMeter, Timer, accuracy,
                      loss_diverged)
from .scaling import (with_dynamic_loss_scale, DynamicScaleState,
                      find_dynamic_scale)
from .lm import lm_state_specs, make_lm_train_step
from .pp import make_pp_eval_step, make_pp_train_step, pp_state_specs
from .moe import make_moe_eval_step, make_moe_train_step, moe_state_specs

__all__ = [
    "make_pp_train_step", "make_pp_eval_step", "pp_state_specs",
    "make_moe_train_step", "make_moe_eval_step", "moe_state_specs",
    "TrainState", "create_train_state",
    "cross_entropy_loss", "seg_cross_entropy_loss", "make_eval_step",
    "make_seg_eval_step", "make_train_step",
    "lars", "make_optimizer", "quant_sgd", "sgd",
    "shampoo_lite", "ShampooLite",
    "iter_table", "piecewise_linear", "warmup_cosine", "warmup_step_decay",
    "AverageMeter", "ResilienceMeter", "Timer", "accuracy",
    "with_dynamic_loss_scale", "DynamicScaleState", "find_dynamic_scale",
    "make_lm_train_step", "lm_state_specs",
    "CheckpointManager", "PreemptionGuard", "preempt_save",
    "loss_diverged", "save_checkpoint", "restore_latest",
    "RestoreResult", "checkpoint_digest",
]

_CHECKPOINT_NAMES = {"CheckpointManager", "PreemptionGuard",
                     "preempt_save", "save_checkpoint", "restore_latest",
                     "RestoreResult", "checkpoint_digest"}


def __getattr__(name):
    # Checkpoint exports resolve lazily so importing cpd_tpu.train does not
    # pay the orbax import cost unless checkpointing is actually used.
    if name in _CHECKPOINT_NAMES:
        from . import checkpoint
        return getattr(checkpoint, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
