"""LR schedules — the reference's three schedules as pure step->lr functions.

All schedules are `Callable[[step], float32]`, jit-traceable, usable both
with optax (inject_hyperparams) and with the manual LARS update.

Parity map (reference → here):
  * `adjust_learning_rate` warmup→1.6, /10 at epochs 40/80
    (example/ResNet18/tools/mix.py:181-198)        → `warmup_step_decay`
  * `PiecewiseLinear([0,5,24],[0,0.4,0])`
    (example/DavidNet/dawn.py:65)                  → `piecewise_linear`
  * ResNet50 5-epoch warmup to 3.2, /10 at 30/60/80
    (example/ResNet50/main.py:237-252)             → `warmup_step_decay`
  * `IterLRScheduler` (explicit iteration->lr table,
    ResNet18/utils/train_util.py:68-107)           → `iter_table`
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax.numpy as jnp

__all__ = ["warmup_step_decay", "piecewise_linear", "iter_table",
           "warmup_cosine", "Schedule"]

Schedule = Callable[[jnp.ndarray], jnp.ndarray]


def warmup_step_decay(base_lr: float, warmup_iters: int,
                      decay_iters: Sequence[int], warmup_from: float = 0.1,
                      decay_factor: float = 0.1) -> Schedule:
    """Linear warmup from `warmup_from` to `base_lr` over `warmup_iters`,
    then multiply by `decay_factor` after each boundary in `decay_iters`.

    With base_lr=1.6, warmup=5 epochs, boundaries at 40/80 epochs this is
    exactly mix.py:181-198 (which starts warmup at 0.1, not 0); ResNet50's
    schedule (main.py:237-252) is the same shape with base 3.2, warmup_from
    equal to base/warmup_epochs increments, boundaries 30/60/80.
    """
    boundaries = jnp.asarray(list(decay_iters), jnp.float32)

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        warm = warmup_from + (base_lr - warmup_from) * (step / max(warmup_iters, 1))
        decays = jnp.sum(step > boundaries)
        decayed = base_lr * decay_factor ** decays
        return jnp.where(step <= warmup_iters, warm, decayed)

    return schedule


def warmup_cosine(base_lr: float, warmup_iters: int, total_iters: int,
                  final_lr: float = 0.0,
                  warmup_from: float = 0.0) -> Schedule:
    """Linear warmup then cosine decay to `final_lr` at `total_iters`.

    No reference counterpart (its trainers use step/piecewise schedules)
    — so unlike its hand-rolled reference-parity siblings above, this one
    simply delegates to optax's identical implementation; kept as a named
    entry for the uniform Schedule surface plus an early shape check."""
    import optax

    if total_iters <= warmup_iters:
        raise ValueError(f"total_iters {total_iters} must exceed "
                         f"warmup_iters {warmup_iters}")
    return optax.warmup_cosine_decay_schedule(
        init_value=warmup_from, peak_value=base_lr,
        warmup_steps=warmup_iters, decay_steps=total_iters,
        end_value=final_lr)


def piecewise_linear(knot_steps: Sequence[float],
                     knot_values: Sequence[float]) -> Schedule:
    """Linear interpolation through (step, value) knots, clamped at the ends
    — reference PiecewiseLinear (DavidNet/utils.py: np.interp over epochs,
    dawn.py:65 uses knots [0, 5, 24] -> [0, 0.4, 0])."""
    xs = jnp.asarray(list(knot_steps), jnp.float32)
    ys = jnp.asarray(list(knot_values), jnp.float32)

    def schedule(step):
        return jnp.interp(jnp.asarray(step, jnp.float32), xs, ys)

    return schedule


def iter_table(lr_steps: Sequence[int], lr_mults: Sequence[float],
               base_lr: float, warmup_steps: int = 0,
               warmup_lr: float = 0.0) -> Schedule:
    """Explicit iteration->multiplier table with optional linear warmup —
    reference IterLRScheduler (train_util.py:68-107): at each step in
    `lr_steps` the lr is multiplied by the matching entry of `lr_mults`;
    warmup interpolates warmup_lr -> base_lr over `warmup_steps`."""
    if len(lr_steps) != len(lr_mults):
        raise ValueError("lr_steps and lr_mults must have equal length")
    steps = jnp.asarray(list(lr_steps), jnp.float32)
    cum = jnp.cumprod(jnp.asarray(list(lr_mults), jnp.float32))

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        idx = jnp.sum(step >= steps).astype(jnp.int32)
        mult = jnp.where(idx == 0, 1.0, cum[jnp.maximum(idx - 1, 0)])
        lr = base_lr * mult
        if warmup_steps > 0:
            warm = warmup_lr + (base_lr - warmup_lr) * (step / warmup_steps)
            lr = jnp.where(step < warmup_steps, warm, lr)
        return lr

    return schedule
