"""TrainState — one pytree holding everything a training run mutates.

Replaces the reference's scattered mutable state: model params + BN running
stats (torch module buffers), fp32 master copies (mix.py:53-63 — structural
here: params ARE fp32, bf16 is a compute dtype), optimizer state
(torch SGD momentum buffers / mix.py's manual `momentum_buffer` list), and
the step counter (mix.py's `curr_step`).  Being a pytree, the whole thing
shards/checkpoints/donates as a unit.
"""

from __future__ import annotations

from typing import Any, Callable

import flax.struct
import jax
import jax.numpy as jnp
import optax

from ..compat import shard_map

__all__ = ["TrainState", "create_train_state", "state_specs_like",
           "reject_norm_based", "make_sharded_stepper"]


@flax.struct.dataclass
class TrainState:
    step: jnp.ndarray               # scalar int32
    params: Any                     # fp32 master weights
    batch_stats: Any                # BN running stats ({} for stat-less models)
    opt_state: Any


def create_train_state(model, tx: optax.GradientTransformation,
                       sample_input: jnp.ndarray, rng: jax.Array,
                       train: bool = True) -> TrainState:
    """Initialize params/stats with a sample batch and build optimizer state.

    Equivalent of the reference's model construction + broadcast + master
    prep + optimizer construction block (mix.py:82-103); the rank-0
    broadcast (mix.py:86-88) happens when the caller `replicate()`s the
    returned state onto a mesh."""
    variables = model.init(rng, sample_input, train=False)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    return TrainState(step=jnp.zeros([], jnp.int32), params=params,
                      batch_stats=batch_stats, opt_state=tx.init(params))


def state_specs_like(state: TrainState, p_specs: Any) -> TrainState:
    """PartitionSpec pytree shaped like `state`, given the params' specs.

    Optimizer-state subtrees that structurally mirror the params
    (momentum/mu/nu) take the param specs wholesale; containers recurse;
    scalars/counters are replicated.  Structural (not shape-based)
    matching: same-shaped-but-differently-sharded leaves must not collide.
    """
    from jax.sharding import PartitionSpec as P

    params_td = jax.tree.structure(state.params)

    def mirror(obj):
        if jax.tree.structure(obj) == params_td:
            return p_specs
        if isinstance(obj, tuple) and hasattr(obj, "_fields"):  # NamedTuple
            return type(obj)(*(mirror(x) for x in obj))
        if isinstance(obj, (tuple, list)):
            return type(obj)(mirror(x) for x in obj)
        if isinstance(obj, dict):
            return {k: mirror(v) for k, v in obj.items()}
        return P()

    return TrainState(step=P(), params=p_specs, batch_stats=P(),
                      opt_state=mirror(state.opt_state))


def reject_norm_based(tx, where: str) -> None:
    """Shared guard: shard-local optimizer updates are only exact for
    elementwise transforms; LARS trust ratios need global norms."""
    if getattr(tx, "norm_based", False):
        raise ValueError(
            f"norm-based gradient transforms (LARS trust ratios, "
            f"clip_norm global-norm clipping) are not supported by the "
            f"{where}: they need GLOBAL norms but the update is "
            f"shard-local. Use an elementwise optimizer (sgd/nesterov/"
            f"adamw) without clip_norm here.")


def make_sharded_stepper(step_fn: Callable, specs_fn: Callable, mesh,
                         data_spec, donate: bool = True) -> Callable:
    """Structure-keyed cache of jitted shard_map steps — the shared tail of
    every multi-axis train-step factory (lm/pp/moe).

    step_fn(state, a, b) -> (state, metrics); specs_fn(state_template) ->
    PartitionSpec TrainState; data batches get `data_spec`, metrics P().
    """
    from jax.sharding import PartitionSpec as P

    cache: dict = {}

    def build(state_template):
        specs = specs_fn(state_template)
        shard_fn = shard_map(
            step_fn, mesh=mesh,
            in_specs=(specs, data_spec, data_spec),
            out_specs=(specs, P()),
            check_vma=False)
        return jax.jit(shard_fn, donate_argnums=(0,) if donate else ())

    def stepper(state, a, b):
        key = jax.tree.structure(state)
        if key not in cache:
            cache[key] = build(state)
        return cache[key](state, a, b)

    return stepper
