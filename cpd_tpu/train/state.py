"""TrainState — one pytree holding everything a training run mutates.

Replaces the reference's scattered mutable state: model params + BN running
stats (torch module buffers), fp32 master copies (mix.py:53-63 — structural
here: params ARE fp32, bf16 is a compute dtype), optimizer state
(torch SGD momentum buffers / mix.py's manual `momentum_buffer` list), and
the step counter (mix.py's `curr_step`).  Being a pytree, the whole thing
shards/checkpoints/donates as a unit.
"""

from __future__ import annotations

from typing import Any, Callable

import flax.struct
import jax
import jax.numpy as jnp
import optax

__all__ = ["TrainState", "create_train_state", "state_specs_like"]


@flax.struct.dataclass
class TrainState:
    step: jnp.ndarray               # scalar int32
    params: Any                     # fp32 master weights
    batch_stats: Any                # BN running stats ({} for stat-less models)
    opt_state: Any


def create_train_state(model, tx: optax.GradientTransformation,
                       sample_input: jnp.ndarray, rng: jax.Array,
                       train: bool = True) -> TrainState:
    """Initialize params/stats with a sample batch and build optimizer state.

    Equivalent of the reference's model construction + broadcast + master
    prep + optimizer construction block (mix.py:82-103); the rank-0
    broadcast (mix.py:86-88) happens when the caller `replicate()`s the
    returned state onto a mesh."""
    variables = model.init(rng, sample_input, train=False)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    return TrainState(step=jnp.zeros([], jnp.int32), params=params,
                      batch_stats=batch_stats, opt_state=tx.init(params))


def state_specs_like(state: TrainState, p_specs: Any) -> TrainState:
    """PartitionSpec pytree shaped like `state`, given the params' specs.

    Optimizer-state subtrees that structurally mirror the params
    (momentum/mu/nu) take the param specs wholesale; containers recurse;
    scalars/counters are replicated.  Structural (not shape-based)
    matching: same-shaped-but-differently-sharded leaves must not collide.
    """
    from jax.sharding import PartitionSpec as P

    params_td = jax.tree.structure(state.params)

    def mirror(obj):
        if jax.tree.structure(obj) == params_td:
            return p_specs
        if isinstance(obj, tuple) and hasattr(obj, "_fields"):  # NamedTuple
            return type(obj)(*(mirror(x) for x in obj))
        if isinstance(obj, (tuple, list)):
            return type(obj)(mirror(x) for x in obj)
        if isinstance(obj, dict):
            return {k: mirror(v) for k, v in obj.items()}
        return P()

    return TrainState(step=P(), params=p_specs, batch_stats=P(),
                      opt_state=mirror(state.opt_state))
